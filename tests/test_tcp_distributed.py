"""Distributed runtime tests over the cross-process TCP backend.

The same GEMM/POTRF flows as test_distributed.py (which runs ranks as
threads in one process) but with N REAL OS processes joined by the TCP
mesh — the claim "the CE vtable is transport-agnostic" is only true if
both backends pass the same cases (ref: the reference's only production
backend is cross-process MPI, parsec/parsec_mpi_funnelled.c).

Program functions live at module top level so multiprocessing spawn can
import them; each child forces the CPU jax platform before any backend
touch (children do not inherit conftest).
"""

import numpy as np
import pytest

from parsec_tpu.comm.tcp import run_distributed_procs
from parsec_tpu.comm.xhost import XHostTransfer

N, TS = 32, 16
_SEED = 11

# device-native cross-rank pulls need the PJRT transfer API; without it
# these cases are env-impossible — skip like test_xhost.py does instead
# of spending a spawned-rank job discovering the same ImportError
_needs_transfer = pytest.mark.skipif(
    not XHostTransfer.available(),
    reason="jax.experimental.transfer unavailable")


# -------------------------------------------------- failure attribution unit

def test_transport_error_classification():
    """Typed checks first; PJRT-plane markers attribute outright; weak
    markers (words ordinary local errors also use) are at most ambiguous
    (ADVICE.md r5: substring matching let a local RuntimeError containing
    'RESET' mark a live peer dead)."""
    from parsec_tpu.comm.tcp import classify_transport_error as cls

    assert cls(ConnectionResetError("peer went away")) == "transport"
    assert cls(TimeoutError("recv timed out")) == "transport"
    assert cls(EOFError()) == "transport"
    assert cls(RuntimeError(
        "UNAVAILABLE: failed to connect to all addresses")) == "transport"
    assert cls(RuntimeError("transfer server handshake lost")) == "transport"
    # weak markers in a backend RuntimeError: ambiguous, never outright
    assert cls(RuntimeError("buffer RESET while tracing")) == "ambiguous"
    assert cls(RuntimeError("stream CLOSED mid-collective")) == "ambiguous"
    # non-RuntimeError non-socket exceptions are this rank's own fault
    assert cls(ValueError("connection reset by peer")) == "local"
    # the consumer's own OOM is never the wire
    assert cls(RuntimeError("RESOURCE_EXHAUSTED: out of memory "
                            "while UNAVAILABLE")) == "local"
    assert cls(RuntimeError("shape mismatch in reduction")) == "local"


def test_attributed_pull_retry_semantics():
    """Ambiguous failures retry once: transient hiccups recover, spoofed
    local messages raise locally, and only genuine transport verdicts
    mark the peer."""
    from parsec_tpu.comm.tcp import _attributed_pull

    calls = []

    def flaky(ref):
        calls.append(ref)
        if len(calls) == 1:
            raise RuntimeError("stream CLOSED unexpectedly")
        return "payload"

    assert _attributed_pull(flaky, 1) == ("ok", "payload")
    assert len(calls) == 2

    # deterministic LOCAL error with a spoofed weak marker: raises; a live
    # peer is never blamed for it
    def spoofed(ref):
        raise RuntimeError("tensor RESET in local op")

    with pytest.raises(RuntimeError, match="tensor RESET"):
        _attributed_pull(spoofed, 1)

    def gone(ref):
        raise RuntimeError("UNAVAILABLE: transfer server unreachable")

    status, exc = _attributed_pull(gone, 1)
    assert status == "transport"

    def oom(ref):
        raise RuntimeError("RESOURCE_EXHAUSTED: device OOM")

    with pytest.raises(RuntimeError, match="RESOURCE_EXHAUSTED"):
        _attributed_pull(oom, 1)


def _force_cpu():
    import jax
    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass


def _mkctx(rank, ce):
    from parsec_tpu.comm.remote_dep import RemoteDepEngine
    from parsec_tpu.core.context import Context
    ctx = Context(nb_cores=1, my_rank=rank, nb_ranks=ce.nb_ranks)
    RemoteDepEngine(ctx, ce)
    return ctx


def _am_program(rank, ce):
    """Raw CE: AM ring + barrier, no jax involved."""
    got = []
    from parsec_tpu.comm.engine import TAG_DSL_BASE
    ce.tag_register(TAG_DSL_BASE,
                    lambda _ce, src, hdr, pl: got.append((src, hdr, pl)))
    ce.sync()
    dst = (rank + 1) % ce.nb_ranks
    ce.send_am(TAG_DSL_BASE, dst, {"from": rank},
               np.full((8,), rank, np.int32))
    import time
    t0 = time.time()
    while not got and time.time() - t0 < 20:
        ce.progress()
        time.sleep(0.001)
    ce.sync()
    ce.fini()
    src, hdr, pl = got[0]
    return (src, hdr["from"], int(pl[0]))


def test_tcp_am_roundtrip_and_barrier():
    res = run_distributed_procs(3, _am_program, timeout=90)
    for rank, (src, hdr_from, val) in enumerate(res):
        expect = (rank - 1) % 3
        assert src == expect and hdr_from == expect and val == expect


def _quiet_lull_program(rank, ce):
    """A >2s traffic lull, then a normal AM exchange: the dialed socket
    must survive the silence. Regression — create_connection's 2s dial
    timeout used to persist on the socket, so the dialed end's reader
    misread any compile-length lull as peer death (the symmetric
    'connection lost without clean shutdown' full-suite flake)."""
    import time
    got = []
    from parsec_tpu.comm.engine import TAG_DSL_BASE
    ce.tag_register(TAG_DSL_BASE,
                    lambda _ce, src, hdr, pl: got.append(src))
    ce.sync()
    time.sleep(2.6)               # longer than the dial timeout
    assert not ce.dead_peers, f"lull killed peers: {ce.dead_peers}"
    ce.send_am(TAG_DSL_BASE, (rank + 1) % ce.nb_ranks, {}, None)
    t0 = time.time()
    while not got and time.time() - t0 < 20:
        ce.progress()
        time.sleep(0.001)
    ce.sync()
    ce.fini()
    return got[0]


def test_tcp_mesh_survives_quiet_lull():
    res = run_distributed_procs(2, _quiet_lull_program, timeout=90)
    assert res == [1, 0]


def _gemm_program(rank, ce):
    _force_cpu()
    from parsec_tpu.data.matrix import TwoDimBlockCyclic
    from parsec_tpu.dsl.dtd import DTDTaskpool
    from parsec_tpu.ops.gemm import insert_gemm_tasks

    rng = np.random.default_rng(_SEED)
    a = rng.standard_normal((N, N)).astype(np.float32)
    b = rng.standard_normal((N, N)).astype(np.float32)
    ctx = _mkctx(rank, ce)
    kw = dict(nodes=ce.nb_ranks, myrank=rank, P=ce.nb_ranks, Q=1)
    A = TwoDimBlockCyclic("A", N, N, TS, TS, **kw)
    B = TwoDimBlockCyclic("B", N, N, TS, TS, **kw)
    C = TwoDimBlockCyclic("C", N, N, TS, TS, **kw)
    A.fill(lambda m, n: a[m*TS:(m+1)*TS, n*TS:(n+1)*TS])
    B.fill(lambda m, n: b[m*TS:(m+1)*TS, n*TS:(n+1)*TS])
    C.fill(lambda m, n: np.zeros((TS, TS), np.float32))
    tp = DTDTaskpool(ctx, "tcpgemm")
    insert_gemm_tasks(tp, A, B, C)
    tp.wait(timeout=60)
    tp.close()
    ctx.wait(timeout=60)
    ctx.fini()
    ce.fini()
    return {(m, n): np.asarray(C.data_of(m, n).newest_copy().payload)
            for m in range(C.mt) for n in range(C.nt)
            if C.rank_of(m, n) == rank}


def test_tcp_distributed_dtd_gemm():
    results = run_distributed_procs(2, _gemm_program, timeout=180)
    rng = np.random.default_rng(_SEED)
    a = rng.standard_normal((N, N)).astype(np.float32)
    b = rng.standard_normal((N, N)).astype(np.float32)
    ref = a @ b
    full = {}
    for out in results:
        for k, v in out.items():
            assert k not in full
            full[k] = v
    assert len(full) == (N // TS) ** 2
    for (m, n), tile in full.items():
        np.testing.assert_allclose(
            tile, ref[m*TS:(m+1)*TS, n*TS:(n+1)*TS], rtol=1e-3, atol=1e-3)


def _gemm_device_program(rank, ce):
    """The production shape: process per rank, one device per process. Each
    rank binds virtual CPU device #rank through PARSEC_TPU_LOCAL_DEVICE (the
    launcher's --virtual-devices env contract) and runs its tile bodies
    through the TPU device module's async pipeline."""
    import os
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") +
                               " --xla_force_host_platform_device_count=2").strip()
    os.environ["PARSEC_TPU_LOCAL_DEVICE"] = str(rank)
    _force_cpu()
    from parsec_tpu.utils import mca
    mca.set("device_tpu_over_cpu", True)
    from parsec_tpu.data.matrix import TwoDimBlockCyclic
    from parsec_tpu.device.tpu import TPUDevice
    from parsec_tpu.dsl.dtd import DTDTaskpool
    from parsec_tpu.ops.gemm import insert_gemm_tasks

    rng = np.random.default_rng(_SEED)
    a = rng.standard_normal((N, N)).astype(np.float32)
    b = rng.standard_normal((N, N)).astype(np.float32)
    ctx = _mkctx(rank, ce)
    tpus = [d for d in ctx.devices.devices if isinstance(d, TPUDevice)]
    kw = dict(nodes=ce.nb_ranks, myrank=rank, P=ce.nb_ranks, Q=1)
    A = TwoDimBlockCyclic("A", N, N, TS, TS, **kw)
    B = TwoDimBlockCyclic("B", N, N, TS, TS, **kw)
    C = TwoDimBlockCyclic("C", N, N, TS, TS, **kw)
    A.fill(lambda m, n: a[m*TS:(m+1)*TS, n*TS:(n+1)*TS])
    B.fill(lambda m, n: b[m*TS:(m+1)*TS, n*TS:(n+1)*TS])
    C.fill(lambda m, n: np.zeros((TS, TS), np.float32))
    tp = DTDTaskpool(ctx, "tcpdevgemm")
    insert_gemm_tasks(tp, A, B, C)
    tp.wait(timeout=60)
    tp.close()
    ctx.wait(timeout=60)
    ctx.fini()
    ce.fini()
    out = {(m, n): np.asarray(C.data_of(m, n).newest_copy().payload)
           for m in range(C.mt) for n in range(C.nt)
           if C.rank_of(m, n) == rank}
    return (out,
            [d.jax_device.id for d in tpus],
            sum(d.executed_tasks for d in tpus))


def test_tcp_distributed_device_module_gemm():
    """DTD GEMM through per-process TPU device modules over the TCP mesh:
    every rank bound to a DISTINCT device, bodies executed on-device
    (VERDICT r2 item 3; ref: the mpiexec+device production test mode)."""
    results = run_distributed_procs(2, _gemm_device_program, timeout=240)
    rng = np.random.default_rng(_SEED)
    a = rng.standard_normal((N, N)).astype(np.float32)
    b = rng.standard_normal((N, N)).astype(np.float32)
    ref = a @ b
    full = {}
    bound = []
    for out, dev_ids, executed in results:
        assert len(dev_ids) == 1, "each rank must bind exactly one device"
        bound.extend(dev_ids)
        assert executed > 0, "tile bodies must run through the device module"
        full.update(out)
    assert len(set(bound)) == 2, f"ranks share a device: {bound}"
    assert len(full) == (N // TS) ** 2
    for (m, n), tile in full.items():
        np.testing.assert_allclose(
            tile, ref[m*TS:(m+1)*TS, n*TS:(n+1)*TS], rtol=1e-3, atol=1e-3)


@_needs_transfer
def test_launcher_virtual_device_binding():
    """The launcher CLI maps rank i -> local device i (--virtual-devices):
    each spawned process binds a distinct virtual chip and executes its
    tile bodies through the TPU device module."""
    import os
    import re
    import subprocess
    import sys as _sys
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    out = subprocess.run(
        [_sys.executable, "-m", "parsec_tpu.launch", "-n", "2",
         "--virtual-devices", "2", os.path.join("tests", "_launch_device_probe.py")],
        cwd=repo, capture_output=True, text=True, timeout=240)
    assert out.returncode == 0, (out.stdout[-1000:], out.stderr[-2000:])
    lines = re.findall(r"PROBE rank=(\d+) devices=\[(\d+)\] executed=(\d+)",
                       out.stdout)
    assert len(lines) == 2, out.stdout
    by_rank = {int(r): (int(d), int(e)) for r, d, e in lines}
    assert set(by_rank) == {0, 1}
    assert by_rank[0][0] != by_rank[1][0], f"ranks share a device: {by_rank}"
    assert all(e > 0 for _, e in by_rank.values())


def _potrf_program(rank, ce):
    _force_cpu()
    from parsec_tpu.data.matrix import TwoDimBlockCyclic
    from parsec_tpu.dsl.dtd import DTDTaskpool
    from parsec_tpu.ops.potrf import insert_potrf_tasks, make_spd

    spd = make_spd(N, seed=_SEED)
    ctx = _mkctx(rank, ce)
    A = TwoDimBlockCyclic("A", N, N, TS, TS, P=2, Q=1,
                          nodes=2, myrank=rank)
    A.fill(lambda m, n: spd[m*TS:(m+1)*TS, n*TS:(n+1)*TS])
    tp = DTDTaskpool(ctx, "tcppotrf")
    insert_potrf_tasks(tp, A)
    tp.wait(timeout=60)
    tp.close()
    ctx.wait(timeout=60)
    ctx.fini()
    ce.fini()
    return {(m, n): np.asarray(A.data_of(m, n).newest_copy().payload)
            for m in range(A.mt) for n in range(A.nt)
            if A.rank_of(m, n) == rank and m >= n}


def test_tcp_distributed_dtd_potrf():
    results = run_distributed_procs(2, _potrf_program, timeout=180)
    from parsec_tpu.ops.potrf import make_spd
    spd = make_spd(N, seed=_SEED)
    L = np.zeros((N, N), np.float32)
    for out in results:
        for (m, n), tile in out.items():
            L[m*TS:(m+1)*TS, n*TS:(n+1)*TS] = tile
    L = np.tril(L)
    np.testing.assert_allclose(L @ L.T, spd, rtol=1e-2, atol=1e-2)


def _crash_program(rank, ce):
    ce.fini()
    if rank == 1:
        import os
        os._exit(17)   # die without reporting (simulates segfault/OOM-kill)
    return "ok"


def test_tcp_dead_child_raises():
    """A rank that dies without reporting must raise, not yield None results."""
    with pytest.raises(RuntimeError, match="died without reporting"):
        run_distributed_procs(2, _crash_program, timeout=60)


def _arena_recv_program(rank, ce):
    _force_cpu()
    from parsec_tpu.data.arena import arena_for
    from parsec_tpu.data.matrix import TwoDimBlockCyclic
    from parsec_tpu.dsl.dtd import DTDTaskpool, READ, RW

    ctx = _mkctx(rank, ce)
    A = TwoDimBlockCyclic("AR", 32, 16, 16, 16, P=2, Q=1,
                          nodes=2, myrank=rank)
    A.fill(lambda m, n: np.full((16, 16), float(m + 1), np.float32))
    tp = DTDTaskpool(ctx, "arenarecv")
    src = tp.tile_of(A, 0, 0)   # rank 0
    dst = tp.tile_of(A, 1, 0)   # rank 1
    tp.insert_task(lambda x: x + 1.0, (src, RW), name="w")
    tp.insert_task(lambda y, x: y + x, (dst, RW), (src, READ), name="r")
    tp.wait(timeout=60); tp.close(); ctx.wait(timeout=60); ctx.fini()
    ce.fini()
    stats = arena_for((16, 16), np.float32).stats()
    val = float(np.asarray(A.data_of(1, 0).newest_copy().payload)[0, 0]) \
        if rank == 1 else None
    return (stats, val)


def test_tcp_receives_land_in_arena_buffers():
    """Wire payloads are read into arena-allocated buffers on the receiver
    (ref: remote copies allocated from the dep's arena,
    remote_dep_mpi.c:2120) — the arena high-water mark must show use."""
    results = run_distributed_procs(2, _arena_recv_program, timeout=180)
    stats1, val = results[1]
    assert val == 4.0                       # 2 + (1+1)
    assert stats1["hwm"] >= 1, f"receiver arena never used: {stats1}"


def _counter_program(rank, ce):
    _force_cpu()
    from parsec_tpu.comm.remote_dep import RemoteDepEngine
    from parsec_tpu.core.context import Context
    from parsec_tpu.utils.counters import counters

    ctx = Context(nb_cores=1, my_rank=rank, nb_ranks=ce.nb_ranks)
    eng = RemoteDepEngine(ctx, ce)
    counters.register("test.widgets")
    counters.add("test.widgets", 10 * (rank + 1))   # genuinely per-process
    ce.sync()
    table = eng.aggregate_counters(timeout=30)
    ce.sync()
    ctx.fini()
    ce.fini()
    return table


def test_tcp_counter_aggregation():
    """Cross-rank counter aggregation: rank 0 merges every process's
    snapshot into per-rank columns + a sum (aggregator_visu role, run on
    REAL processes so the per-rank values are genuinely distinct)."""
    results = run_distributed_procs(2, _counter_program, timeout=120)
    table = results[0]
    assert results[1] is None          # only rank 0 gets the merged table
    assert table["per_rank"][0]["test.widgets"] == 10
    assert table["per_rank"][1]["test.widgets"] == 20
    assert table["sum"]["test.widgets"] == 30


def _victim_or_survivor(rank, ce):
    _force_cpu()
    import socket as _socket
    from parsec_tpu.data.matrix import TwoDimBlockCyclic
    from parsec_tpu.dsl.dtd import DTDTaskpool, READ, RW

    ctx = _mkctx(rank, ce)
    A = TwoDimBlockCyclic("FD", 32, 16, 16, 16, P=2, Q=1,
                          nodes=2, myrank=rank)
    A.fill(lambda m, n: np.ones((16, 16), np.float32))
    tp = DTDTaskpool(ctx, "faildet")
    src = tp.tile_of(A, 0, 0)   # rank 0 (the victim) produces
    dst = tp.tile_of(A, 1, 0)   # rank 1 (the survivor) consumes
    tp.insert_task(lambda x: x + 1.0, (src, RW), jit=False, name="w")
    tp.insert_task(lambda y, x: y + x, (dst, RW), (src, READ),
                   jit=False, name="r")
    if rank == 0:
        # simulate a crash: sever every connection WITHOUT the BYE
        # handshake (the process itself stays alive to report to the
        # parent, so the survivor's observation can be asserted directly)
        for s in ce._peers.values():
            try:
                s.shutdown(_socket.SHUT_RDWR)
            except OSError:
                pass
            s.close()
        return "victim-done"
    try:
        tp.wait(timeout=60)
        return "no-error"
    except RuntimeError as e:
        return "attributed" if "FAILED" in str(e) and "0" in str(e) \
            else f"other: {e}"


def test_tcp_rank_failure_is_attributed():
    """A peer dying mid-job (no clean shutdown) surfaces as a prompt,
    attributed fatal on the survivor instead of a silent hang (failure
    detection — SURVEY §5 lists it; the reference has none)."""
    results = run_distributed_procs(2, _victim_or_survivor, timeout=120)
    assert results[0] == "victim-done"
    assert results[1] == "attributed", results[1]


def _divergent_sync(rank, ce):
    """Rank 1 skips the barrier and exits cleanly; the others must see an
    attributed collective-divergence error, not a bare barrier timeout."""
    if rank == 1:
        import time
        time.sleep(0.3)       # let the others enter the barrier first
        ce.fini()             # clean BYE without ever calling sync()
        return "skipped"
    try:
        ce.sync(timeout=20)
        return "no-error"
    except RuntimeError as e:
        return "attributed" if "divergence" in str(e) and "1" in str(e) \
            else f"other: {e}"
    except TimeoutError:
        return "timeout"


def test_tcp_clean_exit_mid_barrier_is_attributed():
    """A peer departing cleanly (BYE) while others wait in a barrier is a
    collective divergence surfaced as an attributed error on every waiter
    (rank 0 observes it directly; non-roots via the failed-list release)."""
    results = run_distributed_procs(3, _divergent_sync, timeout=60)
    assert results[1] == "skipped"
    assert results[0] == "attributed", results[0]
    assert results[2] == "attributed", results[2]


# --------------------------------------------- cross-host device payloads

def _xhost_program(rank, ce):
    """Two OS ranks: a DEVICE-resident payload crosses via the PJRT
    transfer server (rendezvous descriptor in the AM frame, buffer pulled
    device-to-device), a host numpy payload rides the wire as before, and
    with the flag OFF the device payload host-bounces and is COUNTED."""
    _force_cpu()
    import time

    import jax.numpy as jnp

    from parsec_tpu.comm.engine import TAG_DSL_BASE
    from parsec_tpu.utils.counters import counters

    got = []
    ce.tag_register(TAG_DSL_BASE,
                    lambda _ce, src, hdr, pl: got.append((hdr, pl)))
    ce.sync()

    def exchange(tagval, payload):
        got.clear()
        dst = (rank + 1) % ce.nb_ranks
        ce.send_am(TAG_DSL_BASE, dst, {"k": tagval}, payload)
        t0 = time.time()
        while not got and time.time() - t0 < 30:
            ce.progress()
            time.sleep(0.001)
        assert got, f"no payload for {tagval}"
        return got[0]

    # device-resident: jax array (CPU backend stands in for the chip)
    hdr, pl = exchange("dev", jnp.full((16, 16), float(rank + 1)))
    ce.sync()
    import jax
    peer = (rank - 1) % ce.nb_ranks
    if ce._xhost is not None:
        # the pulled payload arrives DEVICE-resident on the consumer
        assert isinstance(pl, jax.Array), type(pl)
        # extended dtypes must survive the descriptor round-trip (dtype
        # NAME, not .str which collapses bf16 to raw void)
        hdrb, plb = exchange("bf16", jnp.full((8, 8), float(rank + 2),
                                              jnp.bfloat16))
        ce.sync()
        assert plb.dtype == jnp.bfloat16, plb.dtype
        assert float(np.asarray(plb.astype(jnp.float32))[0, 0]) == \
            float(peer + 2)
    assert float(np.asarray(pl)[0, 0]) == float(peer + 1)

    # host numpy payload: unaffected by the device-mem plane
    hdr2, pl2 = exchange("host", np.full((4,), rank, np.int32))
    ce.sync()
    assert int(pl2[0]) == peer

    # wait for the peer's ACK to retire our pin (reader-thread async)
    t0 = time.time()
    while ce._xhost is not None and ce._xhost.pending() \
            and time.time() - t0 < 20:
        ce.progress()
        time.sleep(0.002)
    stats = {
        "d2d": counters.read("comm.xhost_d2d_msgs"),
        "offered": counters.read("comm.xhost_offered_msgs"),
        "bounced": counters.read("comm.host_materialized_msgs"),
        "pins": ce._xhost.pending() if ce._xhost is not None else -1,
    }
    ce.sync()
    ce.fini()
    return stats


def _xhost_program_enabled(rank, ce):
    from parsec_tpu.utils import mca
    mca.set("comm_device_mem", True)
    # the CE was built before the flag was set (run_distributed_procs
    # constructs it); rebuild the xhost plane the way __init__ would
    from parsec_tpu.comm.engine import CAP_ACCELERATOR_MEM
    from parsec_tpu.comm.xhost import XHostTransfer
    assert XHostTransfer.available()
    ce._xhost = ce._xpull = XHostTransfer()
    ce.capabilities |= CAP_ACCELERATOR_MEM
    return _xhost_program(rank, ce)


@_needs_transfer
def test_tcp_xhost_device_payload_pull():
    """comm_device_mem=1: device payloads cross OS ranks via PJRT pull —
    zero host materializations, pins retired by the ACK."""
    results = run_distributed_procs(2, _xhost_program_enabled, timeout=120)
    for s in results:
        assert s["offered"] == 2, s       # f32 + bf16 payloads offered
        assert s["d2d"] == 2, s           # both pulled device-to-device
        assert s["bounced"] == 0, s       # never host-materialized
        assert s["pins"] == 0, s          # ACKs retired the pins


def test_tcp_xhost_disabled_bounces_and_counts():
    """Flag off (the default): the same device payload host-bounces into
    the wire frame and the bounce is COUNTED — the measured-cost fallback
    the design requires."""
    results = run_distributed_procs(2, _xhost_program, timeout=120)
    for s in results:
        assert s["offered"] == 0, s
        assert s["d2d"] == 0, s
        assert s["bounced"] == 1, s       # counted fallback
        assert s["pins"] == -1, s         # no xhost plane was built


def _potrf_device_xhost_program(rank, ce):
    """The full stack with the device-native cross-rank plane ON: DTD
    POTRF over the TCP mesh with comm_device_mem=1. POTRF's panels are
    PRODUCED by tasks (jit outputs = device-resident arrays) and consumed
    remotely, so the protocol's sends carry device payloads — which must
    ride PJRT transfer-server pulls (rendezvous descriptors in the AM
    frames), not wire bytes. (A plain GEMM only ships host-FILLED input
    tiles — legitimately host content — so it never exercises this.)"""
    import os
    os.environ["PARSEC_TPU_LOCAL_DEVICE"] = "0"
    _force_cpu()
    from parsec_tpu.comm.engine import CAP_ACCELERATOR_MEM
    from parsec_tpu.comm.xhost import XHostTransfer
    from parsec_tpu.utils import mca
    from parsec_tpu.utils.counters import counters
    mca.set("comm_device_mem", True)
    # the CE predates the flag in this harness; wire the plane as
    # __init__ would
    ce._xhost = ce._xpull = XHostTransfer()
    ce.capabilities |= CAP_ACCELERATOR_MEM

    from parsec_tpu.data.matrix import TwoDimBlockCyclic
    from parsec_tpu.dsl.dtd import DTDTaskpool
    from parsec_tpu.ops.potrf import insert_potrf_tasks, make_spd

    spd = make_spd(N, seed=_SEED)
    ctx = _mkctx(rank, ce)
    A = TwoDimBlockCyclic("A", N, N, TS, TS, P=2, Q=1,
                          nodes=2, myrank=rank)
    A.fill(lambda m, n: spd[m*TS:(m+1)*TS, n*TS:(n+1)*TS])
    tp = DTDTaskpool(ctx, "xhostpotrf")
    insert_potrf_tasks(tp, A)
    tp.wait(timeout=90)
    tp.close()
    ctx.wait(timeout=60)
    ctx.fini()
    stats = {
        "offered": int(counters.read("comm.xhost_offered_msgs")),
        "pulled": int(counters.read("comm.xhost_d2d_msgs")),
        "bounced": int(counters.read("comm.host_materialized_msgs")),
        "pins": ce._xhost.pending(),
    }
    ce.sync()
    ce.fini()
    L = np.linalg.cholesky(spd.astype(np.float64))
    err = 0.0
    for m in range(A.mt):
        for n in range(A.nt):
            if A.rank_of(m, n) == rank and m >= n:
                got = np.asarray(A.data_of(m, n).newest_copy().payload,
                                 np.float64)
                err = max(err, float(np.abs(
                    got - L[m*TS:(m+1)*TS, n*TS:(n+1)*TS]).max()))
    return dict(stats, err=err)


@_needs_transfer
def test_tcp_distributed_potrf_device_payloads_via_xhost():
    """End-to-end: the remote-dep protocol's PRODUCED tile payloads
    (device-resident jit outputs) cross OS ranks via PJRT pulls; results
    correct, zero host materializations, all pins retired."""
    results = run_distributed_procs(2, _potrf_device_xhost_program,
                                    timeout=240)
    for s in results:
        assert s["err"] < 1e-2, s
        assert s["bounced"] == 0, s        # nothing host-materialized
        assert s["pins"] == 0, s           # every ACK arrived
    total_offered = sum(s["offered"] for s in results)
    total_pulled = sum(s["pulled"] for s in results)
    assert total_offered == total_pulled > 0, results
