"""Controller script for the multi-host rehearsal test: joins the jax
multi-controller job (2 processes x 4 virtual CPU devices = ONE global
8-device mesh), runs the flagship LM train step over the GLOBAL (dp, tp)
mesh — collectives cross the process boundary — and prints the losses.

Launched by parsec_tpu.parallel.multihost.run_multicontroller.
"""
import sys

sys.path.insert(0, __file__.rsplit("/", 2)[0])


def main():
    import jax
    jax.config.update("jax_platforms", "cpu")
    from parsec_tpu.parallel.multihost import (fetch_replicated,
                                               global_mesh, init_multihost)
    pid = init_multihost()

    import numpy as np
    from parsec_tpu.parallel.model import (ModelConfig, init_lm_params,
                                           make_lm_train_step)

    import os
    n = len(jax.devices())
    assert n >= 4 and n % 2 == 0, f"need an even mesh, got {n} devices"
    tp = n // 2
    mesh = global_mesh(("dp", "tp"), (2, tp))
    if os.environ.get("PARSEC_TPU_NUM_PROCESSES", "1") != "1":
        assert len(jax.local_devices()) < n     # the rest are the peers'

    cfg = ModelConfig(vocab_size=64, d_model=32, d_ff=64,
                      n_heads=max(4, tp), n_layers=2, max_seq=16)
    params = init_lm_params(0, cfg)          # identical on every controller
    step, place_p, place_t = make_lm_train_step(mesh, params=params, lr=0.1)
    params = place_p(params)

    rng = np.random.default_rng(5)
    toks = rng.integers(0, 64, size=(8, 8)).astype(np.int32)
    tokens, targets = place_t(toks[:, :-1]), place_t(toks[:, 1:])
    losses = []
    for _ in range(3):
        params, loss = step(params, tokens, targets)
        losses.append(float(fetch_replicated(loss)))
    print(f"MHLOSS pid={pid} losses={','.join(f'{l:.6f}' for l in losses)}",
          flush=True)
    assert losses[-1] < losses[0]

    # input-feeding leg: the REAL multi-host idiom — each controller
    # contributes only ITS dp shard of the global batch
    # (host_local_to_global = make_array_from_process_local_data); the
    # assembled batch must reproduce the place_t loss exactly
    from jax.sharding import PartitionSpec as P
    from parsec_tpu.parallel.multihost import host_local_to_global
    nproc = int(os.environ.get("PARSEC_TPU_NUM_PROCESSES", "1"))
    rows = toks.shape[0] // nproc
    mine = toks[pid * rows:(pid + 1) * rows]
    g_tok = host_local_to_global(mesh, P("dp", None), mine[:, :-1])
    g_tgt = host_local_to_global(mesh, P("dp", None), mine[:, 1:])
    p0 = place_p(init_lm_params(0, cfg))
    _, loss_fed = step(p0, g_tok, g_tgt)
    _, loss_ref = step(place_p(init_lm_params(0, cfg)), tokens, targets)
    df = abs(float(fetch_replicated(loss_fed)) -
             float(fetch_replicated(loss_ref)))
    print(f"MHFEED pid={pid} diff={df:.2e}", flush=True)
    assert df < 1e-6

    # checkpoint leg: a COORDINATED orbax save of the sharded train state
    # across both controllers, restored back onto the global mesh shardings
    import tempfile

    from parsec_tpu.utils.model_ckpt import (restore_train_state,
                                             save_train_state)
    from parsec_tpu.parallel.multihost import ENV_COORD
    job = os.environ.get(ENV_COORD, "solo").replace(":", "_").replace(".", "-")
    ckdir = os.path.join(tempfile.gettempdir(), f"mh_ckpt_{job}")
    save_train_state(ckdir, params, None, step=3)
    like = jax.tree_util.tree_map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding),
        params)
    p2, _, got_step = restore_train_state(ckdir, like=(like, None))
    assert got_step == 3
    for a, b in zip(jax.tree_util.tree_leaves(params),
                    jax.tree_util.tree_leaves(p2)):
        sa = np.asarray(a.addressable_shards[0].data)
        sb = np.asarray(b.addressable_shards[0].data)
        assert sa.shape == sb.shape and np.allclose(sa, sb)
    print(f"MHCKPT pid={pid} step={got_step} ok=1", flush=True)

    # expert-parallel leg: top-2 MoE with the EXPERTS split across the
    # controllers — the dispatch/combine all_to_all crosses the boundary
    from jax.sharding import Mesh
    from parsec_tpu.parallel.moe import (dense_reference, init_moe_params,
                                         moe_forward)
    emesh = Mesh(np.array(jax.devices()), ("ep",))
    eE, eD, eT = len(jax.devices()), 16, 4 * len(jax.devices())
    mo_params = init_moe_params(0, eE, eD, 32)
    mx = np.random.default_rng(7).standard_normal((eT, eD)).astype(np.float32)
    mout, maux = moe_forward(mo_params, mx, mesh=emesh, k=2, return_aux=True)
    mref = np.asarray(dense_reference(mo_params, mx, k=2))
    mo_shards = sorted(mout.addressable_shards,
                       key=lambda s: s.index[0].start or 0)
    mo_lo = mo_shards[0].index[0].start or 0
    mo_hi = mo_shards[-1].index[0].stop
    mo_got = np.concatenate([np.asarray(s.data) for s in mo_shards], axis=0)
    mo_err = float(np.abs(mo_got - mref[mo_lo:mo_hi]).max())
    print(f"MHMOE pid={pid} err={mo_err:.2e}", flush=True)
    assert mo_err < 1e-4

    # pipeline leg: GPipe microbatches over ALL global devices — one stage
    # per device, activations hop the ppermute ring across the process
    # boundary every tick
    from parsec_tpu.parallel.pipeline import (init_pipeline_params,
                                              pipeline_forward_stages,
                                              reference_forward, _mlp_stage)
    pmesh = Mesh(np.array(jax.devices()), ("pp",))
    pp_params = init_pipeline_params(3, n, 16)
    px = np.random.default_rng(8).standard_normal((n, 2, 16)) \
        .astype(np.float32)
    p_out = pipeline_forward_stages(
        {"w": pp_params["w"], "b": pp_params["b"]}, px, _mlp_stage,
        mesh=pmesh)
    p_ref = np.asarray(reference_forward(pp_params, px.reshape(-1, 16))
                       ).reshape(px.shape)
    pp_err = float(np.abs(np.asarray(p_out) - p_ref).max())
    print(f"MHPP pid={pid} err={pp_err:.2e} stages={n}", flush=True)
    assert pp_err < 1e-4

    # long-context leg: causal ring attention with the SEQUENCE axis
    # sharded across both controllers — the K/V ppermute ring crosses the
    # process boundary every hop
    from jax.sharding import Mesh
    from parsec_tpu.parallel.ring_attention import (
        dense_attention_reference, ring_attention)
    smesh = Mesh(np.array(jax.devices()), ("sp",))
    r = np.random.default_rng(9)
    q = r.standard_normal((1, 2, 64, 8)).astype(np.float32)
    k = r.standard_normal((1, 2, 64, 8)).astype(np.float32)
    v = r.standard_normal((1, 2, 64, 8)).astype(np.float32)
    out = ring_attention(q, k, v, mesh=smesh, causal=True)
    ref = np.asarray(dense_attention_reference(q, k, v, causal=True))
    got = np.concatenate([np.asarray(s.data) for s in
                          sorted(out.addressable_shards,
                                 key=lambda s: s.index[2].start or 0)],
                         axis=2)
    lo = min(s.index[2].start or 0 for s in out.addressable_shards)
    hi = max(s.index[2].stop for s in out.addressable_shards)
    err = float(np.abs(got - ref[:, :, lo:hi]).max())
    print(f"MHRING pid={pid} err={err:.2e} span={lo}:{hi}", flush=True)
    assert err < 1e-4


if __name__ == "__main__":
    main()
