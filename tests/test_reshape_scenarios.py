"""The reference's reshape scenario battery, re-done for this runtime.

Analogues of /root/reference/tests/collections/reshape/ (13 scenarios across
testing_reshape.c, testing_avoidable_reshape.c,
testing_input_dep_reshape_single_copy.c,
testing_remote_multiple_outs_same_pred_flow.c): named dep datatypes
([type = NAME]) drive read/input/output reshapes through the shared
reshape-promise engine (data/reshape.py + DataCopyFuture), typed memory
write-back merges only the datatype's region, and remote deps reshape
BEFORE the wire (pre-send, parsec/remote_dep.h:117) and never re-reshape
at the receiver.
"""

import numpy as np
import pytest

from parsec_tpu.comm.remote_dep import RemoteDepEngine
from parsec_tpu.comm.threads import ThreadsCE, run_distributed
from parsec_tpu.core.context import Context
from parsec_tpu.data.matrix import TwoDimBlockCyclic
from parsec_tpu.data.reshape import NamedDatatype, lower_tile, upper_tile
from parsec_tpu.dsl.ptg.compiler import compile_ptg

M, TS = 8, 4   # 2x2 tiles of 4x4, like the reference's 8x8/4x4 default


def _mk(name, val=1.0, nodes=1, rank=0, P=1):
    dc = TwoDimBlockCyclic(name, M, M, TS, TS, P=P, Q=1,
                           nodes=nodes, myrank=rank)
    dc.fill(lambda m, n: np.full((TS, TS), val, np.float32))
    return dc


def _counting(base: NamedDatatype):
    calls = {"extract": 0}

    def ex(a, _b=base):
        calls["extract"] += 1
        return _b.extract(a)

    return NamedDatatype(base.name, extract=ex, insert=base.insert), calls


# the reference's 3-task chain: READ -> ZERO -> WRITE (local_*.jdf)
def _chain_src(read_attr="", out_attr="", zero_in_attr="", write_attr=""):
    return f"""
%global descA

READ_A(m, k)
  m = 0 .. 1
  k = 0 .. 1
  : descA(m, k)
  RW A <- descA(m, k)   {read_attr}
       -> A SET_ZEROS(m, k)   {out_attr}
BODY
  A = A
END

SET_ZEROS(m, k)
  m = 0 .. 1
  k = 0 .. 1
  : descA(m, k)
  RW A <- A READ_A(m, k)   {zero_in_attr}
       -> A WRITE_A(m, k)
BODY
  A = A * 0.0
END

WRITE_A(m, k)
  m = 0 .. 1
  k = 0 .. 1
  : descA(m, k)
  RW A <- A SET_ZEROS(m, k)
       -> descA(m, k)   {write_attr}
BODY
  A = A
END
"""


def _run_chain(src, datatypes=None):
    ctx = Context(nb_cores=1)
    A = _mk("descA")
    tp = compile_ptg(src, "chain").instantiate(
        ctx, collections={"descA": A}, datatypes=datatypes)
    ctx.add_taskpool(tp)
    ctx.wait(timeout=30)
    ctx.fini()
    return A.to_dense(), tp


def test_s1_local_no_reshape():
    """No [type]: successors see the full tile; everything is zeroed
    (local_no_reshape.jdf)."""
    out, _ = _run_chain(_chain_src())
    np.testing.assert_array_equal(out, np.zeros((M, M), np.float32))


def test_s2_local_read_reshape():
    """[type] when reading from the matrix: the zeroing hits a NEW lower
    datacopy; typed write-back replaces only the lower region — the upper
    part of the original survives (local_read_reshape.jdf)."""
    out, _ = _run_chain(
        _chain_src(read_attr="[type = LOWER_TILE]",
                   write_attr="[type = LOWER_TILE]"),
        datatypes={"LOWER_TILE": lower_tile()})
    expect = np.kron(np.ones((2, 2), np.float32),
                     np.triu(np.ones((TS, TS), np.float32), 1))
    np.testing.assert_array_equal(out, expect)


def test_s3_local_output_reshape():
    """[type] on an output dep: the successor receives the reshaped copy
    (local_output_reshape.jdf)."""
    out, _ = _run_chain(
        _chain_src(out_attr="[type = LOWER_TILE]",
                   write_attr="[type = LOWER_TILE]"),
        datatypes={"LOWER_TILE": lower_tile()})
    expect = np.kron(np.ones((2, 2), np.float32),
                     np.triu(np.ones((TS, TS), np.float32), 1))
    np.testing.assert_array_equal(out, expect)


def test_s4_local_input_reshape():
    """[type] on an input dep: same result through the consumer-side
    conversion (local_input_reshape.jdf)."""
    out, _ = _run_chain(
        _chain_src(zero_in_attr="[type = LOWER_TILE]",
                   write_attr="[type = LOWER_TILE]"),
        datatypes={"LOWER_TILE": lower_tile()})
    expect = np.kron(np.ones((2, 2), np.float32),
                     np.triu(np.ones((TS, TS), np.float32), 1))
    np.testing.assert_array_equal(out, expect)


def test_s5_typed_writeback_preserves_complement():
    """Typed memory write-back merges ONLY the datatype's region; an
    UPPER write leaves the strictly-lower region untouched."""
    out, _ = _run_chain(
        _chain_src(write_attr="[type = UPPER_TILE]"),
        datatypes={"UPPER_TILE": upper_tile()})
    # zeros written through UPPER: upper becomes 0, strict lower stays 1
    expect = np.kron(np.ones((2, 2), np.float32),
                     np.tril(np.ones((TS, TS), np.float32), -1))
    np.testing.assert_array_equal(out, expect)


def test_s6_avoidable_reshape_same_type_converts_once():
    """Producer [type] == consumer [type]: ONE conversion, not two
    (avoidable_reshape.jdf)."""
    dtt, calls = _counting(lower_tile())
    out, tp = _run_chain(
        _chain_src(out_attr="[type = LOWER_TILE]",
                   zero_in_attr="[type = LOWER_TILE]",
                   write_attr="[type = LOWER_TILE]"),
        datatypes={"LOWER_TILE": dtt})
    # 4 tiles, one READ_A->SET_ZEROS conversion each + 0 re-conversions
    assert calls["extract"] == 4, calls
    expect = np.kron(np.ones((2, 2), np.float32),
                     np.triu(np.ones((TS, TS), np.float32), 1))
    np.testing.assert_array_equal(out, expect)


def test_s7_default_type_is_identity():
    """[type = DEFAULT] never converts: registered implicitly, identity
    semantics (the adt_default of the reference harness)."""
    out, tp = _run_chain(_chain_src(out_attr="[type = DEFAULT]",
                                    write_attr="[type = DEFAULT]"))
    np.testing.assert_array_equal(out, np.zeros((M, M), np.float32))
    assert len(tp._typed_cache) == 0


def test_s8_unknown_datatype_is_fatal():
    """A dep referencing an unregistered datatype fails loudly."""
    with pytest.raises(RuntimeError, match="unknown .*datatype"):
        _run_chain(_chain_src(read_attr="[type = NO_SUCH]"))


def test_s9_input_dep_single_copy():
    """Two consumer tasks reading the same tile with the same [type] share
    ONE converted copy (input_dep_single_copy_reshape.jdf)."""
    dtt, calls = _counting(lower_tile())
    src = """
%global descA
%global descB

C(i, j)
  i = 0 .. 1
  j = 0 .. 1
  : descB(i, j)
  READ A <- descA(0, 0)    [type = LOWER_TILE]
  RW   B <- descB(i, j)
       -> descB(i, j)
BODY
  B = B + A
END
"""
    ctx = Context(nb_cores=1)
    A = _mk("descA")
    B = _mk("descB", val=0.0)
    tp = compile_ptg(src, "single").instantiate(
        ctx, collections={"descA": A, "descB": B},
        datatypes={"LOWER_TILE": dtt})
    ctx.add_taskpool(tp)
    ctx.wait(timeout=30)
    ctx.fini()
    assert calls["extract"] == 1, calls     # 4 consumers, ONE conversion
    expect = np.kron(np.ones((2, 2), np.float32),
                     np.tril(np.ones((TS, TS), np.float32)))
    np.testing.assert_array_equal(B.to_dense(), expect)


def test_s10_local_LU_LL_two_types():
    """Producer ships LOWER, consumer asks UPPER: the conversions CHAIN
    (local_input_LU_LL.jdf's two-datatype path). tril then triu leaves the
    diagonal only."""
    src = """
%global descA
%global descB

P(m, k)
  m = 0 .. 1
  k = 0 .. 1
  : descA(m, k)
  RW A <- descA(m, k)
       -> A C(m, k)        [type = LOWER_TILE]
BODY
  A = A
END

C(m, k)
  m = 0 .. 1
  k = 0 .. 1
  : descB(m, k)
  RW A <- A P(m, k)        [type = UPPER_TILE]
       -> descB(m, k)
BODY
  A = A
END
"""
    ctx = Context(nb_cores=1)
    A = _mk("descA")
    B = _mk("descB", val=0.0)
    tp = compile_ptg(src, "lull").instantiate(
        ctx, collections={"descA": A, "descB": B},
        datatypes={"LOWER_TILE": lower_tile(), "UPPER_TILE": upper_tile()})
    ctx.add_taskpool(tp)
    ctx.wait(timeout=30)
    ctx.fini()
    expect = np.kron(np.ones((2, 2), np.float32),
                     np.eye(TS, dtype=np.float32))
    np.testing.assert_array_equal(B.to_dense(), expect)


# ---------------------------------------------------------------- remote ----
_REMOTE_SRC = """
%global descA
%global descB

P(m)
  m = 0 .. 1
  : descA(m, 0)
  RW A <- descA(m, 0)
       -> A C(m)           [type = LOWER_TILE]
BODY
  A = A
END

C(m)
  m = 0 .. 1
  : descB(m, 0)
  RW B <- descB(m, 0)
       -> descB(m, 0)
  READ A <- A P(m)         [type = LOWER_TILE]
BODY
  B = B + A
END
"""


def _remote_program(dtt_factory):
    """2 ranks: producers own descA (rank 0), consumers own descB (rank 1)."""
    def program(rank, fabric):
        ctx = Context(nb_cores=1, my_rank=rank, nb_ranks=2)
        RemoteDepEngine(ctx, ThreadsCE(fabric, rank))
        A = TwoDimBlockCyclic("descA", M, TS, TS, TS, P=1, Q=1,
                              nodes=2, myrank=rank)       # all rank 0
        B = TwoDimBlockCyclic("descB", M, TS, TS, TS, P=2, Q=1,
                              nodes=2, myrank=rank)
        # force descB tiles onto rank 1 (rows 0,1 -> ranks 0,1; row 1 only?)
        A.fill(lambda m, n: np.full((TS, TS), 1.0, np.float32))
        B.fill(lambda m, n: np.zeros((TS, TS), np.float32))
        dtt, calls = dtt_factory()
        tp = compile_ptg(_REMOTE_SRC, "rrr").instantiate(
            ctx, collections={"descA": A, "descB": B},
            datatypes={"LOWER_TILE": dtt})
        ctx.add_taskpool(tp)
        ctx.wait(timeout=60)
        ctx.fini()
        mine = {m: np.asarray(B.data_of(m, 0).newest_copy().payload)
                for m in range(2) if B.rank_of(m, 0) == rank}
        return mine, calls["extract"]
    return program


def test_s11_remote_presend_reshape_no_re_reshape():
    """Distributed: the payload is reshaped BEFORE the wire on the producer
    rank; the consumer (same [type]) does NOT re-reshape
    (remote_read_reshape.jdf + remote_no_re_reshape.jdf)."""
    results = run_distributed(2, _remote_program(
        lambda: _counting(lower_tile())), timeout=60)
    tiles = {}
    for mine, _ in results:
        tiles.update(mine)
    expect = np.tril(np.ones((TS, TS), np.float32))
    for m in range(2):
        np.testing.assert_array_equal(tiles[m], expect)
    # rank 0 (producer side) converts once per cross-rank tile; rank 1
    # (consumer side) must not convert at all for its remote input
    extracts = [c for _, c in results]
    assert extracts[0] >= 1
    # rank 1 owns descB(1,0); its consumer C(1) is remote-fed and must not
    # re-extract. C(0) runs on rank 0 (local path, may extract there).
    assert extracts[1] == 0, extracts


_MULTI_SRC = """
%global descA
%global descB
%global descC

P(m)
  m = 0 .. 0
  : descA(0, 0)
  RW A <- descA(0, 0)
       -> A CL(m)          [type = LOWER_TILE]
       -> A CU(m)          [type = UPPER_TILE]
BODY
  A = A
END

CL(m)
  m = 0 .. 0
  : descB(1, 0)
  RW B <- descB(1, 0)
       -> descB(1, 0)
  READ A <- A P(m)         [type = LOWER_TILE]
BODY
  B = B + A
END

CU(m)
  m = 0 .. 0
  : descC(1, 0)
  RW C <- descC(1, 0)
       -> descC(1, 0)
  READ A <- A P(m)         [type = UPPER_TILE]
BODY
  C = C + A
END
"""


def test_s12_s13_remote_multiple_outs_same_pred_flow():
    """One producer flow fans out to remote consumers under TWO different
    datatypes: each consumer receives its own shape, each type is packed/
    sent once (remote_multiple_outs_same_pred_flow*.jdf)."""
    def program(rank, fabric):
        ctx = Context(nb_cores=1, my_rank=rank, nb_ranks=2)
        RemoteDepEngine(ctx, ThreadsCE(fabric, rank))
        A = TwoDimBlockCyclic("descA", TS, TS, TS, TS, P=1, Q=1,
                              nodes=2, myrank=rank)       # rank 0
        B = TwoDimBlockCyclic("descB", M, TS, TS, TS, P=2, Q=1,
                              nodes=2, myrank=rank)       # row 1 -> rank 1
        C = TwoDimBlockCyclic("descC", M, TS, TS, TS, P=2, Q=1,
                              nodes=2, myrank=rank)
        A.fill(lambda m, n: np.full((TS, TS), 1.0, np.float32))
        B.fill(lambda m, n: np.zeros((TS, TS), np.float32))
        C.fill(lambda m, n: np.zeros((TS, TS), np.float32))
        tp = compile_ptg(_MULTI_SRC, "multi").instantiate(
            ctx, collections={"descA": A, "descB": B, "descC": C},
            datatypes={"LOWER_TILE": lower_tile(),
                       "UPPER_TILE": upper_tile()})
        ctx.add_taskpool(tp)
        ctx.wait(timeout=60)
        ctx.fini()
        if rank == 1:
            return (np.asarray(B.data_of(1, 0).newest_copy().payload),
                    np.asarray(C.data_of(1, 0).newest_copy().payload))
        return None

    results = run_distributed(2, program, timeout=60)
    lower_got, upper_got = results[1]
    np.testing.assert_array_equal(lower_got,
                                  np.tril(np.ones((TS, TS), np.float32)))
    np.testing.assert_array_equal(upper_got,
                                  np.triu(np.ones((TS, TS), np.float32)))


def test_s14_guarded_typed_edges_resolve_exactly():
    """Two guarded out-deps to the same (class, flow), only one typed: the
    datatype attaches to the edge that actually FIRES for each task
    (regression: name-only matching reshaped C(1)'s input too)."""
    src = """
%global descA
%global descB

P(m)
  m = 0 .. 1
  : descA(m, 0)
  RW A <- descA(m, 0)
       -> (m == 0) ? A C(m)   [type = LOWER_TILE]
       -> (m == 1) ? A C(m)
BODY
  A = A
END

C(m)
  m = 0 .. 1
  : descB(m, 0)
  RW B <- descB(m, 0)
       -> descB(m, 0)
  READ A <- A P(m)
BODY
  B = B + A
END
"""
    ctx = Context(nb_cores=1)
    A = _mk("descA")
    B = _mk("descB", val=0.0)
    tp = compile_ptg(src, "guarded").instantiate(
        ctx, collections={"descA": A, "descB": B},
        datatypes={"LOWER_TILE": lower_tile()})
    ctx.add_taskpool(tp)
    ctx.wait(timeout=30)
    ctx.fini()
    got = B.to_dense()
    ones, tril = np.ones((TS, TS), np.float32), \
        np.tril(np.ones((TS, TS), np.float32))
    np.testing.assert_array_equal(got[:TS, :TS], tril)   # C(0): typed edge
    np.testing.assert_array_equal(got[TS:, :TS], ones)   # C(1): untyped edge


def test_s15_type_remote_applies_on_wire_only():
    """[type_remote]: the LOCAL successor keeps the full original copy
    while the REMOTE successor receives the wire-typed payload
    (local_no_reshape.jdf's type_remote semantics)."""
    src = """
%global descA
%global descB
%global descC

P(m)
  m = 0 .. 0
  : descA(0, 0)
  RW A <- descA(0, 0)
       -> A CL(m)            [type_remote = LOWER_TILE]
       -> A CR(m)            [type_remote = LOWER_TILE]
BODY
  A = A
END

CL(m)
  m = 0 .. 0
  : descB(0, 0)
  RW B <- descB(0, 0)
       -> descB(0, 0)
  READ A <- A P(m)
BODY
  B = B + A
END

CR(m)
  m = 0 .. 0
  : descC(1, 0)
  RW C <- descC(1, 0)
       -> descC(1, 0)
  READ A <- A P(m)
BODY
  C = C + A
END
"""
    def program(rank, fabric):
        ctx = Context(nb_cores=1, my_rank=rank, nb_ranks=2)
        RemoteDepEngine(ctx, ThreadsCE(fabric, rank))
        A = TwoDimBlockCyclic("descA", TS, TS, TS, TS, P=1, Q=1,
                              nodes=2, myrank=rank)       # rank 0
        B = TwoDimBlockCyclic("descB", TS, TS, TS, TS, P=1, Q=1,
                              nodes=2, myrank=rank)       # rank 0 (local)
        C = TwoDimBlockCyclic("descC", M, TS, TS, TS, P=2, Q=1,
                              nodes=2, myrank=rank)       # row 1 -> rank 1
        A.fill(lambda m, n: np.full((TS, TS), 1.0, np.float32))
        B.fill(lambda m, n: np.zeros((TS, TS), np.float32))
        C.fill(lambda m, n: np.zeros((TS, TS), np.float32))
        tp = compile_ptg(src, "trem").instantiate(
            ctx, collections={"descA": A, "descB": B, "descC": C},
            datatypes={"LOWER_TILE": lower_tile()})
        ctx.add_taskpool(tp)
        ctx.wait(timeout=60)
        ctx.fini()
        if rank == 0:
            return np.asarray(B.data_of(0, 0).newest_copy().payload)
        return np.asarray(C.data_of(1, 0).newest_copy().payload)

    results = run_distributed(2, program, timeout=60)
    # local successor saw the FULL tile; remote got the wire-typed payload
    np.testing.assert_array_equal(results[0], np.ones((TS, TS), np.float32))
    np.testing.assert_array_equal(results[1],
                                  np.tril(np.ones((TS, TS), np.float32)))
