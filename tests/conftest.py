"""Test configuration.

Tests run on a virtual 8-device CPU mesh so multi-chip sharding paths are
exercised without TPU hardware (the reference's analogue: running every test
under oversubscribed localhost MPI with 2-4 ranks, tests/CMakeLists.txt:1032).
Must set the env before jax is imported anywhere.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"  # force: the ambient env points at the TPU
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

# The environment's sitecustomize may have force-registered a TPU platform and
# overridden jax_platforms at interpreter boot; override it back before any
# backend initialization so tests never touch the TPU tunnel.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _fresh_cost_model():
    """The online cost model (ISSUE 18) is process-global by design —
    it must survive context fini to feed warm instantiations. Under
    pytest that globality would leak measurements between unrelated
    tests (a class measured slow in one test steers placement/fusion in
    the next), so every test starts from a cold model, mirroring how
    LaneStats snapshots isolate the engagement counters."""
    yield
    from parsec_tpu.core import costmodel
    costmodel.model.reset()


@pytest.fixture()
def context():
    """A fresh single-rank runtime context per test."""
    from parsec_tpu.core.context import Context
    ctx = Context(nb_cores=1)
    yield ctx
    ctx.fini()
