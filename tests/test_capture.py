"""Graph-capture tests: a DTD taskpool compiled into one XLA executable.

The capture mode (dsl/capture.py) must produce bit-for-bit the same tile
results as the task-by-task scheduler on the same DAGs, cache compiled
programs across identical DAG shapes, and reject what it cannot capture.
"""

import numpy as np
import pytest

import parsec_tpu as pt
from parsec_tpu.data.matrix import TwoDimBlockCyclic
from parsec_tpu.dsl.dtd import DTDTaskpool, READ, RW
from parsec_tpu.ops.gemm import insert_gemm_tasks
from parsec_tpu.ops.potrf import insert_potrf_tasks, make_spd


@pytest.fixture()
def ctx():
    c = pt.Context(nb_cores=1)
    yield c
    c.fini()


def _gemm_collections(prefix, n, ts, a, b):
    A = TwoDimBlockCyclic(prefix + "A", n, n, ts, ts, P=1, Q=1)
    B = TwoDimBlockCyclic(prefix + "B", n, n, ts, ts, P=1, Q=1)
    C = TwoDimBlockCyclic(prefix + "C", n, n, ts, ts, P=1, Q=1)
    A.fill(lambda m, k: a[m*ts:(m+1)*ts, k*ts:(k+1)*ts])
    B.fill(lambda m, k: b[m*ts:(m+1)*ts, k*ts:(k+1)*ts])
    C.fill(lambda m, k: np.zeros((ts, ts), np.float32))
    return A, B, C


@pytest.mark.parametrize("batch_k", [False, True])
def test_capture_gemm_matches_scheduler(ctx, batch_k):
    n, ts = 64, 16
    rng = np.random.default_rng(3)
    a = rng.standard_normal((n, n)).astype(np.float32)
    b = rng.standard_normal((n, n)).astype(np.float32)

    _, _, Cs = _gemm_collections("s", n, ts, a, b)
    As, Bs, _ = _gemm_collections("s2", n, ts, a, b)
    tp = DTDTaskpool(ctx, "sched-gemm")
    insert_gemm_tasks(tp, As, Bs, Cs, batch_k=batch_k)
    tp.wait(timeout=60)
    tp.close()
    ctx.wait(timeout=30)

    Ac, Bc, Cc = _gemm_collections("c", n, ts, a, b)
    cap = DTDTaskpool(ctx, "cap-gemm", capture=True)
    insert_gemm_tasks(cap, Ac, Bc, Cc, batch_k=batch_k)
    assert cap.inserted == tp.inserted
    cap.wait()
    cap.close()
    ctx.wait(timeout=30)

    np.testing.assert_allclose(np.asarray(Cc.to_dense()),
                               np.asarray(Cs.to_dense()), rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(Cc.to_dense()), a @ b,
                               rtol=1e-3, atol=1e-3)


def test_capture_potrf_matches_scheduler(ctx):
    """The serial-critical-path DAG where capture matters most: POTRF's
    panel chain becomes one executable."""
    n, ts = 64, 16
    spd = make_spd(n, seed=9)

    P1 = TwoDimBlockCyclic("pS", n, n, ts, ts, P=1, Q=1)
    P1.fill(lambda m, k: spd[m*ts:(m+1)*ts, k*ts:(k+1)*ts])
    tp = DTDTaskpool(ctx, "sched-potrf")
    insert_potrf_tasks(tp, P1)
    tp.wait(timeout=60)
    tp.close()
    ctx.wait(timeout=30)

    P2 = TwoDimBlockCyclic("pC", n, n, ts, ts, P=1, Q=1)
    P2.fill(lambda m, k: spd[m*ts:(m+1)*ts, k*ts:(k+1)*ts])
    cap = DTDTaskpool(ctx, "cap-potrf", capture=True)
    insert_potrf_tasks(cap, P2)
    cap.wait()
    cap.close()
    ctx.wait(timeout=30)

    got = np.tril(np.asarray(P2.to_dense(), dtype=np.float64))
    ref = np.tril(np.asarray(P1.to_dense(), dtype=np.float64))
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(got, np.linalg.cholesky(spd.astype(np.float64)),
                               rtol=0, atol=2e-2)


def test_capture_program_cache(ctx):
    """Identical DAG shapes reuse the compiled executable; a changed shape
    recompiles."""
    n, ts = 32, 16
    rng = np.random.default_rng(5)
    a = rng.standard_normal((n, n)).astype(np.float32)
    b = rng.standard_normal((n, n)).astype(np.float32)

    A, B, C = _gemm_collections("h", n, ts, a, b)
    cap = DTDTaskpool(ctx, "cache-gemm", capture=True)
    insert_gemm_tasks(cap, A, B, C, batch_k=True)
    cap.wait()
    assert not cap._capture.cache_hit        # first shape: compile
    insert_gemm_tasks(cap, A, B, C, batch_k=True)
    cap.wait()
    assert cap._capture.cache_hit            # same shape: cached
    assert cap._capture.executions == 2
    cap.close()
    ctx.wait(timeout=30)
    # C accumulated the product twice
    np.testing.assert_allclose(np.asarray(C.to_dense()), 2 * (a @ b),
                               rtol=1e-3, atol=1e-3)


def test_capture_rejects_nonjit_and_multirank(ctx):
    from parsec_tpu.utils import mca as _mca
    _mca.set("capture_auto_defer", False)   # restore the hard reject
    try:
        cap = DTDTaskpool(ctx, "cap-neg", capture=True)
        t = cap.tile_new((4, 4), np.float32)
        with pytest.raises(RuntimeError, match="jit-traceable"):
            cap.insert_task(lambda x: x, (t, RW), jit=False)
        cap.close()
    finally:
        _mca.params.unset("capture_auto_defer")

    from parsec_tpu.comm.remote_dep import RemoteDepEngine
    from parsec_tpu.comm.threads import ThreadsCE, run_distributed

    def program(rank, fabric):
        c = pt.Context(nb_cores=1, my_rank=rank, nb_ranks=2)
        RemoteDepEngine(c, ThreadsCE(fabric, rank))
        try:
            DTDTaskpool(c, "cap2", capture=True)
            return "accepted"
        except RuntimeError as e:
            return str(e)
        finally:
            c.fini(timeout=5)

    results = run_distributed(2, program, timeout=30)
    assert all("single-rank" in r for r in results)


def test_capture_close_executes_pending(ctx):
    """close() without wait() must execute the recorded DAG, matching
    scheduler semantics where inserted tasks run without an explicit
    taskpool wait."""
    cap = DTDTaskpool(ctx, "cap-close", capture=True)
    t = cap.tile_new((4, 4), np.float32)
    t.data.create_copy(0, np.ones((4, 4), np.float32))
    cap.insert_task(lambda x: x + 1.0, (t, RW))
    cap.close()                     # no wait()
    ctx.wait(timeout=30)
    np.testing.assert_allclose(np.asarray(t.data.newest_copy().payload), 2.0)
    assert cap._capture.executions == 1


def test_capture_mixed_value_args(ctx):
    """Scalar params bake into the trace; ndarray params ride as inputs."""
    cap = DTDTaskpool(ctx, "cap-mixed", capture=True)
    t = cap.tile_new((4, 4), np.float32)
    host = cap.tile_new((4, 4), np.float32)
    t.data.create_copy(0, np.ones((4, 4), np.float32))
    host.data.create_copy(0, np.zeros((4, 4), np.float32))
    bias = np.full((4, 4), 0.5, np.float32)

    def scale_add(x, alpha, b):
        return x * alpha + b

    cap.insert_task(scale_add, (t, RW), 3.0, bias)
    cap.insert_task(lambda dst, s: dst + s, (host, RW), (t, READ))
    cap.wait()
    cap.close()
    ctx.wait(timeout=30)
    np.testing.assert_allclose(np.asarray(host.data.newest_copy().payload),
                               3.0 + 0.5)
    np.testing.assert_allclose(np.asarray(t.data.newest_copy().payload),
                               3.0 + 0.5)


def test_capture_ptg_via_replay(ctx):
    """A PTG program — static task space — compiled into ONE XLA executable
    through the cross-DSL replay (ptg_to_dtd + capture): tile GEMM results
    match the PTG scheduler execution."""
    from parsec_tpu.core.pins_modules import ptg_to_dtd_replay
    from parsec_tpu.data.matrix import TiledMatrix
    from parsec_tpu.dsl.ptg.compiler import compile_ptg

    src = """
%global MT
%global KT
%global descA
%global descB
%global descC

GEMM(m, n, k)
  m = 0 .. MT-1
  n = 0 .. MT-1
  k = 0 .. KT-1
  : descC(m, n)
  READ A <- descA(m, k)
  READ B <- descB(k, n)
  RW   C <- (k == 0) ? descC(m, n) : C GEMM(m, n, k-1)
       -> (k < KT-1) ? C GEMM(m, n, k+1) : descC(m, n)
BODY
  C = C + jnp.dot(A, B, preferred_element_type=jnp.float32)
END
"""
    MT = KT = 2
    TS = 8
    rng = np.random.default_rng(13)
    a = rng.standard_normal((MT*TS, KT*TS)).astype(np.float32)
    b = rng.standard_normal((KT*TS, MT*TS)).astype(np.float32)

    def mats(prefix):
        A = TiledMatrix(prefix + "A", MT*TS, KT*TS, TS, TS)
        B = TiledMatrix(prefix + "B", KT*TS, MT*TS, TS, TS)
        Cm = TiledMatrix(prefix + "C", MT*TS, MT*TS, TS, TS)
        A.fill(lambda m, k: a[m*TS:(m+1)*TS, k*TS:(k+1)*TS])
        B.fill(lambda k, n: b[k*TS:(k+1)*TS, n*TS:(n+1)*TS])
        Cm.fill(lambda m, n: np.zeros((TS, TS), np.float32))
        return A, B, Cm

    # scheduler PTG execution
    A1, B1, C1 = mats("rs")
    prog = compile_ptg(src, "capgemm")
    ptp = prog.instantiate(ctx, globals={"MT": MT, "KT": KT},
                           collections={"descA": A1, "descB": B1, "descC": C1})
    ctx.add_taskpool(ptp)
    ctx.wait(timeout=60)

    # captured replay of the same program
    A2, B2, C2 = mats("rc")
    ptp2 = prog.instantiate(ctx, globals={"MT": MT, "KT": KT},
                            collections={"descA": A2, "descB": B2,
                                         "descC": C2}, name="capgemm2")
    dtp = ptg_to_dtd_replay(ptp2, ctx, capture=True)
    assert dtp._capture is not None
    dtp.wait()
    dtp.close()
    ctx.wait(timeout=60)
    assert dtp._capture.executions == 1

    # replay writes through the same C tiles the PTG version wrote
    np.testing.assert_allclose(np.asarray(C2.to_dense()),
                               np.asarray(C1.to_dense()), rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(C2.to_dense()), a @ b,
                               rtol=1e-4, atol=1e-4)


# ------------------------------------------------------- mesh capture

def _mesh2d():
    import jax
    from jax.sharding import Mesh
    devs = jax.devices()
    if len(devs) < 8:
        pytest.skip("needs 8 virtual devices")
    return Mesh(np.array(devs[:8]).reshape(2, 4), ("x", "y"))


def test_mesh_capture_gemm(ctx):
    """The whole tiled-GEMM DAG as ONE GSPMD program over a 2x4 mesh:
    collection tiles become slices of sharded globals, XLA partitions the
    ops and inserts the transfers; results match numpy."""
    mesh = _mesh2d()
    n, ts = 64, 16
    rng = np.random.default_rng(21)
    a = rng.standard_normal((n, n)).astype(np.float32)
    b = rng.standard_normal((n, n)).astype(np.float32)
    A, B, C = _gemm_collections("m", n, ts, a, b)
    cap = DTDTaskpool(ctx, "mesh-gemm", capture=True)
    insert_gemm_tasks(cap, A, B, C, batch_k=True)
    cap.wait_mesh(mesh)
    cap.close()
    ctx.wait(timeout=30)
    np.testing.assert_allclose(np.asarray(C.to_dense()), a @ b,
                               rtol=1e-3, atol=1e-3)


def test_mesh_capture_potrf_matches_single(ctx):
    """Mesh capture on the factorization DAG (slices + update-slices with
    serial dependencies) matches the single-device captured result."""
    mesh = _mesh2d()
    n, ts = 64, 16
    spd = make_spd(n, seed=17)

    P1 = TwoDimBlockCyclic("mp1", n, n, ts, ts, P=1, Q=1)
    P1.fill(lambda m, k: spd[m*ts:(m+1)*ts, k*ts:(k+1)*ts])
    cap1 = DTDTaskpool(ctx, "mp-single", capture=True)
    insert_potrf_tasks(cap1, P1)
    cap1.wait()
    cap1.close()

    P2 = TwoDimBlockCyclic("mp2", n, n, ts, ts, P=1, Q=1)
    P2.fill(lambda m, k: spd[m*ts:(m+1)*ts, k*ts:(k+1)*ts])
    cap2 = DTDTaskpool(ctx, "mp-mesh", capture=True)
    insert_potrf_tasks(cap2, P2)
    cap2.wait_mesh(mesh)
    cap2.close()
    ctx.wait(timeout=30)

    got = np.tril(np.asarray(P2.to_dense(), np.float64))
    ref = np.tril(np.asarray(P1.to_dense(), np.float64))
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-5)


def test_mesh_capture_scratch_and_guards(ctx):
    """Scratch tiles ride replicated; indivisible globals are rejected."""
    mesh = _mesh2d()
    cap = DTDTaskpool(ctx, "mesh-scratch", capture=True)
    t = cap.tile_new((8, 8), np.float32)
    t.data.create_copy(0, np.ones((8, 8), np.float32))
    cap.insert_task(lambda x: x * 3.0, (t, RW))
    cap.wait_mesh(mesh)
    cap.close()
    ctx.wait(timeout=30)
    np.testing.assert_allclose(np.asarray(t.data.newest_copy().payload), 3.0)

    bad = TwoDimBlockCyclic("meshbad", 10, 10, 5, 5, P=1, Q=1)  # 10 % 4 != 0
    bad.fill(lambda m, n: np.zeros((5, 5), np.float32))
    cap2 = DTDTaskpool(ctx, "mesh-bad", capture=True)
    try:
        cap2.insert_task(lambda x: x + 1.0, (cap2.tile_of(bad, 0, 0), RW))
        with pytest.raises(RuntimeError, match="divisible"):
            cap2.wait_mesh(mesh)
        # the rejected batch is DISCARDED: close() must not silently run it
        # single-device
        assert cap2._capture.ops == []
    finally:
        cap2.close()
    assert cap2._capture.executions == 0
    np.testing.assert_allclose(
        np.asarray(bad.data_of(0, 0).newest_copy().payload), 0.0)


def test_mesh_capture_program_cache(ctx):
    """Identical distributed DAG shapes over the same mesh reuse the
    compiled GSPMD executable."""
    mesh = _mesh2d()
    n, ts = 32, 8
    rng = np.random.default_rng(23)
    a = rng.standard_normal((n, n)).astype(np.float32)
    b = rng.standard_normal((n, n)).astype(np.float32)
    A, B, C = _gemm_collections("mc", n, ts, a, b)
    cap = DTDTaskpool(ctx, "mesh-cache", capture=True)
    insert_gemm_tasks(cap, A, B, C, batch_k=True)
    cap.wait_mesh(mesh)
    assert not cap._capture.cache_hit
    insert_gemm_tasks(cap, A, B, C, batch_k=True)
    cap.wait_mesh(mesh)
    assert cap._capture.cache_hit
    cap.close()
    ctx.wait(timeout=30)
    np.testing.assert_allclose(np.asarray(C.to_dense()), 2 * (a @ b),
                               rtol=1e-3, atol=1e-3)


def test_wait_mesh_requires_capture(ctx):
    tp = DTDTaskpool(ctx, "nomesh")
    with pytest.raises(RuntimeError, match="capture"):
        tp.wait_mesh(None)
    tp.close()


@pytest.mark.parametrize("which", ["getrf", "geqrf"])
def test_capture_lu_qr_match_scheduler(ctx, which):
    """Capture generality: the LU and QR tile DAGs (solves, householder
    panels) compile whole and match the scheduler path."""
    n, ts = 48, 16
    if which == "getrf":
        from parsec_tpu.ops.getrf import insert_getrf_tasks as ins, make_dd
        src = make_dd(n, seed=3)
    else:
        from parsec_tpu.ops.geqrf import insert_geqrf_tasks as ins
        rng = np.random.default_rng(3)
        src = rng.standard_normal((n, n)).astype(np.float32)

    def run(capture):
        M = TwoDimBlockCyclic(f"{which}{capture}", n, n, ts, ts, P=1, Q=1)
        M.fill(lambda m, k: src[m*ts:(m+1)*ts, k*ts:(k+1)*ts])
        tp = DTDTaskpool(ctx, f"{which}-{capture}", capture=capture)
        ins(tp, M)
        tp.wait(timeout=60)
        tp.close()
        ctx.wait(timeout=30)
        return np.asarray(M.to_dense(), np.float64)

    sched = run(False)
    cap = run(True)
    np.testing.assert_allclose(cap, sched, rtol=1e-4, atol=1e-4)


def test_capture_stencil_matches_scheduler(ctx):
    """The iterative halo-exchange DAG (BASELINE config 4's 1D shape)
    compiles whole: ping-pong buffers and neighbor reads trace through."""
    from parsec_tpu.data.matrix import TiledMatrix
    from parsec_tpu.ops.stencil import insert_stencil1d_tasks

    cols, ts, iters = 64, 16, 4
    rng = np.random.default_rng(2)
    init = rng.standard_normal((8, cols)).astype(np.float32)

    def run(capture):
        A = TiledMatrix(f"stA{capture}", 8, cols, 8, ts)
        B = TiledMatrix(f"stB{capture}", 8, cols, 8, ts)
        A.fill(lambda m, n: init[:, n*ts:(n+1)*ts])
        B.fill(lambda m, n: np.zeros((8, ts), np.float32))
        tp = DTDTaskpool(ctx, f"st{capture}", capture=capture)
        insert_stencil1d_tasks(tp, A, B, iters)
        tp.wait(timeout=60)
        tp.close()
        ctx.wait(timeout=30)
        return np.asarray(A.to_dense())     # iters even -> result in A

    np.testing.assert_allclose(run(True), run(False), rtol=1e-6, atol=1e-6)


# ------------------------------------------------- scan-interpreter capture

def test_scan_capture_gemm_matches_scheduler(ctx):
    """The scanned task interpreter (capture="scan") produces the same tile
    results as the scheduler on the tiled-GEMM DAG."""
    n, ts = 64, 16
    rng = np.random.default_rng(31)
    a = rng.standard_normal((n, n)).astype(np.float32)
    b = rng.standard_normal((n, n)).astype(np.float32)

    A1, B1, C1 = _gemm_collections("zs", n, ts, a, b)
    tp = DTDTaskpool(ctx, "zsched")
    insert_gemm_tasks(tp, A1, B1, C1, batch_k=False)
    tp.wait(timeout=60)
    tp.close()
    ctx.wait(timeout=30)

    A2, B2, C2 = _gemm_collections("zc", n, ts, a, b)
    cap = DTDTaskpool(ctx, "zscan", capture="scan")
    insert_gemm_tasks(cap, A2, B2, C2, batch_k=False)
    cap.wait()
    cap.close()
    ctx.wait(timeout=30)
    assert cap._capture.last_mode == "scan"

    np.testing.assert_allclose(np.asarray(C2.to_dense()),
                               np.asarray(C1.to_dense()), rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(C2.to_dense()), a @ b,
                               rtol=1e-3, atol=1e-3)


def test_scan_capture_potrf_matches_scheduler(ctx):
    """The DAG the scan mode exists for: POTRF's decompose-heavy bodies
    (cholesky, triangular solves) appear ONCE per class in the program
    instead of once per task."""
    n, ts = 64, 16
    spd = make_spd(n, seed=29)

    P1 = TwoDimBlockCyclic("zp1", n, n, ts, ts, P=1, Q=1)
    P1.fill(lambda m, k: spd[m*ts:(m+1)*ts, k*ts:(k+1)*ts])
    tp = DTDTaskpool(ctx, "zp-sched")
    insert_potrf_tasks(tp, P1)
    tp.wait(timeout=60)
    tp.close()
    ctx.wait(timeout=30)

    P2 = TwoDimBlockCyclic("zp2", n, n, ts, ts, P=1, Q=1)
    P2.fill(lambda m, k: spd[m*ts:(m+1)*ts, k*ts:(k+1)*ts])
    cap = DTDTaskpool(ctx, "zp-scan", capture="scan")
    insert_potrf_tasks(cap, P2)
    cap.wait()
    cap.close()
    ctx.wait(timeout=30)
    assert cap._capture.last_mode == "scan"

    got = np.tril(np.asarray(P2.to_dense(), np.float64))
    ref = np.tril(np.asarray(P1.to_dense(), np.float64))
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-5)


def test_scan_capture_program_reuse_across_different_dags(ctx):
    """Descriptor rows are runtime DATA: two DIFFERENT DAGs with the same
    task classes, op count and store geometry share one compiled executable
    (the PTG task-class insight applied to XLA program size)."""
    ts = 8

    def axpy(y, x):
        return y + 2.0 * x

    def run(perm, name):
        cap = DTDTaskpool(ctx, name, capture="scan")
        tiles = [cap.tile_new((ts, ts), np.float32) for _ in range(4)]
        for i, t in enumerate(tiles):
            t.data.create_copy(0, np.full((ts, ts), float(i), np.float32))
        for dst, src in perm:                     # same class, different rows
            cap.insert_task(axpy, (tiles[dst], RW), (tiles[src], READ))
        cap.wait()
        hit = cap._capture.cache_hit
        cap.close()
        ctx.wait(timeout=30)
        vals = [np.asarray(t.data.newest_copy().payload)[0, 0] for t in tiles]
        return hit, vals

    hit1, v1 = run([(0, 1), (2, 3), (0, 2), (1, 3)], "zr1")
    hit2, v2 = run([(3, 0), (1, 2), (3, 1), (2, 0)], "zr2")
    assert not hit1 and hit2       # second DAG reuses the first's executable
    # independent references (task graph semantics on the host side)
    assert v1 == [0 + 2*1 + 2*(2 + 2*3), 1 + 2*3, 2 + 2*3, 3.0]
    assert v2 == [0.0, 1 + 2*2, 2 + 2*0, 3 + 2*0 + 2*(1 + 2*2)]


def test_scan_capture_scalar_args_split_classes(ctx):
    """Scalar args are baked per class: ops differing only in a scalar are
    distinct classes and produce distinct results."""
    cap = DTDTaskpool(ctx, "zsc", capture="scan")
    t1 = cap.tile_new((4, 4), np.float32)
    t2 = cap.tile_new((4, 4), np.float32)
    t1.data.create_copy(0, np.ones((4, 4), np.float32))
    t2.data.create_copy(0, np.ones((4, 4), np.float32))

    def scale(x, alpha):
        return x * alpha

    cap.insert_task(scale, (t1, RW), 3.0)
    cap.insert_task(scale, (t2, RW), 5.0)
    cap.wait()
    cap.close()
    ctx.wait(timeout=30)
    np.testing.assert_allclose(np.asarray(t1.data.newest_copy().payload), 3.0)
    np.testing.assert_allclose(np.asarray(t2.data.newest_copy().payload), 5.0)


def test_scan_capture_rejects_raw_array_args(ctx):
    """Raw ndarray args are not scannable (they would bloat the descriptor
    rows); explicit scan mode must fail loudly, auto must fall back."""
    cap = DTDTaskpool(ctx, "zneg", capture="scan")
    t = cap.tile_new((4, 4), np.float32)
    t.data.create_copy(0, np.ones((4, 4), np.float32))
    cap.insert_task(lambda x, b: x + b, (t, RW),
                    np.zeros((4, 4), np.float32))
    with pytest.raises(Exception, match="scan"):
        cap.wait()
    cap._capture.ops.clear()        # drop the unexecutable recording
    cap.close()


def test_auto_capture_picks_scan_above_threshold(ctx):
    """capture=True (auto) stays inline below the MCA threshold and switches
    to the scan interpreter above it."""
    from parsec_tpu.utils import mca
    old = mca.get("capture_scan_threshold", 64)
    mca.set("capture_scan_threshold", 8)
    try:
        def bump(x):
            return x + 1.0

        def run(nops, name):
            cap = DTDTaskpool(ctx, name, capture=True)
            t = cap.tile_new((4, 4), np.float32)
            t.data.create_copy(0, np.zeros((4, 4), np.float32))
            for _ in range(nops):
                cap.insert_task(bump, (t, RW))
            cap.wait()
            mode = cap._capture.last_mode
            cap.close()
            ctx.wait(timeout=30)
            return mode, np.asarray(t.data.newest_copy().payload)[0, 0]

        mode_small, v_small = run(4, "zat-s")
        mode_big, v_big = run(16, "zat-b")
        assert mode_small == "inline" and v_small == 4.0
        assert mode_big == "scan" and v_big == 16.0
    finally:
        mca.set("capture_scan_threshold", old)


# ------------------------------------------- mesh-capture sharding quality

def _collective_ops(hlo: str):
    """(op kind, result bytes) for every collective in compiled HLO text."""
    import re
    bytes_of = {"f32": 4, "f64": 8, "bf16": 2, "f16": 2, "s32": 4,
                "u32": 4, "s8": 1, "u8": 1, "pred": 1}
    out = []
    for line in hlo.splitlines():
        m = re.search(
            r"=\s+(\w+)\[([\d,]*)\][^ ]*\s+"
            r"(all-gather|all-reduce|collective-permute|all-to-all|"
            r"reduce-scatter)", line)
        if m:
            el = 1
            for d in m.group(2).split(","):
                if d:
                    el *= int(d)
            out.append((m.group(3), el * bytes_of.get(m.group(1), 4)))
    return out


@pytest.mark.parametrize("n", [64, 128])
def test_mesh_capture_collectives_scale_with_halo(ctx, n):
    """Sharding quality of the GSPMD program wait_mesh compiles: every
    collective moves tile-halo-sized data — no collective materializes a
    whole matrix, and the largest transfer stays at tile granularity as
    the matrix grows (communication scales with the halo, not O(N^2)
    replication)."""
    mesh = _mesh2d()
    ts = 16
    rng = np.random.default_rng(25)
    a = rng.standard_normal((n, n)).astype(np.float32)
    b = rng.standard_normal((n, n)).astype(np.float32)
    A, B, C = _gemm_collections(f"hq{n}", n, ts, a, b)
    cap = DTDTaskpool(ctx, f"hlo-gemm{n}", capture=True)
    insert_gemm_tasks(cap, A, B, C, batch_k=True)
    cap.wait_mesh(mesh)
    hlo = cap._capture.mesh_hlo()
    cap.close()
    ctx.wait(timeout=30)
    np.testing.assert_allclose(np.asarray(C.to_dense()), a @ b,
                               rtol=1e-3, atol=1e-3)

    colls = _collective_ops(hlo)
    assert colls, "compiled mesh program has no collectives (unexpected " \
                  "for a 2x4-sharded GEMM)"
    tile_bytes = ts * ts * 4
    matrix_bytes = n * n * 4
    worst = max(by for _, by in colls)
    # halo granularity: the largest single collective moves at most one
    # tile (2x slack for fused pairs) — and NEVER a whole matrix
    assert worst <= 2 * tile_bytes, \
        f"largest collective moves {worst} B (> tile {tile_bytes} B)"
    assert worst < matrix_bytes / 4, \
        f"collective {worst} B is matrix-scale ({matrix_bytes} B)"


def test_scan_capture_scales_to_hundreds_of_tasks(ctx):
    """The round-3 pathology regression gate: an 816-task POTRF DAG under
    the scan strategy compiles + runs in seconds (the inlined strategy
    compiled superlinearly and ran 25-60x its op-sum on chip), and a
    second DAG of the same geometry reuses the executable."""
    import time

    NT, ts = 16, 32
    n = NT * ts
    spd = make_spd(n, seed=3)
    P = TwoDimBlockCyclic("scS", n, n, ts, ts, P=1, Q=1)
    P.fill(lambda m, k: spd[m*ts:(m+1)*ts, k*ts:(k+1)*ts])
    tp = DTDTaskpool(ctx, "scan-scale", capture="scan")
    insert_potrf_tasks(tp, P)
    t0 = time.perf_counter()
    tp.wait()
    first_s = time.perf_counter() - t0
    assert not tp._capture.cache_hit
    assert first_s < 60, f"compile+run took {first_s:.1f}s (blowup regressed)"

    P.fill(lambda m, k: spd[m*ts:(m+1)*ts, k*ts:(k+1)*ts])
    insert_potrf_tasks(tp, P)
    tp.wait()
    assert tp._capture.cache_hit        # same classes/geometry: cached
    tp.close()
    ctx.wait(timeout=30)
    L = np.tril(np.asarray(P.to_dense(), np.float64))
    np.testing.assert_allclose(
        L, np.linalg.cholesky(spd.astype(np.float64)), rtol=0, atol=1e-4)


def test_scan_capture_multi_write_flows(ctx):
    """A body with TWO write flows under the scan interpreter: both
    outputs land in their stores in argument order (the inline path's
    semantics)."""
    def swapscale(a, b):
        return b * 2.0, a * 3.0             # writes (a_new, b_new)

    cap = DTDTaskpool(ctx, "zmw", capture="scan")
    ta = cap.tile_new((4, 4), np.float32)
    tb = cap.tile_new((4, 4), np.float32)
    ta.data.create_copy(0, np.full((4, 4), 1.0, np.float32))
    tb.data.create_copy(0, np.full((4, 4), 10.0, np.float32))
    cap.insert_task(swapscale, (ta, RW), (tb, RW))
    cap.insert_task(swapscale, (ta, RW), (tb, RW))
    cap.wait()
    cap.close()
    ctx.wait(timeout=30)
    # step1: a=20, b=3; step2: a=6, b=60
    np.testing.assert_allclose(np.asarray(ta.data.newest_copy().payload), 6.0)
    np.testing.assert_allclose(np.asarray(tb.data.newest_copy().payload), 60.0)


def test_scan_rejects_dtype_mismatch_auto_falls_back_to_inline(ctx):
    """ADVICE r4 (medium): a body upcasting its f16 tile to f32 must land
    f32 under EVERY strategy — scan would silently round-trip through f16,
    so the planner rejects it and auto takes inline."""
    from parsec_tpu.utils import mca

    def upcast(a):
        return a.astype(np.float32) * 1.5

    mca.set("capture_scan_threshold", 2)   # force auto into scan territory
    try:
        cap = DTDTaskpool(ctx, "zdt", capture="auto")
        t = cap.tile_new((4, 4), np.float16)
        t.data.create_copy(0, np.full((4, 4), 2.0, np.float16))
        for _ in range(4):
            cap.insert_task(upcast, (t, RW))
        cap.wait()
        assert cap._capture.last_mode == "inline"
        cap.close()
        ctx.wait(timeout=30)
        out = np.asarray(t.data.newest_copy().payload)
        assert out.dtype == np.float32          # inline semantics preserved
        np.testing.assert_allclose(out, 2.0 * 1.5 ** 4)
    finally:
        mca.params.unset("capture_scan_threshold")


def test_scan_explicit_mode_rejects_dtype_mismatch(ctx):
    """Explicit capture='scan' with a dtype-changing body is an error, not
    a silent cast (f16 -> f32: a real change without x64 enabled)."""
    def upcast(a):
        return a.astype(np.float32)

    cap = DTDTaskpool(ctx, "zdx", capture="scan")
    t = cap.tile_new((4, 4), np.float16)
    t.data.create_copy(0, np.ones((4, 4), np.float16))
    cap.insert_task(upcast, (t, RW))
    with pytest.raises(Exception, match="scan capture rejected.*float32"):
        cap.wait()
    cap.close()


def test_scan_matching_dtypes_still_scans(ctx):
    """The dtype gate must not regress the scannable case."""
    def scale(a):
        return a * 2.0

    cap = DTDTaskpool(ctx, "zok", capture="scan")
    t = cap.tile_new((4, 4), np.float32)
    t.data.create_copy(0, np.ones((4, 4), np.float32))
    for _ in range(3):
        cap.insert_task(scale, (t, RW))
    cap.wait()
    assert cap._capture.last_mode == "scan"
    cap.close()
    ctx.wait(timeout=30)
    np.testing.assert_allclose(np.asarray(t.data.newest_copy().payload), 8.0)


def test_capture_auto_defers_noncapturable_window(ctx):
    """Per-region auto-defer (ISSUE 10): a window poisoned by a jit=False
    insert replays through the scheduler — the recorded prefix keeps its
    program order, results match a captured run — and the NEXT window
    captures again."""
    from parsec_tpu.dsl.dtd import PTDTD_STATS
    cap = DTDTaskpool(ctx, "cap-defer", capture=True)
    t = cap.tile_new((4, 4), np.float32)
    t.data.create_copy(0, np.ones((4, 4), np.float32))
    snap = PTDTD_STATS.snapshot()
    # window 1: two capturable inserts, then one that defeats capture
    cap.insert_task(lambda x: x * 2.0, (t, RW))
    cap.insert_task(lambda x: x + 1.0, (t, RW))

    def host_body(x):
        return np.asarray(x) + 0.5          # numpy: not jit-traceable

    cap.insert_task(host_body, (t, RW), jit=False)
    assert cap._capture_deferred
    assert PTDTD_STATS.delta(snap)["capture_windows_deferred"] == 1
    assert cap._capture.ops == []           # prefix handed to the scheduler
    cap.wait(timeout=30)
    np.testing.assert_allclose(np.asarray(t.data.newest_copy().payload),
                               1.0 * 2.0 + 1.0 + 0.5)
    # window 2: capture re-armed — a capturable window compiles whole
    assert not cap._capture_deferred
    cap.insert_task(lambda x: x * 3.0, (t, RW))
    assert len(cap._capture.ops) == 1
    cap.wait(timeout=30)
    cap.close()
    ctx.wait(timeout=30)
    np.testing.assert_allclose(np.asarray(t.data.newest_copy().payload),
                               3.5 * 3.0)
