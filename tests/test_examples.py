"""Every tutorial example runs green (the reference treats examples as
integration tests in its ctest suite).

Environment guards (the _needs_transfer pattern from
test_tcp_distributed.py): capabilities the INSTALLED jax/jaxlib may lack
— the PJRT transfer API (ex14's device-mem comms) and multiprocess CPU
collectives (ex15's multi-controller job) — skip instead of failing, so
tier-1 goes red only on real regressions."""

import os
import subprocess
import sys

import pytest

from parsec_tpu.comm.xhost import XHostTransfer
from parsec_tpu.parallel.multihost import cpu_collectives_available

EXAMPLES = [f"ex0{i}" for i in range(9)] + ["ex10", "ex11", "ex12", "ex13",
                                            "ex14", "ex15", "ex16", "ex17"]
EX_DIR = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                      "examples")

_needs_transfer = pytest.mark.skipif(
    not XHostTransfer.available(),
    reason="jax.experimental.transfer unavailable")


@pytest.mark.parametrize("ex", EXAMPLES)
def test_example_runs(ex):
    if ex == "ex15" and not cpu_collectives_available():
        pytest.skip("multiprocess CPU collectives unavailable in this jax")
    fname = [f for f in os.listdir(EX_DIR) if f.startswith(ex)][0]
    env = dict(os.environ, EXAMPLES_CPU="1", JAX_PLATFORMS="cpu")
    out = subprocess.run([sys.executable, fname], cwd=EX_DIR, env=env,
                         capture_output=True, text=True, timeout=110)
    assert out.returncode == 0, out.stderr[-2000:]


def test_example_tcp_launch():
    """Ex09 goes through the real multi-process launcher CLI."""
    fname = "ex09_tcp_launch.py"
    env = dict(os.environ, EXAMPLES_CPU="1")
    out = subprocess.run(
        [sys.executable, "-m", "parsec_tpu.launch", "-n", "2", "--cpu",
         os.path.join("examples", fname)],
        cwd=os.path.dirname(EX_DIR), env=env,
        capture_output=True, text=True, timeout=200)
    assert out.returncode == 0, out.stderr[-2000:]


@_needs_transfer
def test_example_device_mem_comms():
    """Ex14: device-native cross-rank payloads via the launcher's --mca."""
    fname = "ex14_device_mem_comms.py"
    env = dict(os.environ, EXAMPLES_CPU="1")
    out = subprocess.run(
        [sys.executable, "-m", "parsec_tpu.launch", "-n", "2", "--cpu",
         "--mca", "comm_device_mem", "1", os.path.join("examples", fname)],
        cwd=os.path.dirname(EX_DIR), env=env,
        capture_output=True, text=True, timeout=200)
    assert out.returncode == 0, out.stderr[-2000:]
