"""Device-native (ICI-role) CE backend tests.

The SURVEY §2.3 deliverable: task-runtime tile payloads move
device→device through the comm engine — remote tiles land in the
consumer's device memory without ever materializing host bytes on the
way (ref: accelerator-mem comms capability parsec/parsec_internal.h:504,
consumer-device landing remote_dep_mpi.c:2120). Each in-process rank
binds a distinct virtual device (the 8-device CPU mesh stands in for
chips; the transfer API — jax.device_put onto the consumer's device —
is exactly what rides ICI on real TPU hardware).
"""

import threading

import numpy as np
import pytest

from parsec_tpu.comm.ici import (CTR_D2D_BYTES, CTR_D2D_MSGS,
                                 CTR_HOST_MATERIALIZED, ICICE)
from parsec_tpu.comm.remote_dep import RemoteDepEngine
from parsec_tpu.comm.threads import run_distributed
from parsec_tpu.core.context import Context
from parsec_tpu.data.matrix import TwoDimBlockCyclic
from parsec_tpu.dsl.dtd import DTDTaskpool, READ, RW
from parsec_tpu.ops.gemm import insert_gemm_tasks
from parsec_tpu.ops.potrf import insert_potrf_tasks, make_spd
from parsec_tpu.utils import mca
from parsec_tpu.utils.counters import counters

_setup_lock = threading.Lock()


def _device_map(nb_ranks):
    import jax
    devs = jax.devices()
    return [devs[r % len(devs)] for r in range(nb_ranks)]


def _mkctx(rank, fabric, device_map):
    """Per-rank context whose TPU module binds device_map[rank] — the
    production shape (chip per rank), virtual devices standing in."""
    with _setup_lock:   # mca is process-global; serialize the binding
        mca.set("device_tpu_over_cpu", True)
        mca.set("device_tpu_over_cpu_index", device_map[rank].id)
        ctx = Context(nb_cores=1, my_rank=rank, nb_ranks=fabric.nb_ranks)
    RemoteDepEngine(ctx, ICICE(fabric, rank, device_map))
    return ctx


@pytest.fixture(autouse=True)
def _over_cpu_cleanup():
    yield
    mca.params.unset("device_tpu_over_cpu")
    mca.params.unset("device_tpu_over_cpu_index")


@pytest.mark.parametrize("nb_ranks", [2, 4])
def test_ici_dtd_gemm_device_to_device(nb_ranks):
    """Distributed DTD GEMM over the ICI backend: correctness AND the
    device-native property — produced tiles cross rank boundaries
    device→device (d2d counter advances) with ZERO host materializations
    of device payloads on the remote path."""
    N, TS = 64, 16
    rng = np.random.default_rng(21)
    a = rng.standard_normal((N, N)).astype(np.float32)
    b = rng.standard_normal((N, N)).astype(np.float32)
    dmap = _device_map(nb_ranks)

    def program(rank, fabric):
        ctx = _mkctx(rank, fabric, dmap)
        P = 2
        Q = nb_ranks // P
        kw = dict(nodes=nb_ranks, myrank=rank, P=P, Q=Q)
        A = TwoDimBlockCyclic("iA", N, N, TS, TS, **kw)
        B = TwoDimBlockCyclic("iB", N, N, TS, TS, **kw)
        C = TwoDimBlockCyclic("iC", N, N, TS, TS, **kw)
        A.fill(lambda m, n: a[m*TS:(m+1)*TS, n*TS:(n+1)*TS])
        B.fill(lambda m, n: b[m*TS:(m+1)*TS, n*TS:(n+1)*TS])
        C.fill(lambda m, n: np.zeros((TS, TS), np.float32))
        tp = DTDTaskpool(ctx, "ici-gemm")
        # warm A/B on-device at their owners first (a producing task per
        # tile): the panels that cross ranks are then DEVICE-resident
        # outputs — the steady-state shape of a real pipeline — so the
        # d2d counter measures produced-tile movement, not initial
        # host-data distribution
        for M in (A, B):
            for m in range(M.mt):
                for n in range(M.nt):
                    tp.insert_task(lambda x: x * 1.0,
                                   (tp.tile_of(M, m, n), RW), name="warm")
        insert_gemm_tasks(tp, A, B, C)
        tp.wait(timeout=60)
        tp.close()
        ctx.wait(timeout=60)
        ctx.fini()
        return {(m, n): np.asarray(C.data_of(m, n).newest_copy().payload)
                for m in range(C.mt) for n in range(C.nt)
                if C.rank_of(m, n) == rank}

    d2d0 = counters.read(CTR_D2D_MSGS)
    mat0 = counters.read(CTR_HOST_MATERIALIZED)
    bytes0 = counters.read(CTR_D2D_BYTES)
    results = run_distributed(nb_ranks, program, timeout=120)
    # the device-native property, asserted:
    assert counters.read(CTR_D2D_MSGS) > d2d0, \
        "no payload moved device-to-device"
    assert counters.read(CTR_D2D_BYTES) > bytes0
    assert counters.read(CTR_HOST_MATERIALIZED) == mat0, \
        "a device payload was materialized to host on the remote path"
    ref = a @ b
    full = {}
    for out in results:
        full.update(out)
    assert len(full) == (N // TS) ** 2
    for (m, n), tile in full.items():
        np.testing.assert_allclose(
            tile, ref[m*TS:(m+1)*TS, n*TS:(n+1)*TS], rtol=1e-3, atol=1e-3)


def test_ici_dtd_potrf():
    """Distributed DTD Cholesky over the ICI backend (the other headline
    kernel): factor panels cross HBM→HBM."""
    N, TS = 64, 16
    spd = make_spd(N, seed=23)
    dmap = _device_map(2)

    def program(rank, fabric):
        ctx = _mkctx(rank, fabric, dmap)
        A = TwoDimBlockCyclic("iP", N, N, TS, TS, P=2, Q=1,
                              nodes=2, myrank=rank)
        A.fill(lambda m, n: spd[m*TS:(m+1)*TS, n*TS:(n+1)*TS])
        tp = DTDTaskpool(ctx, "ici-potrf")
        insert_potrf_tasks(tp, A)
        tp.wait(timeout=60)
        tp.close()
        ctx.wait(timeout=60)
        ctx.fini()
        return {(m, n): np.asarray(A.data_of(m, n).newest_copy().payload)
                for m in range(A.mt) for n in range(A.nt)
                if A.rank_of(m, n) == rank and m >= n}

    d2d0 = counters.read(CTR_D2D_MSGS)
    mat0 = counters.read(CTR_HOST_MATERIALIZED)
    results = run_distributed(2, program, timeout=120)
    assert counters.read(CTR_D2D_MSGS) > d2d0
    assert counters.read(CTR_HOST_MATERIALIZED) == mat0
    L = np.zeros((N, N), np.float32)
    for out in results:
        for (m, n), tile in out.items():
            L[m*TS:(m+1)*TS, n*TS:(n+1)*TS] = tile
    L = np.tril(L)
    np.testing.assert_allclose(L @ L.T, spd, rtol=1e-2, atol=1e-2)


def test_ici_consumer_device_landing():
    """A produced tile consumed remotely arrives ALREADY RESIDENT on the
    consumer's bound device and becomes that device's copy at the new
    version (zero-copy landing; ref remote_dep_mpi.c:2120) — the
    consumer's stage-in takes the version-match fast path with no
    transfer."""
    dmap = _device_map(2)

    def program(rank, fabric):
        import jax
        ctx = _mkctx(rank, fabric, dmap)
        A = TwoDimBlockCyclic("iL", 8, 8, 4, 4, P=2, Q=1,
                              nodes=2, myrank=rank)
        A.fill(lambda m, n: np.full((4, 4), 1.0, np.float32))
        tp = DTDTaskpool(ctx, "ici-landing")
        src = tp.tile_of(A, 0, 0)   # rank 0 produces
        dst = tp.tile_of(A, 1, 0)   # rank 1 consumes
        tp.insert_task(lambda x: x * 5.0, (src, RW), name="w")
        tp.insert_task(lambda y, x: y + x[0, 0], (dst, RW), (src, READ),
                       name="r")
        tp.wait(timeout=30)
        tp.close()
        ctx.wait(timeout=30)
        out = None
        if rank == 1:
            from parsec_tpu.device.tpu import TPUDevice
            tdev = next(d for d in ctx.devices.devices
                        if isinstance(d, TPUDevice))
            dcopy = src.data.get_copy(tdev.device_index)
            host = src.data.get_copy(0)
            out = {
                "has_device_copy": dcopy is not None,
                "on_my_device": dcopy is not None
                and isinstance(dcopy.payload, jax.Array)
                and dcopy.payload.devices() == {tdev.jax_device},
                "version_current": dcopy is not None and host is not None
                and dcopy.version == host.version,
                "value": float(np.asarray(
                    A.data_of(1, 0).newest_copy().payload)[0, 0]),
            }
        ctx.fini()
        return out

    res = run_distributed(2, program, timeout=60)[1]
    assert res["value"] == 6.0            # 1 + 5*1
    assert res["has_device_copy"], "payload did not land as a device copy"
    assert res["on_my_device"], "landed copy is not on the consumer's device"
    assert res["version_current"], "landed device copy has a stale version"


def test_ici_rendezvous_path_stays_device_native():
    """Payloads over the eager limit take GET/PUT rendezvous — the PUT
    payload must still relocate device→device."""
    mca.set("comm_eager_limit", 64)   # force rendezvous for 16x16 tiles
    try:
        N, TS = 32, 16
        rng = np.random.default_rng(29)
        a = rng.standard_normal((N, N)).astype(np.float32)
        dmap = _device_map(2)

        def program(rank, fabric):
            ctx = _mkctx(rank, fabric, dmap)
            A = TwoDimBlockCyclic("iR", N, N, TS, TS, P=2, Q=1,
                                  nodes=2, myrank=rank)
            A.fill(lambda m, n: a[m*TS:(m+1)*TS, n*TS:(n+1)*TS])
            tp = DTDTaskpool(ctx, "ici-rdv")
            acc = tp.tile_of(A, 0, 0)
            for n in range(A.nt):
                src = tp.tile_of(A, 1, n)
                # produce on rank 1's device so the rendezvous PUT carries
                # a device-resident payload
                tp.insert_task(lambda x: x * 1.0, (src, RW), name="warm")
                tp.insert_task(lambda x, y: x + y, (acc, RW), (src, READ))
            tp.wait(timeout=30)
            tp.close()
            ctx.wait(timeout=30)
            ctx.fini()
            if rank == 0:
                return np.asarray(A.data_of(0, 0).newest_copy().payload)
            return None

        d2d0 = counters.read(CTR_D2D_MSGS)
        mat0 = counters.read(CTR_HOST_MATERIALIZED)
        results = run_distributed(2, program, timeout=60)
        assert counters.read(CTR_D2D_MSGS) > d2d0
        assert counters.read(CTR_HOST_MATERIALIZED) == mat0
        expect = a[:TS, :TS] + a[TS:2*TS, :TS] + a[TS:2*TS, TS:2*TS]
        np.testing.assert_allclose(results[0], expect, rtol=1e-4, atol=1e-4)
    finally:
        mca.params.unset("comm_eager_limit")
