"""Core runtime tests: hand-built task classes through the generic engines.

These play the role of the reference's tests/api + tests/runtime suites
(e.g. multichain.jdf): chains, fork-join, priorities, per-task dep goals.
"""

import threading

import pytest

from parsec_tpu.core.context import Context
from parsec_tpu.core.task import (
    Chore, DEV_CPU, Dep, Flow, FLOW_ACCESS_CTL, HOOK_DONE, Task, TaskClass,
    Taskpool,
)
from parsec_tpu.core import termdet as termdet_mod


def _ctl_class(tp, name, body, goal=0, count_mode=True):
    tc = TaskClass(name)
    tc.add_flow(Flow("ctl", FLOW_ACCESS_CTL))
    tc.count_mode = count_mode
    tc.dependencies_goal = goal
    tc.add_chore(Chore(DEV_CPU, body))
    tp.add_task_class(tc)
    return tc


def test_chain(context):
    """T(0) -> T(1) -> ... -> T(N-1), strictly ordered."""
    N = 64
    tp = Taskpool("chain")
    order = []

    def body(stream, task):
        order.append(task.locals["k"])
        return HOOK_DONE

    tc = _ctl_class(tp, "T", body, goal=1)
    tc.flows[0].deps_out.append(Dep(
        task_class=tc, flow_index=0, dep_index=0,
        cond=lambda l: l["k"] < N - 1,
        target_locals=lambda l: [{"k": l["k"] + 1}],
    ))

    def startup(stream, pool):
        pool.set_nb_tasks(N)
        return [Task(pool, tc, {"k": 0})]

    tp.startup_hook = startup
    context.add_taskpool(tp)
    context.wait()
    assert order == list(range(N))
    assert tp.completed


def test_fork_join(context):
    """A -> B(i) for i<W -> C; C must see all W contributions."""
    W = 16
    tp = Taskpool("forkjoin")
    hits = []

    def body_a(stream, task):
        hits.append("A")
        return HOOK_DONE

    def body_b(stream, task):
        hits.append(("B", task.locals["i"]))
        return HOOK_DONE

    def body_c(stream, task):
        hits.append("C")
        return HOOK_DONE

    tc_c = _ctl_class(tp, "C", body_c, goal=W)
    tc_b = _ctl_class(tp, "B", body_b, goal=1)
    tc_a = _ctl_class(tp, "A", body_a)
    tc_a.flows[0].deps_out.append(Dep(
        task_class=tc_b, flow_index=0, dep_index=0,
        target_locals=lambda l: [{"i": i} for i in range(W)],
    ))
    tc_b.flows[0].deps_out.append(Dep(
        task_class=tc_c, flow_index=0, dep_index=0,
        target_locals=lambda l: [{}],
    ))

    def startup(stream, pool):
        pool.set_nb_tasks(1 + W + 1)
        return [Task(pool, tc_a, {})]

    tp.startup_hook = startup
    context.add_taskpool(tp)
    context.wait()
    assert hits[0] == "A"
    assert hits[-1] == "C"
    assert sorted(h[1] for h in hits[1:-1]) == list(range(W))


@pytest.mark.parametrize("sched", ["lfq", "gd", "ap", "ll", "llp", "rnd", "spq",
                                   "pbq", "ip", "ltq", "lhq"])
def test_all_schedulers_run_dag(sched):
    """Every scheduler module executes a diamond DAG correctly
    (the reference compares schedulers on the ep.jdf microbenchmark)."""
    ctx = Context(nb_cores=2, scheduler=sched)
    tp = Taskpool("diamond")
    done = []

    def body(stream, task):
        done.append((task.task_class.name, dict(task.locals)))
        return HOOK_DONE

    W = 8
    tc_top = _ctl_class(tp, "TOP", body)
    tc_mid = _ctl_class(tp, "MID", body, goal=1)
    tc_bot = _ctl_class(tp, "BOT", body, goal=W)
    tc_top.flows[0].deps_out.append(Dep(
        task_class=tc_mid, flow_index=0, dep_index=0,
        target_locals=lambda l: [{"i": i} for i in range(W)],
    ))
    tc_mid.flows[0].deps_out.append(Dep(
        task_class=tc_bot, flow_index=0, dep_index=0,
        target_locals=lambda l: [{}],
    ))

    def startup(stream, pool):
        pool.set_nb_tasks(W + 2)
        return [Task(pool, tc_top, {})]

    tp.startup_hook = startup
    ctx.add_taskpool(tp)
    ctx.wait()
    ctx.fini()
    assert len(done) == W + 2
    assert done[0][0] == "TOP"
    assert done[-1][0] == "BOT"


def test_priority_ordering():
    """With the absolute-priority scheduler and one worker, ready tasks run
    highest-priority-first (ref: sched_ap)."""
    ctx = Context(nb_cores=1, scheduler="ap")
    tp = Taskpool("prio")
    ran = []

    def body(stream, task):
        ran.append(task.locals["i"])
        return HOOK_DONE

    tc = _ctl_class(tp, "P", body)

    def startup(stream, pool):
        pool.set_nb_tasks(10)
        tasks = []
        for i in range(10):
            t = Task(pool, tc, {"i": i}, priority=i)
            tasks.append(t)
        return tasks

    tp.startup_hook = startup
    ctx.add_taskpool(tp)
    ctx.wait()
    ctx.fini()
    # first selected may race with scheduling order; the tail must be sorted
    assert ran == sorted(ran, reverse=True)


def test_user_trigger_termdet(context):
    """user_trigger termdet: pool ends when the designated task says so
    (ref: parsec/mca/termdet/user_trigger/)."""
    tp = Taskpool("trigger")
    td = termdet_mod.UserTriggerTermdet()
    td.monitor_taskpool(tp)
    ran = []

    def body(stream, task):
        ran.append(task.locals["k"])
        if task.locals["k"] == 5:
            td.trigger(tp)
        return HOOK_DONE

    tc = _ctl_class(tp, "T", body, goal=1)
    tc.flows[0].deps_out.append(Dep(
        task_class=tc, flow_index=0, dep_index=0,
        cond=lambda l: l["k"] < 5,
        target_locals=lambda l: [{"k": l["k"] + 1}],
    ))

    def startup(stream, pool):
        pool.set_nb_tasks(Taskpool.UNDETERMINED_NB_TASKS)
        return [Task(pool, tc, {"k": 0})]

    tp.startup_hook = startup
    context.add_taskpool(tp)
    context.wait()
    assert ran == list(range(6))


def test_taskpool_wait_two_pools(context):
    """Two taskpools in flight; taskpool_wait isolates one."""
    tps = []
    for name in ("one", "two"):
        tp = Taskpool(name)
        tc = _ctl_class(tp, f"T{name}", lambda s, t: HOOK_DONE)

        def startup(stream, pool, tc=tc):
            pool.set_nb_tasks(4)
            return [Task(pool, tc, {"i": i}) for i in range(4)]

        tp.startup_hook = startup
        tps.append(tp)
    for tp in tps:
        context.add_taskpool(tp)
    assert tps[0].wait(timeout=10)
    context.wait()
    assert all(tp.completed for tp in tps)


def test_body_exception_propagates():
    """A raising task body surfaces from wait() instead of deadlocking
    (workers record the error; the master re-raises)."""
    ctx = Context(nb_cores=2)
    from parsec_tpu.dsl.dtd import DTDTaskpool, RW
    import numpy as np
    tp = DTDTaskpool(ctx, "boom")
    t = tp.tile_new((2, 2), np.float32)

    def bad(x):
        raise ValueError("intentional body failure")

    tp.insert_task(bad, (t, RW), jit=False)
    with pytest.raises((ValueError, RuntimeError)):
        tp.wait(timeout=10)
        tp.close()
        ctx.wait(timeout=10)
    ctx.fini()   # poisoned context still shuts down cleanly


def test_cli_help_mca():
    import subprocess, sys, os
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    out = subprocess.run([sys.executable, "-m", "parsec_tpu", "--help-mca"],
                         capture_output=True, text=True, timeout=110,
                         cwd=root, env=dict(os.environ, JAX_PLATFORMS="cpu"))
    assert out.returncode == 0
    assert "--mca sched" in out.stdout
    assert "dtd_window_size" in out.stdout


def test_dtd_and_ptg_concurrently():
    """Both frontends share one context and run interleaved."""
    import numpy as np
    from parsec_tpu.data.matrix import TiledMatrix
    from parsec_tpu.dsl.dtd import DTDTaskpool, RW
    from parsec_tpu.dsl.ptg.compiler import compile_ptg

    ctx = Context(nb_cores=1)
    A = TiledMatrix("mixA", 4, 4, 4, 4)
    A.fill(lambda m, n: np.zeros((4, 4), np.float32))
    ptg = compile_ptg("""
%global NT
%global A
T(k)
  k = 0 .. NT-1
  : A(0, 0)
  RW X <- (k == 0) ? A(0, 0) : X T(k-1)
     -> (k < NT-1) ? X T(k+1) : A(0, 0)
BODY
  X = X + 1.0
END
""", "mixptg").instantiate(ctx, globals={"NT": 5}, collections={"A": A})
    ctx.add_taskpool(ptg)
    dtp = DTDTaskpool(ctx, "mixdtd")
    t = dtp.tile_new((2, 2), np.float32)
    for _ in range(5):
        dtp.insert_task(lambda x: x + 2.0, (t, RW))
    dtp.wait(); dtp.close()
    ctx.wait()
    ctx.fini()
    assert ptg.completed and dtp.completed
    assert np.allclose(A.to_dense(), 5.0)
    assert np.allclose(np.asarray(t.data.newest_copy().payload), 10.0)


def test_context_argv_mca():
    """parsec_init-style cmdline: --mca pairs consumed at context creation."""
    from parsec_tpu.utils import mca
    ctx = Context(nb_cores=1, argv=["prog", "--mca", "sched", "ap", "x"])
    try:
        assert ctx.sched.name == "ap"
    finally:
        ctx.fini()
        mca.params._params["sched"].has_cmdline = False  # restore default


@pytest.mark.parametrize("sched,chain_early", [("pbq", True), ("ap", True),
                                               ("ltq", True), ("gd", False),
                                               ("rnd", False)])
def test_scheduler_policy_separation(sched, chain_early):
    """Policy probe (behavioral, order-based): a high-priority serial chain
    races a gated backlog of low-priority fillers. Priority-aware modules
    must finish the chain before most fillers run; FIFO/random must not
    (the structural distinctness the reference gets from hbbuffer/maxheap
    designs — sched_bench.py reports the timing version)."""
    import threading
    from parsec_tpu.dsl.dtd import DTDTaskpool, READ, RW

    ctx = Context(nb_cores=1, scheduler=sched)
    tp = DTDTaskpool(ctx, f"sep-{sched}")
    fill_tiles = [tp.tile_new((2, 2)) for _ in range(16)]
    chain_tile = tp.tile_new((2, 2))
    gate_tile = tp.tile_new((2, 2))
    nfill, chain_len = 400, 40
    fills_done = [0]
    fills_at_chain_end = [None]
    release = threading.Event()

    def gate(g):
        release.wait(30)
        return g

    def filler(x, g):
        fills_done[0] += 1

    def link(x, g):
        return x

    def last(x, g):
        fills_at_chain_end[0] = fills_done[0]
        return x

    tp.insert_task(gate, (gate_tile, RW), jit=False, name="GATE")
    for i in range(nfill):
        tp.insert_task(filler, (fill_tiles[i % 16], READ), (gate_tile, READ),
                       jit=False, name="FILL", priority=0)
    for i in range(chain_len):
        tp.insert_task(last if i == chain_len - 1 else link,
                       (chain_tile, RW), (gate_tile, READ),
                       jit=False, name="CHAIN", priority=1000)
    release.set()
    tp.wait(); tp.close(); ctx.wait(); ctx.fini()
    assert fills_at_chain_end[0] is not None
    frac = fills_at_chain_end[0] / nfill
    if chain_early:
        assert frac < 0.5, f"{sched}: chain finished after {frac:.0%} of fillers"
    else:
        assert frac > 0.5, f"{sched}: chain finished after only {frac:.0%}"


def test_paranoid_tier_catches_premature_schedule():
    """--mca debug_paranoid 1: scheduling a task with unmet deps (or
    re-scheduling a completed one) is an immediate attributed fatal — the
    PARSEC_DEBUG_PARANOID assertion tier."""
    from parsec_tpu.dsl.dtd import DTDTaskpool, RW
    from parsec_tpu.utils import mca

    mca.set("debug_paranoid", 1)
    ctx = None
    try:
        ctx = Context(nb_cores=1)
        tp = DTDTaskpool(ctx, "paranoid")
        t = tp.tile_new((2, 2))
        task = tp.insert_task(lambda x: x + 1.0, (t, RW), jit=False)
        tp.wait(); tp.close(); ctx.wait()
        # seeded bug 1: re-schedule the completed task
        with pytest.raises(RuntimeError, match="PARANOID.*re-scheduled"):
            ctx.schedule([task])
        # seeded bug 2: a task with unmet deps enters the queues
        task.status = 0
        task.deps_remaining = 3
        with pytest.raises(RuntimeError, match="PARANOID.*unmet"):
            ctx.schedule([task])
    finally:
        if ctx is not None:
            ctx.fini()
        mca.unset("debug_paranoid")


def test_paranoid_ptg_clean_run():
    """PTG taskpools (base Task, no deps_remaining field) run clean under
    the paranoid tier (regression: the check crashed on the missing
    attribute instead of passing valid DAGs)."""
    import numpy as np
    from parsec_tpu.dsl.ptg.compiler import compile_ptg
    from parsec_tpu.data.matrix import TiledMatrix
    from parsec_tpu.utils import mca

    src = """
%global descA
T(k)
  k = 0 .. 3
  : descA(0, k)
  RW X <- descA(0, k)
     -> descA(0, k)
BODY
  X = X + 1.0
END
"""
    mca.set("debug_paranoid", 1)
    ctx = None
    try:
        ctx = Context(nb_cores=1)
        A = TiledMatrix("PARG", 4, 16, 4, 4)
        A.fill(lambda m, n: np.zeros((4, 4), np.float32))
        tp = compile_ptg(src, "par").instantiate(ctx, collections={"descA": A})
        ctx.add_taskpool(tp)
        ctx.wait(timeout=30)
        np.testing.assert_allclose(A.to_dense(), 1.0)
    finally:
        if ctx is not None:
            ctx.fini()
        mca.unset("debug_paranoid")


def test_paranoid_off_by_default(context):
    """The hot path carries no paranoid cost unless asked for."""
    assert context.paranoid == 0
