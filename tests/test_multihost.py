"""Multi-controller SPMD: the true multi-host shape, rehearsed with real
OS processes.

Two controller processes x 4 virtual CPU devices join ONE jax job
(`jax.distributed.initialize`): `jax.devices()` is global, the (dp, tp)
mesh spans both processes, and the flagship LM train step's collectives
cross the process boundary (Gloo here; ICI/DCN on a pod). The reference
reaches this scale through mpirun + NCCL/MPI; here the ENTIRE data plane
is XLA collectives — the framework layer only brings the job up.
"""

import os
import re

import numpy as np
import pytest

from parsec_tpu.parallel.multihost import (cpu_collectives_available,
                                           run_multicontroller)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: failure signatures that mean the ENVIRONMENT cannot run multiprocess
#: CPU jobs — not that the runtime regressed. "Multiprocess computations
#: aren't implemented" is a jaxlib without CPU collectives; a
#: gloo::EnforceNotMet C++ abort (e.g. "op.preamble.length <= op.nbytes")
#: is the known-buggy gloo TCP pair in some jaxlib builds, uncatchable in
#: Python. Real assertion failures match neither and still fail.
_ENV_LIMIT_SIGNATURES = (
    "Multiprocess computations aren't implemented on the CPU backend",
    "gloo::EnforceNotMet",
    "op.preamble.length <= op.nbytes",
)

#: the gloo env-limit leg is BIMODAL (ISSUE 11 satellite): a working
#: jaxlib finishes the 2-controller job in ~4s; a jaxlib with the buggy
#: gloo TCP pair either aborts with a signature above or HANGS inside a
#: collective for ~100s before gloo's internal timeouts fire — dragging
#: every full-suite run. The per-test job timeout below (>10x the fast
#: mode) bounds the hang; hitting it IS the hang-mode signature.
_JOB_TIMEOUT_S = 60.0
_HANG_SKIP_REASON = (
    "multihost CPU backend env-limited: 2-controller gloo job exceeded "
    f"{_JOB_TIMEOUT_S:.0f}s (the known bimodal gloo-TCP hang mode — "
    "~4s when the jaxlib's gloo pair works, a ~100s in-collective hang "
    "when it doesn't; verified to hang identically on clean HEAD, i.e. "
    "an environment limit, not a runtime regression)")


def _losses(out: str):
    m = re.search(r"MHLOSS pid=\d+ losses=([\d.,-]+)", out)
    assert m, f"no MHLOSS line in:\n{out[-1200:]}"
    return [float(v) for v in m.group(1).split(",")]


def _run_or_skip_on_env_limit(*args, **kw):
    """run_multicontroller, skipping (not failing) when the failure is an
    attributed environment limit (the _needs_transfer-style guard, but
    for faults only observable by running). The job deadline is bounded
    (_JOB_TIMEOUT_S) so the gloo hang mode costs ~1 minute, not ~100s
    per leg; a timeout whose controllers produced no assertion output is
    attributed to that hang mode and skipped, while a real failure
    (assertion text in a controller's tail) still propagates."""
    kw.setdefault("timeout", _JOB_TIMEOUT_S)
    try:
        return run_multicontroller(*args, **kw)
    except RuntimeError as e:
        msg = str(e)
        for sig in _ENV_LIMIT_SIGNATURES:
            if sig in msg:
                pytest.skip(f"multihost CPU backend env-limited: {sig!r}")
        if "controller timed out" in msg and "AssertionError" not in msg:
            pytest.skip(_HANG_SKIP_REASON)
        raise


def test_two_controller_global_mesh_lm_train_step():
    if not cpu_collectives_available():
        pytest.skip("multiprocess CPU collectives unavailable in this jax")
    outs = _run_or_skip_on_env_limit(
        2, os.path.join(REPO, "tests", "_multihost_worker.py"),
        devices_per_proc=4)
    l0, l1 = _losses(outs[0]), _losses(outs[1])
    # every controller observes the SAME replicated losses (one global
    # program, not two independent runs)
    np.testing.assert_allclose(l0, l1, rtol=1e-6, atol=1e-6)
    assert l0[-1] < l0[0]                   # it actually trains
    # ring attention's K/V ring crossed the process boundary; each
    # controller validated ITS sequence span against the dense reference
    spans = sorted(re.search(r"MHRING pid=\d+ err=[\d.e-]+ span=(\d+):(\d+)",
                             o).groups() for o in outs)
    assert spans == [("0", "32"), ("32", "64")], spans
    # both controllers completed the coordinated sharded orbax save/restore
    assert all(re.search(r"MHCKPT pid=\d+ step=3 ok=1", o) for o in outs)
    # the MoE dispatch/combine all_to_all crossed the boundary too
    assert all(re.search(r"MHMOE pid=\d+ err=", o) for o in outs)
    # per-host input shards assembled into the global batch reproduce the
    # replicated-feed loss exactly
    assert all(re.search(r"MHFEED pid=\d+ diff=", o) for o in outs)
    # the GPipe activation ring hopped the process boundary too: with
    # this, every parallelism mode (dp, tp, pp, ep, sp) has crossed it
    assert all(re.search(r"MHPP pid=\d+ err=", o) for o in outs)

    # and the global 2-process run computes the SAME numbers as one
    # process with the same 8-device mesh: the mesh is the program, the
    # process boundary is invisible (same bounded deadline: a hung
    # single-controller job must not drag the suite either)
    ref = _run_or_skip_on_env_limit(
        1, os.path.join(REPO, "tests", "_multihost_worker.py"),
        devices_per_proc=8)
    np.testing.assert_allclose(_losses(ref[0]), l0, rtol=2e-5, atol=2e-5)
