"""PTG advanced dependency features — user-defined functions, control
gather, multisize broadcast, time_estimate (the analogues of the reference's
tests/dsl/ptg/user-defined-functions (udf.jdf), controlgather (ctlgat.jdf),
and multisize_bcast suites, plus parsec_internal.h:431-458 time_estimate
feeding best-device selection)."""

import numpy as np
import pytest

from parsec_tpu.comm.remote_dep import RemoteDepEngine
from parsec_tpu.comm.threads import ThreadsCE, run_distributed
from parsec_tpu.core.context import Context
from parsec_tpu.core.task import HOOK_DONE, HOOK_NEXT
from parsec_tpu.data.matrix import TwoDimBlockCyclic
from parsec_tpu.data.reshape import NamedDatatype
from parsec_tpu.dsl.ptg.compiler import compile_ptg


def _mk(name, n=8, ts=4, val=1.0, **kw):
    dc = TwoDimBlockCyclic(name, n, n, ts, ts, P=kw.pop("P", 1), Q=1, **kw)
    dc.fill(lambda m, k: np.full((ts, ts), val, np.float32))
    return dc


def test_user_defined_make_key():
    """[make_key_fn = f]: the task key comes from the user function, which
    feeds the dep repo and hash tables (udf.jdf UD_MAKE_KEY)."""
    calls = []

    def my_key(tp, loc):
        calls.append(dict(loc))
        return ("udk", loc["m"] * 100 + loc["n"])

    src = """
%global descA
%global my_key

P(m, n) [ make_key_fn = my_key ]
  m = 0 .. 1
  n = 0 .. 1
  : descA(m, n)
  RW A <- descA(m, n)
       -> A C(m, n)
BODY
  A = A + 1.0
END

C(m, n)
  m = 0 .. 1
  n = 0 .. 1
  : descA(m, n)
  RW A <- A P(m, n)
       -> descA(m, n)
BODY
  A = A + 1.0
END
"""
    ctx = Context(nb_cores=1)
    A = _mk("descA")
    tp = compile_ptg(src, "udk").instantiate(
        ctx, globals={"my_key": my_key}, collections={"descA": A})
    ctx.add_taskpool(tp)
    ctx.wait(timeout=30)
    ctx.fini()
    np.testing.assert_array_equal(A.to_dense(),
                                  np.full((8, 8), 3.0, np.float32))
    assert len(calls) >= 4      # every P task keyed through the user fn
    keys = {my_key(tp, c) for c in list(calls)}
    assert ("udk", 101) in keys


def test_user_defined_startup_fn():
    """[startup_fn = f]: the class's initial ready tasks come from the user
    enumerator instead of the goal==0 scan (udf.jdf UD_STARTUP1/2)."""
    seeded = []

    def my_startup(tp, tc):
        for m in range(2):
            for n in range(2):
                seeded.append((m, n))
                yield {"m": m, "n": n}

    src = """
%global descA
%global my_startup

P(m, n) [ startup_fn = my_startup ]
  m = 0 .. 1
  n = 0 .. 1
  : descA(m, n)
  RW A <- descA(m, n)
       -> descA(m, n)
BODY
  A = A * 2.0
END
"""
    ctx = Context(nb_cores=1)
    A = _mk("descA")
    tp = compile_ptg(src, "uds").instantiate(
        ctx, globals={"my_startup": my_startup}, collections={"descA": A})
    ctx.add_taskpool(tp)
    ctx.wait(timeout=30)
    ctx.fini()
    assert seeded == [(0, 0), (0, 1), (1, 0), (1, 1)]
    np.testing.assert_array_equal(A.to_dense(),
                                  np.full((8, 8), 2.0, np.float32))


def test_body_evaluate_selects_incarnation():
    """[evaluate = fn]: a chore whose evaluate returns HOOK_NEXT is skipped
    and the next incarnation runs (udf.jdf UD_HASH_STRUCT's never_here /
    always_here bodies)."""
    hits = {"never": 0, "always": 0}

    def never_here(stream, task):
        hits["never"] += 1
        return HOOK_NEXT

    def always_here(stream, task):
        hits["always"] += 1
        return HOOK_DONE

    src = """
%global descA
%global never_here
%global always_here

P(m, n)
  m = 0 .. 1
  n = 0 .. 1
  : descA(m, n)
  RW A <- descA(m, n)
       -> descA(m, n)
BODY [evaluate = never_here]
  A = A * 100.0
END
BODY [evaluate = always_here]
  A = A + 1.0
END
"""
    ctx = Context(nb_cores=1)
    A = _mk("descA")
    tp = compile_ptg(src, "udev").instantiate(
        ctx, globals={"never_here": never_here, "always_here": always_here},
        collections={"descA": A})
    ctx.add_taskpool(tp)
    ctx.wait(timeout=30)
    ctx.fini()
    # the gated first body never ran; the second did, on every task
    np.testing.assert_array_equal(A.to_dense(),
                                  np.full((8, 8), 2.0, np.float32))
    assert hits["never"] == 4 and hits["always"] == 4


def test_time_estimate_feeds_best_device():
    """[time_estimate = f]: the class property is consumed by the device
    layer's load estimate (parsec_internal.h:431-458; DeviceRegistry
    select_best_device min-ETA)."""
    est_calls = []

    def my_estimate(task, device):
        est_calls.append((task.locals["m"], type(device).__name__))
        return 123.0

    src = """
%global descA
%global my_estimate

P(m, n) [ time_estimate = my_estimate ]
  m = 0 .. 1
  n = 0 .. 1
  : descA(m, n)
  RW A <- descA(m, n)
       -> descA(m, n)
BODY [type=TPU]
  A = A + 1.0
END
"""
    from parsec_tpu.utils import mca
    mca.set("device_tpu_over_cpu", True)
    try:
        ctx = Context(nb_cores=1)
        from parsec_tpu.device.tpu import TPUDevice
        dev = [d for d in ctx.devices.devices if isinstance(d, TPUDevice)][0]
        A = _mk("descA")
        tp = compile_ptg(src, "udte").instantiate(
            ctx, globals={"my_estimate": my_estimate},
            collections={"descA": A})
        ctx.add_taskpool(tp)
        ctx.wait(timeout=30)
        ctx.fini()
    finally:
        mca.params.unset("device_tpu_over_cpu")
    np.testing.assert_array_equal(A.to_dense(),
                                  np.full((8, 8), 2.0, np.float32))
    assert est_calls, "time_estimate was never consulted"


def test_control_gather_across_ranks():
    """CTL range gather: TC(0) collects a control from EVERY TA(k) and
    TB(k) across ranks before it may run (ctlgat.jdf). Execution counting
    rides per-execution evaluate probes (bodies are jitted: Python side
    effects in BODY fire once per trace, not per task)."""
    NT = 6
    src = """
%global NT
%global descA
%global probe_a
%global probe_b
%global probe_c

TA(k)
  k = 0 .. NT-1
  : descA(k, 0)
  CTL X -> X TC(0)
BODY [evaluate = probe_a]
  pass
END

TB(k)
  k = 0 .. NT-1
  : descA(k, 0)
  CTL X -> Y TC(0)
BODY [evaluate = probe_b]
  pass
END

TC(j)
  j = 0 .. 0
  : descA(0, 0)
  CTL X <- X TA(0 .. NT-1)
  CTL Y <- X TB(0 .. NT-1)
BODY [evaluate = probe_c]
  pass
END
"""
    def program(rank, fabric):
        ctx = Context(nb_cores=1, my_rank=rank, nb_ranks=2)
        RemoteDepEngine(ctx, ThreadsCE(fabric, rank))
        A = TwoDimBlockCyclic("descA", NT * 4, 4, 4, 4, P=2, Q=1,
                              nodes=2, myrank=rank)
        A.fill(lambda m, n: np.zeros((4, 4), np.float32))
        order = []

        def probe(tag):
            def ev(stream, task):
                order.append(tag)
                return HOOK_DONE
            return ev

        tp = compile_ptg(src, "ctlgat").instantiate(
            ctx, globals={"NT": NT, "probe_a": probe("A"),
                          "probe_b": probe("B"), "probe_c": probe("C")},
            collections={"descA": A})
        ctx.add_taskpool(tp)
        ctx.wait(timeout=60)
        ctx.fini()
        return order

    results = run_distributed(2, program, timeout=60)
    merged = results[0] + results[1]
    # every TA/TB ran exactly once somewhere; TC ran ONCE, on rank 0 (owner
    # of descA(0,0)), strictly after all 2*NT controls reached it
    assert merged.count("A") == NT and merged.count("B") == NT, merged
    assert results[0].count("C") == 1 and results[1].count("C") == 0
    assert results[0][-1] == "C"


def test_multisize_broadcast():
    """One producer flow broadcast to successor groups under DIFFERENT
    payload sizes (the [count = N] multisize broadcast of
    check_multisize_bcast.jdf, expressed as named datatypes): each group
    receives its own size."""
    rows2 = NamedDatatype("ROWS2", extract=lambda a: np.asarray(a)[:2].copy())
    rows3 = NamedDatatype("ROWS3", extract=lambda a: np.asarray(a)[:3].copy())
    got = {}

    def shape_probe(name):
        def ev(stream, task):
            v = task.data[0].data_in
            p = getattr(v, "payload", v)
            got.setdefault(name, set()).add(tuple(np.asarray(p).shape))
            return HOOK_DONE
        return ev

    src = """
%global descA
%global probe2
%global probe3

P(j)
  j = 0 .. 0
  : descA(0, 0)
  RW A <- descA(0, 0)
       -> A C2(0 .. 1)     [type = ROWS2]
       -> A C3(0 .. 1)     [type = ROWS3]
BODY
  A = A
END

C2(i)
  i = 0 .. 1
  : descA(i, 1)
  READ A <- A P(0)         [type = ROWS2]
BODY [evaluate = probe2]
  pass
END

C3(i)
  i = 0 .. 1
  : descA(i, 1)
  READ A <- A P(0)         [type = ROWS3]
BODY [evaluate = probe3]
  pass
END
"""
    ctx = Context(nb_cores=1)
    A = _mk("descA")
    tp = compile_ptg(src, "msb").instantiate(
        ctx, globals={"probe2": shape_probe("c2"), "probe3": shape_probe("c3")},
        collections={"descA": A},
        datatypes={"ROWS2": rows2, "ROWS3": rows3})
    ctx.add_taskpool(tp)
    ctx.wait(timeout=30)
    ctx.fini()
    assert got["c2"] == {(2, 4)} and got["c3"] == {(3, 4)}, got
