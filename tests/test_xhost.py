"""Unit tests for the cross-host device-payload plane (comm/xhost.py):
the PJRT transfer server loopback, pin lifecycle, and the concurrent
first-offer race (both threads must observe ONE server)."""

import threading

import numpy as np
import pytest

from parsec_tpu.comm.xhost import XHostRef, XHostTransfer


@pytest.fixture(scope="module")
def xh():
    if not XHostTransfer.available():
        pytest.skip("jax.experimental.transfer unavailable")
    return XHostTransfer()


def test_offer_pull_loopback_and_pin_lifecycle(xh):
    import jax.numpy as jnp
    x = jnp.arange(64.0).reshape(8, 8)
    ref = xh.offer(x, dst=3)
    assert isinstance(ref, XHostRef)
    assert ref.shape == (8, 8) and ref.dtype == "float32"
    assert xh.pending() == 1                  # pinned until ACK
    got = xh.pull(ref)
    np.testing.assert_allclose(np.asarray(got), np.asarray(x))
    xh.retire(ref.uuid)
    assert xh.pending() == 0


def test_bfloat16_round_trip(xh):
    import jax.numpy as jnp
    x = jnp.full((4, 4), 2.5, jnp.bfloat16)
    ref = xh.offer(x)
    assert ref.dtype == "bfloat16"            # NAME, not raw-void '<V2'
    got = xh.pull(ref)
    assert got.dtype == jnp.bfloat16
    np.testing.assert_allclose(np.asarray(got.astype(jnp.float32)), 2.5)
    xh.retire(ref.uuid)


def test_retire_peer_and_clear(xh):
    import jax.numpy as jnp
    for dst in (1, 1, 2):
        xh.offer(jnp.zeros((2, 2)), dst=dst)
    assert xh.pending() == 3
    xh.retire_peer(1)                         # dead peer: its pulls never come
    assert xh.pending() == 1
    xh.clear()
    assert xh.pending() == 0


def test_concurrent_first_offers_share_one_server():
    """Two threads race the lazy server init: both refs must carry the
    SAME server address (the loser of an unlocked race would stamp a
    garbage-collected server into its ref) and both must be pullable."""
    if not XHostTransfer.available():
        pytest.skip("jax.experimental.transfer unavailable")
    import jax.numpy as jnp
    fresh = XHostTransfer()
    refs = [None, None]
    barrier = threading.Barrier(2)

    def offerer(i):
        barrier.wait()
        refs[i] = fresh.offer(jnp.full((4,), float(i + 1)))

    ts = [threading.Thread(target=offerer, args=(i,)) for i in range(2)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=30)
    assert refs[0] is not None and refs[1] is not None
    assert refs[0].address == refs[1].address == fresh.address
    for i, ref in enumerate(refs):
        got = fresh.pull(ref)
        np.testing.assert_allclose(np.asarray(got), float(i + 1))
        fresh.retire(ref.uuid)
    assert fresh.pending() == 0


def test_tcpce_flag_on_but_transfer_unavailable_warns_and_bounces(monkeypatch):
    """comm_device_mem=1 on a jax build without the transfer API must warn
    and leave the counted host-bounce path in place, not crash."""
    from parsec_tpu.comm import tcp as tcp_mod
    from parsec_tpu.utils import mca

    monkeypatch.setattr(tcp_mod.XHostTransfer, "available",
                        staticmethod(lambda: False))
    mca.set("comm_device_mem", True)
    try:
        ce = tcp_mod.TCPCE(0, 1, ("127.0.0.1", 0))   # single rank: no mesh
        assert ce._xhost is None and ce._xpull is None
        from parsec_tpu.comm.engine import CAP_ACCELERATOR_MEM
        assert not (ce.capabilities & CAP_ACCELERATOR_MEM)
        ce.fini()
    finally:
        mca.params.unset("comm_device_mem")


def test_tcpce_pull_failure_attributes_peer_not_crash(monkeypatch):
    """ADVICE r4: a rendezvous pull that raises (producer crashed before
    the pull / transfer server unreachable) must be attributed as a dead
    peer — mirroring the BYE/EOF paths — not crash the progress driver."""
    from parsec_tpu.comm import tcp as tcp_mod
    from parsec_tpu.comm.engine import TAG_DSL_BASE
    from parsec_tpu.comm.xhost import XHostRef

    ce = tcp_mod.TCPCE(0, 1, ("127.0.0.1", 0))   # single rank: no mesh
    try:
        class _BoomPull:
            def pull(self, ref):
                raise ConnectionRefusedError("transfer server gone")
        ce._xpull = _BoomPull()
        delivered = []
        ce.tag_register(TAG_DSL_BASE,
                        lambda _ce, src, hdr, pl: delivered.append(pl))
        ref = XHostRef(uuid=7, address="127.0.0.1:1", shape=(2,),
                       dtype="float32")
        ce._inbound.append((TAG_DSL_BASE, 3, {"h": 1}, ref))
        n = ce.progress()                         # must NOT raise
        assert n == 1
        assert 3 in ce.dead_peers                 # failure attributed
        assert delivered == []                    # message dead-lettered
    finally:
        ce.fini()
