"""Device-module pipeline tests over a host jax device (test mode).

Exercises the full async device path — kernel_scheduler enqueue, manager
drive, version-checked stage-in, LRU residency, is_ready event polling,
epilog write-back, and batched dispatch — without TPU hardware (the
reference's analogue: device tests runnable on any CUDA-capable node).
"""

import numpy as np
import pytest

from parsec_tpu.core.context import Context
from parsec_tpu.data.matrix import TiledMatrix
from parsec_tpu.dsl.dtd import DTDTaskpool, READ, RW
from parsec_tpu.utils import mca


@pytest.fixture()
def dctx():
    mca.set("device_tpu_over_cpu", True)
    c = Context(nb_cores=1)
    yield c
    c.fini()
    mca.params.unset("device_tpu_over_cpu")


def _tpu_dev(ctx):
    from parsec_tpu.device.tpu import TPUDevice
    devs = [d for d in ctx.devices.devices if isinstance(d, TPUDevice)]
    assert devs, "device module did not register over the host device"
    return devs[0]


def test_async_device_pipeline(dctx):
    dev = _tpu_dev(dctx)
    A = TiledMatrix("AD", 32, 32, 16, 16)
    rng = np.random.default_rng(40)
    dense = rng.standard_normal((32, 32)).astype(np.float32)
    A.fill(lambda m, n: dense[m*16:(m+1)*16, n*16:(n+1)*16])
    tp = DTDTaskpool(dctx, "dev")
    for m in range(2):
        for n in range(2):
            tp.insert_task(lambda x: x * 2.0, (tp.tile_of(A, m, n), RW))
    tp.wait(); tp.close(); dctx.wait()
    np.testing.assert_allclose(A.to_dense(), dense * 2.0, rtol=1e-5)
    assert dev.executed_tasks == 4
    assert dev.transfer_in_bytes > 0          # staged tiles in
    assert len(dev._lru) > 0                  # resident copies tracked


def test_device_chain_reuses_resident_tiles(dctx):
    """Second pass over the same tiles must not re-stage (version match)."""
    dev = _tpu_dev(dctx)
    A = TiledMatrix("AR", 16, 16, 16, 16)
    A.fill(lambda m, n: np.ones((16, 16), np.float32))
    tp = DTDTaskpool(dctx, "resident")
    t = tp.tile_of(A, 0, 0)
    for _ in range(4):
        tp.insert_task(lambda x: x + 1.0, (t, RW))
    tp.wait(); tp.close(); dctx.wait()
    staged_once = dev.transfer_in_bytes
    assert staged_once == 16 * 16 * 4          # exactly one initial stage-in
    assert np.allclose(np.asarray(t.data.newest_copy().payload), 5.0)


def test_batched_dispatch(dctx):
    """Independent same-class tasks collapse into vmapped dispatches
    (ref: parsec_gpu_task_collect_batch). A host device completes work
    instantly, so the batch window never fills on its own; holding the
    manager lock during enqueue models a busy chip accumulating work."""
    dev = _tpu_dev(dctx)
    A = TiledMatrix("AB", 16 * 8, 16, 16, 16)
    A.fill(lambda m, n: np.full((16, 16), float(m), np.float32))
    tp = DTDTaskpool(dctx, "batch")

    def scale(x):
        return x * 3.0

    for m in range(8):
        tp.insert_task(scale, (tp.tile_of(A, m, 0), RW), batch=True)
    # run the hooks (enqueue on the device) while the manager is "busy":
    # progress is a no-op for everyone else, so the batch accumulates
    with dev._manager_lock:
        dctx._progress_loop(dctx.streams[0],
                            until=lambda: len(dev._pending) == 8,
                            timeout=10)
    tp.wait(); tp.close(); dctx.wait()
    for m in range(8):
        assert np.allclose(np.asarray(A.data_of(m, 0).newest_copy().payload),
                           3.0 * m)
    assert dev.batched_dispatches >= 1


def test_eviction_under_pressure(dctx):
    """A tiny HBM budget forces LRU eviction with write-back; the pt_zone
    ledger (offsets + occupancy stats) tracks every resident tile."""
    dev = _tpu_dev(dctx)
    tile_b = 16 * 16 * 4
    dev.set_budget(3 * tile_b, unit=tile_b)    # room for ~3 tiles
    A = TiledMatrix("AE", 16 * 8, 16, 16, 16)
    A.fill(lambda m, n: np.full((16, 16), float(m), np.float32))
    tp = DTDTaskpool(dctx, "evict")
    for m in range(8):
        tp.insert_task(lambda x: x + 0.5, (tp.tile_of(A, m, 0), RW))
    tp.wait(); tp.close(); dctx.wait()
    for m in range(8):
        assert np.allclose(np.asarray(A.data_of(m, 0).newest_copy().payload),
                           m + 0.5)
    assert dev._resident_bytes <= dev._budget + tile_b
    # the zone ledger: one live segment per resident tile, occupancy within
    # budget, eviction churn visible in the high-water mark
    zs = dev.zone_stats()
    assert len(dev._lru_segs) == len(dev._lru)
    assert zs["in_use_bytes"] == len(dev._lru_segs) * tile_b
    assert zs["in_use_bytes"] <= zs["total_bytes"]
    assert zs["hwm_bytes"] >= zs["in_use_bytes"] > 0


def test_ptg_body_through_device_module(dctx):
    """PTG [type=TPU] bodies route through the async device module; PTG
    intermediates ride as raw arrays without a backing Data (regression:
    _gather_inputs/_epilog assumed DataCopy everywhere and crashed on
    ArrayImpl inputs)."""
    from parsec_tpu.dsl.ptg.compiler import compile_ptg

    src = """
%global KT
%global descC

STEP(k)
  k = 0 .. KT-1
  : descC(0, 0)
  RW C <- (k == 0) ? descC(0, 0) : C STEP(k-1)
       -> (k < KT-1) ? C STEP(k+1) : descC(0, 0)
BODY [type=TPU]
  C = C + 1.0
END
"""
    dev = _tpu_dev(dctx)
    C = TiledMatrix("PDEV", 8, 8, 8, 8)
    C.fill(lambda m, n: np.zeros((8, 8), np.float32))
    prog = compile_ptg(src, "pdev")
    tp = prog.instantiate(dctx, globals={"KT": 5},
                          collections={"descC": C}, name="pdev")
    dctx.add_taskpool(tp)
    dctx.wait(timeout=30)
    np.testing.assert_allclose(C.to_dense(), np.full((8, 8), 5.0), rtol=1e-6)
    assert dev.executed_tasks >= 5


def test_pinned_copies_survive_eviction(dctx):
    """An inflight task's reader pin protects its device copies from the
    eviction walks (ref: the readers guard of device_gpu.c:1210) — the
    guard that was previously dead code because nothing ever incremented
    DataCopy.readers."""
    dev = _tpu_dev(dctx)
    A = TiledMatrix("PIN", 32, 16, 16, 16)
    A.fill(lambda m, n: np.full((16, 16), float(m + 1), np.float32))
    tp = DTDTaskpool(dctx, "pin")
    t0, t1 = tp.tile_of(A, 0, 0), tp.tile_of(A, 1, 0)
    tp.insert_task(lambda x: x * 2.0, (t0, RW))
    tp.insert_task(lambda x: x * 3.0, (t1, RW))
    tp.wait(); tp.close(); dctx.wait()
    # both tiles resident; pin one through the device's pin protocol
    # (exactly what _gather_inputs does for an inflight task — pin_copy
    # mirrors the reader count into the native coherency table so C's
    # victim selection honors it too)
    c0 = t0.data.get_copy(dev.device_index)
    c1 = t1.data.get_copy(dev.device_index)
    assert c0 is not None and c1 is not None
    dev.pin_copy(c0)
    try:
        freed = dev.evict_bytes(dev._resident_bytes)   # demand everything
        assert dev.pinned_skips > 0, "eviction walk never saw the pin"
        assert c0.payload is not None, "pinned copy was evicted"
        assert c0.coherency_state != 0                  # not INVALID
        assert c1.payload is None, "unpinned copy should have been evicted"
        assert freed > 0
    finally:
        dev.unpin_copy(c0)
    # unpinned now: the same demand evicts it
    dev.evict_bytes(dev._resident_bytes)
    assert c0.payload is None


def _acc(a, x):
    return a + x


def test_inflight_pins_balance_and_pressure_correctness(dctx):
    """Seeded eviction pressure (budget = ~2 tiles) while a DAG with many
    live tiles runs through the device module: every task's reader pins
    are dropped at epilog (readers balances back to 0), evictions DO
    happen, and the results are still correct."""
    dev = _tpu_dev(dctx)
    tile_bytes = 16 * 16 * 4
    dev.set_budget(2 * tile_bytes + 64, unit=1024)
    n_rows = 8
    A = TiledMatrix("PRS", 16 * n_rows, 16, 16, 16)
    dense = np.stack([np.full((16, 16), float(m), np.float32)
                      for m in range(n_rows)])
    A.fill(lambda m, n: dense[m])
    tp = DTDTaskpool(dctx, "pressure")
    acc = tp.tile_new(np.zeros((16, 16), np.float32))
    for m in range(n_rows):
        tp.insert_task(_acc, (acc, RW), (tp.tile_of(A, m, 0), READ))
    tp.wait(); tp.close(); dctx.wait()
    out = np.asarray(acc.data.newest_copy().payload)
    np.testing.assert_allclose(out, dense.sum(axis=0), rtol=1e-5)
    assert dev.evictions > 0, "budget pressure produced no evictions"
    # pins all released: no copy left with a nonzero reader count
    for m in range(n_rows):
        for c in A.data_of(m, 0).copies.values():
            assert c.readers == 0
    for c in acc.data.copies.values():
        assert c.readers == 0


# ---------------------------------------------------------------------------
# ISSUE 10: the native device lane (ptdev) + C-side coherency table
# ---------------------------------------------------------------------------

_MIXED_SRC = """
%global NT
%global DEPTH
%global descA
%global descB

DEVSTEP(i, l)
  i = 0 .. NT-1
  l = 0 .. DEPTH-1
  : descA(0, i)
  RW X <- (l == 0) ? descA(0, i) : Y HOSTSTEP(i, l-1)
       -> Y HOSTSTEP(i, l)
BODY [type=TPU]
  X = X * 2.0 + l
END

HOSTSTEP(i, l)
  i = 0 .. NT-1
  l = 0 .. DEPTH-1
  : descA(0, i)
  RW Y <- X DEVSTEP(i, l)
       -> (l < DEPTH-1) ? X DEVSTEP(i, l+1) : descB(0, i)
BODY
  Y = Y - 0.5 * i
END
"""


def _mixed_replay(a_cols, nt, depth):
    """Exact numpy replay of the mixed CPU+TPU DAG."""
    out = []
    for i in range(nt):
        x = a_cols[i].astype(np.float64)
        for l in range(depth):
            x = x * 2.0 + l          # DEVSTEP
            x = x - 0.5 * i          # HOSTSTEP
        out.append(x)
    return out


def _run_mixed(ctx, nt, depth, a_cols, tag):
    from parsec_tpu.data.matrix import TiledMatrix
    from parsec_tpu.dsl.ptg.compiler import compile_ptg
    A = TiledMatrix(f"mxA{tag}", 4, 4 * nt, 4, 4)
    A.fill(lambda m, n: a_cols[n])
    B = TiledMatrix(f"mxB{tag}", 4, 4 * nt, 4, 4)
    B.fill(lambda m, n: np.zeros((4, 4), np.float32))
    prog = compile_ptg(_MIXED_SRC, f"mixed-{tag}")
    tp = prog.instantiate(ctx, globals={"NT": nt, "DEPTH": depth},
                          collections={"descA": A, "descB": B},
                          name=f"mixed-{tag}")
    ctx.add_taskpool(tp)
    ctx.wait(timeout=90)
    return tp, A, B


def test_mixed_dag_parity_lane_on_off(dctx):
    """Randomized mixed CPU+TPU-body DAG parity harness (the PR 1-7
    template): the native execution+device lanes on vs the full
    interpreted FSM + interpreted device module — identical completion,
    final payloads (vs an exact numpy replay), data versions, and
    coherency invariants."""
    from parsec_tpu.device.native import PTDEV_STATS
    from parsec_tpu.dsl.ptg.compiler import PTEXEC_STATS
    rng = np.random.default_rng(1234)
    for round_ in range(3):
        nt = int(rng.integers(2, 5))
        depth = int(rng.integers(2, 6))
        a_cols = [rng.standard_normal((4, 4)).astype(np.float32)
                  for _ in range(nt)]
        expect = _mixed_replay(a_cols, nt, depth)

        snap = PTEXEC_STATS.snapshot()
        dsnap = PTDEV_STATS.snapshot()
        tp_on, _A_on, B_on = _run_mixed(dctx, nt, depth, a_cols,
                                        f"on{round_}")
        delta = PTEXEC_STATS.delta(snap)
        ddelta = PTDEV_STATS.delta(dsnap)
        assert tp_on._ptexec_state is not None, "lane leg fell back"
        assert delta["pools_fallback"] == 0 and \
            ddelta["pools_fallback"] == 0, (delta, ddelta)
        assert delta["pools_device"] == 1, delta
        assert ddelta["tasks_engaged"] == nt * depth, ddelta

        mca.set("ptg_native_exec", False)
        try:
            tp_off, _A_off, B_off = _run_mixed(dctx, nt, depth, a_cols,
                                               f"off{round_}")
        finally:
            mca.params.unset("ptg_native_exec")
        assert tp_off._ptexec_state is None

        for i in range(nt):
            on = np.asarray(B_on.data_of(0, i).newest_copy().payload,
                            np.float64)
            off = np.asarray(B_off.data_of(0, i).newest_copy().payload,
                             np.float64)
            np.testing.assert_allclose(on, expect[i], rtol=1e-4)
            np.testing.assert_allclose(on, off, rtol=1e-5)
            # data versions: both legs land exactly one write-back per
            # descB tile on top of fill()'s version 1; coherency
            # invariant: the newest version is carried by a valid copy
            # with a live payload
            d_on = B_on.data_of(0, i)
            d_off = B_off.data_of(0, i)
            assert d_on.version == d_off.version == 2
            for d in (d_on, d_off):
                best = d.newest_copy()
                assert best is not None and best.payload is not None
                assert best.version == d.version


def test_device_lane_engagement_counters(dctx):
    """The ci.sh gate contract: a TPU-bodied pool engages the native
    device lane end-to-end — pools_fallback == 0, every device task
    dispatched AND retired through ptdev (graph dev counters match the
    lane's), zero coherency violations in the table."""
    from parsec_tpu.dsl.ptg.compiler import PTEXEC_STATS
    dev = _tpu_dev(dctx)
    rng = np.random.default_rng(7)
    a_cols = [rng.standard_normal((4, 4)).astype(np.float32)
              for _ in range(3)]
    snap = PTEXEC_STATS.snapshot()
    tp, A, _B = _run_mixed(dctx, 3, 4, a_cols, "gate")
    delta = PTEXEC_STATS.delta(snap)
    assert delta["pools_fallback"] == 0 and delta["pools_device"] == 1
    lane = dctx._ptdev
    assert lane is not None and lane is not False
    gstats = tp._ptexec_state["graph"].dev_stats()
    assert gstats["dev_tx"] == gstats["dev_done"] == 3 * 4
    assert gstats["dev_bad"] == 0
    ls = lane.clane.stats()
    assert ls["retired"] >= 3 * 4 and ls["cb_errors"] == 0
    assert lane.failed() is None
    # coherency: every staged descA tile's table entry matches the live
    # Data version (zero violations)
    cs = lane.coh_stats_cached(ttl=0)
    if cs is not None:
        for i in range(3):
            d = A.data_of(0, i)
            st = dev._ncoh.state(dev.res_key(d))
            if st is not None and st[0] != 0:      # still resident+valid
                assert st[1] == (d.version & 0xFFFFFFFF), \
                    f"coherency violation on descA(0,{i}): {st} vs {d.version}"


def test_device_lane_dispatch_error_surfaces(dctx):
    """A body raising on the lane's manager thread must poison the pool
    and surface to the waiter — not hang the drain loops."""
    from parsec_tpu.data.matrix import TiledMatrix
    from parsec_tpu.dsl.ptg.compiler import compile_ptg
    src = ("%global NT\n%global descA\n"
           "T(k)\n  k = 0 .. NT-1\n"
           "  RW X <- (k == 0) ? descA(0, k) : X T(k-1)\n"
           "       -> (k < NT-1) ? X T(k+1) : descA(0, k)\n"
           "BODY [type=TPU]\n  X = jnp.linalg.cholesky(X) * bad_name\nEND\n")
    A = TiledMatrix("errA", 1, 4, 1, 1)
    A.fill(lambda m, k: np.zeros((1, 1), np.float32))
    prog = compile_ptg(src, "dev-err")
    tp = prog.instantiate(dctx, globals={"NT": 4}, collections={"descA": A})
    dctx.add_taskpool(tp)
    with pytest.raises(BaseException):
        dctx.wait(timeout=30)
    # the context stays poisoned: the fixture's fini skips the drain and
    # tears down cleanly (the documented error contract)


def test_coh_table_units():
    """CohTable policy units: version-checked stage-in, LRU victim order,
    pin veto, budget shrink, ownership bumps."""
    from parsec_tpu import native as native_mod
    mod = native_mod.load_ptdev()
    if mod is None:
        pytest.skip("_ptdev unavailable")
    t = mod.CohTable(1000)
    need, v = t.stage_in(1, 400, 0)
    assert need == 1 and v == []
    need, v = t.stage_in(1, 400, 0)          # same version: resident hit
    assert need == 0 and v == []
    need, v = t.stage_in(1, 400, 1)          # version bumped: re-stage
    assert need == 1 and v == []
    need, v = t.stage_in(2, 400, 0)
    assert need == 1 and v == []
    # third tile exceeds the budget: key 1 is LRU victim
    need, v = t.stage_in(3, 400, 0)
    assert need == 1 and v == [(1, 0)]
    st = t.stats()
    assert st["evictions"] == 1 and st["resident_bytes"] == 800
    # a pinned entry is skipped; the next unpinned one evicts instead
    t.pin(2)
    need, v = t.stage_in(4, 400, 0)
    assert need == 1 and v == [(3, 0)]
    assert t.stats()["pinned_skips"] >= 1
    t.unpin(2)
    # ownership: mark_owned flags the victim as dirty (owned) on eviction
    vs = t.mark_owned(4, 5, 400)
    assert vs == []
    assert t.state(4)[:2] == (mod.COH_OWNED, 5)
    vict = t.set_budget(100)                 # evicts everything resident
    assert (2, 0) in vict                    # clean victim
    assert (4, 1) in vict                    # owned victim reported dirty
    assert t.stats()["resident_bytes"] == 0


def test_eviction_races_reader_atomically(dctx):
    """Regression (the zone-heap eviction/coherency gap): an OWNED copy
    evicted under pressure writes back AND downgrades atomically with the
    version check. A reader racing eviction must always find the data's
    newest version on a valid copy with a live payload, and a concurrent
    host write must never be clobbered by a stale write-back."""
    import threading
    from parsec_tpu.data.data import COHERENCY_INVALID, data_from_array
    dev = _tpu_dev(dctx)
    data = data_from_array(np.zeros((16, 16), np.float32), key="race-tile")
    stop = threading.Event()
    errors = []

    def reader():
        last = -1
        while not stop.is_set():
            with data._lock:
                best = None
                for c in data.copies.values():
                    if c.coherency_state != COHERENCY_INVALID:
                        if best is None or c.version > best.version:
                            best = c
                if best is None or best.payload is None:
                    errors.append("newest version lost its payload")
                    break
                if best.version < last or best.version < data.version:
                    errors.append(
                        f"version went backwards: {best.version} < "
                        f"{max(last, data.version)}")
                    break
                last = best.version
        stop.set()

    def host_writer():
        n = 0
        while not stop.is_set() and n < 400:
            host = data.get_copy(0)
            if host is not None and host.payload is not None:
                data.bump_version(0)
            n += 1
            time.sleep(0)
        stop.set()

    import time
    rt = threading.Thread(target=reader)
    wt = threading.Thread(target=host_writer)
    rt.start(); wt.start()
    try:
        for _ in range(400):
            if stop.is_set():
                break
            copy = dev.lane_stage_in(data)
            data.bump_version(dev.device_index)      # device owns newest
            dev._lru_touch(dev.res_key(data), copy)
            dev._coh_mark_owned(data, copy)
            dev.evict_bytes(1 << 30)                 # force the write-back
    finally:
        stop.set()
        rt.join(timeout=10); wt.join(timeout=10)
    assert not errors, errors
    best = data.newest_copy()
    assert best is not None and best.payload is not None
    assert best.version == data.version


def test_ptdtd_dev_wiring_engine_level():
    """The ptdtd half of the lane contract (wired + tested at the engine
    level; DTD pools stay on the interpreted device path this PR): ready
    tasks of a device-marked class surface onto a ptdev Lane, the
    manager dispatches them through the pool callbacks, and the GIL-free
    dev_retire release walk completes them — including surfacing their
    per-task-lane successors through drain_ready."""
    import time as _t
    from parsec_tpu import native as native_mod
    dmod = native_mod.load_ptdev()
    emod = native_mod.load_ptdtd()
    if dmod is None or emod is None:
        pytest.skip("native modules unavailable")
    eng = emod.Engine()
    tile = eng.tile()
    eng.slot_set(tile, 1.0)
    lane = dmod.Lane()

    def cb(args_list):                 # CPU batch callback (unused here)
        return [(a[0],) for a in args_list]

    cls = eng.register_class(cb, [0], [3], None, -1, 1)   # device=1
    dispatched = []

    def dispatch(pool, ids):
        for tid in ids:
            v = eng.slot_get(tile)
            eng.slot_set(tile, v * 2.0)
            dispatched.append(tid)
        return len(ids)

    done_box = []

    def poll():
        out = [(1, tid) for tid in dispatched]
        done_box.extend(out)
        del dispatched[:]
        return out

    lane.bind_pool(1, eng.dev_retire_capsule(), eng)
    lane.start(dispatch, poll, 100)
    try:
        eng.dev_bind(lane.submit_capsule(), 1)
        # a device-class chain: t0 -> t1 (RAW on the tile), plus a
        # per-task-lane reader that must surface at the end
        n = eng.insert_many([(cls, None, tile, 3), (cls, None, tile, 3)])
        assert n == 2
        tid, held = eng.insert([tile], [1])   # per-task-lane reader
        eng.activate(tid)
        deadline = _t.monotonic() + 10
        surfaced = []
        while _t.monotonic() < deadline:
            _nexec, sur = eng.drain_ready(64, 1024)
            surfaced.extend(sur)
            if eng.dev_stats()["dev_done"] == 2 and surfaced:
                break
            _t.sleep(0.005)
        ds = eng.dev_stats()
        assert ds["dev_tx"] == 2 and ds["dev_done"] == 2 and \
            ds["dev_bad"] == 0, ds
        assert surfaced == [tid], (surfaced, tid)
        assert eng.slot_get(tile) == 4.0      # both device bodies ran
        ls = lane.stats()
        assert ls["retired"] == 2 and ls["cb_errors"] == 0
    finally:
        lane.stop()
        lane.unbind_pool(1)


def test_device_lane_off_by_mca(dctx):
    """--mca device_native 0 keeps TPU-bodied pools on the interpreted
    device module (counted ineligible, never fallback)."""
    from parsec_tpu.device.native import PTDEV_STATS
    mca.set("device_native", False)
    try:
        rng = np.random.default_rng(3)
        a_cols = [rng.standard_normal((4, 4)).astype(np.float32)
                  for _ in range(2)]
        snap = PTDEV_STATS.snapshot()
        tp, _A, B = _run_mixed(dctx, 2, 2, a_cols, "mcaoff")
        delta = PTDEV_STATS.delta(snap)
        assert tp._ptexec_state is None
        assert delta["pools_ineligible"] >= 1 and delta["pools_fallback"] == 0
        expect = _mixed_replay(a_cols, 2, 2)
        for i in range(2):
            np.testing.assert_allclose(
                np.asarray(B.data_of(0, i).newest_copy().payload,
                           np.float64), expect[i], rtol=1e-4)
    finally:
        mca.params.unset("device_native")


def test_device_lane_under_budget_pressure(dctx):
    """Regression (found by the verify drive): under a tight HBM budget,
    staging tile k+1 of one dispatch batch must not evict tile k staged
    moments earlier — staged copies pin the moment they stage. The run
    stays correct, C-decided evictions DO happen, and every pin balances
    back to zero."""
    from parsec_tpu.dsl.ptg.compiler import compile_ptg
    dev = _tpu_dev(dctx)
    n, ts = 64, 16
    dev.set_budget(4 * ts * ts * 4, unit=1024)   # room for ~4 tiles
    rng = np.random.default_rng(21)
    a = rng.standard_normal((n, n)).astype(np.float32)
    b = rng.standard_normal((n, n)).astype(np.float32)
    src = ("%global MT\n%global KT\n%global descA\n%global descB\n"
           "%global descC\n"
           "GEMM(m, n, k)\n  m = 0 .. MT-1\n  n = 0 .. MT-1\n"
           "  k = 0 .. KT-1\n  : descC(m, n)\n"
           "  READ A <- descA(m, k)\n  READ B <- descB(k, n)\n"
           "  RW   C <- (k == 0) ? descC(m, n) : C GEMM(m, n, k-1)\n"
           "       -> (k < KT-1) ? C GEMM(m, n, k+1) : descC(m, n)\n"
           "BODY [type=TPU]\n"
           "  C = C + jnp.dot(A, B, preferred_element_type=jnp.float32)\n"
           "END\n")
    A = TiledMatrix("pbA", n, n, ts, ts)
    A.fill(lambda m, k: a[m*ts:(m+1)*ts, k*ts:(k+1)*ts])
    B = TiledMatrix("pbB", n, n, ts, ts)
    B.fill(lambda m, k: b[m*ts:(m+1)*ts, k*ts:(k+1)*ts])
    C = TiledMatrix("pbC", n, n, ts, ts)
    C.fill(lambda m, k: np.zeros((ts, ts), np.float32))
    prog = compile_ptg(src, "pb-gemm")
    # per-task staging pressure under test: region fusion stages each
    # fused chain's tiles once per REGION (different pressure shape,
    # covered by tests/test_fusion.py); the in-batch pin regression
    # needs the per-task dispatch path
    mca.set("region_fusion", False)
    try:
        tp = prog.instantiate(dctx,
                              globals={"MT": n // ts, "KT": n // ts},
                              collections={"descA": A, "descB": B,
                                           "descC": C})
        dctx.add_taskpool(tp)
        dctx.wait(timeout=90)
    finally:
        mca.params.unset("region_fusion")
    assert tp._ptexec_state is not None and \
        tp._ptexec_state.get("dev_pool") is not None
    err = float(np.abs(C.to_dense() - a @ b).max())
    assert err < 1e-2, f"tight-budget device-lane GEMM wrong: {err}"
    cs = dev.coh_stats()
    if cs is not None:
        assert cs["evictions"] > 0, cs
        # pins balance: with the pool done, nothing stays pinned
        for M in (A, B, C):
            for m in range(M.mt):
                for nn in range(M.nt):
                    st = dev._ncoh.state(dev.res_key(M.data_of(m, nn)))
                    assert st is None or st[3] == 0, (m, nn, st)
    assert dctx._ptdev.failed() is None
