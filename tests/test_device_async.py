"""Device-module pipeline tests over a host jax device (test mode).

Exercises the full async device path — kernel_scheduler enqueue, manager
drive, version-checked stage-in, LRU residency, is_ready event polling,
epilog write-back, and batched dispatch — without TPU hardware (the
reference's analogue: device tests runnable on any CUDA-capable node).
"""

import numpy as np
import pytest

from parsec_tpu.core.context import Context
from parsec_tpu.data.matrix import TiledMatrix
from parsec_tpu.dsl.dtd import DTDTaskpool, READ, RW
from parsec_tpu.utils import mca


@pytest.fixture()
def dctx():
    mca.set("device_tpu_over_cpu", True)
    c = Context(nb_cores=1)
    yield c
    c.fini()
    mca.params.unset("device_tpu_over_cpu")


def _tpu_dev(ctx):
    from parsec_tpu.device.tpu import TPUDevice
    devs = [d for d in ctx.devices.devices if isinstance(d, TPUDevice)]
    assert devs, "device module did not register over the host device"
    return devs[0]


def test_async_device_pipeline(dctx):
    dev = _tpu_dev(dctx)
    A = TiledMatrix("AD", 32, 32, 16, 16)
    rng = np.random.default_rng(40)
    dense = rng.standard_normal((32, 32)).astype(np.float32)
    A.fill(lambda m, n: dense[m*16:(m+1)*16, n*16:(n+1)*16])
    tp = DTDTaskpool(dctx, "dev")
    for m in range(2):
        for n in range(2):
            tp.insert_task(lambda x: x * 2.0, (tp.tile_of(A, m, n), RW))
    tp.wait(); tp.close(); dctx.wait()
    np.testing.assert_allclose(A.to_dense(), dense * 2.0, rtol=1e-5)
    assert dev.executed_tasks == 4
    assert dev.transfer_in_bytes > 0          # staged tiles in
    assert len(dev._lru) > 0                  # resident copies tracked


def test_device_chain_reuses_resident_tiles(dctx):
    """Second pass over the same tiles must not re-stage (version match)."""
    dev = _tpu_dev(dctx)
    A = TiledMatrix("AR", 16, 16, 16, 16)
    A.fill(lambda m, n: np.ones((16, 16), np.float32))
    tp = DTDTaskpool(dctx, "resident")
    t = tp.tile_of(A, 0, 0)
    for _ in range(4):
        tp.insert_task(lambda x: x + 1.0, (t, RW))
    tp.wait(); tp.close(); dctx.wait()
    staged_once = dev.transfer_in_bytes
    assert staged_once == 16 * 16 * 4          # exactly one initial stage-in
    assert np.allclose(np.asarray(t.data.newest_copy().payload), 5.0)


def test_batched_dispatch(dctx):
    """Independent same-class tasks collapse into vmapped dispatches
    (ref: parsec_gpu_task_collect_batch). A host device completes work
    instantly, so the batch window never fills on its own; holding the
    manager lock during enqueue models a busy chip accumulating work."""
    dev = _tpu_dev(dctx)
    A = TiledMatrix("AB", 16 * 8, 16, 16, 16)
    A.fill(lambda m, n: np.full((16, 16), float(m), np.float32))
    tp = DTDTaskpool(dctx, "batch")

    def scale(x):
        return x * 3.0

    for m in range(8):
        tp.insert_task(scale, (tp.tile_of(A, m, 0), RW), batch=True)
    # run the hooks (enqueue on the device) while the manager is "busy":
    # progress is a no-op for everyone else, so the batch accumulates
    with dev._manager_lock:
        dctx._progress_loop(dctx.streams[0],
                            until=lambda: len(dev._pending) == 8,
                            timeout=10)
    tp.wait(); tp.close(); dctx.wait()
    for m in range(8):
        assert np.allclose(np.asarray(A.data_of(m, 0).newest_copy().payload),
                           3.0 * m)
    assert dev.batched_dispatches >= 1


def test_eviction_under_pressure(dctx):
    """A tiny HBM budget forces LRU eviction with write-back; the pt_zone
    ledger (offsets + occupancy stats) tracks every resident tile."""
    dev = _tpu_dev(dctx)
    tile_b = 16 * 16 * 4
    dev.set_budget(3 * tile_b, unit=tile_b)    # room for ~3 tiles
    A = TiledMatrix("AE", 16 * 8, 16, 16, 16)
    A.fill(lambda m, n: np.full((16, 16), float(m), np.float32))
    tp = DTDTaskpool(dctx, "evict")
    for m in range(8):
        tp.insert_task(lambda x: x + 0.5, (tp.tile_of(A, m, 0), RW))
    tp.wait(); tp.close(); dctx.wait()
    for m in range(8):
        assert np.allclose(np.asarray(A.data_of(m, 0).newest_copy().payload),
                           m + 0.5)
    assert dev._resident_bytes <= dev._budget + tile_b
    # the zone ledger: one live segment per resident tile, occupancy within
    # budget, eviction churn visible in the high-water mark
    zs = dev.zone_stats()
    assert len(dev._lru_segs) == len(dev._lru)
    assert zs["in_use_bytes"] == len(dev._lru_segs) * tile_b
    assert zs["in_use_bytes"] <= zs["total_bytes"]
    assert zs["hwm_bytes"] >= zs["in_use_bytes"] > 0


def test_ptg_body_through_device_module(dctx):
    """PTG [type=TPU] bodies route through the async device module; PTG
    intermediates ride as raw arrays without a backing Data (regression:
    _gather_inputs/_epilog assumed DataCopy everywhere and crashed on
    ArrayImpl inputs)."""
    from parsec_tpu.dsl.ptg.compiler import compile_ptg

    src = """
%global KT
%global descC

STEP(k)
  k = 0 .. KT-1
  : descC(0, 0)
  RW C <- (k == 0) ? descC(0, 0) : C STEP(k-1)
       -> (k < KT-1) ? C STEP(k+1) : descC(0, 0)
BODY [type=TPU]
  C = C + 1.0
END
"""
    dev = _tpu_dev(dctx)
    C = TiledMatrix("PDEV", 8, 8, 8, 8)
    C.fill(lambda m, n: np.zeros((8, 8), np.float32))
    prog = compile_ptg(src, "pdev")
    tp = prog.instantiate(dctx, globals={"KT": 5},
                          collections={"descC": C}, name="pdev")
    dctx.add_taskpool(tp)
    dctx.wait(timeout=30)
    np.testing.assert_allclose(C.to_dense(), np.full((8, 8), 5.0), rtol=1e-6)
    assert dev.executed_tasks >= 5


def test_pinned_copies_survive_eviction(dctx):
    """An inflight task's reader pin protects its device copies from the
    eviction walks (ref: the readers guard of device_gpu.c:1210) — the
    guard that was previously dead code because nothing ever incremented
    DataCopy.readers."""
    dev = _tpu_dev(dctx)
    A = TiledMatrix("PIN", 32, 16, 16, 16)
    A.fill(lambda m, n: np.full((16, 16), float(m + 1), np.float32))
    tp = DTDTaskpool(dctx, "pin")
    t0, t1 = tp.tile_of(A, 0, 0), tp.tile_of(A, 1, 0)
    tp.insert_task(lambda x: x * 2.0, (t0, RW))
    tp.insert_task(lambda x: x * 3.0, (t1, RW))
    tp.wait(); tp.close(); dctx.wait()
    # both tiles resident; pin one by hand (as an inflight task would)
    c0 = t0.data.get_copy(dev.device_index)
    c1 = t1.data.get_copy(dev.device_index)
    assert c0 is not None and c1 is not None
    c0.readers += 1
    try:
        freed = dev.evict_bytes(dev._resident_bytes)   # demand everything
        assert dev.pinned_skips > 0, "eviction walk never saw the pin"
        assert c0.payload is not None, "pinned copy was evicted"
        assert c0.coherency_state != 0                  # not INVALID
        assert c1.payload is None, "unpinned copy should have been evicted"
        assert freed > 0
    finally:
        c0.readers -= 1
    # unpinned now: the same demand evicts it
    dev.evict_bytes(dev._resident_bytes)
    assert c0.payload is None


def _acc(a, x):
    return a + x


def test_inflight_pins_balance_and_pressure_correctness(dctx):
    """Seeded eviction pressure (budget = ~2 tiles) while a DAG with many
    live tiles runs through the device module: every task's reader pins
    are dropped at epilog (readers balances back to 0), evictions DO
    happen, and the results are still correct."""
    dev = _tpu_dev(dctx)
    tile_bytes = 16 * 16 * 4
    dev.set_budget(2 * tile_bytes + 64, unit=1024)
    n_rows = 8
    A = TiledMatrix("PRS", 16 * n_rows, 16, 16, 16)
    dense = np.stack([np.full((16, 16), float(m), np.float32)
                      for m in range(n_rows)])
    A.fill(lambda m, n: dense[m])
    tp = DTDTaskpool(dctx, "pressure")
    acc = tp.tile_new(np.zeros((16, 16), np.float32))
    for m in range(n_rows):
        tp.insert_task(_acc, (acc, RW), (tp.tile_of(A, m, 0), READ))
    tp.wait(); tp.close(); dctx.wait()
    out = np.asarray(acc.data.newest_copy().payload)
    np.testing.assert_allclose(out, dense.sum(axis=0), rtol=1e-5)
    assert dev.evictions > 0, "budget pressure produced no evictions"
    # pins all released: no copy left with a nonzero reader count
    for m in range(n_rows):
        for c in A.data_of(m, 0).copies.values():
            assert c.readers == 0
    for c in acc.data.copies.values():
        assert c.readers == 0
