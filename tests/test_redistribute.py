"""Redistribution battery (ref: tests/collections/redistribute + the
reshuffle variant redistribute_reshuffle.jdf): randomized geometries,
offsets and bounds on 1 and 4 ranks, plus the aligned fast path's
zero-copy property.
"""

import numpy as np
import pytest

import parsec_tpu as pt
from parsec_tpu.data.matrix import TiledMatrix, TwoDimBlockCyclic
from parsec_tpu.data.redistribute import redistribute
from parsec_tpu.dsl.dtd import DTDTaskpool


@pytest.fixture()
def ctx():
    c = pt.Context(nb_cores=1)
    yield c
    c.fini()


def _filled(name, lm, ln, mb, nb, base):
    M = TiledMatrix(name, lm, ln, mb, nb)
    M.fill(lambda m, k: base[m * mb:(m + 1) * mb, k * nb:(k + 1) * nb])
    return M


def _dense(M):
    return M.to_dense()


def test_random_sweep_single_rank(ctx):
    """Property battery: random tile sizes, region sizes and offsets on
    both sides; result must equal the numpy slice assignment."""
    rng = np.random.default_rng(123)
    for trial in range(16):
        s_mb, s_nb = rng.integers(3, 24, 2)
        t_mb, t_nb = rng.integers(3, 24, 2)
        s_lm, s_ln = rng.integers(30, 80, 2)
        t_lm, t_ln = rng.integers(30, 80, 2)
        m = int(rng.integers(1, min(s_lm, t_lm)))
        n = int(rng.integers(1, min(s_ln, t_ln)))
        si = int(rng.integers(0, s_lm - m + 1))
        sj = int(rng.integers(0, s_ln - n + 1))
        ti = int(rng.integers(0, t_lm - m + 1))
        tj = int(rng.integers(0, t_ln - n + 1))
        src = rng.standard_normal((s_lm, s_ln)).astype(np.float32)
        dst = rng.standard_normal((t_lm, t_ln)).astype(np.float32)
        S = _filled(f"rs{trial}", s_lm, s_ln, int(s_mb), int(s_nb), src)
        T = _filled(f"rt{trial}", t_lm, t_ln, int(t_mb), int(t_nb), dst)
        tp = DTDTaskpool(ctx, f"rsweep{trial}")
        ntasks = redistribute(tp, S, T, m, n, si, sj, ti, tj)
        tp.wait()
        tp.close()
        ctx.wait(timeout=60)
        expect = dst.copy()
        expect[ti:ti + m, tj:tj + n] = src[si:si + m, sj:sj + n]
        np.testing.assert_array_equal(
            _dense(T), expect,
            err_msg=f"trial {trial}: S({s_mb}x{s_nb}) T({t_mb}x{t_nb}) "
                    f"m={m} n={n} s=({si},{sj}) t=({ti},{tj}) "
                    f"tasks={ntasks}")
        assert ntasks >= 1


def test_reshuffle_fast_path_moves_by_reference(ctx):
    """Aligned same-geometry redistribution takes whole-tile moves: the
    landed payload IS the source tile's array (zero copies), and interior
    tiles produce exactly one task each."""
    rng = np.random.default_rng(7)
    mb = nb = 8
    src = rng.standard_normal((32, 32)).astype(np.float32)
    S = _filled("fpS", 32, 32, mb, nb, src)
    T = _filled("fpT", 32, 32, mb, nb, np.zeros((32, 32), np.float32))
    tp = DTDTaskpool(ctx, "fp")
    ntasks = redistribute(tp, S, T)              # full, aligned
    tp.wait()
    tp.close()
    ctx.wait(timeout=30)
    assert ntasks == 16                          # one per tile, no fragments
    np.testing.assert_array_equal(_dense(T), src)
    for tm in range(4):
        for tn in range(4):
            sp = S.data_of(tm, tn).newest_copy().payload
            dp = T.data_of(tm, tn).newest_copy().payload
            assert dp is sp                      # moved, not copied


def test_reshuffle_offset_congruent_but_nonzero(ctx):
    """si-ti congruent mod tile: interior tiles still whole-move; ragged
    edges fall back to fragments. Correctness against numpy either way."""
    rng = np.random.default_rng(8)
    mb = nb = 8
    src = rng.standard_normal((40, 40)).astype(np.float32)
    dst = rng.standard_normal((40, 40)).astype(np.float32)
    S = _filled("ocS", 40, 40, mb, nb, src)
    T = _filled("ocT", 40, 40, mb, nb, dst)
    tp = DTDTaskpool(ctx, "oc")
    # offsets differ by exactly one tile: congruent, fast path applies
    redistribute(tp, S, T, m=24, n=24, si=8, sj=8, ti=16, tj=16)
    tp.wait()
    tp.close()
    ctx.wait(timeout=30)
    expect = dst.copy()
    expect[16:40, 16:40] = src[8:32, 8:32]
    np.testing.assert_array_equal(_dense(T), expect)
    # an interior whole tile moved by reference
    assert T.data_of(2, 2).newest_copy().payload is \
        S.data_of(1, 1).newest_copy().payload


def test_unaligned_never_takes_fast_path(ctx):
    """Non-congruent offsets keep the fragment algebra (and stay right)."""
    rng = np.random.default_rng(9)
    mb = nb = 8
    src = rng.standard_normal((32, 32)).astype(np.float32)
    dst = rng.standard_normal((32, 32)).astype(np.float32)
    S = _filled("uaS", 32, 32, mb, nb, src)
    T = _filled("uaT", 32, 32, mb, nb, dst)
    tp = DTDTaskpool(ctx, "ua")
    redistribute(tp, S, T, m=16, n=16, si=3, sj=5, ti=6, tj=2)
    tp.wait()
    tp.close()
    ctx.wait(timeout=30)
    expect = dst.copy()
    expect[6:22, 2:18] = src[3:19, 5:21]
    np.testing.assert_array_equal(_dense(T), expect)


def _redist_4rank(rank, fabric):
    from parsec_tpu.comm.remote_dep import RemoteDepEngine
    from parsec_tpu.comm.threads import ThreadsCE

    rng = np.random.default_rng(77)
    src = rng.standard_normal((48, 48)).astype(np.float32)
    dst = rng.standard_normal((48, 48)).astype(np.float32)
    ctx = pt.Context(nb_cores=1, my_rank=rank, nb_ranks=4)
    RemoteDepEngine(ctx, ThreadsCE(fabric, rank))
    kw = dict(P=2, Q=2, nodes=4, myrank=rank)
    S = TwoDimBlockCyclic("d4S", 48, 48, 8, 8, **kw)
    T = TwoDimBlockCyclic("d4T", 48, 48, 12, 12, **kw)
    S.fill(lambda m, k: src[m * 8:(m + 1) * 8, k * 8:(k + 1) * 8])
    T.fill(lambda m, k: dst[m * 12:(m + 1) * 12, k * 12:(k + 1) * 12])
    tp = DTDTaskpool(ctx, "d4")
    redistribute(tp, S, T, m=30, n=26, si=5, sj=9, ti=11, tj=3)
    tp.wait(timeout=120)
    tp.close()
    ctx.wait(timeout=120)
    expect = dst.copy()
    expect[11:41, 3:29] = src[5:35, 9:35]
    out = {}
    for tm in range(4):
        for tn in range(4):
            if T.rank_of(tm, tn) == rank:
                out[(tm, tn)] = np.asarray(
                    T.data_of(tm, tn).newest_copy().payload)
    ctx.fini()
    errs = [float(np.abs(out[(tm, tn)]
                         - expect[tm * 12:(tm + 1) * 12,
                                  tn * 12:(tn + 1) * 12]).max())
            for (tm, tn) in out]
    return max(errs) if errs else 0.0


def test_random_offsets_four_ranks():
    """Unaligned cross-geometry redistribution across a 2x2 rank grid:
    owner-computes placement + remote source reads."""
    from parsec_tpu.comm.threads import run_distributed
    errs = run_distributed(4, _redist_4rank, timeout=240)
    assert max(errs) == 0.0, errs
