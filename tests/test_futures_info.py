"""Direct class-primitive unit tests: futures, datacopy futures, info slots.

Mirrors the reference's class-level batteries (tests/class/future.c,
tests/class/future_datacopy.c, info registration in parsec/class/info.h)
rather than exercising these types only through reshape/taskpool paths:
single-assignment and callback ordering, countdown combination, the
trigger-exactly-once datacopy promise under thread contention, and the
process-wide info slot registry.
"""

import threading
import time

import numpy as np
import pytest

from parsec_tpu.core.futures import CountdownFuture, DataCopyFuture, Future
from parsec_tpu.utils.info import InfoBag, InfoRegistry


# ------------------------------------------------------------------ Future

def test_future_single_assignment_and_callbacks():
    f = Future()
    seen = []
    f.on_ready(seen.append)            # registered before completion
    assert not f.ready
    f.set(42)
    assert f.ready and f.get() == 42
    f.on_ready(seen.append)            # registered after completion
    assert seen == [42, 42]
    with pytest.raises(RuntimeError, match="already completed"):
        f.set(43)


def test_future_get_blocks_until_set_across_threads():
    f = Future()
    vals = []

    def consumer():
        vals.append(f.get(timeout=10))

    ts = [threading.Thread(target=consumer) for _ in range(4)]
    for t in ts:
        t.start()
    time.sleep(0.02)
    f.set("payload")
    for t in ts:
        t.join(timeout=10)
    assert vals == ["payload"] * 4


def test_future_timeout_and_progress_pump():
    f = Future()
    with pytest.raises(TimeoutError):
        f.get(timeout=0.05)
    # the progress callable is pumped while waiting, so a single-threaded
    # runtime can fulfil its own future from inside the wait loop
    pumps = []

    def progress():
        pumps.append(1)
        if len(pumps) == 3:
            f.set("pumped")

    assert f.get(timeout=5, progress=progress) == "pumped"
    assert len(pumps) == 3


# --------------------------------------------------------- CountdownFuture

def test_countdown_future_combines_contributions():
    f = CountdownFuture(3, combine=lambda a, b: a + b)
    f.contribute(5)
    f.contribute(7)
    assert not f.ready
    f.contribute(30)
    assert f.ready and f.get() == 42


def test_countdown_future_threaded_contributions():
    n = 32
    f = CountdownFuture(n, combine=lambda a, b: a + b)
    ts = [threading.Thread(target=f.contribute, args=(i,)) for i in range(n)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=10)
    assert f.ready and f.get() == sum(range(n))


# ---------------------------------------------------------- DataCopyFuture

class _FakeCopy:
    def __init__(self, payload):
        self.payload = payload
        self.released = 0

    def release(self):
        self.released += 1


def test_datacopy_future_trigger_runs_exactly_once_under_contention():
    """The reshape-promise contract (ref future_datacopy.c): many consumers
    race request(); the conversion trigger runs once and every consumer
    observes the SAME converted copy."""
    src = _FakeCopy(np.arange(16, dtype=np.float32))
    calls = []

    def trigger(src_copy, spec):
        calls.append(spec)
        time.sleep(0.01)               # widen the race window
        return _FakeCopy(src_copy.payload.reshape(spec))

    fut = DataCopyFuture(src, (4, 4), trigger)
    got = []

    def consumer():
        got.append(fut.request())

    ts = [threading.Thread(target=consumer) for _ in range(8)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=10)
    assert len(calls) == 1             # trigger ran exactly once
    assert all(g is got[0] for g in got)
    assert got[0].payload.shape == (4, 4)


def test_datacopy_future_release_drops_reference():
    src = _FakeCopy(np.zeros(4))
    fut = DataCopyFuture(src, None, lambda c, s: _FakeCopy(c.payload))
    fut.release()                      # before trigger: nothing to drop
    out = fut.request()
    fut.release()
    fut.release()
    assert out.released == 2


# ------------------------------------------------------------- info slots

def test_info_registry_idempotent_ids_and_lookup():
    reg = InfoRegistry()
    a = reg.register("sched::spray")
    b = reg.register("device::load")
    assert a != b
    assert reg.register("sched::spray") == a     # idempotent
    assert reg.lookup("device::load") == b
    assert reg.lookup("missing") is None
    reg.unregister("sched::spray")
    assert reg.lookup("sched::spray") is None


def test_info_bag_sparse_slots():
    reg = InfoRegistry()
    bag = InfoBag()
    hi = reg.register("x")
    for _ in range(7):                 # ids grow; bag must autosize
        hi = reg.register(f"slot{hi}")
    bag.set(hi, "v")
    assert bag.get(hi) == "v"
    assert bag.get(0, default="d") == "d"        # unset low slot
    assert bag.get(hi + 100, default="d") == "d"  # beyond storage
    bag.set(0, 11)
    assert bag.get(0) == 11


def test_info_registry_threaded_registration_unique_ids():
    reg = InfoRegistry()
    ids = {}
    lock = threading.Lock()

    def worker(w):
        for i in range(50):
            iid = reg.register(f"name{i}")
            with lock:
                ids.setdefault(f"name{i}", set()).add(iid)

    ts = [threading.Thread(target=worker, args=(w,)) for w in range(4)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=10)
    # every name got exactly one id, and ids are distinct across names
    assert all(len(v) == 1 for v in ids.values())
    all_ids = [next(iter(v)) for v in ids.values()]
    assert len(set(all_ids)) == len(all_ids)
