"""Headroom-aware ingest gateway (ISSUE 11, tentpole part c).

The front door of the serving mesh: a client stream lands on ANY rank
and the gateway routes each insert to the rank with the most admission
headroom — **without a probe**. The advertisement is the credit balance
the fabric already holds per (rank, tenant): the serving side granted
those credits from its live window headroom, so the local ledger IS a
(slightly stale, strictly safe) view of every peer's capacity. Routing
therefore costs a few C map reads; the insert itself costs one local
credit spend plus one AM — zero admission round trips.

Placement policy per submit: pick the candidate rank with the largest
advertised headroom (self-rank advertises its live plane headroom);
stale-but-positive balances self-correct because each spend decrements
the balance read by the next submit. When EVERY candidate is exhausted,
the gateway blocks for replenishment (the serving tier's bounded-ingest
contract) or raises :class:`AdmissionBackpressure` under ``nowait=True``
— the adversarial-tenant example (examples/ex17_serving_fabric.py)
shows that this is what keeps one flooding tenant from moving another
tenant's p99.
"""

from __future__ import annotations

import time
from typing import Callable, Dict, List, Optional

from ..utils import mca
from .fabric import FAB_STATS, ServingFabric


class IngestGateway:
    """Routes one tenant-tagged insert stream across the mesh by
    advertised admission headroom."""

    def __init__(self, fabric: ServingFabric,
                 ranks: Optional[List[int]] = None) -> None:
        self.fabric = fabric
        #: candidate serving ranks (default: the whole mesh)
        self.ranks = list(ranks) if ranks is not None \
            else list(range(fabric.nb_ranks))
        #: routing outcome counts per rank (observability/tests)
        self.routed: Dict[int, int] = {r: 0 for r in self.ranks}

    # ---------------------------------------------------------- headroom
    def headroom_of(self, rank: int, tenant: str) -> int:
        """The advertised admission headroom of ``rank`` for ``tenant``:
        the local credit balance for peers, the live plane headroom for
        this rank itself (-1 = unlimited, ranked above any balance)."""
        fab = self.fabric
        if rank == fab.my_rank:
            return fab.headroom(tenant)
        if rank in fab._dead:
            return 0
        return fab.avail(rank, tenant)

    def headrooms(self, tenant: str) -> Dict[int, int]:
        return {r: self.headroom_of(r, tenant) for r in self.ranks}

    # ------------------------------------------------------------ routing
    def _pick(self, tenant: str) -> Optional[int]:
        """Largest advertised headroom wins; -1 (unlimited self) beats
        everything; all-zero -> None (backpressure)."""
        best, best_h = None, 0
        for r in self.ranks:
            h = self.headroom_of(r, tenant)
            if h < 0:
                return r
            if h > best_h:
                best, best_h = r, h
        return best

    def submit(self, tenant: str, payload, nowait: bool = False,
               timeout: Optional[float] = None) -> int:
        """Route one insert; returns the rank it landed on.

        Backpressure contract: with every candidate exhausted,
        ``nowait=True`` raises
        :class:`~parsec_tpu.dsl.dtd.AdmissionBackpressure` immediately
        (counted ``ptfab.remote_rejects``) — retry after the mesh
        retires work; otherwise block until any candidate's
        replenishment lands (counted ``ptfab.remote_stalls``)."""
        fab = self.fabric
        deadline = time.monotonic() + (
            timeout if timeout is not None
            else mca.get("fab_acquire_timeout", 30.0))
        stalled = False
        while True:
            r = self._pick(tenant)
            if r is not None:
                if r == fab.my_rank:
                    if self._ingest_local(tenant, payload):
                        self.routed[r] += 1
                        return r
                elif self._ingest_remote(r, tenant, payload):
                    self.routed[r] += 1
                    return r
                continue   # lost the race for that headroom: re-pick
            if nowait:
                from ..dsl.dtd import AdmissionBackpressure
                FAB_STATS["remote_rejects"] += 1
                raise AdmissionBackpressure(
                    f"every serving rank's admission window is exhausted "
                    f"for tenant {tenant!r} (ranks {self.ranks})")
            if not stalled:
                stalled = True
                FAB_STATS["remote_stalls"] += 1
            if time.monotonic() >= deadline:
                raise TimeoutError(
                    f"no serving rank freed admission room for tenant "
                    f"{tenant!r} within the timeout")
            if fab._thread is None:
                fab.step()             # harness mode: self-driven
            time.sleep(2e-4)

    def _ingest_local(self, tenant: str, payload) -> bool:
        fab = self.fabric
        t = fab.tenant(tenant)
        if t is None:
            return False
        if fab.plane is not None and t.handle >= 0 and \
                fab.plane.over_window(t.handle):
            return False
        if t.owns_handle and fab.plane is not None and t.handle >= 0:
            fab.plane.admit(t.handle, 1)
        if t.handler is not None:
            t.handler(payload, fab.my_rank)
        return True

    def _ingest_remote(self, rank: int, tenant: str, payload) -> bool:
        fab = self.fabric
        if not fab.comm.cred_take(rank, fab._pool_id(tenant),
                                  _tid(tenant), 1):
            return False
        fab.send_insert(rank, tenant, payload)
        return True


def _tid(tenant: str) -> int:
    from .fabric import tenant_id_for
    return tenant_id_for(tenant)


def serve_dtd_tenant(fabric: ServingFabric, tenant: str, taskpool,
                     insert: Callable) -> None:
    """Convenience glue for the common shape: serve ``tenant`` backed by
    a plane-bound DTD ``taskpool``, routing each gateway insert through
    ``insert(payload)`` (which calls ``taskpool.insert_task``); window +
    weight come from the pool's own plane registration."""
    fabric.serve(tenant, handler=lambda payload, src: insert(payload),
                 taskpool=taskpool)
