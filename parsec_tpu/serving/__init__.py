"""parsec_tpu.serving — the cross-rank serving fabric (ptfab, ISSUE 11).

The multi-tenant control plane over the native lanes: credit-based
remote admission on the ptcomm wire, mesh-wide QoS share reconciliation
nudging per-rank ptsched DRR weights, and a headroom-aware ingest
gateway. See docs/serving.md.
"""

from .fabric import (FAB_STATS, FAB_WIRE_KEYS, ServingFabric,
                     fab_wire_sampler, tenant_id_for)
from .gateway import IngestGateway, serve_dtd_tenant
from .reconcile import ShareReconciler

__all__ = ["FAB_STATS", "FAB_WIRE_KEYS", "ServingFabric",
           "fab_wire_sampler", "tenant_id_for", "IngestGateway",
           "serve_dtd_tenant", "ShareReconciler"]
