"""Mesh-wide QoS share reconciliation (ISSUE 11, tentpole part b).

Per-rank weighted DRR (PR 9) guarantees shares WITHIN a rank; nothing
guaranteed them ACROSS ranks — a tenant draining mostly on rank 3 could
take 3x its global share while staying exactly on-weight everywhere.
This module closes the loop without a global lock anywhere near the hot
path:

* rank 0 runs a :class:`ShareReconciler` — a slow control loop (default
  4 Hz) that scrapes every rank's ``/metrics`` endpoint (the PR 8
  observability plane) for the ``ptfab.served.<tenant>`` counters the
  fabric registers per served tenant;
* each round it computes the MEASURED global share of every tenant over
  the last window (served deltas summed across ranks), compares against
  the target share from the global weights, and nudges a per-tenant
  weight multiplier: ``m *= (target / measured) ** gain`` (clamped — a
  cold tenant must not explode its weight);
* the nudged weights quantize to integer DRR weights (scale 16) and ride
  one ``TAG_PTFAB {"k": "weights"}`` AM to every rank, where the fabric
  applies them through the new ``Plane.set_weight`` capsule entry —
  weights bind at the next DRR round top-up, so convergence is smooth,
  not steppy;
* consumer (c) of the online cost model loop (ISSUE 18): the nudge
  exponent itself ADAPTS to the measured convergence error instead of
  staying the fixed 0.6 — an error that grew since the last round means
  the loop overshot (damp the gain), an error that stays large and
  barely shrinks means it converges too slowly (raise it). Gated by
  ``--mca costmodel_reconcile`` and clamped to [0.1, 1.5]; every nudge
  counts ``costmodel.gain_adapted``.

Convergence caveats (documented in docs/serving.md): shares only bind
while every tenant keeps every rank's drain backlogged (DRR serves an
idle tenant at its arrival rate, as within one rank), and the loop
measures SERVED tasks — heterogeneous task costs reconcile task-shares,
not cpu-shares.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

from ..utils import output
from .fabric import FAB_STATS, ServingFabric


class ShareReconciler:
    """Rank-0 control loop converging measured per-tenant global shares
    to the global QoS weights by nudging per-rank local DRR weights."""

    #: integer-weight quantization: multiplier 1.0 -> DRR weight
    #: base_weight * scale stays exact for the common small weights.
    #: CAVEAT (docs/serving.md): a pool's weight binds only while its
    #: backlog exceeds weight * plane-quantum, so scale * quantum should
    #: stay well under the admission windows — serving meshes pair a
    #: small --mca sched_quantum with a small scale.
    SCALE = 16

    #: a round whose total served delta is below this carries no usable
    #: share signal (a 0-delta tenant would read as "starved" and get a
    #: runaway boost): skip the nudge, keep the baseline
    MIN_WINDOW_TASKS = 32

    def __init__(self, fabric: ServingFabric, endpoints: List[str],
                 weights: Dict[str, float], *, period: float = 0.25,
                 gain: float = 0.6, max_mult: float = 16.0,
                 scale: Optional[int] = None) -> None:
        self.fabric = fabric
        self.endpoints = list(endpoints)   # rank-indexed /metrics addrs
        self.weights = dict(weights)       # tenant -> global weight
        self.period = period
        self.gain = gain
        self.max_mult = max_mult
        self.scale = scale if scale is not None else self.SCALE
        self._mult = {t: 1.0 for t in weights}       # nudged multiplier
        self._last: Optional[Dict[str, int]] = None  # served at last round
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.rounds = 0
        self.last_err_pct: Optional[float] = None
        self._prev_err: Optional[float] = None   # gain scheduling state

    # ------------------------------------------------------------ scraping
    def _scrape(self) -> Optional[Dict[str, int]]:
        """Global served-per-tenant: the ptfab.served.* counters summed
        over every rank's /metrics. ANY failed endpoint voids the whole
        round (None): a partial sum would read a tenant served mostly on
        the missing rank as STARVED and runaway-boost its weight — the
        loop is advisory and must mis-steer on no round."""
        from ..tools.metrics_server import fetch
        served = {t: 0 for t in self.weights}
        for ep in self.endpoints:
            try:
                counters = fetch(ep)["counters"]
            except Exception:  # noqa: BLE001 — scrape again next round
                return None
            for t in served:
                served[t] += int(counters.get(f"ptfab.served.{t}", 0) or 0)
        return served

    # ------------------------------------------------------------- rounds
    def step(self) -> Optional[float]:
        """One reconciliation round; returns the max share error (pct)
        over the window, or None when the window carried no service."""
        served = self._scrape()
        if served is None:
            return None           # _last unchanged: cumulative counters
                                  # make the next delta span both rounds
        last, self._last = self._last, served
        if last is None:
            return None
        delta = {t: max(0, served[t] - last.get(t, 0)) for t in served}
        total = sum(delta.values())
        tot_w = sum(self.weights.values())
        if total < self.MIN_WINDOW_TASKS or tot_w <= 0:
            return None
        err_max = 0.0
        new_w: Dict[str, int] = {}
        for t, w in self.weights.items():
            target = w / tot_w
            measured = delta[t] / total
            if measured > 0:
                err = abs(measured - target) / target * 100.0
                err_max = max(err_max, err)
                nudge = (target / measured) ** self.gain
                # clamp the per-round nudge AND the cumulative multiplier
                nudge = min(2.0, max(0.5, nudge))
                self._mult[t] = min(self.max_mult,
                                    max(1.0 / self.max_mult,
                                        self._mult[t] * nudge))
            else:
                # a starved tenant: open its weight decisively (measured
                # share 0 has no finite ratio)
                err_max = max(err_max, 100.0)
                self._mult[t] = min(self.max_mult, self._mult[t] * 2.0)
            new_w[t] = max(1, int(round(w * self._mult[t] * self.scale)))
        self.rounds += 1
        self.last_err_pct = round(err_max, 1)
        self._adapt_gain(err_max)
        FAB_STATS["reconcile_rounds"] += 1
        FAB_STATS["share_err_pct"] = self.last_err_pct
        self._broadcast(new_w, self.last_err_pct)
        return err_max

    def _adapt_gain(self, err: float) -> None:
        """Consumer (c) of the online cost model loop (ISSUE 18): the
        nudge exponent tracks MEASURED convergence error round to round.
        Error grew >5% over the last round → the loop overshot (the
        clamped multiplier oscillates around the target): damp the gain
        by 0.7. Error still large (>5%) and shrinking by less than 30% →
        too timid: raise it by 1.15. Clamped to [0.1, 1.5] — above ~1
        the pure-ratio controller is already at the edge of ringing, 0.1
        still converges, just slowly. One float compare per 4 Hz round:
        nowhere near any hot path."""
        from ..utils import mca
        if not mca.get("costmodel_reconcile", True):
            self._prev_err = err
            return
        prev, self._prev_err = self._prev_err, err
        if prev is None:
            return
        g = self.gain
        if err > prev * 1.05:
            g *= 0.7
        elif err > 5.0 and err > prev * 0.7:
            g *= 1.15
        g = min(1.5, max(0.1, g))
        if g != self.gain:
            self.gain = g
            from ..core.costmodel import COSTMODEL_STATS
            COSTMODEL_STATS["gain_adapted"] += 1

    def _broadcast(self, weights: Dict[str, int], err_pct: float) -> None:
        fab = self.fabric
        # apply locally first (rank 0 serves too), then AM the peers
        for t, w in weights.items():
            fab.set_weight(t, w)
        if fab.rde is None:
            return
        from ..comm.engine import TAG_PTFAB
        hdr = {"k": "weights", "w": weights, "err": err_pct}
        for r in range(fab.nb_ranks):
            if r == fab.my_rank or r in fab._dead:
                continue
            try:
                fab.rde.ce.send_am(TAG_PTFAB, r, hdr, None)
            except Exception:  # noqa: BLE001 — a dying peer reconciles 0x
                pass

    # ---------------------------------------------------------- lifecycle
    def start(self) -> "ShareReconciler":
        if self._thread is None:
            self._thread = threading.Thread(target=self._loop, daemon=True,
                                            name="ptfab-reconcile")
            self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.wait(self.period):
            try:
                self.step()
            except Exception as e:  # noqa: BLE001 — advisory loop
                output.debug_verbose(1, "ptfab", f"reconcile round: {e}")

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None
