"""Mesh-wide QoS share reconciliation (ISSUE 11, tentpole part b).

Per-rank weighted DRR (PR 9) guarantees shares WITHIN a rank; nothing
guaranteed them ACROSS ranks — a tenant draining mostly on rank 3 could
take 3x its global share while staying exactly on-weight everywhere.
This module closes the loop without a global lock anywhere near the hot
path:

* rank 0 runs a :class:`ShareReconciler` — a slow control loop (default
  4 Hz) that reads every rank's ``ptfab.served.<tenant>`` counters. With
  the pttel telemetry plane running (ISSUE 20) the readings come out of
  the PUSHED mesh rollup — zero HTTP fetches per round, the tree already
  delivered every rank's counters to rank 0; without it the loop falls
  back to scraping each rank's ``/metrics`` endpoint (the PR 8
  observability plane). Either way a missing rank (stale in the rollup,
  or a failed fetch) no longer voids the round: the loop reconciles over
  the reporting ranks (``reconcile.partial_rounds``) and skips only the
  missing ranks' weight nudges — their cumulative counters make the next
  delta span both rounds;
* each round it computes the MEASURED global share of every tenant over
  the last window (served deltas summed across ranks), compares against
  the target share from the global weights, and nudges a per-tenant
  weight multiplier: ``m *= (target / measured) ** gain`` (clamped — a
  cold tenant must not explode its weight);
* the nudged weights quantize to integer DRR weights (scale 16) and ride
  one ``TAG_PTFAB {"k": "weights"}`` AM to every rank, where the fabric
  applies them through the new ``Plane.set_weight`` capsule entry —
  weights bind at the next DRR round top-up, so convergence is smooth,
  not steppy;
* consumer (c) of the online cost model loop (ISSUE 18): the nudge
  exponent itself ADAPTS to the measured convergence error instead of
  staying the fixed 0.6 — an error that grew since the last round means
  the loop overshot (damp the gain), an error that stays large and
  barely shrinks means it converges too slowly (raise it). Gated by
  ``--mca costmodel_reconcile`` and clamped to [0.1, 1.5]; every nudge
  counts ``costmodel.gain_adapted``.

Convergence caveats (documented in docs/serving.md): shares only bind
while every tenant keeps every rank's drain backlogged (DRR serves an
idle tenant at its arrival rate, as within one rank), and the loop
measures SERVED tasks — heterogeneous task costs reconcile task-shares,
not cpu-shares.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, Iterable, List, Optional, Set, Tuple

from ..utils import output
from ..utils.counters import LaneStats
from .fabric import FAB_STATS, ServingFabric

#: exported as ``reconcile.*`` by install_native_counters
RECONCILE_STATS = LaneStats(
    push_rounds=0,      # rounds fed by the pttel mesh rollup (0 fetches)
    scrape_rounds=0,    # rounds that fell back to per-rank HTTP
    http_fetches=0,     # individual /metrics GETs issued (fallback only)
    partial_rounds=0,   # rounds reconciled with >= 1 rank missing
    missing_ranks=0,    # cumulative missing-rank observations
)


class ShareReconciler:
    """Rank-0 control loop converging measured per-tenant global shares
    to the global QoS weights by nudging per-rank local DRR weights."""

    #: integer-weight quantization: multiplier 1.0 -> DRR weight
    #: base_weight * scale stays exact for the common small weights.
    #: CAVEAT (docs/serving.md): a pool's weight binds only while its
    #: backlog exceeds weight * plane-quantum, so scale * quantum should
    #: stay well under the admission windows — serving meshes pair a
    #: small --mca sched_quantum with a small scale.
    SCALE = 16

    #: a round whose total served delta is below this carries no usable
    #: share signal (a 0-delta tenant would read as "starved" and get a
    #: runaway boost): skip the nudge, keep the baseline
    MIN_WINDOW_TASKS = 32

    #: a rank whose rollup entry is staler than this many telemetry
    #: intervals counts as missing for the round (push mode): nudging on
    #: a frozen snapshot would mis-read a live tenant as starved
    STALE_INTERVALS = 8.0

    def __init__(self, fabric: ServingFabric, endpoints: List[str],
                 weights: Dict[str, float], *, period: float = 0.25,
                 gain: float = 0.6, max_mult: float = 16.0,
                 scale: Optional[int] = None, tel: Any = "auto") -> None:
        self.fabric = fabric
        self.endpoints = list(endpoints)   # rank-indexed /metrics addrs
        self.weights = dict(weights)       # tenant -> global weight
        self.period = period
        self.gain = gain
        self.max_mult = max_mult
        self.scale = scale if scale is not None else self.SCALE
        #: "auto" = discover the telemetry plane through fabric.rde per
        #: round (it attaches after the reconciler in some harnesses);
        #: None = HTTP only; a TelemetryPlane pins the push source
        self.tel = tel
        self._mult = {t: 1.0 for t in weights}       # nudged multiplier
        #: per-rank served-at-last-round; a missing rank KEEPS its old
        #: entry so its next delta spans the gap (cumulative counters)
        self._last: Optional[Dict[int, Dict[str, int]]] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.rounds = 0
        self.last_err_pct: Optional[float] = None
        self.last_mode: Optional[str] = None     # "push" | "scrape"
        self.converged_round: Optional[int] = None  # first round <= 15%
        self._prev_err: Optional[float] = None   # gain scheduling state

    # ------------------------------------------------------------ scraping
    def _telemetry(self):
        if self.tel == "auto":
            return getattr(getattr(self.fabric, "rde", None),
                           "telemetry", None)
        return self.tel or None

    def _served_of(self, counters: Dict[str, Any]) -> Dict[str, int]:
        return {t: int(counters.get(f"ptfab.served.{t}", 0) or 0)
                for t in self.weights}

    def _from_push(self, tel) -> Optional[
            Tuple[Dict[int, Dict[str, int]], Set[int]]]:
        """Read the round out of the pushed mesh rollup: zero network
        traffic here — the tree already delivered every rank's counters.
        A rank absent from the rollup (or staler than STALE_INTERVALS
        telemetry rounds) counts as missing for this round."""
        roll = tel.rollup()
        ranks = roll.get("ranks", {})
        bound = max(0.25, self.STALE_INTERVALS * tel.interval_s)
        per_rank: Dict[int, Dict[str, int]] = {}
        missing: Set[int] = set()
        for r in range(self.fabric.nb_ranks):
            ent = ranks.get(r)
            if ent is None or ent.get("staleness_s", bound) > bound:
                missing.add(r)
                continue
            per_rank[r] = self._served_of(ent.get("counters", {}))
        if not per_rank:
            return None           # no subtree landed yet: let HTTP try
        RECONCILE_STATS["push_rounds"] += 1
        self.last_mode = "push"
        return per_rank, missing

    def _from_http(self) -> Optional[
            Tuple[Dict[int, Dict[str, int]], Set[int]]]:
        """Fallback: per-rank /metrics GETs. A failed endpoint no longer
        voids the round — it joins the missing set and only its nudges
        are skipped (the partial-round contract, ISSUE 20 satellite)."""
        from ..tools.metrics_server import fetch
        per_rank: Dict[int, Dict[str, int]] = {}
        missing: Set[int] = set()
        for r, ep in enumerate(self.endpoints):
            try:
                RECONCILE_STATS["http_fetches"] += 1
                per_rank[r] = self._served_of(fetch(ep)["counters"])
            except Exception:  # noqa: BLE001 — scrape that rank next round
                missing.add(r)
        if not per_rank:
            return None
        RECONCILE_STATS["scrape_rounds"] += 1
        self.last_mode = "scrape"
        return per_rank, missing

    def _scrape(self):
        """The round's readings: the pushed rollup when the telemetry
        plane runs, per-rank HTTP otherwise. Returns ``(per_rank,
        missing)`` — or a flat ``{tenant: total}`` dict from legacy
        monkeypatched tests, which :meth:`step` normalizes."""
        tel = self._telemetry()
        if tel is not None:
            got = self._from_push(tel)
            if got is not None:
                return got
        return self._from_http()

    # ------------------------------------------------------------- rounds
    def step(self) -> Optional[float]:
        """One reconciliation round; returns the max share error (pct)
        over the window, or None when the window carried no service."""
        got = self._scrape()
        if got is None:
            return None           # _last unchanged: cumulative counters
                                  # make the next delta span both rounds
        if isinstance(got, dict):
            # legacy monkeypatched scrape: flat {tenant: mesh total} —
            # model it as a single pseudo-rank so the math is unchanged
            per_rank: Dict[int, Dict[str, int]] = {
                0: {t: int(got.get(t, 0) or 0) for t in self.weights}}
            missing: Set[int] = set()
        else:
            per_rank, missing = got
        if missing:
            RECONCILE_STATS["partial_rounds"] += 1
            RECONCILE_STATS["missing_ranks"] += len(missing)
        last = self._last or {}
        # missing ranks keep their old entry: the cumulative counters
        # make their next delta span the gap instead of losing it
        self._last = {**last, **per_rank}
        common = [r for r in per_rank if r in last]
        if not common:
            return None
        delta = {t: 0 for t in self.weights}
        for r in common:
            cur, prev = per_rank[r], last[r]
            for t in delta:
                delta[t] += max(0, cur.get(t, 0) - prev.get(t, 0))
        total = sum(delta.values())
        tot_w = sum(self.weights.values())
        if total < self.MIN_WINDOW_TASKS or tot_w <= 0:
            return None
        err_max = 0.0
        new_w: Dict[str, int] = {}
        for t, w in self.weights.items():
            target = w / tot_w
            measured = delta[t] / total
            if measured > 0:
                err = abs(measured - target) / target * 100.0
                err_max = max(err_max, err)
                nudge = (target / measured) ** self.gain
                # clamp the per-round nudge AND the cumulative multiplier
                nudge = min(2.0, max(0.5, nudge))
                self._mult[t] = min(self.max_mult,
                                    max(1.0 / self.max_mult,
                                        self._mult[t] * nudge))
            else:
                # a starved tenant: open its weight decisively (measured
                # share 0 has no finite ratio)
                err_max = max(err_max, 100.0)
                self._mult[t] = min(self.max_mult, self._mult[t] * 2.0)
            new_w[t] = max(1, int(round(w * self._mult[t] * self.scale)))
        self.rounds += 1
        self.last_err_pct = round(err_max, 1)
        if self.converged_round is None and err_max <= 15.0:
            self.converged_round = self.rounds
        self._adapt_gain(err_max)
        FAB_STATS["reconcile_rounds"] += 1
        FAB_STATS["share_err_pct"] = self.last_err_pct
        self._broadcast(new_w, self.last_err_pct, skip=missing)
        return err_max

    def _adapt_gain(self, err: float) -> None:
        """Consumer (c) of the online cost model loop (ISSUE 18): the
        nudge exponent tracks MEASURED convergence error round to round.
        Error grew >5% over the last round → the loop overshot (the
        clamped multiplier oscillates around the target): damp the gain
        by 0.7. Error still large (>5%) and shrinking by less than 30% →
        too timid: raise it by 1.15. Clamped to [0.1, 1.5] — above ~1
        the pure-ratio controller is already at the edge of ringing, 0.1
        still converges, just slowly. One float compare per 4 Hz round:
        nowhere near any hot path."""
        from ..utils import mca
        if not mca.get("costmodel_reconcile", True):
            self._prev_err = err
            return
        prev, self._prev_err = self._prev_err, err
        if prev is None:
            return
        g = self.gain
        if err > prev * 1.05:
            g *= 0.7
        elif err > 5.0 and err > prev * 0.7:
            g *= 1.15
        g = min(1.5, max(0.1, g))
        if g != self.gain:
            self.gain = g
            from ..core.costmodel import COSTMODEL_STATS
            COSTMODEL_STATS["gain_adapted"] += 1

    def _broadcast(self, weights: Dict[str, int], err_pct: float,
                   skip: Iterable[int] = ()) -> None:
        fab = self.fabric
        skip = set(skip)
        # apply locally first (rank 0 serves too), then AM the peers;
        # ranks missing from this round's readings are skipped — their
        # share was not measured, so a nudge would mis-steer them
        for t, w in weights.items():
            fab.set_weight(t, w)
        if fab.rde is None:
            return
        from ..comm.engine import TAG_PTFAB
        hdr = {"k": "weights", "w": weights, "err": err_pct}
        for r in range(fab.nb_ranks):
            if r == fab.my_rank or r in fab._dead or r in skip:
                continue
            try:
                fab.rde.ce.send_am(TAG_PTFAB, r, hdr, None)
            except Exception:  # noqa: BLE001 — a dying peer reconciles 0x
                pass

    # ---------------------------------------------------------- lifecycle
    def start(self) -> "ShareReconciler":
        if self._thread is None:
            self._thread = threading.Thread(target=self._loop, daemon=True,
                                            name="ptfab-reconcile")
            self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.wait(self.period):
            try:
                self.step()
            except Exception as e:  # noqa: BLE001 — advisory loop
                output.debug_verbose(1, "ptfab", f"reconcile round: {e}")

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None
