"""ptfab — the cross-rank serving fabric (ISSUE 11).

The fifth subsystem, layered on ptcomm + ptsched: everything PR 9 built
per rank (QoS weights, admission windows, backpressure) made to SPAN the
mesh. Three cooperating pieces live in this package:

* **credit-based remote admission** (this module): a rank serving a
  tenant grants admission credits to every remote inserter over the wire
  (the ``K_CRED`` frame beside ACTS — layout in
  ``native/src/ptcomm_iface.h``); a remote insert then SPENDS a credit
  locally (``Comm.cred_take``, one map op, zero wire round trips on the
  hot path) and blocks or raises
  :class:`~parsec_tpu.dsl.dtd.AdmissionBackpressure` when the balance is
  exhausted. Grants are replenished from the target pool's retire-driven
  headroom (``Plane.headroom``: window − inflight − remote_granted, so
  local and remote admission share ONE budget) and reclaimed on peer
  death through ptcomm's containment surface (``broken_peers`` +
  ``cred_reclaim``) — no hung inserter, no leaked window.
* **mesh-wide share reconciliation**
  (:mod:`parsec_tpu.serving.reconcile`): a rank-0 control loop scraping
  the per-rank ``/metrics`` served counters and nudging each rank's
  local DRR weights through the new ``Plane.set_weight`` entry — no
  global lock anywhere near the hot path.
* **headroom-aware ingest gateway**
  (:mod:`parsec_tpu.serving.gateway`): load-balances inserts across
  ranks by the credits it already holds — the advertised admission
  headroom — so a loaded rank sheds ingest to its peers without a probe.

The fabric is the CONTROL plane: credits gate insertion, the inserted
work itself rides whatever lane its pool rides. Engagement is counted
(``FAB_STATS``), declines are honest, and every wire counter exports as
``ptfab.*`` through the unified registry (docs/serving.md).
"""

from __future__ import annotations

import threading
import time
import zlib
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

import weakref

from ..utils import mca, output
from ..utils.counters import LaneStats

mca.register("fab_enabled", True,
             "Arm the cross-rank serving fabric (ptfab) when a native "
             "comm lane and a scheduler plane are both up; 0 keeps "
             "admission rank-local (PR 9 semantics)", type=bool)
mca.register("fab_credit_line", 0,
             "Per-(tenant, peer) credit line the replenisher maintains: "
             "grants top each remote inserter's spendable balance back "
             "up to this many credits as the target pool retires work. "
             "0 = auto: window // (2 * npeers) for bounded pools (half "
             "the window is reserved for remote ingest), 64 for "
             "unlimited pools", type=int)
mca.register("fab_replenish_ms", 5.0,
             "Cadence of the fabric's replenish/containment round "
             "(credit top-ups, inbox drain, dead-peer reclaim)",
             type=float)
mca.register("fab_acquire_timeout", 30.0,
             "Seconds a BLOCKING remote acquire waits for credits before "
             "raising (a dead target is detected earlier via reclaim)",
             type=float)

#: engagement + outcome counters (the honest-fallback contract of the
#: lanes). ``share_err_pct`` is a gauge, not a counter: the latest
#: reconciliation round's max per-tenant share error, pushed to every
#: rank with the weight nudges so each /metrics endpoint exports it.
FAB_STATS = LaneStats(fabrics_up=0, fabrics_unavailable=0,
                      tenants_served=0, remote_stalls=0, remote_rejects=0,
                      remote_inserts_tx=0, remote_inserts_rx=0,
                      reconcile_rounds=0, share_err_pct=0,
                      peer_reclaims=0)

#: C-side wire counters exported as ``ptfab.<name>`` (summed across the
#: live fabrics' comm lanes — the ptcomm.* sampler pattern)
FAB_WIRE_KEYS = {"credits_granted": "creds_granted_tx",
                 "credits_received": "creds_granted_rx",
                 "credits_spent": "creds_spent",
                 "credits_returned": "creds_returned_tx",
                 "credits_reclaimed": "creds_reclaimed",
                 "cred_frames_tx": "cred_frames_tx",
                 "cred_frames_rx": "cred_frames_rx"}

_fabrics: "weakref.WeakSet[ServingFabric]" = weakref.WeakSet()


def fab_wire_sampler(comm_key: str):
    """Registry sampler summing one ptcomm credit counter over live
    fabrics (each fabric's lane TTL-caches its stats() snapshot)."""
    def sample():
        total = 0
        for fab in list(_fabrics):
            try:
                total += fab.comm_stats().get(comm_key, 0)
            except Exception:  # noqa: BLE001 — a torn-down fabric reads 0
                pass
        return total
    return sample


def tenant_id_for(name: str) -> int:
    """Rank-consistent tenant ids, the pool_id_for discipline: derived
    from the NAME so every rank keys the same (pool, tenant) ledger."""
    return zlib.crc32(name.encode()) & 0x7FFFFFFF


class _Tenant:
    """One served tenant on this rank: plane identity + ingest handler."""

    __slots__ = ("name", "tid", "pool_id", "handle", "owns_handle",
                 "handler", "taskpool", "credit_line")

    def __init__(self, name: str, tid: int, pool_id: int, handle: int,
                 owns_handle: bool, handler, taskpool, credit_line: int):
        self.name = name
        self.tid = tid
        self.pool_id = pool_id
        self.handle = handle
        self.owns_handle = owns_handle
        self.handler = handler
        self.taskpool = taskpool
        self.credit_line = credit_line


class ServingFabric:
    """One rank's serving fabric: credit ledgers + replenisher + ingest.

    Two construction modes:

    * :meth:`attach` — the production path: built from a live
      distributed :class:`~parsec_tpu.core.context.Context` whose native
      comm lane and scheduler plane are up (declines are counted);
    * direct — the harness path: tests hand a raw ``_ptcomm.Comm`` pair
      (socketpair-pumped) and a plane, drive :meth:`step` manually.
    """

    def __init__(self, comm, plane, my_rank: int, nb_ranks: int, *,
                 rde=None, lane=None, replenish: bool = True) -> None:
        self.comm = comm
        self.plane = plane            # SchedPlane (may be None: no QoS)
        self.my_rank = my_rank
        self.nb_ranks = nb_ranks
        self.rde = rde
        self.lane = lane              # NativeCommLane (stats TTL cache)
        self._tenants: Dict[str, _Tenant] = {}
        self._by_key: Dict[Tuple[int, int], _Tenant] = {}
        #: peers' /metrics endpoints (announce_endpoint exchange): how
        #: the rank-0 reconciler discovers its scrape targets
        self.endpoints: Dict[int, str] = {}
        #: harness-mode insert transport: (dst, hdr, payload) callable
        #: standing in for the CE AM plane when no rde is attached
        self.insert_transport: Optional[Callable] = None
        self._lock = threading.Lock()
        self._inbox: "deque[Tuple[int, Dict, Any]]" = deque()
        self._dead: set = set()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._up = True
        FAB_STATS["fabrics_up"] += 1
        _fabrics.add(self)
        if rde is not None:
            rde.fab_attach(self)
        if replenish:
            self._thread = threading.Thread(
                target=self._loop, daemon=True,
                name=f"ptfab-replenish-r{my_rank}")
            self._thread.start()

    # ------------------------------------------------------------ creation
    @classmethod
    def attach(cls, ctx) -> Optional["ServingFabric"]:
        """Build the fabric on a live distributed context, or None with
        the decline COUNTED (lane down, plane down, or mca-disabled)."""
        if not mca.get("fab_enabled", True):
            return None
        rde = getattr(ctx, "comm", None)
        lane = getattr(rde, "native", None) if rde is not None else None
        plane = getattr(ctx, "sched_plane", None)
        if lane is None or plane is None:
            FAB_STATS["fabrics_unavailable"] += 1
            output.debug_verbose(1, "ptfab",
                                 "serving fabric off: "
                                 f"lane={'up' if lane else 'down'} "
                                 f"plane={'up' if plane else 'down'}")
            return None
        fab = cls(lane.comm, plane, ctx.my_rank, ctx.nb_ranks,
                  rde=rde, lane=lane)
        output.debug_verbose(1, "ptfab",
                             f"serving fabric up on rank {ctx.my_rank}")
        return fab

    # ------------------------------------------------------------- tenants
    def serve(self, tenant: str, handler: Optional[Callable] = None, *,
              window: int = 0, weight: int = 1, taskpool=None,
              credit_line: Optional[int] = None) -> None:
        """Serve ``tenant`` on this rank: remote inserters may acquire
        credits against it and route inserts here.

        With ``taskpool`` (a plane-bound DTD pool), admission accounting
        rides the pool's own plane handle — its window/weight are
        authoritative and an arriving insert's reservation converts into
        the pool's normal admit-at-insert. Without one, the fabric
        registers its own plane pool (KIND_EXT) with ``window``/
        ``weight`` and callers retire via :meth:`done`. ``handler(payload,
        src)`` runs each routed insert (from the fabric thread, or
        :meth:`step` in harness mode)."""
        tid = tenant_id_for(tenant)
        pool_id = self._pool_id(tenant, taskpool)
        handle, owns = -1, False
        if taskpool is not None and \
                getattr(taskpool, "_sched_pool", None) is not None:
            handle = taskpool._sched_pool
        elif self.plane is not None:
            h = self.plane.register_pool(f"fab:{tenant}",
                                         self.plane.KIND_EXT,
                                         weight=weight, window=window)
            if h >= 0:
                handle, owns = h, True
        t = _Tenant(tenant, tid, pool_id, handle, owns, handler, taskpool,
                    credit_line if credit_line is not None
                    else mca.get("fab_credit_line", 0))
        with self._lock:
            self._tenants[tenant] = t
            self._by_key[(pool_id, tid)] = t
        FAB_STATS["tenants_served"] += 1
        self._register_served_counter(t)

    @staticmethod
    def _pool_id(tenant: str, taskpool=None) -> int:
        # the wire ledger key is ALWAYS the fabric identity, taskpool-
        # backed or not: both ends derive it from the tenant name alone,
        # so a pure-gateway rank (serving nothing) addresses the same
        # ledger as a serving rank (the rank-consistent-id discipline of
        # NativeCommLane.pool_id_for)
        from ..comm.native import NativeCommLane
        return NativeCommLane.pool_id_for(f"fab:{tenant}")

    def _register_served_counter(self, t: _Tenant) -> None:
        """``ptfab.served.<tenant>`` on /metrics: what the reconciler
        scrapes. Weakly bound — a retired pool handle samples 0."""
        from ..utils.counters import counters
        plane, handle = self.plane, t.handle
        if plane is None or handle < 0:
            return

        def sample():
            try:
                return plane.pool_stats(handle)["served"]
            except Exception:  # noqa: BLE001 — plane torn down
                return 0
        counters.register(f"ptfab.served.{t.name}", sampler=sample)

    def tenant(self, name: str) -> Optional[_Tenant]:
        with self._lock:
            return self._tenants.get(name)

    def set_weight(self, tenant: str, weight: int) -> None:
        t = self.tenant(tenant)
        if t is not None and self.plane is not None and t.handle >= 0:
            self.plane.set_weight(t.handle, int(weight))

    def headroom(self, tenant: str) -> int:
        """LOCAL grantable window room of the tenant's pool (-1 =
        unlimited) — the gateway's self-rank advertisement."""
        t = self.tenant(tenant)
        if t is None or self.plane is None or t.handle < 0:
            return 0
        return self.plane.headroom(t.handle)

    def done(self, tenant: str, n: int = 1) -> None:
        """Retire n routed inserts of a fabric-owned tenant (taskpool-
        backed tenants retire through their pool's own accounting)."""
        t = self.tenant(tenant)
        if t is not None and t.owns_handle and self.plane is not None:
            self.plane.retired(t.handle, n)

    # --------------------------------------------------- inserter side
    def avail(self, dst: int, tenant: str) -> int:
        """Spendable credit balance toward rank ``dst`` — the advertised
        admission headroom, read locally (zero round trips)."""
        t_id = tenant_id_for(tenant)
        return self.comm.cred_avail(
            dst, self._pool_id_remote(tenant), t_id)

    def _pool_id_remote(self, tenant: str) -> int:
        return self._pool_id(tenant)

    def acquire(self, dst: int, tenant: str, n: int = 1,
                nowait: bool = False,
                timeout: Optional[float] = None) -> None:
        """Spend ``n`` admission credits toward rank ``dst`` — LOCALLY.

        The hot path is one C map op (``cred_take``); no wire traffic,
        no round trip. Exhausted balance: ``nowait=True`` raises
        :class:`AdmissionBackpressure` (counted ``remote_rejects``),
        otherwise block-polls until the granting rank's retire-driven
        replenishment lands (counted ``remote_stalls``) — or the peer
        dies, which raises instead of hanging."""
        pool_id, tid = self._pool_id_remote(tenant), tenant_id_for(tenant)
        if self.comm.cred_take(dst, pool_id, tid, n):
            return
        from ..dsl.dtd import AdmissionBackpressure
        if nowait:
            FAB_STATS["remote_rejects"] += 1
            raise AdmissionBackpressure(
                f"rank {dst} admission window exhausted for tenant "
                f"{tenant!r} (no remote credits; retry after the target "
                f"retires work)")
        FAB_STATS["remote_stalls"] += 1
        deadline = time.monotonic() + (
            timeout if timeout is not None
            else mca.get("fab_acquire_timeout", 30.0))
        while not self.comm.cred_take(dst, pool_id, tid, n):
            if dst in self._dead or self._peer_broken(dst):
                self.reclaim_peer(dst)
                raise RuntimeError(
                    f"rank {dst} died while tenant {tenant!r} waited for "
                    f"admission credits (balance reclaimed)")
            if time.monotonic() >= deadline:
                raise TimeoutError(
                    f"no admission credits from rank {dst} for tenant "
                    f"{tenant!r} within the acquire timeout")
            if self._thread is None:
                self.step()        # harness mode: self-driven progress
            time.sleep(2e-4)

    def release(self, dst: int, tenant: str, n: int) -> int:
        """Hand unspent credits back to the granting rank."""
        return self.comm.cred_return(
            dst, self._pool_id_remote(tenant), tenant_id_for(tenant), n)

    def announce_endpoint(self, endpoint: str) -> None:
        """Tell every peer where this rank's /metrics endpoint lives (so
        the rank-0 reconciler can scrape the mesh without config)."""
        self.endpoints[self.my_rank] = endpoint
        if self.rde is None:
            return
        from ..comm.engine import TAG_PTFAB
        for r in self._peers():
            try:
                self.rde.ce.send_am(TAG_PTFAB, r,
                                    {"k": "endpoint", "ep": endpoint,
                                     "rank": self.my_rank}, None)
            except Exception:  # noqa: BLE001 — peer gone; reclaim later
                pass

    def send_insert(self, dst: int, tenant: str, payload) -> None:
        """Ship one acquired insert to ``dst`` over the CE AM plane (the
        gateway data path; the credit was spent in :meth:`acquire`)."""
        hdr = {"k": "insert", "t": tenant}
        if self.insert_transport is not None:
            self.insert_transport(dst, hdr, payload)
        elif self.rde is not None:
            from ..comm.engine import TAG_PTFAB
            self.rde.ce.send_am(TAG_PTFAB, dst, hdr, payload)
        else:
            raise RuntimeError("send_insert needs a distributed context "
                               "or an insert_transport")
        FAB_STATS["remote_inserts_tx"] += 1

    # ----------------------------------------------------- target side
    def on_fab(self, src: int, hdr: Dict, payload) -> None:
        """TAG_PTFAB dispatch (comm-thread context: park, don't work)."""
        k = hdr.get("k")
        if k == "insert":
            self._inbox.append((src, hdr, payload))
        elif k == "endpoint":
            self.endpoints[hdr.get("rank", src)] = hdr.get("ep")
        elif k == "weights":
            # reconciliation nudge from rank 0: apply to local DRR
            for name, w in (hdr.get("w") or {}).items():
                self.set_weight(name, w)
            err = hdr.get("err")
            if err is not None:
                FAB_STATS["share_err_pct"] = err
        else:
            output.warning(f"ptfab: unknown control kind {k!r} from "
                           f"rank {src}")

    def _drain_inbox(self) -> int:
        n = 0
        while self._inbox:
            try:
                src, hdr, payload = self._inbox.popleft()
            except IndexError:
                break
            with self._lock:
                t = self._tenants.get(hdr["t"])
            if t is None:
                # still consume the spent credit from the outstanding
                # ledger (the ids are pure functions of the name): a
                # dropped insert must not deflate the peer's credit
                # line forever
                try:
                    self.comm.cred_consume(src, self._pool_id(hdr["t"]),
                                           tenant_id_for(hdr["t"]), 1)
                except Exception:  # noqa: BLE001 — bad src rides along
                    pass
                output.warning(
                    f"ptfab: insert for unserved tenant {hdr['t']!r}")
                continue
            # the spent credit converts: outstanding ledger shrinks, the
            # window reservation becomes either real inflight (owned
            # handle) or the pool's own admit-at-insert (taskpool-backed)
            self.comm.cred_consume(src, t.pool_id, t.tid, 1)
            if self.plane is not None and t.handle >= 0:
                self.plane.remote_release(t.handle, 1)
                if t.owns_handle:
                    self.plane.admit(t.handle, 1)
            FAB_STATS["remote_inserts_rx"] += 1
            if t.handler is not None:
                t.handler(payload, src)
            n += 1
        return n

    # ------------------------------------------------- replenish loop
    def _peers(self) -> List[int]:
        return [r for r in range(self.nb_ranks)
                if r != self.my_rank and r not in self._dead]

    def _credit_line(self, t: _Tenant, npeers: int) -> int:
        if t.credit_line > 0:
            return t.credit_line
        if self.plane is not None and t.handle >= 0:
            win = self.plane.pool_stats(t.handle).get("window", 0)
            if win > 0:
                return max(1, int(win) // max(1, 2 * npeers))
        return 64

    def _replenish(self) -> int:
        """One grant round: top each (tenant, peer) spendable balance
        back up toward its credit line, bounded by the pool's live
        headroom — the retire counters ARE the replenishment signal
        (retires shrink inflight, headroom reopens, grants flow)."""
        granted = 0
        peers = self._peers()
        if not peers:
            return 0
        with self._lock:
            tenants = list(self._tenants.values())
        for t in tenants:
            line = self._credit_line(t, len(peers))
            hr = -1
            if self.plane is not None and t.handle >= 0:
                hr = self.plane.headroom(t.handle)
            for r in peers:
                out = self.comm.cred_outstanding(r, t.pool_id, t.tid)
                want = line - out
                if want <= 0:
                    continue
                if hr >= 0:
                    if hr <= 0:
                        break          # window exhausted: later peers
                                       # wait for retires too
                    want = min(want, hr)
                    hr -= want
                if self.plane is not None and t.handle >= 0:
                    self.plane.remote_grant(t.handle, want)
                try:
                    self.comm.cred_grant(r, t.pool_id, t.tid, want)
                except Exception:  # noqa: BLE001 — peer gone mid-round
                    if self.plane is not None and t.handle >= 0:
                        self.plane.remote_release(t.handle, want)
                    continue
                granted += want
        return granted

    def _peer_broken(self, rank: int) -> bool:
        try:
            return rank in self.comm_stats().get("broken_peers", ())
        except Exception:  # noqa: BLE001
            return False

    def _check_dead(self) -> None:
        broken = set()
        try:
            broken |= set(self.comm_stats().get("broken_peers", ()))
        except Exception:  # noqa: BLE001
            pass
        if self.rde is not None:
            broken |= set(getattr(self.rde.ce, "dead_peers", ()) or ())
        for r in broken - self._dead:
            self.reclaim_peer(r)

    def reclaim_peer(self, rank: int) -> int:
        """Peer-death containment: zero both credit ledgers for ``rank``
        and RELEASE the matching window reservations, so the dead
        inserter's unspent grants cannot leak admission room forever.
        Idempotent; returns the outstanding credits reclaimed."""
        if rank in self._dead:
            return 0
        self._dead.add(rank)
        reclaimed, _dropped = self.comm.cred_reclaim(rank)
        total = 0
        for pool_id, tid, n in reclaimed:
            t = self._by_key.get((pool_id, tid))
            if t is not None and self.plane is not None and t.handle >= 0:
                self.plane.remote_release(t.handle, n)
            total += n
        if total or _dropped:
            FAB_STATS["peer_reclaims"] += 1
            output.debug_verbose(1, "ptfab",
                                 f"rank {rank} reclaimed: {total} "
                                 f"outstanding, {_dropped} unspendable")
        return total

    def step(self) -> int:
        """One fabric round (containment -> inbox -> flush -> grants).
        The replenish thread calls this on its cadence; harness-mode
        tests and single-threaded drivers call it directly."""
        if not self._up:
            return 0
        self._check_dead()
        n = self._drain_inbox()
        self._flush_tenants()
        n += self._replenish()
        return n

    def _flush_tenants(self) -> None:
        """Flush served taskpools' insert buffers on the fabric cadence:
        a batch-lane pool only flushes at its threshold or when a
        progress loop STARVES, and a serving drain under sustained load
        never starves — a low-rate tenant's gateway inserts would sit
        buffered (invisible to the drain) behind a busy antagonist.
        Bounded staleness (the replenish period) instead."""
        with self._lock:
            pools = [t.taskpool for t in self._tenants.values()
                     if t.taskpool is not None]
        for tp in pools:
            try:
                flush = getattr(tp, "_flush_ready", None)
                if flush is not None:
                    flush()
            except Exception:  # noqa: BLE001 — a closing pool
                pass

    def _loop(self) -> None:
        period = max(0.5e-3, mca.get("fab_replenish_ms", 5.0) / 1e3)
        while not self._stop.wait(period):
            try:
                self.step()
            except Exception as e:  # noqa: BLE001 — the loop must survive
                output.debug_verbose(1, "ptfab", f"replenish round: {e}")

    # ----------------------------------------------------------- stats
    def comm_stats(self) -> Dict[str, Any]:
        if self.lane is not None:
            return self.lane.stats_cached()
        return self.comm.stats()

    def stats_brief(self) -> Dict[str, Any]:
        s = self.comm_stats()
        return {k: s.get(k, 0) for k in
                ("creds_granted_tx", "creds_granted_rx", "creds_spent",
                 "creds_returned_tx", "creds_reclaimed", "frame_errors")}

    # ------------------------------------------------------------- fini
    def fini(self) -> None:
        if not self._up:
            return
        self._up = False
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None
        with self._lock:
            tenants = list(self._tenants.values())
            self._tenants.clear()
            self._by_key.clear()
        if self.plane is not None:
            for t in tenants:
                if t.owns_handle:
                    self.plane.unregister_pool(t.handle)
        if self.rde is not None and getattr(self.rde, "fabric", None) is self:
            self.rde.fabric = None
