"""2-rank serving-fabric harness programs (ISSUE 11).

Shared by ``tests/test_ptfab.py``, ``benchmarks/serving.py --fab-gate``
and the ``serving_*_2rank`` bench keys, so the acceptance scenario —
credits on the wire, an antagonist tenant flooding every rank while a
victim tenant's p99 holds, cross-rank shares reconciled to global
weights — is measured by ONE program however it is launched. Lives in
the package (not the test/bench file) because multiprocessing spawn
must re-import the program by module path.

Topology per rank process: a **distributed control context** (the CE
mesh + native comm lane + TAG_PTFAB plane — what the fabric's credits
and control AMs ride) and a **local serving context** (single-rank,
2 workers) hosting one plane-bound DTD taskpool per tenant — the
serving-tier shape where each rank runs its own pool instances and the
GATEWAY, not a distributed task graph, spreads the requests.

Latency is measured on the SERVING rank per tenant: the ingest handler
stamps arrival, the (single, batch-lane-eligible) body fn pops the
stamp — queue wait + execution under the local plane's arbitration,
which is exactly what tenant isolation protects. Stamps and bodies
share one process, so the clock is coherent.
"""

from __future__ import annotations

import time
from collections import deque
from typing import Dict, List, Optional

import numpy as np


def _force_cpu() -> None:
    import jax
    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:  # noqa: BLE001
        pass


class _TenantHost:
    """One served tenant on one rank: pool, stamps, latencies.

    ``work`` is elements dotted per body, burned as repeated
    ``np.dot`` passes over a 500k-element array (~20us per pass, GIL
    RELEASED during the BLAS loop) — bodies stay honest wall-clock
    under thread contention instead of measuring GIL queueing."""

    def __init__(self, ctx, name: str, window: int, work: int,
                 weight: int = 1) -> None:
        from ..dsl.dtd import READ, DTDTaskpool
        self.name = name
        self.READ = READ
        self.tp = DTDTaskpool(ctx, f"srv-{name}")
        self.tp.admission_window = window
        self.tp.qos_weight = weight
        self.tiles = [self.tp.tile_new((2, 2)) for _ in range(8)]
        self.stamps: "deque[int]" = deque()
        self.lats_ns: List[int] = []
        self.inserted = 0
        self.sheds = 0
        burn = np.arange(500_000.0)
        reps = max(1, int(work) // 500_000)
        stamps, lats = self.stamps, self.lats_ns

        def body(x, _b=burn, _r=reps, _s=stamps, _l=lats):
            try:
                t0 = _s.popleft()
            except IndexError:
                t0 = None
            acc = 0.0
            for _ in range(_r):
                acc += float(np.dot(_b, _b))
            if t0 is not None:
                _l.append(time.perf_counter_ns() - t0)
            return None

        self.body = body
        # warm-up insert: arms the batch lane + plane registration so
        # tp._sched_pool exists before fabric.serve reads it
        self.tp.insert_task(body, (self.tiles[0], READ), jit=False,
                            name=name)
        self.tp.wait(timeout=60)

    def ingest(self, payload, src) -> None:
        # nowait at the handler: the credit pre-gated this arrival, so an
        # overshoot is only an inbox-race transient — shed it (counted)
        # rather than block the fabric thread behind a full window
        from ..dsl.dtd import AdmissionBackpressure
        try:
            self.tp.insert_task(self.body,
                                (self.tiles[self.inserted % 8], self.READ),
                                jit=False, name=self.name, nowait=True)
        except AdmissionBackpressure:
            self.sheds += 1
            return
        self.stamps.append(time.perf_counter_ns())
        self.inserted += 1

    def served(self, plane) -> int:
        h = self.tp._sched_pool
        return 0 if h is None else plane.pool_stats(h)["served"]


def _p99_us(lats_ns: List[int]) -> Optional[float]:
    if not lats_ns:
        return None
    return round(float(np.percentile(np.asarray(lats_ns), 99)) / 1e3, 1)


def fabric_2rank_program(rank, ce, *, isolation_s: float = 2.0,
                         loaded_s: float = 2.5, shares_s: float = 3.0,
                         window_victim: int = 64, window_ant: int = 8,
                         victim_hz: float = 35.0,
                         work_victim: int = 75_000_000,
                         work_ant: int = 500_000, victim_weight: int = 4,
                         window_shares: int = 1024,
                         work_shares: int = 25_000_000,
                         global_weights=(2.0, 1.0),
                         run_shares: bool = True) -> Dict:
    """The acceptance program. Tenants: ``tv`` (victim) and ``ta``
    (antagonist). Phase 1: victim probes alone (baseline p99). Phase 2:
    the antagonist floods EVERY rank through the gateway while the
    victim keeps its fixed rate (loaded p99 + backpressure evidence).
    Phase 3 (optional): both tenants flood while the rank-0 reconciler
    converges cross-rank shares to the global weights.

    Tuning contract (isolation): the victim body (~3 ms of GIL-released
    BLAS) DOMINATES the worst-case antagonist burst ahead of it. The
    burst bound is NOT just window_ant: nowait admission reads plane
    inflight, which updates at batch FLUSH, so up to flush_n
    (= dtd_window_size // 2) specs ride ahead of the window check — the
    harness pins dtd_window_size to 64 (flush_n 32), giving a worst-case
    burst of ~(32 + 8) x 20 us << the victim body. The antagonist still
    saturates (tiny window, arrival > service), so rejects flow.

    Tuning contract (shares): DRR weights bind only on pools whose
    backlog exceeds BOTH weight x quantum and the drain's pop cap
    (Context._dtd_drain pops 256 — a smaller backlog is simply drained
    whole, making served track ARRIVAL). Phase 3 therefore floods two
    DEDICATED tenants with ~1 ms equal-cost bodies behind big windows
    (1024), pinned dtd window_size out of the way, so the plane's
    arbitration — nudged by the reconciler — is what the measured
    shares reflect."""
    import sys
    import threading

    _force_cpu()
    # GIL re-acquire after each GIL-released BLAS pass must not wait a
    # full default 5 ms switch interval behind the flood/control threads
    sys.setswitchinterval(5e-4)
    from ..comm.remote_dep import RemoteDepEngine
    from ..core.context import Context
    from ..serving.fabric import FAB_STATS, ServingFabric
    from ..serving.gateway import IngestGateway
    from ..serving.reconcile import ShareReconciler
    from ..tools.metrics_server import MetricsServer
    from ..utils import mca

    # small flush threshold (see the tuning contract above), a small DRR
    # quantum (weights must bind on window-bounded backlogs), and the
    # DEDICATED rde progress thread (this context has no workers to
    # drive TAG_PTFAB AM delivery implicitly)
    mca.set("dtd_window_size", 64)
    mca.set("sched_quantum", 4)
    mca.set("comm_thread", True)
    nb_ranks = ce.nb_ranks
    ctx_d = Context(nb_cores=1, my_rank=rank, nb_ranks=nb_ranks)
    rde = RemoteDepEngine(ctx_d, ce)
    lane = rde.native
    if lane is None:
        ce.sync()
        ctx_d.fini()
        ce.fini()
        return {"fabric": False, "reason": "native comm lane down"}
    # start the CONTROL context now: comm.enable() spawns the rde
    # progress thread, which is what delivers TAG_PTFAB AMs (no
    # distributed taskpool ever registers here to do it implicitly)
    ctx_d.start()
    # nb_cores=2 = ONE background worker thread (streams[0] is the
    # master, driven only inside wait): the single-worker drain keeps
    # the DRR arbitration model exact on a 2-core CI host
    ctx_l = Context(nb_cores=2)
    plane = ctx_l.sched_plane
    if plane is None:
        ce.sync()
        ctx_l.fini()
        ctx_d.fini()
        ce.fini()
        return {"fabric": False, "reason": "scheduler plane down"}

    fab_before = FAB_STATS.snapshot()
    fab = ServingFabric(lane.comm, plane, rank, nb_ranks, rde=rde,
                        lane=lane)
    tv = _TenantHost(ctx_l, "tv", window_victim, work_victim,
                     weight=victim_weight)
    ta = _TenantHost(ctx_l, "ta", window_ant, work_ant)
    fab.serve("tv", handler=tv.ingest, taskpool=tv.tp)
    fab.serve("ta", handler=ta.ingest, taskpool=ta.tp)
    ctx_l.start()                      # the serving worker drains from here
    ms = MetricsServer(rank=rank, nb_ranks=nb_ranks, port=0).start()
    fab.announce_endpoint(ms.endpoint)
    gw = IngestGateway(fab)
    ce.sync()
    # one replenish round has certainly run by now (5 ms cadence); the
    # first submits may still stall briefly until grants land — counted

    out: Dict = {"fabric": True, "rank": rank}

    # ---- phase 1: victim alone --------------------------------------
    def victim_probe(seconds: float) -> int:
        n, t_end = 0, time.monotonic() + seconds
        period = 1.0 / victim_hz
        nxt = time.monotonic()
        while time.monotonic() < t_end:
            gw.submit("tv", {"n": n})
            n += 1
            nxt += period
            time.sleep(max(0.0, nxt - time.monotonic()))
        return n

    # BOTH ranks probe: twice the p99 samples, and rank asymmetry (the
    # probe thread's own CPU cost) averages out of the merged bound
    out["victim_probes_base"] = victim_probe(isolation_s)
    ce.sync()
    # settle: let queued victim tasks finish before snapshotting
    tv.tp.wait(timeout=60)
    base_lats = list(tv.lats_ns)
    tv.lats_ns.clear()
    out["victim_p99_us_unloaded"] = _p99_us(base_lats)
    out["victim_lats_base_ns"] = base_lats
    ce.sync()

    # ---- phase 2: antagonist floods every rank ----------------------
    stop = threading.Event()
    rejects = [0]

    def antagonist() -> None:
        from ..dsl.dtd import AdmissionBackpressure
        n = 0
        while not stop.is_set():
            try:
                gw.submit("ta", {"n": n}, nowait=True)
                n += 1
            except AdmissionBackpressure:
                rejects[0] += 1
                time.sleep(2e-4)
            except (RuntimeError, TimeoutError):
                break

    flood = threading.Thread(target=antagonist, daemon=True,
                             name="ptfab-antagonist")
    flood.start()
    out["victim_probes_load"] = victim_probe(loaded_s)
    stop.set()
    flood.join(timeout=10)
    ce.sync()
    tv.tp.wait(timeout=120)
    load_lats = list(tv.lats_ns)
    tv.lats_ns.clear()
    out["victim_p99_us_loaded"] = _p99_us(load_lats)
    out["victim_lats_load_ns"] = load_lats
    out["antagonist_rejects"] = rejects[0]
    out["antagonist_served"] = ta.served(plane)
    ce.sync()

    # ---- phase 3: share reconciliation under dual flood -------------
    # dedicated tenants (see the shares tuning contract): equal ~1 ms
    # bodies, big windows so the backlog exceeds the drain's pop cap and
    # the plane's (reconciler-nudged) arbitration is what shares measure
    hosts = {"tv": tv, "ta": ta}
    if run_shares:
        sv = _TenantHost(ctx_l, "sv", window_shares, work_shares)
        sa = _TenantHost(ctx_l, "sa", window_shares, work_shares)
        for h in (sv, sa):
            h.tp.window_size = 1 << 20     # the dtd inserter-drain stall
                                           # must not cap the backlog
            fab.serve(h.name, handler=h.ingest, taskpool=h.tp)
        hosts.update({"sv": sv, "sa": sa})
        ce.sync()
        rec = None
        if rank == 0:
            deadline = time.monotonic() + 15
            while len(fab.endpoints) < nb_ranks and \
                    time.monotonic() < deadline:
                time.sleep(5e-3)
            eps = [fab.endpoints[r] for r in sorted(fab.endpoints)]
            rec = ShareReconciler(
                fab, eps, {"sv": global_weights[0],
                           "sa": global_weights[1]},
                period=0.25, gain=0.6, scale=4).start()
        stop2 = threading.Event()

        def flood_tenant(name: str) -> None:
            from ..dsl.dtd import AdmissionBackpressure
            n = 0
            while not stop2.is_set():
                try:
                    gw.submit(name, {"n": n}, nowait=True)
                    n += 1
                except AdmissionBackpressure:
                    time.sleep(2e-4)
                except (RuntimeError, TimeoutError):
                    break

        floods = [threading.Thread(target=flood_tenant, args=(t,),
                                   daemon=True) for t in ("sv", "sa")]
        for th in floods:
            th.start()
        # measurement window = the SECOND half, after the reconciler has
        # had rounds to bite; synchronized by ce.sync on both edges
        time.sleep(shares_s / 2)
        ce.sync()
        mid = {"sv": sv.served(plane), "sa": sa.served(plane)}
        time.sleep(shares_s / 2)
        ce.sync()
        end = {"sv": sv.served(plane), "sa": sa.served(plane)}
        stop2.set()
        for th in floods:
            th.join(timeout=10)
        if rec is not None:
            rec.stop()
            out["reconcile_rounds"] = rec.rounds
            out["share_err_pct_last"] = rec.last_err_pct
        out["shares_window"] = {t: end[t] - mid[t] for t in end}
        out["weight_adjusts"] = plane.stats().get("weight_adjusts", 0)
        out["weights_now"] = {
            h.name: plane.pool_stats(h.tp._sched_pool)["weight"]
            if h.tp._sched_pool is not None else None
            for h in (sv, sa)}
        ce.sync()

    # ---- teardown + evidence ----------------------------------------
    # the fabric stops FIRST (after the sync above proved every rank
    # quit producing): a straggler gateway insert delivered after
    # tp.close() would be an insert into a closed pool
    fab.fini()
    for host in hosts.values():
        host.tp.wait(timeout=120)
        host.tp.close()
    ctx_l.wait(timeout=120)
    s = lane.comm.stats()
    out["wire"] = {k: s[k] for k in
                   ("creds_granted_tx", "creds_granted_rx", "creds_spent",
                    "creds_returned_tx", "creds_reclaimed",
                    "cred_frames_tx", "cred_frames_rx", "frame_errors",
                    "acts_tx", "acts_rx")}
    out["fab_stats"] = FAB_STATS.delta(fab_before)
    out["routed"] = dict(gw.routed)
    out["sheds"] = {h.name: h.sheds for h in hosts.values()}
    out["ingested"] = {h.name: h.inserted for h in hosts.values()}
    out["wall_s"] = round(isolation_s + loaded_s +
                          (shares_s if run_shares else 0.0), 2)
    ce.sync()
    ms.stop()
    ctx_l.fini()
    ctx_d.fini()
    ce.fini()
    return out


def pttel_2rank_program(rank, ce, *, load_s: float = 1.2,
                        tel_interval_ms: int = 25,
                        watchdog_stall_ms: int = 500,
                        stall: bool = False,
                        flight_dir: str = "") -> Dict:
    """The ISSUE 20 acceptance program (2 OS ranks): the pttel push
    plane under real serving load.

    Both ranks serve two tenants and feed them through the gateway for
    ``load_s``; the telemetry plane pushes counter deltas up the tree
    the whole time. After quiescing, rank 0 waits for the pushed rollup
    to settle and reports BOTH views of every ``ptfab.served.*``
    counter — the rolled-up value and each rank's own registry value
    travels back in the per-rank results — so the driver can assert the
    tree-aggregated numbers equal the per-rank truth exactly. Rank 0
    also runs push-mode reconciler rounds and reports the
    ``reconcile.*`` deltas (the zero-HTTP-fetch contract).

    With ``stall=True`` rank 1 injects a never-drained KIND_EXT plane
    pool under its (already armed) watchdog and waits for detection:
    exactly one attributed flight record must land in ``flight_dir``.
    Rank 0's watchdog runs the whole time WITHOUT an injected stall —
    its clean ``watchdog.*`` counters are the zero-false-positive
    evidence under real load."""
    import glob
    import threading

    _force_cpu()
    from ..comm.remote_dep import RemoteDepEngine
    from ..comm.pttel import TEL_STATS
    from ..core.context import Context
    from ..core.watchdog import WATCHDOG_STATS
    from ..serving.fabric import ServingFabric
    from ..serving.gateway import IngestGateway
    from ..serving.reconcile import RECONCILE_STATS, ShareReconciler
    from ..tools.metrics_server import MetricsServer
    from ..utils import mca
    from ..utils.counters import counters

    mca.set("dtd_window_size", 64)
    mca.set("sched_quantum", 4)
    mca.set("comm_thread", True)
    mca.set("tel_interval_ms", tel_interval_ms)
    mca.set("tel_fanout", 2)
    mca.set("watchdog_stall_ms", watchdog_stall_ms)
    if flight_dir:
        mca.set("flight_dir", flight_dir)
    nb_ranks = ce.nb_ranks
    ctx_d = Context(nb_cores=1, my_rank=rank, nb_ranks=nb_ranks)
    rde = RemoteDepEngine(ctx_d, ce)
    lane = rde.native
    tel = rde.telemetry
    if lane is None or tel is None:
        ce.sync()
        ctx_d.fini()
        ce.fini()
        return {"telemetry": False,
                "reason": "native comm lane down" if lane is None
                else "telemetry plane not built"}
    ctx_d.start()                       # rde progress + telemetry pusher up
    ctx_l = Context(nb_cores=2)         # watchdog arms here (mca above)
    plane = ctx_l.sched_plane
    if plane is None:
        ce.sync()
        ctx_l.fini()
        ctx_d.fini()
        ce.fini()
        return {"telemetry": False, "reason": "scheduler plane down"}
    fab = ServingFabric(lane.comm, plane, rank, nb_ranks, rde=rde,
                        lane=lane)
    tv = _TenantHost(ctx_l, "tv", 256, 1_000_000, weight=2)
    ta = _TenantHost(ctx_l, "ta", 256, 500_000)
    fab.serve("tv", handler=tv.ingest, taskpool=tv.tp)
    fab.serve("ta", handler=ta.ingest, taskpool=ta.tp)
    ctx_l.start()
    ms = MetricsServer(rank=rank, nb_ranks=nb_ranks, port=0).start()
    fab.announce_endpoint(ms.endpoint)
    gw = IngestGateway(fab)
    rec_before = RECONCILE_STATS.snapshot()
    ce.sync()

    out: Dict = {"telemetry": True, "rank": rank}

    # ---- load phase: both tenants, modest rate, every rank ----------
    t_end = time.monotonic() + load_s
    n = 0
    from ..dsl.dtd import AdmissionBackpressure
    while time.monotonic() < t_end:
        for t in ("tv", "ta"):
            try:
                gw.submit(t, {"n": n}, nowait=True)
            except AdmissionBackpressure:
                pass
            except (RuntimeError, TimeoutError):
                break
        n += 1
        time.sleep(2e-3)
    ce.sync()
    for host in (tv, ta):
        host.tp.wait(timeout=120)

    # ---- push-mode reconciler rounds (rank 0) -----------------------
    # the serve counters are frozen now (load done), so the interesting
    # assertions are mechanical: rounds ran off the pushed rollup with
    # ZERO per-round HTTP fetches
    if rank == 0:
        deadline = time.monotonic() + 15
        while len(fab.endpoints) < nb_ranks and \
                time.monotonic() < deadline:
            time.sleep(5e-3)
        eps = [fab.endpoints[r] for r in sorted(fab.endpoints)]
        rec = ShareReconciler(fab, eps, {"tv": 2.0, "ta": 1.0},
                              period=0.05, tel="auto")
        for _ in range(6):
            rec.step()
            time.sleep(max(0.06, 2 * tel_interval_ms / 1e3))
        out["reconcile"] = RECONCILE_STATS.delta(rec_before)
        out["reconcile_mode"] = rec.last_mode
    ce.sync()

    # ---- quiesced rollup-vs-truth comparison ------------------------
    served_local = {k: v for k, v in counters.snapshot().items()
                    if k.startswith("ptfab.served.")}
    out["served_local"] = served_local
    tel.flush()
    if rank == 0:
        # the background pusher keeps folding; wait for the rolled-up
        # ptfab.served.* columns to settle (all ranks quiesced above)
        def served_view():
            roll = tel.rollup()
            return {r: {k: v for k, v in ent["counters"].items()
                        if k.startswith("ptfab.served.")}
                    for r, ent in roll["ranks"].items()}
        deadline = time.monotonic() + 15
        prev = None
        while time.monotonic() < deadline:
            cur = served_view()
            if len(cur) == nb_ranks and cur == prev:
                break
            prev = cur
            time.sleep(max(0.1, 3 * tel_interval_ms / 1e3))
        roll = tel.rollup()
        out["rollup_served"] = {k: v for k, v in roll["rollup"].items()
                                if k.startswith("ptfab.served.")}
        out["per_rank_served"] = served_view()
        out["staleness_s"] = {r: ent["staleness_s"]
                              for r, ent in roll["ranks"].items()}
        out["ranks_seen"] = sorted(roll["ranks"])
        out["depth"] = roll["depth"]
    ce.sync()

    # ---- forced stall (rank 1 only, when asked) ---------------------
    wd = ctx_l.watchdog
    out["watchdog_armed"] = wd is not None
    if stall and rank == 1 and wd is not None:
        before = WATCHDOG_STATS.snapshot()
        h = plane.register_pool("stall-inject", plane.KIND_EXT,
                                weight=1, window=0)
        if h >= 0:
            plane.admit(h, 4)           # held work that never drains
        t0 = time.monotonic()
        deadline = t0 + 4 * watchdog_stall_ms / 1e3
        while WATCHDOG_STATS["pool_stalls"] <= before["pool_stalls"] \
                and time.monotonic() < deadline:
            time.sleep(watchdog_stall_ms / 1e3 / 20)
        detected_ms = round((time.monotonic() - t0) * 1e3, 1)
        # the counter ticks BEFORE the watchdog thread finishes writing
        # the dump: give the file its own (bounded) wait
        nrec = 0
        while flight_dir and time.monotonic() < deadline + 2.0:
            nrec = len(glob.glob(f"{flight_dir}/flight-r*-*.json"))
            if nrec:
                break
            time.sleep(0.02)
        out["stall"] = {
            "detected_ms": detected_ms,
            "watchdog": WATCHDOG_STATS.delta(before),
            "flight_records": nrec,
        }
        if h >= 0:
            plane.unregister_pool(h)
    ce.sync()

    # ---- teardown + evidence ----------------------------------------
    fab.fini()
    for host in (tv, ta):
        host.tp.wait(timeout=120)
        host.tp.close()
    ctx_l.wait(timeout=120)
    s = lane.comm.stats()
    out["frame_errors"] = s["frame_errors"]
    out["tel_stats"] = TEL_STATS.snapshot()
    out["watchdog_stats"] = WATCHDOG_STATS.snapshot()
    ce.sync()
    ms.stop()
    ctx_l.fini()
    ctx_d.fini()
    ce.fini()
    return out


def reclaim_2rank_program(rank, ce, *, window: int = 32) -> Dict:
    """Peer-death containment, with REAL processes: rank 0 serves a
    windowed tenant, grants credits, then dies mid-window (hard
    ``os._exit`` from a timer — no BYE, no teardown); rank 1 must
    observe reclaim — spendable balance zeroed, a blocking acquire
    RAISES instead of hanging — with no leaked window. (The satellite's
    2-rank harness; the in-process variant in tests/test_ptfab.py
    covers the target-side ledger release.)"""
    import os
    import threading

    _force_cpu()
    from ..comm.remote_dep import RemoteDepEngine
    from ..core.context import Context
    from ..serving.fabric import ServingFabric, tenant_id_for

    ctx_d = Context(nb_cores=1, my_rank=rank, nb_ranks=ce.nb_ranks)
    rde = RemoteDepEngine(ctx_d, ce)
    lane = rde.native
    if lane is None:
        ce.sync()
        ctx_d.fini()
        ce.fini()
        return {"fabric": False}
    ctx_l = Context(nb_cores=1)
    fab = ServingFabric(lane.comm, ctx_l.sched_plane, rank, ce.nb_ranks,
                        rde=rde, lane=lane)
    if rank == 0:
        # serve + grant, then report the result and die WITHOUT teardown
        # shortly after (the timer fires once the return value is safely
        # on the parent's queue): mid-window death, credits outstanding
        fab.serve("tx", handler=lambda p, s: None, window=window,
                  weight=1)
        ce.sync()                         # rank 1 sees us up
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            s = lane.comm.stats()
            if s["creds_granted_tx"] > 0 and s["out_pending"] == 0:
                break
            time.sleep(2e-3)
        time.sleep(0.2)                   # grants definitely on the wire
        granted = lane.comm.stats()["creds_granted_tx"]
        threading.Timer(0.8, os._exit, args=(0,)).start()
        return {"fabric": True, "role": "target", "granted": granted}
    # rank 1: the inserter
    ce.sync()
    deadline = time.monotonic() + 30
    while fab.avail(0, "tx") <= 0 and time.monotonic() < deadline:
        time.sleep(2e-3)
    avail_before = fab.avail(0, "tx")
    # spend a few locally while the peer is alive or dying — spends
    # against a positive balance never block and never touch the wire
    spent = 0
    while spent < min(4, avail_before) and fab.comm.cred_take(
            0, fab._pool_id("tx"), tenant_id_for("tx"), 1):
        spent += 1
    # a blocking acquire that can NEVER be satisfied must raise once the
    # death is detected (containment), not hang to its timeout
    t0 = time.monotonic()
    try:
        fab.acquire(0, "tx", n=10**6, timeout=60)
        outcome = "acquired"
    except RuntimeError:
        outcome = "raised"
    except TimeoutError:
        outcome = "timeout"
    waited = time.monotonic() - t0
    out = {"fabric": True, "role": "inserter",
           "avail_before": avail_before, "spent": spent,
           "outcome": outcome, "waited_s": round(waited, 2),
           "avail_after": fab.avail(0, "tx"),
           "dead": sorted(fab._dead)}
    fab.fini()
    ctx_l.fini()
    # the dead peer makes polite ctx_d/ce teardown moot; exit directly
    # (daemonized spawn reaps us) after reporting
    return out
