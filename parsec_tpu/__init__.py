"""parsec_tpu — a TPU-native task-based dataflow runtime.

A brand-new framework with the capabilities of PaRSEC (ICLDisco/parsec):
applications are DAGs of micro-tasks with dataflow dependencies, described
either through a compiled Parameterized Task Graph DSL or a dynamic
insert-task interface, executed by a distributed runtime that overlaps
computation with communication and manages versioned data copies across
memory spaces. Task bodies on the compute path are pre-compiled XLA/Pallas
executables dispatched asynchronously through JAX; distribution is expressed
over TPU meshes with XLA collectives on ICI/DCN.

Layer map (mirrors SURVEY.md §1):
  utils/   — config (MCA params), logging, tracing        (ref L0)
  core/    — task model, scheduling, termdet, PINS        (ref L2)
  data/    — data copies/coherency, collections, arenas   (ref L1/L6)
  comm/    — comm engine + remote dependency protocol     (ref L3)
  device/  — device modules incl. the TPU module          (ref L4)
  dsl/     — PTG compiler + DTD insert_task               (ref L5)
  ops/     — Pallas/XLA tile kernels (gemm, potrf, ...)
  parallel/— mesh/SPMD execution paths (shard_map)
  tools/   — trace readers/converters                     (ref L7)
"""

__version__ = "0.5.0"

from .core.context import Context, init, fini
from .core.task import (
    Task, TaskClass, Taskpool, Flow, Dep, Chore,
    HOOK_DONE, HOOK_AGAIN, HOOK_ASYNC, HOOK_NEXT, HOOK_DISABLE, HOOK_ERROR,
    FLOW_ACCESS_READ, FLOW_ACCESS_WRITE, FLOW_ACCESS_RW, FLOW_ACCESS_CTL,
    DEV_CPU, DEV_TPU, DEV_ALL,
)
from .utils import mca

__all__ = [
    "Context", "init", "fini", "Task", "TaskClass", "Taskpool", "Flow", "Dep",
    "Chore", "mca",
    "HOOK_DONE", "HOOK_AGAIN", "HOOK_ASYNC", "HOOK_NEXT", "HOOK_DISABLE",
    "HOOK_ERROR",
    "FLOW_ACCESS_READ", "FLOW_ACCESS_WRITE", "FLOW_ACCESS_RW",
    "FLOW_ACCESS_CTL", "DEV_CPU", "DEV_TPU", "DEV_ALL",
    # lazy (PEP 562) exports below
    "DTDTaskpool", "READ", "WRITE", "RW", "AFFINITY", "compile_ptg",
    "TiledMatrix", "TwoDimBlockCyclic", "NamedDatatype",
    "RemoteDepEngine", "ThreadsCE", "TCPCE", "run_distributed",
    "run_distributed_procs", "init_from_env", "checkpoint",
]

# the rest of the user surface resolves lazily so `import parsec_tpu`
# stays light (DSLs, collections, comm backends pull in their own deps)
_LAZY = {
    "DTDTaskpool": ("parsec_tpu.dsl.dtd", "DTDTaskpool"),
    "READ": ("parsec_tpu.dsl.dtd", "READ"),
    "WRITE": ("parsec_tpu.dsl.dtd", "WRITE"),
    "RW": ("parsec_tpu.dsl.dtd", "RW"),
    "AFFINITY": ("parsec_tpu.dsl.dtd", "AFFINITY"),
    "compile_ptg": ("parsec_tpu.dsl.ptg.compiler", "compile_ptg"),
    "TiledMatrix": ("parsec_tpu.data.matrix", "TiledMatrix"),
    "TwoDimBlockCyclic": ("parsec_tpu.data.matrix", "TwoDimBlockCyclic"),
    "SymTwoDimBlockCyclic": ("parsec_tpu.data.matrix", "SymTwoDimBlockCyclic"),
    "SymTwoDimBlockCyclicBand": ("parsec_tpu.data.matrix", "SymTwoDimBlockCyclicBand"),
    "SBCDistribution": ("parsec_tpu.data.matrix", "SBCDistribution"),
    "VectorTwoDimCyclic": ("parsec_tpu.data.matrix", "VectorTwoDimCyclic"),
    "NamedDatatype": ("parsec_tpu.data.reshape", "NamedDatatype"),
    "RemoteDepEngine": ("parsec_tpu.comm.remote_dep", "RemoteDepEngine"),
    "ThreadsCE": ("parsec_tpu.comm.threads", "ThreadsCE"),
    "TCPCE": ("parsec_tpu.comm.tcp", "TCPCE"),
    "run_distributed": ("parsec_tpu.comm.threads", "run_distributed"),
    "run_distributed_procs": ("parsec_tpu.comm.tcp", "run_distributed_procs"),
    "init_from_env": ("parsec_tpu.comm.tcp", "init_from_env"),
    "checkpoint": ("parsec_tpu.utils.checkpoint", None),
}


def __getattr__(name):
    entry = _LAZY.get(name)
    if entry is None:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    import importlib
    mod = importlib.import_module(entry[0])
    value = mod if entry[1] is None else getattr(mod, entry[1])
    globals()[name] = value
    return value


def __dir__():
    return sorted(set(list(globals()) + list(_LAZY)))
