"""Tiled Cholesky (POTRF) kernels and DAG builder.

The second headline benchmark (BASELINE.md: tiled dPOTRF). Right-looking
tiled Cholesky — the canonical PaRSEC/DPLASMA example (the reference ships it
as dplasma's dpotrf and exercises the same DAG shape in its DTD tests):

    for k in range(T):
        A[k,k] = POTRF(A[k,k])
        for m > k:    A[m,k] = TRSM(A[k,k], A[m,k])
        for m > k:    A[m,m] = SYRK(A[m,k], A[m,m])
        for m > n > k: A[m,n] = GEMM(A[m,k], A[n,k], A[m,n])

Tile bodies are jittable; XLA lowers cholesky/triangular_solve natively on
TPU. The DAG (RAW on panels, WAW on trailing updates) is discovered by the
DTD tile chains, exactly like the insert-task Cholesky of the reference
(BASELINE.json config 3: "DTD Cholesky (dpotrf)").
"""

from __future__ import annotations

import numpy as np

from ..data.matrix import TiledMatrix
from ..dsl.dtd import AFFINITY, DTDTaskpool, READ, RW


def tile_potrf(a):
    """Cholesky of the diagonal tile (lower)."""
    import jax
    import jax.numpy as jnp
    # cholesky's internal dots have no precision arg; scope the default so
    # f32 factorization keeps f32 accuracy on the MXU
    with jax.default_matmul_precision("highest"):
        return jnp.linalg.cholesky(a)


def tile_trsm(akk, amk):
    """A[m,k] <- A[m,k] · L(k,k)^{-T}  (right, lower, transposed)."""
    import jax
    import jax.numpy as jnp
    # solve L X^T = A^T  =>  X = A L^{-T}
    with jax.default_matmul_precision("highest"):
        return jax.scipy.linalg.solve_triangular(akk, amk.T, lower=True).T


def tile_syrk(amk, amm):
    """A[m,m] <- A[m,m] - A[m,k] · A[m,k]^T."""
    import jax.numpy as jnp
    from .pallas_kernels import dot_precision
    return amm - jnp.dot(amk, amk.T, precision=dot_precision(),
                         preferred_element_type=jnp.float32).astype(amm.dtype)


def tile_gemm_update(amk, ank, amn):
    """A[m,n] <- A[m,n] - A[m,k] · A[n,k]^T."""
    import jax.numpy as jnp
    from .pallas_kernels import dot_precision
    return amn - jnp.dot(amk, ank.T, precision=dot_precision(),
                         preferred_element_type=jnp.float32).astype(amn.dtype)


def insert_potrf_tasks(tp: DTDTaskpool, A: TiledMatrix) -> int:
    """Insert the right-looking tiled Cholesky DAG (lower). Returns task count.

    Priorities follow the critical path (panel first), the standard trick the
    reference relies on priority-aware schedulers for.
    """
    T = A.mt
    assert A.mt == A.nt, "POTRF needs a square tile grid"
    n0 = tp.inserted
    for k in range(T):
        prio = (T - k) * 10000
        tp.insert_task(tile_potrf, (tp.tile_of(A, k, k), RW | AFFINITY),
                       priority=prio + 3000, name="POTRF")
        for m in range(k + 1, T):
            tp.insert_task(tile_trsm,
                           (tp.tile_of(A, k, k), READ),
                           (tp.tile_of(A, m, k), RW | AFFINITY),
                           priority=prio + 2000, name="TRSM")
        for m in range(k + 1, T):
            tp.insert_task(tile_syrk,
                           (tp.tile_of(A, m, k), READ),
                           (tp.tile_of(A, m, m), RW | AFFINITY),
                           priority=prio + 1000, name="SYRK")
            for n in range(k + 1, m):
                tp.insert_task(tile_gemm_update,
                               (tp.tile_of(A, m, k), READ),
                               (tp.tile_of(A, n, k), READ),
                               (tp.tile_of(A, m, n), RW | AFFINITY),
                               priority=prio, name="GEMM")
    return tp.inserted - n0


def potrf_flops(N: int) -> float:
    """N^3/3 (+ lower order), the standard dpotrf count."""
    return N ** 3 / 3.0 + N ** 2 / 2.0


def make_spd(n: int, seed: int = 0, dtype=np.float32) -> np.ndarray:
    """A well-conditioned SPD matrix for tests/benchmarks."""
    rng = np.random.default_rng(seed)
    a = rng.standard_normal((n, n)).astype(np.float64) / np.sqrt(n)
    spd = a @ a.T + np.eye(n) * n * 0.05
    return spd.astype(dtype)
