"""Pallas TPU kernels for the hot tile operations.

Where the reference hand-writes CUDA kernels for its GPU task bodies
(tests/runtime/cuda/*.cu), this module supplies Pallas kernels for the TPU
chore path:

* :func:`gemm_chain` — the fused k-chain  C += Σ_k A[k]·B[k]  as ONE kernel:
  the C block stays in VMEM across the whole k grid (the task-batching
  analogue at kernel level), each step is an MXU dot; Pallas double-buffers
  the A/B block streams from HBM automatically.
* :func:`matmul` — classic blocked matmul with a (M/bm, N/bn, K/bk) grid and
  VMEM accumulation, for large single dots.
* :func:`stencil1d` — fused 3-point stencil with halo columns (one VPU pass,
  no intermediate materialization).
* :func:`flash_attention` — blockwise attention with the online-softmax
  accumulation fused into one kernel: scores, running max/sum and the
  weighted-V accumulation never leave VMEM (the HBM-bandwidth win that
  motivates flash attention), grid over (batch·heads, query blocks), k/v
  resident per head. Positional offsets make it usable on rotated ring
  blocks (`parallel/ring_attention.py`) and sequence-sharded shards.

Every entry point degrades gracefully: on non-TPU backends the kernels run
in interpreter mode (tests), and any Pallas failure falls back to the XLA
expression of the same math.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import numpy as np

from ..utils import mca

mca.register("pallas_strict", False,
             "Fail loudly instead of falling back to XLA when a Pallas "
             "kernel cannot lower/run (the CI compile gate)", type=bool)

mca.register("tile_dot_precision", "highest",
             "MXU pass count for float32 tile dots: 'default' (fast bf16 "
             "passes), 'high' (3-pass), 'highest' (6-pass, dgemm-accuracy "
             "f32). bf16 inputs are always single-pass native.", type=str)


def dot_precision():
    """The lax.Precision for f32 tile dots. On TPU the MXU multiplies in
    bf16; 'highest' recovers f32 accuracy via 6 passes — the semantics a
    dgemm-parity runtime must default to. bf16 tiles ignore this (native)."""
    import jax
    name = str(mca.get("tile_dot_precision", "highest")).lower()
    return {"default": jax.lax.Precision.DEFAULT,
            "high": jax.lax.Precision.HIGH,
            "highest": jax.lax.Precision.HIGHEST}.get(
                name, jax.lax.Precision.HIGHEST)


def _backend() -> str:
    import jax
    try:
        return jax.default_backend()
    except Exception:
        return "cpu"


def _interpret() -> bool:
    return _backend() not in ("tpu",)


_warned_fallbacks: set = set()


def _fallback(kernel_name: str, err, reason: str = None) -> None:
    """A Pallas failure must never be invisible: strict mode re-raises
    (the CI compile gate), default mode warns ONCE per kernel before the
    XLA fallback runs. ``err=None`` with a ``reason`` marks a deliberate
    shape-based routing decision (not a failure) — never a strict-mode
    error, but still warned once so the path is visible."""
    from ..utils import mca, output
    if err is None:
        key = f"{kernel_name}:routed"
        if key not in _warned_fallbacks:
            _warned_fallbacks.add(key)
            output.warning(f"pallas kernel {kernel_name!r} routed to XLA: "
                           f"{reason}")
        return
    if mca.get("pallas_strict", False):
        raise RuntimeError(
            f"pallas kernel {kernel_name!r} failed to lower/run "
            f"(pallas_strict=1): {err}") from err
    if kernel_name not in _warned_fallbacks:
        _warned_fallbacks.add(kernel_name)
        output.warning(f"pallas kernel {kernel_name!r} fell back to XLA: "
                       f"{type(err).__name__}: {err}")


def verify_lowering(shapes=((256, 256, 256), ), kt: int = 4) -> dict:
    """Compile-only gate: lower every kernel for the CURRENT backend (real
    Mosaic lowering on TPU, interpreter elsewhere) and FAIL LOUDLY on any
    error instead of silently falling back. Returns {kernel: 'ok'|error}.

    Run under pallas_strict in CI / at bench startup so a Mosaic breakage
    on real hardware is a red build, not a quiet perf regression."""
    import jax
    import numpy as np
    results = {}
    interp = _interpret()
    errors = []
    f32 = np.float32
    for m, k, n in shapes:
        checks = {
            f"gemm_chain[{m}x{k}x{n}]": (
                lambda m=m, k=k, n=n: _gemm_chain_call(
                    kt, m, k, n, "float32", interp),
                (jax.ShapeDtypeStruct((m, n), f32),
                 jax.ShapeDtypeStruct((kt, m, k), f32),
                 jax.ShapeDtypeStruct((kt, k, n), f32))),
            f"matmul[{m}x{k}x{n}]": (
                lambda m=m, k=k, n=n: _matmul_call(
                    m, n, k, min(m, 256), min(n, 256), min(k, 256),
                    "float32", interp),
                (jax.ShapeDtypeStruct((m, k), f32),
                 jax.ShapeDtypeStruct((k, n), f32))),
            f"stencil1d[{n}]": (
                lambda n=n: _stencil_call(
                    8, n, (0.25, 0.5, 0.25), "float32", interp),
                (jax.ShapeDtypeStruct((8, n), f32),
                 jax.ShapeDtypeStruct((8, n), f32),
                 jax.ShapeDtypeStruct((8, n), f32))),
            "flash_attention[2x256x128]": (
                lambda: _flash_attn_call(
                    2, 256, 256, 128, 128, 128, True, 0.088388,
                    0, 0, "float32", interp, None),
                (jax.ShapeDtypeStruct((2, 256, 128), f32),
                 jax.ShapeDtypeStruct((2, 256, 128), f32),
                 jax.ShapeDtypeStruct((2, 256, 128), f32))),
        }
        for name, (build, args) in checks.items():
            try:
                # lower+compile without executing (the compile-only part)
                jax.jit(build()).lower(*args).compile()
                results[name] = "ok"
            except Exception as e:  # noqa: BLE001 - collected and re-raised
                results[name] = f"{type(e).__name__}: {e}"
                errors.append(name)
    if errors:
        raise RuntimeError(f"pallas lowering FAILED for {errors}: "
                           f"{ {k: results[k] for k in errors} }")
    return results


# ---------------------------------------------------------------------------
# fused GEMM k-chain
# ---------------------------------------------------------------------------

@functools.lru_cache(maxsize=None)
def _gemm_chain_call(kt: int, ts_m: int, ts_k: int, ts_n: int, dtype: str,
                     interpret: bool, prec=None):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    def kernel(c_ref, a_ref, b_ref, out_ref):
        k = pl.program_id(0)

        @pl.when(k == 0)
        def _():
            out_ref[:] = c_ref[:]

        out_ref[:] += jnp.dot(a_ref[0], b_ref[0], precision=prec,
                              preferred_element_type=jnp.float32
                              ).astype(out_ref.dtype)

    call = pl.pallas_call(
        kernel,
        grid=(kt,),
        in_specs=[
            pl.BlockSpec((ts_m, ts_n), lambda k: (0, 0)),          # C
            pl.BlockSpec((1, ts_m, ts_k), lambda k: (k, 0, 0)),    # A[k]
            pl.BlockSpec((1, ts_k, ts_n), lambda k: (k, 0, 0)),    # B[k]
        ],
        out_specs=pl.BlockSpec((ts_m, ts_n), lambda k: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((ts_m, ts_n), dtype),
        interpret=interpret,
    )
    return jax.jit(call)


def gemm_chain(c, a_stack, b_stack):
    """C += sum_k A[k] @ B[k]; one kernel, C resident in VMEM throughout."""
    import jax.numpy as jnp
    kt, ts_m, ts_k = a_stack.shape
    ts_n = b_stack.shape[2]
    try:
        call = _gemm_chain_call(kt, ts_m, ts_k, ts_n, str(c.dtype),
                                _interpret(), dot_precision())
        return call(c, a_stack, b_stack)
    except Exception as e:  # noqa: BLE001
        _fallback("gemm_chain", e)
        # XLA fallback: scan keeps the accumulator in registers too
        import jax

        def step(acc, ab):
            a, b = ab
            return acc + jnp.dot(a, b, precision=dot_precision(),
                                 preferred_element_type=jnp.float32
                                 ).astype(acc.dtype), None

        out, _ = jax.lax.scan(step, c, (a_stack, b_stack))
        return out


# ---------------------------------------------------------------------------
# blocked matmul
# ---------------------------------------------------------------------------

@functools.lru_cache(maxsize=None)
def _matmul_call(m: int, n: int, k: int, bm: int, bn: int, bk: int,
                 dtype: str, interpret: bool, prec=None):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    def kernel(a_ref, b_ref, out_ref):
        kk = pl.program_id(2)

        @pl.when(kk == 0)
        def _():
            out_ref[:] = jnp.zeros_like(out_ref)

        out_ref[:] += jnp.dot(a_ref[:], b_ref[:], precision=prec,
                              preferred_element_type=jnp.float32
                              ).astype(out_ref.dtype)

    call = pl.pallas_call(
        kernel,
        grid=(m // bm, n // bn, k // bk),
        in_specs=[
            pl.BlockSpec((bm, bk), lambda i, j, kk: (i, kk)),
            pl.BlockSpec((bk, bn), lambda i, j, kk: (kk, j)),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j, kk: (i, j)),
        out_shape=jax.ShapeDtypeStruct((m, n), dtype),
        interpret=interpret,
    )
    return jax.jit(call)


def matmul(a, b, block: Tuple[int, int, int] = (256, 256, 256)):
    """Blocked A @ B; falls back to jnp.dot on shape mismatch or error."""
    import jax.numpy as jnp
    m, k = a.shape
    n = b.shape[1]
    bm, bn, bk = (min(block[0], m), min(block[1], n), min(block[2], k))
    if m % bm or n % bn or k % bk:
        return jnp.dot(a, b, precision=dot_precision(),
                       preferred_element_type=jnp.float32).astype(a.dtype)
    try:
        return _matmul_call(m, n, k, bm, bn, bk, str(a.dtype),
                            _interpret(), dot_precision())(a, b)
    except Exception as e:  # noqa: BLE001
        _fallback("matmul", e)
        return jnp.dot(a, b, precision=dot_precision(),
                       preferred_element_type=jnp.float32).astype(a.dtype)


# ---------------------------------------------------------------------------
# fused 1D stencil
# ---------------------------------------------------------------------------

@functools.lru_cache(maxsize=None)
def _stencil_call(rows: int, cols: int, w: Tuple[float, float, float],
                  dtype: str, interpret: bool):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    w0, w1, w2 = w

    def kernel(x_ref, l_ref, r_ref, out_ref):
        x = x_ref[:]
        xm = jnp.concatenate([l_ref[:, -1:], x[:, :-1]], axis=1)
        xp = jnp.concatenate([x[:, 1:], r_ref[:, :1]], axis=1)
        out_ref[:] = (w0 * xm + w1 * x + w2 * xp).astype(out_ref.dtype)

    call = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((rows, cols), dtype),
        interpret=interpret,
    )
    return jax.jit(call)


def stencil1d(x, left, right, weights=(0.25, 0.5, 0.25)):
    """Fused 3-point stencil; ``left``/``right`` are the neighbor tiles
    (pass zero tiles at the domain boundary)."""
    try:
        call = _stencil_call(x.shape[0], x.shape[1], tuple(weights),
                             str(x.dtype), _interpret())
        return call(x, left, right)
    except Exception as e:  # noqa: BLE001
        _fallback("stencil1d", e)
        import jax.numpy as jnp
        w0, w1, w2 = weights
        xm = jnp.concatenate([left[:, -1:], x[:, :-1]], axis=1)
        xp = jnp.concatenate([x[:, 1:], right[:, :1]], axis=1)
        return (w0 * xm + w1 * x + w2 * xp).astype(x.dtype)


def _sds(jax, shape, dtype, vma=None):
    """``ShapeDtypeStruct`` with a version-tolerant ``vma``: newer jax
    types shard_map-varying outputs through the kwarg; older jax has no
    VMA checker at all, so dropping it there is the correct degrade
    (passing even ``vma=None`` raises TypeError on those versions)."""
    if vma:
        try:
            return jax.ShapeDtypeStruct(shape, dtype, vma=set(vma))
        except TypeError:
            pass
    return jax.ShapeDtypeStruct(shape, dtype)


# ---------------------------------------------------------------------------
# flash attention
# ---------------------------------------------------------------------------

@functools.lru_cache(maxsize=None)
def _flash_attn_call(bh: int, sq: int, sk: int, d: int, bq: int, bk: int,
                     causal: bool, scale: float, q_off: int, k_off: int,
                     dtype: str, interpret: bool, vma=None):
    """Grid (bh, sq//bq, sk//bk): k/v STREAM through VMEM one block per
    step (so sequence length is HBM-bounded, not VMEM-bounded) while the
    online-softmax state (running max ``m``, rescaled sum ``l``,
    accumulator ``acc``) lives in VMEM scratch across the k dimension —
    scores and probabilities are never written to HBM.

    ``q_off``/``k_off`` are the GLOBAL positions of row/col 0, so the
    causal mask is correct on sequence shards and rotated ring blocks;
    fully-masked rows produce ZERO output (ring-fold convention).
    ``vma`` types the output as varying over those mesh axes so the kernel
    can sit inside a ``shard_map`` with the VMA checker on."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    nk = sk // bk
    neg = -1e30

    def kernel(q_ref, k_ref, v_ref, out_ref, acc_ref, m_ref, l_ref):
        iq = pl.program_id(1)
        kk = pl.program_id(2)

        @pl.when(kk == 0)
        def _():
            acc_ref[:] = jnp.zeros_like(acc_ref)
            m_ref[:] = jnp.full_like(m_ref, neg)
            l_ref[:] = jnp.zeros_like(l_ref)

        # blocks entirely above the causal diagonal contribute nothing
        intersects = True
        if causal:
            intersects = (k_off + kk * bk) <= (q_off + (iq + 1) * bq - 1)

        @pl.when(intersects)
        def _():
            q = q_ref[0].astype(jnp.float32) * scale      # (bq, d)
            kb = k_ref[0].astype(jnp.float32)             # (bk, d)
            vb = v_ref[0].astype(jnp.float32)
            s = jax.lax.dot_general(q, kb, (((1,), (1,)), ((), ())),
                                    preferred_element_type=jnp.float32)
            if causal:
                q_pos = q_off + iq * bq + jax.lax.broadcasted_iota(
                    jnp.int32, (bq, bk), 0)
                k_pos = k_off + kk * bk + jax.lax.broadcasted_iota(
                    jnp.int32, (bq, bk), 1)
                s = jnp.where(k_pos <= q_pos, s, neg)
            m = jnp.max(m_ref[...], axis=1, keepdims=True)   # lanes equal
            l = jnp.max(l_ref[...], axis=1, keepdims=True)
            m_new = jnp.maximum(m, jnp.max(s, axis=1, keepdims=True))
            p = jnp.exp(s - m_new)
            # a masked score must carry ZERO weight even when the whole
            # row is masked (s == m_new == neg would give p = 1)
            p = jnp.where(s > 0.5 * neg, p, 0.0)
            corr = jnp.exp(m - m_new)
            l = l * corr + jnp.sum(p, axis=1, keepdims=True)
            acc_ref[:] = acc_ref[...] * corr + jax.lax.dot_general(
                p, vb, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
            l_ref[:] = jnp.broadcast_to(l, l_ref.shape)

        @pl.when(kk == nk - 1)
        def _():
            l = jnp.max(l_ref[...], axis=1, keepdims=True)
            out_ref[0] = (acc_ref[...] / jnp.maximum(l, 1e-30)
                          ).astype(out_ref.dtype)

    call = pl.pallas_call(
        kernel,
        grid=(bh, sq // bq, nk),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda b, iq, kk: (b, iq, 0)),
            pl.BlockSpec((1, bk, d), lambda b, iq, kk: (b, kk, 0)),
            pl.BlockSpec((1, bk, d), lambda b, iq, kk: (b, kk, 0)),
        ],
        out_specs=pl.BlockSpec((1, bq, d), lambda b, iq, kk: (b, iq, 0)),
        out_shape=_sds(jax, (bh, sq, d), dtype, vma),
        scratch_shapes=[
            pltpu.VMEM((bq, d), jnp.float32),     # acc
            pltpu.VMEM((bq, 128), jnp.float32),   # running max (lanes equal)
            pltpu.VMEM((bq, 128), jnp.float32),   # running sum (lanes equal)
        ],
        interpret=interpret,
    )
    return jax.jit(call)


def flash_attention(q, k, v, causal: bool = False, scale: float = None,
                    q_offset: int = 0, k_offset: int = 0,
                    block_q: int = 256, block_k: int = 512, vma=None):
    """Fused softmax(q·kᵀ·scale)·v over (..., seq, head_dim) operands.

    Accepts (B, H, S, D) or (BH, S, D); k/v may have a different sequence
    length than q (cross-attention, ring blocks, sequence shards —
    ``q_offset``/``k_offset`` give the global position of element 0 so the
    causal mask stays correct; fully-masked rows return zeros). Inside a
    ``shard_map``, pass ``vma=(axis, ...)`` so the output is typed as
    device-varying. Sequence lengths not divisible by the block sizes
    shrink the blocks to the largest divisor (a caller-shape property,
    handled here — never a silent fallback). The XLA fallback is reserved
    for Pallas LOWERING/runtime failures raised at trace/call time — a
    Mosaic error surfacing later, at an OUTER jit's compile, is out of
    reach by design; :func:`verify_lowering` is the gate for that class."""
    import jax.numpy as jnp
    q4 = q.reshape((-1,) + q.shape[-2:])
    k4 = k.reshape((-1,) + k.shape[-2:])
    v4 = v.reshape((-1,) + v.shape[-2:])
    bhn, sq, d = q4.shape
    sk = k4.shape[1]
    if scale is None:
        scale = 1.0 / float(np.sqrt(d))
    # block sizes must divide the sequence lengths — that is a property of
    # the CALLER's shapes, not a Pallas failure, so resolve it here by
    # shrinking to the largest divisor (never silently fall back over it):
    # an odd length degrades the block size, not the numerics
    def _divisor_block(s: int, b: int) -> int:
        b = min(b, s)
        while s % b:
            b -= 1
        return b

    bq = _divisor_block(sq, block_q)
    bk = _divisor_block(sk, block_k)

    def _dense(q4, k4, v4):
        import jax
        s = jnp.einsum("bqd,bkd->bqk", q4.astype(jnp.float32),
                       k4.astype(jnp.float32),
                       precision=jax.lax.Precision.DEFAULT) * scale
        if causal:
            qp = q_offset + jnp.arange(sq)[:, None]
            kp = k_offset + jnp.arange(sk)[None, :]
            s = jnp.where(kp <= qp, s, -jnp.inf)
        # explicit guarded softmax: fully-masked rows give ZERO output
        # (jax.nn.softmax would return uniform weights there)
        m = jnp.max(s, axis=-1, keepdims=True)
        p = jnp.where(jnp.isfinite(s), jnp.exp(s - jnp.where(
            jnp.isfinite(m), m, 0.0)), 0.0)
        l = jnp.maximum(p.sum(axis=-1, keepdims=True), 1e-30)
        return jnp.einsum("bqk,bkd->bqd", p / l, v4.astype(jnp.float32)
                          ).astype(q.dtype)

    # A prime/odd sequence length degrades the largest divisor toward 1,
    # which is below TPU tile granularity — a severe Pallas perf cliff or a
    # Mosaic trace failure. Below _MIN_BLOCK (unless the block IS the whole
    # sequence), the dense XLA path is the better program: take it
    # deliberately, not via the exception fallback.
    _MIN_BLOCK = 8
    if (bq < _MIN_BLOCK < sq) or (bk < _MIN_BLOCK < sk):
        _fallback("flash_attention", None,
                  reason=f"block degenerated (bq={bq}, bk={bk}) for seq "
                         f"lens ({sq}, {sk}); dense XLA path is faster")
        return _dense(q4, k4, v4).reshape(q.shape)
    try:
        out = _flash_attn_call(bhn, sq, sk, d, bq, bk, bool(causal),
                               float(scale), int(q_offset), int(k_offset),
                               str(q.dtype), _interpret(),
                               tuple(vma) if vma else None)(q4, k4, v4)
    except Exception as e:  # noqa: BLE001
        _fallback("flash_attention", e)
        out = _dense(q4, k4, v4)
    return out.reshape(q.shape)
