"""Tiled-matrix data collections and distribution layouts.

Re-design of parsec/data_dist/matrix: the tiled-matrix descriptor
(parsec_tiled_matrix_t, matrix.h:101-126) and its distributions:

* :class:`TiledMatrix` — base: mb/nb tile sizes, lm/ln global extent,
  submatrix view (i/j/m/n), typed storage.
* :class:`TwoDimBlockCyclic` — the PBLAS 2D block-cyclic layout incl.
  k-cyclicity (ref: two_dim_rectangle_cyclic.c:16-21,109,195-197 closed
  forms; grid helper grid_2Dcyclic.c).
* :class:`SymTwoDimBlockCyclic` — triangular storage variant
  (ref: sym_two_dim_rectangle_cyclic.c).
* :class:`TwoDimBlockCyclicBand` — band-storage variant
  (ref: two_dim_rectangle_cyclic_band.c): band tiles in a cyclic band
  collection, off-band delegated.
* :class:`TabularDistribution` — arbitrary rank table
  (ref: two_dim_tabular.c).

On TPU the rank grid (P×Q) maps onto the ICI mesh axes so that
owner-computes communication between grid neighbors rides ICI links.
Tiles are numpy arrays host-side; device copies are jax arrays managed by the
device layer. mb/nb should be multiples of the MXU tile (128) for peak
efficiency.
"""

from __future__ import annotations

import math
from typing import Any, Callable, Dict, Optional, Tuple

import numpy as np

from .collection import DataCollection
from .data import COHERENCY_OWNED, Data

# matrix storage types (ref: matrix.h enum matrix_type)
MATRIX_FLOAT32 = np.float32
MATRIX_FLOAT64 = np.float64
MATRIX_BFLOAT16 = "bfloat16"


class TiledMatrix(DataCollection):
    """Base tiled matrix (ref: parsec_tiled_matrix_t, matrix.h:101-126)."""

    def __init__(self, name: str, lm: int, ln: int, mb: int, nb: int,
                 i: int = 0, j: int = 0, m: Optional[int] = None,
                 n: Optional[int] = None, dtype=np.float32,
                 nodes: int = 1, myrank: int = 0) -> None:
        super().__init__(name, nodes, myrank)
        self.lm, self.ln = lm, ln          # global extent
        self.mb, self.nb = mb, nb          # tile sizes
        self.i, self.j = i, j              # submatrix origin (elements)
        self.m = m if m is not None else lm
        self.n = n if n is not None else ln
        self.dtype = dtype
        self.lmt = (lm + mb - 1) // mb     # tiles in M
        self.lnt = (ln + nb - 1) // nb     # tiles in N
        self.mt = (self.m + mb - 1) // mb
        self.nt = (self.n + nb - 1) // nb

    def data_key(self, *indices) -> Any:
        m, n = indices
        return m * self.lnt + n

    def key_to_indices(self, key: int) -> Tuple[int, int]:
        return divmod(key, self.lnt)

    def tile_shape(self, m: int, n: int) -> Tuple[int, int]:
        """Edge tiles may be partial (ref: remaining rows/cols in matrix.c)."""
        rows = min(self.mb, self.lm - m * self.mb)
        cols = min(self.nb, self.ln - n * self.nb)
        return rows, cols

    def _create_data(self, key: Any) -> Data:
        m, n = self.key_to_indices(key)
        shape = self.tile_shape(m, n)
        arr = np.zeros(shape, dtype=self.dtype)
        d = Data(key=key, dc=self, shape=shape, dtype=self.dtype)
        d.create_copy(0, arr, COHERENCY_OWNED)
        return d

    # convenience: fill / gather for tests and benchmarks -------------------
    def fill(self, fn: Callable[[int, int], np.ndarray]) -> None:
        """Materialize every local tile via fn(m, n) -> ndarray."""
        for m in range(self.mt):
            for n in range(self.nt):
                if self.rank_of(m, n) != self.myrank:
                    continue
                arr = np.asarray(fn(m, n), dtype=self.dtype)
                d = self.data_of(m, n)
                c = d.get_copy(0)
                if c is None:
                    d.create_copy(0, arr, COHERENCY_OWNED)
                else:
                    c.payload = arr
                d.version += 1
                cc = d.get_copy(0)
                cc.version = d.version

    def to_dense(self) -> np.ndarray:
        """Gather local tiles into a dense array (single-rank testing only)."""
        out = np.zeros((self.lm, self.ln), dtype=self.dtype if self.dtype != MATRIX_BFLOAT16 else np.float32)
        for m in range(self.mt):
            for n in range(self.nt):
                if self.rank_of(m, n) != self.myrank:
                    continue
                c = self.data_of(m, n).newest_copy()
                if c is None:
                    continue
                tile = np.asarray(c.payload)
                r, co = self.tile_shape(m, n)
                out[m * self.mb:m * self.mb + r, n * self.nb:n * self.nb + co] = tile[:r, :co]
        return out


class TwoDimBlockCyclic(TiledMatrix):
    """2D block-cyclic distribution over a P×Q grid with k-cyclicity.

    Closed forms re-derived from the PBLAS definition (the reference
    implements the same math in two_dim_rectangle_cyclic.c:109,195-197):
    tile (m, n) lives on grid row (m // kp) % P, grid col (n // kq) % Q.
    """

    def __init__(self, name: str, lm: int, ln: int, mb: int, nb: int,
                 P: int = 1, Q: Optional[int] = None, kp: int = 1, kq: int = 1,
                 nodes: int = 1, myrank: int = 0, **kw) -> None:
        super().__init__(name, lm, ln, mb, nb, nodes=nodes, myrank=myrank, **kw)
        if Q is None:
            Q = max(1, nodes // P)
        self.P, self.Q = P, Q
        self.kp, self.kq = kp, kq
        assert P * Q <= max(nodes, 1), f"grid {P}x{Q} exceeds {nodes} ranks"

    def grid_of(self, m: int, n: int) -> Tuple[int, int]:
        return (m // self.kp) % self.P, (n // self.kq) % self.Q

    def rank_of(self, *indices) -> int:
        p, q = self.grid_of(*indices)
        return p * self.Q + q

    def rank_of_key(self, key: Any) -> int:
        return self.rank_of(*self.key_to_indices(key))


class SymTwoDimBlockCyclic(TwoDimBlockCyclic):
    """Symmetric (triangular) block-cyclic: only the uplo triangle is stored
    (ref: sym_two_dim_rectangle_cyclic.c)."""

    LOWER, UPPER = 0, 1

    def __init__(self, *args, uplo: int = 0, **kw) -> None:
        super().__init__(*args, **kw)
        self.uplo = uplo

    def in_triangle(self, m: int, n: int) -> bool:
        return (m >= n) if self.uplo == self.LOWER else (m <= n)

    def data_of(self, *indices) -> Data:
        m, n = indices
        if not self.in_triangle(m, n):
            raise KeyError(f"tile ({m},{n}) outside stored {('lower','upper')[self.uplo]} triangle")
        return super().data_of(m, n)


class TwoDimBlockCyclicBand(TiledMatrix):
    """Band distribution: tiles within ``band_size`` of the diagonal live in a
    cyclic band collection; the rest in a regular 2D block-cyclic
    (ref: two_dim_rectangle_cyclic_band.c composition)."""

    def __init__(self, name: str, full: TwoDimBlockCyclic, band_size: int) -> None:
        super().__init__(name, full.lm, full.ln, full.mb, full.nb,
                         dtype=full.dtype, nodes=full.nodes, myrank=full.myrank)
        self.full = full
        self.band_size = band_size

    def in_band(self, m: int, n: int) -> bool:
        return abs(m - n) < self.band_size

    def rank_of(self, *indices) -> int:
        m, n = indices
        if self.in_band(m, n):
            return m % self.nodes  # cyclic along the diagonal
        return self.full.rank_of(m, n)

    def rank_of_key(self, key: Any) -> int:
        return self.rank_of(*self.key_to_indices(key))

    def data_of(self, *indices) -> Data:
        return super().data_of(*indices)


class TabularDistribution(TiledMatrix):
    """Arbitrary (tabular) tile→rank assignment (ref: two_dim_tabular.c)."""

    def __init__(self, name: str, lm: int, ln: int, mb: int, nb: int,
                 table: Optional[Dict[Tuple[int, int], int]] = None,
                 rank_fn: Optional[Callable[[int, int], int]] = None,
                 **kw) -> None:
        super().__init__(name, lm, ln, mb, nb, **kw)
        self.table = table or {}
        self.rank_fn = rank_fn

    def set_rank(self, m: int, n: int, rank: int) -> None:
        self.table[(m, n)] = rank

    def rank_of(self, *indices) -> int:
        m, n = indices
        if (m, n) in self.table:
            return self.table[(m, n)]
        if self.rank_fn is not None:
            return self.rank_fn(m, n)
        return 0

    def rank_of_key(self, key: Any) -> int:
        return self.rank_of(*self.key_to_indices(key))
