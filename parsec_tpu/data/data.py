"""Data and per-device data copies with MOESI-like coherency.

Re-design of parsec/data_internal.h:29-86 + parsec/data.{c,h}. One
:class:`Data` per logical datum (a tile); it owns one :class:`DataCopy` per
device that currently holds a version. Coherency states and version counters
follow the reference:

* ``INVALID``    — copy content is stale
* ``OWNED``      — this device owns the newest version, others may share
* ``EXCLUSIVE``  — only valid copy, writable
* ``SHARED``     — valid read-only replica

On TPU, a device copy's payload is a ``jax.Array`` living in that chip's HBM;
the host copy is a ``numpy.ndarray``. Transfers happen in the device module
(stage_in/stage_out, ref device_gpu.c:1624-1800); this module only tracks
state, versions and reference counts.
"""

from __future__ import annotations

import itertools
import threading
from typing import Any, Dict, Optional

# coherency states (ref: parsec/data.h:28-37)
COHERENCY_INVALID = 0
COHERENCY_OWNED = 1
COHERENCY_EXCLUSIVE = 2
COHERENCY_SHARED = 3

_data_keys = itertools.count()


class DataCopy:
    """One device-resident version of a datum (ref: parsec_data_copy_t)."""

    __slots__ = ("original", "device_index", "payload", "coherency_state",
                 "version", "readers", "refcount", "older", "arena_chunk",
                 "flags")

    def __init__(self, original: "Data", device_index: int, payload: Any = None,
                 state: int = COHERENCY_OWNED) -> None:
        self.original = original
        self.device_index = device_index
        self.payload = payload
        self.coherency_state = state
        self.version = 0
        self.readers = 0
        self.refcount = 1
        self.older = None
        self.arena_chunk = None
        self.flags = 0

    def retain(self) -> "DataCopy":
        self.refcount += 1
        return self

    def release(self) -> None:
        self.refcount -= 1
        if self.refcount <= 0:
            if self.arena_chunk is not None:
                self.arena_chunk.free()
                self.arena_chunk = None
            if self.original is not None:
                self.original._detach(self)
            self.payload = None

    def __repr__(self) -> str:  # pragma: no cover
        return (f"<DataCopy dev={self.device_index} v={self.version} "
                f"state={self.coherency_state}>")


class Data:
    """One logical datum with per-device copies (ref: parsec_data_t)."""

    __slots__ = ("key", "dc", "copies", "owner_device", "preferred_device",
                 "version", "nb_references", "shape", "dtype", "_lock")

    def __init__(self, key: Any = None, dc: Any = None, shape=None, dtype=None) -> None:
        self.key = key if key is not None else next(_data_keys)
        self.dc = dc                      # owning data collection, if any
        self.copies: Dict[int, DataCopy] = {}
        self.owner_device = 0
        self.preferred_device = -1
        self.version = 0
        self.nb_references = 0
        self.shape = shape
        self.dtype = dtype
        self._lock = threading.Lock()

    # -- copy management (ref: parsec_data_copy_attach/detach, data.c) --------
    def attach_copy(self, copy: DataCopy, device_index: Optional[int] = None) -> DataCopy:
        with self._lock:
            idx = device_index if device_index is not None else copy.device_index
            copy.device_index = idx
            prev = self.copies.get(idx)
            if prev is not None:
                copy.older = prev
            self.copies[idx] = copy
            copy.original = self
        return copy

    def _detach(self, copy: DataCopy) -> None:
        with self._lock:
            if self.copies.get(copy.device_index) is copy:
                if copy.older is not None:
                    self.copies[copy.device_index] = copy.older
                else:
                    del self.copies[copy.device_index]

    def get_copy(self, device_index: int = 0) -> Optional[DataCopy]:
        return self.copies.get(device_index)

    def newest_copy(self) -> Optional[DataCopy]:
        """The copy with the highest version (candidate transfer source,
        ref: stage_in source selection device_gpu.c:1800)."""
        copies = self.copies
        if len(copies) == 1:
            # hot path: single-copy data (the common host-only case) — the
            # read is one GIL-atomic dict access, no lock needed
            try:
                c = next(iter(copies.values()))
                return None if c.coherency_state == COHERENCY_INVALID else c
            except (StopIteration, RuntimeError):
                pass    # raced a concurrent attach/detach: take the lock
        with self._lock:
            best = None
            for c in self.copies.values():
                if c.coherency_state == COHERENCY_INVALID:
                    continue
                if best is None or c.version > best.version:
                    best = c
            return best

    def create_copy(self, device_index: int, payload: Any = None,
                    state: int = COHERENCY_OWNED) -> DataCopy:
        copy = DataCopy(self, device_index, payload, state)
        return self.attach_copy(copy)

    # -- coherency transitions (ref: parsec_data_transfer_ownership_to_copy,
    #    data.c) --------------------------------------------------------------
    def transfer_ownership(self, device_index: int, access: int) -> DataCopy:
        """Make the copy on ``device_index`` the owner; invalidate others on
        write access. ``access`` uses FLOW_ACCESS_* bits."""
        from ..core.task import FLOW_ACCESS_WRITE
        with self._lock:
            copy = self.copies[device_index]
            if access & FLOW_ACCESS_WRITE:
                for idx, other in self.copies.items():
                    if idx != device_index:
                        other.coherency_state = COHERENCY_INVALID
                copy.coherency_state = COHERENCY_OWNED
                self.owner_device = device_index
            else:
                if copy.coherency_state == COHERENCY_INVALID:
                    copy.coherency_state = COHERENCY_SHARED
            return copy

    def evict_copy(self, device_index: int, to_host=None):
        """Evict the copy on ``device_index`` atomically with the
        coherency/version bookkeeping (the zone-heap eviction gap, ISSUE
        10): under ONE hold of the data lock, a copy holding the newest
        version writes back to the host copy (which takes the version in
        SHARED state — the w2r moment of transfer_gpu.c) and only then
        drops its payload and goes INVALID. Before this, the device
        module's LRU and this class were two unsynchronized views: a
        reader racing the eviction could see the device copy still
        claiming the newest version with its payload already dropped (or
        the host copy not yet carrying it), and a concurrent host write
        between the version check and the write-back could be clobbered
        by the stale device payload.

        ``to_host(payload)`` converts the device array for the host copy
        (default ``numpy.asarray`` — blocks until the device value is
        ready, which is exactly the write-back barrier).

        Returns ``(evicted, wrote_back)``.
        """
        import numpy as _np
        with self._lock:
            copy = self.copies.get(device_index)
            if copy is None or copy.payload is None:
                return (False, False)
            wrote = False
            newest_other = None
            for c in self.copies.values():
                if c is copy or c.coherency_state == COHERENCY_INVALID:
                    continue
                if newest_other is None or c.version > newest_other.version:
                    newest_other = c
            if device_index != 0 and \
                    copy.coherency_state != COHERENCY_INVALID and (
                    newest_other is None
                    or copy.version > newest_other.version):
                # dirty: the only valid holder of the newest version —
                # write back and downgrade BEFORE invalidating, inside
                # the same critical section as the version check
                host_payload = (to_host or _np.asarray)(copy.payload)
                host = self.copies.get(0)
                if host is None:
                    host = DataCopy(self, 0, host_payload, COHERENCY_SHARED)
                    self.copies[0] = host
                else:
                    host.payload = host_payload
                host.version = copy.version
                host.coherency_state = COHERENCY_SHARED
                self.owner_device = 0
                wrote = True
            copy.coherency_state = COHERENCY_INVALID
            copy.payload = None
            return (True, wrote)

    def bump_version(self, device_index: int, n: int = 1) -> int:
        """Writer completed: new authoritative version on that device
        (ref: version bump in parsec_device_kernel_epilog, device_gpu.c:3180).
        ``n`` folds a batch of writes in one call (the DTD batched lane
        lands N writes per tile natively and syncs the version delta at
        quiescence, keeping version parity with per-write bumping)."""
        with self._lock:
            self.version += n
            copy = self.copies.get(device_index)
            if copy is not None:
                copy.version = self.version
                copy.coherency_state = COHERENCY_OWNED
                self.owner_device = device_index
            return self.version

    def __repr__(self) -> str:  # pragma: no cover
        return f"<Data key={self.key} v={self.version} copies={list(self.copies)}>"


def data_from_array(array: Any, key: Any = None, dc: Any = None,
                    device_index: int = 0) -> Data:
    """Wrap an existing host array as a Data with one host copy
    (ref: parsec_data_create w/ existing pointer)."""
    d = Data(key=key, dc=dc, shape=getattr(array, "shape", None),
             dtype=getattr(array, "dtype", None))
    d.create_copy(device_index, array, COHERENCY_OWNED)
    return d
