"""Redistribution: move a submatrix between two tiled collections.

Re-design of parsec/data_dist/matrix/redistribute (redistribute.jdf,
redistribute_internal.h, redistribute_dtd.c): copy an m×n region from
source collection S (offset si, sj) into target collection T (offset ti,
tj), where S and T may have different tile sizes, grids and alignments.

Strategy (the reference's general case): one task per *target tile
fragment*: every target tile intersects up to four+ source tiles when
offsets are unaligned; each intersection becomes a copy task reading the
source tile and writing the slice of the target tile. Owner-computes places
each task on the target tile's rank; cross-rank source reads ride the
remote-dep machinery automatically.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ..dsl.dtd import AFFINITY, DTDTaskpool, READ, RW
from .matrix import TiledMatrix


def _frag_copy(dst, src, sr, sc, tr, tc, h, w):
    out = np.array(dst, copy=True)
    out[tr:tr + h, tc:tc + w] = np.asarray(src)[sr:sr + h, sc:sc + w]
    return out


def _tile_move(dst, src):
    """Whole-tile move (the reshuffle fast path, ref:
    redistribute_reshuffle.jdf:1-128): same geometry + aligned offsets map
    each target tile to exactly ONE source tile, so the payload moves by
    reference — no slice, no copy. Safe under the runtime's functional
    tile discipline (bodies never mutate inputs in place; a later write to
    either tile REPLACES its payload)."""
    return src


def redistribute(tp: DTDTaskpool, S: TiledMatrix, T: TiledMatrix,
                 m: Optional[int] = None, n: Optional[int] = None,
                 si: int = 0, sj: int = 0, ti: int = 0, tj: int = 0) -> int:
    """Insert copy tasks moving S[si:si+m, sj:sj+n] -> T[ti:ti+m, tj:tj+n].

    Returns the number of inserted tasks. Supports arbitrary tile sizes and
    non-aligned offsets on both sides (ref: redistribute_internal.h's
    NEW/OLD displacement algebra).
    """
    m = m if m is not None else min(S.lm - si, T.lm - ti)
    n = n if n is not None else min(S.ln - sj, T.ln - tj)
    assert si + m <= S.lm and sj + n <= S.ln, "source region out of bounds"
    assert ti + m <= T.lm and tj + n <= T.ln, "target region out of bounds"
    n0 = tp.inserted

    # reshuffle fast path precondition: identical tile geometry AND dtype
    # (the fragment path casts through the target's dtype on assignment;
    # a by-reference move must not change a collection's dtype) and
    # congruent offsets — every FULL target tile then maps to exactly one
    # source tile and moves whole, by reference (no fragment algebra)
    same_geom = (S.mb == T.mb and S.nb == T.nb
                 and getattr(S, "dtype", None) == getattr(T, "dtype", None)
                 and (si - ti) % S.mb == 0 and (sj - tj) % S.nb == 0)

    # iterate target tiles touched by the region
    t_m0, t_m1 = ti // T.mb, (ti + m - 1) // T.mb
    t_n0, t_n1 = tj // T.nb, (tj + n - 1) // T.nb
    for tm in range(t_m0, t_m1 + 1):
        for tn in range(t_n0, t_n1 + 1):
            # region rows/cols covered by this target tile
            r0 = max(tm * T.mb, ti) - ti
            r1 = min((tm + 1) * T.mb, ti + m) - ti
            c0 = max(tn * T.nb, tj) - tj
            c1 = min((tn + 1) * T.nb, tj + n) - tj
            if same_geom and (ti + r0) % T.mb == 0 and r1 - r0 == T.mb \
                    and (tj + c0) % T.nb == 0 and c1 - c0 == T.nb:
                # whole aligned tile: one move task, zero copies
                sm, sn = (si + r0) // S.mb, (sj + c0) // S.nb
                tp.insert_task(_tile_move,
                               (tp.tile_of(T, tm, tn), RW | AFFINITY),
                               (tp.tile_of(S, sm, sn), READ),
                               name="reshuffle", jit=False)
                continue
            # source tiles intersecting [r0:r1, c0:c1] (region coords)
            s_m0, s_m1 = (si + r0) // S.mb, (si + r1 - 1) // S.mb
            s_n0, s_n1 = (sj + c0) // S.nb, (sj + c1 - 1) // S.nb
            dst_tile = tp.tile_of(T, tm, tn)
            for sm in range(s_m0, s_m1 + 1):
                for sn in range(s_n0, s_n1 + 1):
                    fr0 = max(sm * S.mb - si, r0)
                    fr1 = min((sm + 1) * S.mb - si, r1)
                    fc0 = max(sn * S.nb - sj, c0)
                    fc1 = min((sn + 1) * S.nb - sj, c1)
                    if fr0 >= fr1 or fc0 >= fc1:
                        continue
                    # slice coordinates inside the source / target tiles
                    sr, sc = si + fr0 - sm * S.mb, sj + fc0 - sn * S.nb
                    tr, tc = ti + fr0 - tm * T.mb, tj + fc0 - tn * T.nb
                    h, w = fr1 - fr0, fc1 - fc0

                    tp.insert_task(_frag_copy, (dst_tile, RW | AFFINITY),
                                   (tp.tile_of(S, sm, sn), READ),
                                   sr, sc, tr, tc, h, w,
                                   name="redistribute", jit=False)
    return tp.inserted - n0
