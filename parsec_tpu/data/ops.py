"""Generic per-tile helper algorithms over collections.

Re-design of the reference's helper taskpools in parsec/data_dist/matrix
(apply.jdf + wrapper, reduce.jdf / reduce_col.jdf / reduce_row.jdf,
broadcast.jdf, map_operator.c): each builds a small task DAG through the DTD
frontend against any tiled collection. All operators are functional
(tile -> new tile), so they jit and run on the TPU chore path.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import numpy as np

from ..dsl.dtd import AFFINITY, DTDTaskpool, READ, RW
from .matrix import TiledMatrix


def _copy_src(dst, s):
    return s


def apply(tp: DTDTaskpool, A: TiledMatrix,
          op: Callable[[int, int, Any], Any], uplo: str = "full") -> int:
    """Apply ``op(m, n, tile) -> tile`` to every tile (ref: apply.jdf).

    ``uplo`` restricts to 'lower'/'upper' triangles like the reference.
    """
    n0 = tp.inserted
    for m in range(A.mt):
        for n in range(A.nt):
            if uplo == "lower" and n > m:
                continue
            if uplo == "upper" and n < m:
                continue
            tp.insert_task(lambda x, _m, _n: op(int(_m), int(_n), x),
                           (tp.tile_of(A, m, n), RW | AFFINITY), m, n,
                           name="apply", jit=False)
    return tp.inserted - n0


def map_operator(tp: DTDTaskpool, A: TiledMatrix, B: TiledMatrix,
                 op: Callable[[Any, Any], Any]) -> int:
    """dst tile = op(src tile, dst tile) over two collections
    (ref: map_operator.c)."""
    n0 = tp.inserted
    for m in range(A.mt):
        for n in range(A.nt):
            tp.insert_task(op, (tp.tile_of(A, m, n), READ),
                           (tp.tile_of(B, m, n), RW | AFFINITY),
                           name="map2")
    return tp.inserted - n0


def reduce_all(tp: DTDTaskpool, A: TiledMatrix,
               op: Callable[[Any, Any], Any],
               root: tuple = (0, 0)) -> int:
    """Binary-tree reduction of every tile into tile ``root``
    (ref: reduce.jdf). Returns task count; result lands in A[root]."""
    tiles = [(m, n) for m in range(A.mt) for n in range(A.nt)]
    tiles.remove(root)
    tiles.insert(0, root)
    n0 = tp.inserted
    stride = 1
    while stride < len(tiles):
        for i in range(0, len(tiles) - stride, 2 * stride):
            dst, src = tiles[i], tiles[i + stride]
            tp.insert_task(op, (tp.tile_of(A, *dst), RW | AFFINITY),
                           (tp.tile_of(A, *src), READ), name="reduce")
        stride *= 2
    return tp.inserted - n0


def reduce_row(tp: DTDTaskpool, A: TiledMatrix,
               op: Callable[[Any, Any], Any]) -> int:
    """Reduce each row of tiles into column 0 (ref: reduce_row.jdf)."""
    n0 = tp.inserted
    for m in range(A.mt):
        for n in range(1, A.nt):
            tp.insert_task(op, (tp.tile_of(A, m, 0), RW | AFFINITY),
                           (tp.tile_of(A, m, n), READ), name="reduce_row")
    return tp.inserted - n0


def reduce_col(tp: DTDTaskpool, A: TiledMatrix,
               op: Callable[[Any, Any], Any]) -> int:
    """Reduce each column of tiles into row 0 (ref: reduce_col.jdf)."""
    n0 = tp.inserted
    for n in range(A.nt):
        for m in range(1, A.mt):
            tp.insert_task(op, (tp.tile_of(A, 0, n), RW | AFFINITY),
                           (tp.tile_of(A, m, n), READ), name="reduce_col")
    return tp.inserted - n0


def broadcast(tp: DTDTaskpool, A: TiledMatrix, root: tuple = (0, 0)) -> int:
    """Copy tile ``root`` into every tile of A (ref: broadcast.jdf).

    In distributed mode the copies to remote owners ride the runtime's
    multicast trees automatically (one writer, many remote readers)."""
    n0 = tp.inserted
    src = tp.tile_of(A, *root)
    for m in range(A.mt):
        for n in range(A.nt):
            if (m, n) == root:
                continue
            tp.insert_task(_copy_src,
                           (tp.tile_of(A, m, n), RW | AFFINITY), (src, READ),
                           name="bcast")
    return tp.inserted - n0
