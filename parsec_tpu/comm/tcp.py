"""Cross-process CE backend: N OS processes joined by a full TCP mesh.

The production-transport analogue of the reference's funnelled MPI backend
(parsec/parsec_mpi_funnelled.c: init :642, pre-posted AM recv slots :823,
progress :1427). Design mapping:

* **bootstrap** — `mpi_funnelled_init`'s communicator dup becomes a
  rendezvous: every rank opens a listen socket; ranks 1..N-1 dial rank 0 and
  exchange (rank, addr); rank 0 broadcasts the address map; higher ranks
  then dial lower ranks, yielding one socket per pair (the "communicator").
* **pre-posted recv slots** — one reader thread per peer socket plays the
  persistent `MPI_Irecv` slots: frames are decoded off the wire eagerly and
  parked in an inbound deque.
* **funnelled progress** — AM callbacks fire only from :meth:`progress`
  (the caller's progress path / comm thread), never from reader threads,
  preserving the reference's single-threaded AM discipline.
* **one-sided put/get** — emulated over the two-sided stream with internal
  handshake tags, exactly like the reference emulates RDMA over MPI.

Wire format: 4-byte big-endian frame length + pickled
``(kind, tag, src, header, payload)``. Numpy payloads ride pickle protocol 5
(zero extra copies via buffer protocol); jax arrays are converted by the
protocol layer before they reach the CE.

The launcher (:func:`run_distributed_procs`) stands where ``mpiexec -n N``
stands in the reference's test harness — N real processes on one host —
and :func:`init_from_env` supports the ``python -m parsec_tpu.launch``
CLI for standalone scripts.
"""

from __future__ import annotations

import collections
import os
import pickle
import socket
import struct
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from ..utils import mca, output
from .engine import (CommEngine, CAP_ACCELERATOR_MEM, CAP_MULTITHREADED,
                     CAP_STREAMING)
# module-level: registers the comm_device_mem MCA param so the
# PARSEC_MCA_comm_device_mem env layer resolves (an unregistered param
# ignores the environment), and keeps XHostRef out of the progress hot path
from .xhost import XHostRef, XHostTransfer

_LEN = struct.Struct("!I")

# frame kinds
_KIND_AM = 0
_KIND_BAR = 1        # barrier arrival (sent to rank 0)
_KIND_BAR_REL = 2    # barrier release (rank 0 -> all)
_KIND_XACK = 4       # cross-host pull complete: producer may retire the pin
_KIND_BYE = 3        # clean shutdown notice (fini) — EOF after this is
                     # a normal departure, EOF without it is a FAILURE


#: markers that only the PJRT transfer plane emits (gRPC status words and
#: the transfer-server prefix) — strong enough to attribute on sight
_TRANSPORT_STRONG = ("TRANSFER SERVER", "UNAVAILABLE", "DEADLINE_EXCEEDED",
                     "FAILED TO CONNECT", "CONNECTION REFUSED",
                     "UNREACHABLE", "SOCKET")
#: words that ALSO occur in ordinary local errors ("buffer reset",
#: "stream closed", ...) — ambiguous, never trusted on a single failure
_TRANSPORT_WEAK = ("CONNECT", "PEER", "CLOSED", "RESET", "REFUSED",
                   "DEADLINE")


def classify_transport_error(exc: Exception) -> str:
    """Attribute a failure: ``"transport"`` (the PEER's connection/transfer
    plane), ``"local"`` (this rank's own fault), or ``"ambiguous"``.

    Typed checks first: the socket family (OSError covers ConnectionError
    and timeouts) IS the transport. PJRT transfer-plane failures surface
    as backend RuntimeErrors; only messages carrying markers unique to
    that plane are attributed outright — a local RuntimeError that merely
    *mentions* RESET is ambiguous at most, and callers must retry once
    before acting on it (ADVICE.md r5: substring matching alone let a
    local error mark a live peer dead)."""
    if isinstance(exc, (OSError, TimeoutError, EOFError)):
        return "transport"
    msg = str(exc).upper()
    if "RESOURCE_EXHAUSTED" in msg or "OUT OF MEMORY" in msg:
        return "local"       # the consumer's own OOM, never the wire
    if not isinstance(exc, RuntimeError):
        return "local"       # PJRT surfaces transfer faults as RuntimeError
    if any(m in msg for m in _TRANSPORT_STRONG):
        return "transport"
    if any(m in msg for m in _TRANSPORT_WEAK):
        return "ambiguous"
    return "local"


def _attributed_pull(pull_fn, ref):
    """Run ``pull_fn(ref)`` with failure attribution. Returns
    ``("ok", payload)`` or ``("transport", exc)``; local faults raise.

    Ambiguous failures retry ONCE: a transient wire hiccup succeeds the
    second time; a deterministic local error that happens to contain a
    weak marker raises (the peer stays alive — real peer death is also
    caught by the socket EOF/BYE paths, so under-attributing here is
    safe while over-attributing silently drops a payload)."""
    try:
        return "ok", pull_fn(ref)
    except Exception as exc:  # noqa: BLE001 — classified below
        verdict = classify_transport_error(exc)
        if verdict == "local":
            raise
        if verdict == "transport":
            return "transport", exc
        output.debug_verbose(1, "tcp",
                             f"ambiguous pull failure "
                             f"({type(exc).__name__}: {exc}); retrying once")
        try:
            return "ok", pull_fn(ref)
        except Exception as exc2:  # noqa: BLE001
            if classify_transport_error(exc2) == "transport":
                return "transport", exc2
            raise               # twice-ambiguous/local: this rank's problem


def _send_frame(sock: socket.socket, lock: threading.Lock, obj,
                raw: Optional[memoryview] = None) -> None:
    """Frame = [u32 pickle_len][pickle][u32 raw_len][raw bytes].

    Array payloads travel in the raw part straight from the source buffer
    (no pickle copy); the receiver reads them into an arena-allocated
    buffer (the reference allocates remote copies from the dep's arena,
    remote_dep_mpi.c:2120)."""
    blob = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    rl = 0 if raw is None else len(raw)
    with lock:
        sock.sendall(_LEN.pack(len(blob)) + blob + _LEN.pack(rl))
        if rl:
            sock.sendall(raw)


def _recv_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None
        buf += chunk
    return bytes(buf)


def _recv_exact_into(sock: socket.socket, mv: memoryview) -> bool:
    off, n = 0, len(mv)
    while off < n:
        r = sock.recv_into(mv[off:])
        if r == 0:
            return False
        off += r
    return True


def _recv_frame(sock: socket.socket):
    hdr = _recv_exact(sock, _LEN.size)
    if hdr is None:
        return None
    blob = _recv_exact(sock, _LEN.unpack(hdr)[0])
    if blob is None:
        return None
    obj = pickle.loads(blob)
    rhdr = _recv_exact(sock, _LEN.size)
    if rhdr is None:
        return None
    rl = _LEN.unpack(rhdr)[0]
    if isinstance(obj, tuple) and obj and obj[0] == _KIND_AM:
        kind, tag, src, header, inline, meta = obj
        if rl:
            # land the array in an arena recv buffer of its size class;
            # a capped-out arena degrades to a plain allocation rather
            # than killing the reader
            from ..data.arena import arena_for, attach_chunk
            shape, dtype_str = meta
            chunk = None
            try:
                chunk = arena_for(shape, np.dtype(dtype_str)).allocate()
                buf = chunk.buffer
            except MemoryError:
                buf = np.empty(shape, np.dtype(dtype_str))
            if not _recv_exact_into(sock, memoryview(buf).cast("B")):
                if chunk is not None:
                    chunk.free()
                return None
            if chunk is not None:
                attach_chunk(buf, chunk)
            return (kind, tag, src, header, buf)
        return (kind, tag, src, header, inline)
    if rl and _recv_exact(sock, rl) is None:   # non-AM frames carry no raw
        return None
    return obj


class TCPCE(CommEngine):
    """CE backend over a full TCP mesh between processes."""

    capabilities = CAP_MULTITHREADED | CAP_STREAMING

    def __init__(self, my_rank: int, nb_ranks: int,
                 rendezvous: Tuple[str, int], timeout: float = 60.0) -> None:
        super().__init__(my_rank, nb_ranks)
        self._peers: Dict[int, socket.socket] = {}
        self._peer_locks: Dict[int, threading.Lock] = {}
        self._inbound: "collections.deque" = collections.deque()
        self._readers: List[threading.Thread] = []
        self._closing = False
        #: ranks whose connection died while the job was still live
        #: (failure detection: surfaced by the protocol layer's progress)
        self.dead_peers: set = set()
        self._departed: set = set()   # ranks that said BYE (clean exits)
        self.sent_msgs = 0
        self.recv_msgs = 0
        # cross-host device-payload plane (PJRT transfer server), gated by
        # --mca comm_device_mem like the reference's GPU-comms flag
        # (parsec_internal.h:504). _xhost gates the SEND side (None =
        # host-bounce, counted); _xpull services incoming refs regardless,
        # so a flag-off rank can pull from an enabled peer WITHOUT flipping
        # its own sends to the device-mem path
        self._xhost = None
        self._xpull = None
        if mca.get("comm_device_mem", False):
            if XHostTransfer.available():
                self._xhost = self._xpull = XHostTransfer()
                self.capabilities |= CAP_ACCELERATOR_MEM
            else:
                output.warning("comm_device_mem requested but "
                               "jax.experimental.transfer is unavailable; "
                               "device payloads will host-bounce (counted)")
        # barrier state
        self._bar_lock = threading.Lock()
        self._bar_cv = threading.Condition(self._bar_lock)
        self._bar_epoch = 0
        # epoch -> set of ranks whose arrival frame was seen (a set, not a
        # count: a cleanly-departed rank that already arrived must not be
        # mistaken for one blocking the barrier)
        self._bar_arrivals: Dict[int, set] = {}
        # epoch -> (dead_ranks, exited_ranks) rank 0 observed
        # (([], []) = clean release)
        self._bar_released: Dict[int, Tuple[List[int], List[int]]] = {}
        if nb_ranks > 1:
            self._bootstrap(rendezvous, timeout)
            for rank, sock in self._peers.items():
                t = threading.Thread(target=self._reader_main,
                                     args=(rank, sock), daemon=True,
                                     name=f"tcpce-r{self.my_rank}-from{rank}")
                t.start()
                self._readers.append(t)

    # ------------------------------------------------------------ bootstrap
    def _bootstrap(self, rendezvous: Tuple[str, int], timeout: float) -> None:
        """Full-mesh setup (the `mpi_funnelled_init` analogue)."""
        deadline = time.monotonic() + timeout
        listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        if self.my_rank == 0:
            listener.bind(rendezvous)
        else:
            listener.bind(("127.0.0.1", 0))
        listener.listen(self.nb_ranks)
        my_addr = listener.getsockname()

        def _accept() -> socket.socket:
            listener.settimeout(max(0.1, deadline - time.monotonic()))
            conn, _ = listener.accept()
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            return conn

        def _recv_expect(conn: socket.socket, kind: str):
            """Receive one handshake frame, attributing EOF and wrong-kind
            frames (checked before unpack — arity varies by kind)."""
            frame = _recv_frame(conn)
            if frame is None:
                raise RuntimeError(f"bootstrap: peer EOF before {kind}")
            if frame[0] != kind:
                raise RuntimeError(
                    f"bootstrap: expected {kind}, got {frame[0]!r}")
            return frame[1:]

        if self.my_rank == 0:
            # collect hellos, then broadcast the address map
            addrs: Dict[int, Tuple[str, int]] = {0: my_addr}
            for _ in range(self.nb_ranks - 1):
                conn = _accept()
                rank, addr = _recv_expect(conn, "hello")
                addrs[rank] = tuple(addr)
                self._peers[rank] = conn
            for rank, conn in self._peers.items():
                lock = self._peer_locks.setdefault(rank, threading.Lock())
                _send_frame(conn, lock, ("map", addrs))
        else:
            # dial rank 0, announce, receive the map
            conn0 = self._dial(tuple(rendezvous), deadline)
            lock0 = self._peer_locks.setdefault(0, threading.Lock())
            _send_frame(conn0, lock0, ("hello", self.my_rank, my_addr))
            (addrs,) = _recv_expect(conn0, "map")
            self._peers[0] = conn0
            # dial every lower non-zero rank, accept from every higher one
            for rank in range(1, self.my_rank):
                conn = self._dial(tuple(addrs[rank]), deadline)
                lock = self._peer_locks.setdefault(rank, threading.Lock())
                _send_frame(conn, lock, ("peer", self.my_rank))
                self._peers[rank] = conn
            for _ in range(self.my_rank + 1, self.nb_ranks):
                conn = _accept()
                (rank,) = _recv_expect(conn, "peer")
                self._peers[rank] = conn
                self._peer_locks.setdefault(rank, threading.Lock())
        listener.close()
        for rank in self._peers:
            self._peer_locks.setdefault(rank, threading.Lock())
        # mesh complete: clear the dial timeout before the readers take
        # over. create_connection's 2s timeout PERSISTS on the socket, so
        # a dialed end's blocking recv would raise socket.timeout (an
        # OSError) after any >2s traffic lull — which _reader_main must
        # treat as peer death. Under full-suite load (multi-second jax
        # compiles between frames) that misdeclared live peers dead and
        # was the root of the long-standing symmetric "connection lost
        # without clean shutdown" multiproc flaps. Steady-state death
        # detection wants EOF/ECONNRESET only; the handshake above keeps
        # the bounded timeout.
        for sock in self._peers.values():
            sock.settimeout(None)

    @staticmethod
    def _dial(addr: Tuple[str, int], deadline: float) -> socket.socket:
        last: Optional[Exception] = None
        while time.monotonic() < deadline:
            try:
                s = socket.create_connection(addr, timeout=2.0)
                s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                return s
            except OSError as e:   # peer not listening yet
                last = e
                time.sleep(0.05)
        raise TimeoutError(f"could not reach {addr}: {last}")

    # ------------------------------------------------------------ readers
    def _reader_main(self, rank: int, sock: socket.socket) -> None:
        """Per-peer pre-posted recv slot: decode frames, park AMs for the
        progress path, handle barrier control inline."""
        while not self._closing:
            try:
                frame = _recv_frame(sock)
            except OSError:
                frame = None
            except Exception as e:  # noqa: BLE001 - corrupt frame/meta must
                # not silently kill the reader: the rank would stop receiving
                # from this peer with no attribution
                output.warning(f"rank {self.my_rank}: reader from {rank} "
                               f"died on {type(e).__name__}: {e}")
                frame = None
            if frame is None:
                if not self._closing and rank not in self._departed:
                    # the peer died mid-job: a clean shutdown says BYE
                    # first — record it (and wake any barrier waiter) so
                    # the failure is attributed instead of hanging to a
                    # timeout
                    with self._bar_cv:
                        self.dead_peers.add(rank)
                        self._bar_cv.notify_all()
                    if self._xhost is not None:
                        self._xhost.retire_peer(rank)   # its pulls never come
                return
            kind = frame[0]
            if kind == _KIND_BYE:
                # wake barrier waiters: a clean exit while peers still sit
                # in a barrier is a collective divergence they must see
                # attributed, not hang to a timeout
                with self._bar_cv:
                    self._departed.add(rank)
                    self._bar_cv.notify_all()
                if self._xhost is not None:
                    self._xhost.retire_peer(rank)   # clean exit: same deal
                return
            if kind == _KIND_AM:
                self._inbound.append(frame[1:])
            elif kind == _KIND_BAR:
                with self._bar_cv:
                    self._bar_arrivals.setdefault(frame[1], set()).add(rank)
                    self._bar_cv.notify_all()
            elif kind == _KIND_BAR_REL:
                with self._bar_cv:
                    # (epoch, dead_ranks, cleanly_exited_ranks)
                    self._bar_released[frame[1]] = \
                        (frame[2], frame[3]) if len(frame) > 3 else ([], [])
                    self._bar_cv.notify_all()
            elif kind == _KIND_XACK:
                if self._xhost is not None:
                    self._xhost.retire(frame[1])

    # ------------------------------------------------------------ AM path
    def send_am(self, tag: int, dst: int, header: Any, payload: Any = None) -> None:
        self.sent_msgs += 1
        if dst == self.my_rank:
            self._inbound.append((tag, dst, header, payload))
            return
        meta, raw, inline = None, None, payload
        if payload is not None and hasattr(payload, "shape") \
                and hasattr(payload, "dtype"):
            is_device = type(payload).__module__.split(".")[0] \
                not in ("numpy",)
            if is_device and self._xhost is not None:
                # device-native cross-rank path: register for PJRT pull,
                # ship only the rendezvous descriptor in the wire frame —
                # the buffer moves transfer-server-to-device on the
                # consumer's pull (parsec_mpi_funnelled.c:642 role)
                ref = self._xhost.offer(payload, dst=dst)
                _send_frame(self._peers[dst], self._peer_locks[dst],
                            (_KIND_AM, tag, self.my_rank, header, ref,
                             None), None)
                return
            # device arrays materialize host bytes HERE, at the wire
            # boundary — the protocol layer above never forces them.
            # Counted so the ICI backend's "zero host materializations"
            # property is assertable against this stream transport
            # (comm/ici.py docstring).
            if is_device:
                from ..utils.counters import counters
                counters.add("comm.host_materialized_msgs")
            # shared zero-copy codec (CommEngine.encode_payload): raw
            # buffers ship straight from the source array; exotic dtypes
            # stay inline (pickled with the frame header)
            meta, raw, inline = self.encode_payload(payload)
        _send_frame(self._peers[dst], self._peer_locks[dst],
                    (_KIND_AM, tag, self.my_rank, header, inline, meta), raw)

    # one-sided put/get + handle table inherited from CommEngine

    # ------------------------------------------------------------ progress
    def progress(self, max_msgs: int = 64) -> int:
        n = 0
        while n < max_msgs:
            try:
                tag, src, header, payload = self._inbound.popleft()
            except IndexError:
                break
            self.recv_msgs += 1
            if isinstance(payload, XHostRef):
                # rendezvous envelope: pull the device buffer directly onto
                # this rank's device through the PJRT transfer transport,
                # then tell the producer to retire its pin
                ref = payload
                if self._xpull is None:     # pull-only handle: servicing a
                    self._xpull = XHostTransfer()   # peer does NOT enable
                # only TRANSPORT-attributed failures mean the producer is
                # gone (crashed before the pull / transfer server
                # unreachable) — those are attributed like the BYE/EOF
                # paths. A local fault (consumer OOM, bad ref) must not
                # blame a live peer; it propagates as this rank's error,
                # and ambiguous failures get one retry before either
                # (typed classification + retry, ADVICE.md r5)
                status, got = _attributed_pull(self._xpull.pull, ref)
                if status == "ok":
                    payload = got
                else:
                    exc = got
                    output.warning(
                        f"tcp: xhost pull from rank {src} failed "
                        f"({type(exc).__name__}: {exc}); marking peer dead")
                    with self._bar_cv:
                        self.dead_peers.add(src)
                        self._bar_cv.notify_all()
                    if self._xhost is not None:
                        self._xhost.retire_peer(src)
                    n += 1
                    continue
                try:
                    _send_frame(self._peers[src], self._peer_locks[src],
                                (_KIND_XACK, ref.uuid))
                except OSError:
                    # producer already gone (fini/crash): the payload is
                    # ours; its pin dies with the producer's process or
                    # its dead-peer retirement
                    pass
            if not self._deliver(tag, src, header, payload):
                output.debug_verbose(1, "tcp", f"dropped AM tag {tag}")
            n += 1
        return n

    def sync(self, timeout: float = 60.0) -> None:
        """Collective barrier: arrivals funnel to rank 0, release fans out."""
        if self.nb_ranks == 1:
            return
        with self._bar_cv:
            self._bar_epoch += 1
            epoch = self._bar_epoch
        def _dead_check():
            if self.dead_peers:
                raise RuntimeError(
                    f"rank(s) {sorted(self.dead_peers)} FAILED while rank "
                    f"{self.my_rank} was in a barrier (epoch {epoch})")
        if self.my_rank == 0:
            def _blocking_exits():
                # cleanly-departed ranks that never arrived can block the
                # barrier forever: a collective divergence, attributed
                arrived = self._bar_arrivals.get(epoch, set())
                return sorted(self._departed - arrived)
            with self._bar_cv:
                ok = self._bar_cv.wait_for(
                    lambda: self.dead_peers or _blocking_exits() or
                    len(self._bar_arrivals.get(epoch, ()))
                    >= self.nb_ranks - 1,
                    timeout=timeout)
                dead = sorted(self.dead_peers)
                gone = _blocking_exits()
                self._bar_arrivals.pop(epoch, None)
            if ok or dead or gone:
                # fan out the release even on failure (carrying the failed
                # list): an asymmetric link break only rank 0 observed must
                # not strand healthy peers into a misleading barrier
                # timeout — they raise attributed instead
                for rank in self._peers:
                    try:
                        _send_frame(self._peers[rank],
                                    self._peer_locks[rank],
                                    (_KIND_BAR_REL, epoch, dead, gone))
                    except OSError:
                        # a dead socket must not abort releases to the
                        # healthy ranks; readers attribute the death
                        pass
            # a dead peer is a job failure even if its arrival was counted
            # before it died
            _dead_check()
            if gone:
                raise RuntimeError(
                    f"rank(s) {gone} exited cleanly while rank 0 was in a "
                    f"barrier (epoch {epoch}): collective divergence")
            if not ok:
                raise TimeoutError(f"barrier epoch {epoch} timed out")
        else:
            try:
                _send_frame(self._peers[0], self._peer_locks[0],
                            (_KIND_BAR, epoch))
            except OSError:
                # rank 0 already gone (e.g. it raised on another rank's
                # death and exited): fall through to the wait, where the
                # already-delivered release/dead-list attributes the
                # failure instead of a raw BrokenPipeError
                pass
            with self._bar_cv:
                ok = self._bar_cv.wait_for(
                    lambda: self.dead_peers or 0 in self._departed or
                    epoch in self._bar_released,
                    timeout=timeout)
                rel = self._bar_released.pop(epoch, None)
                root_gone = rel is None and 0 in self._departed
                _dead_check()   # our own observation of a death wins
            if rel is not None and rel[0]:
                raise RuntimeError(
                    f"rank(s) {rel[0]} FAILED while rank {self.my_rank} "
                    f"was in a barrier (epoch {epoch}, reported by rank 0)")
            if rel is not None and rel[1]:
                raise RuntimeError(
                    f"rank(s) {rel[1]} exited cleanly while rank "
                    f"{self.my_rank} was in a barrier (epoch {epoch}): "
                    f"collective divergence (reported by rank 0)")
            if root_gone:
                raise RuntimeError(
                    f"rank 0 exited cleanly while rank {self.my_rank} was "
                    f"in a barrier (epoch {epoch}): collective divergence")
            if not ok:
                raise TimeoutError(f"barrier epoch {epoch} timed out")

    def fini(self) -> None:
        self._closing = True
        for rank, sock in self._peers.items():
            try:   # best-effort goodbye so peers see a departure, not a death
                _send_frame(sock, self._peer_locks[rank], (_KIND_BYE,))
            except OSError:
                pass
            try:
                sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            sock.close()
        for t in self._readers:
            t.join(timeout=2.0)
        self._peers.clear()
        if self._xhost is not None:
            self._xhost.clear()        # nothing will pull after goodbye


# ---------------------------------------------------------------------------
# launchers
# ---------------------------------------------------------------------------
ENV_RANK = "PARSEC_TPU_RANK"
ENV_NPROCS = "PARSEC_TPU_NPROCS"
ENV_RDV = "PARSEC_TPU_RDV"       # host:port of rank 0's listener


def init_from_env(timeout: float = 60.0) -> TCPCE:
    """Build the CE from launcher-provided env vars (the `MPI_Init` moment
    for scripts started via ``python -m parsec_tpu.launch -n N script.py``)."""
    rank = int(os.environ.get(ENV_RANK, "0"))
    nprocs = int(os.environ.get(ENV_NPROCS, "1"))
    host, _, port = os.environ.get(ENV_RDV, "127.0.0.1:0").rpartition(":")
    return TCPCE(rank, nprocs, (host, int(port)), timeout=timeout)


def _free_port() -> int:
    s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _proc_main(program: Callable, rank: int, nb_ranks: int,
               rdv: Tuple[str, int], q) -> None:
    try:
        ce = TCPCE(rank, nb_ranks, rdv)
        q.put((rank, "ok", program(rank, ce)))
    except BaseException as e:  # noqa: BLE001 - shipped to the parent
        import traceback
        q.put((rank, "err", f"{e}\n{traceback.format_exc()}"))


def run_distributed_procs(nb_ranks: int,
                          program: Callable[[int, TCPCE], Any],
                          timeout: float = 120.0,
                          relaunches: int = 1) -> List[Any]:
    """Run ``program(rank, ce)`` on N real OS processes joined by TCP.

    The process analogue of :func:`parsec_tpu.comm.threads.run_distributed`
    (which runs ranks as threads): same signature shape, a real process
    boundary. ``program`` must be picklable (module-level) and must force
    its own jax platform before touching a backend.

    Deflaked (ISSUE 4): jobs serialize behind the host-wide
    :func:`parsec_tpu.launch.multiproc_lock` (concurrent sessions push
    each other past their rendezvous deadlines), and a job whose ranks
    HANG to the deadline relaunches up to ``relaunches`` times — load
    flaps retry, while program errors and died-without-reporting crashes
    (deterministic signals) propagate immediately on the first run.
    """
    from ..launch import multiproc_lock
    last: Optional[BaseException] = None
    for _ in range(max(1, relaunches + 1)):
        try:
            with multiproc_lock():
                return _run_distributed_procs_once(nb_ranks, program, timeout)
        except TimeoutError as e:
            last = e
    raise last


def _run_distributed_procs_once(nb_ranks: int,
                                program: Callable[[int, TCPCE], Any],
                                timeout: float) -> List[Any]:
    import multiprocessing as mp
    ctx = mp.get_context("spawn")
    rdv = ("127.0.0.1", _free_port())
    q = ctx.Queue()
    procs = [ctx.Process(target=_proc_main, args=(program, r, nb_ranks, rdv, q),
                         daemon=True, name=f"parsec-rank-{r}")
             for r in range(nb_ranks)]
    for p in procs:
        p.start()
    results: List[Any] = [None] * nb_ranks
    errors: List[Optional[str]] = [None] * nb_ranks
    reported = [False] * nb_ranks
    got = 0
    deadline = time.monotonic() + timeout
    import queue as _q
    while got < nb_ranks and time.monotonic() < deadline:
        try:
            rank, status, value = q.get(timeout=0.2)
        except _q.Empty:
            # a child that died without reporting (segfault, OOM-kill) will
            # never feed the queue — stop waiting as soon as one is seen
            if any(not reported[i] and not p.is_alive() and p.exitcode is not None
                   for i, p in enumerate(procs)):
                time.sleep(0.2)   # drain any result racing the exit
                while True:
                    try:
                        rank, status, value = q.get_nowait()
                    except _q.Empty:
                        break
                    reported[rank] = True
                    (results if status == "ok" else errors)[rank] = value
                    got += 1
                break
            continue
        reported[rank] = True
        if status == "ok":
            results[rank] = value
        else:
            errors[rank] = value
        got += 1
    for p in procs:
        p.join(timeout=max(0.1, deadline - time.monotonic()))
    # hung = alive AND never reported: a rank that reported but lingers
    # past the join budget is slow teardown, not a hang — it must neither
    # discard a complete result set nor shadow a dead rank's exitcode
    hung = [i for i, p in enumerate(procs)
            if p.is_alive() and not reported[i]]
    for p in procs:
        if p.is_alive():
            p.terminate()
            p.join(timeout=2.0)
            if p.is_alive():
                p.kill()
    first = next((e for e in errors if e is not None), None)
    if hung:
        # an unreported rank hung to the deadline: that hang is the root
        # cause and outranks any reported error — terminating the hung
        # rank tears its transport down, so peers report collateral
        # Broken pipe / reset errors. Retrying the whole job (the load
        # flap this classifies) is right, and a DETERMINISTIC peer error
        # just reproduces on the relaunch, so nothing is masked (its text
        # rides along for the post-relaunch raise).
        raise TimeoutError(
            f"ranks {hung} did not finish within {timeout}s"
            + (f"; peer error (likely collateral):\n{first}" if first else ""))
    if first is not None:
        raise RuntimeError(f"distributed rank failed:\n{first}")
    if got < nb_ranks:
        dead = [i for i in range(nb_ranks) if not reported[i]]
        raise RuntimeError(
            f"ranks {dead} died without reporting "
            f"(exitcodes {[procs[i].exitcode for i in dead]})")
    return results
