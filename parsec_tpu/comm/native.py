"""Native communication lane (ptcomm): the Python half of L3-in-C.

``native/src/ptcomm.cpp`` owns the cross-rank hot path — a funneled C
progress thread multiplexing the mesh (TCP sockets handed over as fds,
same-host shared-memory rings for co-located ranks), a fixed binary AM
protocol (activation / eager-data / rendezvous GET frames; no pickle),
and GIL-free ingest straight into the native engines' ready structures
(``ptcomm_iface.h``). This module is everything around it:

* **bootstrap** — a secondary mesh negotiated over the EXISTING comm
  engine's AM plane (``TAG_PTCOMM_BOOT``): every rank advertises
  availability + a host token + a listener address; co-located pairs get
  a shared-memory ring pair (created by the lower rank), remote pairs a
  dedicated TCP connection (dialed by the higher rank). The exchange
  ends with an all-ranks ``up`` confirmation so the lane engages
  EVERYWHERE or NOWHERE — an asymmetric decision would strand frames;
* **pool registry** — rank-consistent pool ids (pools must be
  instantiated in the same order on every rank, the invariant
  ``remote_dep.register_taskpool`` already imposes on names);
* **payload codec** — binary meta (dtype/shape) over the shared
  :meth:`CommEngine.encode_payload` zero-copy split; exotic payloads
  degrade to pickle protocol 5, honestly counted;
* **lifecycle** — rendezvous Py_buffer pins are released via ``reap()``
  from the runtime's drain hooks (the progress thread cannot DECREF),
  and fini tears the thread + shm segments down.

The lane is the FAST path, not the only path: ``remote_dep.py`` stays
as the fallback/paranoid route, and pools that are ineligible for the
native execution lane (typed datatypes/reshapes, DTD audit, capture,
multi-chore bodies) keep using it — counted in ``PTCOMM_STATS`` so a
silent fallback is a CI failure, not a mystery slowdown.
"""

from __future__ import annotations

import pickle
import socket
import struct
import time
import weakref
from typing import Any, Dict, List, Optional, Tuple

from ..utils import mca, output
from ..utils.counters import LaneStats
from .engine import CommEngine, TAG_PTCOMM_BOOT

mca.register("comm_native", True,
             "Drive cross-rank activations and data through the native "
             "communication lane (native/src/ptcomm.cpp): funneled C "
             "progress thread, binary AM frames, GIL-free ingest into "
             "the native engines. Ineligible transports/pools fall back "
             "to the interpreted remote_dep.py path (counted)",
             type=bool)
mca.register("comm_native_shm", True,
             "Short-circuit co-located ranks through shared-memory rings "
             "instead of loopback TCP", type=bool)
mca.register("comm_native_eager_limit", 65536,
             "Native-lane payloads up to this many bytes ride inline in "
             "the eager DATA frame; larger ones rendezvous (receiver-"
             "pulled GET)", type=int)
mca.register("comm_native_ring_bytes", 1 << 22,
             "Per-direction shared-memory ring capacity (bytes)", type=int)
mca.register("comm_native_boot_timeout", 45.0,
             "Seconds to wait for every rank to join the native comm "
             "lane bootstrap before falling back to the interpreted "
             "path", type=float)

#: lane engagement accounting, same template as PTEXEC_STATS /
#: PTDTD_STATS (LaneStats snapshot()/delta() consumed by ci.sh and the
#: bench): ``pools_engaged``/``tasks_engaged`` prove the lane carried a
#: run; ``pools_ineligible`` counts by-design fallbacks (DTD pools,
#: typed datatypes, audit/capture, non-TCP transports);
#: ``pools_fallback`` counts pools that were ELIGIBLE yet declined
#: (flatten refusal, lane missing) — the silent-regression signal.
PTCOMM_STATS = LaneStats(lanes_up=0, pools_engaged=0, tasks_engaged=0,
                         pools_fallback=0, pools_ineligible=0,
                         payloads_tx=0, payloads_pickled=0)

#: live lanes, for the process-wide ``ptcomm.*`` counter samplers
_lanes: "weakref.WeakSet[NativeCommLane]" = weakref.WeakSet()

#: C-side counters exported into the unified registry (ptcomm.<name>)
COMM_COUNTER_KEYS = ("acts_tx", "acts_rx", "data_tx", "data_rx", "rdv_tx",
                     "rdv_rx", "bytes_tx", "bytes_rx", "frame_errors",
                     "early_parked", "dropped_sends")


def comm_counter_sampler(key: str):
    """Sampler summing one C-side counter across every live lane (the
    short-TTL snapshot means one registry sweep costs one stats() call
    per lane, not one per counter key)."""
    def sample():
        total = 0
        for lane in list(_lanes):
            try:
                total += lane.stats_cached()[key]
            except Exception:  # noqa: BLE001 - a torn-down lane samples 0
                pass
        return total
    return sample


# --------------------------------------------------------------- wire meta
#: payload meta layout: u8 kind (0 = raw array, 1 = pickle), u8 len(dtype
#: str), u8 ndim, dtype bytes, ndim * i64 dims. Binary — the data frames
#: carry no pickle unless the payload itself defeats the raw codec.
_META_RAW = 0
_META_PICKLE = 1


def encode_payload(payload) -> Tuple[bytes, Any]:
    """(meta, buffer) for a native-lane data frame. Raw-eligible arrays
    ship their buffer zero-copy (the C side copies once into the frame /
    pins it for rendezvous); anything else pickles, counted."""
    meta_t, raw, inline = CommEngine.encode_payload(payload)
    if raw is not None:
        shape, dtype_str = meta_t
        ds = dtype_str.encode()
        meta = struct.pack("<BBB", _META_RAW, len(ds), len(shape)) + ds + \
            struct.pack(f"<{len(shape)}q", *shape)
        return meta, raw
    PTCOMM_STATS["payloads_pickled"] += 1
    return struct.pack("<BBB", _META_PICKLE, 0, 0), \
        pickle.dumps(inline, protocol=5)


def decode_payload(meta: bytes, data) -> Any:
    """Inverse of :func:`encode_payload` (zero extra copies for raw)."""
    kind, dlen, ndim = struct.unpack_from("<BBB", meta, 0)
    if kind == _META_PICKLE:
        return pickle.loads(data)
    ds = meta[3:3 + dlen].decode()
    shape = struct.unpack_from(f"<{ndim}q", meta, 3 + dlen)
    return CommEngine.decode_raw((shape, ds), data)


# ------------------------------------------------------------- shm helpers

def _make_ring(size: int):
    """Create + header-init one shared-memory ring (the C side maps it by
    name; layout documented in ptcomm.cpp)."""
    from multiprocessing import shared_memory
    from .. import native as native_mod
    mod = native_mod.load_ptcomm()
    shm = shared_memory.SharedMemory(create=True,
                                     size=mod.SHM_DATA_OFF + size)
    struct.pack_into("<II", shm.buf, 0, mod.SHM_MAGIC, size)
    struct.pack_into("<Q", shm.buf, 64, 0)
    struct.pack_into("<Q", shm.buf, 128, 0)
    return shm


def _host_token() -> str:
    """Co-location token: ranks sharing it talk through shm. Hostname
    plus the boot id separates containers that share a hostname but not
    /dev/shm."""
    boot = ""
    try:
        with open("/proc/sys/kernel/random/boot_id") as f:
            boot = f.read().strip()
    except OSError:
        pass
    return f"{socket.gethostname()}|{boot}"


class NativeCommLane:
    """One rank's native comm lane: the C ``Comm`` object plus bootstrap,
    pool registry, and lifecycle. Built by ``RemoteDepEngine`` at
    construction when every rank can join (see :meth:`available`)."""

    @staticmethod
    def available(ce) -> Optional[str]:
        """None when the lane can engage on this transport, else the
        reason it cannot (ineligible-by-design, counted by the caller)."""
        if ce.nb_ranks < 2:
            return "single rank"
        if not mca.get("comm_native", True):
            return "disabled by --mca comm_native 0"
        peers = getattr(ce, "_peers", None)
        if not isinstance(peers, dict) or not all(
                hasattr(s, "fileno") for s in peers.values()):
            return "transport has no peer sockets (in-process fabric)"
        from .. import native as native_mod
        if native_mod.load_ptcomm() is None or \
                native_mod.load_ptexec() is None:
            return "native modules unavailable"
        return None

    def __init__(self, rde, ce, timeout: Optional[float] = None) -> None:
        self.rde = rde
        self.ce = ce
        self.ctx = rde.ctx
        from .. import native as native_mod
        self._mod = native_mod.load_ptcomm()
        self.comm = self._mod.Comm(ce.my_rank, ce.nb_ranks)
        self._segments: List = []          # SharedMemory I created
        self._pools: Dict[int, Any] = {}   # pool_id -> engine object
        self._stats_cache = (0.0, None)    # (stamp, snapshot) for samplers
        self._up = False
        timeout = timeout if timeout is not None else \
            mca.get("comm_native_boot_timeout", 45.0)
        try:
            self._bootstrap(timeout)
        except Exception:
            self._teardown_segments()
            raise
        self.comm.start()
        self._up = True
        PTCOMM_STATS["lanes_up"] += 1
        _lanes.add(self)
        # rendezvous pins release under the GIL from the hot loops
        self.ctx.register_drain_hook(self.reap)
        output.debug_verbose(1, "ptcomm",
                             f"native comm lane up on rank {ce.my_rank} "
                             f"({ce.nb_ranks} ranks)")

    # ------------------------------------------------------------ bootstrap
    def _bootstrap(self, timeout: float) -> None:
        """Build the secondary mesh. Control messages ride the existing
        CE AM plane (TAG_PTCOMM_BOOT, parked into ``rde._ptcomm_box`` by
        the handler registered at RemoteDepEngine construction)."""
        ce, me = self.ce, self.ce.my_rank
        deadline = time.monotonic() + timeout
        box = self.rde._ptcomm_box
        token = _host_token()
        listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        listener.bind(("0.0.0.0", 0))
        listener.listen(ce.nb_ranks)
        listener.settimeout(0.05)
        port = listener.getsockname()[1]
        try:
            self._bootstrap_inner(deadline, box, token, listener, port)
        finally:
            listener.close()

    def _pump(self, deadline: float, what: str, cond) -> None:
        while not cond():
            if time.monotonic() >= deadline:
                raise TimeoutError(
                    f"ptcomm bootstrap: timed out waiting for {what}")
            self.ce.progress()
            time.sleep(2e-4)

    def _bootstrap_inner(self, deadline, box, token, listener, port) -> None:
        ce, me = self.ce, self.ce.my_rank
        peers = [r for r in range(ce.nb_ranks) if r != me]
        use_shm = mca.get("comm_native_shm", True)
        for r in peers:
            ce.send_am(TAG_PTCOMM_BOOT, r,
                       {"k": "hello", "avail": True, "host": token,
                        "port": port, "shm_ok": use_shm}, None)

        def hello_of(r):
            hs = [h for h in box.get(r, []) if h.get("k") == "hello"]
            for h in hs:
                if not h.get("avail"):
                    return h   # a decline outranks an earlier offer (the
                               # peer may have failed mid-bootstrap)
            return hs[0] if hs else None

        self._pump(deadline, "peer hellos",
                   lambda: all(hello_of(r) is not None for r in peers))
        hellos = {r: hello_of(r) for r in peers}
        if not all(h["avail"] for h in hellos.values()):
            bad = [r for r, h in hellos.items() if not h["avail"]]
            raise RuntimeError(f"ranks {bad} cannot join the native lane")

        ring_bytes = mca.get("comm_native_ring_bytes", 1 << 22)
        shm_wait = []
        dial = []
        accept_from = set()
        for r in peers:
            co = use_shm and hellos[r].get("shm_ok") and \
                hellos[r]["host"] == token
            if co:
                if me < r:
                    # lower rank creates the ring pair and advertises it
                    a, b = _make_ring(ring_bytes), _make_ring(ring_bytes)
                    self._segments += [a, b]
                    self.comm.add_peer_shm(r, "/" + a.name, "/" + b.name)
                    ce.send_am(TAG_PTCOMM_BOOT, r,
                               {"k": "shm", "tx": "/" + b.name,
                                "rx": "/" + a.name}, None)
                else:
                    shm_wait.append(r)
            else:
                # cross-host (or shm off): dedicated TCP link, dialed by
                # the higher rank toward the lower rank's listener; the
                # reachable address comes from the existing mesh socket
                if me > r:
                    ip = ce._peers[r].getpeername()[0]
                    dial.append((r, (ip, hellos[r]["port"])))
                else:
                    accept_from.add(r)

        def shm_of(r):
            for h in box.get(r, []):
                if h.get("k") == "shm":
                    return h
            return None

        def check_declines():
            # a peer that failed MID-bootstrap (after its avail=True
            # hello) broadcasts a decline; abort promptly instead of
            # waiting for its links until the timeout
            bad = [r for r in peers
                   if any(h.get("k") == "hello" and not h.get("avail")
                          for h in box.get(r, []))]
            if bad:
                raise RuntimeError(
                    f"ranks {bad} left the native lane bootstrap")

        pending_dial = dict(dial)
        while shm_wait or pending_dial or accept_from:
            if time.monotonic() >= deadline:
                raise TimeoutError(
                    f"ptcomm bootstrap: links outstanding (shm={shm_wait}, "
                    f"dial={list(pending_dial)}, accept={accept_from})")
            check_declines()
            self.ce.progress()
            for r in list(shm_wait):
                h = shm_of(r)
                if h is not None:
                    self.comm.add_peer_shm(r, h["tx"], h["rx"])
                    shm_wait.remove(r)
            for r, addr in list(pending_dial.items()):
                try:
                    s = socket.create_connection(addr, timeout=0.2)
                except OSError:
                    continue
                s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                s.sendall(struct.pack("<I", me))
                self.comm.add_peer_fd(r, s.fileno())
                s.close()                      # the C side holds a dup
                del pending_dial[r]
            if accept_from:
                try:
                    conn, _ = listener.accept()
                except OSError:
                    continue
                conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                conn.settimeout(max(0.1, deadline - time.monotonic()))
                try:
                    who = struct.unpack(
                        "<I", self._recv_exact(conn, 4))[0]
                except OSError:
                    conn.close()
                    continue
                if who in accept_from:
                    self.comm.add_peer_fd(who, conn.fileno())
                    accept_from.discard(who)
                conn.close()

        # all-or-nothing confirmation: the lane engages only once every
        # rank reports its links up — an asymmetric engage would strand
        # activation frames on a pool the peer never registers
        for r in peers:
            ce.send_am(TAG_PTCOMM_BOOT, r, {"k": "up", "ok": True}, None)

        def up_of(r):
            return any(h.get("k") == "up" and h.get("ok")
                       for h in box.get(r, []))

        while not all(up_of(r) for r in peers):
            if time.monotonic() >= deadline:
                raise TimeoutError("ptcomm bootstrap: timed out waiting "
                                   "for the all-ranks up confirmation")
            check_declines()
            self.ce.progress()
            time.sleep(2e-4)

    @staticmethod
    def _recv_exact(conn, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            chunk = conn.recv(n - len(buf))
            if not chunk:
                raise OSError("EOF during ptcomm link handshake")
            buf += chunk
        return buf

    # --------------------------------------------------------- pool registry
    @staticmethod
    def pool_id_for(name: str) -> int:
        """Rank-consistent pool ids derived from the TASKPOOL NAME (which
        remote_dep already requires to be unique among live distributed
        pools and identical across ranks) — a per-rank counter would
        silently desynchronize the id spaces after any rank-local lane
        refusal, routing one pool's frames into another's graph."""
        import zlib
        return zlib.crc32(name.encode()) & 0x7FFFFFFF

    def register_engine(self, pool_id: int, engine) -> None:
        """Route ``pool_id``'s frames into ``engine`` (a ptexec Graph or
        ptdtd Engine); frames that raced ahead replay immediately. A
        stale registration under the same id (a TERMINATED same-name pool
        that owned zero local tasks, so no finalize ever unregistered it)
        is replaced — truly-live name collisions were already fatal'd by
        remote_dep.register_taskpool before this point."""
        try:
            self.comm.register_pool(pool_id, engine,
                                    engine.ingest_capsule())
        except ValueError:
            self.comm.unregister_pool(pool_id)
            self.comm.register_pool(pool_id, engine,
                                    engine.ingest_capsule())
        self._pools[pool_id] = engine

    def unregister_engine(self, pool_id: int) -> None:
        self.comm.unregister_pool(pool_id)
        self._pools.pop(pool_id, None)
        self.reap()

    # ------------------------------------------------------------- data path
    def send_payload(self, dst: int, pool_id: int, slot: int,
                     payload) -> str:
        """Ship one produced slot payload to ``dst`` (eager under the
        limit, rendezvous above it). Returns the mode used."""
        meta, buf = encode_payload(payload)
        PTCOMM_STATS["payloads_tx"] += 1
        return self.comm.send_payload(
            dst, pool_id, slot, meta, buf,
            mca.get("comm_native_eager_limit", 65536))

    def take_payload(self, pool_id: int, slot: int):
        """Materialize an arrived payload (consumes the C-side buffer)."""
        meta, data = self.comm.take_payload(pool_id, slot)
        return decode_payload(meta, data)

    def reap(self) -> None:
        """Release rendezvous Py_buffer pins whose replies streamed out
        (registered as a context drain hook; the progress thread cannot
        DECREF)."""
        try:
            self.comm.reap()
        except Exception:  # noqa: BLE001 - teardown races are benign
            pass

    # -------------------------------------------------------------- teardown
    def _teardown_segments(self) -> None:
        for shm in self._segments:
            try:
                shm.close()
                shm.unlink()
            except Exception:  # noqa: BLE001 - already gone is fine
                pass
        self._segments = []

    def fini(self, flush_timeout: float = 10.0) -> None:
        if not self._up:
            return
        self._up = False
        # a rank whose pools completed may still owe peers bytes: queued
        # frames not yet on a wire, and rendezvous pins a slower consumer
        # has not pulled. Stopping before they drain would strand the
        # peer's parked tasks — wait (bounded; a dead peer times out and
        # is reported by the primary mesh's failure detection).
        deadline = time.monotonic() + flush_timeout
        while time.monotonic() < deadline:
            s = self.comm.stats()
            if not s["out_pending"] and not self.comm.pins_pending():
                break
            self.reap()
            time.sleep(1e-3)
        for pool_id in list(self._pools):
            try:
                self.comm.unregister_pool(pool_id)
            except Exception:  # noqa: BLE001
                pass
        self._pools.clear()
        try:
            self.ctx._ntrace_detach(self.comm)
        except Exception:  # noqa: BLE001 — no bridge attached
            pass
        try:
            self.ctx._hist_detach(self.comm)
        except Exception:  # noqa: BLE001 — no histograms armed
            pass
        self.comm.stop()
        self.reap()
        self._teardown_segments()
        output.debug_verbose(1, "ptcomm",
                             f"native comm lane down on rank "
                             f"{self.ce.my_rank}: {self.stats_brief()}")

    def stats_cached(self, ttl: float = 0.05) -> Dict[str, Any]:
        """stats() memoized for ``ttl`` seconds: the counter registry
        samples many ptcomm.* keys per snapshot sweep."""
        now = time.monotonic()
        stamp, snap = self._stats_cache
        if snap is None or now - stamp > ttl:
            snap = self.comm.stats()
            self._stats_cache = (now, snap)
        return snap

    def stats_brief(self) -> Dict[str, Any]:
        s = self.comm.stats()
        return {k: s[k] for k in ("acts_tx", "acts_rx", "data_tx",
                                  "data_rx", "rdv_tx", "rdv_rx",
                                  "frame_errors", "broken_peers")}
