"""Remote dependency engine: rank-to-rank dataflow over a comm engine.

Re-design of parsec/remote_dep.c + parsec/remote_dep_mpi.c:

* **activate / get / put protocol** (remote_dep_mpi.c:1347-2245): when a
  local producer completes, an *activate* AM travels to each consumer rank;
  small payloads ride inline (the eager short-circuit), large ones trigger a
  GET from the receiver answered by a PUT (one-sided emulation).
* **command pump** (remote_dep_dequeue_main, remote_dep_mpi.c:423;
  nothread_progress :1143-1271): worker threads never touch the network —
  they enqueue commands into a dequeue drained by the progress path (the
  master thread inline, or a dedicated comm thread when
  ``--mca comm_thread 1``, mirroring the funnelled model).
* **collective propagation** (remote_dep.c:40-46,322-411): one output
  multicast to many ranks via rank lists + re-rooted virtual trees —
  chain-pipeline (default), binomial, or star, selected by
  ``--mca comm_coll_bcast``; non-root ranks rebuild the tree and forward.
* **DTD remote edges** (rank_sent_to bitmaps + delayed release,
  remote_dep_mpi.c:2046,2100): payloads arriving before the local reader
  task is inserted park in ``_received`` until the expectation shows up.
* **termination detection**: the fourcounter module's wave protocol
  (Dijkstra/Mattern, ref parsec/mca/termdet/fourcounter/) rides the termdet
  tag: a token circulates the ring accumulating (sent, received, idle);
  two consecutive consistent waves ⇒ broadcast TERMINATE.

On a TPU pod the same engine drives control messages over host transport
while bulk tiles move HBM↔HBM (ICI); this module is transport-agnostic
through the CE vtable.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

from ..core import termdet as termdet_mod
from ..utils import mca, output
from .engine import (CAP_STREAMING, CommEngine, TAG_CLOCKSYNC, TAG_CNT_AGG,
                     TAG_DTD_AUDIT, TAG_INTERNAL_GET, TAG_INTERNAL_PUT,
                     TAG_PTCOMM_BOOT, TAG_PTFAB, TAG_PTTEL,
                     TAG_REMOTE_DEP_ACTIVATE, TAG_TERMDET)

mca.register("comm_eager_limit", 65536,
             "Payloads up to this many bytes ride inside the activate AM", type=int)
mca.register("comm_coll_bcast", "chain",
             "Multicast tree algorithm (chain|binomial|star)")
mca.register("comm_thread", False,
             "Dedicated communication progress thread (funnelled model)", type=bool)
mca.register("counter_aggregate", False,
             "Gather every rank's counter snapshot at fini and print a "
             "merged per-rank + sum table on rank 0 (aggregator_visu role)",
             type=bool)
mca.register("clock_sync_samples", 16,
             "Ping-pong exchanges per rank for the rank-0 clock-offset "
             "estimate (min-RTT sample wins; the estimate's error is "
             "bounded by that sample's RTT/2). The offset rebases this "
             "rank's trace timestamps in the multi-rank merge", type=int)


def bcast_children(ranks: Sequence[int], me: int, algo: str) -> List[Tuple[int, List[int]]]:
    """Split a destination list into (child, subtree) pairs as seen from
    ``me`` (the current forwarder). Every rank rebuilds the same tree
    (ref: parsec_remote_dep_propagate, remote_dep.c:411)."""
    rest = [r for r in ranks if r != me]
    if not rest:
        return []
    if algo == "star":
        return [(r, []) for r in rest]
    if algo == "binomial":
        out: List[Tuple[int, List[int]]] = []
        lst = rest
        while lst:
            half = (len(lst) + 1) // 2
            child, subtree = lst[0], lst[1:half]
            out.append((child, subtree))
            lst = lst[half:]
        return out
    # chain-pipeline (default, ref remote_dep.c:40)
    return [(rest[0], rest[1:])]


class RemoteDepEngine:
    """Per-rank protocol engine bound to one Context + CE."""

    def __init__(self, ctx, ce: CommEngine) -> None:
        self.ctx = ctx
        self.ce = ce
        ctx.comm = self
        ctx._need_wake = True   # comm progress waits on the work event
        ctx.my_rank = ce.my_rank
        ctx.nb_ranks = ce.nb_ranks
        self._cmds: "collections.deque" = collections.deque()  # the dequeue
        self._lock = threading.Lock()
        # (tile_key, version) -> list of (taskpool, task, flow_index)
        self._expected: Dict[Tuple, List[Tuple]] = {}
        # (tile_key, version) -> payload (parked until expectation arrives)
        self._received: Dict[Tuple, Any] = {}
        self._applied_version: Dict[Any, int] = {}
        self._tiles: Dict[Any, Any] = {}          # tile_key -> DTDTile
        self._sent: Set[Tuple] = set()            # (key, version, dst) dedup
        self._taskpools: Dict[str, Any] = {}      # name -> taskpool
        # AMs that arrived before their taskpool registered locally: parked
        # per taskpool name and replayed at registration (the data analogue
        # of requeue_token — dropping them would desync fourcounter sent/recv
        # and starve downstream multicast-tree ranks)
        self._early_ams: Dict[str, List[Tuple]] = {}
        # tile keys touched on behalf of each taskpool, so termination can
        # garbage-collect _received/_sent/_applied_version (unbounded
        # otherwise in long-running jobs)
        self._tp_keys: Dict[str, Set[Any]] = {}
        self.fourcounter = termdet_mod.FourCounterTermdet(self)
        self._td_state: Dict[str, Dict[str, Any]] = {}
        self._enabled = False
        self._comm_thread: Optional[threading.Thread] = None
        self._comm_polls = 0   # loop iterations (idle-backoff regression)
        self._comm_event = threading.Event()   # send-side wake for the park
        ce.tag_register(TAG_REMOTE_DEP_ACTIVATE, self._on_activate)
        ce.tag_register(TAG_INTERNAL_GET, self._on_get)
        ce.tag_register(TAG_INTERNAL_PUT, self._on_put)
        ce.tag_register(TAG_TERMDET, self._on_termdet)
        ce.tag_register(TAG_DTD_AUDIT, self._on_audit)
        self._audit_state: Dict[str, Dict[str, Any]] = {}
        ce.tag_register(TAG_CNT_AGG, self._on_counter_snap)
        self._cnt_snaps: Dict[int, Dict[int, Dict[str, Any]]] = {}  # epoch->rank->snap
        self._cnt_epoch = 0
        self._cnt_closed = -1   # highest epoch already merged/abandoned
        #: the native communication lane (comm/native.py): built HERE —
        #: at protocol-engine construction, when every rank is known to
        #: be standing up its mesh symmetrically — so taskpools created
        #: before start() already see it. The boot handler registers
        #: unconditionally: a peer's bootstrap AM must park, not drop,
        #: even while this rank is still deciding
        self.native = None
        self._ptcomm_box: Dict[int, List[Dict[str, Any]]] = {}
        ce.tag_register(TAG_PTCOMM_BOOT, self._on_ptcomm_boot)
        #: the serving fabric (serving/fabric.py), attached by the app /
        #: harness via fab_attach; control AMs arriving earlier park.
        #: _fab_lock closes the park-vs-attach race: without it the comm
        #: thread could read fabric=None, lose the CPU, and append to a
        #: box fab_attach already swapped out — dropping a routed insert
        #: whose credit was already spent (a leaked window reservation)
        self.fabric = None
        self._fab_lock = threading.Lock()
        self._fab_box: List[Tuple[int, Any, Any]] = []
        ce.tag_register(TAG_PTFAB, self._on_fab)
        #: the mesh telemetry plane (comm/pttel.py, ISSUE 20): built here
        #: when --mca tel_interval_ms > 0 (the whole mesh shares mca, so
        #: every rank decides the same way); the handler registers
        #: unconditionally and PARKS early frames — a child's first push
        #: racing this rank's construction must fold, not drop (the
        #: dropped deltas would be missing from the rollup forever)
        self.telemetry = None
        self._tel_lock = threading.Lock()
        self._tel_box: List[Tuple[int, Any]] = []
        ce.tag_register(TAG_PTTEL, self._on_tel)
        try:
            from .pttel import TelemetryPlane
            if TelemetryPlane.configured():
                self.tel_attach(TelemetryPlane(self))
        except Exception as e:  # noqa: BLE001 — telemetry is advisory
            output.debug_verbose(1, "pttel", f"telemetry plane off: {e}")
        reason = None
        try:
            from .native import NativeCommLane
            reason = NativeCommLane.available(ce)
            if reason is None:
                self.native = NativeCommLane(self, ce)
        except Exception as e:  # noqa: BLE001 — the lane is an optimization
            reason = f"bootstrap failed: {e}"
        if reason is not None and ce.nb_ranks > 1:
            output.debug_verbose(1, "ptcomm",
                                 f"native comm lane off: {reason}")
            # tell every peer we are NOT joining, so their bootstraps
            # abort immediately instead of pumping to the 45 s timeout
            # (a decline outranks any hello this rank sent before a
            # mid-bootstrap failure)
            try:
                for r in range(ce.nb_ranks):
                    if r != ce.my_rank:
                        ce.send_am(TAG_PTCOMM_BOOT, r,
                                   {"k": "hello", "avail": False}, None)
            except Exception:  # noqa: BLE001 — peers fall back on timeout
                pass
        # comm-stream tracing (ref: the comm thread's own profiling stream
        # with typed activate/put/get events + info dictionary,
        # remote_dep_mpi.c:1286-1302); bound lazily to ctx.profiling
        self._pprof = None
        self._pstream = None
        self._pkeys: Dict[str, int] = {}
        self._pev = 0
        # rank-0 clock offset (ISSUE 8): a non-blocking ping-pong state
        # machine over the AM plane — each rank r>0 measures
        # ``local_clock - rank0_clock`` (perf_counter_ns, the SAME clock
        # the PBP traces record) by min-RTT midpoint; the multi-rank
        # trace merge rebases every rank onto rank 0's clock with it.
        # Rank 0 (and single-rank contexts) are trivially offset 0
        self._clk_lock = threading.Lock()
        self._clk_samples: List[Tuple[int, int]] = []   # (offset_ns, rtt_ns)
        self._clk_done = ce.my_rank == 0 or ce.nb_ranks < 2
        self._clk_offset_ns: Optional[int] = 0 if self._clk_done else None
        self._clk_rtt_ns: Optional[int] = 0 if self._clk_done else None
        self._clk_peers_done: Set[int] = set()   # rank 0: peers that finished
        self._clk_stream = None                  # per-tracer meta stream
        self._clk_stream_prof = None
        ce.tag_register(TAG_CLOCKSYNC, self._on_clocksync)
        self._install_clock_counters()

    # ------------------------------------------------------- comm tracing
    COMM_EVENTS = ("activate_snd", "activate_rcv", "get_snd", "get_rcv",
                   "put_snd", "put_rcv")
    COMM_INFO_DESC = "src{i};dst{i};bytes{q};eager{i}"

    def _comm_prof(self):
        """The comm machinery's own profiling stream, one per rank
        (ref: MPI_Activate/MPI_Data_* keywords with src/dst/size info
        blobs, remote_dep_mpi.c:1286-1302)."""
        prof = getattr(self.ctx, "profiling", None)
        if prof is None:
            return None
        if self._pstream is None or self._pprof is not prof:
            self._pprof = prof
            self._pstream = prof.stream(f"comm(rank {self.ce.my_rank})")
            self._pkeys = {}
            for name in self.COMM_EVENTS:
                start, _ = prof.add_dictionary_keyword(
                    f"comm::{name}", info_desc=self.COMM_INFO_DESC)
                self._pkeys[name] = start
        return self._pstream

    @staticmethod
    def _payload_nbytes(p) -> int:
        if p is None:
            return 0
        n = getattr(p, "nbytes", None)
        if n is not None:
            return int(n)
        try:
            return len(p)
        except TypeError:
            return 0

    def _trace_comm(self, kind: str, src: int, dst: int, payload,
                    eager: bool = True) -> None:
        s = self._comm_prof()
        if s is None:
            return
        from ..utils.trace import EVENT_FLAG_POINT
        self._pev += 1
        info = self._pprof.pack_info(f"comm::{kind}", src=src, dst=dst,
                                     bytes=self._payload_nbytes(payload),
                                     eager=int(eager))
        s.trace(self._pkeys[kind], self._pev, 0, EVENT_FLAG_POINT, info)

    # ------------------------------------------------------------ clock sync
    def _install_clock_counters(self) -> None:
        """``comm.clock_offset_ns`` / ``comm.clock_rtt_ns`` in the
        unified registry (weakly bound: a registry sampler must never
        pin a dead engine alive)."""
        import weakref

        from ..utils.counters import counters
        wself = weakref.ref(self)

        def _sample(attr):
            def sample():
                s = wself()
                v = getattr(s, attr, None) if s is not None else None
                return float("nan") if v is None else v
            return sample

        counters.register("comm.clock_offset_ns",
                          sampler=_sample("_clk_offset_ns"))
        counters.register("comm.clock_rtt_ns", sampler=_sample("_clk_rtt_ns"))

    def _clk_ping(self) -> None:
        """Issue one ping toward rank 0 (non-blocking; the pong handler
        chains the next one until enough samples landed)."""
        if self._clk_done:
            return
        self.ce.send_am(TAG_CLOCKSYNC, 0,
                        {"k": "ping", "t0": time.perf_counter_ns()}, None)

    def _on_clocksync(self, ce, src, hdr, payload) -> None:
        kind = hdr.get("k")
        if kind == "ping":
            # answer with our clock reading; the requester brackets it
            ce.send_am(TAG_CLOCKSYNC, src,
                       {"k": "pong", "t0": hdr["t0"],
                        "ts": time.perf_counter_ns()}, None)
            return
        if kind == "done":      # a peer's estimate landed (rank 0 only)
            self._clk_peers_done.add(src)
            return
        t1 = time.perf_counter_ns()
        with self._clk_lock:
            if self._clk_done:
                return          # late/duplicate pong after finalize
            rtt = t1 - hdr["t0"]
            # symmetric-delay midpoint: rank 0 read its clock at ~our
            # (t0+t1)/2, so offset = local - rank0; error <= rtt/2
            self._clk_samples.append(
                ((hdr["t0"] + t1) // 2 - hdr["ts"], rtt))
            if len(self._clk_samples) >= \
                    max(2, mca.get("clock_sync_samples", 16)):
                off, rtt = min(self._clk_samples, key=lambda s: s[1])
                self._clk_offset_ns = off
                self._clk_rtt_ns = rtt
                self._clk_done = True
        if self._clk_done:
            self.stamp_clock_meta()
            # let rank 0 stop pumping on our behalf (clock_sync_wait)
            self.ce.send_am(TAG_CLOCKSYNC, 0, {"k": "done"}, None)
        else:
            self._clk_ping()

    def clock_sync_wait(self, timeout: float = 5.0) -> bool:
        """Pump until the offset estimate lands. On rank 0 — whose own
        offset is trivially 0 — this instead pumps until every PEER
        reported its estimate done: the ladder only advances while rank
        0 answers pings, so a rank-0 caller that stopped progressing
        (post-run barriers don't pump AMs) would strand the peers'
        remaining round trips. Collective in spirit: call it on every
        rank (the gates/tests do) before relying on the metadata."""
        self._clk_ping()
        if self.ce.my_rank == 0 and self.ce.nb_ranks > 1:
            want = self.ce.nb_ranks - 1
            return self._pump_until(
                lambda: len(self._clk_peers_done) >= want, timeout)
        return self._pump_until(lambda: self._clk_done, timeout)

    def clock_sync_finalize(self, timeout: float = 2.0) -> None:
        """Context.fini hook, called BEFORE the trace is stamped and
        dumped: give an unfinished ladder one bounded collective pump.
        Rank 0 participates too (its own estimate is trivially done, but
        the peers' ladders only advance while it answers pings — without
        this, every peer would burn its full timeout against a silent
        rank 0). No-op once everything already completed, which is the
        common case: the ladder usually finishes during the run."""
        if self.ce.nb_ranks < 2 or not self._enabled:
            return
        self.clock_sync_wait(timeout)

    def stamp_clock_meta(self) -> None:
        """Land one ``meta::clock`` POINT event (rank, offset, min-RTT)
        into the attached tracer — the per-rank metadata the multi-rank
        merge (tools/trace_reader.merge_traces) reads to rebase this
        rank's timestamps onto rank 0's clock. Called when the estimate
        lands and again defensively before any dump. Idempotent per
        tracer once the estimate is COMPLETE; an incomplete (ok=0) stamp
        does NOT latch, so a ladder that finishes later still lands its
        real offset — trace_reader.clock_meta prefers the ok=1 record."""
        prof = getattr(self.ctx, "profiling", None)
        if prof is None or not getattr(prof, "enabled", True):
            return
        if getattr(prof, "_clk_stamped", False):
            return
        from ..utils.trace import EVENT_FLAG_POINT
        start, _ = prof.add_dictionary_keyword(
            "meta::clock", info_desc="rank{i};peer{i};offset_ns{q};"
                                     "rtt_ns{q};ok{i}")
        # one stream per tracer (Profiling.stream always appends): an
        # ok=0 stamp followed by the completed one re-uses it instead of
        # minting duplicate identically-named streams in the dump
        if self._clk_stream is None or self._clk_stream_prof is not prof:
            self._clk_stream = prof.stream(f"clock(rank {self.ce.my_rank})")
            self._clk_stream_prof = prof
        info = prof.pack_info(
            "meta::clock", rank=self.ce.my_rank, peer=0,
            offset_ns=self._clk_offset_ns or 0,
            rtt_ns=self._clk_rtt_ns or 0, ok=int(self._clk_done))
        self._clk_stream.trace(start, 0, 0, EVENT_FLAG_POINT, info)
        if self._clk_done:
            prof._clk_stamped = True

    # ------------------------------------------------------------ lifecycle
    def enable(self) -> None:
        """parsec_remote_dep_on: wake the comm machinery."""
        if self._enabled:
            return
        self._enabled = True
        self._clk_ping()        # kick the clock-offset estimate
        if self.telemetry is not None:
            self.telemetry.start()
        if mca.get("comm_thread", False):
            self._comm_thread = threading.Thread(
                target=self._comm_main, name="parsec-tpu-comm", daemon=True)
            self._comm_thread.start()

    def _comm_main(self) -> None:
        """Dedicated progress thread (ref: remote_dep_dequeue_main).

        Adaptive idle backoff: a fixed 50µs cadence burned a visible
        slice of a core on a fully idle multi-rank context (20k wakeups/s
        doing nothing). The loop now spins tight only while traffic
        flows, escalates its sleep while idle, and finally parks on a
        dedicated send-side event (set by every command enqueue, cleared
        here before the re-check so a wakeup can never be missed);
        inbound frames land via the transport reader threads, which
        cannot signal the event, so the park is capped at 20ms to stay
        responsive to pure-receive traffic."""
        import time
        idle = 0
        while self._enabled:
            self._comm_polls += 1
            if self.progress():
                idle = 0
                continue
            idle += 1
            if idle <= 20:
                time.sleep(50e-6)           # tight: mid-burst lulls
            elif idle <= 200:
                time.sleep(min(2e-3, 50e-6 * idle))   # escalate
            else:
                self._comm_event.clear()
                if not self._cmds:          # re-check: no missed wakeup
                    self._comm_event.wait(timeout=0.02)

    def _on_ptcomm_boot(self, ce, src, hdr, payload) -> None:
        """Park native-lane bootstrap AMs (consumed by comm/native.py)."""
        self._ptcomm_box.setdefault(src, []).append(hdr)

    def _on_fab(self, ce, src, hdr, payload) -> None:
        """Serving-fabric control AMs: dispatch to the attached fabric,
        or park until one attaches (a gateway insert racing the serving
        rank's fabric construction must not drop — the spent credit
        would leak a window reservation)."""
        with self._fab_lock:
            fab = self.fabric
            if fab is None:
                self._fab_box.append((src, hdr, payload))
                return
        fab.on_fab(src, hdr, payload)

    def fab_attach(self, fabric) -> None:
        """Attach the serving fabric and replay parked control AMs."""
        with self._fab_lock:
            self.fabric = fabric
            box, self._fab_box = self._fab_box, []
        for src, hdr, payload in box:
            fabric.on_fab(src, hdr, payload)

    def _on_tel(self, ce, src, hdr, payload) -> None:
        """Telemetry frames: fold into the plane, or park until one
        attaches (the _on_fab pattern; the box is bounded — an unarmed
        rank in an armed mesh is a config error, counted not grown)."""
        with self._tel_lock:
            tel = self.telemetry
            if tel is None:
                from .pttel import TEL_STATS
                if len(self._tel_box) < 256:
                    self._tel_box.append((src, hdr))
                    TEL_STATS["parked"] += 1
                else:
                    TEL_STATS["late_drops"] += 1
                return
        tel.on_frame(src, hdr)

    def tel_attach(self, tel) -> None:
        """Attach the telemetry plane and replay parked frames."""
        with self._tel_lock:
            self.telemetry = tel
            box, self._tel_box = self._tel_box, []
        for src, hdr in box:
            tel.on_frame(src, hdr)

    def fini(self) -> None:
        # clock-sync finalization (the bounded collective pump) already
        # ran from Context.fini BEFORE the trace was stamped/dumped;
        # here only the defensive stamp remains, for direct rde.fini
        # users whose tracer never got one (no-op once latched)
        self.stamp_clock_meta()
        if mca.get("counter_aggregate", False):
            try:
                table = self.aggregate_counters()
                if table is not None:
                    self._print_counter_table(table)
            except Exception as e:  # noqa: BLE001 - teardown must proceed
                output.warning(f"counter aggregation at fini failed: {e}")
        if self.telemetry is not None:
            # final flush BEFORE the progress machinery stops: the last
            # deltas ride one more hop while peers still pump AMs
            self.telemetry.stop(flush=True)
        self._enabled = False
        if self._comm_thread is not None:
            self._comm_event.set()       # unpark for a prompt exit
            self._comm_thread.join(timeout=2.0)
        if self.native is not None:
            self.native.fini()

    def _pump_until(self, cond, timeout: float) -> bool:
        """Progress-pump until ``cond()`` or timeout (the rank-0 gather
        loop shared by the audit and counter exchanges)."""
        import time
        deadline = time.monotonic() + timeout
        while not cond():
            if time.monotonic() >= deadline:
                return False
            self.progress()
            time.sleep(1e-4)
        return True

    def register_taskpool(self, tp) -> None:
        # publish under _lock: AM handlers park-or-dispatch under the same
        # lock, so an activate can never fall between "not registered yet"
        # and "early list already drained"
        with self._lock:
            prev = self._taskpools.get(tp.name)
            if prev is not None and prev is not tp:
                st = self._td_state.get(tp.name)
                if st is not None and st.get("terminated"):
                    # a terminated pool never unregisters itself — recycle
                    # its slot (same program run again in one process)
                    self._td_state.pop(tp.name, None)
                else:
                    output.fatal(
                        f"taskpool name collision: {tp.name!r} already "
                        f"registered and live; concurrently-live distributed "
                        f"taskpools must have unique names (DTDTaskpool "
                        f"assigns a per-rank sequence number — construct "
                        f"pools in the same order on every rank)")
            self._taskpools[tp.name] = tp
            self._td_state.setdefault(tp.name, {
                "wave": 0, "token_out": False, "held": None,
                "last": None, "terminated": False,
            })
            early = self._early_ams.pop(tp.name, [])
        # replay AMs that raced ahead of this registration
        for kind, src, hdr, payload in early:
            if kind == "put":
                self._on_put(self.ce, src, hdr, payload)
            else:
                self._on_activate(self.ce, src, hdr, payload)

    # ------------------------------------------------------------ DTD API
    def register_tile(self, tile) -> None:
        self._tiles.setdefault(tile.key, tile)

    def expect(self, tp, task, tile, version: int, src_rank: int,
               flow_index: int) -> None:
        """A local task needs (tile, version) produced on ``src_rank``.

        If the payload already arrived (delayed-release case,
        remote_dep_mpi.c:2100) it is consumed immediately; otherwise the task
        gains one dependency satisfied at arrival time.
        """
        self.register_tile(tile)
        key = (tile.key, version)
        with self._lock:
            self._tp_keys.setdefault(tp.name, set()).add(tile.key)
            payload = self._received.get(key)
            if payload is None:
                with task.lock:
                    task.deps_remaining += 1
                self._expected.setdefault(key, []).append((tp, task, flow_index))
                return
        if task.pending_inputs is None:
            task.pending_inputs = {}
        task.pending_inputs[flow_index] = payload

    def note_send(self, tp, tile, version: int, dst_rank: int,
                  writer=None) -> None:
        """A remote task on ``dst_rank`` will need (tile, version).

        ``writer`` is the local task producing that version (captured by the
        caller BEFORE any same-call chain mutation); a pending writer gets
        the send attached (rank_sent_to bitmap), a finished/absent writer
        means the payload is already the tile's newest local content."""
        self.register_tile(tile)
        with self._lock:
            if (tile.key, version, dst_rank) in self._sent:
                return
        if writer is not None and writer.rank == self.ce.my_rank:
            # attach under the writer's lock and re-check completed there:
            # completion sets the flag and drains remote_sends under the
            # same lock, so an attach can never be lost in between
            with writer.lock:
                if not writer.completed:
                    if writer.remote_sends is None:
                        writer.remote_sends = {}
                    writer.remote_sends.setdefault(id(tile),
                                                   (tile, version, set()))
                    writer.remote_sends[id(tile)][2].add(dst_rank)
                    return
        # data already available locally: send right away (device arrays ship
        # as-is — the transport decides if/when to materialize host bytes,
        # ref parsec_mpi_allow_gpu_memory_communications)
        copy = tile.data.newest_copy()
        if copy is None:
            output.fatal(f"no data to send for {tile!r} v{version}")
        self.send_data(tp, tile, version, [dst_rank], copy.payload)

    def dtd_task_completed(self, tp, task) -> None:
        """Local writer finished: fire queued remote sends (the remote
        activation fork of parsec_release_dep_fct). The payload is this
        task's OWN output for the tile (a later local writer may already
        have advanced the tile's newest copy)."""
        sends = getattr(task, "remote_sends", None)
        if not sends:
            return
        with task.lock:   # excludes concurrent note_send attaches
            entries = list(sends.values())
            sends.clear()
        accesses = getattr(task.task_class, "flow_accesses", ())
        for tile, version, ranks in entries:
            payload = None
            for i, t in enumerate(getattr(task, "tiles", [])):
                # only a WRITE flow's slot holds the produced version (the
                # same tile may also appear as a READ flow holding the old
                # copy)
                if t is tile and i < len(accesses) and (accesses[i] & 0x2):
                    slot = task.data[i]
                    out = slot.data_out if slot.data_out is not None else slot.data_in
                    if out is not None:
                        payload = out.payload if hasattr(out, "payload") else out
                    break
            if payload is None:
                copy = tile.data.newest_copy()
                payload = copy.payload
            self.send_data(tp, tile, version, sorted(ranks), payload)

    def dtd_remote_task(self, tp, task) -> None:
        """Shadow of a task executing elsewhere — nothing to run locally;
        bookkeeping happened during linking."""

    # ------------------------------------------------------------ PTG path
    def ptg_send(self, tp, tc, pkey, flow_index: int, payload,
                 ranks: Sequence[int], dtt: Optional[str] = None) -> None:
        """Ship a PTG task's output flow to the ranks hosting its remote
        successors (the remote activation of parsec_release_dep_fct); the
        receiver re-derives which local tasks it feeds from the replicated
        program (the phantom-task trick of remote_dep_get_datatypes,
        remote_dep_mpi.c:861). ``dtt`` names the datatype the payload was
        pre-send reshaped to (one send per (flow, datatype) group)."""
        key = ("ptg", tp.name, tc.name, tuple(pkey) if isinstance(pkey, (list, tuple)) else pkey,
               flow_index, dtt)
        if payload is not None and not hasattr(payload, "shape"):
            payload = np.asarray(payload)
        with self._lock:
            ranks = [r for r in ranks if (key, 0, r) not in self._sent]
            for r in ranks:
                self._sent.add((key, 0, r))
        if not ranks:
            return
        tp.addto_nb_pending_actions(1)
        self._cmds.append(("ptg_send", tp, key, ranks, payload))
        self._comm_event.set()
        self.ctx._work_event.set()

    def _do_ptg_send(self, tp, key, ranks, payload) -> None:
        algo = mca.get("comm_coll_bcast", "chain")
        for child, subtree in bcast_children(ranks, self.ce.my_rank, algo):
            hdr = {"ptg": True, "tp": key[1], "tc": key[2], "pkey": key[3],
                   "flow": key[4], "dtt": key[5], "forward": subtree,
                   "eager": True, "key": key, "version": 0}
            self.ce.send_am(TAG_REMOTE_DEP_ACTIVATE, child, hdr, payload)
            self._trace_comm("activate_snd", self.ce.my_rank, child, payload)
            self.fourcounter.message_sent(tp)

    # ------------------------------------------------------------ data path
    def send_data(self, tp, tile, version: int, ranks: Sequence[int],
                  payload: Any) -> None:
        """Multicast (tile, version) to ``ranks`` through the selected tree.
        ``payload`` may be a host numpy array or a device (jax) array —
        device arrays cross in-process rank boundaries without a host
        round-trip; wire transports materialize bytes at the frame boundary.

        Enqueues a command; the network is only touched from the progress
        path (the funnelled discipline)."""
        ranks = [r for r in ranks if r != self.ce.my_rank]
        if not ranks:
            return
        if payload is not None and not hasattr(payload, "shape"):
            payload = np.asarray(payload)   # scalar/list body outputs
        with self._lock:
            if tp is not None:
                self._tp_keys.setdefault(tp.name, set()).add(tile.key)
            ranks = [r for r in ranks
                     if (tile.key, version, r) not in self._sent]
            for r in ranks:
                self._sent.add((tile.key, version, r))
        if not ranks:
            return
        tp.addto_nb_pending_actions(1)
        self._cmds.append(("send", tp, tile.key, version, ranks, payload))
        self._comm_event.set()
        self.ctx._work_event.set()

    def _do_send(self, tp, tile_key, version, ranks, payload) -> None:
        algo = mca.get("comm_coll_bcast", "chain")
        eager_limit = mca.get("comm_eager_limit", 65536)
        if (self.ce.capabilities & CAP_STREAMING) and \
                mca.is_default("comm_eager_limit"):
            # ordered-stream transport: the payload crosses the same pipe
            # either way, so rendezvous only adds a GET/PUT round trip —
            # PUT-with-activate at any size (VERDICT r2 weak #4). An
            # explicit --mca comm_eager_limit still forces the 3-hop path
            # (memory-pressure posture: payloads wait at the sender).
            eager_limit = float("inf")
        for child, subtree in bcast_children(ranks, self.ce.my_rank, algo):
            hdr = {
                "tp": tp.name if tp is not None else None,
                "key": tile_key,
                "version": version,
                "forward": subtree,            # re-rooted tree remainder
                "shape": tuple(payload.shape),
                "dtype": str(payload.dtype),
            }
            if payload.nbytes <= eager_limit:
                hdr["eager"] = True
                self.ce.send_am(TAG_REMOTE_DEP_ACTIVATE, child, hdr, payload)
                self._trace_comm("activate_snd", self.ce.my_rank, child,
                                 payload)
            else:
                hdr["eager"] = False
                hdr["handle"] = self.ce.mem_register(payload)
                self.ce.send_am(TAG_REMOTE_DEP_ACTIVATE, child, hdr, None)
                self._trace_comm("activate_snd", self.ce.my_rank, child,
                                 None, eager=False)
            if tp is not None:
                self.fourcounter.message_sent(tp)

    # ------------------------------------------------------------ AM handlers
    def _on_activate(self, ce, src, hdr, payload) -> None:
        name = hdr.get("tp")
        tp, parked = self._taskpool_or_park(name, "activate", src, hdr, payload)
        if parked:
            return
        self._trace_comm("activate_rcv", src, ce.my_rank, payload,
                         eager=bool(hdr.get("eager", True)))
        if tp is not None:
            self.fourcounter.message_received(tp)
        if hdr.get("ptg"):
            self._ptg_arrived(tp, hdr, payload)
            return
        if hdr.get("eager"):
            self._data_arrived(tp, hdr, payload, src)
        else:
            # rendezvous: pull the payload (ref: remote_dep_mpi_get_start)
            ce.send_am(TAG_INTERNAL_GET, src,
                       {"handle": hdr["handle"], "requester": ce.my_rank,
                        "origin": hdr}, None)
            self._trace_comm("get_snd", ce.my_rank, src, None, eager=False)

    def _on_get(self, ce, src, hdr, payload) -> None:
        self._trace_comm("get_rcv", src, ce.my_rank, None, eager=False)
        buf = ce.resolve(hdr["handle"]) if hasattr(ce, "resolve") else None
        ce.send_am(TAG_INTERNAL_PUT, hdr["requester"],
                   {"origin": hdr.get("origin")}, buf)
        self._trace_comm("put_snd", ce.my_rank, hdr["requester"], buf,
                         eager=False)
        ce.mem_unregister(hdr["handle"])

    def _on_put(self, ce, src, hdr, payload) -> None:
        origin = hdr.get("origin") or {}
        tp, parked = self._taskpool_or_park(origin.get("tp"), "put",
                                            src, hdr, payload)
        if parked:
            return
        self._trace_comm("put_rcv", src, ce.my_rank, payload, eager=False)
        self._data_arrived(tp, origin, payload, src)

    def _taskpool_or_park(self, name, kind, src, hdr, payload):
        """Resolve a taskpool by name, or park the AM for replay when the
        name is known but not registered yet (the AM raced ahead of local
        registration — counting/forwarding it now would lose it). Returns
        (taskpool, parked). The re-check happens under _lock: registration
        publishes there, so either we see the pool or our parked AM is
        visible to its replay."""
        tp = self._taskpools.get(name)
        if tp is None and name is not None:
            with self._lock:
                tp = self._taskpools.get(name)
                if tp is None:
                    self._early_ams.setdefault(name, []).append(
                        (kind, src, hdr, payload))
                    return None, True
        return tp, False

    def _data_arrived(self, tp, hdr, payload, src) -> None:
        key = hdr["key"]
        version = hdr["version"]
        # forward to the rest of the multicast tree first (pipeline)
        fwd = hdr.get("forward") or []
        if fwd and tp is not None:
            # re-send from here: we are an interior tree node
            with self._lock:
                fwd = [r for r in fwd if (key, version, r) not in self._sent]
                for r in fwd:
                    self._sent.add((key, version, r))
            if fwd:
                tp.addto_nb_pending_actions(1)
                self._cmds.append(("send", tp, key, version, fwd, payload))
        waiters: List[Tuple] = []
        with self._lock:
            if hdr.get("tp") is not None:
                self._tp_keys.setdefault(hdr["tp"], set()).add(key)
            self._received[(key, version)] = payload
            waiters = self._expected.pop((key, version), [])
            applied = self._applied_version.get(key, -1)
            tile = self._tiles.get(key)
            apply_tile = tile is not None and version > applied
            if apply_tile:
                self._applied_version[key] = version
        if apply_tile:
            from ..data.data import COHERENCY_SHARED
            host = tile.data.get_copy(0)
            if host is None:
                host = tile.data.create_copy(0, payload, COHERENCY_SHARED)
            else:
                # NOTE: the superseded payload is NOT released here — parked
                # _received entries, queued forwards, and waiter
                # pending_inputs may still alias it; arena recycling happens
                # at taskpool-termination GC (_gc_taskpool)
                host.payload = payload
            tile.data.bump_version(0)
            # preferred-device landing (ref: remote_dep_mpi_get_start
            # allocating target copies on the consumer's device,
            # remote_dep_mpi.c:2120): a tile that was device-resident stays
            # device-resident — refresh its accelerator copy in place so the
            # consumer's stage-in sees a version-valid device copy instead
            # of forcing a host->device transfer. With the ICI backend the
            # payload ALREADY lives in this rank's device HBM: it becomes
            # the device copy as-is (zero-copy landing), created if absent.
            pdevs = None
            try:
                import jax
                if isinstance(payload, jax.Array):
                    pdevs = payload.devices()
            except Exception:   # noqa: BLE001 - jax optional at this layer
                pass
            for dev in self.ctx.devices.devices:
                jd = getattr(dev, "jax_device", None)
                if jd is None:
                    continue
                dev_index = dev.device_index
                dcopy = tile.data.get_copy(dev_index)
                already_here = pdevs is not None and pdevs == {jd}
                if dcopy is None and not already_here:
                    continue   # no resident copy to refresh, payload remote
                try:
                    if dcopy is None:
                        dcopy = tile.data.create_copy(
                            dev_index, payload, COHERENCY_SHARED)
                    else:
                        dcopy.payload = payload if already_here \
                            else jax.device_put(payload, jd)
                        dcopy.coherency_state = COHERENCY_SHARED
                    dcopy.version = host.version
                except Exception as e:  # noqa: BLE001 - host copy suffices
                    output.debug_verbose(1, "comm",
                                         f"device landing failed: {e}")
        ready = []
        for wtp, task, flow_index in waiters:
            if task.pending_inputs is None:
                task.pending_inputs = {}
            task.pending_inputs[flow_index] = payload
            if task.dep_satisfied():
                ready.append(task)
        if ready:
            self.ctx.schedule(ready)

    def _ptg_arrived(self, tp, hdr, payload) -> None:
        key = tuple(hdr["key"]) if isinstance(hdr["key"], list) else hdr["key"]
        # forward down the multicast tree
        fwd = hdr.get("forward") or []
        if fwd and tp is not None:
            with self._lock:
                fwd = [r for r in fwd if (key, 0, r) not in self._sent]
                for r in fwd:
                    self._sent.add((key, 0, r))
            if fwd:
                tp.addto_nb_pending_actions(1)
                self._cmds.append(("ptg_send", tp, key, fwd, payload))
        if tp is None:
            output.warning(f"PTG payload for unknown taskpool {hdr.get('tp')!r}")
            return
        tp._ptg_data_arrived(hdr["tc"], hdr["pkey"], hdr["flow"], payload,
                             wire_dtt=hdr.get("dtt"))

    # ------------------------------------------------------------ progress
    def progress(self) -> int:
        n = 0
        while self._cmds:
            try:
                cmd = self._cmds.popleft()
            except IndexError:
                break
            if cmd[0] == "send":
                _, tp, key, version, ranks, payload = cmd
                self._do_send(tp, key, version, ranks, payload)
                if tp is not None:
                    tp.addto_nb_pending_actions(-1)
                n += 1
            elif cmd[0] == "ptg_send":
                _, tp, key, ranks, payload = cmd
                self._do_ptg_send(tp, key, ranks, payload)
                tp.addto_nb_pending_actions(-1)
                n += 1
            elif cmd[0] == "requeue_token":
                token = cmd[1]
                if token.get("tp") in self._taskpools:
                    self._on_termdet(self.ce, -1, token, None)
                    n += 1
                else:
                    # still unregistered: park again and yield this round
                    self._cmds.append(cmd)
                    break
        n += self.ce.progress()
        n += self._termdet_progress()
        if n == 0:
            # failure detection (SURVEY §5 names it; the reference has
            # none): only after a FRUITLESS drain — frames the dead peer
            # sent before dying were queued ahead of the EOF and may still
            # terminate the taskpool cleanly — a dead peer with live
            # taskpools is an attributed fatal, not a hang until timeout
            dead = getattr(self.ce, "dead_peers", None)
            if dead:
                live = [name for name, st in self._td_state.items()
                        if not st["terminated"]]
                if live:
                    output.fatal(
                        f"rank(s) {sorted(dead)} FAILED (connection lost "
                        f"without clean shutdown) while taskpool(s) {live} "
                        f"are still running on rank {self.ce.my_rank}")
        return n

    # ------------------------------------------------------------ audit
    def _on_audit(self, ce, src, hdr, payload) -> None:
        # exchanges are keyed by (taskpool, epoch): every rank audits at
        # the same wait() count, so epochs align and round N+1 reports can
        # never contaminate round N
        st = self._audit_state.setdefault(
            (hdr["tp"], hdr["epoch"]), {"got": {}, "verdict": None})
        if hdr["kind"] == "report":
            st["got"][hdr["rank"]] = (hdr["digest"], hdr["count"])
        else:   # verdict broadcast from rank 0
            st["verdict"] = hdr["ok"]

    def audit_check(self, tp, digest: int, count: int,
                    timeout: float = 30.0) -> None:
        """DTD replay auditor exchange (the DTD analogue of the PTG
        iterators_checker, ref parsec/mca/pins/iterators_checker/): every
        rank reports a deterministic digest of its (tile, version, rank)
        link decisions; rank 0 compares — any divergence between the
        replayed insert sequences is fatal BEFORE the run can hang or
        silently corrupt data. An exchange that cannot complete within
        ``timeout`` is itself fatal on every rank (a silent pass would
        re-open the silent-hang hole the auditor exists to close)."""
        me = self.ce.my_rank
        epoch = getattr(tp, "_audit_epoch", 0)
        tp._audit_epoch = epoch + 1
        key = (tp.name, epoch)
        st = self._audit_state.setdefault(key, {"got": {}, "verdict": None})
        if me == 0:
            st["got"][0] = (digest, count)
            self._pump_until(lambda: len(st["got"]) >= self.ce.nb_ranks,
                             timeout)
            ok = len(st["got"]) == self.ce.nb_ranks and \
                len(set(st["got"].values())) == 1
            for r in range(1, self.ce.nb_ranks):
                self.ce.send_am(TAG_DTD_AUDIT, r,
                                {"tp": tp.name, "epoch": epoch,
                                 "kind": "verdict", "ok": ok}, None)
            got = dict(sorted(st["got"].items()))
            self._audit_state.pop(key, None)
            if not ok:
                output.fatal(
                    f"DTD replay audit FAILED for {tp.name!r} (epoch "
                    f"{epoch}): per-rank (digest, count) = {got} — the "
                    f"ranks did not replay the same insert sequence")
        else:
            self.ce.send_am(TAG_DTD_AUDIT, 0,
                            {"tp": tp.name, "epoch": epoch, "kind": "report",
                             "rank": me, "digest": digest, "count": count},
                            None)
            self._pump_until(lambda: st["verdict"] is not None, timeout)
            verdict = st["verdict"]
            self._audit_state.pop(key, None)
            if verdict is not True:
                why = "no verdict arrived (exchange timed out)" \
                    if verdict is None else "the ranks did not replay the " \
                    "same insert sequence"
                output.fatal(
                    f"DTD replay audit FAILED for {tp.name!r} (epoch "
                    f"{epoch}, rank {me}: digest={digest:#x} "
                    f"count={count}) — {why}")

    # ------------------------------------------------------- counter agg
    def _on_counter_snap(self, ce, src, hdr, payload) -> None:
        # epoch-keyed like the audit exchange: a late round-N snapshot can
        # never satisfy (or contaminate) round N+1; stragglers for an
        # already-merged/abandoned epoch are dropped, not parked forever
        if hdr["epoch"] <= self._cnt_closed:
            return
        self._cnt_snaps.setdefault(hdr["epoch"], {})[hdr["rank"]] = hdr["snap"]

    def aggregate_counters(self, timeout: float = 15.0
                           ) -> Optional[Dict[str, Any]]:
        """Cross-rank counter aggregation (ref:
        tools/aggregator_visu/aggregator.py + papi_sde.c export): every
        rank ships its counters.py snapshot to rank 0, which merges them
        into per-rank columns + a SUM row. Returns the merged table on
        rank 0 (None elsewhere). Enabled at fini via --mca
        counter_aggregate 1.

        Lane-aware (ISSUE 8): a ptcomm-engaged run largely bypasses this
        module, so the rollup would silently miss the native wire unless
        the lanes' samplers (``ptcomm.*`` C-side counters, ``ptexec.*``/
        ``ptdtd.*`` engagement, latency percentiles) are installed in the
        registry before the snapshot — done here, idempotently, so the
        fini table covers whichever path carried the run. The exchange
        itself stays on the CE AM plane, which outlives the native lane
        (NativeCommLane.fini runs after this in RemoteDepEngine.fini)."""
        from ..utils.counters import counters, install_native_counters
        try:
            install_native_counters()
        except Exception:  # noqa: BLE001 — partial native: keep the rest
            pass
        snap = counters.snapshot()
        epoch = self._cnt_epoch
        self._cnt_epoch += 1
        if self.ce.nb_ranks == 1:
            return {"per_rank": {0: snap}, "sum": dict(snap)}
        if self.ce.my_rank != 0:
            self.ce.send_am(TAG_CNT_AGG, 0,
                            {"epoch": epoch, "rank": self.ce.my_rank,
                             "snap": snap}, None)
            return None
        got = self._cnt_snaps.setdefault(epoch, {})
        got[0] = snap
        self._pump_until(lambda: len(got) >= self.ce.nb_ranks, timeout)
        missing = [r for r in range(self.ce.nb_ranks) if r not in got]
        if missing:
            output.warning(f"counter aggregation: no snapshot from ranks "
                           f"{missing}")
        import math

        def gauge(k: str) -> bool:
            # per-rank gauges (latency percentiles, clock offsets) have
            # no meaningful cross-rank SUM — adding four ranks' p99s
            # prints a number that LOOKS like a latency but isn't; they
            # stay in the per-rank columns only
            return (".hist." in k and not k.endswith(".count")) or \
                k.startswith("comm.clock_")

        per_rank = dict(sorted(got.items()))
        total: Dict[str, Any] = {}
        for s in per_rank.values():
            for k, v in s.items():
                # a NaN sampler (clock offset not yet measured, failing
                # sampler) must not poison the whole SUM cell
                if isinstance(v, (int, float)) and math.isfinite(v) \
                        and not gauge(k):
                    total[k] = total.get(k, 0) + v
        self._cnt_snaps.pop(epoch, None)
        self._cnt_closed = max(self._cnt_closed, epoch)
        return {"per_rank": per_rank, "sum": total}

    def _print_counter_table(self, table: Dict[str, Any]) -> None:
        names = sorted({k for s in table["per_rank"].values() for k in s})
        if not names:
            return
        ranks = list(table["per_rank"])
        cols = [("counter", [n for n in names])]
        for r in ranks:
            cols.append((f"r{r}", [str(table["per_rank"][r].get(n, ""))
                                   for n in names]))
        cols.append(("sum", [str(table["sum"].get(n, "")) for n in names]))
        widths = [max(len(h), max((len(c) for c in body), default=0))
                  for h, body in cols]
        def row(cells):
            return " | ".join(c.ljust(w) for c, w in zip(cells, widths))
        lines = [row([h for h, _ in cols])]
        for i in range(len(names)):
            lines.append(row([body[i] for _, body in cols]))
        output.inform("cross-rank counters at fini:\n" + "\n".join(lines))

    # ------------------------------------------------------------ termdet
    def termdet_local_idle(self, tp) -> None:
        """Fourcounter: this rank became locally idle for ``tp``."""
        # waves advance from the progress path; nothing to do eagerly

    def _termdet_progress(self) -> int:
        n = 0
        for name, st in list(self._td_state.items()):
            tp = self._taskpools.get(name)
            if tp is None or st["terminated"]:
                continue
            idle = self.fourcounter.locally_idle(tp)
            held = st["held"]
            if held is not None and idle:
                st["held"] = None
                self._forward_token(tp, st, held)
                n += 1
            elif self.ce.my_rank == 0 and idle and not st["token_out"] \
                    and held is None:
                # initiate a wave
                st["token_out"] = True
                st["wave"] += 1
                s, r = self.fourcounter.counters(tp)
                token = {"type": "wave", "tp": name, "wave": st["wave"],
                         "sent": s, "recv": r, "idle": True, "hops": 1}
                if self.ce.nb_ranks == 1:
                    self._wave_done(tp, st, token)
                else:
                    self.ce.send_am(TAG_TERMDET, 1, token, None)
                n += 1
        return n

    def _forward_token(self, tp, st, token) -> None:
        s, r = self.fourcounter.counters(tp)
        token["sent"] += s
        token["recv"] += r
        token["idle"] = token["idle"] and self.fourcounter.locally_idle(tp)
        token["hops"] += 1
        nxt = (self.ce.my_rank + 1) % self.ce.nb_ranks
        if nxt == 0:
            self.ce.send_am(TAG_TERMDET, 0, token, None)
        else:
            self.ce.send_am(TAG_TERMDET, nxt, token, None)

    def _on_termdet(self, ce, src, token, payload) -> None:
        name = token.get("tp")
        tp = self._taskpools.get(name)
        st = self._td_state.get(name)
        if token.get("type") == "terminate":
            if tp is not None and st is not None and not st["terminated"]:
                st["terminated"] = True
                # forward the termination broadcast down the ring first
                nxt = (ce.my_rank + 1) % ce.nb_ranks
                if nxt != 0:
                    ce.send_am(TAG_TERMDET, nxt, token, None)
                self.fourcounter.declare_terminated(tp)
                self._gc_taskpool(name)
            return
        if tp is None or st is None:
            # taskpool not registered yet: park the token until it is
            self._cmds.append(("requeue_token", token))
            return
        if ce.my_rank == 0:
            self._wave_done(tp, st, token)
        else:
            if self.fourcounter.locally_idle(tp):
                self._forward_token(tp, st, token)
            else:
                st["held"] = token   # hold until idle (Dijkstra-style)

    def _wave_done(self, tp, st, token) -> None:
        st["token_out"] = False
        consistent = token["idle"] and token["sent"] == token["recv"]
        if consistent and st["last"] == (token["sent"], token["recv"]):
            st["terminated"] = True
            if self.ce.nb_ranks > 1:
                self.ce.send_am(TAG_TERMDET, 1,
                                {"type": "terminate", "tp": tp.name}, None)
            self.fourcounter.declare_terminated(tp)
            self._gc_taskpool(tp.name)
            return
        st["last"] = (token["sent"], token["recv"]) if consistent else None

    def _gc_taskpool(self, name: str) -> None:
        """Drop per-payload bookkeeping for a terminated taskpool: every
        reader has run, so parked payloads / send-dedup / applied-version
        entries for its tiles can never be consumed again."""
        from ..data.arena import release_buffer
        dropped: List[Any] = []
        with self._lock:
            keys = self._tp_keys.pop(name, set())
            # a tile key shared with a still-live pool stays accounted to it
            # (remaining _tp_keys entries all belong to live pools)
            for other in self._tp_keys.values():
                keys -= other
                if not keys:
                    break
            # buffers that became live tile content must not be recycled
            live = set()
            for k in keys:
                t = self._tiles.get(k)
                c = t.data.get_copy(0) if t is not None else None
                if c is not None and c.payload is not None:
                    live.add(id(c.payload))
            for k in keys:
                self._applied_version.pop(k, None)
                self._tiles.pop(k, None)
            if keys:
                for kv, p in self._received.items():
                    if kv[0] in keys and id(p) not in live:
                        dropped.append(p)
                self._received = {kv: p for kv, p in self._received.items()
                                  if kv[0] not in keys}
            # tile-key entries + PTG send-dedup entries (which embed the
            # taskpool name in the key) in one pass
            self._sent = {s for s in self._sent
                          if s[0] not in keys
                          and not (isinstance(s[0], tuple) and len(s[0]) >= 5
                                   and s[0][0] == "ptg" and s[0][1] == name)}
        # recycle arena recv buffers outside the lock: termination guarantees
        # no consumer, forward, or late expect can still reference them
        for p in dropped:
            release_buffer(p)
