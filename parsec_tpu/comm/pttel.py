"""pttel: wire-native mesh telemetry — tree-aggregated metric push (ISSUE 20).

PR 8 made every rank observable by PULL (`/metrics` over HTTP/UDS);
everything mesh-wide still funnelled through rank-0 scrapes — O(P) HTTP
fetches per reconciler round, exactly the O(ranks) control path ROADMAP
item 2 says must decentralize. This module is the PUSH half: every
``tel_interval_ms`` each rank ships its counter registry *as deltas*
plus its raw sparse histogram buckets (mergeable by design —
:mod:`parsec_tpu.utils.hist`) one hop UP a configurable-fanout reduction
tree riding a dedicated ``TAG_PTTEL`` AM. Interior ranks fold the
children's entries into their own store before forwarding, so each rank
sends at most ONE frame and receives at most ``fanout`` frames per
round — O(log P) frames per rank per round mesh-wide — and rank 0 ends
up holding an eventually-consistent rollup of the whole mesh with
per-rank staleness bounds.

Wire format (``TAG_PTTEL {"k": "fold", "e": [entry...]}``): one entry
per origin rank in the sender's subtree, each ``{"r": origin, "seq": n,
"ts": wall-clock, "d": {counter: delta}, "h": {hist: [count, sum_ns,
sparse-buckets]}}``. Frames are idempotent per origin: every origin
stamps a monotonically increasing ``seq`` and :func:`fold_entry` drops
``seq <= last-applied`` (counted ``pttel.late_drops``), so a replayed
frame can never double-count. Counter *values* are reconstructed by
telescoping — the per-origin cumulative is exactly the sum of its
deltas — so gauges (samplers) survive the delta encoding too; only the
mesh-wide SUM excludes gauge-shaped keys (:func:`gauge_key`, the
``aggregate_counters`` rule: summing four ranks' p99s prints a number
that LOOKS like a latency but isn't).

Consumers: ``/mesh`` on the metrics endpoint (tools/metrics_server.py)
serves :meth:`TelemetryPlane.rollup`; the share reconciler
(serving/reconcile.py) reads the pushed rollup instead of N HTTP
fetches (scrape stays as the fallback when the plane is down);
``tools/live_view.py --mesh`` polls one rank-0 endpoint instead of P.

Staleness bound: a rank's entry at rank 0 is at most ``depth *
interval`` behind (one hop per round), ``depth <= ceil(log_fanout P)``;
each entry carries its origin wall-clock ``ts`` so the bound is
*measured* (``staleness_s`` per rank in the rollup), not assumed.
"""

from __future__ import annotations

import math
import threading
import time
import weakref
from typing import Any, Dict, List, Optional, Set

from ..utils import mca, output
from ..utils.counters import LaneStats
from .engine import TAG_PTTEL

mca.register("tel_interval_ms", 0,
             "Mesh telemetry push cadence (ms): every interval each rank "
             "sends its counter deltas + sparse histogram buckets one hop "
             "up the fanout-`tel_fanout` reduction tree on TAG_PTTEL; "
             "rank 0 accumulates the mesh rollup served at /mesh. "
             "0 = plane disabled (reconciler falls back to HTTP scrape)",
             type=int)
mca.register("tel_fanout", 2,
             "Reduction-tree fanout: parent(r) = (r-1)//fanout. Higher "
             "fanout = shallower tree (fresher rollup) but more frames "
             "received per interior rank per round", type=int)

#: engagement counters (the honest-fallback contract): exported as
#: ``pttel.*`` by install_native_counters
TEL_STATS = LaneStats(
    rounds=0,        # local push rounds completed
    frames_tx=0,     # frames sent to the parent
    frames_rx=0,     # frames received from children
    folds=0,         # per-origin entries folded into the store
    parked=0,        # frames parked before the plane attached (replayed)
    late_drops=0,    # stale-seq entries dropped (delta idempotence)
    tx_errors=0,     # sends that raised (deltas re-queued, counted)
    ranks_seen=0,    # gauge: origins currently resolved in this store
)


# --------------------------------------------------------------- tree shape
def tel_parent(rank: int, fanout: int) -> Optional[int]:
    """Parent of ``rank`` in the fanout-k reduction tree (None at root)."""
    if rank <= 0:
        return None
    return (rank - 1) // max(1, fanout)


def tel_children(rank: int, nb_ranks: int, fanout: int) -> List[int]:
    """Children of ``rank``: the inverse of :func:`tel_parent`."""
    f = max(1, fanout)
    lo = rank * f + 1
    return list(range(lo, min(lo + f, nb_ranks)))


def tel_depth(nb_ranks: int, fanout: int) -> int:
    """Tree depth = the worst-case hop count (staleness in rounds)."""
    d, r = 0, nb_ranks - 1
    while r > 0:
        r = (r - 1) // max(1, fanout)
        d += 1
    return d


# --------------------------------------------------------------- fold math
def gauge_key(key: str) -> bool:
    """Keys with no meaningful cross-rank SUM (same rule as the fini
    counter aggregation): latency percentiles and clock offsets stay in
    the per-rank columns of the rollup only."""
    return (".hist." in key and not key.endswith(".count")) or \
        key.startswith("comm.clock_")


def fold_entry(store: Dict[int, Dict[str, Any]],
               entry: Dict[str, Any]) -> bool:
    """Fold one wire entry into a per-origin store — the single home of
    the tree-fold invariant (pure: no locks, no counters; the plane and
    the unit tests share it).

    ``store[origin] = {"seq", "ts", "counters", "hists"}`` where
    ``counters`` telescopes the deltas (sum of deltas == origin's latest
    snapshot value) and ``hists`` keeps the latest cumulative sparse
    buckets. Returns False (no-op) for a stale/duplicate ``seq`` — the
    idempotence contract: folding the same entry twice changes nothing.
    """
    r = int(entry["r"])
    st = store.get(r)
    if st is not None and entry["seq"] <= st["seq"]:
        return False
    if st is None:
        st = store[r] = {"seq": 0, "ts": 0.0, "counters": {}, "hists": {}}
    st["seq"] = entry["seq"]
    st["ts"] = entry["ts"]
    cum = st["counters"]
    for k, dv in entry.get("d", {}).items():
        cum[k] = cum.get(k, 0) + dv
    if entry.get("h"):
        st["hists"] = entry["h"]
    return True


def merge_rank_hists(per_rank: List[Dict[str, Any]]) -> Dict[str, list]:
    """Merge sparse histogram snapshots across ranks: counts, sums and
    per-bucket cells add (the NativeHistograms._merge invariant on the
    sparse wire form). Returns ``{name: [count, sum_ns, [[i, c]...]]}``."""
    out: Dict[str, list] = {}
    for hists in per_rank:
        for name, (count, sum_ns, sparse) in hists.items():
            cur = out.get(name)
            if cur is None:
                cur = out[name] = [0, 0, {}]
            cur[0] += count
            cur[1] += sum_ns
            for i, c in sparse:
                cur[2][i] = cur[2].get(i, 0) + c
    return {n: [c, s, sorted([i, v] for i, v in b.items())]
            for n, (c, s, b) in out.items()}


def mesh_sum(ranks: Dict[int, Dict[str, Any]]) -> Dict[str, float]:
    """The mesh-wide counter SUM over per-rank cumulative stores,
    excluding gauge-shaped keys (:func:`gauge_key`) and non-finite
    cells."""
    total: Dict[str, float] = {}
    for st in ranks.values():
        for k, v in st["counters"].items():
            if isinstance(v, (int, float)) and math.isfinite(v) \
                    and not gauge_key(k):
                total[k] = total.get(k, 0) + v
    return total


# ------------------------------------------------------------------- plane
#: the process's newest live plane (weak), for /mesh and live_view
_current: Optional["weakref.ref[TelemetryPlane]"] = None


def current_plane() -> Optional["TelemetryPlane"]:
    ref = _current
    plane = ref() if ref is not None else None
    return plane


class TelemetryPlane:
    """One rank's telemetry pusher + subtree accumulator.

    Built by :class:`~parsec_tpu.comm.remote_dep.RemoteDepEngine` when
    ``--mca tel_interval_ms > 0`` (frames arriving earlier park in the
    engine and replay at attach — the TAG_PTFAB pattern); the push
    thread starts with ``rde.enable()`` and a final flush rides
    ``rde.fini()`` so shutdown counts still reach the root."""

    def __init__(self, rde) -> None:
        self.rde = rde
        self.ce = rde.ce
        self.my_rank = self.ce.my_rank
        self.nb_ranks = self.ce.nb_ranks
        self.interval_s = max(0.005, mca.get("tel_interval_ms", 0) / 1e3)
        self.fanout = max(1, int(mca.get("tel_fanout", 2)))
        self.parent = tel_parent(self.my_rank, self.fanout)
        self.children = tel_children(self.my_rank, self.nb_ranks,
                                     self.fanout)
        self._mu = threading.Lock()
        self._seq = 0
        self._last_sent: Dict[str, float] = {}
        #: origin -> {"seq","ts","counters","hists"} (fold_entry shape)
        self._store: Dict[int, Dict[str, Any]] = {}
        #: origin -> unforwarded delta accumulation (interior ranks)
        self._pending: Dict[int, Dict[str, float]] = {}
        self._dirty: Set[int] = set()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # lanes visible in the pushed snapshots (idempotent)
        try:
            from ..utils.counters import install_native_counters
            install_native_counters()
        except Exception:  # noqa: BLE001 — partial native: push the rest
            pass
        global _current
        _current = weakref.ref(self)
        output.debug_verbose(1, "pttel",
                             f"telemetry plane up: rank {self.my_rank}/"
                             f"{self.nb_ranks} interval "
                             f"{self.interval_s * 1e3:.0f}ms fanout "
                             f"{self.fanout} parent {self.parent} "
                             f"children {self.children}")

    @classmethod
    def configured(cls) -> bool:
        return mca.get("tel_interval_ms", 0) > 0

    # ------------------------------------------------------------ snapshots
    @staticmethod
    def _snapshot_counters() -> Dict[str, float]:
        """Finite numeric registry values only: a NaN sampler (failing,
        or a clock offset not yet measured) must not poison the
        telescoped cumulative forever (NaN + anything = NaN). The
        ``*.hist.*`` percentile gauges are skipped BEFORE sampling — the
        raw sparse buckets already ride each frame (``"h"``), percentiles
        are derivable at any consumer, and those samplers are the
        registry's most expensive (each cache-missing a full bucket walk
        at exactly this cadence: the <1% duty-cycle contract)."""
        from ..utils.counters import counters
        return {k: v for k, v in counters.snapshot(
                    skip=lambda key: ".hist." in key).items()
                if isinstance(v, (int, float)) and math.isfinite(v)}

    @staticmethod
    def _snapshot_hists() -> Dict[str, list]:
        """Latest cumulative sparse buckets (raw, mergeable): hists ride
        whole each round, not as deltas — the bucket arrays are already
        sparse and the merge invariant wants absolute cells."""
        from ..utils.hist import histograms
        out: Dict[str, list] = {}
        for name, d in histograms.snapshot().items():
            out[name] = [d["count"], d["sum_ns"],
                         [[i, c] for i, c in enumerate(d["buckets"]) if c]]
        return out

    # ------------------------------------------------------------- rounds
    def round(self) -> None:
        """One telemetry round: snapshot self, fold into the store, and
        forward every dirty origin (self + folded children) one hop up
        in a single frame."""
        snap = self._snapshot_counters()
        hists = self._snapshot_hists()
        now = time.time()
        entries: List[Dict[str, Any]] = []
        with self._mu:
            self._seq += 1
            delta = {}
            for k, v in snap.items():
                dv = v - self._last_sent.get(k, 0)
                if dv:
                    delta[k] = dv
            self._last_sent = snap
            self._fold_locked({"r": self.my_rank, "seq": self._seq,
                               "ts": now, "d": delta, "h": hists})
            if self.parent is not None:
                for r in sorted(self._dirty):
                    st = self._store[r]
                    entries.append({"r": r, "seq": st["seq"],
                                    "ts": st["ts"],
                                    "d": self._pending.pop(r, {}),
                                    "h": st["hists"]})
                self._dirty.clear()
            TEL_STATS["rounds"] += 1
            TEL_STATS["ranks_seen"] = len(self._store)
        if not entries:
            return
        try:
            self.ce.send_am(TAG_PTTEL, self.parent,
                            {"k": "fold", "e": entries}, None)
            TEL_STATS["frames_tx"] += 1
        except Exception:  # noqa: BLE001 — a dying parent: re-queue deltas
            TEL_STATS["tx_errors"] += 1
            with self._mu:
                for e in entries:
                    p = self._pending.setdefault(e["r"], {})
                    for k, dv in e["d"].items():
                        p[k] = p.get(k, 0) + dv
                    self._dirty.add(e["r"])

    def _fold_locked(self, entry: Dict[str, Any]) -> bool:
        if not fold_entry(self._store, entry):
            TEL_STATS["late_drops"] += 1
            return False
        TEL_STATS["folds"] += 1
        if self.parent is not None:
            p = self._pending.setdefault(int(entry["r"]), {})
            for k, dv in entry.get("d", {}).items():
                p[k] = p.get(k, 0) + dv
        self._dirty.add(int(entry["r"]))
        return True

    def on_frame(self, src: int, hdr: Dict[str, Any]) -> None:
        """TAG_PTTEL delivery (from the rde's progress path)."""
        if hdr.get("k") != "fold":
            return
        TEL_STATS["frames_rx"] += 1
        with self._mu:
            for e in hdr.get("e", ()):
                self._fold_locked(e)

    def flush(self) -> int:
        """One synchronous push round NOW (tests / shutdown); returns
        this rank's new seq so a peer can wait for exactly this state."""
        self.round()
        return self._seq

    def seq_of(self, rank: int) -> int:
        """Last folded seq for ``rank`` (0 = never seen) — the 'did my
        peer's flush land yet' probe."""
        with self._mu:
            st = self._store.get(rank)
            return 0 if st is None else st["seq"]

    # ------------------------------------------------------------- rollup
    def rollup(self) -> Dict[str, Any]:
        """The eventually-consistent mesh view at this rank: per-rank
        cumulative counters + gauges + measured staleness, the gauge-safe
        mesh SUM, and the cross-rank histogram merge. At rank 0 this
        covers the whole mesh; interior ranks see their subtree."""
        now = time.time()
        with self._mu:
            ranks: Dict[int, Dict[str, Any]] = {}
            for r, st in self._store.items():
                ranks[r] = {
                    "seq": st["seq"], "ts": st["ts"],
                    "staleness_s": round(max(0.0, now - st["ts"]), 3),
                    "counters": dict(st["counters"]),
                    "histograms": {n: [v[0], v[1], list(v[2])]
                                   for n, v in st["hists"].items()},
                }
            rounds = TEL_STATS["rounds"]
        return {
            "my_rank": self.my_rank,
            "nb_ranks": self.nb_ranks,
            "fanout": self.fanout,
            "interval_ms": self.interval_s * 1e3,
            "depth": tel_depth(self.nb_ranks, self.fanout),
            "rounds": rounds,
            "ranks": ranks,
            "rollup": mesh_sum(ranks),
            "histograms": merge_rank_hists(
                [st["histograms"] for st in ranks.values()]),
        }

    # ---------------------------------------------------------- lifecycle
    def start(self) -> "TelemetryPlane":
        if self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(target=self._loop, daemon=True,
                                            name="parsec-tpu-pttel")
            self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.round()
            except Exception as e:  # noqa: BLE001 — telemetry is advisory
                output.debug_verbose(1, "pttel", f"round failed: {e}")

    def stop(self, flush: bool = True) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None
        if flush:
            try:
                self.round()   # final deltas reach the root before fini
            except Exception:  # noqa: BLE001 — teardown must proceed
                pass
