"""Communication-engine abstraction (CE).

Re-design of parsec/parsec_comm_engine.h:14-176: a backend-neutral vtable —
active-message tags with fixed max sizes (``tag_register``), memory
registration, one-sided ``put/get`` with remote completion AMs, ``send_am``,
``progress``, ``pack/unpack``, ``sync`` and capability flags. The reference
ships one production backend (single-threaded "funnelled" MPI,
parsec_mpi_funnelled.c); here the backends are:

* :class:`parsec_tpu.comm.threads.ThreadsCE` — N in-process ranks joined by
  queues; the test fabric (stands where oversubscribed localhost MPI stood in
  the reference's test strategy, tests/CMakeLists.txt:1032-1042).
* the SPMD/ICI path (:mod:`parsec_tpu.parallel.spmd`) — bulk tile movement as
  XLA collectives; control messages stay host-side.

Tag space mirrors the reference (parsec_comm_engine.h:29-40): internal
GET/PUT handshake tags, remote-dep activate, termdet, DSL-reserved tags,
``MAX_REGISTERED_TAGS = 12``.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

MAX_REGISTERED_TAGS = 16  # ref: PARSEC_MAX_REGISTERED_TAGS (12 there;
                          # widened for the runtime-internal tags below)

# predefined tags (ref: parsec_comm_engine.h:29-40 enumeration)
TAG_INTERNAL_GET = 0
TAG_INTERNAL_PUT = 1
TAG_REMOTE_DEP_ACTIVATE = 2
TAG_TERMDET = 3
TAG_DSL_BASE = 4          # TTG-style DSL reservations start here
TAG_PTCOMM_BOOT = 8       # native comm lane bootstrap (comm/native.py)
TAG_CLOCKSYNC = 9         # rank-0 clock-offset ping-pong (remote_dep.py)
TAG_CNT_AGG = 10          # cross-rank counter aggregation at fini
TAG_DTD_AUDIT = 11        # DTD replay-consistency auditor exchange
TAG_PTFAB = 12            # serving-fabric control plane (serving/):
                          # gateway-routed inserts + reconciliation
                          # weight nudges; admission credits themselves
                          # ride the NATIVE wire (ptcomm K_CRED)
TAG_PTTEL = 13            # mesh telemetry plane (comm/pttel.py):
                          # counter deltas + sparse histogram buckets
                          # pushed up the fanout reduction tree every
                          # --mca tel_interval_ms; rank 0 serves /mesh

# capability flags (ref: parsec_comm_engine capabilities)
CAP_ONESIDED = 0x1
CAP_MULTITHREADED = 0x2
CAP_ACCELERATOR_MEM = 0x4   # can move device-resident buffers directly
CAP_STREAMING = 0x8         # AM payloads ride the same ordered stream as
                            # headers: rendezvous buys no registration or
                            # one-sidedness, so eager (PUT-with-activate)
                            # is the right default at ANY size


@dataclass
class AMRegistration:
    tag: int
    msg_size: int
    callback: Callable[["CommEngine", int, bytes, Any], None]  # (ce, src, hdr, payload)


class CommEngine:
    """The CE vtable (ref: parsec_comm_engine.h:43-176)."""

    capabilities = 0

    def __init__(self, my_rank: int = 0, nb_ranks: int = 1) -> None:
        self.my_rank = my_rank
        self.nb_ranks = nb_ranks
        self._tags: Dict[int, AMRegistration] = {}
        self._lock = threading.Lock()
        self._handles: Dict[int, Any] = {}
        self._next_handle = 0

    # --- active messages ----------------------------------------------------
    def tag_register(self, tag: int, callback, msg_size: int = 4096) -> None:
        if len(self._tags) >= MAX_REGISTERED_TAGS:
            raise RuntimeError("out of AM tags (MAX_REGISTERED_TAGS)")
        self._tags[tag] = AMRegistration(tag, msg_size, callback)

    def tag_unregister(self, tag: int) -> None:
        self._tags.pop(tag, None)

    def send_am(self, tag: int, dst: int, header: Any,
                payload: Any = None) -> None:
        raise NotImplementedError

    # --- one-sided (emulated over two-sided AMs with internal handshake
    # tags, exactly like the reference emulates RDMA over MPI;
    # parsec_mpi_funnelled.c) — shared by every two-sided backend ----------
    def mem_register(self, buf) -> Any:
        with self._lock:
            h = self._next_handle
            self._next_handle += 1
            self._handles[h] = buf
        return h

    def mem_unregister(self, handle) -> None:
        with self._lock:
            self._handles.pop(handle, None)

    def resolve(self, handle):
        return self._handles.get(handle)

    def put(self, dst: int, local_buf, remote_handle, on_complete=None) -> None:
        self.send_am(TAG_INTERNAL_PUT, dst, {"handle": remote_handle}, local_buf)
        if on_complete is not None:
            on_complete()

    def get(self, src: int, remote_handle, on_complete=None) -> None:
        """Request the remote buffer; data arrives as the matching PUT.

        Unlike :meth:`put`, ``on_complete`` CANNOT fire here — the GET is
        only a request, and completion is observable solely through the
        PUT delivery on the registered tag.  Passing a callback is a
        caller bug (it would wait forever), so it is rejected loudly.
        """
        if on_complete is not None:
            raise ValueError(
                "CommEngine.get() cannot invoke on_complete: completion "
                "arrives as the matching PUT on the registered tag — hook "
                "the PUT delivery instead")
        self.send_am(TAG_INTERNAL_GET, src,
                     {"handle": remote_handle, "requester": self.my_rank}, None)

    # --- progress / sync ----------------------------------------------------
    def progress(self) -> int:
        """Drain incoming messages; returns #messages handled."""
        raise NotImplementedError

    def sync(self) -> None:
        """Collective barrier over all ranks."""
        raise NotImplementedError

    def enable(self) -> None:
        pass

    def fini(self) -> None:
        pass

    # --- pack/unpack --------------------------------------------------------
    #: prefix marking a raw-bytes packed blob: no pickle frame at all.
    #: Pickle streams (protocol >= 2) always begin with b"\x80", so the
    #: NUL-led magic can never collide with a pickled message.
    _RAW_MAGIC = b"\x00PTB1"

    def pack(self, obj: Any) -> bytes:
        """Serialize ``obj`` for the wire. Bytes-like payloads (the hot
        case: raw tile bytes, rendezvous reply bodies) skip pickle
        entirely — one prefix concat instead of a pickle scan+copy."""
        if isinstance(obj, (bytes, bytearray, memoryview)):
            return self._RAW_MAGIC + bytes(obj)
        import pickle
        return pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)

    def unpack(self, data) -> Any:
        """Inverse of :meth:`pack`. Raw-packed blobs come back as a
        zero-copy ``memoryview`` into ``data`` (no pickle, no copy)."""
        view = memoryview(data)
        n = len(self._RAW_MAGIC)
        if len(view) >= n and bytes(view[:n]) == self._RAW_MAGIC:
            return view[n:]
        import pickle
        return pickle.loads(data)

    # --- shared payload codec ----------------------------------------------
    #: dtype kinds whose buffers ride the wire as raw bytes; everything
    #: else (object dtypes, exotic extension types) stays pickled
    RAW_DTYPE_KINDS = "fiub"

    @staticmethod
    def encode_payload(payload):
        """Split an array payload for a zero-copy send:
        ``(meta, raw, inline)`` — ``raw`` is a memoryview straight over
        the source buffer (no serialization copy) with ``meta = (shape,
        dtype_str)`` describing it; payloads that cannot travel raw come
        back as ``inline`` (the transport pickles them). Device arrays
        materialize host bytes HERE, at the wire boundary. Shared by the
        TCP fallback frames and the native lane's eager/rendezvous data
        path."""
        import numpy as np
        a = np.ascontiguousarray(np.asarray(payload))
        if a.dtype.kind in CommEngine.RAW_DTYPE_KINDS:
            return (tuple(a.shape), a.dtype.str), \
                memoryview(a).cast("B"), None
        return None, None, a

    @staticmethod
    def decode_raw(meta, buf):
        """Materialize a raw payload: zero-copy ``np.frombuffer`` over
        the received buffer (the transport owns its lifetime)."""
        import numpy as np
        shape, dtype_str = meta
        return np.frombuffer(buf, np.dtype(dtype_str)).reshape(shape)

    def _deliver(self, tag: int, src: int, header: Any, payload: Any) -> bool:
        reg = self._tags.get(tag)
        if reg is None:
            return False
        reg.callback(self, src, header, payload)
        return True
