"""Whole-taskpool graph capture: one XLA executable per DTD DAG.

The TPU-first execution mode the reference cannot have: where PaRSEC must
dispatch every task through a driver call (and pays per-kernel launch
latency), a captured taskpool TRACES the entire insert_task sequence into a
single jitted program. DTD's sequential-consistency semantics make this
sound: insertion order is a valid serialization of the DAG, so replaying the
bodies in insertion order under `jax.jit` reconstructs the exact dataflow
graph as XLA value dependencies — XLA then re-parallelizes, fuses producers
into consumers, and runs the whole DAG as ONE dispatch.

What that buys on hardware:

* dispatch cost amortized from O(tasks) to O(1) — decisive when per-dispatch
  latency is high (remote chips) or tasks are small;
* cross-task fusion (a GEMM's epilogue fuses into the next task's prologue);
* whole-DAG compilation caching: re-running the same DAG shape (iterative
  solvers, benchmark reps) reuses the compiled executable.

Semantics and limits (checked, not assumed):

* single-rank only — a captured pool never leaves the chip;
* bodies must be jit-traceable (``jit=True`` inserts, jax/numpy-array args);
* execution happens at ``tp.wait()``; tile versions bump exactly as if the
  tasks had run through the scheduler, so collections read back normally.

Usage::

    tp = DTDTaskpool(ctx, "gemm", capture=True)
    insert_gemm_tasks(tp, A, B, C, batch_k=True)
    tp.wait()          # traces (first time) + executes the whole DAG
    tp.close()
"""

from __future__ import annotations

import collections
import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..utils import mca, output

mca.register("capture_scan_threshold", 64,
             help="op count at which capture='auto' switches from inline "
                  "replay to the scanned task interpreter")
mca.register("capture_auto_defer", True,
             "Per-region capture deferral (ISSUE 10): a wait()-delimited "
             "insert window that turns out not to be capturable (a "
             "jit=False insert, a non-traceable argument) replays through "
             "the scheduler — where device bodies ride the async device "
             "lane — instead of aborting the run; capturable windows "
             "still compile whole. 0 restores the hard reject", type=bool)


class CaptureDeferred(Exception):
    """Raised by :meth:`GraphCapture.record` when the current insert
    window cannot be captured and ``--mca capture_auto_defer`` is on: the
    taskpool replays the recorded prefix as ordinary scheduler inserts
    and runs the rest of the window interpreted (capture re-arms at the
    next window). Capture then WINS where it applies — whole-DAG XLA
    compilation for device-only regions — instead of losing globally to
    a single non-capturable task."""

#: process-wide compiled-program cache: the same DAG shape (op sequence,
#: tile shapes/dtypes, scalar params, device fingerprint) compiles exactly
#: once — shared ACROSS pool instantiations, so steady-state serving
#: (the repeated-DAG shape of heavy traffic) re-runs a warm executable
#: instead of paying trace+compile per request. Keys hold the body
#: function OBJECTS (identity equality — two closures over different
#: constants must never share a program), so the cache is LRU-bounded:
#: lambda-per-call users pay a recompile past the bound instead of
#: leaking a compiled executable per capture. Hit/miss/evict counters
#: export through the unified registry as ``capture.cache_*``
#: (ISSUE 12; see dsl/fusion.py ExecCache).
from .fusion import ExecCache, device_fingerprint

_PROGRAM_CACHE_MAX = 64
_program_cache = ExecCache(_PROGRAM_CACHE_MAX)
_cache_lock = threading.Lock()

#: memoized dtype-gate verdicts (None = compatible, str = reject reason):
#: the gate re-traces bodies abstractly per flush otherwise, even when the
#: scan executable itself is a cache hit. Keyed like the program cache
#: (body function identity + slots + store geometry), LRU-bounded.
_dtype_gate_cache: "collections.OrderedDict[Any, Optional[str]]" = \
    collections.OrderedDict()


class GraphCapture:
    """Recorder + compiler for a captured DTD taskpool.

    Two compilation strategies:

    * ``inline`` — replay every body in insertion order under one ``jax.jit``;
      the DAG appears as XLA value dependencies. Program size is O(tasks):
      ideal for small/medium DAGs of cheap-to-inline ops (dots fuse), but
      decompose-heavy ops (cholesky / triangular_solve) inlined N times
      compile superlinearly and execute far slower than the same op iterated
      (measured on-chip: a 20-op POTRF DAG at 25-60x its op-sum).
    * ``scan`` — the DAG as a scanned TASK INTERPRETER: tiles live in
      per-(shape,dtype) stacked stores, ops become descriptor rows
      (class id + store indices), and one ``lax.scan`` steps through them
      with ``lax.switch`` over task CLASSES. Program size is O(distinct
      classes) — PTG's task-class insight applied to XLA program size.
      Insertion order is a valid serialization of the DAG (DTD sequential
      consistency), and a single chip executes HLO serially anyway, so the
      serialized replay costs nothing real; each step pays one tile
      gather/scatter per flow. Descriptor rows are runtime DATA, so any DAG
      with the same classes/op-count/store-geometry reuses the executable.

    ``auto`` picks inline below ``--mca capture_scan_threshold`` ops (default
    64) and scan above it when the recording is scannable (no raw-array
    args; per-class homogeneous shapes — scalar args are baked per class).
    """

    def __init__(self, tp, mode: str = "auto") -> None:
        self.tp = tp
        if mode is True:
            mode = "auto"
        if mode not in ("auto", "inline", "scan"):
            output.fatal(f"capture mode {mode!r} not in auto|inline|scan")
        self.mode = mode
        #: per op: (fn, spec); spec entries are
        #: ("flow", tile_index, access) | ("scalar", value) | ("array", arr)
        self.ops: List[Tuple[Any, List[Tuple]]] = []
        #: per op, parallel to ``ops``: the insert properties capture
        #: itself ignores but a DEFER replay must restore —
        #: (priority, where, name, raw per-flow accesses incl. AFFINITY)
        self.op_extras: List[Tuple] = []
        self._tiles: List[Any] = []          # DTDTile, first-use order
        self._tile_ix: Dict[int, int] = {}   # id(tile) -> index
        self.cache_hit = False
        self.executions = 0
        self.last_mode: Optional[str] = None   # strategy of the last execute

    def _clear_recording(self) -> None:
        """Consume the recorded batch (execute, mesh-reject, take_ops)."""
        self.ops = []
        self.op_extras = []
        self._tiles = []
        self._tile_ix = {}

    # ------------------------------------------------------------ recording
    def record(self, fn, args: Sequence[Any], jit: bool, name: str,
               priority: int = 0, where: Optional[int] = None) -> None:
        from .dtd import AFFINITY, DTDTile, RW
        defer = mca.get("capture_auto_defer", True)
        if not jit:
            if defer:
                raise CaptureDeferred(
                    f"insert of {name or fn!r} passed jit=False")
            output.fatal(f"graph capture requires jit-traceable bodies "
                         f"(insert of {name or fn!r} passed jit=False)")
        spec: List[Tuple] = []
        raw_accs: List[int] = []     # original access bits incl. AFFINITY:
        for a in args:               # a defer replay must restore them
            if isinstance(a, tuple) and len(a) == 2 and isinstance(a[0], DTDTile):
                tile, acc = a
                raw_accs.append(acc)
                acc &= ~AFFINITY           # placement is moot on one chip
                spec.append(("flow", self._tile_index(tile), acc))
            elif isinstance(a, DTDTile):
                raw_accs.append(RW)
                spec.append(("flow", self._tile_index(a), RW))
            elif isinstance(a, (int, float, np.number)):
                spec.append(("scalar", a))
            elif isinstance(a, np.ndarray) or hasattr(a, "dtype"):
                spec.append(("array", a))
            else:
                if defer:
                    raise CaptureDeferred(
                        f"argument {a!r} of {name or fn!r} is not traceable")
                output.fatal(f"graph capture: argument {a!r} of "
                             f"{name or fn!r} is not traceable")
        self.ops.append((fn, spec))
        self.op_extras.append((priority, where, name, tuple(raw_accs)))

    def take_ops(self, fuse: bool = False) -> List[Tuple]:
        """Hand the recorded region back as replayable
        ``(fn, args, priority, where, name)`` inserts and reset the
        recording — the auto-defer hand-off: the deferring taskpool
        re-inserts them through the scheduler in the original program
        order (DTD sequential consistency makes that a valid
        serialization) with their original priorities, placement, and
        affinity bits, so nothing recorded before the non-capturable
        insert is lost, reordered, or re-scheduled differently.

        With ``fuse=True`` (ISSUE 12: ``--mca region_fusion``), maximal
        runs of *fusable* recorded ops — default placement (no custom
        ``where``, no AFFINITY/NOTRACK bits), uniform priority —
        collapse into ONE super-task insert each: a single jittable
        function replaying the run in insertion order over the run's
        tiles with UNION accesses. The deferred window then schedules
        regions + seams instead of every recorded task, so capture
        still wins where it applies even when the window as a whole
        could not compile. Landing semantics match capture's own: one
        version bump per written tile per region. Each fused function
        carries ``_ptdtd_fused`` = the member count (engagement
        accounting for the deferring pool)."""
        from ..core.task import DEV_ALL
        from .dtd import RW, WRITE
        ops, extras, tiles = self.ops, self.op_extras, self._tiles
        self._clear_recording()

        def per_task(i: int) -> Tuple:
            fn, spec = ops[i]
            prio, where, name, raw_accs = extras[i]
            args: List[Any] = []
            fi = 0
            for e in spec:
                if e[0] == "flow":
                    args.append((tiles[e[1]], raw_accs[fi]))
                    fi += 1
                else:
                    args.append(e[1])
            return (fn, args, prio, where, name)

        if not fuse:
            return [per_task(i) for i in range(len(ops))]

        def fusable(i: int) -> bool:
            # default placement only: a custom device restriction,
            # AFFINITY, or NOTRACK bit must keep its own insert
            _prio, where, _name, raw_accs = extras[i]
            return where in (None, DEV_ALL) and \
                all((acc & ~RW) == 0 for acc in raw_accs)

        def fuse_run(lo: int, hi: int) -> Tuple:
            run = ops[lo:hi]
            t_ix: Dict[int, int] = {}     # recording tile ix -> local
            t_list: List[int] = []
            accs: List[int] = []
            for _fn, spec in run:
                for e in spec:
                    if e[0] == "flow":
                        li = t_ix.get(e[1])
                        if li is None:
                            li = t_ix[e[1]] = len(t_list)
                            t_list.append(e[1])
                            accs.append(0)
                        accs[li] |= e[2]
            written_l = [li for li in range(len(t_list))
                         if accs[li] & WRITE]
            arr_vals = [e[1] for _fn, spec in run for e in spec
                        if e[0] == "array"]

            def region_fn(*vals, _run=run, _t_ix=t_ix,
                          _written=tuple(written_l), _arrs=arr_vals):
                env = list(vals)
                GraphCapture._replay(
                    _run, lambda gi: env[_t_ix[gi]],
                    lambda gi, v: env.__setitem__(_t_ix[gi], v), _arrs)
                return tuple(env[li] for li in _written)

            region_fn._ptdtd_fused = hi - lo
            args = [(tiles[gi], accs[li]) for li, gi in enumerate(t_list)]
            prio, _w, name, _a = extras[lo]
            return (region_fn, args, prio, None,
                    f"fused[{hi - lo}]" + (f":{name}" if name else ""))

        rmin = int(mca.get("region_fusion_min", 2))
        rmax = int(mca.get("region_fusion_max", 128))
        out: List[Tuple] = []
        i, n = 0, len(ops)
        while i < n:
            if not fusable(i):
                out.append(per_task(i))
                i += 1
                continue
            j = i + 1
            while j < n and j - i < rmax and fusable(j) \
                    and extras[j][0] == extras[i][0]:   # uniform priority
                j += 1
            if j - i >= rmin:
                out.append(fuse_run(i, j))
            else:
                out.extend(per_task(k) for k in range(i, j))
            i = j
        return out

    def _tile_index(self, tile) -> int:
        ix = self._tile_ix.get(id(tile))
        if ix is None:
            ix = len(self._tiles)
            self._tile_ix[id(tile)] = ix
            self._tiles.append(tile)
        return ix

    # ------------------------------------------------------------ compiling
    def _signature(self, tile_vals: List[Any]) -> Tuple:
        op_sig = []
        for fn, spec in self.ops:
            entries = []
            for e in spec:
                if e[0] == "flow":
                    entries.append(e)                      # (kind, ix, acc)
                elif e[0] == "scalar":
                    entries.append(("scalar", e[1]))       # baked into trace
                else:
                    a = e[1]
                    entries.append(("array", tuple(a.shape), str(a.dtype)))
            op_sig.append((fn, tuple(entries)))
        tiles_sig = tuple((tuple(np.shape(v)), str(getattr(v, "dtype", type(v))))
                          for v in tile_vals)
        # device fingerprint: a cached executable can never be replayed
        # against a different backend/device layout (ISSUE 12 satellite)
        return (tuple(op_sig), tiles_sig, device_fingerprint())

    def _written(self) -> List[int]:
        from .dtd import WRITE
        return sorted({e[1] for _, spec in self.ops for e in spec
                       if e[0] == "flow" and e[2] & WRITE})

    @staticmethod
    def _replay(ops, read, write, arr_vals) -> None:
        """The shared op fold: replay bodies in insertion order against
        tile read/write primitives (an env list for single-device capture;
        slice/dynamic_update_slice of sharded globals for mesh capture).
        XLA recovers the DAG from the value dependencies either way."""
        from .dtd import WRITE
        ai = 0
        for fn, spec in ops:
            ins, wixs = [], []
            for e in spec:
                if e[0] == "flow":
                    ins.append(read(e[1]))
                    if e[2] & WRITE:
                        wixs.append(e[1])
                elif e[0] == "scalar":
                    ins.append(e[1])
                else:
                    ins.append(arr_vals[ai])
                    ai += 1
            outs = fn(*ins)
            if outs is None:
                outs = ()
            elif not isinstance(outs, (tuple, list)):
                outs = (outs,)
            for wi, out in zip(wixs, outs):
                write(wi, out)

    # ------------------------------------------------------ scan interpreter
    def _scan_plan(self, tile_vals: List[Any]):
        """Lower the recording to task-class form for the scan interpreter.

        Returns ``(stores, tile_loc, classes, rows)`` or None when the
        recording is not scannable:

        * ``stores``   — list of [tile_index...] per (shape, dtype) group;
        * ``tile_loc`` — tile_index -> (store_id, slot);
        * ``classes``  — list of (fn, slots) in first-appearance order,
          where slots is a tuple of ("flow", flow_pos, store_id, acc) |
          ("scalar", value) per body argument — scalar values are BAKED
          into the class (two ops differing in a scalar are two classes);
        * ``rows``     — per op: (class_id, [store slot per flow]).
        """
        self._scan_reject: Optional[str] = None
        store_ix: Dict[Tuple, int] = {}
        stores: List[List[int]] = []
        store_meta: List[Tuple[Tuple, Any]] = []   # sid -> (shape, dtype)
        tile_loc: List[Tuple[int, int]] = []
        for i, v in enumerate(tile_vals):
            key = (tuple(np.shape(v)), str(getattr(v, "dtype", type(v))))
            sid = store_ix.get(key)
            if sid is None:
                sid = store_ix[key] = len(stores)
                stores.append([])
                store_meta.append((tuple(np.shape(v)),
                                   getattr(v, "dtype", None)))
            tile_loc.append((sid, len(stores[sid])))
            stores[sid].append(i)

        class_ix: Dict[Tuple, int] = {}
        classes: List[Tuple[Any, Tuple]] = []
        rows: List[Tuple[int, List[int]]] = []
        for fn, spec in self.ops:
            slots: List[Tuple] = []
            flow_slots: List[int] = []
            fp = 0
            for e in spec:
                if e[0] == "flow":
                    sid, slot = tile_loc[e[1]]
                    slots.append(("flow", fp, sid, e[2]))
                    flow_slots.append(slot)
                    fp += 1
                elif e[0] == "scalar":
                    slots.append(("scalar", e[1]))
                else:
                    self._scan_reject = "raw-array arguments"
                    return None          # raw-array args: not scannable
            ckey = (fn, tuple(slots))
            cid = class_ix.get(ckey)
            if cid is None:
                cid = class_ix[ckey] = len(classes)
                classes.append((fn, tuple(slots)))
            rows.append((cid, flow_slots))

        # dtype-compatibility gate: inline lands whatever dtype the body
        # RETURNS; the scan interpreter lands into the store, whose dtype is
        # the tile's INPUT dtype. A body that upcasts (f16 tiles -> f32
        # result) would silently round-trip intermediates through f16 every
        # step under scan — a precision change that must not depend on which
        # strategy 'auto' picks. Detect it abstractly (no FLOPs) per class
        # and reject scan so auto falls back to inline.
        for fn, slots in classes:
            reject = self._dtype_gate(fn, slots, store_meta)
            if reject is not None:
                self._scan_reject = reject
                return None
        return stores, tile_loc, classes, rows

    @staticmethod
    def _dtype_gate(fn, slots, store_meta) -> Optional[str]:
        """None if ``fn``'s written outputs land their stores' dtypes;
        otherwise the reject reason. Memoized — the abstract trace depends
        only on (fn, slots, store geometry), not on this flush's values."""
        key = (fn, slots,
               tuple(store_meta[sd[2]] for sd in slots if sd[0] == "flow"))
        with _cache_lock:
            if key in _dtype_gate_cache:
                _dtype_gate_cache.move_to_end(key)
                return _dtype_gate_cache[key]

        import jax
        from .dtd import WRITE
        args, wstores = [], []
        for sd in slots:
            if sd[0] == "flow":
                _, fp, sid, acc = sd
                shape, dt = store_meta[sid]
                args.append(jax.ShapeDtypeStruct(shape, dt))
                if acc & WRITE:
                    wstores.append(sid)
            else:
                args.append(sd[1])
        reject: Optional[str] = None
        try:
            out = jax.eval_shape(fn, *args)
        except Exception as e:  # noqa: BLE001 — conservative: inline can
            reject = (f"body {fn!r} not abstractly "
                      f"evaluable ({type(e).__name__})")
            out = None                   # still trace what scan cannot plan
        if reject is None:
            if out is None:
                outs: Tuple = ()
            elif not isinstance(out, (tuple, list)):
                outs = (out,)
            else:
                outs = tuple(out)
            for sid, o in zip(wstores, outs):
                if np.dtype(o.dtype) != np.dtype(store_meta[sid][1]):
                    reject = (
                        f"body {getattr(fn, '__name__', fn)!r} returns "
                        f"{o.dtype} into a {store_meta[sid][1]} store — "
                        f"scan would silently cast; use inline")
                    break
        with _cache_lock:
            _dtype_gate_cache[key] = reject
            while len(_dtype_gate_cache) > _PROGRAM_CACHE_MAX:
                _dtype_gate_cache.popitem(last=False)
        return reject

    def _build_scan(self, classes):
        """The scanned-interpreter program: one lax.scan over descriptor
        rows, lax.switch over task classes. Descriptor rows are runtime
        data — the executable depends only on classes, store shapes and op
        count."""
        import jax
        from jax import lax
        from .dtd import WRITE

        def make_branch(fn, slots):
            def branch(stores, row):
                stores = list(stores)
                ins, wr = [], []
                for sd in slots:
                    if sd[0] == "flow":
                        _, fp, sid, acc = sd
                        ins.append(lax.dynamic_index_in_dim(
                            stores[sid], row[fp], axis=0, keepdims=False))
                        if acc & WRITE:
                            wr.append((fp, sid))
                    else:
                        ins.append(sd[1])
                outs = fn(*ins)
                if outs is None:
                    outs = ()
                elif not isinstance(outs, (tuple, list)):
                    outs = (outs,)
                for (fp, sid), out in zip(wr, outs):
                    stores[sid] = lax.dynamic_update_index_in_dim(
                        stores[sid], out.astype(stores[sid].dtype),
                        row[fp], axis=0)
                return tuple(stores)
            return branch

        branches = [make_branch(fn, slots) for fn, slots in classes]

        def program(store_vals, class_ids, flow_idx):
            def step(stores, x):
                cid, row = x
                if len(branches) == 1:
                    return branches[0](stores, row), None
                return lax.switch(cid, branches, stores, row), None
            out, _ = jax.lax.scan(step, tuple(store_vals),
                                  (class_ids, flow_idx))
            return out

        return program

    def _execute_scan(self, tile_vals, plan):
        """Run the scan interpreter; returns (written tile indices, their
        values) for landing."""
        import jax
        import jax.numpy as jnp

        stores, tile_loc, classes, rows = plan
        n_flows_max = max((len(fs) for _, fs in rows), default=0)
        class_ids = np.asarray([cid for cid, _ in rows], np.int32)
        flow_idx = np.zeros((len(rows), max(n_flows_max, 1)), np.int32)
        for i, (_, fs) in enumerate(rows):
            flow_idx[i, :len(fs)] = fs

        sig = ("scan",
               tuple((fn, slots) for fn, slots in classes),
               tuple((len(ixs),) + tuple(np.shape(tile_vals[ixs[0]]))
                     + (str(getattr(tile_vals[ixs[0]], "dtype", "")),)
                     for ixs in stores),
               len(rows), flow_idx.shape[1], device_fingerprint())
        jitted, self.cache_hit = _program_cache.get_or_build(
            sig, lambda: jax.jit(self._build_scan(classes)))

        store_vals = tuple(jnp.stack([tile_vals[i] for i in ixs])
                           for ixs in stores)
        out_stores = jitted(store_vals, class_ids, flow_idx)
        written = self._written()
        vals = []
        for ix in written:
            sid, slot = tile_loc[ix]
            vals.append(out_stores[sid][slot])
        return written, vals

    def _build(self):
        """The single-device traced program: fold over a tile-value env."""
        ops = self.ops
        written = self._written()

        def program(tile_vals, arr_vals):
            env = list(tile_vals)
            GraphCapture._replay(ops, env.__getitem__, env.__setitem__,
                                 arr_vals)
            return tuple(env[i] for i in written)

        return program, written

    # ------------------------------------------------------------ execution
    def execute(self) -> None:
        if not self.ops:
            return
        import jax
        tile_vals = []
        for t in self._tiles:
            copy = t.data.newest_copy()
            if copy is None or copy.payload is None:
                output.fatal(f"graph capture: tile {t!r} has no data")
            v = copy.payload
            if isinstance(v, np.ndarray):
                # stage once and persist: the tile crosses to the backend a
                # single time across repeated executions (same discipline as
                # the cpu-hook payload persistence)
                v = jax.device_put(v)
                copy.payload = v
            tile_vals.append(v)
        arr_vals = [e[1] for _, spec in self.ops for e in spec
                    if e[0] == "array"]

        mode, plan = self.mode, None
        if mode == "auto":
            if len(self.ops) >= mca.get("capture_scan_threshold", 64):
                plan = self._scan_plan(tile_vals)
                if plan is None:
                    output.debug_verbose(
                        1, "capture", "auto: scan rejected ("
                        + (getattr(self, "_scan_reject", None) or "?")
                        + "); falling back to inline replay")
            mode = "scan" if plan is not None else "inline"
        elif mode == "scan":
            plan = self._scan_plan(tile_vals)
            if plan is None:
                # deterministic config error: consume the batch FIRST so
                # close()/fini() don't re-raise or hang on the open action
                self._clear_recording()
                output.fatal("scan capture rejected: "
                             + (getattr(self, "_scan_reject", None)
                                or "recording is not scannable"))
        self.last_mode = mode
        if mode == "scan":
            written, results = self._execute_scan(tile_vals, plan)
        else:
            sig = self._signature(tile_vals)

            def _build_jitted():
                import jax as _jax
                program, written = self._build()
                return (_jax.jit(program), written)

            jitted, self.cache_hit = _program_cache.get_or_build(
                sig, _build_jitted)
            fn, written = jitted
            results = fn(tuple(tile_vals), tuple(arr_vals))
        # land results exactly like task completions would (cpu-hook tail)
        from ..data.data import COHERENCY_OWNED
        for ix, val in zip(written, results):
            tile = self._tiles[ix]
            host = tile.data.get_copy(0)
            if host is None:
                tile.data.create_copy(0, val, COHERENCY_OWNED)
            else:
                host.payload = val
            tile.data.bump_version(0)
        self.executions += 1
        # consume: a later insert batch into the same pool starts a fresh
        # capture (wait() executes each batch exactly once)
        self._clear_recording()

    def mesh_hlo(self) -> str:
        """Compiled (post-GSPMD) HLO text of the last mesh execution — the
        sharding-quality introspection surface: collective ops and their
        shapes are visible here, so tests can assert communication volume
        scales with tile halos, not whole matrices."""
        if getattr(self, "_last_mesh_call", None) is None:
            output.fatal("mesh_hlo: no mesh execution recorded")
        jitted, args = self._last_mesh_call
        return jitted.lower(*args).compile().as_text()

    # ------------------------------------------------------- mesh execution
    def execute_mesh(self, mesh, axis_names=None) -> None:
        """Compile the captured DAG into ONE GSPMD program over a device
        mesh: collection tiles become slices of per-collection GLOBAL
        arrays sharded over the mesh, tile writes become
        dynamic_update_slice — XLA partitions the ops across devices and
        inserts the ICI transfers/collectives the dataflow implies. The
        whole distributed DAG is a single launch.

        v1 contract: collection-backed tiles must come from TiledMatrix
        collections with uniform full tiles, and every global dimension
        must divide by its mesh axis (checked; a failed validation
        DISCARDS the recorded batch — it must not silently fall back to a
        single-device execute at close()). Scratch (tile_new) tiles ride
        as replicated inputs. Results scatter back to the tile copies
        through one host assembly per written collection (on a real pod
        you would keep the globals resident — the compiled program is the
        deliverable here). Compiled programs are cached on the DAG shape
        + tile placement + mesh, like the single-device path.
        """
        if not self.ops:
            return
        import jax
        import numpy as np_mod
        from jax.sharding import NamedSharding, PartitionSpec
        from .dtd import WRITE

        try:
            axes = tuple(axis_names) if axis_names is not None \
                else tuple(mesh.axis_names)
            if len(axes) != 2:
                output.fatal(f"execute_mesh needs a 2D mesh, got axes {axes}")

            # classify tiles: collection-backed -> (dc, m, n); else local
            colls: Dict[str, Any] = {}
            placement: List[Tuple] = []    # ("c", name, m, n) | ("l", li)
            local_vals: List[Any] = []
            for t in self._tiles:
                dc = t.dc
                if dc is not None and hasattr(dc, "lnt") and hasattr(dc, "mb"):
                    if dc.lm % dc.mb or dc.ln % dc.nb:
                        output.fatal(f"execute_mesh: collection {dc.name} "
                                     f"has partial edge tiles")
                    colls.setdefault(dc.name, dc)
                    m, n = divmod(t.key[1], dc.lnt)
                    placement.append(("c", dc.name, m, n))
                else:
                    copy = t.data.newest_copy()
                    if copy is None or copy.payload is None:
                        output.fatal(f"execute_mesh: tile {t!r} has no data")
                    placement.append(("l", len(local_vals)))
                    local_vals.append(copy.payload)

            mx, my = (mesh.devices.shape[mesh.axis_names.index(a)]
                      for a in axes)
            for dc in colls.values():
                if dc.lm % mx or dc.ln % my:
                    output.fatal(f"execute_mesh: {dc.name} {dc.lm}x{dc.ln} "
                                 f"not divisible by mesh {mx}x{my}")
        except Exception:
            # a batch the mesh path rejected must not linger: close()/wait()
            # would otherwise execute it single-device behind the
            # caller's back
            self._clear_recording()
            raise

        coll_names = sorted(colls)
        sh = NamedSharding(mesh, PartitionSpec(*axes))
        globals_in = []
        for name in coll_names:
            dc = colls[name]
            dense = np_mod.zeros((dc.lm, dc.ln), dtype=dc.dtype)
            for m in range(dc.lmt):
                for n in range(dc.lnt):
                    if not dc.stored(m, n):
                        continue
                    c = dc.data_of(m, n).newest_copy()
                    if c is not None and c.payload is not None:
                        dense[m*dc.mb:(m+1)*dc.mb, n*dc.nb:(n+1)*dc.nb] = \
                            np_mod.asarray(c.payload)
            globals_in.append(jax.device_put(dense, sh))

        ops = self.ops
        coll_ix = {n: i for i, n in enumerate(coll_names)}
        written_cols = sorted({placement[e[1]][1] for _, spec in ops
                               for e in spec if e[0] == "flow"
                               and e[2] & WRITE and placement[e[1]][0] == "c"})
        written_locals = sorted({placement[e[1]][1] for _, spec in ops
                                 for e in spec if e[0] == "flow"
                                 and e[2] & WRITE and placement[e[1]][0] == "l"})
        mbnb = {n: (colls[n].mb, colls[n].nb) for n in coll_names}
        arr_vals = [e[1] for _, spec in ops for e in spec if e[0] == "array"]

        def build_mesh_program():
            def program(globs, locs, arrs):
                globs = list(globs)
                locs = list(locs)

                def read(ti):
                    kind = placement[ti]
                    if kind[0] == "l":
                        return locs[kind[1]]
                    _, name, m, n = kind
                    mb, nb = mbnb[name]
                    return jax.lax.slice(globs[coll_ix[name]],
                                         (m*mb, n*nb), ((m+1)*mb, (n+1)*nb))

                def write(ti, v):
                    kind = placement[ti]
                    if kind[0] == "l":
                        locs[kind[1]] = v
                        return
                    _, name, m, n = kind
                    mb, nb = mbnb[name]
                    gi = coll_ix[name]
                    globs[gi] = jax.lax.dynamic_update_slice(
                        globs[gi], v.astype(globs[gi].dtype), (m*mb, n*nb))

                GraphCapture._replay(ops, read, write, arrs)
                return (tuple(globs[coll_ix[n]] for n in written_cols),
                        tuple(locs[i] for i in written_locals))

            return jax.jit(
                program,
                in_shardings=(tuple(sh for _ in globals_in), None, None),
                out_shardings=(tuple(sh for _ in written_cols), None))

        # cache on DAG shape + tile placement + collection geometry + mesh:
        # re-running the same distributed DAG skips trace and GSPMD compile
        sig = ("mesh", self._signature(local_vals), tuple(placement),
               tuple((n, colls[n].lm, colls[n].ln, *mbnb[n])
                     for n in coll_names),
               tuple(mesh.devices.shape), tuple(mesh.axis_names), axes,
               tuple(d.id for d in mesh.devices.flat))
        jitted, self.cache_hit = _program_cache.get_or_build(
            sig, build_mesh_program)
        # kept for sharding-quality introspection (mesh_hlo): jax caches
        # the executable, so lowering these args again is trace-only cost
        self._last_mesh_call = (jitted, (tuple(globals_in),
                                         tuple(local_vals),
                                         tuple(arr_vals)))
        out_globs, out_locs = jitted(tuple(globals_in), tuple(local_vals),
                                     tuple(arr_vals))

        # scatter results back to tile copies (one host assembly per
        # written collection in v1)
        from ..data.data import COHERENCY_OWNED

        def land(tile, val):
            host = tile.data.get_copy(0)
            if host is None:
                tile.data.create_copy(0, val, COHERENCY_OWNED)
            else:
                host.payload = val
            tile.data.bump_version(0)

        dense_out = {n: np_mod.asarray(g)
                     for n, g in zip(written_cols, out_globs)}
        written_tiles = {e[1] for _, spec in ops for e in spec
                         if e[0] == "flow" and e[2] & WRITE}
        li = {v: i for i, v in enumerate(written_locals)}
        for ti in sorted(written_tiles):
            kind = placement[ti]
            tile = self._tiles[ti]
            if kind[0] == "l":
                land(tile, out_locs[li[kind[1]]])
            else:
                _, name, m, n = kind
                mb, nb = mbnb[name]
                land(tile, dense_out[name][m*mb:(m+1)*mb, n*nb:(n+1)*nb])
        self.executions += 1
        self._clear_recording()
