"""Whole-taskpool graph capture: one XLA executable per DTD DAG.

The TPU-first execution mode the reference cannot have: where PaRSEC must
dispatch every task through a driver call (and pays per-kernel launch
latency), a captured taskpool TRACES the entire insert_task sequence into a
single jitted program. DTD's sequential-consistency semantics make this
sound: insertion order is a valid serialization of the DAG, so replaying the
bodies in insertion order under `jax.jit` reconstructs the exact dataflow
graph as XLA value dependencies — XLA then re-parallelizes, fuses producers
into consumers, and runs the whole DAG as ONE dispatch.

What that buys on hardware:

* dispatch cost amortized from O(tasks) to O(1) — decisive when per-dispatch
  latency is high (remote chips) or tasks are small;
* cross-task fusion (a GEMM's epilogue fuses into the next task's prologue);
* whole-DAG compilation caching: re-running the same DAG shape (iterative
  solvers, benchmark reps) reuses the compiled executable.

Semantics and limits (checked, not assumed):

* single-rank only — a captured pool never leaves the chip;
* bodies must be jit-traceable (``jit=True`` inserts, jax/numpy-array args);
* execution happens at ``tp.wait()``; tile versions bump exactly as if the
  tasks had run through the scheduler, so collections read back normally.

Usage::

    tp = DTDTaskpool(ctx, "gemm", capture=True)
    insert_gemm_tasks(tp, A, B, C, batch_k=True)
    tp.wait()          # traces (first time) + executes the whole DAG
    tp.close()
"""

from __future__ import annotations

import collections
import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..utils import output

#: process-wide compiled-program cache: the same DAG shape (op sequence,
#: tile shapes/dtypes, scalar params) compiles exactly once. Keys hold the
#: body function OBJECTS (identity equality — two closures over different
#: constants must never share a program), so the cache is LRU-bounded:
#: lambda-per-call users pay a recompile past the bound instead of leaking
#: a compiled executable per capture.
_PROGRAM_CACHE_MAX = 64
_program_cache: "collections.OrderedDict[Any, Any]" = collections.OrderedDict()
_cache_lock = threading.Lock()


class GraphCapture:
    """Recorder + compiler for a captured DTD taskpool."""

    def __init__(self, tp) -> None:
        self.tp = tp
        #: per op: (fn, spec); spec entries are
        #: ("flow", tile_index, access) | ("scalar", value) | ("array", arr)
        self.ops: List[Tuple[Any, List[Tuple]]] = []
        self._tiles: List[Any] = []          # DTDTile, first-use order
        self._tile_ix: Dict[int, int] = {}   # id(tile) -> index
        self.cache_hit = False
        self.executions = 0

    # ------------------------------------------------------------ recording
    def record(self, fn, args: Sequence[Any], jit: bool, name: str) -> None:
        from .dtd import AFFINITY, DTDTile, RW
        if not jit:
            output.fatal(f"graph capture requires jit-traceable bodies "
                         f"(insert of {name or fn!r} passed jit=False)")
        spec: List[Tuple] = []
        for a in args:
            if isinstance(a, tuple) and len(a) == 2 and isinstance(a[0], DTDTile):
                tile, acc = a
                acc &= ~AFFINITY           # placement is moot on one chip
                spec.append(("flow", self._tile_index(tile), acc))
            elif isinstance(a, DTDTile):
                spec.append(("flow", self._tile_index(a), RW))
            elif isinstance(a, (int, float, np.number)):
                spec.append(("scalar", a))
            elif isinstance(a, np.ndarray) or hasattr(a, "dtype"):
                spec.append(("array", a))
            else:
                output.fatal(f"graph capture: argument {a!r} of "
                             f"{name or fn!r} is not traceable")
        self.ops.append((fn, spec))

    def _tile_index(self, tile) -> int:
        ix = self._tile_ix.get(id(tile))
        if ix is None:
            ix = len(self._tiles)
            self._tile_ix[id(tile)] = ix
            self._tiles.append(tile)
        return ix

    # ------------------------------------------------------------ compiling
    def _signature(self, tile_vals: List[Any]) -> Tuple:
        op_sig = []
        for fn, spec in self.ops:
            entries = []
            for e in spec:
                if e[0] == "flow":
                    entries.append(e)                      # (kind, ix, acc)
                elif e[0] == "scalar":
                    entries.append(("scalar", e[1]))       # baked into trace
                else:
                    a = e[1]
                    entries.append(("array", tuple(a.shape), str(a.dtype)))
            op_sig.append((fn, tuple(entries)))
        tiles_sig = tuple((tuple(np.shape(v)), str(getattr(v, "dtype", type(v))))
                          for v in tile_vals)
        return (tuple(op_sig), tiles_sig)

    def _build(self):
        """The traced program: fold the op list over a tile-value env.
        XLA recovers the DAG from value dependencies."""
        from .dtd import WRITE
        ops = self.ops
        written = sorted({e[1] for _, spec in ops for e in spec
                          if e[0] == "flow" and e[2] & WRITE})

        def program(tile_vals, arr_vals):
            env = list(tile_vals)
            ai = 0
            for fn, spec in ops:
                ins = []
                wixs = []
                for e in spec:
                    if e[0] == "flow":
                        ins.append(env[e[1]])
                        if e[2] & WRITE:
                            wixs.append(e[1])
                    elif e[0] == "scalar":
                        ins.append(e[1])
                    else:
                        ins.append(arr_vals[ai])
                        ai += 1
                outs = fn(*ins)
                if outs is None:
                    outs = ()
                elif not isinstance(outs, (tuple, list)):
                    outs = (outs,)
                for wi, out in zip(wixs, outs):
                    env[wi] = out
            return tuple(env[i] for i in written)

        return program, written

    # ------------------------------------------------------------ execution
    def execute(self) -> None:
        if not self.ops:
            return
        import jax
        tile_vals = []
        for t in self._tiles:
            copy = t.data.newest_copy()
            if copy is None or copy.payload is None:
                output.fatal(f"graph capture: tile {t!r} has no data")
            v = copy.payload
            if isinstance(v, np.ndarray):
                # stage once and persist: the tile crosses to the backend a
                # single time across repeated executions (same discipline as
                # the cpu-hook payload persistence)
                v = jax.device_put(v)
                copy.payload = v
            tile_vals.append(v)
        arr_vals = [e[1] for _, spec in self.ops for e in spec
                    if e[0] == "array"]

        sig = self._signature(tile_vals)
        with _cache_lock:
            jitted = _program_cache.get(sig)
            self.cache_hit = jitted is not None
            if jitted is None:
                program, written = self._build()
                jitted = (jax.jit(program), written)
                _program_cache[sig] = jitted
                while len(_program_cache) > _PROGRAM_CACHE_MAX:
                    _program_cache.popitem(last=False)
            else:
                _program_cache.move_to_end(sig)
        fn, written = jitted
        results = fn(tuple(tile_vals), tuple(arr_vals))
        # land results exactly like task completions would (cpu-hook tail)
        from ..data.data import COHERENCY_OWNED
        for ix, val in zip(written, results):
            tile = self._tiles[ix]
            host = tile.data.get_copy(0)
            if host is None:
                tile.data.create_copy(0, val, COHERENCY_OWNED)
            else:
                host.payload = val
            tile.data.bump_version(0)
        self.executions += 1
        # consume: a later insert batch into the same pool starts a fresh
        # capture (wait() executes each batch exactly once)
        self.ops = []
        self._tiles = []
        self._tile_ix = {}
