"""Region fusion + the persistent compiled-executable cache (ISSUE 12).

The 1810.09868 inversion: whole-region XLA compilation should BEAT
per-task dispatch wherever it applies. This module holds the pieces both
DSLs share:

* :func:`partition_regions` — the fusion pass over a flattened CSR task
  graph: identify *capturable* subgraphs (same-device, jittable bodies,
  no cross-rank edge — the caller encodes all of that in a per-task
  ``kind``) and group them into **convex regions**. Each region later
  collapses into ONE fused super-task whose body is a single jitted
  program replaying the region in a valid serialization order; the
  scheduler handles only the un-fusable seams.

* :class:`ExecCache` — the persistent compiled-program cache shared
  across pool instantiations, with hit/miss/evict counters exported
  through the unified registry (``capture.cache_{hits,misses,
  evictions}``). A second instantiation of the same DAG shape re-runs a
  warm executable with zero re-tracing — the repeated-DAG shape of
  steady-state serving traffic (the 2112.01075 schedule-reuse argument).

* :func:`device_fingerprint` — the device/mesh component of every
  executable-cache key (and of the compiler's flatten cache key): a
  cached program can never be replayed against a different device
  layout.

Soundness of the region partition (the condensed graph must stay a DAG —
a cycle between a region and a seam is a deadlock at runtime):

For each capturable kind ``k`` define the *seam depth*
``d_k(t) = [t is not kind k] + max(d_k(pred), default 0)`` over the
task DAG. ``d_k`` is monotone non-decreasing along every edge and
strictly increases across any non-``k`` node. A region is a connected
component (over direct edges) of kind-``k`` tasks with EQUAL ``d_k``.
Any path leaving such a region passes either through a non-``k`` node —
after which every downstream kind-``k`` task has depth > d, so the path
can never re-enter a depth-``d`` region — or through a same-kind,
same-depth task, which by definition of connectivity is in the SAME
region. Hence no condensed cycle. Splitting an oversized region into
chunks contiguous in a global topological order preserves convexity for
the same reason: every escape route is depth-increasing, and direct
same-kind edges only run forward in topo order.
"""

from __future__ import annotations

import collections
import threading
from typing import Any, Callable, Dict, Hashable, List, Optional, Sequence, Tuple

from ..utils import mca
from ..utils.counters import LaneStats

mca.register("region_fusion", True,
             "Fusion pass over the flattened CSR (ISSUE 12): capturable "
             "subgraphs (same-device jittable bodies, static shapes, no "
             "cross-rank edge) collapse into ONE fused super-task — a "
             "single jitted program replaying the region in a valid "
             "serialization order — and the scheduler handles only the "
             "un-fusable seams. Applies to eligible PTG data pools on "
             "the native lane and to deferred DTD capture windows. "
             "0 restores per-task dispatch everywhere", type=bool)
mca.register("region_fusion_min", 2,
             "Minimum region size worth fusing: capturable components "
             "smaller than this stay per-task (a 1-task 'region' is "
             "pure wrapper overhead)")
mca.register("region_fusion_max", 128,
             "Maximum tasks per fused region: larger regions split into "
             "topo-contiguous chunks. Bounds XLA program size — "
             "decompose-heavy bodies inlined N times compile "
             "superlinearly (the capture-inline pathology, "
             "docs/capture.md)")

#: unified-registry export (``capture.*`` — installed by
#: utils/counters.install_native_counters): the persistent executable
#: cache's engagement truth. ``cache_hits`` nonzero on the second
#: instantiation of the same DAG shape IS the warm-pool contract the
#: ci gate asserts.
CAPTURE_CACHE_STATS = LaneStats(cache_hits=0, cache_misses=0,
                                cache_evictions=0)


def device_fingerprint() -> Tuple:
    """The device component of every executable-cache key. Two processes
    (or two contexts) with different backend layouts must never share a
    compiled program; identical layouts should."""
    try:
        import jax
        devs = jax.devices()
        return (devs[0].platform, getattr(devs[0], "id", 0), len(devs))
    except Exception:  # noqa: BLE001 — no backend: still a valid key
        return ("nodev",)


class ExecCache:
    """LRU cache of compiled executables keyed by (class signature, tile
    shapes/dtypes, device/mesh fingerprint) — the caller builds the key;
    this class owns lifetime and the unified hit/miss/evict accounting.

    ``get_or_build`` holds the lock across the builder call (builders
    only construct the jitted callable — tracing/compilation happens
    lazily at first call), so two concurrent instantiations of the same
    shape share ONE program instead of racing to build two."""

    def __init__(self, cap: int = 64,
                 stats: Optional[Dict[str, int]] = None) -> None:
        self.cap = cap
        self.stats = CAPTURE_CACHE_STATS if stats is None else stats
        self._d: "collections.OrderedDict[Hashable, Any]" = \
            collections.OrderedDict()
        self._mu = threading.Lock()

    def get_or_build(self, key: Hashable,
                     builder: Callable[[], Any]) -> Tuple[Any, bool]:
        """Return ``(value, hit)``. ``key=None`` (uncacheable shape)
        builds fresh and counts a miss — the honest signal that this
        instantiation paid a trace."""
        if key is None:
            self.stats["cache_misses"] += 1
            return builder(), False
        with self._mu:
            v = self._d.get(key)
            if v is not None:
                self._d.move_to_end(key)
                self.stats["cache_hits"] += 1
                return v, True
            self.stats["cache_misses"] += 1
            v = self._d[key] = builder()
            while len(self._d) > self.cap:
                self._d.popitem(last=False)
                self.stats["cache_evictions"] += 1
            return v, False

    def __len__(self) -> int:
        with self._mu:
            return len(self._d)

    def clear(self) -> None:
        with self._mu:
            self._d.clear()


def adaptive_fusion_limits(classes: Sequence[Tuple[str, int, str]],
                           ) -> Tuple[set, int, int]:
    """Consumer (b) of the online cost model (ISSUE 18): size the fusion
    pass by MEASUREMENT instead of the static knobs.

    ``classes`` lists each capturable class as ``(name, shape_bucket,
    device_key)`` ('cpu' or 'tpu' — the fused flavor is looked up as
    ``<key>_fused``). Returns ``(declined, min_size, max_size)``:

    * ``declined`` — class indices to UN-fuse: the model has measured
      both flavors and the fused per-task cost (which prices in the
      in-dispatch re-trace a shape-churning workload pays N-bodies-wide
      per region) meets or exceeds the unfused per-task dispatch cost —
      fusion's premise ("dispatch overhead exceeds the region's marginal
      compiled-dispatch cost") measurably fails for that class.
    * ``max_size`` — the measured break-even region cap: the largest
      power-of-two band whose per-member trace cost (the
      ``__region_trace__`` pseudo-class, fed by the compiler timing each
      region program's first call), amortized by the executable cache's
      measured reuse ratio, stays below the measured per-task dispatch
      saving. Replaces the static ``region_fusion_max`` ceiling — the
      static knob stays the hard upper bound (the compile-blowup escape
      hatch is not negotiable), the model only ever splits SOONER.

    ``min_size`` stays the static knob: the fuse-at-all break-even is
    per-class (handled by ``declined``), not size-dependent once the
    batch amortization is in effect. With the model disabled or cold
    this degrades to exactly the static limits — instantiation never
    blocks on measurement."""
    min_size = int(mca.get("region_fusion_min", 2))
    max_size = int(mca.get("region_fusion_max", 128))
    declined: set = set()
    from ..core import costmodel as _cm     # lazy: utils-only module deps
    if not (_cm.enabled() and mca.get("costmodel_fusion", True)):
        return declined, min_size, max_size
    m = _cm.model
    saving = None                # measured per-task dispatch cost avoided
    for ci, (name, bucket, dev) in enumerate(classes):
        if not m.measured(name, bucket, dev):
            continue
        unfused = m.cost(name, bucket, dev)
        if m.measured(name, bucket, dev + "_fused") and \
                m.cost(name, bucket, dev + "_fused") >= unfused:
            declined.add(ci)
            _cm.COSTMODEL_STATS["fusion_declined"] += 1
            continue
        if saving is None or unfused < saving:
            saving = unfused     # conservative: the cheapest class bounds
                                 # what fusion can save per member
    sized = False
    if saving is not None and saving > 0:
        # the break-even comparison RAN on real measurements — a model-
        # derived sizing decision even when it confirms the static cap
        sized = True
        hits = CAPTURE_CACHE_STATS["cache_hits"]
        total = hits + CAPTURE_CACHE_STATS["cache_misses"]
        reuse = (hits / total) if total else 0.0
        cap = max_size
        while cap > min_size:
            per_member = m.region_trace_ns("cpu", cap)
            if per_member is None or per_member * (1.0 - reuse) <= saving:
                break            # unmeasured band: trust the static knob
            # halve only when the model has MEASURED the smaller band
            # cheaper per member: trace cost has a fixed per-program
            # floor, so splitting a region doubles the programs and can
            # RAISE total trace time — without a measured win the split
            # is speculation, and a speculative split re-plans the pool
            # (new flatten key → every region re-traces cold), the exact
            # oscillation this guard exists to prevent
            band = max(0, (cap // 2).bit_length() - 1)
            if not m.measured(_cm.REGION_TRACE, band, "cpu"):
                break
            half = m.region_trace_ns("cpu", cap // 2)
            if half is None or half >= per_member:
                break
            cap //= 2
        if cap != max_size:
            max_size = max(cap, min_size)
    if declined or sized:
        _cm.COSTMODEL_STATS["fusion_sized"] += 1
    return declined, min_size, max_size


def topo_order(n: int, off: Sequence[int], succs: Sequence[int]) -> List[int]:
    """Kahn topological order of a CSR DAG (the flatten output is a DAG
    by construction: indeg == goals was validated)."""
    indeg = [0] * n
    for s in succs:
        indeg[s] += 1
    q = collections.deque(i for i in range(n) if indeg[i] == 0)
    order: List[int] = []
    while q:
        u = q.popleft()
        order.append(u)
        for k in range(off[u], off[u + 1]):
            s = succs[k]
            indeg[s] -= 1
            if indeg[s] == 0:
                q.append(s)
    return order


def partition_regions(n: int, off: Sequence[int], succs: Sequence[int],
                      kind: Sequence[Optional[Hashable]],
                      min_size: int = 2, max_size: int = 128,
                      order: Optional[List[int]] = None,
                      ) -> List[List[int]]:
    """The fusion pass: group capturable tasks into convex regions.

    ``kind[t]`` is None for a seam (un-fusable) task, else a hashable
    capturability kind ('cpu' / 'dev' — tasks of different kinds never
    share a region: a region runs as ONE program on ONE dispatch path).
    Returns regions as member-id lists in topological order; every
    region has ``min_size <= len <= max_size`` and the condensed graph
    (regions + seams) is acyclic (see the module docstring's argument).
    """
    if n == 0:
        return []
    order = topo_order(n, off, succs) if order is None else order
    kinds_present = {k for k in kind if k is not None}
    if not kinds_present:
        return []
    topo_ix = [0] * n
    for ix, t in enumerate(order):
        topo_ix[t] = ix
    # per-kind seam depth, one topo sweep per kind (<= 2 kinds in
    # practice: 'cpu' and 'dev')
    depth: Dict[Hashable, List[int]] = {}
    for k in kinds_present:
        d = [0] * n
        for u in order:
            base = d[u] + (0 if kind[u] == k else 1)
            for e in range(off[u], off[u + 1]):
                s = succs[e]
                if base > d[s]:
                    d[s] = base
        depth[k] = d
    # union-find over direct same-kind same-depth edges
    parent = list(range(n))

    def find(x: int) -> int:
        while parent[x] != x:
            parent[x] = parent[parent[x]]
            x = parent[x]
        return x

    for u in range(n):
        ku = kind[u]
        if ku is None:
            continue
        du = depth[ku][u]
        for e in range(off[u], off[u + 1]):
            s = succs[e]
            if kind[s] == ku and depth[ku][s] == du:
                ru, rs = find(u), find(s)
                if ru != rs:
                    parent[rs] = ru
    groups: Dict[int, List[int]] = {}
    for t in order:                      # members land in topo order
        if kind[t] is None:
            continue
        groups.setdefault(find(t), []).append(t)
    regions: List[List[int]] = []
    for members in groups.values():
        if len(members) < min_size:
            continue
        # topo-contiguous chunking keeps each chunk convex; a tail chunk
        # below min_size folds into its predecessor only while the
        # combined region respects max_size (the knob is a HARD bound on
        # XLA program size — the compile-blowup escape hatch), otherwise
        # the tail stays per-task
        for lo in range(0, len(members), max_size):
            chunk = members[lo:lo + max_size]
            if len(chunk) >= min_size:
                regions.append(chunk)
            elif regions and regions[-1][-1] == members[lo - 1] and \
                    len(regions[-1]) + len(chunk) <= max_size:
                regions[-1].extend(chunk)
    # deterministic output order (instantiations must agree with the
    # cached plan): sort by first member's topo position
    regions.sort(key=lambda m: topo_ix[m[0]])
    return regions
