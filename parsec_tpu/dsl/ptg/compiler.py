"""PTG compiler: ProgramSpec → runtime task classes.

Stands where the reference's jdf2c.c code generator stands (SURVEY §2.5:
structure/symbols/flows/deps/startup/init/ctor/keys/hooks/data_lookup/
release_deps/iterate_successors), but instead of emitting C against the
task-class contract it *builds* :class:`parsec_tpu.core.task.TaskClass`
objects directly:

* parameter ranges → the startup enumerator counting the task space and
  seeding ready tasks (the generated startup/internal_init, jdf2c.c:3047,3455)
* guarded in-deps → ``prepare_input`` (the generated data_lookup, jdf2c.c:45)
  + per-task dependency goals (count mode — the DYNAMIC_HASH_TABLE dep mode)
* guarded out-deps → ``Dep`` descriptors consumed by the generic
  release-deps engine (iterate_successors, jdf2c.c:47)
* BODY blocks → chores: the body text becomes a Python function of
  (params..., flows...) returning its written flows, jitted once per class —
  a PTG body IS an XLA executable on TPU (the BODY[type=TPU] goal of
  BASELINE.json)
* memory out-deps → write-back to the data collection at completion

Python expressions are compiled once at class-build time and evaluated
against task locals + user globals.
"""

from __future__ import annotations

import textwrap
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ...core.context import Context
from ...core.datarepo import DataRepo
from ...core.task import (
    Chore, DEV_CPU, DEV_TPU, Dep, Flow, FLOW_ACCESS_CTL, FLOW_ACCESS_READ,
    FLOW_ACCESS_RW, FLOW_ACCESS_WRITE, HOOK_DONE, Task, TaskClass, Taskpool,
)
from ...core.futures import DataCopyFuture
from ...data.data import COHERENCY_OWNED, DataCopy
from ...data.reshape import NamedDatatype, default_datatype
from ...device.tpu import make_tpu_hook
from ...utils import mca, output
from . import parser as P

mca.register("ptg_agglomerate", True,
             "Execute statically-independent flowless PTG classes "
             "as one fused sweep at startup (no per-task "
             "scheduling cycle)", type=bool)
mca.register("ptg_native_exec", True,
             "Drain eligible PTG taskpools (CTL and DATA-flow classes "
             "with single ungated CPU chores, incl. priorities) through "
             "the native execution lane (native/src/ptexec.cpp): the "
             "full dependency FSM — dep decrement, ready heap, data-slot "
             "retire — runs batched in C with the GIL dropped. "
             "Ineligible pools (named datatypes/reshapes, distributed "
             "ranks, PINS, multi-chore classes) fall back to the Python "
             "FSM (docs/native_exec.md)",
             type=bool)

#: lane-engagement accounting (consumed by ci.sh's perf smoke gate and the
#: bench — through the LaneStats snapshot()/delta() helpers, not raw key
#: pokes). ``pools_fallback`` counts pools whose classes were ALL eligible
#: yet the lane still declined (flatten refusal, native module missing) —
#: the silent perf regression no throughput number reliably catches on a
#: noisy host. ``pools_ineligible`` counts pools declined by DESIGN
#: (ineligible class features or pool-level gates: distributed/
#: pins-paranoid/debug-paranoid/mca-off) — expected fallbacks, never a CI
#: failure. utils/counters.install_native_counters exports these under
#: ``ptexec.*`` for live_view and the SDE-style snapshot
from ...utils.counters import LaneStats as _LaneStats
from ..fusion import (
    ExecCache, adaptive_fusion_limits, device_fingerprint, partition_regions,
)

PTEXEC_STATS = _LaneStats(pools_engaged=0, tasks_engaged=0,
                          pools_fallback=0, pools_ineligible=0,
                          pools_device=0, tasks_device=0,
                          # region fusion (ISSUE 12): original tasks
                          # collapsed into fused super-tasks vs tasks the
                          # scheduler still handles per-task (the seams)
                          fused_regions=0, fused_tasks=0, seam_tasks=0)

_ACCESS_MAP = {
    P.FLOW_READ: FLOW_ACCESS_READ,
    P.FLOW_WRITE: FLOW_ACCESS_WRITE,
    P.FLOW_RW: FLOW_ACCESS_RW,
    P.FLOW_CTL: FLOW_ACCESS_CTL,
}


def _payload_of(v: Any) -> Any:
    return v.payload if isinstance(v, DataCopy) else v


class _Expr:
    """One compiled Python expression evaluated against task locals."""

    __slots__ = ("code", "src")
    is_range = False

    def __init__(self, src: str) -> None:
        self.src = src = src.strip()
        try:
            self.code = compile(src, f"<ptg:{src}>", "eval")
        except SyntaxError as e:
            raise P.PTGSyntaxError(f"bad expression {src!r}: {e}") from e

    def __call__(self, env: Dict[str, Any]) -> Any:
        return eval(self.code, env)  # noqa: S307 - the DSL is code by design

    def values(self, env: Dict[str, Any]) -> List[int]:
        return [int(self(env))]


class _RangeExpr:
    """A JDF range endpoint index ``lo .. hi`` — broadcast/gather fan-out
    (e.g. ``-> Y WORK(0 .. W-1)`` multicasts one output to many tasks)."""

    __slots__ = ("lo", "hi")
    is_range = True

    def __init__(self, lo: str, hi: str) -> None:
        self.lo = _Expr(lo)
        self.hi = _Expr(hi)

    def values(self, env: Dict[str, Any]) -> List[int]:
        return list(range(int(self.lo(env)), int(self.hi(env)) + 1))


def _index_expr(src: str):
    # top-level '..' only (not inside parens/brackets)
    depth = 0
    for i, c in enumerate(src):
        if c in "([":
            depth += 1
        elif c in ")]":
            depth -= 1
        elif c == "." and depth == 0 and src[i:i+2] == ".." and src[i:i+3] != "...":
            return _RangeExpr(src[:i], src[i+2:])
    return _Expr(src)


def _timed_region_program(fn, n_members: int):
    """Wrap a jitted region program so its FIRST call — the one paying
    the XLA trace+compile — feeds the cost model's ``__region_trace__``
    pseudo-class (per-member cost by region-size band; ISSUE 18). The
    wrapper, not the bare jit, is what the executable cache stores: a
    warm cache hit reuses it with the first call already burned, so only
    real traces are ever observed. Steady-state calls pay one dict-free
    boolean check."""
    state = [True]

    def call(ev):
        if state[0]:
            state[0] = False
            t0 = time.perf_counter_ns()
            out = fn(ev)
            from ...core.costmodel import model
            model.note_region_trace("cpu", n_members,
                                    time.perf_counter_ns() - t0)
            return out
        return fn(ev)
    return call


def _mk_region_program(rp: Dict[str, Any], fns, written_by_class):
    """The fused super-task's body (ISSUE 12): ONE traceable program
    replaying the region's members in serialization order (topo order of
    the member subgraph — a valid serialization, the DTD-capture
    soundness argument applied to a PTG region). Internal dataflow rides
    a trace-time slot env (XLA recovers the DAG from the value
    dependencies and re-fuses across task boundaries); member memory
    WRITES feed later members' memory READS of the same (collection,
    index) through a trace-time mem env, matching the per-task path's
    release-edge ordering. Returns (externally-consumed slot values,
    member write-back values in emission order). Pure w.r.t. its inputs
    — safe to jit once and reuse across pool instantiations."""
    steps, out_slots = rp["steps"], rp["out_slots"]

    def region_program(ext_vals):
        env: Dict[int, Any] = {}
        menv: Dict[Tuple, Any] = {}
        wb_vals: List[Any] = []
        for ci, key, srcs, base, nd, wbs in steps:
            vals: List[Any] = []
            for kk, v in srcs:
                if kk == "int":
                    vals.append(env[v])
                elif kk == "ext":
                    vals.append(ext_vals[v])
                elif kk == "intm":
                    vals.append(menv[v])
                else:                      # "none": NEW/no input
                    vals.append(None)
            fn = fns[ci]
            if fn is not None:
                outs = fn(*key, *vals)
                for oj, dj in enumerate(written_by_class[ci]):
                    vals[dj] = outs[oj]
            for dj in range(nd):
                env[base + dj] = vals[dj]
            for dj, mk in wbs:
                menv[mk] = vals[dj]
                wb_vals.append(vals[dj])
        return (tuple(env[s] for s in out_slots), tuple(wb_vals))
    return region_program


class PTGTaskpool(Taskpool):
    """A taskpool instantiated from a PTG program."""

    def __init__(self, program: "PTGProgram", ctx: Context,
                 globals_: Dict[str, Any],
                 collections: Dict[str, Any],
                 name: Optional[str] = None,
                 datatypes: Optional[Dict[str, NamedDatatype]] = None) -> None:
        super().__init__(name or program.spec.name)
        self.program = program
        self.ctx = ctx
        # named dep datatypes (the arenas_datatypes table of the generated
        # taskpool, ref parsec_internal.h:42-47); DEFAULT is the identity
        self.datatypes: Dict[str, NamedDatatype] = {"DEFAULT": default_datatype()}
        self.datatypes.update(datatypes or {})
        #: (id(source payload), dtt name) -> DataCopyFuture — the reshape
        #: promise table: every consumer of (copy, datatype) shares ONE
        #: conversion (ref: parsec_reshape.c repo entries;
        #: input_dep_single_copy_reshape.jdf)
        self._typed_cache: Dict[Tuple[int, str], DataCopyFuture] = {}
        self._typed_lock = threading.Lock()
        #: compiled out-dep tables per (producer class, flow) for the
        #: guard-exact producer-datatype lookup
        self._odt_cache: Dict[Tuple[str, str], List] = {}
        self.env_base: Dict[str, Any] = {"__builtins__": {}}
        self.env_base.update({
            "min": min, "max": max, "abs": abs, "range": range, "len": len,
            "int": int, "divmod": divmod,
        })
        prologue_names: Dict[str, Any] = {}
        if program.spec.prologue:
            # the '%{...%}' host-language escape (jdf2c.c:54): full Python,
            # executed once per instantiation; its definitions become
            # program globals visible to ranges, guards, and bodies
            pns: Dict[str, Any] = {"np": np}
            try:
                exec(compile(program.spec.prologue,  # noqa: S102
                             f"<ptg-prologue:{program.spec.name}>", "exec"),
                     pns)
            except Exception as e:
                output.fatal(f"PTG taskpool {self.name}: prologue failed: {e}")
            prologue_names = {k: v for k, v in pns.items()
                              if not k.startswith("__") and k != "np"}
            self.env_base.update(prologue_names)
        self.env_base.update(globals_)
        self.collections = collections
        missing = [g for g in program.spec.globals
                   if g not in globals_ and g not in collections
                   and g not in prologue_names]
        if missing:
            output.fatal(f"PTG taskpool {self.name}: missing globals {missing}")
        #: (tc_name, pkey, flow_index) -> payload shipped from a remote
        #: producer (consumed by prepare_input)
        self._ptg_received: Dict[Tuple, Any] = {}
        self._ptg_lock = threading.Lock()
        #: native execution lane state (set by _startup when eligible) and
        #: the decline reason ("ineligible" | "fallback" | None = engaged)
        self._ptexec_state: Optional[Dict[str, Any]] = None
        self._ptexec_refusal: Optional[str] = None
        self._build()
        if ctx.comm is not None and ctx.nb_ranks > 1:
            # distributed PTG: global termination + name-keyed routing
            ctx.comm.fourcounter.monitor_taskpool(self)
            ctx.comm.register_taskpool(self)

    # ------------------------------------------------------------------ build
    def _build(self) -> None:
        spec = self.program.spec
        self._classes: Dict[str, TaskClass] = {}
        # pass 1: shells
        for tcs in spec.task_classes:
            tc = TaskClass(tcs.name, nb_locals=len(tcs.params))
            tc.count_mode = True
            for fs in tcs.flows:
                tc.add_flow(Flow(fs.name, _ACCESS_MAP[fs.access]))
            tc.make_key = (lambda params: (
                lambda tp, loc: tuple(loc[p] for p in params)
            ))(tcs.params)
            # the wire always carries the canonical parameter tuple, even
            # when make_key_fn customizes the local hash key (the receiving
            # rank re-derives locals from it)
            tc._ptg_canonical_key = (lambda params: (
                lambda task: tuple(task.locals[p] for p in params)
            ))(tcs.params)
            self.add_task_class(tc)
            self.repos[tc.task_class_id] = DataRepo(tc.nb_flows, tcs.name)
            self._classes[tcs.name] = tc
        # pass 2: deps, goals, hooks
        for tcs in spec.task_classes:
            self._build_class(tcs, self._classes[tcs.name])
        self.startup_hook = self._startup

    def _env(self, locals_: Dict[str, int]) -> Dict[str, Any]:
        env = dict(self.env_base)
        env.update(locals_)
        return env

    def _build_class(self, tcs: P.TaskClassSpec, tc: TaskClass) -> None:
        spec = self.program.spec
        # ranges
        ranges = [(r.param, _Expr(r.lo_expr), _Expr(r.hi_expr), _Expr(r.step_expr))
                  for r in tcs.ranges]
        # order ranges by parameter declaration order
        order = {p: i for i, p in enumerate(tcs.params)}
        ranges.sort(key=lambda r: order[r[0]])
        tc._ptg_ranges = ranges
        tc._ptg_spec = tcs
        # header property block (ref: udf.jdf user-defined functions):
        # names resolve against the taskpool globals at instantiate time
        mk_fn = self._resolve_callable(tcs, "make_key_fn",
                                       tcs.header_props.get("make_key_fn"))
        if mk_fn is not None:
            # user-defined task key (ref: udf.jdf ud_make_key): fn(tp,
            # locals) -> hashable key used by the dep repo/hash tables
            tc.make_key = mk_fn
        te_fn = self._resolve_callable(tcs, "time_estimate",
                                       tcs.header_props.get("time_estimate"))
        if te_fn is not None:
            # feeds best-device selection (ref: parsec_internal.h:431-458
            # time_estimate; consumed by DeviceRegistry.select_best_device)
            tc.time_estimate = te_fn
        tc._ptg_startup_fn = self._resolve_callable(
            tcs, "startup_fn", tcs.header_props.get("startup_fn"))

        if tcs.priority_expr:
            prio = _Expr(tcs.priority_expr)
            tc.properties["priority"] = lambda loc, _p=prio: int(_p(self._env(loc)))
        if tcs.affinity is not None:
            aff_name = tcs.affinity.name
            aff_exprs = [_Expr(e) for e in tcs.affinity.index_exprs]
            def affinity_rank(loc, _n=aff_name, _e=aff_exprs):
                dc = self.collections.get(_n)
                if dc is None:
                    return 0
                env = self._env(loc)
                return dc.rank_of(*[ex(env) for ex in _e])
            tc._ptg_rank_of = affinity_rank
        else:
            tc._ptg_rank_of = lambda loc: 0

        # in-deps: per flow, ordered guarded alternatives
        in_specs: List[List[Tuple]] = []
        for fs in tcs.flows:
            alts = []
            for d in fs.deps:
                if d.direction != "in":
                    continue
                guard = _Expr(d.guard) if d.guard else None
                alts.append((guard, self._mk_ep(d.endpoint, d.dtt)))
                if d.else_endpoint is not None:
                    alts.append(("else", self._mk_ep(d.else_endpoint, d.dtt)))
            in_specs.append(alts)
        tc._ptg_in_specs = in_specs

        def active_in(alts: List[Tuple], env: Dict[str, Any]):
            taken = False
            for guard, ep in alts:
                if guard is None:
                    return ep
                if guard == "else":
                    if not taken:
                        return ep
                    continue
                taken = bool(guard(env))
                if taken:
                    return ep
            return None

        def goal_fn(loc: Dict[str, int]) -> int:
            env = self._env(loc)
            goal = 0
            for alts in in_specs:
                ep = active_in(alts, env)
                if ep is not None and ep["kind"] == "task":
                    n = 1
                    for ex in ep["exprs"]:
                        if ex.is_range:
                            n *= len(ex.values(env))
                    goal += n
            return goal

        tc.dependencies_goal_fn = goal_fn
        tc._ptg_active_in = active_in
        for fs, alts in zip(tcs.flows, in_specs):
            if fs.access == P.FLOW_CTL:
                continue
            for _guard, ep in alts:
                if ep and ep["kind"] == "task" and \
                        any(ex.is_range for ex in ep["exprs"]):
                    raise P.PTGSyntaxError(
                        f"{tcs.name}.{fs.name}: range gather is only valid "
                        f"on CTL flows (a data flow has exactly one input)")

        # out-deps -> generic-engine Dep descriptors
        for fi, fs in enumerate(tcs.flows):
            flow = tc.flows[fi]
            for d in fs.deps:
                if d.direction != "out":
                    continue
                self._add_out_dep(tc, flow, d.guard, d.endpoint, dtt=d.dtt,
                                  dtt_remote=d.dtt_remote)
                if d.else_endpoint is not None:
                    self._add_out_dep(tc, flow, d.guard, d.else_endpoint,
                                      negate=True, dtt=d.dtt,
                                      dtt_remote=d.dtt_remote)

        # hooks — flowless AND CTL-only classes (the EP/control shapes)
        # skip the data prepare hook entirely instead of paying per-task
        # env construction for flows that carry no data (the generic
        # prepare's CTL skip is a cheap loop; this one built an env first)
        has_data_flows = any(not (f.access & FLOW_ACCESS_CTL)
                             for f in tc.flows)
        tc.prepare_input = self._mk_prepare_input(tc) if has_data_flows \
            else None
        if any(getattr(f, "_ptg_mem_out", None) for f in tc.flows):
            tc.complete_execution = self._mk_complete(tc)
        nb_bodies = 0
        for body in tcs.bodies:
            fn = self._compile_body(tcs, body)
            if nb_bodies == 0:
                tc._ptg_body_fn = fn    # cross-DSL replay (pins ptg_to_dtd)
            # [evaluate = fn]: per-incarnation gate (ref: udf.jdf evaluate
            # properties selecting the chore); fn(stream, task) -> HOOK_*
            evaluate = self._resolve_callable(tcs, "evaluate", body.evaluate)
            if body.device == "TPU":
                tc.add_chore(Chore(DEV_TPU, make_tpu_hook(
                    self._mk_tpu_submit(tc, fn)), evaluate=evaluate))
                # TPU bodies also serve as host chores through the same
                # jitted function (degrades to the CPU backend off-pod)
                tc.add_chore(Chore(DEV_CPU, self._mk_cpu_hook(tc, fn),
                                   evaluate=evaluate))
            else:
                tc.add_chore(Chore(DEV_CPU, self._mk_cpu_hook(tc, fn),
                                   evaluate=evaluate))
            nb_bodies += 1

    def _resolve_callable(self, tcs: P.TaskClassSpec, prop: str,
                          name: Optional[str]):
        """Resolve a user-function property name against the taskpool
        globals; fatal when it does not name a callable."""
        if name is None:
            return None
        fn = self.env_base.get(name)
        if not callable(fn):
            output.fatal(f"{tcs.name}: property {prop}={name!r} does not "
                         f"name a callable in the taskpool globals")
        return fn

    def _mk_ep(self, ep: Optional[P.Endpoint],
               dtt: Optional[str] = None) -> Optional[Dict[str, Any]]:
        if ep is None:
            return None
        return {
            "kind": ep.kind,
            "name": ep.name,
            "flow": ep.flow,
            "exprs": [_index_expr(e) for e in ep.index_exprs],
            "dtt": dtt,
        }

    # ------------------------------------------------------------- datatypes
    def _dtt(self, name: Optional[str]) -> Optional[NamedDatatype]:
        if name is None:
            return None
        d = self.datatypes.get(name)
        if d is None:
            output.fatal(f"PTG taskpool {self.name}: dep references unknown "
                         f"datatype {name!r} (registered: "
                         f"{sorted(self.datatypes)})")
        return d

    def _typed_payload(self, value: Any, dtt: Optional[NamedDatatype]) -> Any:
        """Reshape-promise path (ref: parsec_get_copy_reshape_from_dep,
        parsec_internal.h:688-696): the conversion runs lazily, ONCE, and is
        shared by every consumer of (source copy, datatype). Identity
        datatypes return the original untouched (avoidable_reshape.jdf)."""
        if dtt is None or dtt.identity:
            return value
        payload = _payload_of(value)
        key = (id(payload), dtt.name)
        with self._typed_lock:
            fut = self._typed_cache.get(key)
            if fut is None:
                src = value if isinstance(value, DataCopy) \
                    else DataCopy(None, 0, payload)
                fut = DataCopyFuture(src, dtt, lambda c, d: d.convert(c))
                self._typed_cache[key] = fut
        return fut.request()

    def _out_dep_table(self, peer_name: str, peer_flow: str) -> List:
        """Compiled (guard, [(which, class, flow, index_exprs)], dtt, wire)
        rows for a producer flow's out-deps (compiled once per edge)."""
        key = (peer_name, peer_flow)
        tbl = self._odt_cache.get(key)
        if tbl is None:
            tbl = []
            pf = self.program.spec.task_class(peer_name).flow(peer_flow)
            for d in (pf.deps if pf is not None else []):
                if d.direction != "out":
                    continue
                g = _Expr(d.guard) if d.guard else None
                eps = {}
                for which, ep in (("then", d.endpoint),
                                  ("else", d.else_endpoint)):
                    if ep is not None and ep.kind == "task":
                        eps[which] = (ep.name, ep.flow,
                                      [_index_expr(e) for e in ep.index_exprs])
                wire = d.dtt_remote if d.dtt_remote is not None else d.dtt
                tbl.append((g, eps, d.dtt, wire))
            self._odt_cache[key] = tbl
        return tbl

    def _producer_out_dtt(self, peer_name: str, peer_flow: str,
                          my_class: str, my_flow: str,
                          plocals: Dict[str, int],
                          my_key: Tuple[int, ...]
                          ) -> Tuple[Optional[str], Optional[str]]:
        """(local [type], wire type) the producer declared on the out-dep
        that ACTUALLY feeds this task — guards evaluated under the
        producer's locals and the fan-out index set checked against my key
        (a flow may have several typed edges to the same class/flow behind
        different guards)."""
        env = self._env(plocals)
        import itertools
        for g, eps, dtt, wire in self._out_dep_table(peer_name, peer_flow):
            # guard/index exceptions propagate: the sender side evaluates
            # the same expressions (dep.cond / target_locals) and lets them
            # raise, and the two ends of a remote edge must agree
            which = "then"
            if g is not None:
                which = "then" if bool(g(env)) else "else"
            ep = eps.get(which)
            if ep is None or ep[0] != my_class or ep[1] != my_flow:
                continue
            axes = [ex.values(env) for ex in ep[2]]
            if tuple(my_key) not in set(itertools.product(*axes)):
                continue
            return dtt, wire
        return None, None

    def _add_out_dep(self, tc: TaskClass, flow: Flow, guard: Optional[str],
                     ep: P.Endpoint, negate: bool = False,
                     dtt: Optional[str] = None,
                     dtt_remote: Optional[str] = None) -> None:
        gexpr = _Expr(guard) if guard else None

        def cond(loc, _g=gexpr, _n=negate):
            if _g is None:
                return True
            v = bool(_g(self._env(loc)))
            return (not v) if _n else v

        if ep.kind == "task":
            peer_tc = self._classes[ep.name]
            peer_spec = self.program.spec.task_class(ep.name)
            peer_flow_idx = next(i for i, f in enumerate(peer_spec.flows)
                                 if f.name == ep.flow)
            exprs = [_index_expr(e) for e in ep.index_exprs]

            def target_locals(loc, _e=exprs, _params=tuple(peer_spec.params)):
                env = self._env(loc)
                import itertools
                axes = [ex.values(env) for ex in _e]
                return [dict(zip(_params, combo))
                        for combo in itertools.product(*axes)]

            dep = Dep(
                task_class=peer_tc, flow_index=peer_flow_idx,
                dep_index=len(flow.deps_out), cond=cond,
                target_locals=target_locals,
                datatype=dtt)        # named datatype (local reshape)
            # [type_remote] overrides the wire datatype only — local
            # successors keep the original copy (local_no_reshape.jdf)
            dep.wire_datatype = dtt_remote if dtt_remote is not None else dtt
            flow.deps_out.append(dep)
        elif ep.kind == "memory":
            exprs = [_Expr(e) for e in ep.index_exprs]
            flow._ptg_mem_out = getattr(flow, "_ptg_mem_out", [])
            flow._ptg_mem_out.append((cond, ep.name, exprs, dtt))
        # 'null' endpoints: data is dropped

    # ------------------------------------------------------------------ hooks
    def _mk_prepare_input(self, tc: TaskClass):
        my_class = tc._ptg_spec.name
        my_flows = [f.name for f in tc._ptg_spec.flows]

        def prepare_input(stream, task: Task) -> int:
            env = self._env(task.locals)
            # datatype resolution always compares CANONICAL parameter
            # tuples, independent of any user make_key_fn hash key
            canonical_key = tc._ptg_canonical_key(task)
            for fi, flow in enumerate(tc.flows):
                if flow.access & FLOW_ACCESS_CTL:
                    # control deps carry no data: their only job (the
                    # dependency count) was done at the producer's release
                    continue
                alts = tc._ptg_in_specs[fi]
                ep = tc._ptg_active_in(alts, env)
                if ep is None:
                    continue
                slot = task.data[fi]
                in_dtt = self._dtt(ep.get("dtt"))
                if ep["kind"] == "memory":
                    dc = self.collections.get(ep["name"])
                    if dc is None:
                        output.fatal(f"unknown collection {ep['name']!r}")
                    data = dc.data_of(*[ex(env) for ex in ep["exprs"]])
                    copy = data.newest_copy()
                    if in_dtt is not None and not in_dtt.identity:
                        # read-reshape: a NEW typed datacopy, shared by all
                        # consumers of (copy, datatype) via the promise table
                        slot.data_in = self._typed_payload(copy, in_dtt)
                    else:
                        # unattached wrapper: body outputs never mutate the
                        # collection implicitly (write-back = explicit out-deps)
                        slot.data_in = DataCopy(None, 0, _payload_of(copy))
                elif ep["kind"] == "task":
                    peer = self._classes[ep["name"]]
                    peer_spec = self.program.spec.task_class(ep["name"])
                    pkey = tuple(ex.values(env)[0] for ex in ep["exprs"])
                    pf_idx = next(i for i, f in enumerate(peer_spec.flows)
                                  if f.name == ep["flow"])
                    plocals = dict(zip(peer_spec.params, pkey))
                    out_dtt_name, wire_dtt_name = self._producer_out_dtt(
                        ep["name"], ep["flow"], my_class, my_flows[fi],
                        plocals, canonical_key)
                    if (self.ctx.nb_ranks > 1 and self.ctx.comm is not None
                            and self.task_rank_of(peer, plocals) != self.ctx.my_rank):
                        # remote producer: payload was shipped by its rank,
                        # ALREADY reshaped to the out-dep type before the
                        # wire (pre-send reshape); never re-reshape with the
                        # same type (remote_no_re_reshape.jdf). The arrival
                        # is keyed by wire datatype so one flow fanning out
                        # under several types delivers each shape intact
                        # (remote_multiple_outs_same_pred_flow.jdf)
                        with self._ptg_lock:
                            got = self._ptg_received.get(
                                (ep["name"], pkey, pf_idx, wire_dtt_name))
                        if got is None:
                            output.fatal(f"{task!r}: remote payload "
                                         f"{ep['name']}{pkey} missing")
                        payload, wire_dtt = got
                        if in_dtt is not None and not in_dtt.identity \
                                and in_dtt.name != wire_dtt:
                            slot.data_in = self._typed_payload(payload, in_dtt)
                        else:
                            slot.data_in = DataCopy(None, 0, payload)
                        continue
                    repo = self.repos[peer.task_class_id]
                    # repo entries are stored under the producer's task key,
                    # which may come from a user make_key_fn
                    entry = repo.lookup_entry(peer.make_key(self, plocals))
                    if entry is None:
                        output.fatal(f"{task!r}: missing repo entry "
                                     f"{ep['name']}{pkey}")
                    value = entry.data[pf_idx]
                    # output-reshape (producer's [type]) then input-reshape
                    # (this dep's [type]) when they differ; identical names
                    # convert exactly once (avoidable_reshape.jdf)
                    out_dtt = self._dtt(out_dtt_name)
                    value = self._typed_payload(value, out_dtt)
                    if in_dtt is not None and (out_dtt is None
                                               or in_dtt.name != out_dtt.name):
                        value = self._typed_payload(value, in_dtt)
                    slot.data_in = value
                    slot.source_repo_entry = entry
                elif ep["kind"] == "new":
                    slot.data_in = None
            return HOOK_DONE
        return prepare_input

    def _body_inputs(self, tc: TaskClass, task: Task) -> List[Any]:
        vals = [task.locals[p] for p in tc._ptg_spec.params]
        for fi, flow in enumerate(tc.flows):
            if flow.access & FLOW_ACCESS_CTL:
                continue
            vals.append(_payload_of(task.data[fi].data_in))
        return vals

    def _store_outputs(self, tc: TaskClass, task: Task, outs) -> None:
        if outs is None:
            outs = ()
        elif not isinstance(outs, (tuple, list)):
            outs = (outs,)
        oi = 0
        for fi, flow in enumerate(tc.flows):
            if flow.access & FLOW_ACCESS_CTL or not (flow.access & FLOW_ACCESS_WRITE):
                continue
            if oi < len(outs):
                task.data[fi].data_out = outs[oi]
            oi += 1

    def _mk_cpu_hook(self, tc: TaskClass, fn):
        if all(f.access & FLOW_ACCESS_CTL for f in tc.flows):
            # flowless or CTL-only class (the EP/control-task shapes): no
            # arrays flow through the body, so the jit wrapper is pure
            # dispatch overhead (~10us/call) — run the raw python body
            raw = getattr(fn, "__wrapped__", fn)
            # the agglomerated-sweep entry (flowless) and the native
            # execution lane's batched-dispatch entry (CTL-only) both
            # call the raw body with the class parameters
            tc._ptg_raw_body = raw

            def flowless_hook(stream, task: Task) -> int:
                raw(*[task.locals[p] for p in tc._ptg_spec.params])
                return HOOK_DONE
            return flowless_hook

        def hook(stream, task: Task) -> int:
            outs = fn(*self._body_inputs(tc, task))
            self._store_outputs(tc, task, outs)
            return HOOK_DONE
        return hook

    def _mk_tpu_submit(self, tc: TaskClass, fn):
        def submit(device, task: Task, inputs: List[Any]):
            vals = [task.locals[p] for p in tc._ptg_spec.params]
            for fi, flow in enumerate(tc.flows):
                if flow.access & FLOW_ACCESS_CTL:
                    continue
                vals.append(inputs[fi])
            return fn(*vals)
        return submit

    def _mk_complete(self, tc: TaskClass):
        def complete(stream, task: Task) -> int:
            env = self._env(task.locals)
            for fi, flow in enumerate(tc.flows):
                mem_outs = getattr(flow, "_ptg_mem_out", None)
                if not mem_outs:
                    continue
                slot = task.data[fi]
                value = slot.data_out if slot.data_out is not None else \
                    _payload_of(slot.data_in)
                value = _payload_of(value)
                for cond, dc_name, exprs, dtt_name in mem_outs:
                    if not cond(task.locals):
                        continue
                    dc = self.collections.get(dc_name)
                    data = dc.data_of(*[ex(env) for ex in exprs])
                    host = data.get_copy(0)
                    dtt = self._dtt(dtt_name)
                    if host is None:
                        v = value if dtt is None or dtt.identity \
                            else dtt.extract(value)
                        data.create_copy(0, v, COHERENCY_OWNED)
                    elif dtt is not None and not dtt.identity:
                        # typed write-back merges only the datatype's region
                        # into the tile; the complement is preserved
                        host.payload = dtt.insert(host.payload, value)
                    else:
                        host.payload = value
                    data.bump_version(0)
            return HOOK_DONE
        return complete

    def _compile_body(self, tcs: P.TaskClassSpec, body: P.BodySpec):
        """Body text → jitted function(params..., flows...) -> written flows."""
        data_flows = [f.name for f in tcs.flows if f.access != P.FLOW_CTL]
        written = [f.name for f in tcs.flows
                   if f.access in (P.FLOW_WRITE, P.FLOW_RW)]
        args = list(tcs.params) + data_flows
        for name in args:
            if not name.isidentifier():
                raise P.PTGSyntaxError(f"bad identifier {name!r}")
        src = textwrap.dedent(body.source)
        import re as _re
        if _re.search(r"\breturn\b", src):
            raise P.PTGSyntaxError(
                f"BODY of {tcs.name} must not use 'return'; written flows "
                f"are returned automatically", body.line_no)
        fn_src = (f"def __ptg_body__({', '.join(args)}):\n"
                  + textwrap.indent(src if src.strip() else "pass", "    ")
                  + f"\n    return ({', '.join(written)}{',' if written else ''})")
        ns: Dict[str, Any] = {}
        ns.update(self.env_base)
        try:
            import jax
            import jax.numpy as jnp
            ns.setdefault("jnp", jnp)
            ns.setdefault("jax", jax)
            ns.setdefault("lax", jax.lax)
        except Exception:
            pass
        ns.setdefault("np", np)
        try:
            exec(compile(fn_src, f"<ptg-body:{tcs.name}>", "exec"), ns)  # noqa: S102
        except SyntaxError as e:
            raise P.PTGSyntaxError(
                f"BODY of {tcs.name} does not compile: {e}", body.line_no) from e
        raw = ns["__ptg_body__"]
        import jax
        return jax.jit(raw)

    def _ptg_data_arrived(self, tc_name: str, pkey, flow_index: int,
                          payload, wire_dtt: Optional[str] = None) -> None:
        """A remote producer's output landed here: credit every local
        successor it feeds, re-deriving them from the replicated program
        (the reference's phantom-task iterate-successors,
        remote_dep_mpi.c:861). ``wire_dtt`` names the datatype the payload
        was reshaped to BEFORE the wire (pre-send reshape) so consumers
        never re-reshape with the same type."""
        pkey = tuple(pkey) if isinstance(pkey, (list, tuple)) else (pkey,)
        with self._ptg_lock:
            self._ptg_received[(tc_name, pkey, flow_index, wire_dtt)] = \
                (payload, wire_dtt)
        tc = self._classes[tc_name]
        tcs = self.program.spec.task_class(tc_name)
        plocals = dict(zip(tcs.params, pkey))
        my = self.ctx.my_rank
        ready = []
        flow = tc.flows[flow_index]
        for dep in flow.deps_out:
            if getattr(dep, "wire_datatype", dep.datatype) != wire_dtt:
                # each typed send credits exactly the successors on edges
                # of its own wire datatype (one flow may fan out under
                # several)
                continue
            if dep.cond is not None and not dep.cond(plocals):
                continue
            targets = dep.target_locals(plocals) if dep.target_locals else [plocals]
            for tl in targets:
                succ_tc = dep.task_class
                if self.task_rank_of(succ_tc, tl) != my:
                    continue
                key = succ_tc.make_key(self, tl)
                goal = (succ_tc.dependencies_goal_fn(tl)
                        if succ_tc.dependencies_goal_fn else None)
                if self.update_deps(succ_tc, key, 1, goal):
                    ready.append(self.ctx.make_task(self, succ_tc, dict(tl)))
        if ready:
            self.ctx.schedule(ready)

    def _declare_complete(self) -> None:
        super()._declare_complete()
        # retire the reshape-promise table and parked remote payloads: the
        # graph is done, no consumer can request them again (the reference
        # retires reshape promises with repo-entry refcounts)
        with self._typed_lock:
            self._typed_cache.clear()
        with self._ptg_lock:
            self._ptg_received.clear()

    # ------------------------------------------------------------------ startup
    def _enumerate(self):
        """Yield every locals assignment in the task space, class by class
        (the generated startup-task enumerator, jdf2c.c:3047)."""
        for tcs in self.program.spec.task_classes:
            tc = self._classes[tcs.name]
            yield from ((tc, loc) for loc in self._enum_class(tc))

    def _enum_class(self, tc: TaskClass):
        ranges = tc._ptg_ranges
        def rec(i: int, loc: Dict[str, int]):
            if i == len(ranges):
                yield dict(loc)
                return
            param, lo, hi, step = ranges[i]
            env = self._env(loc)
            lo_v, hi_v, st_v = int(lo(env)), int(hi(env)), int(step(env))
            end = hi_v + 1 if st_v > 0 else hi_v - 1
            for v in range(lo_v, end, st_v):        # inclusive, like JDF
                loc[param] = v
                yield from rec(i + 1, loc)
            loc.pop(param, None)
        yield from rec(0, {})

    def _agglomerable(self, tc: TaskClass) -> bool:
        """A class the runtime may execute as ONE fused sweep at startup:
        statically proven independent — no flows at all (so no deps in or
        out, no data, nothing downstream waits on any instance) and no
        custom startup seeding. The PTG analogue of capture: when the
        static structure proves there is nothing to schedule AROUND, the
        per-task scheduling cycle is pure overhead (the reference pays ~0
        for that cycle in C; we eliminate it instead)."""
        return (not tc.flows
                and getattr(tc, "_ptg_startup_fn", None) is None
                # exactly one ungated body: multi-incarnation classes pick
                # a chore per task ([evaluate] gates, device choice) — the
                # sweep must not bypass that selection
                and len(tc.incarnations) == 1
                and tc.incarnations[0].evaluate is None
                # a sweep runs on the startup thread: with worker streams
                # the per-task path spreads instances across cores instead
                and len(self.ctx.streams) == 1
                and mca.get("ptg_agglomerate", True)
                and not self.ctx.pins.enabled
                and not self.ctx.paranoid)

    def _enum_class_fast(self, tc: TaskClass):
        """Param-value tuples via itertools.product when every range bound
        is static (depends on globals only); None when bounds reference
        other params (triangular spaces fall back to the dict walk)."""
        import itertools
        env0 = self._env({})
        rs = []
        for i, (param, lo, hi, step) in enumerate(tc._ptg_ranges):
            if param != tc._ptg_spec.params[i]:
                return None
            try:
                lo_v, hi_v, st_v = int(lo(env0)), int(hi(env0)), int(step(env0))
            except Exception:  # noqa: BLE001 — bound needs an outer param
                return None
            rs.append(range(lo_v, hi_v + 1 if st_v > 0 else hi_v - 1, st_v))
        return itertools.product(*rs) if rs else iter(((),))

    def _run_agglomerated(self, stream, tc: TaskClass) -> int:
        """Execute a proven-independent flowless class as one fused sweep;
        returns the instance count (reported executed, never scheduled)."""
        raw = tc._ptg_raw_body
        my_rank = self.ctx.my_rank
        distributed = self.ctx.nb_ranks > 1 and self.ctx.comm is not None
        n = 0
        it = None if distributed else self._enum_class_fast(tc)
        if it is not None:
            for vals in it:
                raw(*vals)
                n += 1
        else:
            params = tc._ptg_spec.params
            for loc in self._enum_class(tc):
                if distributed and tc._ptg_rank_of(loc) != my_rank:
                    continue
                raw(*[loc[p] for p in params])
                n += 1
        stream.nb_executed += n
        return n

    # ------------------------------------------------------- native exec lane
    def _ptexec_class_device(self, tc: TaskClass) -> bool:
        """True for the TPU-bodied shape (``BODY [type=TPU]``): exactly
        the two ungated incarnations _build_class emits — the TPU chore
        plus its CPU twin running the same jitted function."""
        incs = tc.incarnations
        return (len(incs) == 2 and incs[0].device_type == DEV_TPU
                and incs[1].device_type == DEV_CPU
                and incs[0].evaluate is None and incs[1].evaluate is None)

    def _ptexec_class_eligible(self, tc: TaskClass) -> bool:
        """May this class's whole FSM run inside the native lane
        (native/src/ptexec.cpp)?  Eligibility = the per-task cycle carries
        no state the lane does not model. The lane models: CTL edges, DATA
        flows (the versioned slot hand-off + the datarepo usagelmt/usagecnt
        retire protocol live in the lane's per-task slot array), memory
        reads/write-backs, ``priority`` properties (a native ready heap),
        and — eligibility v3, ISSUE 10 — TPU-bodied classes: their tasks
        surface onto the native DEVICE lane (ptdev) when one is up, or run
        the same jitted function through the CPU dispatch when no
        accelerator device exists (which is exactly what the interpreted
        FSM's chore selection would have picked). It does NOT model: named
        datatypes (reshape promises), evaluate-gated or >2-incarnation
        chore selection, multi-body classes, or custom startup seeding.
        Pool-level gates (distributed ranks, PINS, paranoid, device-lane
        availability) live in :meth:`_ptexec_prepare`."""
        if getattr(tc, "_ptg_startup_fn", None) is not None:
            return False
        if tc._ptg_spec.header_props.get("make_key_fn") is not None:
            # a user task-key function feeds the dep repos / hash tables —
            # machinery the lane bypasses entirely; calling (or silently
            # not calling) a user hook is observable behavior
            return False
        if len(tc._ptg_spec.bodies) != 1:
            return False
        if not self._ptexec_class_device(tc):
            # (a device class with a user `time_estimate` hook used to
            # decline here — the PR 10 carve-out. ISSUE 18 erased it: the
            # lane now CALLS the hook at the instantiation boundary to
            # seed the cost model's cold-start prior, restoring the
            # best-device semantics natively instead of falling back to
            # the interpreted FSM. See _ptexec_seed_prior.)
            if len(tc.incarnations) != 1 or \
                    tc.incarnations[0].device_type != DEV_CPU or \
                    tc.incarnations[0].evaluate is not None:
                return False
        has_body = tc._ptg_spec.bodies[0].source.strip() not in ("", "pass")
        if not any(not (f.access & FLOW_ACCESS_CTL) for f in tc.flows):
            # CTL/flowless: non-empty bodies dispatch through the raw-body
            # callback (params only, no data marshalling)
            return not has_body or getattr(tc, "_ptg_raw_body", None) is not None
        # data flows: any NAMED datatype means reshape promises / typed
        # write-backs — state that stays with the Python FSM
        for alts in tc._ptg_in_specs:
            for _guard, ep in alts:
                if ep is not None and ep.get("dtt") is not None:
                    return False
        for f in tc.flows:
            for dep in f.deps_out:
                if dep.datatype is not None or \
                        getattr(dep, "wire_datatype", None) is not None:
                    return False
            for mo in getattr(f, "_ptg_mem_out", None) or []:
                if mo[3] is not None:     # (cond, dc_name, exprs, dtt_name)
                    return False
        # non-empty data bodies dispatch the jitted class function
        return not has_body or getattr(tc, "_ptg_body_fn", None) is not None

    #: the builtins __init__ injects into env_base — identical in every
    #: instantiation, so they never enter the cache signature. Matched by
    #: IDENTITY: a user global that shadows one of these names is real
    #: state and must poison the cache key instead.
    _PTEXEC_SAFE_ENV = {"min": min, "max": max, "abs": abs, "range": range,
                        "len": len, "int": int, "divmod": divmod}

    def _ptexec_cache_key(self, names: Tuple[str, ...], place: Tuple):
        """Cache signature for the flattened graph: the task space and the
        edge structure depend only on the program text and the globals the
        range/guard/index expressions read. Non-primitive globals (incl.
        user callables a guard might invoke) make the instantiation
        uncacheable — flatten still runs, per pool.

        ``place`` is the placement fingerprint (ISSUE 12 satellite):
        (nb_ranks, comm lane, device lane, device fingerprint, fusion
        config). The cached entry now carries the FUSION PLAN — which
        depends on which classes ride the device lane and on the fusion
        knobs — and the region executable cache hangs off this key, so a
        cached CSR (or compiled region program) can never be replayed
        against a different mesh/device layout."""
        sig = []
        for k, v in self.env_base.items():
            if k == "__builtins__" or self._PTEXEC_SAFE_ENV.get(k) is v:
                continue
            if v is None or isinstance(v, (int, float, str, bool)):
                sig.append((k, v))
            else:
                return None
        return (tuple(sorted(sig)), names, place)

    def _ptexec_flatten(self, classes: List[TaskClass]):
        """Emit the flattened tables the native lane consumes (the jdf2c
        moment: the whole control structure leaves Python): the CSR
        successor table + per-task dependency goals, and — for data-flow
        pools — each task's flow table: one data slot per (task, data
        flow), per-slot usage limits (the repo usagelmt, counted from the
        consumer side), input slot references resolved from the guarded
        in-deps, memory reads (symbolic: collection name + static index,
        resolved per pool), memory write-backs, and per-task priorities.
        Returns None when the declared in/out dep sides disagree — the
        Python FSM would mask one-sided declarations differently, so the
        lane refuses rather than diverge."""
        id_of: Dict[Tuple[int, Tuple[int, ...]], int] = {}
        params_by_class: List[List[Tuple[int, ...]]] = []
        bases: List[int] = []
        n = 0
        for ci, tc in enumerate(classes):
            params = tc._ptg_spec.params
            insts = [tuple(loc[p] for p in params)
                     for loc in self._enum_class(tc)]
            bases.append(n)
            params_by_class.append(insts)
            for key in insts:
                id_of[(ci, key)] = n
                n += 1
        class_index = {tc._ptg_spec.name: ci
                       for ci, tc in enumerate(classes)}
        # per-class data-flow tables: flow indices that carry data, in flow
        # order (= the body's flow-argument order, _compile_body)
        dflows_by_class = [[fi for fi, f in enumerate(tc.flows)
                            if not (f.access & FLOW_ACCESS_CTL)]
                           for tc in classes]
        has_data = any(dflows_by_class)
        has_prio = any("priority" in tc.properties for tc in classes)
        # slot assignment: contiguous per task, one per data flow
        slot_base = [0] * n
        n_slots = 0
        if has_data:
            for ci, tc in enumerate(classes):
                nd = len(dflows_by_class[ci])
                for key in params_by_class[ci]:
                    slot_base[id_of[(ci, key)]] = n_slots
                    n_slots += nd
        goals = [0] * n
        prio = [0] * n
        edges: List[List[int]] = [[] for _ in range(n)]
        indeg = [0] * n
        in_refs = [-1] * n_slots    # per slot: the owning flow's input ref
        slot_uses = [0] * n_slots   # per slot: task-kind consumer count
        in_edges: List[List[int]] = [[] for _ in range(n)] if has_data else []
        mem_idx_of: Dict[Tuple[str, Tuple[int, ...]], int] = {}
        mem_reads: List[Tuple[str, Tuple[int, ...]]] = []
        writebacks: List[Tuple[int, int, str, Tuple[int, ...]]] = []
        for ci, tc in enumerate(classes):
            params = tc._ptg_spec.params
            prio_fn = tc.properties.get("priority")
            dflows = dflows_by_class[ci]
            # replay the param tuples materialized above instead of
            # re-walking the range expressions (halves flatten latency)
            for key in params_by_class[ci]:
                loc = dict(zip(params, key))
                my_id = id_of[(ci, key)]
                goals[my_id] = tc.dependencies_goal_fn(loc)
                if prio_fn is not None:
                    p = int(prio_fn(loc))
                    if not (-(1 << 31) <= p < (1 << 31)):
                        # the native heap is int32; the Python FSM orders
                        # by full ints — decline rather than wrap/clamp
                        # into a different dispatch order
                        return None
                    prio[my_id] = p
                for flow in tc.flows:
                    for dep in flow.deps_out:
                        if dep.task_class is None:
                            continue
                        if dep.cond is not None and not dep.cond(loc):
                            continue
                        si = class_index.get(dep.task_class.name)
                        if si is None:
                            return None     # edge into a non-lane class
                        sparams = classes[si]._ptg_spec.params
                        targets = dep.target_locals(loc) \
                            if dep.target_locals else [loc]
                        if isinstance(targets, dict):
                            targets = [targets]
                        for tl in targets:
                            sid = id_of.get(
                                (si, tuple(tl[p] for p in sparams)))
                            if sid is None:
                                return None  # successor outside the space
                            edges[my_id].append(sid)
                            indeg[sid] += 1
                if not dflows:
                    continue
                # the data side of the flow table: resolve this task's
                # active in-dep per data flow (exactly what prepare_input
                # does, once, at flatten instead of per dispatch)
                env = self._env(loc)
                base = slot_base[my_id]
                for dj, fi in enumerate(dflows):
                    ep = tc._ptg_active_in(tc._ptg_in_specs[fi], env)
                    if ep is None or ep["kind"] in ("new", "null"):
                        pass                          # ref stays -1 (no input)
                    elif ep["kind"] == "task":
                        si = class_index.get(ep["name"])
                        if si is None:
                            return None   # producer outside the lane set
                        peer_spec = classes[si]._ptg_spec
                        pf_idx = next(i for i, f in enumerate(peer_spec.flows)
                                      if f.name == ep["flow"])
                        try:
                            pdj = dflows_by_class[si].index(pf_idx)
                        except ValueError:
                            return None   # data read from a CTL flow
                        pkey = tuple(ex.values(env)[0] for ex in ep["exprs"])
                        pid = id_of.get((si, pkey))
                        if pid is None:
                            return None   # producer outside the space
                        ref = slot_base[pid] + pdj
                        in_refs[base + dj] = ref
                        slot_uses[ref] += 1           # the repo usagelmt
                        in_edges[my_id].append(ref)
                    elif ep["kind"] == "memory":
                        idx = tuple(int(ex(env)) for ex in ep["exprs"])
                        mk = (ep["name"], idx)
                        mi = mem_idx_of.get(mk)
                        if mi is None:
                            mi = mem_idx_of[mk] = len(mem_reads)
                            mem_reads.append(mk)
                        in_refs[base + dj] = -2 - mi
                    else:
                        return None       # an endpoint kind the lane ignores
                    mem_outs = getattr(tc.flows[fi], "_ptg_mem_out", None)
                    if mem_outs:
                        for cond, dc_name, exprs, _dtt in mem_outs:
                            if not cond(loc):
                                continue
                            idx = tuple(int(ex(env)) for ex in exprs)
                            writebacks.append((my_id, dj, dc_name, idx))
        if indeg != goals:
            # producer-declared edges and consumer-declared goals disagree
            output.debug_verbose(1, "ptg",
                                 f"{self.name}: native lane refused "
                                 f"(in-dep goals != out-dep edges)")
            return None
        off = [0] * (n + 1)
        for i, e in enumerate(edges):
            off[i + 1] = off[i] + len(e)
        succs: List[int] = []
        for e in edges:
            succs.extend(e)
        flat = {"n": n, "goals": goals, "off": off, "succs": succs,
                "bases": bases, "params": params_by_class,
                "prio": prio if has_prio else None, "data": None}
        if has_data:
            in_off = [0] * (n + 1)
            for i, e in enumerate(in_edges):
                in_off[i + 1] = in_off[i] + len(e)
            in_slots: List[int] = []
            for e in in_edges:
                in_slots.extend(e)
            # per-id class index: the dispatch loop runs per TASK — a list
            # lookup beats a bisect over the class bases at that frequency
            cls_of: List[int] = []
            for ci in range(len(classes)):
                cls_of.extend([ci] * len(params_by_class[ci]))
            flat["data"] = {
                "slot_base": slot_base, "n_slots": n_slots,
                "in_refs": in_refs, "slot_uses": slot_uses,
                "in_off": in_off, "in_slots": in_slots,
                "ndflows": [len(d) for d in dflows_by_class],
                "dflow_idx": dflows_by_class,   # THE per-class data-flow
                # index rule (body-argument order) — derived once, shipped
                # to the dispatch callback instead of re-derived there
                "cls_of": cls_of,
                "mem_reads": mem_reads, "writebacks": writebacks,
            }
        return flat

    # ------------------------------------------ online cost model (ISSUE 18)
    def _ptexec_pool_bucket(self) -> int:
        """The pool's shape bucket: the log4 byte-size bucket of its
        largest tile (TiledMatrix mb*nb*itemsize over the bound
        collections). Pools whose tiles sit within 4x of each other —
        one cost regime — share cost-model keys; collection-less pools
        key at bucket 0."""
        from ...core.costmodel import shape_bucket
        nbytes = 0
        for dc in self.collections.values():
            mb = getattr(dc, "mb", None)
            nb = getattr(dc, "nb", None)
            if not mb or not nb:
                continue
            try:
                item = np.dtype(getattr(dc, "dtype", np.float32)).itemsize
            except TypeError:
                item = 4
            nbytes = max(nbytes, int(mb) * int(nb) * item)
        return shape_bucket(nbytes)

    def _ptexec_seed_prior(self, tc: TaskClass, name: str,
                           bucket: int) -> None:
        """Fold a user ``time_estimate`` hook into the cost model as the
        class's cold-start prior (ISSUE 18 — the PR 10 carve-out,
        inverted): call the hook once per device flavor with a
        representative task (`make_task` is side-effect free) and the
        real device modules — the observable calling convention the
        interpreted best-device path used — and install the answers (in
        seconds, like the reference's ETA vtable) as priors. Measured
        costs override the prior as soon as the key warms up."""
        est = tc.time_estimate
        if est is None:
            return
        from ...core.costmodel import model
        try:
            loc = next(iter(self._enum_class(tc)))
        except StopIteration:
            return
        task = self.ctx.make_task(self, tc, loc)
        tpus = self.ctx.devices.by_type(DEV_TPU)
        for dev_obj, key in ((self.ctx.devices.cpu, "cpu"),
                             (tpus[0] if tpus else None, "tpu")):
            if dev_obj is None:
                continue
            try:
                eta = float(est(task, dev_obj))
            except Exception:  # noqa: BLE001 — a hook error never ejects
                continue       # the pool from the lane (the old behavior
                               # it replaces was a flat decline)
            model.seed_prior(name, bucket, key, eta * 1e9)

    def _ptexec_place_classes(self, classes: List[TaskClass],
                              dev_classes: List[bool],
                              names: Tuple[str, ...],
                              bucket: int) -> List[bool]:
        """Consumer (a) of the online cost model: per-instantiation
        best-device selection for the pool's TPU-bodied classes (each
        has a CPU twin of the same jitted function — the placement is
        free to move the whole class either way).

        Decision ladder per class, most-informed first: both flavors
        MEASURED → cheaper wins, with the device side carrying its
        measured stage-in cost pro-rated by the observed stage-in/task
        ratio (the coherency table's hit rate prices itself in); one
        flavor measured → explore the cold twin ONCE (the model cannot
        compare costs it never collected); neither measured → compare
        the user-hook priors when both were seeded, else the static
        has-a-device-body heuristic. Runs at the instantiation boundary
        only — its wall time lands in ``costmodel.decision_ns`` (the
        <1% contract's numerator)."""
        from ...core import costmodel as _cm
        if not (_cm.enabled() and mca.get("costmodel_placement", True)):
            return list(dev_classes)
        m = _cm.model
        m.maybe_load()
        t0 = time.perf_counter_ns()
        stats = _cm.COSTMODEL_STATS
        out: List[bool] = []
        for ci, tc in enumerate(classes):
            if not dev_classes[ci]:
                out.append(False)
                continue
            name = names[ci]
            self._ptexec_seed_prior(tc, name, bucket)
            cpu_known = m.measured(name, bucket, "cpu")
            tpu_known = m.measured(name, bucket, "tpu")
            if cpu_known and tpu_known:
                tpu_ns = m.cost(name, bucket, "tpu")
                st = m.cost(_cm.STAGE_IN, bucket, "tpu")
                if st is not None:
                    n_st = m.count(_cm.STAGE_IN, bucket, "tpu")
                    n_tpu = max(1, m.count(name, bucket, "tpu"))
                    tpu_ns += st * min(1.0, n_st / n_tpu)
                choice = tpu_ns <= m.cost(name, bucket, "cpu")
            elif tpu_known:
                choice = not m.begin_explore(name, bucket, "cpu")
            elif cpu_known:
                choice = m.begin_explore(name, bucket, "tpu")
            else:
                pc = m.cost(name, bucket, "cpu")
                pt = m.cost(name, bucket, "tpu")
                choice = (pt <= pc) if (pc is not None and pt is not None) \
                    else True
            out.append(choice)
            stats["placements_adaptive"] += 1
            if choice != dev_classes[ci]:
                stats["placements_diverged"] += 1
        stats["decisions"] += 1
        stats["decision_ns"] += time.perf_counter_ns() - t0
        return out

    def _ptexec_cost_bind(self, lane: Dict[str, Any], graph, flat,
                          names: Tuple[str, ...], bucket: int,
                          plan=None, cold_regions=None) -> None:
        """Attach the C-side cost rows (ISSUE 18): one row per (class,
        flavor), node-mapped so run()'s batch-amortized exec bump lands
        each task's share in the right accumulator. Unfused tasks row at
        their class index ('cpu'); fused region nodes row at n_classes +
        first-member class ('cpu_fused' — a multi-class region is
        attributed to its lead class; the capturable chains the fusion
        pass emits are single-class in practice). Device-placed nodes
        never pass the C bump site (they retire through the ptdev lane,
        observed there) — their rows simply stay zero and the fold skips
        them. The row → key metadata rides the lane dict to the fold at
        detach (Context._cost_fold)."""
        from ...core import costmodel as _cm
        if not _cm.enabled():
            return
        ncls = len(names)
        meta = [(names[ci], bucket, "cpu") for ci in range(ncls)] + \
               [(names[ci], bucket, "cpu_fused") for ci in range(ncls)]
        if plan is None:
            cls_of = flat["data"]["cls_of"] if flat["data"] is not None \
                else None
            if cls_of is None:
                rows = []
                for ci, insts in enumerate(flat["params"]):
                    rows.extend([ci] * len(insts))
            else:
                rows = list(cls_of)
        else:
            cls_of = flat["data"]["cls_of"]
            rows = []
            for nd in plan["node"]:
                if nd[0] == "t":
                    rows.append(cls_of[nd[1]])
                elif cold_regions and nd[1] in cold_regions:
                    # a COLD region (executable-cache miss): its first
                    # dispatch pays the jit trace, and the C bump cannot
                    # split that one batch out — so the whole run stays
                    # unobserved (-1). Only warm instantiations feed the
                    # <cls>_fused EWMA; the trace itself is measured
                    # separately by _timed_region_program. Without this
                    # a tiny cold DAG reads fusion as "slower than
                    # unfused" forever and wrongly declines it.
                    rows.append(-1)
                else:
                    members = plan["regions"][nd[1]]["members"]
                    rows.append(ncls + cls_of[members[0]])
        try:
            graph.cost_bind(rows)
        except Exception:  # noqa: BLE001 — an old native build without
            return         # cost rows just leaves the model CPU-blind
        lane["cost_meta"] = meta

    def _ptexec_prepare(self, agg) -> Optional[Dict[str, Any]]:
        """Build (or fetch from the program cache) the native-lane state
        for this pool, or None → the Python FSM runs as before. The fall
        back is per-pool: one ineligible class keeps cross-class release
        edges in Python, so the whole pool stays there.
        ``self._ptexec_refusal`` records WHY a pool declined —
        "ineligible" (by design: class features or pool-level gates) vs
        "fallback" (every class eligible, but the lane build refused:
        flatten mismatch or missing native module) — feeding the
        PTEXEC_STATS split the ci.sh gate relies on."""
        ctx = self.ctx
        self._ptexec_refusal = "ineligible"
        # PINS no longer ejects pools from the lane (PR 5: the lane traces
        # itself — in-lane ring events land in the PBP streams, see
        # utils/native_trace.py); only --mca pins_paranoid 1 restores the
        # full per-task Python instrumentation
        if (not mca.get("ptg_native_exec", True) or ctx.pins.paranoid
                or ctx.paranoid):
            return None
        # distributed pools may now ride the lane too — when the native
        # COMMUNICATION lane (comm/native.py, ISSUE 7) is up: cross-rank
        # release edges surface as activation frames, payloads move
        # eager/rendezvous, and arrived activations ingest GIL-free. A
        # distributed context without that lane (in-process ThreadsCE
        # fabric, --mca comm_native 0, missing native modules) keeps the
        # interpreted remote_dep path, counted as ineligible-by-design.
        distributed = ctx.nb_ranks > 1 and ctx.comm is not None
        lane_comm = getattr(ctx.comm, "native", None) if distributed else None
        if (ctx.comm is not None or ctx.nb_ranks > 1) and lane_comm is None:
            return None
        classes = [self._classes[tcs.name]
                   for tcs in self.program.spec.task_classes
                   if tcs.name not in agg]
        if not classes:
            return None
        for tc in classes:
            if not self._ptexec_class_eligible(tc):
                return None
        dev_classes = [self._ptexec_class_device(tc) for tc in classes]
        use_dev = False
        if any(dev_classes):
            # eligibility v3 (ISSUE 10): TPU-bodied classes. With an
            # accelerator device registered their tasks surface onto the
            # native DEVICE lane (ptdev); without one, the CPU twin of
            # the same jitted body runs through the ordinary lane
            # dispatch — exactly the chore the interpreted FSM's device
            # selection would pick on a CPU-only host.
            if ctx.devices.by_type(DEV_TPU):
                from ...device.native import PTDEV_STATS
                if lane_comm is not None or not mca.get("device_native",
                                                        True):
                    # device + cross-rank lanes are not combined yet, and
                    # --mca device_native 0 keeps the interpreted device
                    # module: both ineligible-by-design
                    PTDEV_STATS["pools_ineligible"] += 1
                    return None
                use_dev = True
        self._ptexec_refusal = "fallback"
        from ... import native as native_mod
        mod = native_mod.load_ptexec()
        if mod is None:
            return None
        # consumer (a) of the online cost model (ISSUE 18): per-
        # instantiation best-device selection. The static heuristic
        # ("has a device body") is the cold-start fallback; once both
        # flavors are measured the cheaper one wins, and a pool whose
        # device classes ALL measure cheaper on their CPU twins skips
        # the device lane entirely.
        bucket = self._ptexec_pool_bucket()
        # cost-model keys are qualified by the PROGRAM name: two programs
        # are free to both name a class "A" with wildly different bodies,
        # and the model must never blend their measurements (the taskpool
        # name would work too, but the program name survives a caller
        # passing per-instantiation pool names, keeping warm-cache and
        # persisted entries addressable)
        names = tuple(f"{self.program.spec.name}.{tc._ptg_spec.name}"
                      for tc in classes)
        place_dev = list(dev_classes)
        if use_dev:
            place_dev = self._ptexec_place_classes(classes, dev_classes,
                                                   names, bucket)
            if not any(place_dev):
                use_dev = False
        devlane = None
        if use_dev:
            devlane = ctx._ptdev_lane()
            if devlane is None:
                # eligible, device present, but the ptdev module/lane is
                # missing: the silent-regression signal
                from ...device.native import PTDEV_STATS
                PTDEV_STATS["pools_fallback"] += 1
                return None
        # consumer (b): measured fusion limits (dsl/fusion.py). The
        # decline set and the break-even cap shape the fusion plan, so
        # they join the flatten cache key — a plan sized for one cost
        # regime is never replayed under another.
        fus_declined, fus_min, fus_max = adaptive_fusion_limits(
            [(names[ci], bucket,
              "tpu" if (use_dev and place_dev[ci]) else "cpu")
             for ci in range(len(classes))])
        place = (ctx.nb_ranks, lane_comm is not None, use_dev,
                 device_fingerprint(),
                 bool(mca.get("region_fusion", True)),
                 fus_min, fus_max,
                 tuple(place_dev), tuple(sorted(fus_declined)))
        key = self._ptexec_cache_key(names, place)
        cache = self.program.__dict__.setdefault("_ptexec_cache", {})
        ent = cache.get(key) if key is not None else None
        if ent is None:
            flat = self._ptexec_flatten(classes)
            if flat is None:
                return None
            plan = None
            if flat["n"] and flat["data"] is not None \
                    and lane_comm is None:
                # the fusion pass (ISSUE 12): single-rank data pools only
                # — a fused region must never hide a cross-rank edge
                plan = self._ptexec_fuse_plan(
                    flat, classes, place_dev, use_dev,
                    (fus_declined, fus_min, fus_max))
            ent = {"flat": flat, "fusion": plan}
            if key is not None:
                cache[key] = ent
        flat = ent["flat"]
        owners = None
        if lane_comm is not None:
            # per-task owner ranks (owner-computes affinity) — computed
            # per INSTANTIATION, never cached: rank_of depends on the
            # collection dict, which is outside the flatten cache key
            owners = self._ptexec_owners(classes, flat)
            if owners is None:
                return None
        self._ptexec_refusal = None
        if flat["n"] == 0:
            return {"n": 0}
        # the CSR (the expensive flatten) is shared across instantiations;
        # the Graph (counters + ready state + ~1ms of list parsing) is
        # built fresh PER POOL — a stream holding a stale drain-queue entry
        # can then never walk another pool's tasks, and bodies/callbacks
        # (which resolve against THIS instantiation's globals) can never
        # cross pools. Empty bodies dispatch nothing at all.
        data = flat["data"]
        if data is None:
            graph = mod.Graph(flat["goals"], flat["off"], flat["succs"],
                              flat["prio"])
            bodies = [None if tc._ptg_spec.bodies[0].source.strip()
                      in ("", "pass") else tc._ptg_raw_body for tc in classes]
            callback = None
            if any(b is not None for b in bodies):
                callback = self._mk_ptexec_callback(flat["bases"], bodies,
                                                    flat["params"])
            lane = {"graph": graph, "callback": callback,
                    "n": flat["n"], "finalized": False}
            self._ptexec_cost_bind(lane, graph, flat, names, bucket)
            if owners is not None:
                self._ptexec_bind_comm(lane, lane_comm, owners)
            return lane
        # data-flow pool with a FUSION PLAN (ISSUE 12): capturable
        # subgraphs collapse into fused super-tasks — one jitted program
        # per region, dispatched through the normal callback (CPU
        # regions) or the ptdev lane (device regions) — and the graph
        # carries only regions + seams, weighted back to original tasks.
        if ent.get("fusion") is not None and owners is None:
            return self._ptexec_lane_fused(flat, ent["fusion"], classes,
                                           mod, key,
                                           devlane if use_dev else None,
                                           place_dev, names, bucket)
        # data-flow pool: the graph additionally owns slot LIFETIMES (the
        # usagelmt/usagecnt retire protocol); Python owns slot VALUES —
        # one flat list the batched callback reads inputs from and lands
        # outputs into. Memory endpoints were flattened symbolically
        # (collection name + static index) so the cached CSR stays valid
        # across instantiations with different collection dicts.
        comm_info = None
        slot_uses = data["slot_uses"]
        if owners is not None:
            # distributed data pool: slot usage limits count LOCAL
            # consumers only (a remote consumer's use is the one payload
            # send, done at production time), remote input slots pull
            # their value from the comm lane's payload store, and
            # produced slots feeding remote consumers ship once per
            # destination rank
            comm_info = self._ptexec_comm_data(flat, owners)
            slot_uses = comm_info["slot_uses"]
        graph = mod.Graph(flat["goals"], flat["off"], flat["succs"],
                          flat["prio"], data["in_off"], data["in_slots"],
                          slot_uses)
        slots: List[Any] = [None] * data["n_slots"]
        mem_datas = []
        for dc_name, idx in data["mem_reads"]:
            dc = self.collections.get(dc_name)
            if dc is None:
                output.fatal(f"PTG taskpool {self.name}: unknown "
                             f"collection {dc_name!r}")
            mem_datas.append(dc.data_of(*idx))
        writebacks: Dict[int, List] = {}
        for tid, dj, dc_name, idx in data["writebacks"]:
            dc = self.collections.get(dc_name)
            if dc is None:
                output.fatal(f"PTG taskpool {self.name}: unknown "
                             f"collection {dc_name!r}")
            writebacks.setdefault(tid, []).append((dj, dc.data_of(*idx)))
        lane = {"graph": graph, "slots": slots,
                "n": flat["n"], "finalized": False}
        self._ptexec_cost_bind(lane, graph, flat, names, bucket)
        if owners is not None:
            self._ptexec_bind_comm(lane, lane_comm, owners)
        lane["callback"] = self._mk_ptexec_data_callback(
            flat, classes, slots, mem_datas, writebacks,
            comm=None if comm_info is None else dict(
                comm_info, lane=lane_comm, pool_id=lane["pool_id"]))
        if use_dev:
            # bind LAST: dev_bind surfaces zero-dep device seeds onto the
            # lane immediately, and the manager may dispatch them before
            # this function returns — every closure it touches (slots,
            # mem_datas, writebacks) exists by now
            self._ptexec_bind_dev(lane, devlane, flat, classes,
                                  place_dev, slots, mem_datas, writebacks,
                                  bucket)
        return lane

    # ---------------------------------------------- region fusion (ISSUE 12)
    def _ptexec_fuse_plan(self, flat, classes: List[TaskClass],
                          dev_classes: List[bool],
                          use_dev: bool,
                          limits=None) -> Optional[Dict[str, Any]]:
        """The fusion pass over the flattened CSR: identify capturable
        subgraphs — same-device jittable bodies (the class's single
        jitted ``_ptg_body_fn``, or an empty forwarding body), static
        shapes (automatic: jit traces per shape), no cross-rank edge
        (the caller only fuses single-rank pools) — and collapse each
        into ONE fused super-task node. Returns the fused COMPACT graph
        (regions + seams; a fused node inherits the union of its
        region's external in/out edges and in-slot lists, so the C
        release walk and the slot-retire protocol cross the seam
        correctly) plus per-region replay plans, or None when nothing
        is worth fusing. Pure structure — no per-instantiation objects
        — so the whole plan rides the flatten cache."""
        if not mca.get("region_fusion", True):
            return None
        # measured fusion limits (ISSUE 18, dsl/fusion.py): the decline
        # set un-fuses classes whose fused per-task cost measurably beats
        # nothing; the cap is the measured break-even region size. Cold
        # model → exactly the static knobs.
        if limits is None:
            limits = (set(), int(mca.get("region_fusion_min", 2)),
                      int(mca.get("region_fusion_max", 128)))
        fus_declined, fus_min, fus_max = limits
        data = flat["data"]
        n = flat["n"]
        cls_of = data["cls_of"]
        ndflows = data["ndflows"]
        # per-class capturability kind: None = seam (un-fusable)
        kind_by_class: List[Optional[str]] = []
        for ci, tc in enumerate(classes):
            if ndflows[ci] == 0 or ci in fus_declined:
                # CTL/flowless classes run raw Python bodies — seams;
                # model-declined classes stay per-task by measurement
                kind_by_class.append(None)
                continue
            empty = tc._ptg_spec.bodies[0].source.strip() in ("", "pass")
            if not empty and getattr(tc, "_ptg_body_fn", None) is None:
                kind_by_class.append(None)
                continue
            kind_by_class.append("dev" if (use_dev and dev_classes[ci])
                                 else "cpu")
        if not any(k is not None for k in kind_by_class):
            return None
        empty_body = [tc._ptg_spec.bodies[0].source.strip() in ("", "pass")
                      for tc in classes]
        slot_base0, in_refs0 = data["slot_base"], data["in_refs"]
        kind: List[Optional[str]] = []
        for t in range(n):
            ci = cls_of[t]
            k = kind_by_class[ci]
            if k is not None and empty_body[ci]:
                # an empty (forwarding) body with a NEW/NULL or memory
                # input can forward None — the per-task path's "A NULL
                # is forwarded" source guard must keep firing at the
                # producer, so such tasks stay seams (a fused region
                # would swallow the None into its trace env)
                b = slot_base0[t]
                for dj in range(data["ndflows"][ci]):
                    if in_refs0[b + dj] < 0:
                        k = None
                        break
            kind.append(k)
        regions = partition_regions(
            n, flat["off"], flat["succs"], kind, fus_min, fus_max)
        if not regions:
            return None
        off, succs = flat["off"], flat["succs"]
        in_off, in_slots = data["in_off"], data["in_slots"]
        slot_base, in_refs = data["slot_base"], data["in_refs"]
        mem_reads = data["mem_reads"]
        reg_of = [-1] * n
        for ri, members in enumerate(regions):
            for m in members:
                reg_of[m] = ri
        member_sets = [set(m) for m in regions]
        task_of_slot = [0] * data["n_slots"]
        for t in range(n):
            b = slot_base[t]
            for dj in range(ndflows[cls_of[t]]):
                task_of_slot[b + dj] = t
        # compact node list: seams/unfused keep their own node; each
        # region becomes ONE node at its topo-first member's id position
        rep_of = [m[0] for m in regions]
        node: List[Tuple] = []
        cid_of = [0] * n
        rcid = [-1] * len(regions)
        for i in range(n):
            ri = reg_of[i]
            if ri < 0:
                cid_of[i] = len(node)
                node.append(("t", i))
            elif i == rep_of[ri]:
                rcid[ri] = len(node)
                node.append(("r", ri))
        for i in range(n):
            if reg_of[i] >= 0:
                cid_of[i] = rcid[reg_of[i]]
        nc = len(node)
        # edges: internal (both ends one region) drop; the rest remap —
        # a fused node thereby inherits the union of its region's
        # external out-edges, and goals recount to external in-edges
        edges2: List[List[int]] = [[] for _ in range(nc)]
        for i in range(n):
            src = cid_of[i]
            ri = reg_of[i]
            for k in range(off[i], off[i + 1]):
                t = succs[k]
                if ri >= 0 and reg_of[t] == ri:
                    continue
                edges2[src].append(cid_of[t])
        goals2 = [0] * nc
        for es in edges2:
            for d in es:
                goals2[d] += 1
        off2 = [0] * (nc + 1)
        succs2: List[int] = []
        for i2, es in enumerate(edges2):
            off2[i2 + 1] = off2[i2] + len(es)
            succs2.extend(es)
        prio = flat["prio"]
        prio2 = None
        if prio is not None:
            prio2 = [prio[nd[1]] if nd[0] == "t"
                     else max(prio[m] for m in regions[nd[1]])
                     for nd in node]
        # in-slot lists (the retire protocol): a fused node consumes the
        # multiset of its members' EXTERNAL input slots — decrementing k
        # uses at region retire matches the k per-member decrements the
        # unfused walk would have done; internal consumption vanishes
        # (the region reads those values from its own trace env)
        in2: List[List[int]] = [[] for _ in range(nc)]
        for i2, nd in enumerate(node):
            if nd[0] == "t":
                i = nd[1]
                in2[i2] = list(in_slots[in_off[i]:in_off[i + 1]])
            else:
                mem = member_sets[nd[1]]
                in2[i2] = [ref for m in regions[nd[1]]
                           for ref in in_slots[in_off[m]:in_off[m + 1]]
                           if task_of_slot[ref] not in mem]
        in_off2 = [0] * (nc + 1)
        in_slots2: List[int] = []
        for i2, lst in enumerate(in2):
            in_off2[i2 + 1] = in_off2[i2] + len(lst)
            in_slots2.extend(lst)
        slot_uses2 = [0] * data["n_slots"]
        for ref in in_slots2:
            slot_uses2[ref] += 1
        # per-region replay plans: members in topo order (a valid
        # serialization — the same argument as DTD capture: insertion/
        # topo order respects every internal edge), each flow input
        # resolved statically to an internal value, an external slot, a
        # memory read, or an earlier member's memory WRITE (the region-
        # internal mem env — per-task dispatch would also order those
        # through the release edges)
        wb_by_task: Dict[int, List[Tuple]] = {}
        for tid, dj, dcn, idx in data["writebacks"]:
            wb_by_task.setdefault(tid, []).append((dj, dcn, idx))
        bases = flat["bases"]
        params_by_class = flat["params"]
        rplans: List[Dict[str, Any]] = []
        for ri, members in enumerate(regions):
            ext: List[Tuple] = []
            ext_ix: Dict[Tuple, int] = {}

            def eix(e):
                j = ext_ix.get(e)
                if j is None:
                    j = ext_ix[e] = len(ext)
                    ext.append(e)
                return j

            steps: List[Tuple] = []
            produced: set = set()
            memw: set = set()
            wb_keys: List[Tuple] = []
            for m in members:
                ci = cls_of[m]
                b = slot_base[m]
                nd_ = ndflows[ci]
                srcs: List[Tuple] = []
                for dj in range(nd_):
                    r = in_refs[b + dj]
                    if r == -1:
                        srcs.append(("none", 0))
                    elif r >= 0:
                        srcs.append(("int", r) if r in produced
                                    else ("ext", eix(("slot", r))))
                    else:
                        mi = -2 - r
                        mk = mem_reads[mi]
                        srcs.append(("intm", mk) if mk in memw
                                    else ("ext", eix(("mem", mi))))
                wbs = tuple((dj, (dcn, idx))
                            for dj, dcn, idx in wb_by_task.get(m, ()))
                steps.append((ci, tuple(params_by_class[ci][m - bases[ci]]),
                              tuple(srcs), b, nd_, wbs))
                for dj in range(nd_):
                    produced.add(b + dj)
                for dj, mk in wbs:
                    memw.add(mk)
                    wb_keys.append(mk)
            outs = [slot_base[m] + dj for m in members
                    for dj in range(ndflows[cls_of[m]])
                    if slot_uses2[slot_base[m] + dj] > 0]
            rplans.append({"members": list(members),
                           "kind": kind[members[0]],
                           "ext": ext,
                           "ext_mems": [v for k2, v in ext if k2 == "mem"],
                           "steps": steps, "wb_keys": wb_keys,
                           "out_slots": outs})
        dev_mask2 = None
        ndev_tasks = 0
        if use_dev:
            dev_mask2 = []
            for nd in node:
                if nd[0] == "t":
                    i = nd[1]
                    d = 1 if (dev_classes[cls_of[i]]
                              and ndflows[cls_of[i]] > 0) else 0
                    dev_mask2.append(d)
                    ndev_tasks += d
                else:
                    d = 1 if rplans[nd[1]]["kind"] == "dev" else 0
                    dev_mask2.append(d)
                    if d:
                        ndev_tasks += len(regions[nd[1]])
            if ndev_tasks == 0:
                dev_mask2 = None
        n_fused = sum(len(m) for m in regions)
        return {"node": node, "goals": goals2, "off": off2,
                "succs": succs2, "prio": prio2, "in_off": in_off2,
                "in_slots": in_slots2, "slot_uses": slot_uses2,
                "weights": [1 if nd[0] == "t" else len(regions[nd[1]])
                            for nd in node],
                "orig_of": [nd[1] if nd[0] == "t" else rep_of[nd[1]]
                            for nd in node],
                "rcid": rcid, "regions": rplans,
                "writebacks": [w for w in data["writebacks"]
                               if reg_of[w[0]] < 0],
                "dev_mask": dev_mask2, "ndev_tasks": ndev_tasks,
                "n_seam": n - n_fused, "n_fused": n_fused}

    def _ptexec_class_fns(self, classes: List[TaskClass], data):
        """Per-class (dispatch fn, written flow positions): the jitted
        body for data classes, the raw body for CTL classes, None for
        empty bodies. One home — the batched data callback, the device
        dispatch, and the region program builder must agree."""
        fns, written = [], []
        for ci, tc in enumerate(classes):
            empty = tc._ptg_spec.bodies[0].source.strip() in ("", "pass")
            if data["ndflows"][ci]:
                fns.append(None if empty else tc._ptg_body_fn)
                written.append(tuple(
                    dj for dj, fi in enumerate(data["dflow_idx"][ci])
                    if tc.flows[fi].access & FLOW_ACCESS_WRITE))
            else:
                fns.append(None if empty
                           else getattr(tc, "_ptg_raw_body", None))
                written.append(())
        return fns, written

    def _mk_region_runner(self, graph, cid: int, rp: Dict[str, Any],
                          jitted, slots: List[Any], mem_datas,
                          wb_datas, mod):
        """The fused super-task's dispatch wrapper (CPU regions, called
        from the batched data callback): resolve the region's external
        inputs (producer slots + memory reads at dispatch time — the
        same prepare-at-ready timing as per-task dispatch), run the ONE
        jitted region program, land externally-consumed outputs back
        into their original slot ids, and perform the members' memory
        write-backs in serialization order (one version bump per member
        write, like the per-task path). Brackets the body in EV_REGION
        ring events so merged timelines show regions vs seams."""
        from ...data.data import COHERENCY_OWNED as _OWNED
        ext, out_slots = rp["ext"], rp["out_slots"]
        evr, fs, fe = mod.EV_REGION, mod.FLAG_START, mod.FLAG_END

        def run_region():
            graph.trace_mark(evr, cid, fs)
            ev: List[Any] = []
            for kk, v in ext:
                if kk == "slot":
                    ev.append(slots[v])
                else:
                    copy = mem_datas[v].newest_copy()
                    ev.append(None if copy is None else copy.payload)
            outs, wbs = jitted(tuple(ev))
            for s, v in zip(out_slots, outs):
                if v is None:
                    raise RuntimeError(
                        f"A NULL is forwarded from fused region {cid} "
                        f"(slot {s}, native lane)")
                slots[s] = v
            for dref, v in zip(wb_datas, wbs):
                host = dref.get_copy(0)
                if host is None:
                    dref.create_copy(0, v, _OWNED)
                else:
                    host.payload = v
                dref.bump_version(0)
            graph.trace_mark(evr, cid, fe)
        return run_region

    def _ptexec_lane_fused(self, flat, plan, classes: List[TaskClass],
                           mod, ckey, devlane, place_dev: List[bool],
                           names: Tuple[str, ...],
                           bucket: int) -> Dict[str, Any]:
        """Build the native-lane state for a pool with a fusion plan:
        the compact graph (regions + seams) with original-task weights,
        per-region jitted programs out of the PERSISTENT executable
        cache (program-scoped, keyed by the placement-aware flatten key
        + region index — a second instantiation of the same DAG shape
        reuses the compiled program with zero re-tracing), and the
        region-aware dispatch callbacks."""
        import jax
        data = flat["data"]
        graph = mod.Graph(plan["goals"], plan["off"], plan["succs"],
                          plan["prio"], plan["in_off"], plan["in_slots"],
                          plan["slot_uses"])
        graph.region_bind(plan["weights"])
        slots: List[Any] = [None] * data["n_slots"]
        mem_datas = []
        for dc_name, idx in data["mem_reads"]:
            dc = self.collections.get(dc_name)
            if dc is None:
                output.fatal(f"PTG taskpool {self.name}: unknown "
                             f"collection {dc_name!r}")
            mem_datas.append(dc.data_of(*idx))
        writebacks: Dict[int, List] = {}
        for tid, dj, dc_name, idx in plan["writebacks"]:
            dc = self.collections.get(dc_name)
            if dc is None:
                output.fatal(f"PTG taskpool {self.name}: unknown "
                             f"collection {dc_name!r}")
            writebacks.setdefault(tid, []).append((dj, dc.data_of(*idx)))
        fns, written_by_class = self._ptexec_class_fns(classes, data)
        cache = self.program.__dict__.setdefault(
            "_region_prog_cache", ExecCache(128))
        runners: Dict[int, Any] = {}
        dev_regions: Dict[int, Dict[str, Any]] = {}
        cold_regions: set = set()
        for ri, rp in enumerate(plan["regions"]):
            # the cached object is the TIMED wrapper: its first call (the
            # jit trace+compile) feeds the __region_trace__ pseudo-class
            # fusion sizing reads back; a cache HIT reuses the wrapper
            # with the first call already burned, so warm replays never
            # observe a phantom trace
            jitted, hit = cache.get_or_build(
                None if ckey is None else (ckey, ri),
                lambda rp=rp: _timed_region_program(
                    jax.jit(_mk_region_program(rp, fns, written_by_class)),
                    len(rp["members"])))
            if not hit:
                cold_regions.add(ri)
            wb_datas = []
            for dcn, idx in rp["wb_keys"]:
                dc = self.collections.get(dcn)
                if dc is None:
                    output.fatal(f"PTG taskpool {self.name}: unknown "
                                 f"collection {dcn!r}")
                wb_datas.append(dc.data_of(*idx))
            cid = plan["rcid"][ri]
            if rp["kind"] == "dev":
                dev_regions[cid] = {
                    "ext": rp["ext"], "ext_mems": rp["ext_mems"],
                    "out_slots": rp["out_slots"], "jitted": jitted,
                    "wb_pairs": list(enumerate(wb_datas)),
                    "ntasks": len(rp["members"]),
                    "cls": data["cls_of"][rp["members"][0]],
                    "cold": not hit}
            else:
                runners[cid] = self._mk_region_runner(
                    graph, cid, rp, jitted, slots, mem_datas, wb_datas,
                    mod)
        lane = {"graph": graph, "slots": slots, "n": flat["n"],
                "finalized": False}
        self._ptexec_cost_bind(lane, graph, flat, names, bucket, plan=plan,
                               cold_regions=cold_regions)
        lane["callback"] = self._mk_ptexec_data_callback(
            flat, classes, slots, mem_datas, writebacks,
            fusion={"orig_of": plan["orig_of"], "regions": runners},
            class_fns=(fns, written_by_class))
        PTEXEC_STATS["fused_regions"] += len(plan["regions"])
        PTEXEC_STATS["fused_tasks"] += plan["n_fused"]
        PTEXEC_STATS["seam_tasks"] += plan["n_seam"]
        if devlane is not None and plan["dev_mask"] is not None:
            self._ptexec_bind_dev_fused(lane, devlane, flat, plan,
                                        classes, slots, mem_datas,
                                        writebacks, dev_regions, mod,
                                        place_dev, bucket)
        return lane

    def _ptexec_bind_dev_fused(self, lane: Dict[str, Any], devlane, flat,
                               plan, classes: List[TaskClass],
                               slots: List[Any], mem_datas,
                               writebacks: Dict[int, List],
                               dev_regions: Dict[int, Dict], mod,
                               place_dev: List[bool],
                               bucket: int = 0) -> None:
        """Device binding for a fused pool: same contract as
        :meth:`_ptexec_bind_dev`, but the mask covers compact nodes and
        device REGIONS dispatch as one region-sized async program on
        the lane (ptdev needs nothing new beyond that region-sized
        dispatch — the retire capsule walks the fused node exactly like
        any device task, weighted back to original tasks). ``place_dev``
        is the cost model's EFFECTIVE placement (ISSUE 18), not the
        static has-a-device-body shape — the fusion plan's dev_mask was
        built from the same list, and the two must agree."""
        data = flat["data"]
        dev_of_class = [place_dev[ci] and data["ndflows"][ci] > 0
                        for ci in range(len(classes))]
        graph = lane["graph"]
        cost_obs = self._ptexec_cost_obs(lane)
        dispatch, poll = self._mk_ptexec_dev_dispatch(
            flat, classes, dev_of_class, slots, mem_datas, writebacks,
            devlane, fusion={"orig_of": plan["orig_of"],
                             "dev_regions": dev_regions, "graph": graph,
                             "evr": mod.EV_REGION, "fls": mod.FLAG_START,
                             "fle": mod.FLAG_END},
            cost_obs=cost_obs, bucket=bucket)
        pid = devlane.bind_pool(graph, dispatch, poll)
        lane["dev"] = devlane
        lane["dev_pool"] = pid
        from ...device.native import PTDEV_STATS
        PTDEV_STATS["pools_engaged"] += 1
        PTDEV_STATS["tasks_engaged"] += plan["ndev_tasks"]
        PTEXEC_STATS["pools_device"] += 1
        PTEXEC_STATS["tasks_device"] += plan["ndev_tasks"]
        graph.dev_bind(devlane.submit_capsule(), pid, plan["dev_mask"])
        devlane.clane.notify()

    def _ptexec_cost_obs(self, lane: Dict[str, Any]):
        """The device lane's observation dict (ISSUE 18): (class name,
        bucket, dev) -> [count, sum_ns], written only by the lane's
        manager thread (dispatch/poll run there — no lock needed) and
        folded into the cost model at the lane's detach."""
        from ...core import costmodel as _cm
        if not _cm.enabled():
            return None
        obs = lane.setdefault("cost_dev", {})
        return obs

    def _ptexec_bind_dev(self, lane: Dict[str, Any], devlane, flat,
                         classes: List[TaskClass], dev_classes: List[bool],
                         slots: List[Any], mem_datas,
                         writebacks: Dict[int, List],
                         bucket: int = 0) -> None:
        """Bind a flattened data graph to the native device lane (ISSUE
        10): build the per-pool dispatch/poll closures, register them
        with the lane (the retire capsule routes completions back into
        the graph's GIL-free release walk), and hand the graph the submit
        vtable + per-task device mask — from then on a device-bodied task
        becoming ready surfaces onto the lane's MPSC pending queue
        instead of the ready structure."""
        data = flat["data"]
        # only data-carrying TPU classes ride the device plane; a CTL-only
        # [type=TPU] class has no arrays to place and runs its raw body
        # through the ordinary CPU dispatch
        dev_of_class = [d and nd > 0
                        for d, nd in zip(dev_classes, data["ndflows"])]
        if not any(dev_of_class):
            return
        dev_mask: List[int] = []
        for ci, insts in enumerate(flat["params"]):
            dev_mask.extend([1 if dev_of_class[ci] else 0] * len(insts))
        ndev = sum(dev_mask)
        graph = lane["graph"]
        dispatch, poll = self._mk_ptexec_dev_dispatch(
            flat, classes, dev_of_class, slots, mem_datas, writebacks,
            devlane, cost_obs=self._ptexec_cost_obs(lane), bucket=bucket)
        pid = devlane.bind_pool(graph, dispatch, poll)
        lane["dev"] = devlane
        lane["dev_pool"] = pid
        from ...device.native import PTDEV_STATS
        PTDEV_STATS["pools_engaged"] += 1
        PTDEV_STATS["tasks_engaged"] += ndev
        PTEXEC_STATS["pools_device"] += 1
        PTEXEC_STATS["tasks_device"] += ndev
        graph.dev_bind(devlane.submit_capsule(), pid, dev_mask)
        devlane.clane.notify()

    def _mk_ptexec_dev_dispatch(self, flat, classes: List[TaskClass],
                                dev_of_class: List[bool], slots: List[Any],
                                mem_datas, writebacks: Dict[int, List],
                                devlane, fusion=None, cost_obs=None,
                                bucket=0):
        """The device lane's per-pool dispatch/poll pair, both run on the
        lane's manager thread with the GIL held:

        * ``dispatch(ids)`` — the push+exec phases of the reference's
          stream pipeline (device_gpu.c:3438), collapsed onto XLA's async
          runtime: FIRST every memory-endpoint input of the whole batch
          stages in (version-checked through the C coherency table;
          ``device_put`` is asynchronous, so these H2D transfers overlap
          whatever compute is already in flight — the early-push overlap
          the interpreted path never had), THEN each task's jitted body
          dispatches (async) and its future outputs land in the lane's
          slot array immediately — safe because no consumer can run
          before this task RETIRES, which only happens after its
          completion events fire;
        * ``poll()`` — the event queue: ``jax.Array.is_ready`` over each
          inflight task's outputs (cudaEventQuery, device_gpu.c:2593).
          Completed tasks perform their memory write-backs + version
          bumps, drop their stage-in pins, and return their ids — the C
          side then calls the graph's GIL-free ``dev_retire``.
        """
        from ...data.data import COHERENCY_OWNED as _OWNED
        dev = devlane.device
        bases = flat["bases"]
        params_by_class = flat["params"]
        data = flat["data"]
        slot_base = data["slot_base"]
        in_refs = data["in_refs"]
        ndflows = data["ndflows"]
        cls_of = data["cls_of"]
        fns, written_by_class = [], []
        for ci, tc in enumerate(classes):
            empty = tc._ptg_spec.bodies[0].source.strip() in ("", "pass")
            fns.append(None if empty or not dev_of_class[ci]
                       else tc._ptg_body_fn)
            written_by_class.append(tuple(
                dj for dj, fi in enumerate(data["dflow_idx"][ci])
                if tc.flows[fi].access & FLOW_ACCESS_WRITE))
        import collections as _collections
        inflight: "_collections.deque" = _collections.deque()
        # device-side cost observation (ISSUE 18): each inflight entry is
        # stamped at dispatch and observed at retire — the elapsed window
        # covers the async compute, the output-ready wait, AND the lane's
        # poll cadence, i.e. the throughput a task actually experiences
        # on this path (what placement must compare against the CPU
        # lane's batch-amortized cost). Stage-ins time separately into
        # the __stage_in__ pseudo-class. All writes happen on the
        # manager thread; the fold reads after unbind.
        _pc = time.perf_counter_ns
        dev_clock = [0]      # batch-amortization mark (see poll)
        if cost_obs is not None:
            from ...core.costmodel import STAGE_IN as _STG, shape_bucket
            cnames = [f"{self.program.spec.name}.{tc._ptg_spec.name}"
                      for tc in classes]

            def _obs(key, w, ns):
                e = cost_obs.get(key)
                if e is None:
                    cost_obs[key] = [w, ns]
                else:
                    e[0] += w
                    e[1] += ns

            def _stage(mi):
                t0 = _pc()
                copy = dev.lane_stage_in(mem_datas[mi], pin=True)
                nb = getattr(getattr(copy, "payload", None), "nbytes", 0)
                _obs((_STG, shape_bucket(nb), "tpu"), 1, _pc() - t0)
                return copy
        else:
            _obs = None

            def _stage(mi):
                return dev.lane_stage_in(mem_datas[mi], pin=True)
        if fusion is not None:
            # fused pool (ISSUE 12): a device REGION dispatches as one
            # region-sized async program; its inflight/retire id is the
            # COMPACT node id (what the C release walk expects), while
            # slot/param arrays index by original id via orig_of
            _forig = fusion["orig_of"]
            _dregs = fusion["dev_regions"]
            _graph = fusion["graph"]
            _evr, _fs, _fe = fusion["evr"], fusion["fls"], fusion["fle"]
        else:
            _forig = _dregs = _graph = None

        def dispatch(ids):
            # PUSH phase: issue every memory-endpoint stage-in for the
            # whole batch before any compute dispatch. Each staged copy is
            # pinned THE MOMENT it stages: under a tight budget, staging
            # tile k+1 of this very batch can otherwise evict tile k
            # before the exec phase reads it (found by the verify drive —
            # "dot got NoneType"). Batch pins release after the exec
            # phase has taken its per-task inflight pins.
            staged: Dict[int, Any] = {}
            batch_pins: List[Any] = []
            if _obs is not None and not inflight:
                # idle -> active: restart the amortization clock so idle
                # gaps between batches never land in any task's cost
                dev_clock[0] = _pc()
            for i in ids:
                if _dregs is not None:
                    r = _dregs.get(i)
                    if r is not None:
                        for mi in r["ext_mems"]:
                            if mi not in staged:
                                copy = _stage(mi)
                                batch_pins.append(copy)
                                staged[mi] = copy
                        continue
                    i = _forig[i]
                base = slot_base[i]
                for dj in range(ndflows[cls_of[i]]):
                    r = in_refs[base + dj]
                    if r < -1 and (-2 - r) not in staged:
                        mi = -2 - r
                        # pin=True: the eviction pin is taken inside the
                        # table's reserve critical section, so no peer
                        # thread's stage-in can evict this entry first
                        copy = _stage(mi)
                        batch_pins.append(copy)
                        staged[mi] = copy
            # EXEC phase: dispatch each ready device task asynchronously
            for i in ids:
                oi = i
                if _dregs is not None:
                    r = _dregs.get(i)
                    if r is not None:
                        # region-sized dispatch: ONE jitted program for
                        # the whole fused region, async like any task;
                        # the retire id stays the compact node id
                        pins: List[Any] = []
                        ev: List[Any] = []
                        for kk, v in r["ext"]:
                            if kk == "slot":
                                ev.append(slots[v])
                            else:
                                copy = staged[v]
                                dev.pin_copy(copy)
                                pins.append(copy)
                                ev.append(copy.payload)
                        _graph.trace_mark(_evr, i, _fs)
                        outs, wbs_v = r["jitted"](tuple(ev))
                        _graph.trace_mark(_evr, i, _fe)
                        for s, v in zip(r["out_slots"], outs):
                            slots[s] = v
                        events = tuple(v for v in tuple(outs) + tuple(wbs_v)
                                       if hasattr(v, "is_ready"))
                        inflight.append((
                            i, events, r["wb_pairs"], list(wbs_v), pins,
                            r["ntasks"],
                            None if (_obs is None or r.get("cold")) else
                            (cnames[r["cls"]], bucket, "tpu_fused")))
                        continue
                    oi = _forig[i]
                k = cls_of[oi]
                base = slot_base[oi]
                nd = ndflows[k]
                vals: List[Any] = []
                pins = []
                for dj in range(nd):
                    r = in_refs[base + dj]
                    if r >= 0:
                        vals.append(slots[r])
                    elif r == -1:
                        vals.append(None)
                    else:
                        copy = staged[-2 - r]
                        dev.pin_copy(copy)     # readers guard while inflight
                        pins.append(copy)
                        vals.append(copy.payload)
                fn = fns[k]
                events = ()
                if fn is not None:
                    outs = fn(*params_by_class[k][oi - bases[k]], *vals)
                    for oj, dj in enumerate(written_by_class[k]):
                        vals[dj] = outs[oj]
                    events = tuple(v for v in outs
                                   if hasattr(v, "is_ready"))
                for dj in range(nd):
                    slots[base + dj] = vals[dj]
                inflight.append((i, events, writebacks.get(oi), vals, pins,
                                 1,
                                 None if _obs is None else
                                 (cnames[k], bucket, "tpu")))
            for copy in batch_pins:         # per-task pins hold from here
                dev.unpin_copy(copy)
            return len(ids)

        def poll():
            done: List[int] = []
            retired: List[Tuple] = []
            for _ in range(len(inflight)):
                ent = inflight.popleft()
                i, events, wbs, vals, pins, w, ckey2 = ent
                if events and not all(a.is_ready() for a in events):
                    inflight.append(ent)
                    continue
                if wbs:
                    for dj, dref in wbs:
                        v = vals[dj]
                        host = dref.get_copy(0)
                        if host is None:
                            dref.create_copy(0, v, _OWNED)
                        else:
                            host.payload = v
                        dref.bump_version(0)
                for copy in pins:
                    dev.unpin_copy(copy)
                dev.executed_tasks += w
                retired.append((ckey2, w))
                done.append(i)
            if retired and _obs is not None:
                # batch amortization, the SAME semantics as the C lane's
                # exec bump: the wall window since the last retire sweep
                # (or the idle->active mark) divides across every task
                # weight retired in it. Per-entry dispatch->retire spans
                # overlap under pipelining, so summing them would bill
                # the same wall clock N-inflight times over and make the
                # device look slower than the wall it actually consumed
                # — placement would then mis-compare against the CPU
                # lane's throughput-denominated cost. Keyless entries
                # (cold regions) still weigh in the denominator: they
                # consumed part of the window.
                now = _pc()
                total_w = sum(w for _, w in retired)
                per = (now - dev_clock[0]) / max(total_w, 1)
                for ckey2, w in retired:
                    if ckey2 is not None:
                        _obs(ckey2, w, per * w)
                dev_clock[0] = now
            return done

        return dispatch, poll

    def _ptexec_owners(self, classes: List[TaskClass],
                       flat) -> Optional[List[int]]:
        """Per-task owner ranks in flattened-id order, or None when any
        rank is out of range (the lane declines rather than misroute)."""
        nb = self.ctx.nb_ranks
        owners: List[int] = []
        for ci, tc in enumerate(classes):
            params = tc._ptg_spec.params
            rank_of = tc._ptg_rank_of
            for key in flat["params"][ci]:
                try:
                    r = int(rank_of(dict(zip(params, key))))
                except Exception:  # noqa: BLE001 — decline, don't die
                    return None
                if not 0 <= r < nb:
                    return None
                owners.append(r)
        return owners

    def _ptexec_bind_comm(self, lane: Dict[str, Any], lane_comm,
                          owners: List[int]) -> None:
        """Bind a flattened graph to the native comm lane: allocate the
        rank-consistent pool id, hand the owner table + send vtable to
        the graph (remote successors then surface as activation frames
        from the GIL-free release sweep), and route this pool's inbound
        frames into the graph's ingest entry points. ``lane['n']``
        becomes the LOCAL task count — the pool accounting a rank owns."""
        pool_id = lane_comm.pool_id_for(self.name)
        graph = lane["graph"]
        # comm/compute overlap is measured, not asserted: the comm
        # lane's EV_COMM_* ring joins the same trace the engines feed.
        # Armed BEFORE the pool registration so a frame that lands the
        # instant routing opens records its ingest point — frames that
        # raced even earlier park and replay with recording, so the
        # merged timeline never reports a send without its ingest
        self.ctx._ntrace_attach("ptcomm", lane_comm.comm)
        self.ctx._hist_attach("ptcomm", lane_comm.comm)
        n_local = graph.comm_bind(lane_comm.comm.send_capsule(), pool_id,
                                  self.ctx.my_rank, owners)
        lane_comm.register_engine(pool_id, graph)
        lane["pool_id"] = pool_id
        lane["comm"] = lane_comm
        lane["n"] = n_local

    def _ptexec_comm_data(self, flat, owners: List[int]) -> Dict[str, Any]:
        """Distributed data-pool tables, derived per instantiation:

        * ``slot_uses``: LOCAL consumer count per slot (the retire
          protocol runs rank-local; a remote consumer's use is satisfied
          by the payload send at production time);
        * ``remote_in``: input slots whose producer runs elsewhere — the
          dispatch callback materializes them from the comm lane's
          payload store (landed eager or pulled rendezvous);
        * ``feeds``: produced slot -> destination ranks (payload ships
          once per rank, before the release sweep's activations — FIFO
          frame order makes eager payloads race-free)."""
        data = flat["data"]
        me = self.ctx.my_rank
        in_off, in_slots = data["in_off"], data["in_slots"]
        slot_base, cls_of = data["slot_base"], data["cls_of"]
        ndflows = data["ndflows"]
        n = flat["n"]
        task_of_slot = [0] * data["n_slots"]
        for tid in range(n):
            base = slot_base[tid]
            for dj in range(ndflows[cls_of[tid]]):
                task_of_slot[base + dj] = tid
        slot_uses = [0] * data["n_slots"]
        remote_in = set()
        feeds: Dict[int, List[int]] = {}
        for tid in range(n):
            local = owners[tid] == me
            for k in range(in_off[tid], in_off[tid + 1]):
                ref = in_slots[k]
                producer_local = owners[task_of_slot[ref]] == me
                if local:
                    slot_uses[ref] += 1
                    if not producer_local:
                        remote_in.add(ref)
                elif producer_local:
                    dsts = feeds.setdefault(ref, [])
                    if owners[tid] not in dsts:
                        dsts.append(owners[tid])
        return {"slot_uses": slot_uses, "remote_in": frozenset(remote_in),
                "feeds": feeds}

    def _mk_ptexec_callback(self, bases: List[int], bodies,
                            params_by_class):
        """Batched body dispatch: the engine hands over a list of ready
        task ids; every body must run before it returns (successor release
        happens after, preserving release-edge ordering for observers)."""
        import bisect as _bisect
        def run_batch(ids):
            for i in ids:
                k = _bisect.bisect_right(bases, i) - 1
                fn = bodies[k]
                if fn is not None:
                    fn(*params_by_class[k][i - bases[k]])
        return run_batch

    def _mk_ptexec_data_callback(self, flat, classes: List[TaskClass],
                                 slots: List[Any], mem_datas,
                                 writebacks: Dict[int, List], comm=None,
                                 fusion=None, class_fns=None):
        """Batched dispatch for data-flow pools — the lane's replacement
        for generic_prepare_input + the body hook + complete_execution +
        the repo side of generic_release_deps, amortized over one Python
        call per ~256 ready tasks:

        * inputs resolve from the slot array (producer outputs), memory
          endpoints (``newest_copy`` at dispatch time, matching the Python
          FSM's prepare-at-ready timing), or None (``NEW``);
        * non-empty bodies call the class's jitted function (the same
          object the CPU hook dispatches) — empty bodies forward inputs
          by identity with no dispatch at all;
        * every data flow's post-body value lands in the task's own slot
          (data_out for written flows, forwarded data_in otherwise), then
          memory out-deps write back and bump the data version;
        * ``retired`` slot ids (reported by the engine once a slot's last
          consumer body has run) drop their payload reference — the
          entry-retire moment of core/datarepo.py, one list op instead of
          a locked hash-table dance per use.

        With ``comm`` set (a distributed pool on the native comm lane),
        two extra moves happen inside the same batched dispatch: input
        slots produced on another rank materialize from the comm lane's
        payload store (landed eager, or rendezvous-pulled — readiness was
        gated in C until the pull completed), and produced slots feeding
        remote consumers ship BEFORE the engine's release sweep sends
        their activations, so the per-link FIFO makes eager data
        race-free by construction.
        """
        from ...data.data import COHERENCY_OWNED as _OWNED
        bases = flat["bases"]
        params_by_class = flat["params"]
        data = flat["data"]
        slot_base = data["slot_base"]
        in_refs = data["in_refs"]
        slot_uses = data["slot_uses"]
        ndflows = data["ndflows"]
        cls_of = data["cls_of"]
        # fused pools pass the SAME (fns, written) pair their region
        # programs were jitted against — one object, not two derivations
        fns, written_by_class = class_fns if class_fns is not None \
            else self._ptexec_class_fns(classes, data)
        if fusion is not None:
            # fused pool: region nodes dispatch through their runner,
            # everything else maps its compact id back to the original
            # (the arrays above are all original-id indexed); the C side
            # retires slots by original slot id either way
            _forig = fusion["orig_of"]
            _fregions = fusion["regions"]
        else:
            _forig = _fregions = None
        # single-data-flow classes whose flow is WRITTEN are the hot shape
        # (RW chains); the dispatch loop specializes them. A READ-only
        # single flow must take the general path: its body returns an
        # EMPTY written tuple and the flow forwards the input unchanged
        single = [nd == 1 and w == (0,)
                  for nd, w in zip(ndflows, written_by_class)]
        if comm is not None:
            lane, pool = comm["lane"], comm["pool_id"]
            remote_in, feeds = comm["remote_in"], comm["feeds"]
        else:
            lane = pool = None
            remote_in, feeds = frozenset(), {}
        has_feeds = bool(feeds)
        #: remote slots already materialized (so a producer's legitimate
        #: None payload is not re-fetched); retire clears entries
        fetched: set = set()
        _fetch_mu = threading.Lock()

        def _fetch_remote(r):
            # two workers can dispatch two consumers of the same remote
            # slot concurrently; take_payload CONSUMES the C-side entry,
            # so the check-then-fetch must be atomic (rare path: once
            # per remote slot — the lock never touches local slots)
            with _fetch_mu:
                if r in fetched:
                    return slots[r]
                v = lane.take_payload(pool, r)
                slots[r] = v
                fetched.add(r)
                return v

        def _null_guard(k, i):
            raise RuntimeError(
                f"A NULL is forwarded from {classes[k]._ptg_spec.name}"
                f"{tuple(params_by_class[k][i - bases[k]])} (native lane)")

        def run_batch(ids, retired):
            # locals: this loop runs once per TASK of every data pool
            _slots, _refs, _uses = slots, in_refs, slot_uses
            _base, _cls, _wb = slot_base, cls_of, writebacks
            for j in retired:
                _slots[j] = None          # the entry-retire moment
            if fetched:
                for j in retired:
                    fetched.discard(j)
            for i in ids:
                if _forig is not None:
                    rr = _fregions.get(i)
                    if rr is not None:
                        rr()              # ONE fused super-task dispatch
                        continue
                    i = _forig[i]
                k = _cls[i]
                fn = fns[k]
                nd = ndflows[k]
                if nd == 0:               # CTL class riding a data pool
                    if fn is not None:
                        fn(*params_by_class[k][i - bases[k]])
                    continue
                base = _base[i]
                if single[k]:
                    r = _refs[base]
                    if r >= 0:
                        v = _slots[r]
                        if v is None and r in remote_in \
                                and r not in fetched:
                            # produced on another rank: materialize from
                            # the comm lane's payload store (consumed
                            # once; later local readers hit _slots[r])
                            v = _fetch_remote(r)
                    elif r == -1:
                        v = None
                    else:
                        copy = mem_datas[-2 - r].newest_copy()
                        v = None if copy is None else copy.payload
                    if fn is not None:
                        v = fn(*params_by_class[k][i - bases[k]], v)[0]
                    if v is None and _uses[base] > 0:
                        _null_guard(k, i)    # parsec.c:1879 source guard
                    _slots[base] = v
                    if has_feeds:
                        dsts = feeds.get(base)
                        if dsts:
                            # ship BEFORE the release sweep runs: the
                            # consumer's activation then trails its data
                            # on the FIFO link
                            for dst in dsts:
                                lane.send_payload(dst, pool, base, v)
                    wbs = _wb.get(i)
                    if wbs is None:
                        continue
                    vals = (v,)
                else:
                    vals = []
                    for dj in range(nd):
                        r = _refs[base + dj]
                        if r >= 0:
                            v = _slots[r]
                            if v is None and r in remote_in \
                                    and r not in fetched:
                                v = _fetch_remote(r)
                            vals.append(v)
                        elif r == -1:
                            vals.append(None)
                        else:
                            copy = mem_datas[-2 - r].newest_copy()
                            vals.append(None if copy is None
                                        else copy.payload)
                    if fn is not None:
                        outs = fn(*params_by_class[k][i - bases[k]], *vals)
                        for oj, dj in enumerate(written_by_class[k]):
                            vals[dj] = outs[oj]
                    for dj in range(nd):
                        v = vals[dj]
                        if v is None and _uses[base + dj] > 0:
                            _null_guard(k, i)
                        _slots[base + dj] = v
                    if has_feeds:
                        for dj in range(nd):
                            dsts = feeds.get(base + dj)
                            if dsts:
                                for dst in dsts:
                                    lane.send_payload(dst, pool, base + dj,
                                                      vals[dj])
                    wbs = _wb.get(i)
                    if wbs is None:
                        continue
                for dj, dref in wbs:
                    v = vals[dj]
                    host = dref.get_copy(0)
                    if host is None:
                        dref.create_copy(0, v, _OWNED)
                    else:
                        host.payload = v
                    dref.bump_version(0)
        return run_batch

    def _ptexec_finalize(self, lane: Dict[str, Any]) -> None:
        """Called exactly once (by whichever stream drains the graph last)
        after every lane task executed: retire the task accounting in one
        step — the per-task complete/release cycle already ran in C — and
        drop the remaining slot payloads (terminal outputs were already
        written back by the callback; slots the last release sweep retired
        never met another dispatch to clear them)."""
        output.debug_verbose(2, "ptg",
                             f"{self.name}: native lane retired "
                             f"{lane['n']} tasks")
        if lane.get("pool_id") is not None:
            # stop routing this pool's frames; parked payloads (already
            # consumed or unreachable) drop with the registration
            lane["comm"].unregister_engine(lane["pool_id"])
        if lane.get("dev_pool") is not None:
            # every device task retired (the graph is done), so the lane
            # owes this pool nothing; drop the routing + the engine pin
            lane["dev"].unbind_pool(lane["dev_pool"])
        slots = lane.get("slots")
        if slots:
            # lane-side datarepo accounting into the counter registry
            # (the slot_stats retire counter, ptexec.slots_retired)
            from ...utils.counters import PTEXEC_SLOTS_RETIRED, counters
            counters.add(PTEXEC_SLOTS_RETIRED, lane["graph"].slot_stats()[1])
            slots.clear()
        self.addto_nb_tasks(-lane["n"])

    # ------------------------------------------------------------------ startup
    def _startup(self, stream, tp) -> List[Task]:
        total = 0
        ready: List[Task] = []
        my_rank = self.ctx.my_rank
        distributed = self.ctx.nb_ranks > 1 and self.ctx.comm is not None
        agg = {tcs.name for tcs in self.program.spec.task_classes
               if self._agglomerable(self._classes[tcs.name])}
        self._agglomerated = 0
        for name in agg:
            self._agglomerated += self._run_agglomerated(
                stream, self._classes[name])
        nonagg = any(tcs.name not in agg
                     for tcs in self.program.spec.task_classes)
        lane = self._ptexec_prepare(agg)
        if lane is not None:
            PTEXEC_STATS["pools_engaged"] += 1
            PTEXEC_STATS["tasks_engaged"] += lane["n"]
            if lane.get("pool_id") is not None:
                from ...comm.native import PTCOMM_STATS
                PTCOMM_STATS["pools_engaged"] += 1
                PTCOMM_STATS["tasks_engaged"] += lane["n"]
            self._ptexec_state = lane
            self.set_nb_tasks(lane["n"])
            if lane["n"]:
                self.ctx._ptexec_enqueue(self, lane)
            elif lane.get("pool_id") is not None:
                # a rank owning zero tasks of a distributed pool still
                # keeps the registration until the pool is globally done;
                # nothing will be ingested, unregistration happens at
                # lane fini (no local finalize will run)
                pass
            output.debug_verbose(2, "ptg",
                                 f"{self.name}: {lane['n']} tasks on the "
                                 f"native execution lane")
            return []
        if nonagg and mca.get("ptg_native_exec", True):
            if self._ptexec_refusal == "fallback":
                PTEXEC_STATS["pools_fallback"] += 1
            else:
                PTEXEC_STATS["pools_ineligible"] += 1
            if distributed:
                from ...comm.native import PTCOMM_STATS
                PTCOMM_STATS["pools_fallback" if self._ptexec_refusal ==
                             "fallback" else "pools_ineligible"] += 1
        for tcs in self.program.spec.task_classes:
            if tcs.name in agg:
                continue        # executed above, never scheduled/counted
            tc = self._classes[tcs.name]
            for loc in self._enum_class(tc):
                if distributed and tc._ptg_rank_of(loc) != my_rank:
                    continue
                total += 1
                if getattr(tc, "_ptg_startup_fn", None) is not None:
                    continue    # custom startup seeds this class below
                if tc.dependencies_goal_fn(loc) == 0:
                    ready.append(self.ctx.make_task(self, tc, loc))
        # user-defined startup (ref: udf.jdf startup_fn): fn(taskpool,
        # task_class) yields the locals of this class's initial ready tasks
        for tcs in self.program.spec.task_classes:
            tc = self._classes[tcs.name]
            fn = getattr(tc, "_ptg_startup_fn", None)
            if fn is None:
                continue
            for loc in fn(self, tc):
                loc = dict(loc)
                if distributed and tc._ptg_rank_of(loc) != my_rank:
                    continue
                ready.append(self.ctx.make_task(self, tc, loc))
        self.set_nb_tasks(total)
        output.debug_verbose(2, "ptg",
                             f"{self.name}: {total} tasks, {len(ready)} at startup")
        return ready


class PTGProgram:
    """A compiled PTG program; instantiate per (globals, collections) run."""

    def __init__(self, spec: P.ProgramSpec) -> None:
        self.spec = spec

    def instantiate(self, ctx: Context, globals: Optional[Dict[str, Any]] = None,
                    collections: Optional[Dict[str, Any]] = None,
                    name: Optional[str] = None,
                    datatypes: Optional[Dict[str, NamedDatatype]] = None
                    ) -> PTGTaskpool:
        return PTGTaskpool(self, ctx, dict(globals or {}),
                           dict(collections or {}), name,
                           datatypes=datatypes)


def compile_ptg(source: str, name: str = "ptg") -> PTGProgram:
    """Compile PTG source (the parsec-ptgpp entry point)."""
    return PTGProgram(P.parse(source, name))
