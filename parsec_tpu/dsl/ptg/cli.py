"""``parsec-tpu-ptgc`` — the ptgpp-role CLI (ref: tools/ptgpp).

The reference's ptgpp translates a .jdf file to C; here PTG sources are
host-language strings compiled at runtime, so the CLI's job is the
*front-half* of that role: parse + class-build a ``.ptg`` file, report its
task classes, parameter spaces, flows and dependency structure, and fail
with ptgpp-style diagnostics on bad input — the compile gate a build
system can run without executing the program.

Usage::

    parsec-tpu-ptgc program.ptg                 # check + summary
    parsec-tpu-ptgc program.ptg --globals N=4   # also enumerate task counts
"""

from __future__ import annotations

import argparse
import sys


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="parsec-tpu-ptgc",
        description="compile-check a PTG source file (the ptgpp role)")
    ap.add_argument("source", help=".ptg source file")
    ap.add_argument("--globals", nargs="*", default=[], metavar="NAME=INT",
                    help="global values; enables task-space enumeration")
    ap.add_argument("-q", "--quiet", action="store_true",
                    help="exit status only")
    opts = ap.parse_args(argv)

    from . import parser as P

    try:
        src = open(opts.source).read()
    except OSError as e:
        print(f"parsec-tpu-ptgc: {e}", file=sys.stderr)
        return 2
    try:
        spec = P.parse(src, opts.source)
    except P.PTGSyntaxError as e:
        print(f"parsec-tpu-ptgc: {e}", file=sys.stderr)
        return 1

    if not opts.quiet:
        print(f"{opts.source}: {len(spec.task_classes)} task class(es)")
        for tcs in spec.task_classes:
            flows = ", ".join(f"{f.access} {f.name}" for f in tcs.flows)
            print(f"  {tcs.name}({', '.join(tcs.params)})"
                  + (f"  [{flows}]" if flows else "  [flowless]"))

    if opts.globals:
        import jax
        jax.config.update("jax_platforms", "cpu")
        from ...core.context import Context
        from .compiler import PTGProgram
        g = {}
        for item in opts.globals:
            name, _, val = item.partition("=")
            g[name] = int(val)
        ctx = Context(nb_cores=1)
        try:
            tp = PTGProgram(spec).instantiate(ctx, globals=g, collections={},
                                              name="ptgc-check")
            total = sum(1 for _ in tp._enumerate())
            if not opts.quiet:
                print(f"  task space under {g}: {total} task(s)")
        finally:
            ctx.fini(timeout=10)
    return 0


if __name__ == "__main__":
    sys.exit(main())
