"""PTG source parser: a JDF-flavored task-graph language.

Plays the role of the reference's JDF front end (lexer parsec.l, grammar
parsec.y, AST jdf.h) re-imagined for a Python/JAX host language: parameter
ranges, affinity, guarded dataflow expressions, and per-device bodies — but
expressions are Python expressions and bodies are jittable Python/JAX code,
so PTG task bodies compile straight to XLA executables.

Source shape (one taskpool per file/string)::

    %global NT
    %global descA          // a data collection

    T(k)
      k = 0 .. NT-1        // inclusive range, like JDF
      : descA(k)           // affinity (owner-computes)
      priority = NT - k
      RW  X <- (k == 0) ? descA(k) : X T(k-1)
          ->  (k < NT-1) ? X T(k+1) : descA(k)
      READ Y <- descB(k)
      CTL c -> c T(k+1)
    BODY [type=TPU]
      X = X + Y
    END

Guards use the JDF C-ternary form ``(cond) ? EP : EP`` or a plain guarded
endpoint ``(cond) ? EP``; conditions and index expressions are Python.
Endpoints: ``FLOW Class(exprs)`` (peer task), ``Collection(exprs)`` (memory),
``NEW`` (scratch), ``NULL``. Bodies end with ``END``; multiple BODY blocks
give per-device chores (ref: __parsec_chore_t incarnations).
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

FLOW_READ = "READ"
FLOW_WRITE = "WRITE"
FLOW_RW = "RW"
FLOW_CTL = "CTL"

_ACCESS_KEYWORDS = {"READ": FLOW_READ, "WRITE": FLOW_WRITE, "RW": FLOW_RW,
                    "CTL": FLOW_CTL, "IN": FLOW_READ, "OUT": FLOW_WRITE,
                    "INOUT": FLOW_RW}

MAX_LOCAL_COUNT = 16   # mirrors the ptgpp negative test too_many_local_vars
MAX_FLOW_COUNT = 16    # mirrors too_many_write_flows-style limits


class PTGSyntaxError(SyntaxError):
    """Compile-time rejection, the analogue of parsec-ptgpp fatal errors."""

    def __init__(self, msg: str, line_no: int = 0, line: str = "") -> None:
        where = f" (line {line_no}: {line.strip()!r})" if line_no else ""
        super().__init__(msg + where)
        self.line_no = line_no


@dataclass
class Endpoint:
    """One side of a dep: a peer task flow, a memory reference, NEW or NULL."""
    kind: str                      # 'task' | 'memory' | 'new' | 'null'
    name: str = ""                 # task class or collection name
    flow: str = ""                 # peer flow name (task endpoints)
    index_exprs: List[str] = field(default_factory=list)


@dataclass
class DepSpec:
    direction: str                 # 'in' | 'out'
    guard: Optional[str] = None    # python expression or None
    endpoint: Optional[Endpoint] = None
    else_endpoint: Optional[Endpoint] = None   # ternary alternative
    line_no: int = 0
    dtt: Optional[str] = None          # [type = NAME] named datatype
    dtt_remote: Optional[str] = None   # [type_remote = NAME] wire-only


@dataclass
class FlowSpec:
    name: str
    access: str
    deps: List[DepSpec] = field(default_factory=list)


@dataclass
class RangeSpec:
    param: str
    lo_expr: str
    hi_expr: str                  # inclusive, like JDF
    step_expr: str = "1"


@dataclass
class BodySpec:
    device: str = "CPU"           # CPU | TPU
    source: str = ""
    line_no: int = 0
    evaluate: Optional[str] = None   # [evaluate = fn]: chore gate, resolved
                                     # from taskpool globals


@dataclass
class TaskClassSpec:
    name: str
    params: List[str]
    #: header property block ``NAME(m, n) [ make_key_fn = f ... ]``
    #: (ref: udf.jdf make_key_fn/startup_fn/time_estimate properties)
    header_props: Dict[str, str] = field(default_factory=dict)
    ranges: List[RangeSpec] = field(default_factory=list)
    affinity: Optional[Endpoint] = None
    priority_expr: Optional[str] = None
    properties: Dict[str, str] = field(default_factory=dict)
    flows: List[FlowSpec] = field(default_factory=list)
    bodies: List[BodySpec] = field(default_factory=list)

    def flow(self, name: str) -> Optional[FlowSpec]:
        for f in self.flows:
            if f.name == name:
                return f
        return None


@dataclass
class ProgramSpec:
    globals: List[str] = field(default_factory=list)
    task_classes: List[TaskClassSpec] = field(default_factory=list)
    name: str = "ptg"
    #: host-language prologue executed into program globals at instantiate
    #: time (the JDF inline-C escape 'extern "C" %{...%}', jdf2c.c:54)
    prologue: str = ""

    def task_class(self, name: str) -> Optional[TaskClassSpec]:
        for tc in self.task_classes:
            if tc.name == name:
                return tc
        return None


_RE_GLOBAL = re.compile(r"^%global\s+(\w+)\s*$")
_RE_OPTION = re.compile(r"^%option\s+(\w+)\s*=\s*(\S+)\s*$")
_RE_HEADER = re.compile(r"^(\w+)\s*\(\s*([\w\s,]*)\)\s*(?:\[([^\]]*)\])?\s*$")
_RE_RANGE = re.compile(r"^(\w+)\s*=\s*(.+?)\s*\.\.\s*(.+?)(?:\s*\.\.\s*(.+?))?\s*$")
_RE_AFFINITY = re.compile(r"^:\s*(\w+)\s*\(([^)]*)\)\s*$")
_RE_PROPERTY = re.compile(r"^(\w+)\s*=\s*(.+)$")
_RE_BODY = re.compile(r"^BODY(?:\s*\[([^\]]*)\])?\s*$")


def _strip_comment(line: str) -> str:
    # '//' comments, but not inside strings (bodies handled separately)
    idx = line.find("//")
    return line[:idx] if idx >= 0 else line


def _match_call(text: str) -> Optional[Tuple[str, str]]:
    """``NAME(exprs)`` with BALANCED parens -> (name, inner) or None.
    The old regex form ``\\(([^)]*)\\)`` broke on nested parentheses in
    index expressions (e.g. ``T(((a*i+b) % N), 0)``); endpoints accept
    the same nesting the expression splitter already does."""
    m = re.match(r"^(\w+)\s*\(", text)
    if not m:
        return None
    depth, start = 0, m.end() - 1
    for i in range(start, len(text)):
        if text[i] == "(":
            depth += 1
        elif text[i] == ")":
            depth -= 1
            if depth == 0:
                if text[i + 1:].strip():
                    return None          # trailing junk after the call
                return m.group(1), text[start + 1:i]
    return None                          # unbalanced


def _parse_endpoint(text: str, line_no: int, line: str) -> Endpoint:
    text = text.strip()
    if text == "NEW":
        return Endpoint("new")
    if text == "NULL":
        return Endpoint("null")
    parts = text.split(None, 1)
    if len(parts) == 2 and re.fullmatch(r"\w+", parts[0]):
        call = _match_call(parts[1])
        if call is not None:
            # "X T(k-1)" — flow then class
            return Endpoint("task", name=call[0], flow=parts[0],
                            index_exprs=_split_exprs(call[1]))
    call = _match_call(text)
    if call is not None:
        return Endpoint("memory", name=call[0],
                        index_exprs=_split_exprs(call[1]))
    raise PTGSyntaxError(f"cannot parse dependency endpoint {text!r}",
                         line_no, line)


def _split_exprs(text: str) -> List[str]:
    """Split comma-separated expressions, respecting nested parens."""
    out, depth, cur = [], 0, []
    for ch in text:
        if ch == "," and depth == 0:
            out.append("".join(cur).strip())
            cur = []
            continue
        if ch in "([":
            depth += 1
        elif ch in ")]":
            depth -= 1
        cur.append(ch)
    tail = "".join(cur).strip()
    if tail:
        out.append(tail)
    return out


_RE_DEP_ATTRS = re.compile(r"\[([^\]]*)\]\s*$")
_RE_DEP_ATTR = re.compile(r"(\w+)\s*=\s*(\w+)")


def _parse_attr_block(body: str, allowed, what: str, line_no: int,
                      line: str) -> Dict[str, str]:
    """Shared '[key = NAME ...]' attribute grammar (deps, BODY, task
    headers). Malformed blocks and unknown keys are parse errors — a
    silently-dropped attribute is wrong results later."""
    if not re.fullmatch(r"(?:\s*\w+\s*=\s*\w+\s*)*", body):
        raise PTGSyntaxError(
            f"malformed {what} attribute block [{body}] "
            f"(expected 'key = NAME' pairs)", line_no, line)
    pairs = _RE_DEP_ATTR.findall(body)
    attrs: Dict[str, str] = {}
    for k, v in pairs:
        if k not in allowed:
            raise PTGSyntaxError(f"unknown {what} attribute {k!r}",
                                 line_no, line)
        if k in attrs and attrs[k] != v:
            raise PTGSyntaxError(
                f"conflicting {what} attribute {k!r}: "
                f"{attrs[k]!r} vs {v!r}", line_no, line)
        attrs[k] = v
    return attrs


def _parse_dep(direction: str, text: str, line_no: int, line: str) -> DepSpec:
    """Parse '(guard) ? EP : EP' | '(guard) ? EP' | 'EP', with an optional
    trailing attribute block '[type = NAME type_data = NAME]' (the JDF dep
    datatype annotations, ref: jdf.h datatype properties)."""
    text = text.strip()
    dep = DepSpec(direction=direction, line_no=line_no)
    am = _RE_DEP_ATTRS.search(text)
    if am:
        text = text[:am.start()].strip()
        attrs = _parse_attr_block(am.group(1),
                                  ("type", "type_data", "type_remote"),
                                  "dep", line_no, line)
        t, td = attrs.get("type"), attrs.get("type_data")
        if t is not None and td is not None and t != td:
            raise PTGSyntaxError(
                f"conflicting type/type_data {t!r} vs {td!r}", line_no, line)
        dep.dtt = t if t is not None else td
        dep.dtt_remote = attrs.get("type_remote")
    if "?" in text:
        qpos = _top_level_find(text, "?")
        if qpos < 0:
            raise PTGSyntaxError("malformed ternary guard", line_no, line)
        guard = text[:qpos].strip()
        if guard.startswith("(") and guard.endswith(")"):
            guard = guard[1:-1]
        rest = text[qpos + 1:]
        cpos = _top_level_find(rest, ":")
        dep.guard = guard
        if cpos >= 0:
            dep.endpoint = _parse_endpoint(rest[:cpos], line_no, line)
            dep.else_endpoint = _parse_endpoint(rest[cpos + 1:], line_no, line)
        else:
            dep.endpoint = _parse_endpoint(rest, line_no, line)
    else:
        dep.endpoint = _parse_endpoint(text, line_no, line)
    if direction == "out":
        # NEW/NULL are input-only, in ANY branch of a guarded dep (ref:
        # ptgpp errors, tests/dsl/ptg/ptgpp/output_{NULL,NEW}[_true,_false])
        for ep in (dep.endpoint, dep.else_endpoint):
            if ep is None:
                continue
            if ep.kind == "null":
                raise PTGSyntaxError(
                    "NULL data only supported in IN dependencies",
                    line_no, line)
            if ep.kind == "new":
                raise PTGSyntaxError(
                    "Automatic data allocation with NEW only supported "
                    "in IN dependencies", line_no, line)
    return dep


def _top_level_find(text: str, ch: str) -> int:
    depth = 0
    for i, c in enumerate(text):
        if c in "([":
            depth += 1
        elif c in ")]":
            depth -= 1
        elif c == ch and depth == 0:
            return i
    return -1


def parse(source: str, name: str = "ptg") -> ProgramSpec:
    """Parse PTG source into a :class:`ProgramSpec` (the jdf.h AST role)."""
    prog = ProgramSpec(name=name)
    lines = source.splitlines()
    i = 0
    cur: Optional[TaskClassSpec] = None
    cur_flow: Optional[FlowSpec] = None

    def err(msg: str) -> PTGSyntaxError:
        return PTGSyntaxError(msg, i + 1, lines[i] if i < len(lines) else "")

    while i < len(lines):
        raw = lines[i]
        line = _strip_comment(raw).strip()
        if not line:
            i += 1
            continue
        if line in ("%{", "%prologue"):
            # '%{ ... %}' / '%prologue ... %}': host-language helper block,
            # executed into program globals when the taskpool instantiates
            # (the reference JDF's inline-C prologue, jdf2c.c:54) — a .jdf-
            # style file can carry its own helper functions and constants
            block: List[str] = []
            i += 1
            while i < len(lines) and lines[i].strip() != "%}":
                block.append(lines[i])
                i += 1
            if i >= len(lines):
                raise err("unterminated %{ prologue block (missing %})")
            prog.prologue += "\n".join(block) + "\n"
            i += 1
            continue
        m = _RE_GLOBAL.match(line)
        if m:
            prog.globals.append(m.group(1))
            i += 1
            continue
        m = _RE_OPTION.match(line)
        if m:
            if m.group(1) == "name":
                prog.name = m.group(2)
            i += 1
            continue
        m = _RE_BODY.match(line)
        if m:
            if cur is None:
                raise err("BODY outside a task class")
            device, evaluate = "CPU", None
            if m.group(1):
                attrs = _parse_attr_block(m.group(1), ("type", "evaluate"),
                                          "BODY", i + 1, raw)
                device = attrs.get("type", "CPU").upper()
                evaluate = attrs.get("evaluate")
            if device not in ("CPU", "TPU"):
                raise err(f"unknown body device type {device!r}")
            body_lines: List[str] = []
            i += 1
            start = i
            while i < len(lines) and lines[i].strip() != "END":
                body_lines.append(lines[i])
                i += 1
            if i >= len(lines):
                raise err("BODY without END")
            cur.bodies.append(BodySpec(device=device,
                                       source="\n".join(body_lines),
                                       line_no=start, evaluate=evaluate))
            cur_flow = None
            i += 1
            continue
        # dep continuation lines: "<- ..." / "-> ..."
        if line.startswith("<-") or line.startswith("->"):
            if cur_flow is None:
                raise err("dependency line outside a flow declaration")
            direction = "in" if line.startswith("<-") else "out"
            cur_flow.deps.append(_parse_dep(direction, line[2:], i + 1, raw))
            i += 1
            continue
        # flow declaration: "RW X <- ... " (first dep may be inline)
        first_word = line.split(None, 1)[0].upper()
        if first_word in _ACCESS_KEYWORDS and cur is not None:
            rest = line.split(None, 1)[1] if " " in line else ""
            fm = re.match(r"^(\w+)\s*(.*)$", rest)
            if not fm:
                raise err("malformed flow declaration")
            fname = fm.group(1)
            if cur.flow(fname) is not None:
                raise err(f"duplicate flow {fname!r} in task class {cur.name}")
            if len(cur.flows) >= MAX_FLOW_COUNT:
                raise err(f"too many flows in task class {cur.name} "
                          f"(max {MAX_FLOW_COUNT})")
            cur_flow = FlowSpec(fname, _ACCESS_KEYWORDS[first_word])
            cur.flows.append(cur_flow)
            tail = fm.group(2).strip()
            if tail:
                if not (tail.startswith("<-") or tail.startswith("->")):
                    raise err("expected '<-' or '->' after flow name")
                direction = "in" if tail.startswith("<-") else "out"
                cur_flow.deps.append(_parse_dep(direction, tail[2:], i + 1, raw))
            i += 1
            continue
        m = _RE_AFFINITY.match(line)
        if m and cur is not None:
            cur.affinity = Endpoint("memory", name=m.group(1),
                                    index_exprs=_split_exprs(m.group(2)))
            i += 1
            continue
        m = _RE_RANGE.match(line)
        if m and cur is not None and m.group(1) in cur.params:
            step = m.group(4) if m.group(4) else "1"
            cur.ranges.append(RangeSpec(m.group(1), m.group(2), m.group(3), step))
            i += 1
            continue
        m = _RE_HEADER.match(line)
        if m and (cur is None or cur.bodies or not cur.params or True):
            # a new task class header, optionally with a property block
            # (ref: udf.jdf '[ make_key_fn = ud_make_key ]')
            params = [p.strip() for p in m.group(2).split(",") if p.strip()]
            if len(params) != len(set(params)):
                raise err(f"duplicate parameter names in {m.group(1)}")
            if len(params) > MAX_LOCAL_COUNT:
                raise err(f"too many task parameters (max {MAX_LOCAL_COUNT})")
            props: Dict[str, str] = {}
            if m.group(3):
                props = _parse_attr_block(
                    m.group(3), ("make_key_fn", "startup_fn", "time_estimate"),
                    "task-class", i + 1, raw)
            cur = TaskClassSpec(name=m.group(1), params=params,
                                header_props=props)
            prog.task_classes.append(cur)
            cur_flow = None
            i += 1
            continue
        m = _RE_PROPERTY.match(line)
        if m and cur is not None:
            if m.group(1) == "priority":
                cur.priority_expr = m.group(2).strip()
            else:
                cur.properties[m.group(1)] = m.group(2).strip()
            i += 1
            continue
        raise err(f"cannot parse line: {line!r}")

    _validate(prog)
    return prog


def _validate(prog: ProgramSpec) -> None:
    """Compile-time sanity checks (the ptgpp negative-test battery role)."""
    if not prog.task_classes:
        raise PTGSyntaxError("no task classes defined")
    names = [tc.name for tc in prog.task_classes]
    if len(names) != len(set(names)):
        raise PTGSyntaxError(f"duplicate task class names: {names}")
    for tc in prog.task_classes:
        if not tc.bodies:
            raise PTGSyntaxError(f"task class {tc.name} has no BODY")
        ranged = {r.param for r in tc.ranges}
        missing = [p for p in tc.params if p not in ranged]
        if missing:
            raise PTGSyntaxError(
                f"task class {tc.name}: parameters {missing} have no range")
        for f in tc.flows:
            # WRITE-only flows are scratch outputs (ref: write_check.jdf's
            # "WRITE A1 -> ..." — allocated at run time, body fills them);
            # READ/RW flows must name where their data comes from
            if f.access not in (FLOW_CTL, FLOW_WRITE) and \
                    not any(d.direction == "in" for d in f.deps):
                raise PTGSyntaxError(
                    f"task class {tc.name}: data flow {f.name!r} has no input dep")
            for d in f.deps:
                for ep in (d.endpoint, d.else_endpoint):
                    if ep is None or ep.kind != "task":
                        continue
                    peer = prog.task_class(ep.name)
                    if peer is None:
                        raise PTGSyntaxError(
                            f"{tc.name}.{f.name}: unknown task class {ep.name!r}",
                            d.line_no)
                    pf = peer.flow(ep.flow)
                    if pf is None:
                        raise PTGSyntaxError(
                            f"{tc.name}.{f.name}: task class {ep.name} has no "
                            f"flow {ep.flow!r}", d.line_no)
                    if len(ep.index_exprs) != len(peer.params):
                        raise PTGSyntaxError(
                            f"{tc.name}.{f.name}: {ep.name} takes "
                            f"{len(peer.params)} params, got "
                            f"{len(ep.index_exprs)}", d.line_no)
