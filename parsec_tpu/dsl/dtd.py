"""DTD: dynamic task discovery — the insert-task frontend.

Re-design of parsec/interfaces/dtd (insert_function.c, insert_function.h,
insert_function_internal.h). The user (on every rank, in the same order)
inserts tasks against *tiles*; the runtime builds the DAG on the fly from each
tile's access chain and executes tasks as their dependencies retire:

* :class:`DTDTile` — ref: parsec_dtd_tile_t (insert_function_internal.h:174-196)
  with ``last_writer`` / reader lists driving RAW/WAR/WAW chaining
  (WAR strategy per overlap_strategies.c: a writer waits on all readers since
  the previous write; readers wait on the last writer).
* :class:`DTDTaskpool` — ref: parsec_dtd_taskpool_new (insert_function.c:1513);
  task classes are auto-created per body function + parameter profile
  (the reference's function_h_table); flow-control **window/threshold**
  (insert_function.h:149-157): the inserter blocks past the window and helps
  execute until the executed count catches up.
* ``insert_task`` — ref: parsec_dtd_insert_task (insert_function.c:3617) →
  create/initialize (:2801), param linking (:2896), schedule-if-ready (:2963).
* distributed mode: every rank runs the same insert sequence; tasks filtered
  by the affinity tile's rank (owner-computes); remote edges are forwarded to
  the comm layer (rank_sent_to bitmaps, delayed release — wired in
  :mod:`parsec_tpu.comm.remote_dep`).

TPU-first shape: bodies are *functional* — ``fn(*args) -> outputs`` returns
fresh arrays for its WRITE flows instead of mutating in place. The same body
runs as the CPU chore (eager, host arrays) or the TPU chore (jitted once per
task class, dispatched asynchronously to the chip). This keeps bodies jittable
and makes version-tracked copies natural (every write is a new buffer).
"""

from __future__ import annotations

import threading
import time
import zlib
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..core import pins as pins_mod
from ..core.context import Context
from ..core.task import (
    Chore, DEV_ALL, DEV_CPU, DEV_TPU, Flow, FLOW_ACCESS_READ, FLOW_ACCESS_RW,
    FLOW_ACCESS_WRITE, HOOK_DONE, TASK_STATUS_COMPLETE, Task, TaskClass,
    Taskpool,
)
from ..data.collection import DataCollection
from ..data.data import COHERENCY_OWNED, Data, data_from_array
from ..device.tpu import TPUDevice, make_tpu_hook
from ..utils import mca, output

# access flags for insert_task args (ref: PARSEC_INPUT/OUTPUT/INOUT | AFFINITY)
READ = FLOW_ACCESS_READ
WRITE = FLOW_ACCESS_WRITE
RW = FLOW_ACCESS_RW
AFFINITY = 0x100          # ref: PARSEC_AFFINITY bit on a dtd param
NOTRACK = 0x200           # ref: PARSEC_DONT_TRACK (dtd_test_flag_dont_track.c):
                          # the tile's VALUE flows to the body, but the access
                          # creates no RAW/WAR/WAW edges and no distributed
                          # version bookkeeping — ordering w.r.t. tracked
                          # accesses of the same tile is the caller's problem.
                          # Rank-local by contract (like tile_new scratch).

mca.register("dtd_window_size", 2048,
             "Max in-flight inserted-but-not-executed tasks", type=int)
mca.register("dtd_audit", False,
             "Replay auditor: digest every rank's (tile, version, rank) "
             "link decisions and compare across ranks at wait() (the DTD "
             "analogue of the PTG iterators_checker)", type=bool)
mca.register("dtd_threshold_size", 1024,
             "Catch-up target once the window is hit", type=int)
mca.register("dtd_batch_insert", True,
             "Batched native insert lane: buffer eligible insert_task calls "
             "and link them in the engine N at a time under one GIL drop; "
             "ready tasks execute through in-engine batched drains "
             "(drain_ready) instead of per-task scheduler cycles", type=bool)

#: engagement counters for the batched DTD lane (the DTD analogue of
#: dsl/ptg/compiler.py PTEXEC_STATS — the ci.sh gate watches ENGAGEMENT,
#: not throughput, through the LaneStats snapshot()/delta() helpers).
#: ``tasks_batched`` counts inserts that rode the batch buffer;
#: ``tasks_per_task`` counts inserts on batch-enabled pools that fell
#: back to the per-task engine path (first insert of a class, shape
#: mismatch, priority/where/NOTRACK/AFFINITY, jittable bodies with
#: by-value args); ``pools_batch`` counts pools that enabled the lane.
#: utils/counters.install_native_counters exports these under ``ptdtd.*``
from ..utils.counters import LaneStats as _LaneStats

PTDTD_STATS = _LaneStats(pools_batch=0, tasks_batched=0, tasks_per_task=0,
                         batches=0, classes_ineligible=0,
                         capture_windows_deferred=0,
                         # ISSUE 12: deferred-window region fusion —
                         # capturable runs of a deferred capture window
                         # replay as ONE fused super-task insert each
                         capture_regions_fused=0, capture_tasks_fused=0)

#: "batch registration not yet attempted" marker for the one-entry class
#: cache (None means attempted-and-ineligible, which must not retry)
_BINFO_UNSET = object()


class AdmissionBackpressure(RuntimeError):
    """insert_task(nowait=True) on a pool past its scheduler-plane
    admission window (--mca sched_admission_window / tp.admission_window):
    the ready plane is protecting itself from a runaway inserter. Retry
    later, drop the request, or insert blocking (the default) — the
    serving-tier choice, not the runtime's."""


def _flush_body(arr):
    """data_flush task body: force device->host materialization."""
    return np.asarray(arr)


#: serializes Context._dtd_batch_pools updates (pools arming/retiring from
#: different threads; a torn read-modify-write would wedge the count and
#: either stall the drains or run them forever)
_BATCH_POOLS_LOCK = threading.Lock()


def _pool_sync_on_complete(tp: "DTDTaskpool") -> None:
    """Taskpool.on_complete hook for batch-lane pools: sync the engine's
    tile payload slots into tile.data even when the user never calls
    tp.wait() (close + ctx.wait drains through termination detection),
    then hand the pool's engine-side state back (termdet fires this
    exactly once, after close() — no further inserts can arrive)."""
    tp._sync_slots()
    tp._retire_batch_lane()


class DTDTile:
    """Ref: parsec_dtd_tile_t (insert_function_internal.h:174-196)."""

    __slots__ = ("data", "key", "dc", "lock", "last_writer", "readers",
                 "rank", "new_tile", "wcount", "writer_rank",
                 "last_writer_version", "compact_at", "nid")

    def __init__(self, data: Data, key: Any, dc: Optional[DataCollection],
                 rank: int = 0, new_tile: bool = False) -> None:
        self.data = data
        self.key = key
        self.dc = dc
        self.lock = threading.Lock()
        self.last_writer: Optional["DTDTask"] = None
        self.readers: List["DTDTask"] = []
        self.rank = rank
        self.new_tile = new_tile
        self.compact_at = 32      # next reader-list compaction watermark
        #: logical write sequence number, identical on every rank because all
        #: ranks replay the same insert sequence (the basis remote transfers
        #: are keyed on, standing for the reference's output version tracking)
        self.wcount = 0
        self.writer_rank = rank      # rank holding the newest version
        self.last_writer_version = 0
        #: native-engine tile id (dsl chains in native/src/ptdtd.cpp);
        #: assigned on first native-mode link. Tiles are POOL-local, so a
        #: tile's chain lives entirely in one engine mode.
        self.nid: Optional[int] = None

    def __repr__(self) -> str:  # pragma: no cover
        return f"<DTDTile {self.key}>"


class DTDTask(Task):
    """Task with runtime-discovered deps (ref: parsec_dtd_task_t)."""

    __slots__ = ("deps_remaining", "successors", "completed", "lock",
                 "arg_spec", "tiles", "rank", "pending_inputs",
                 "remote_sends", "ident", "nid")

    def __init__(self, taskpool, task_class, priority=0) -> None:
        super().__init__(taskpool, task_class, None, priority)
        self.ident = 0          # insertion index (repr/debug identity)
        self.nid = -1           # native-engine task id (-1: Python engine)
        # starts at 1: the insertion-in-progress guard (dropped at the end of
        # insert_task, mirroring the count-then-activate protocol of
        # parsec_dtd_schedule_task_if_ready, insert_function.c:2963)
        self.deps_remaining = 1
        self.completed = False
        # Python-engine pools assign a real lock + successor list at insert
        # (pred linking / release walk); the native lane never touches
        # either (GIL-serialized engine), so allocation would be pure
        # insert-path cost
        self.successors: Optional[List[DTDTask]] = None
        self.lock = None
        self.arg_spec: List[Tuple[str, Any]] = []  # ('flow', i) | ('value', v)
        self.tiles: List[Optional[DTDTile]] = []
        self.rank = 0
        #: flow_index -> payload delivered by the comm engine (exact-version
        #: remote inputs override newest_copy resolution). Lazily allocated:
        #: only distributed consumers need it, and a per-task dict is
        #: GC-tracked churn on the insert hot path
        self.pending_inputs: Optional[Dict[int, Any]] = None
        #: id(tile) -> (tile, version, {dst ranks}) — the rank_sent_to
        #: bitmap; lazily allocated for the same reason
        self.remote_sends: Optional[Dict[int, Tuple]] = None

    def dep_satisfied(self) -> bool:
        with self.lock:
            self.deps_remaining -= 1
            return self.deps_remaining == 0

    def __repr__(self) -> str:  # pragma: no cover
        return f"{self.task_class.name}(#{self.ident})"


#: process-wide jit cache keyed by the body function object, so the same body
#: used across many taskpools compiles exactly once (jax.jit caches traces on
#: the wrapper object — a fresh wrapper per task class would retrace).
_jit_cache: Dict[Any, Any] = {}
_jit_cache_lock = threading.Lock()


def _vmapped(fn: Callable):
    """jit(vmap(fn)) cached per body function (batched dispatch path)."""
    key = ("__vmap__", fn)
    j = _jit_cache.get(key)
    if j is None:
        with _jit_cache_lock:
            j = _jit_cache.get(key)
            if j is None:
                import jax
                j = jax.jit(jax.vmap(fn))
                _jit_cache[key] = j
    return j


_host_dev_cache = [False, None]   # [resolved, device]


def _host_device():
    """The host jax device, resolved once (a per-task jax.local_devices()
    lookup showed up in the benchmark profile). Only a successful lookup is
    cached: a transient backend failure (flaky accelerator discovery) must
    not latch None for the process lifetime."""
    if not _host_dev_cache[0]:
        try:
            import jax
            _host_dev_cache[1] = jax.local_devices(backend="cpu")[0]
            _host_dev_cache[0] = True
        except Exception:
            return None
    return _host_dev_cache[1]


def _jitted(fn: Callable):
    j = _jit_cache.get(fn)
    if j is None:
        with _jit_cache_lock:
            j = _jit_cache.get(fn)
            if j is None:
                import jax
                j = jax.jit(fn)
                _jit_cache[fn] = j
    return j


class DTDTaskClass(TaskClass):
    """Auto-created per (body fn, param profile)
    (ref: function_h_table, insert_function_internal.h:206-224)."""

    def __init__(self, name: str, fn: Callable, flow_accesses: Tuple[int, ...],
                 nb_values: int, jit_ok: bool = True,
                 batchable: bool = False) -> None:
        super().__init__(name, nb_flows=len(flow_accesses))
        self.fn = fn
        self.count_mode = True
        self.lazy_data = True     # fused lane retires tasks slot-free
        self.flow_accesses = flow_accesses
        #: False for side-effectful bodies (callbacks, host I/O): run eagerly
        self.jit_ok = jit_ok
        #: True: compatible queued device tasks collapse into one vmapped
        #: dispatch (ref: dtd GPU batching flag on task-class chores)
        self.batchable = batchable
        for i, acc in enumerate(flow_accesses):
            self.add_flow(Flow(f"f{i}", acc))

    def jitted(self):
        return _jitted(self.fn)

    @property
    def fast_inline(self) -> bool:
        """True when this class can take the fused inline cycle: exactly
        one synchronous CPU chore, no evaluate gate — completion is
        immediate, so insert can run prepare->hook->complete in place."""
        fi = getattr(self, "_fast_inline", None)
        if fi is None:
            fi = self._fast_inline = (
                len(self.incarnations) == 1
                and self.incarnations[0].device_type == DEV_CPU
                and self.incarnations[0].evaluate is None)
        return fi


class DTDTaskpool(Taskpool):
    """Ref: parsec_dtd_taskpool_new (insert_function.c:1513)."""

    def __init__(self, context: Context, name: str = "dtd",
                 capture=False) -> None:
        # per-context (i.e. per-rank) sequence number per base name: every
        # rank constructs its taskpools in the same order, so "dtd#3" means
        # the same pool on all ranks while two concurrently-live pools can
        # never collide in the remote-dep registry
        seqs = getattr(context, "_dtd_name_seq", None)
        if seqs is None:
            seqs = context._dtd_name_seq = {}
        seq = seqs.get(name, 0)
        seqs[name] = seq + 1
        if seq:
            name = f"{name}#{seq}"
        super().__init__(name)
        self.ctx = context
        self._classes: Dict[Any, DTDTaskClass] = {}
        self._tiles: Dict[Any, DTDTile] = {}
        self._tiles_lock = threading.Lock()
        self.window_size = mca.get("dtd_window_size", 2048)
        self.threshold_size = mca.get("dtd_threshold_size", 1024)
        #: serializes the WHOLE insert path (ADVICE r5 medium): concurrent
        #: user-thread inserts are an advertised contract, but the ready
        #: buffer was the only locked piece — the tile.nid check-then-create
        #: could mint two engine chains for one shared tile (silently
        #: dropping RAW/WAR edges), the inserted/local_inserted RMWs could
        #: undercount (wait() then targets too few tasks), and two
        #: concurrently stalling inserters both drove
        #: _progress_loop(streams[0]), racing on stream.next_task.
        #: REENTRANT on purpose: a window-stalled inserter executes tasks
        #: inline, and a body may itself insert (recursive task insertion).
        #: NOT held across the window stall (see _window_stall) — blocking
        #: a worker-thread body's insert on a stalled user thread would
        #: deadlock; _stall_lock elects the one user thread that drives
        #: the master stream's drain loop
        self._insert_lock = threading.RLock()
        self._stall_lock = threading.Lock()
        self.inserted = 0
        self.local_inserted = 0   # tasks this rank actually executes
        self.window_stalls = 0    # inserter blocked on the task window
        self._executed = 0
        self._exec_lock = threading.Lock()
        self._open = False
        self._touched_tiles: List[DTDTile] = []
        self._new_tile_count = 0
        self._audit = mca.get("dtd_audit", False)
        self._audit_digest = 0      # zlib.crc32 chain: process-independent
        self._audit_count = 0
        #: native dependency engine (native/src/ptdtd.cpp) — the insert/
        #: release hot path as a C extension. Decided at first insert:
        #: single-rank, no comm engine, no audit (those stay on the Python
        #: engine, which owns the distributed protocol bookkeeping)
        self._neng = None
        self._neng_decided = False
        #: batched native insert lane (ISSUE 4): eligible repeat inserts of
        #: one class buffer their specs here (plain list: append is
        #: GIL-atomic, so the fast path takes NO lock; flushers serialize
        #: on the insert lock and drain a snapshot prefix with del-slice,
        #: which can never race a concurrent tail append) and link in the
        #: engine N at a time under one GIL drop (engine.insert_many).
        #: Batched tasks have NO Python task object: the engine owns the
        #: whole insert->link->ready->execute->release cycle; bodies run
        #: through per-class batched callbacks at the drain points
        #: (Context._dtd_drain in every stream's hot loop)
        self._batch_on = False
        self._batch_retired = False   # final-completion hand-back ran
        self._slots_stale = False     # quiescence sync emptied the slots
        #: scheduler-plane pool handle (core/sched_plane.py): set when the
        #: batch lane arms on a plane-carrying context; batch classes
        #: register with it so their ready tasks drain by QoS weight, and
        #: the admission window (tp.admission_window / --mca
        #: sched_admission_window) backpressures insert_task through it
        self._sched_pool: Optional[int] = None
        self._bbuf: List[tuple] = []
        self._batch_flush_n = max(1, min(256, self.window_size // 2))
        #: one-entry FAST-PATH cache: (fn, jit, batch, kinds|k0, cls_nid,
        #: bbuf, flush_n, DTDTile) — everything the native try_buffer
        #: fast path needs in one tuple. kinds collapses to the bare acc
        #: int for the dominant single-flow shape. Rebound wherever
        #: _last_class gains a batch registration; cleared on close()
        self._fast: Optional[tuple] = None
        self._tbuf = None        # native try_buffer (set with _batch_on)
        #: ready-at-insert batch (native lane only): single-stream contexts
        #: gain nothing from per-task scheduler pushes, so ready tasks
        #: buffer here and enter the scheduler in BULK at the drain points
        #: (window stall, wait, close) — one push lock + one priority sort
        #: per batch instead of per task
        self._ready_buf: List[DTDTask] = []
        self._last_class = None   # (fn, accs, nvals, jit, batch, tc)
        if context.comm is not None:
            # distributed: global termination detection + name-keyed registry
            context.comm.fourcounter.monitor_taskpool(self)
            context.comm.register_taskpool(self)
        # hold the "user may still insert" action BEFORE attaching, so the
        # termdet can never observe transiently-zero counters at enqueue time
        # (the reference keeps the taskpool's own nb_pending_actions pinned
        # while attached)
        #: True while the CURRENT insert window is deferred to the
        #: scheduler (a non-capturable insert poisoned it); wait() resets
        #: it so the next window captures again (per-region auto-defer)
        self._capture_deferred = False
        # whole-DAG capture mode (dsl/capture.py): record inserts, execute
        # the entire pool as ONE jitted XLA program at wait()
        self._capture = None
        if capture:
            if context.nb_ranks > 1:
                output.fatal("graph capture is single-rank "
                             "(a captured pool never leaves the chip)")
            from .capture import GraphCapture
            # capture=True -> "auto"; or an explicit "inline"/"scan" strategy
            self._capture = GraphCapture(self, mode=capture)
        self.addto_nb_pending_actions(1)
        self._open = True
        context.add_taskpool(self)

    # ------------------------------------------------------------- tiles
    def tile_of(self, dc: DataCollection, *indices) -> DTDTile:
        """PARSEC_DTD_TILE_OF (ref: parsec_dtd_tile_of, insert_function.c:1403)."""
        key = (dc.name, dc.data_key(*indices))
        with self._tiles_lock:
            t = self._tiles.get(key)
            if t is None:
                data = dc.data_of(*indices)
                t = DTDTile(data, key, dc, rank=dc.rank_of(*indices))
                self._tiles[key] = t
                self._touched_tiles.append(t)
            return t

    def tile_of_key(self, dc: DataCollection, key: Any) -> DTDTile:
        tkey = (dc.name, key)
        with self._tiles_lock:
            t = self._tiles.get(tkey)
            if t is None:
                data = dc.data_of_key(key)
                t = DTDTile(data, tkey, dc, rank=dc.rank_of_key(key))
                self._tiles[tkey] = t
                self._touched_tiles.append(t)
            return t

    def tile_new(self, array_or_shape, dtype=np.float32, key: Any = None) -> DTDTile:
        """parsec_dtd_tile_new (ref: insert_function.h:239): a taskpool-lifetime
        scratch tile not backed by any collection."""
        if hasattr(array_or_shape, "shape"):
            arr = np.asarray(array_or_shape)
        else:
            arr = np.zeros(array_or_shape, dtype=dtype)
        data = data_from_array(arr)
        self._new_tile_count += 1
        t = DTDTile(data, ("new", self.name, self._new_tile_count), None,
                    rank=self.ctx.my_rank, new_tile=True)
        with self._tiles_lock:
            self._tiles[t.key] = t
            self._touched_tiles.append(t)
        return t

    # ------------------------------------------------------------- classes
    def _class_of(self, fn: Callable, flow_accesses: Tuple[int, ...],
                  nb_values: int, name: Optional[str],
                  jit_ok: bool = True, batchable: bool = False) -> DTDTaskClass:
        key = (fn, flow_accesses, nb_values, jit_ok, batchable)
        tc = self._classes.get(key)
        if tc is None:
            tc = DTDTaskClass(name or getattr(fn, "__name__", "dtd_task"),
                              fn, flow_accesses, nb_values, jit_ok=jit_ok,
                              batchable=batchable)
            tc.prepare_input = self._prepare_input
            tc.release_deps = self._release_deps
            tc.complete_execution = self._complete_execution
            # the TPU chore only exists where a TPU device does — on
            # CPU-only contexts every task would walk (and fail) it first.
            # Non-jittable bodies never get one: they would ride the whole
            # async device pipeline (stage-in/events/epilog) only to run
            # raw Python anyway — pure per-task overhead
            if jit_ok and any(d.type & DEV_TPU
                              for d in self.ctx.devices.devices):
                tc.add_chore(Chore(DEV_TPU, self._tpu_hook))
            tc.add_chore(Chore(DEV_CPU, self._cpu_hook))
            self.add_task_class(tc)
            self._classes[key] = tc
        return tc

    # ------------------------------------------------------------- insert
    def _native_engine(self):
        """The per-context native DTD engine, or None (gated)."""
        if self._neng_decided:
            return self._neng
        self._neng_decided = True
        ctx = self.ctx
        # PINS no longer ejects pools from the native engine (PR 5): the
        # per-task lane keeps firing the full event cycle through the
        # Python FSM (successor lists mirrored on demand from the engine,
        # see _complete_execution), the batched lane records in-lane ring
        # events (utils/native_trace.py). Only --mca pins_paranoid 1
        # restores the all-Python engine for full-fidelity debugging
        if ctx.comm is not None or ctx.nb_ranks > 1 or self._audit \
                or ctx.pins.paranoid or not mca.get("native_enabled", True):
            return None
        eng = getattr(ctx, "_dtd_neng", None)
        if eng is None and not getattr(ctx, "_dtd_neng_failed", False):
            # serialized: two pools first-inserting from different client
            # threads must not BOTH mint an engine (the loser's tasks
            # would link into a chain state nobody drains)
            with _BATCH_POOLS_LOCK:
                eng = getattr(ctx, "_dtd_neng", None)
                if eng is None and \
                        not getattr(ctx, "_dtd_neng_failed", False):
                    from .. import native as native_mod
                    mod = native_mod.load_ptdtd()
                    if mod is None:
                        ctx._dtd_neng_failed = True
                    else:
                        ctx._dtd_ntasks = {}
                        eng = ctx._dtd_neng = mod.Engine()
        if eng is not None:
            # progress loops drain our ready buffer even when the user
            # drives the context directly (no tp.wait()); weakly bound so
            # a dropped pool unregisters itself
            ctx.register_drain_hook(self._flush_ready)
            # batched insert lane: engine v2 (insert_many/drain_ready)
            # on a CPU-only context with the DEFAULT scheduler. TPU
            # contexts stay per-task — device selection / async epilogs
            # are policy the in-engine drain bypasses, and a TPU epilog
            # writing a tile behind the engine's payload slot would break
            # slot coherence. An explicitly-chosen scheduler module also
            # refuses the lane: batched tasks never enter the scheduler
            # queues, so a user-selected ordering policy (FIFO, priority
            # heap, ...) could not see them
            if mca.get("dtd_batch_insert", True) \
                    and hasattr(eng, "insert_many") \
                    and not getattr(ctx, "sched_explicit", False) \
                    and not any(d.type & DEV_TPU
                                for d in ctx.devices.devices):
                # an explicitly-chosen scheduler still refuses the batch
                # lane even with the scheduler plane up: a DTD pool mixes
                # batch-lane tasks (plane-ordered) with per-task-lane
                # tasks (Python-queue-ordered — every prioritized or
                # shape-ineligible insert), and the user's policy spans
                # BOTH, which no per-lane ordering can honor
                # (test_scheduler_policy_separation is the contract).
                # PTG lanes are whole-pool native, so THEY honor an
                # explicit policy through the plane's flavor instead
                self._batch_on = True
                from .. import native as _nm     # memoized load
                self._tbuf = _nm.load_ptdtd().try_buffer
                # ring lifecycle (enable): the batched lane's insert/exec
                # cycle never surfaces per-task pins events, so its
                # observability is the in-lane rings (no-op when no
                # profiling is attached). The engine is per-CONTEXT and
                # outlives pools, so its events carry taskpool id 0
                ctx._ntrace_attach("ptdtd", eng)
                ctx._hist_attach("ptdtd", eng)
                # open-batch-pool count gates the stream hot loops' engine
                # drain; decremented at final completion so pools running
                # AFTER this one (e.g. with the batch lane mca-disabled)
                # don't pay an empty drain_ready every idle iteration
                with _BATCH_POOLS_LOCK:
                    ctx._dtd_batch_pools += 1
                PTDTD_STATS["pools_batch"] += 1
                # scheduler plane (ISSUE 9): bind the engine (idempotent —
                # one plane per context) and register this pool's QoS
                # identity; batch classes then route ready tasks through
                # the shared plane, so N concurrent DTD pools drain by
                # DRR weight and the admission window gains teeth
                plane = getattr(ctx, "sched_plane", None)
                if plane is not None:
                    try:
                        eng.sched_bind(plane.capsule)
                        h = plane.register_pool(
                            self.name, plane.KIND_PTDTD,
                            weight=getattr(self, "qos_weight", None),
                            window=getattr(self, "admission_window", None))
                        self._sched_pool = h if h >= 0 else None
                    except Exception:  # noqa: BLE001 — private ready path
                        self._sched_pool = None
                # tile payload slots sync back into tile.data when the
                # pool completes, even when the user never calls wait().
                # CHAIN any prior hook — compound stages and recursive
                # device pools set on_complete BEFORE their first insert,
                # and must see the synced tile.data values when they fire
                prev = self.on_complete
                if prev is None:
                    self.on_complete = _pool_sync_on_complete
                else:
                    def _chained(tp, _prev=prev):
                        _pool_sync_on_complete(tp)
                        _prev(tp)
                    self.on_complete = _chained
        self._neng = eng
        return eng

    # ------------------------------------------------------- batched lane
    def _tile_nid(self, tile: DTDTile) -> int:
        """The tile's engine chain id, created (and its payload slot
        seeded) on first native touch. The check-then-create runs under
        the insert lock: two threads racing here must not mint two engine
        chains for one shared tile (the PR 2 concurrent-inserter bug)."""
        nid = tile.nid
        if nid is None:
            with self._insert_lock:
                nid = tile.nid
                if nid is None:
                    neng = self._neng
                    nid = neng.tile()
                    if self._batch_on:
                        copy = tile.data.newest_copy()
                        if copy is not None:
                            neng.slot_set(nid, copy.payload)
                    tile.nid = nid
        return nid

    def _slot_payload(self, tile: DTDTile):
        """Newest payload of a tile on a batch-lane pool: the engine slot
        is authoritative while batched writers are in flight (tile.data
        syncs at wait/complete); falls back to newest_copy."""
        if self._batch_on and tile.nid is not None:
            p = self._neng.slot_get(tile.nid)
            if p is not None:
                return p
        copy = tile.data.newest_copy()
        return None if copy is None else copy.payload

    def _mk_batch_callback(self, tc: "DTDTaskClass", argmap: Tuple[int, ...]):
        """The per-class batched dispatch the engine's drain_ready invokes
        once per (class, batch): run every body on its gathered args and
        hand WRITE-flow outputs back for native slot landing. Execution
        accounting does NOT happen here — the engine invokes
        ``_batch_retire`` only after phase 3 has landed the outputs, so a
        wait()er can never observe the counters ahead of the payloads."""
        fn = tc.fn
        use_jit = tc.jit_ok
        wflows = [i for i, a in enumerate(tc.flow_accesses) if a & WRITE]
        nw = len(wflows)
        # arg position each write flow's input payload sits at (a body
        # returning fewer outputs keeps the old payload, like _run_lean)
        wpos = [argmap.index(i) for i in wflows]

        def _batch_cb(args_list):
            f = _jitted(fn) if use_jit else fn
            if nw:
                outs_list = []
                ap = outs_list.append
                for vals in args_list:
                    o = f(*vals)
                    if o is None:
                        o = ()
                    elif type(o) is not tuple:
                        o = tuple(o) if isinstance(o, list) else (o,)
                    if len(o) < nw:
                        o = tuple(o[k] if k < len(o) else vals[wpos[k]]
                                  for k in range(nw))
                    ap(o)
            else:
                for vals in args_list:
                    f(*vals)
                outs_list = None
            return outs_list

        return _batch_cb

    def _batch_retire(self, ne: int) -> None:
        """Engine-invoked AFTER a batch's outputs have landed in the tile
        slots and its release walk has run (drain_ready phase 3): retire
        the batch's execution accounting in bulk (one _exec_lock acquire
        and one nb_tasks update per BATCH instead of per task). Ordering
        matters: retiring inside the batch callback — before the landing —
        would let a concurrent wait() see ``executed >= target`` and
        _sync_slots() the PRE-batch payloads, silently dropping the final
        batch's writes."""
        with self._exec_lock:
            self._executed += ne
        self.addto_nb_tasks(-ne)

    def _mk_batch_info(self, tc: "DTDTaskClass", flow_accesses,
                       arg_spec) -> Optional[tuple]:
        """Register an engine batch class for (tc, arg interleaving), or
        None when ineligible. Eligibility (honest-fallback contract, the
        ptexec pattern — refusals ride the per-task lane and count in
        PTDTD_STATS):
          * plain READ/WRITE/RW flows only (NOTRACK snapshots the value at
            insert time, which a deferred batch cannot honor; AFFINITY is
            placement policy);
          * jittable bodies take no by-value args (the batched dispatch
            calls the class's jitted fn on payloads only);
          * TPU contexts never reach here (pool-level gate)."""
        if not self._batch_on:
            return None
        for acc in flow_accesses:
            if acc & ~0x3:
                PTDTD_STATS["classes_ineligible"] += 1
                return None
        if tc.jit_ok and any(kind != "flow" for kind, _ in arg_spec):
            PTDTD_STATS["classes_ineligible"] += 1
            return None
        kinds: List[Optional[int]] = []
        argmap: List[int] = []
        for kind, v in arg_spec:
            if kind == "flow":
                kinds.append(flow_accesses[v])
                argmap.append(v)
            else:
                kinds.append(None)
                argmap.append(-1)
        reg = getattr(tc, "_breg", None)
        if reg is None:
            reg = tc._breg = {}
        key = tuple(argmap)
        nid = reg.get(key)
        if nid is None:
            cb = self._mk_batch_callback(tc, key)
            nid = self._neng.register_class(
                cb, key, [a & 0x3 for a in flow_accesses],
                self._batch_retire,
                -1 if self._sched_pool is None else self._sched_pool)
            reg[key] = nid
        return (nid, tuple(kinds))

    def _flush_batch(self) -> None:
        """Hand the buffered insert specs to the engine in one call.
        Flushers serialize on the insert lock; the del-slice prefix drain
        cannot race concurrent tail appends (both are GIL-atomic and the
        fast path only ever appends)."""
        if not self._bbuf:
            return
        with self._insert_lock:
            self._flush_batch_locked()

    def _flush_batch_locked(self) -> None:
        lst = self._bbuf
        n = len(lst)
        if not n:
            return
        if self._slots_stale:
            # a quiescence sync emptied the slots (tile.data became
            # authoritative again, honoring any user reseed since); the
            # next batch gathers args from the slots, so refill them from
            # the host copies before linking
            self._slots_stale = False
            neng = self._neng
            with self._tiles_lock:
                tiles = list(self._touched_tiles)
            for t in tiles:
                if t.nid is not None:
                    copy = t.data.newest_copy()
                    if copy is not None:
                        neng.slot_set(t.nid, copy.payload)
        chunk = lst[:n]
        del lst[:n]
        # count BEFORE linking: a linked task may be drained by a worker
        # immediately, and its -1 must never underflow the counter
        self.addto_nb_tasks(n)
        self.inserted += n
        self.local_inserted += n
        PTDTD_STATS["tasks_batched"] += n
        PTDTD_STATS["batches"] += 1
        try:
            self._neng.insert_many(chunk)
        except BaseException:
            # insert_many validates the WHOLE batch before linking any of
            # it, so a raise means nothing linked: roll the counters back
            # or the pool could never quiesce (wait() would spin to its
            # timeout on tasks that do not exist)
            self.addto_nb_tasks(-n)
            self.inserted -= n
            self.local_inserted -= n
            PTDTD_STATS["tasks_batched"] -= n
            PTDTD_STATS["batches"] -= 1
            raise

    def _sync_slots(self) -> None:
        """Land the engine's tile payload slots back into tile.data (the
        slot-ownership hand-off: C owned the values while batched writers
        were in flight; Python re-takes them at quiescence points). The
        version delta equals the number of batched writes, keeping
        tile.data.version in parity with the per-task lanes. slot_sync
        also EMPTIES each slot, making tile.data authoritative until the
        next flush re-seeds — so a user reseeding a tile's host copy
        between waits is honored exactly like on the per-task lanes.

        Runs under the insert lock (RLock — callers already holding it
        are fine): a concurrent inserter thread's flush must never link a
        batch against slots this sync is mid-way through emptying (the
        drained bodies would gather None payloads), and the stale flag
        must be set before any later flush can read it."""
        if not self._batch_on:
            return
        neng = self._neng
        with self._insert_lock:
            with self._tiles_lock:
                tiles = list(self._touched_tiles)
            synced = False
            for t in tiles:
                nid = t.nid
                if nid is None:
                    continue
                payload, writes = neng.slot_sync(nid)
                synced = True
                if not writes:
                    continue
                data = t.data
                host = data.get_copy(0)
                if host is None:
                    data.create_copy(0, payload, COHERENCY_OWNED)
                else:
                    host.payload = payload
                data.bump_version(0, writes)
                t.wcount += writes
                t.last_writer_version = t.wcount
            if synced:
                self._slots_stale = True

    def _retire_batch_lane(self) -> None:
        """Final-completion hand-back for batch-lane pools (fires once,
        from on_complete): drop this pool from the context's open-batch
        count (stream hot loops stop paying the engine drain once no
        batch pool is live) and release the engine-side state the pool
        pinned."""
        if not self._batch_on or self._batch_retired:
            return
        self._batch_retired = True
        with _BATCH_POOLS_LOCK:
            self.ctx._dtd_batch_pools -= 1
        self._release_native()
        if self._sched_pool is not None:
            # free the plane slot AFTER release_pool cleared the classes'
            # pool routing (a released class must never route to a slot
            # another pool may reuse)
            plane = getattr(self.ctx, "sched_plane", None)
            if plane is not None:
                plane.unregister_pool(self._sched_pool)
            self._sched_pool = None
        if self.ctx._ntrace is not None:
            # ring lifecycle (quiescence): land this pool's in-lane events
            # now — the engine outlives the pool, but a dumped trace must
            # not be missing a completed pool's tail
            self.ctx._ntrace.drain_all(wait=True)

    def _release_native(self) -> None:
        """Hand the pool's engine-side references back: tile payload slots
        and batch-class callbacks. The Engine is per-CONTEXT while pools
        come and go — without this, every dead pool's payloads (and the
        pool object itself, through the callback closures) stay pinned
        until context teardown. Only called once the pool is fully drained
        (no task of a released class can ever be ready again)."""
        rel = getattr(self._neng, "release_pool", None)
        if rel is None:
            return
        with self._tiles_lock:
            nids = [t.nid for t in self._touched_tiles if t.nid is not None]
        cls_ids: List[int] = []
        for tc in self._classes.values():
            reg = getattr(tc, "_breg", None)
            if reg:
                cls_ids.extend(reg.values())
        if nids or cls_ids:
            rel(nids, cls_ids)
        self._fast = None

    def _run_lean(self, task: "DTDTask", tc: "DTDTaskClass",
                  tiles, arg_spec) -> None:
        """Non-jittable fused body: resolve payloads straight from the
        tiles, run eagerly, write WRITE flows back — the _cpu_hook eager
        branch without TaskData slot churn (fused-inline path only)."""
        pend = task.pending_inputs
        batch_on = self._batch_on
        payloads = []
        for i, tile in enumerate(tiles):
            p = pend.pop(i, None) if pend else None
            if p is None and batch_on and tile.nid is not None:
                # batch-lane coherence: the engine slot holds the newest
                # payload while batched writers are in flight
                p = self._neng.slot_get(tile.nid)
            if p is None:
                copy = tile.data.newest_copy()
                if copy is None:
                    output.fatal(f"tile {tile!r} has no valid copy "
                                 f"for {task!r}")
                p = copy.payload
            payloads.append(p)
        vals = [payloads[v] if kind == "flow" else v for kind, v in arg_spec]
        outs = tc.fn(*vals)
        if outs is None:
            outs = ()
        elif not isinstance(outs, (tuple, list)):
            outs = (outs,)
        oi = 0
        for i, acc in enumerate(tc.flow_accesses):
            if acc & WRITE:
                new = outs[oi] if oi < len(outs) else payloads[i]
                oi += 1
                tile = tiles[i]
                data = tile.data
                host = data.get_copy(0)
                if host is None:
                    data.create_copy(0, new, COHERENCY_OWNED)
                else:
                    host.payload = new
                data.bump_version(0)
                if batch_on and tile.nid is not None:
                    # mirror into the engine slot so batched readers see
                    # this write (slot_set bumps no batch-write counter:
                    # the version was bumped Python-side above)
                    self._neng.slot_set(tile.nid, new)

    def _lean_cycle(self, stream, task: "DTDTask") -> None:
        """The fused select-side task cycle for native-lane eager bodies:
        run, land outputs, retire, release successors — one call from the
        progress loop instead of the generic prepare/execute/complete FSM
        (the machinery a C runtime pays ~0 for; fusing it is how the
        interpreted runtime stays in the reference's rate class).

        Profiling no longer ejects tasks from this lane (PR 5): with PINS
        enabled the fused cycle fires the core lifecycle events itself —
        EXEC and COMPLETE/RELEASE pairs plus the engine-successor mirror —
        so TaskProfiler/ALPerf/grapher consumers keep their contract at
        near-lean cost; ``--mca pins_paranoid 1`` restores the full FSM
        (which additionally fires the PREPARE_INPUT pair)."""
        tc = task.task_class
        pins = self.ctx.pins
        pins_on = pins.enabled
        if pins_on:
            pins.fire(pins_mod.EXEC_BEGIN, stream, task)
        self._run_lean(task, tc, task.tiles, task.arg_spec)
        stream.nb_executed += 1
        if pins_on:
            pins.fire(pins_mod.EXEC_END, stream, task)
            pins.fire(pins_mod.COMPLETE_EXEC_BEGIN, stream, task)
            # engine-successor mirror for RELEASE consumers (the grapher);
            # complete() below moves the engine's list out
            ntasks = self.ctx._dtd_ntasks
            task.successors = [ntasks[s]
                               for s in self._neng.successors(task.nid)
                               if s in ntasks]
            pins.fire(pins_mod.RELEASE_DEPS_BEGIN, stream, task)
        task.status = TASK_STATUS_COMPLETE
        task.completed = True
        with self._exec_lock:
            self._executed += 1
        ready_ids = self._neng.complete(task.nid)
        self.ctx._dtd_ntasks.pop(task.nid, None)
        task.tiles = ()
        task.arg_spec = ()
        task.data = ()
        task.pending_inputs = None
        if ready_ids:
            self._schedule_native_ready(ready_ids, stream)
        if pins_on:
            task.successors = None
            pins.fire(pins_mod.RELEASE_DEPS_END, stream, task)
            pins.fire(pins_mod.COMPLETE_EXEC_END, stream, task)
        self.addto_nb_tasks(-1)

    def _schedule_native_ready(self, ready_ids, stream=None) -> None:
        """Map newly-ready native task ids to their Python tasks and queue
        them (shared by the release path and the fused-inline complete)."""
        ntasks = self.ctx._dtd_ntasks
        rtasks = []
        for rid in ready_ids:
            rt = ntasks[rid]
            rt.deps_remaining = 0   # paranoid-check coherence
            rtasks.append(rt)
        self.ctx.schedule(rtasks, stream)

    def _flush_ready(self) -> None:
        """Hand the buffered ready-at-insert batch to the scheduler (and
        flush the batch-lane insert buffer: this doubles as the pool's
        progress-loop drain hook, so starving loops always see buffered
        work)."""
        if self._bbuf:
            self._flush_batch()
        if not self._ready_buf:
            return
        with self._exec_lock:
            buf = self._ready_buf
            self._ready_buf = []
        if buf:
            self.ctx.schedule(buf)

    def _window_stall(self) -> None:
        """Window flow control (ref: insert_function.h:149-157).

        Runs OUTSIDE the insert lock — a stalling inserter must never
        block another thread's (in particular a worker-thread body's)
        insert fast path, or a mid-body recursive insert would deadlock
        the pool. Flow control NEVER blocks inside a task body (a thread
        currently driving a progress loop, ``ctx.in_progress_loop()`` —
        thread-local, so one thread's wait()/stall cannot mask another
        thread's top-level inserts): the unfinished task's successors may
        be the only drainable work, so waiting there can never converge —
        recursive inserts overshoot the window instead, bounded by the
        DAG's recursive fan-out (the reference's window also only ever
        throttles the user-side inserter). Top-level user threads elect
        ONE drainer via a try-lock — the loser waits for the window to
        drain instead of racing the winner on streams[0].next_task
        (ADVICE r5)."""
        if self.local_inserted - self.executed <= self.window_size:
            return
        if self.ctx.in_progress_loop():
            return              # mid-body insert: never block flow control
        self._flush_ready()
        self.window_stalls += 1
        self.ctx.start()
        while self.local_inserted - self.executed > self.window_size:
            if self.ctx._error is not None:
                return
            if self._stall_lock.acquire(blocking=False):
                try:
                    target = self.local_inserted - self.threshold_size
                    self.ctx._progress_loop(
                        self.ctx.streams[0],
                        until=lambda: self.executed >= target)
                finally:
                    self._stall_lock.release()
                return
            time.sleep(50e-6)   # another user thread is draining

    def _admission_stall(self) -> None:
        """Admission backpressure (ISSUE 9): the scheduler plane reported
        this pool past its admission window (in-flight inserted-but-not-
        completed tasks > --mca sched_admission_window / tp.admission_
        window), so the inserting thread HELPS DRAIN until the pool is
        back under — a runaway client thread saturates the ingest budget
        instead of OOMing the ready plane. Same discipline as
        _window_stall: never blocks inside a task body (recursive inserts
        overshoot, bounded by the DAG's fan-out), one elected drainer."""
        h = self._sched_pool
        if h is None:
            return
        plane = self.ctx.sched_plane
        if plane is None or not plane.over_window(h):
            return
        if self.ctx.in_progress_loop():
            return              # mid-body insert: never block flow control
        self._flush_ready()
        plane.count_stall(h)
        self.ctx.start()
        while plane.over_window(h):
            if self.ctx._error is not None or self._batch_retired:
                return
            if self._stall_lock.acquire(blocking=False):
                try:
                    self.ctx._progress_loop(
                        self.ctx.streams[0],
                        until=lambda: not plane.over_window(h))
                finally:
                    self._stall_lock.release()
                return
            time.sleep(50e-6)   # another user thread is draining

    def insert_task(self, fn: Callable, *args, priority: int = 0,
                    where: int = DEV_ALL, name: Optional[str] = None,
                    jit: bool = True, batch: bool = False,
                    nowait: bool = False) -> Optional[DTDTask]:
        """parsec_dtd_insert_task (ref: insert_function.c:3617).

        ``args``: ``(tile, access)`` tuples become data flows; anything else
        is a by-value parameter. ``access`` may carry the AFFINITY bit to pick
        the task's rank (default: first WRITE tile's rank) and/or the
        NOTRACK bit to pass the tile's value without dependency tracking
        (ref PARSEC_DONT_TRACK).

        Thread-safe: concurrent user threads may insert into one pool —
        the whole linking path (tile chain check-then-create, engine
        calls, counters, ready buffering) runs under the taskpool insert
        lock, so shared-tile chains stay exact; window flow control runs
        AFTER the lock drops (one drainer elected, see _window_stall).

        Batched native lane: on a single-rank CPU context, repeat inserts
        of an eligible class (same body fn, same flow shape — the one-
        entry class cache) buffer their specs and link in the engine N at
        a time; such inserts return ``None`` (no per-task Python object
        exists — like capture mode, the handle-free contract of the
        batched lane). The FIRST insert of a class, and any ineligible
        insert (priority, NOTRACK/AFFINITY, device restriction, jittable
        body with by-value args), takes the per-task path and returns the
        task. Buffered inserts flush at window boundaries, at wait/close,
        and whenever a progress loop starves.

        Admission backpressure: past the scheduler plane's per-pool
        window the insert BLOCKS (helping drain) — or raises
        :class:`AdmissionBackpressure` with ``nowait=True``, the
        serving-tier "shed load instead of queueing" contract. The window
        is a soft limit: buffered-but-unflushed specs (at most the flush
        threshold) do not count against it.
        """
        if nowait and self._sched_pool is not None:
            plane = self.ctx.sched_plane
            if plane is not None and plane.over_window(self._sched_pool):
                from ..core.sched_plane import SCHED_STATS
                SCHED_STATS["admission_rejects"] += 1
                raise AdmissionBackpressure(
                    f"taskpool {self.name!r} over its admission window "
                    f"(in-flight tasks > configured "
                    f"sched_admission_window)")
        # batch-lane fast path: NO lock — the whole validate+spec-build+
        # buffer-append collapses into one C call (native try_buffer); the
        # list append it performs is GIL-atomic. A 0 return (unknown fn,
        # shape mismatch, priority, device restriction, un-entered tile)
        # falls through to the per-task slow path
        fi = self._fast
        if fi is not None:
            r = self._tbuf(fi, fn, args, priority, where, jit, batch)
            if r:
                if r == 2:      # flush threshold reached
                    self._flush_batch()
                    self._window_stall()
                    if not nowait:
                        self._admission_stall()
                return None
        with self._insert_lock:
            task = self._insert_task_locked(fn, args, priority, where, name,
                                            jit, batch)
        self._window_stall()
        if not nowait:
            self._admission_stall()
        return task

    def _insert_task_locked(self, fn: Callable, args, priority: int,
                            where: int, name: Optional[str],
                            jit: bool, batch: bool) -> Optional[DTDTask]:
        if not self._open:
            output.fatal("insert_task on a closed DTD taskpool")
        if self._bbuf:
            # chain-order guarantee: buffered batch specs precede this
            # task in program order, so they must link first
            self._flush_batch_locked()
        if self._capture is not None and not self._capture_deferred:
            from .capture import CaptureDeferred
            try:
                self._capture.record(fn, args, jit=jit, name=name or "",
                                     priority=priority, where=where)
                self.inserted += 1
                return None
            except CaptureDeferred as e:
                # per-region auto-defer (ISSUE 10): this wait()-delimited
                # window holds a non-capturable insert — replay the
                # recorded prefix through the scheduler in program order
                # (device bodies then ride the device module / ptdev
                # lane) and run the REST of the window interpreted too;
                # capture re-arms at the next window, so capture wins
                # where it applies instead of losing globally
                output.debug_verbose(1, "capture",
                                     f"{self.name}: window deferred to "
                                     f"the scheduler ({e})")
                self._capture_deferred = True
                PTDTD_STATS["capture_windows_deferred"] += 1
                n_rec = len(self._capture.ops)
                # region fusion (ISSUE 12): capturable RUNS of the
                # deferred window collapse into one super-task insert
                # each — capture still wins where it applies, the
                # scheduler handles only the seams
                replays = self._capture.take_ops(
                    fuse=bool(mca.get("region_fusion", True)))
                self.inserted -= n_rec          # re-counted by the replay
                for rfn, rargs, rprio, rwhere, rname in replays:
                    nf = getattr(rfn, "_ptdtd_fused", 0)
                    if nf:
                        PTDTD_STATS["capture_regions_fused"] += 1
                        PTDTD_STATS["capture_tasks_fused"] += nf
                    self._insert_task_locked(rfn, rargs, rprio,
                                             DEV_ALL if rwhere is None
                                             else rwhere, rname or None,
                                             True, False)
                # fall through: THIS task inserts normally below
        flow_accesses: List[int] = []
        arg_spec: List[Tuple[str, Any]] = []
        tiles: List[DTDTile] = []
        affinity_tile: Optional[DTDTile] = None
        for a in args:
            if isinstance(a, tuple) and len(a) == 2 and isinstance(a[0], DTDTile):
                tile, acc = a
                if acc & AFFINITY:
                    affinity_tile = tile
                acc &= ~AFFINITY
                arg_spec.append(("flow", len(flow_accesses)))
                flow_accesses.append(acc)
                tiles.append(tile)
            elif isinstance(a, DTDTile):
                arg_spec.append(("flow", len(flow_accesses)))
                flow_accesses.append(RW)
                tiles.append(a)
            else:
                arg_spec.append(("value", a))
        # one-entry class cache: the dominant pattern is a loop inserting
        # the same body with the same flow shape (the reference's task
        # class reuse), so the 5-tuple dict key is usually redundant.
        # Entry 6 is the batch-lane registration (engine class id + arg
        # kind pattern) the insert_task fast path matches against
        lc = self._last_class
        if lc is not None and lc[0] is fn and lc[1] == flow_accesses \
                and lc[2] == len(arg_spec) and lc[3] == jit and lc[4] == batch:
            tc = lc[5]
            binfo = lc[6]
        else:
            tc = self._class_of(fn, tuple(flow_accesses), len(arg_spec),
                                name, jit_ok=jit, batchable=batch)
            binfo = _BINFO_UNSET
            self._last_class = (fn, list(flow_accesses), len(arg_spec),
                                jit, batch, tc, None)
        task = DTDTask(self, tc, priority)
        task.arg_spec = arg_spec
        task.tiles = tiles
        task.ident = self.inserted
        self.inserted += 1

        neng = self._neng if self._neng_decided else self._native_engine()
        if neng is not None:
            if self._batch_on:
                if binfo is _BINFO_UNSET:
                    # register (or refuse) the batch-lane class for this
                    # arg interleaving so the NEXT insert can take the
                    # lock-free buffered fast path
                    binfo = self._mk_batch_info(tc, flow_accesses, arg_spec)
                    self._last_class = (fn, list(flow_accesses),
                                        len(arg_spec), jit, batch, tc, binfo)
                    if binfo is not None:
                        kinds = binfo[1]
                        if len(kinds) == 1 and kinds[0] is not None:
                            kinds = kinds[0]    # single-flow collapse
                        self._fast = (fn, jit, batch, kinds, binfo[0],
                                      self._bbuf, self._batch_flush_n,
                                      DTDTile)
                PTDTD_STATS["tasks_per_task"] += 1
            # single-rank: owner-computes placement is the identity — the
            # affinity scan below would always land on my_rank
            task.rank = self.ctx.my_rank
            # native fast lane (single-rank): per-tile chain linking, pred
            # discovery, and the insertion-guard drop happen in ONE
            # C-extension call; Python keeps the id->task map plus a cheap
            # chain MIRROR (last_writer/readers/wcount) so tile
            # introspection keeps its documented meaning
            nids, naccs = [], []
            for fi, (tile, acc) in enumerate(zip(tiles, flow_accesses)):
                if acc & NOTRACK:
                    p = self._slot_payload(tile)
                    if p is not None:
                        if task.pending_inputs is None:
                            task.pending_inputs = {}
                        task.pending_inputs[fi] = p
                    continue
                nid = tile.nid
                if nid is None:
                    nid = self._tile_nid(tile)
                nids.append(nid)
                naccs.append(acc & 0x3)
                if acc & WRITE:
                    tile.last_writer = task
                    tile.readers = []
                    tile.compact_at = 32
                    tile.wcount += 1
                    tile.last_writer_version = tile.wcount
                else:
                    readers = tile.readers
                    if len(readers) >= tile.compact_at:
                        live = [r for r in readers if not r.completed]
                        live.append(task)
                        tile.readers = live
                        tile.compact_at = max(32, 2 * len(live))
                    else:
                        readers.append(task)
            # count-then-activate (ref: parsec_dtd_schedule_task_if_ready,
            # insert_function.c:2963): insert() links the chains but KEEPS
            # the insertion guard held, so a fast predecessor completing on
            # a worker thread cannot surface this id from complete() before
            # the id->task map below is populated (the round-5 activation
            # race, ADVICE.md). activate() drops the guard only after the
            # task is findable.
            tid, _held = neng.insert(nids, naccs)
            task.nid = tid
            self.ctx._dtd_ntasks[tid] = task
            self.addto_nb_tasks(1)
            li = self.local_inserted = self.local_inserted + 1
            ndeps = neng.activate(tid)
            if ndeps == 0:
                task.deps_remaining = 0
                # ready now — but insert_task is ASYNCHRONOUS by contract
                # (bodies run at the window stall / wait drain, never at
                # insert): batch toward the scheduler so priorities stay
                # policy-visible while the push cost amortizes. The lock
                # pairs the append with the flusher's swap — two USER
                # threads may insert concurrently regardless of stream
                # count, and an append racing the swap would land in an
                # already-scheduled list
                with self._exec_lock:
                    buf = self._ready_buf
                    buf.append(task)
                if len(buf) >= 1024:
                    self._flush_ready()
            return task     # window stall runs after the insert lock drops

        task.lock = threading.Lock()      # Python engine: preds/release lock
        task.successors = []
        # owner-computes rank (ref: rank from affinity tile's rank_of_key);
        # untracked flows don't steer placement
        if affinity_tile is None:
            for t, acc in zip(tiles, flow_accesses):
                if acc & WRITE and not acc & NOTRACK:
                    affinity_tile = t
                    break
            if affinity_tile is None:
                # fallback prefers tracked flows too: an untracked scratch
                # tile is rank-local and would diverge owner-computes
                # placement across the distributed replay
                tracked = [t for t, acc in zip(tiles, flow_accesses)
                           if not acc & NOTRACK]
                if tracked:
                    affinity_tile = tracked[0]
                elif tiles:
                    affinity_tile = tiles[0]
        task.rank = affinity_tile.rank if affinity_tile is not None \
            else self.ctx.my_rank

        distributed = self.ctx.comm is not None and self.ctx.nb_ranks > 1
        remote = distributed and task.rank != self.ctx.my_rank
        # link against each tile's chain (ref: parsec_dtd_set_params_of_task
        # insert_function.c:2896; WAR via overlap_strategies.c). In
        # distributed mode every rank replays the same sequence, so the
        # version bookkeeping below is globally consistent without messages.
        for fi, (tile, acc) in enumerate(zip(tiles, flow_accesses)):
            self._link_tile(task, tile, acc, fi, remote, distributed)
        if remote:
            # shadow task: executes elsewhere; local role is only data routing
            self.ctx.comm.dtd_remote_task(self, task)
            self._drop_insertion_guard(task, schedule=False)
            return task
        self.addto_nb_tasks(1)
        self.local_inserted += 1
        self._drop_insertion_guard(task, schedule=True)
        return task     # window stall runs after the insert lock drops

    def _link_tile(self, task: DTDTask, tile: DTDTile, acc: int,
                   flow_index: int, remote: bool, distributed: bool) -> None:
        if acc & NOTRACK:
            # untracked access: no chaining, no version bump, no comm
            # bookkeeping, no audit entry — and the VALUE is snapshotted NOW
            # (ref: insert_function.c:3038 captures tile->data_copy at insert
            # time): an untracked flow has no ordering edges, so resolving
            # newest_copy at execution would let the body observe a tracked
            # write that landed after this insertion
            copy = tile.data.newest_copy()
            if copy is not None:
                if task.pending_inputs is None:
                    task.pending_inputs = {}
                task.pending_inputs[flow_index] = copy.payload
            return
        my = self.ctx.my_rank
        preds: List[DTDTask] = []
        with tile.lock:
            read_version = tile.wcount
            src_rank = tile.writer_rank
            # the producer of read_version — captured BEFORE the write side
            # below replaces last_writer (the consumer must attach its send
            # to the task that PRODUCES the version it reads, not to itself)
            prev_writer = tile.last_writer
            if acc & READ or not (acc & WRITE):
                # RAW: predecessor is the last writer (local chain) or a
                # remote version expectation / outbound send
                if tile.last_writer is not None and \
                        (not distributed or tile.last_writer.rank == my):
                    preds.append(tile.last_writer)
                if not remote:
                    readers = tile.readers
                    if len(readers) >= tile.compact_at:
                        # amortized compaction: completed readers are
                        # already-satisfied WAR predecessors — pruning them
                        # keeps long read-chains (and the live object
                        # graph) from growing unboundedly between writes.
                        # The watermark doubles past the survivors so a
                        # burst of never-retiring readers costs O(n log n)
                        # total, not a full rescan per insert
                        live = [r for r in readers if not r.completed]
                        live.append(task)
                        tile.readers = live
                        tile.compact_at = max(32, 2 * len(live))
                    else:
                        readers.append(task)
            if acc & WRITE:
                # WAR: wait on local readers since the previous write; WAW on
                # the local last writer (remote ones are covered by the
                # version expectation on the READ side of RW, or need no
                # local ordering at all)
                for r in tile.readers:
                    if not distributed or r.rank == my:
                        preds.append(r)
                if tile.last_writer is not None and \
                        (not distributed or tile.last_writer.rank == my) and \
                        tile.last_writer not in preds:
                    preds.append(tile.last_writer)
                tile.last_writer = task
                tile.readers = []
                tile.compact_at = 32
                tile.wcount += 1
                tile.last_writer_version = tile.wcount
                tile.writer_rank = task.rank
        if self._audit and not tile.new_tile:
            # deterministic digest of this link decision (crc32: stable
            # across processes, unlike str hash under PYTHONHASHSEED): all
            # ranks replay the same COLLECTION-BACKED inserts, so the
            # chains must agree (tile_new scratch tiles are rank-local by
            # contract and excluded). The digest item avoids a repr()
            # round-trip where the key is already bytes-able: collection
            # keys are (dc.name, data_key) with int/str/tuple-of-int parts,
            # so a %-format over the scalar fields byte-compiles the same
            # decision without building the intermediate repr string of a
            # nested tuple (the link-path profile showed repr+encode as
            # the audit branch's dominant cost)
            key = tile.key
            if type(key) is tuple and len(key) == 2 and \
                    isinstance(key[1], (int, str)):
                item = b"%s\x00%a\x00%d\x00%d\x00%d\x00%d" % (
                    key[0].encode(), key[1], acc & 0x3, read_version,
                    src_rank, task.rank)
            else:
                item = repr((key, acc & 0x3, read_version, src_rank,
                             task.rank)).encode()
            self._audit_digest = zlib.crc32(item, self._audit_digest)
            self._audit_count += 1
        if distributed:
            comm = self.ctx.comm
            needs_data = bool(acc & READ)   # pure WRITE flows ship nothing
            if not remote and needs_data and src_rank != my:
                # local consumer of a remotely-produced version
                comm.expect(self, task, tile, read_version, src_rank,
                            flow_index)
            elif remote and needs_data and src_rank == my:
                # remote consumer of a locally-held/produced version
                comm.note_send(self, tile, read_version, task.rank,
                               writer=prev_writer)
        if remote:
            return
        seen = set()
        for p in preds:
            if id(p) in seen or p is task:
                continue
            seen.add(id(p))
            with p.lock:
                if not p.completed:
                    p.successors.append(task)
                    with task.lock:
                        task.deps_remaining += 1

    def _drop_insertion_guard(self, task: DTDTask, schedule: bool) -> None:
        if task.dep_satisfied() and schedule:
            # ref: parsec_dtd_schedule_task_if_ready (insert_function.c:2963)
            self.ctx.schedule([task])

    # ------------------------------------------------------------- hooks
    def _prepare_input(self, stream, task: DTDTask) -> int:
        if task.data is None:     # lazy_data: first touch allocates
            from ..core.task import TaskData
            task.data = [TaskData()
                         for _ in range(task.task_class.nb_flows)]
        pending = task.pending_inputs
        batch_on = self._batch_on
        for i, tile in enumerate(task.tiles):
            pend = pending.pop(i, None) if pending else None
            if pend is None and batch_on and tile.nid is not None:
                # batch-lane coherence: in-flight batched writes live in
                # the engine slot, not yet in tile.data (synced at wait)
                p = self._neng.slot_get(tile.nid)
                copy = tile.data.newest_copy()
                if p is not None and (copy is None or p is not copy.payload):
                    pend = p
            if pend is not None:
                # remote exact-version payload (may differ from newest_copy
                # when versions raced in through the network out of order);
                # an unattached copy: carries the right Data for write-back
                # without perturbing newest_copy resolution
                from ..data.data import DataCopy
                task.data[i].data_in = DataCopy(tile.data, 0, pend)
                continue
            copy = tile.data.newest_copy()
            if copy is None:
                output.fatal(f"tile {tile!r} has no valid copy for {task!r}")
            task.data[i].data_in = copy
        return HOOK_DONE

    def _gather_args(self, task: DTDTask, flow_payloads: Sequence[Any]) -> List[Any]:
        vals = []
        for kind, v in task.arg_spec:
            if kind == "flow":
                vals.append(flow_payloads[v])
            else:
                vals.append(v)
        return vals

    def _apply_outputs(self, task: DTDTask, outs) -> List[Any]:
        if outs is None:
            outs = ()
        elif not isinstance(outs, (tuple, list)):
            outs = (outs,)
        return list(outs)

    def _jittable(self, task: DTDTask) -> bool:
        if not task.task_class.jit_ok:
            return False
        return all(kind != "value" or isinstance(v, (int, float, np.number, np.ndarray))
                   for kind, v in task.arg_spec)

    def _cpu_hook(self, stream, task: DTDTask) -> int:
        tc: DTDTaskClass = task.task_class
        payloads = [s.data_in.payload if s.data_in is not None else None
                    for s in task.data]
        vals = self._gather_args(task, payloads)
        # jit the body on the host backend too: eager per-op dispatch is the
        # dominant cost for jax-expressed bodies (compiled once per class)
        if self._jittable(task):
            fn = tc.jitted()
            cpu = _host_device()
            import jax
            conv = []
            for v in vals:
                if isinstance(v, (int, float)):
                    v = np.asarray(v)
                elif cpu is not None and isinstance(v, np.ndarray):
                    v = jax.device_put(v, cpu)
                conv.append(v)
            # persist converted flow payloads on their copies: each tile
            # crosses into the backend ONCE per DAG instead of on every
            # consuming task (the dominant re-copy cost for READ panels).
            # Only when the conversion is lossless — device_put canonicalizes
            # 64-bit dtypes under default x64-disabled jax, and that must
            # stay confined to the jitted computation, not the stored copy
            for (kind, fi), cv in zip(task.arg_spec, conv):
                if kind == "flow":
                    slot = task.data[fi]
                    if slot.data_in is not None and \
                            isinstance(slot.data_in.payload, np.ndarray) and \
                            getattr(cv, "dtype", None) == slot.data_in.payload.dtype:
                        slot.data_in.payload = cv
            if cpu is not None:
                with jax.default_device(cpu):
                    outs = self._apply_outputs(task, fn(*conv))
            else:
                outs = self._apply_outputs(task, fn(*conv))
        else:
            outs = self._apply_outputs(task, tc.fn(*vals))
        oi = 0
        for i, acc in enumerate(tc.flow_accesses):
            if acc & WRITE:
                tile = task.tiles[i]
                new = outs[oi] if oi < len(outs) else payloads[i]
                oi += 1
                copy = task.data[i].data_in
                host = tile.data.get_copy(0)
                if host is None:
                    host = tile.data.create_copy(0, new, COHERENCY_OWNED)
                else:
                    host.payload = new
                tile.data.bump_version(0)
                if self._batch_on and tile.nid is not None:
                    # keep the engine slot coherent for batched readers
                    # (no batch-write count: version bumped above)
                    self._neng.slot_set(tile.nid, new)
                task.data[i].data_out = host
        return HOOK_DONE

    def _tpu_hook(self, stream, task: "DTDTask") -> int:
        """TPU chore: enqueue on the selected device, with batching metadata
        (plays the generated GPU hook role, jdf2c.c:6613)."""
        from ..device.tpu import TPUTask, _run_inline
        dev = task.selected_device
        if dev is None or not isinstance(dev, TPUDevice):
            return _run_inline(stream, task, self._tpu_submit)
        tc: DTDTaskClass = task.task_class
        batchable = tc.batchable and self._jittable(task)
        gt = TPUTask(task, self._tpu_submit, batchable=batchable,
                     batch_submit=self._tpu_batch_submit if batchable else None)
        return dev.kernel_scheduler(stream, task, tpu_task=gt)

    def _tpu_batch_submit(self, device: TPUDevice, tasks: List["DTDTask"],
                          inputs_list: List[List[Any]]):
        """One vmapped dispatch over a batch of compatible independent tasks
        (they are mutually independent by construction: only dependency-free
        tasks sit in the device queue)."""
        import jax
        import jax.numpy as jnp
        tc: DTDTaskClass = tasks[0].task_class
        vals_list = [self._gather_args(t, inp)
                     for t, inp in zip(tasks, inputs_list)]
        stacked = []
        for i in range(len(vals_list[0])):
            col = [np.asarray(v) if isinstance(v, (int, float)) else v
                   for v in (vals[i] for vals in vals_list)]
            stacked.append(jnp.stack(col))
        vm = _vmapped(tc.fn)
        outs = vm(*stacked)
        if outs is None:
            return [() for _ in tasks]
        if not isinstance(outs, (tuple, list)):
            outs = (outs,)
        return [tuple(o[i] for o in outs) for i in range(len(tasks))]

    def _tpu_submit(self, device: TPUDevice, task: DTDTask, inputs: List[Any]):
        """TPU chore body: call the jitted class function on device arrays.

        Non-jittable bodies (non-numeric by-value args) fall back to eager;
        JAX still dispatches the ops asynchronously.
        """
        tc: DTDTaskClass = task.task_class
        vals = self._gather_args(task, inputs)
        jittable = self._jittable(task)
        fn = tc.jitted() if jittable else tc.fn
        if jittable:
            vals = [np.asarray(v) if isinstance(v, (int, float)) else v
                    for v in vals]
        outs = self._apply_outputs(task, fn(*vals))
        # order outputs by WRITE flows (contract shared with device epilog)
        return tuple(outs)

    def _complete_execution(self, stream, task: DTDTask) -> int:
        with self._exec_lock:
            self._executed += 1
        if task.nid >= 0 and self.ctx.pins.enabled:
            # instrumentation mirror: the native engine owns the successor
            # lists, but PINS consumers (the DOT grapher) read
            # task.successors at RELEASE_DEPS_BEGIN — which fires after
            # this hook and before _release_deps moves the engine's list.
            # Only per-task-lane successors have Python task objects;
            # batch-lane ids stay engine-internal
            ntasks = self.ctx._dtd_ntasks
            task.successors = [ntasks[s]
                               for s in self._neng.successors(task.nid)
                               if s in ntasks]
        return HOOK_DONE

    @property
    def executed(self) -> int:
        return self._executed

    def _release_deps(self, stream, task: DTDTask) -> None:
        """DTD successor release (ref: parsec_dtd_ordering_correctly,
        insert_function_internal.h:277): flip completed, wake successors."""
        if task.nid >= 0:
            # native fast lane: the successor walk + newly-ready collection
            # is one C-extension call (no per-successor locks — the GIL
            # already serializes engine access)
            task.completed = True
            ready_ids = self._neng.complete(task.nid)
            self.ctx._dtd_ntasks.pop(task.nid, None)
            task.tiles = ()
            task.arg_spec = ()
            task.data = ()
            task.pending_inputs = None
            task.successors = None   # drop the instrumentation mirror
            if ready_ids:
                self._schedule_native_ready(ready_ids, stream)
            return
        with task.lock:
            task.completed = True
            succs = task.successors
            task.successors = []
        # ship remote sends FIRST: the payload references must be captured
        # before any released successor can rebind the tile's host copy
        if self.ctx.comm is not None:
            self.ctx.comm.dtd_task_completed(self, task)
        # retire the task's object graph (the mempool-return moment of
        # parsec_dtd_release_task): dropping the tile/copy references here
        # lets refcounting reclaim payload buffers immediately and keeps
        # the completed shell acyclic, so deferred GC at quiescence walks
        # shells, not the whole DAG
        task.tiles = ()
        task.arg_spec = ()
        task.data = ()
        task.pending_inputs = None
        ready = [s for s in succs if s.dep_satisfied()]
        if ready:
            self.ctx.schedule(ready, stream)

    # ------------------------------------------------------------- flush/wait
    def data_flush(self, tile: DTDTile) -> None:
        """parsec_dtd_data_flush (ref: parsec_dtd_data_flush.c): insert a task
        that writes the tile's newest version back home (host copy of the
        owner)."""
        self.insert_task(_flush_body, (tile, RW), name="dtd_flush", jit=False)

    def data_flush_all(self, dc: DataCollection) -> None:
        """parsec_dtd_data_flush_all: flush every tile of ``dc`` seen so far."""
        with self._tiles_lock:
            tiles = [t for t in self._touched_tiles if t.dc is dc]
        for t in tiles:
            self.data_flush(t)

    def wait_mesh(self, mesh, axis_names=None) -> bool:
        """Capture-mode only: execute the recorded DAG as ONE GSPMD program
        over ``mesh`` — collection tiles become slices of sharded global
        arrays, XLA partitions the work and inserts the ICI transfers
        (see dsl/capture.py:execute_mesh)."""
        if self._capture is None:
            output.fatal("wait_mesh requires DTDTaskpool(capture=True)")
        self._capture.execute_mesh(mesh, axis_names)
        return True

    def wait(self, timeout: Optional[float] = None) -> bool:
        """parsec_dtd_taskpool_wait: drain everything this rank executes."""
        if self._capture is not None:
            if not self._capture_deferred:
                self._capture.execute()
                return True
            # deferred window: the region's tasks went through the
            # scheduler — drain them like an uncaptured pool, then re-arm
            # capture for the next window
            self._capture_deferred = False
        if self._audit and self.ctx.comm is not None and self.ctx.nb_ranks > 1:
            # replay audit BEFORE blocking on completion: a divergent insert
            # sequence surfaces as a fatal here instead of a silent hang
            self.ctx.comm.audit_check(self, self._audit_digest,
                                      self._audit_count)
        self._flush_ready()
        self.ctx.start()
        target = self.local_inserted
        self.ctx._progress_loop(self.ctx.streams[0],
                                until=lambda: self.executed >= target and
                                self.nb_tasks == 0,
                                timeout=timeout)
        done = self.executed >= target
        if done:
            # slot-ownership hand-off: batched writes land back in
            # tile.data now that the pool is drained
            self._sync_slots()
        return done

    def close(self) -> None:
        """End of insertion: drop the open action so termination can fire."""
        self._fast = None     # closed pools must fatal via the slow path
        if self._capture is not None and self._capture.ops:
            # scheduler-mode inserts execute without an explicit wait();
            # captured ops must not be silently dropped on close
            self._capture.execute()
        self._flush_ready()
        if self._neng is not None:
            self.ctx.unregister_drain_hook(self._flush_ready)
        if self._open:
            self._open = False
            self.addto_nb_pending_actions(-1)

    def __enter__(self) -> "DTDTaskpool":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        if exc_type is None:
            self.wait()
        self.close()
