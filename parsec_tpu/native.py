"""ctypes bindings for the native C++ core (native/src/ptcore.cpp).

The library is built on demand with the in-tree Makefile; every binding has
a pure-Python fallback so the framework works without a toolchain. Wired-in
fast paths:

* :class:`NativeDepTable` — the dependency-update engine
  (parsec_update_deps_with_mask role) behind ``Taskpool.update_deps`` for
  integer-tuple keys.
* :class:`NativeZone` — backend for :class:`parsec_tpu.utils.zone_malloc`.

A native ready-deque was prototyped here for the schedulers and REMOVED
after measurement: a ctypes call costs ~2µs at the boundary while a
``collections.deque`` op is ~0.14µs and already GIL-atomic — the
measured gap was 7x IN FAVOR of the Python deque (200k push+pop pairs:
0.39s native vs 0.057s deque, this container). The scheduler
ready-queues therefore use lock-free single-call deque ops
(core/scheduler.py:_LockedDeque); native code is reserved for paths
where the work per call dominates the boundary cost (the dep table:
hash + probe per update).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional, Tuple

from .utils import mca, output

mca.register("native_enabled", True, "Use the native C++ core when available", type=bool)

_PKG_DIR = os.path.dirname(os.path.abspath(__file__))
_ROOT = os.path.dirname(_PKG_DIR)
_NATIVE_DIR = os.path.join(_ROOT, "native")
_SO = os.path.join(_NATIVE_DIR, "build", "libptcore.so")


def _installed_so(stem: str):
    """ABI-tagged extension inside the installed package (wheel layout:
    setup.py builds parsec_tpu._ptcore/_ptdtd into the package dir), or
    None. Only the exact RUNNING interpreter's suffix is accepted."""
    import sysconfig
    p = os.path.join(_PKG_DIR, stem + sysconfig.get_config_var("EXT_SUFFIX"))
    return p if os.path.exists(p) else None

_lib = None
_lib_lock = threading.Lock()
_KEY_MAX = 16


def _build() -> bool:
    try:
        import sys
        r = subprocess.run(["make", "-C", _NATIVE_DIR,
                            f"PYTHON={sys.executable}"],
                           capture_output=True, text=True, timeout=120)
        if r.returncode != 0:
            output.debug_verbose(1, "native", f"build failed: {r.stderr[-500:]}")
            return False
        return os.path.exists(_SO)
    except Exception as e:  # noqa: BLE001
        output.debug_verbose(1, "native", f"build error: {e}")
        return False


def load() -> Optional[ctypes.CDLL]:
    """Load (building if needed) the native library; None on failure."""
    global _lib
    if _lib is not None:
        return _lib
    if not mca.get("native_enabled", True):
        return None
    with _lib_lock:
        if _lib is not None:
            return _lib
        # installed wheel first (parsec_tpu/_ptcore.*.so — a C-ABI library
        # that happens to be built by the Extension machinery), then the
        # in-tree build, then build-on-demand
        so = _installed_so("_ptcore")
        if so is None:
            if not os.path.exists(_SO) and not _build():
                return None
            so = _SO
        try:
            lib = ctypes.CDLL(so)
        except OSError as e:
            output.debug_verbose(1, "native", f"dlopen failed: {e}")
            return None
        # signatures
        lib.pt_dep_table_create.restype = ctypes.c_void_p
        lib.pt_dep_table_create.argtypes = [ctypes.c_uint64]
        lib.pt_dep_table_destroy.argtypes = [ctypes.c_void_p]
        lib.pt_dep_table_size.restype = ctypes.c_int64
        lib.pt_dep_table_size.argtypes = [ctypes.c_void_p]
        lib.pt_dep_table_update.restype = ctypes.c_int32
        lib.pt_dep_table_update.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_int64), ctypes.c_int32,
            ctypes.c_int64, ctypes.c_int64, ctypes.c_int32]
        lib.pt_dep_table_get.restype = ctypes.c_int64
        lib.pt_dep_table_get.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_int64), ctypes.c_int32]
        lib.pt_zone_create.restype = ctypes.c_void_p
        lib.pt_zone_create.argtypes = [ctypes.c_int64, ctypes.c_int64]
        lib.pt_zone_destroy.argtypes = [ctypes.c_void_p]
        lib.pt_zone_alloc.restype = ctypes.c_int64
        lib.pt_zone_alloc.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        lib.pt_zone_free.argtypes = [ctypes.c_void_p, ctypes.c_int64,
                                     ctypes.c_int64]
        lib.pt_zone_stats.argtypes = [ctypes.c_void_p,
                                      ctypes.POINTER(ctypes.c_int64)]
        _lib = lib
        output.debug_verbose(1, "native", f"native core loaded from {_SO}")
        return _lib


def available() -> bool:
    return load() is not None


_ptdtd_mod = [None, False]   # [module, attempted]
_ptexec_mod = [None, False]
_ptcomm_mod = [None, False]
_ptsched_mod = [None, False]
_ptdev_mod = [None, False]


def _load_pyext(stem: str, cache):
    """Load a CPython extension (built by native/Makefile or installed in
    the wheel), memoized in ``cache`` ([module, attempted]).

    ``attempted`` is published only AFTER the load finished (inside the
    lock): the unlocked fast check races the loader, and publishing it
    up front let a second thread observe attempted=True with the module
    still None — it then recorded a permanent "native unavailable"
    (found by the serving bench's concurrent first-inserts, where N
    client threads hit the first load simultaneously)."""
    if cache[1]:
        return cache[0]
    with _lib_lock:
        if cache[1]:
            return cache[0]
        try:
            if not mca.get("native_enabled", True):
                return None
            import importlib.util
            import sysconfig
            # installed wheel first; else the in-tree build. Exact
            # ABI-tagged filename of the RUNNING interpreter — a wildcard
            # could load a stale extension built against another Python
            so = _installed_so(stem)
            if so is None:
                so = os.path.join(
                    _NATIVE_DIR, "build",
                    stem + sysconfig.get_config_var("EXT_SUFFIX"))
                if not os.path.exists(so) and not (_build()
                                                   and os.path.exists(so)):
                    return None
            try:
                spec = importlib.util.spec_from_file_location(
                    f"parsec_tpu.{stem}", so)
                mod = importlib.util.module_from_spec(spec)
                spec.loader.exec_module(mod)
                cache[0] = mod
                output.debug_verbose(1, "native",
                                     f"{stem} loaded from {so}")
            except Exception as e:  # noqa: BLE001
                output.debug_verbose(1, "native",
                                     f"{stem} load failed: {e}")
            return cache[0]
        finally:
            cache[1] = True


def load_ptdtd():
    """The CPython-extension DTD engine (native/src/ptdtd.cpp), or None.

    A separate artifact from libptcore.so: per-task hot paths need
    C-extension call costs (~0.2us) — the ctypes boundary (~2us) that the
    coarse bindings above tolerate would eat the entire win (module
    docstring)."""
    return _load_pyext("_ptdtd", _ptdtd_mod)


def load_ptexec():
    """The CPython-extension PTG execution lane (native/src/ptexec.cpp),
    or None. Runs the generic task FSM — dep-count decrement, ready
    detect, dispatch, successor release — over a flattened successor
    table, batched, with the GIL dropped across the walk (see
    docs/native_exec.md for the eligibility and GIL contract)."""
    return _load_pyext("_ptexec", _ptexec_mod)


def load_ptcomm():
    """The CPython-extension communication lane (native/src/ptcomm.cpp),
    or None. A funneled C progress thread that multiplexes the cross-rank
    mesh (TCP fds + same-host shm rings), speaks the fixed binary AM
    protocol, and ingests activations straight into the ptexec/ptdtd
    ready structures without the GIL (docs/native_exec.md)."""
    return _load_pyext("_ptcomm", _ptcomm_mod)


def load_ptsched():
    """The CPython-extension scheduler plane (native/src/ptsched.cpp), or
    None. Per-worker bounded hot queues with cross-worker steal-half,
    per-pool overflow heaps, weighted deficit-round-robin arbitration and
    admission windows — the shared ready plane the ptexec/ptdtd engines
    drain through when a Context arms it (docs/scheduling.md)."""
    return _load_pyext("_ptsched", _ptsched_mod)


def load_ptdev():
    """The CPython-extension device lane (native/src/ptdev.cpp), or None.
    Per-device async dispatch queues fed GIL-free from the engines'
    release sweeps, a manager thread issuing JAX dispatch and polling
    completion events, GIL-free retirement back into the engines, and the
    C-side coherency/residency table (docs/device_lane.md)."""
    return _load_pyext("_ptdev", _ptdev_mod)


class NativeDepTable:
    """Dependency tracker for int-tuple keys (mask or counter mode)."""

    __slots__ = ("_t", "_lib")

    def __init__(self, capacity: int = 1 << 16) -> None:
        self._lib = load()
        if self._lib is None:
            raise RuntimeError("native core unavailable")
        self._t = self._lib.pt_dep_table_create(capacity)
        if not self._t:
            raise MemoryError("pt_dep_table_create failed")

    @staticmethod
    def key_ok(key) -> bool:
        if isinstance(key, int):
            return True
        return (isinstance(key, tuple) and len(key) <= _KEY_MAX
                and all(isinstance(k, int) for k in key))

    @staticmethod
    def _pack(key) -> Tuple[ctypes.Array, int]:
        # fresh array per call: update() is invoked concurrently from worker
        # threads, a shared buffer would race before the C side copies it
        if isinstance(key, int):
            return (ctypes.c_int64 * 1)(key), 1
        return (ctypes.c_int64 * len(key))(*key), len(key)

    def update(self, key, contribution: int, goal: int, count_mode: bool) -> bool:
        buf, klen = self._pack(key)
        rc = self._lib.pt_dep_table_update(self._t, buf, klen, contribution,
                                           goal, 1 if count_mode else 0)
        if rc < 0:
            raise RuntimeError(f"native dep table error {rc}")
        return rc == 1

    def get(self, key) -> int:
        buf, klen = self._pack(key)
        return self._lib.pt_dep_table_get(self._t, buf, klen)

    def __len__(self) -> int:
        return self._lib.pt_dep_table_size(self._t)

    def __del__(self) -> None:
        try:
            if self._t and self._lib:
                self._lib.pt_dep_table_destroy(self._t)
        except Exception:  # noqa: BLE001 - interpreter shutdown
            pass


class NativeZone:
    """Native zone allocator backend (see utils/zone_malloc.py)."""

    __slots__ = ("_z", "_lib")

    def __init__(self, total_bytes: int, unit: int = 1 << 20) -> None:
        self._lib = load()
        if self._lib is None:
            raise RuntimeError("native core unavailable")
        self._z = self._lib.pt_zone_create(total_bytes, unit)

    def alloc(self, nbytes: int) -> Optional[int]:
        off = self._lib.pt_zone_alloc(self._z, nbytes)
        return None if off < 0 else off

    def free(self, offset: int, nbytes: int) -> None:
        self._lib.pt_zone_free(self._z, offset, nbytes)

    def stats(self) -> dict:
        out = (ctypes.c_int64 * 4)()
        self._lib.pt_zone_stats(self._z, out)
        return {"free_bytes": out[0], "in_use_bytes": out[1],
                "hwm_bytes": out[2], "largest_hole_bytes": out[3]}

    def __del__(self) -> None:
        try:
            if self._z and self._lib:
                self._lib.pt_zone_destroy(self._z)
        except Exception:  # noqa: BLE001
            pass


