"""Sequence/context parallelism: ring attention and Ulysses all-to-all.

The long-context half of the framework (first-class here even though the
reference has no sequence dimension at all — SURVEY §5 "Long-context":
absent; its chain-pipeline broadcasts + neighbor deps are the moral
pattern, stencil_1D.jdf). Two TPU-native schemes over one
``jax.sharding.Mesh`` axis:

* :func:`ring_attention` — the sequence axis stays sharded; K/V blocks
  rotate around the ring via ``lax.ppermute`` (ICI neighbor hops, fully
  overlapped by XLA with the per-step matmuls) while each device folds
  every block into a numerically-stable online softmax (the
  flash/blockwise accumulation: running max + rescaled sum). Memory per
  chip stays O(S/P · S/P); no materialized S×S attention matrix, ever.
  Causal masking works on global positions reconstructed from the ring
  step, and fully-masked early blocks contribute nothing.
* :func:`ulysses_attention` — the all-to-all scheme: resharding seq→heads
  via ``lax.all_to_all``, dense per-head attention locally, then
  heads→seq back. Two A2As instead of P-1 neighbor hops; wins when
  H >= P and the sequence blocks are small.

Both are pure ``shard_map`` programs: pick the mesh, annotate the
shardings, let XLA insert the collectives (the scaling-book recipe).
"""

from __future__ import annotations

import functools
from typing import Optional

import numpy as np


def _seq_mesh(n_devices: Optional[int] = None):
    """A 1D mesh over the sequence-parallel axis ``sp``."""
    from .spmd import make_1d_mesh
    return make_1d_mesh("sp", n_devices)


def _fold_block(acc, k, v, src, q, scale, causal, q_pos, k_pos0, block):
    """Fold the resident K/V block into the (o, m, l) online softmax."""
    import jax.numpy as jnp
    o, m, l = acc
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    if causal:
        kp = src * block + k_pos0                      # global key positions
        mask = kp[None, None, None, :] <= q_pos[None, None, :, None]
        s = jnp.where(mask, s, -jnp.inf)
    m_new = jnp.maximum(m, s.max(axis=-1))
    # exp(-inf - -inf) guards: a fully-masked row keeps m=-inf, p=0
    p = jnp.exp(s - m_new[..., None])
    p = jnp.where(jnp.isfinite(s), p, 0.0)
    corr = jnp.where(jnp.isfinite(m), jnp.exp(m - m_new), 0.0)
    l = l * corr + p.sum(axis=-1)
    o = o * corr[..., None] + jnp.einsum("bhqk,bhkd->bhqd", p, v)
    return o, m_new, l


@functools.lru_cache(maxsize=None)
def _ring_call(mesh, causal: bool, block: int, scale: float):
    """One compiled shard_map program per (mesh, causal, block, scale) —
    every attention layer / training step reuses it (jax.Mesh is
    hashable; jit's own cache handles the remaining shape signature)."""
    import jax
    import jax.numpy as jnp
    from .compat import shard_map
    from jax.sharding import PartitionSpec as P

    axis = mesh.axis_names[0]
    nP = mesh.devices.size
    perm = [(i, (i + 1) % nP) for i in range(nP)]

    def local(qb, kb, vb):
        idx = jax.lax.axis_index(axis)
        q_pos = idx * block + jnp.arange(block)
        k_pos0 = jnp.arange(block)
        o = jnp.zeros_like(qb)
        # derive from qb so the carry is device-varying from step 0 (the
        # shard_map manual-axes type system requires carry-in == carry-out)
        m = qb[..., 0] * 0.0 - jnp.inf
        l = qb[..., 0] * 0.0
        fold = functools.partial(_fold_block, q=qb, scale=scale,
                                 causal=causal, q_pos=q_pos, k_pos0=k_pos0,
                                 block=block)
        # fold the resident block, then P-1 x (rotate, fold): exactly the
        # P-1 neighbor hops the ring needs, none wasted
        acc = fold((o, m, l), kb, vb, idx)

        def step(carry, _):
            acc, k, v, src = carry
            k = jax.lax.ppermute(k, axis, perm)
            v = jax.lax.ppermute(v, axis, perm)
            src = jax.lax.ppermute(src, axis, perm)
            return (fold(acc, k, v, src), k, v, src), None

        if nP > 1:
            (acc, _, _, _), _ = jax.lax.scan(
                step, (acc, kb, vb, idx), None, length=nP - 1)
        o, m, l = acc
        safe_l = jnp.where(l > 0, l, 1.0)
        return o / safe_l[..., None]

    spec = P(None, None, axis, None)
    return jax.jit(shard_map(local, mesh=mesh, in_specs=(spec, spec, spec),
                             out_specs=spec))


def ring_attention(q, k, v, mesh=None, causal: bool = False,
                   scale: Optional[float] = None):
    """Multi-head attention with the sequence axis sharded over the mesh.

    ``q``/``k``/``v``: (batch, heads, seq, head_dim) global arrays (host or
    device); the mesh size must divide seq. Returns the attention output
    with the same global shape and sharding.
    """
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = mesh if mesh is not None else _seq_mesh()
    nP = mesh.devices.size
    B, H, S, D = q.shape
    assert S % nP == 0, f"the {nP}-device mesh must divide seq {S}"
    block = S // nP
    sc = scale if scale is not None else 1.0 / float(np.sqrt(D))
    fn = _ring_call(mesh, causal, block, sc)
    sharding = NamedSharding(mesh, P(None, None, mesh.axis_names[0], None))
    qd, kd, vd = (jax.device_put(x, sharding) for x in (q, k, v))
    return fn(qd, kd, vd)


@functools.lru_cache(maxsize=None)
def _ulysses_call(mesh, causal: bool, scale: float):
    import jax
    import jax.numpy as jnp
    from .compat import shard_map
    from jax.sharding import PartitionSpec as P

    axis = mesh.axis_names[0]
    sc = scale

    def local(qb, kb, vb):
        # (B, H, S/P, D) -> all_to_all -> (B, H/P, S, D)
        def a2a(x):
            return jax.lax.all_to_all(x, axis, split_axis=1, concat_axis=2,
                                      tiled=True)
        qh, kh, vh = a2a(qb), a2a(kb), a2a(vb)
        # full sequence per device after the A2A: the fused flash kernel
        # streams k/v blocks through VMEM (falls back to the XLA
        # expression of the same math off-TPU); vma types the output as
        # device-varying for the shard_map checker
        from ..ops.pallas_kernels import flash_attention
        oh = flash_attention(qh, kh, vh, causal=causal, scale=sc,
                             vma=(axis,))
        # back: (B, H/P, S, D) -> (B, H, S/P, D)
        return jax.lax.all_to_all(oh, axis, split_axis=2, concat_axis=1,
                                  tiled=True)

    spec = P(None, None, axis, None)
    # check_vma=False: pallas interpret mode cannot yet discharge a
    # vma-typed pallas_call (jax raises "dynamic_slice requires varying
    # manual axes to match ... as a temporary workaround pass
    # check_vma=False"); the kernel still declares vma on its output so
    # re-enabling the checker is a one-line change when jax supports it.
    return jax.jit(shard_map(local, mesh=mesh, in_specs=(spec, spec, spec),
                             out_specs=spec, check_vma=False))


def ulysses_attention(q, k, v, mesh=None, causal: bool = False,
                      scale: Optional[float] = None):
    """All-to-all (Ulysses) sequence parallelism: reshard seq->heads, run
    dense attention per device on full sequences of H/P heads, reshard
    back. The mesh size must divide both heads and seq."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = mesh if mesh is not None else _seq_mesh()
    nP = mesh.devices.size
    B, H, S, D = q.shape
    assert H % nP == 0, f"the {nP}-device mesh must divide heads {H}"
    assert S % nP == 0, f"the {nP}-device mesh must divide seq {S}"
    sc = scale if scale is not None else 1.0 / float(np.sqrt(D))
    fn = _ulysses_call(mesh, causal, sc)
    sharding = NamedSharding(mesh, P(None, None, mesh.axis_names[0], None))
    qd, kd, vd = (jax.device_put(x, sharding) for x in (q, k, v))
    return fn(qd, kd, vd)


def dense_attention_reference(q, k, v, causal: bool = False,
                              scale: Optional[float] = None):
    """Single-device reference for the tests."""
    import jax.numpy as jnp
    D = q.shape[-1]
    sc = scale if scale is not None else 1.0 / float(np.sqrt(D))
    s = jnp.einsum("bhqd,bhkd->bhqk", jnp.asarray(q), jnp.asarray(k)) * sc
    if causal:
        S = s.shape[-1]
        mask = jnp.tril(jnp.ones((S, S), bool))
        s = jnp.where(mask[None, None], s, -jnp.inf)
    import jax
    a = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", a, jnp.asarray(v))
