"""SPMD execution paths over TPU meshes.

This is the TPU-native embodiment of the reference's distribution machinery
(SURVEY §2.8): where PaRSEC pairs owner-computes collections
(two_dim_rectangle_cyclic.c) with per-dep multicast trees
(remote_dep.c:322-411, chain-pipeline/binomial over rank-bit masks), the TPU
framework lays the P×Q process grid directly over the ICI mesh axes and lets
XLA collectives carry the dataflow:

* :func:`distributed_gemm` — Cannon's algorithm under ``shard_map``:
  pre-skew, then T steps of (local MXU dot, neighbor ``ppermute``). All
  traffic is nearest-neighbor on the torus — the moral equivalent of the
  reference's chain-pipelined broadcast, with zero host involvement.
* :func:`distributed_gemm_allgather` — the bandwidth-optimal 2-collective
  variant (all_gather row/col panels, one local dot); XLA overlaps the
  gathers with compute.
* :func:`distributed_potrf` — right-looking blocked Cholesky: per-k jitted
  shard_map step (panel factor + broadcast + trailing SYRK/GEMM update),
  host loop over k. The broadcast of the panel is an ``all_gather`` along
  one mesh axis = the reference's multicast tree ridden by the torus.

These functions double as the driver's multi-chip dry-run payload
(``__graft_entry__.dryrun_multichip``).
"""

from __future__ import annotations

import math
from functools import partial
from typing import Optional, Tuple

import numpy as np


def _jax():
    import jax
    return jax


def best_grid(n: int) -> Tuple[int, int]:
    """Most-square P×Q factorization of n (grid helper, ref grid_2Dcyclic.c)."""
    p = int(math.sqrt(n))
    while n % p:
        p -= 1
    return p, n // p


def make_1d_mesh(axis_name: str, n_devices: Optional[int] = None):
    """A 1D mesh over the first n devices (the seq/pipeline/expert axis
    builder shared by ring_attention/pipeline/moe)."""
    jax = _jax()
    devs = jax.devices()
    n = n_devices or len(devs)
    if n > len(devs):
        raise ValueError(f"requested {n} devices for axis {axis_name!r}, "
                         f"have {len(devs)}")
    return jax.sharding.Mesh(np.array(devs[:n]), (axis_name,))


def make_mesh(n_devices: Optional[int] = None,
              axis_names: Tuple[str, str] = ("p", "q")):
    """Build a 2D device mesh over the available chips.

    On a real pod the default device order follows the ICI torus so that
    adjacent mesh coordinates are physical neighbors.
    """
    jax = _jax()
    devs = jax.devices()
    n = n_devices or len(devs)
    P, Q = best_grid(n)
    arr = np.array(devs[:n]).reshape(P, Q)
    return jax.sharding.Mesh(arr, axis_names)


def distributed_gemm(A, B, mesh=None, dtype=None):
    """C = A @ B via Cannon's algorithm on a P×P mesh slice.

    Per step: one local tile dot (MXU) + one neighbor ppermute per operand
    (ICI). Requires a square grid; falls back to the all-gather variant
    otherwise.
    """
    jax = _jax()
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P
    from .compat import shard_map

    if mesh is None:
        mesh = make_mesh()
    Pm, Qm = mesh.devices.shape
    if Pm != Qm:
        return distributed_gemm_allgather(A, B, mesh, dtype)
    T = Pm

    # pre-skew permutations over the flattened (p, q) rank space: block (p, j)
    # moves to (p, (j - p) % T); (i, q) to ((i - q) % T, q). Static — the
    # compiler schedules them as one collective-permute each.
    skew_a = [(p * T + j, p * T + (j - p) % T)
              for p in range(T) for j in range(T)]
    skew_b = [(i * T + q, ((i - q) % T) * T + q)
              for i in range(T) for q in range(T)]

    def body(a_blk, b_blk):
        a = jax.lax.ppermute(a_blk, ("p", "q"), skew_a)
        b = jax.lax.ppermute(b_blk, ("p", "q"), skew_b)

        def step(carry, _):
            a, b, acc = carry
            acc = acc + jnp.dot(a, b, preferred_element_type=jnp.float32)
            a = jax.lax.ppermute(a, "q", [(j, (j - 1) % T) for j in range(T)])
            b = jax.lax.ppermute(b, "p", [(i, (i - 1) % T) for i in range(T)])
            return (a, b, acc), None

        acc = jnp.zeros((a.shape[0], b.shape[1]), jnp.float32)
        if hasattr(jax.lax, "pcast"):
            # newer jax: type the replicated zeros as device-varying for
            # the VMA checker; old jax has no VMA system (nothing to cast)
            acc = jax.lax.pcast(acc, ("p", "q"), to="varying")
        (_, _, acc), _ = jax.lax.scan(step, (a, b, acc), None, length=T)
        return acc.astype(a_blk.dtype if dtype is None else dtype)

    fn = shard_map(body, mesh=mesh,
                   in_specs=(P("p", "q"), P("p", "q")),
                   out_specs=P("p", "q"))
    return jax.jit(fn)(A, B)


def distributed_gemm_allgather(A, B, mesh=None, dtype=None):
    """C = A @ B with row/col panel all_gathers + one local dot.

    C[p,q] = (gather_q A[p,:]) @ (gather_p B[:,q]) — two collectives total;
    XLA overlaps the gathers with the dot's first steps.
    """
    jax = _jax()
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P
    from .compat import shard_map

    if mesh is None:
        mesh = make_mesh()

    def body(a_blk, b_blk):
        a_row = jax.lax.all_gather(a_blk, "q", axis=1, tiled=True)
        b_col = jax.lax.all_gather(b_blk, "p", axis=0, tiled=True)
        out = jnp.dot(a_row, b_col, preferred_element_type=jnp.float32)
        return out.astype(a_blk.dtype if dtype is None else dtype)

    fn = shard_map(body, mesh=mesh,
                   in_specs=(P("p", "q"), P("p", "q")),
                   out_specs=P("p", "q"))
    return jax.jit(fn)(A, B)


def distributed_potrf(A, mesh=None, block: Optional[int] = None):
    """Blocked right-looking Cholesky (lower) over the mesh.

    Layout: A is ("p", "q")-sharded. Each outer step k:
      1. the owner block row factors the diagonal block (replicated cholesky
         of a small gathered block — the panel),
      2. panel broadcast = all_gather along the mesh axes (the multicast
         tree of the reference, ridden by the torus),
      3. trailing update A22 -= L21 L21^T runs fully sharded (MXU + psum).

    The per-k step is one jitted shard_map program; the k loop stays on host
    exactly like the reference's task DAG unrolls over k. Returns the lower
    Cholesky factor with the strict upper triangle zeroed.
    """
    jax = _jax()
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P
    from .compat import shard_map

    if mesh is None:
        mesh = make_mesh()
    n = A.shape[0]
    nb = block or max(A.shape[0] // (mesh.devices.shape[0] * 4), 128)
    nb = min(nb, n)

    sharding = jax.sharding.NamedSharding(mesh, P("p", "q"))
    A = jax.device_put(A, sharding)

    @partial(jax.jit, static_argnames=("nb",))
    def step(A, k, nb: int):
        # panel column [*, k:k+nb] is small (n x nb); k is a traced scalar so
        # one executable serves every outer iteration
        panel = jax.lax.dynamic_slice(A, (0, k), (n, nb))
        akk = jax.lax.dynamic_slice(panel, (k, 0), (nb, nb))
        lkk = jnp.linalg.cholesky(akk)
        l21 = jax.scipy.linalg.solve_triangular(lkk, panel.T, lower=True).T
        rows = jnp.arange(n)[:, None]
        l21 = jnp.where(rows >= k + nb, l21, 0.0)   # only rows below the block
        newpanel = jax.lax.dynamic_update_slice(l21, lkk, (k, 0))
        A = jax.lax.dynamic_update_slice(A, newpanel, (0, k))
        # trailing update: A -= l21 @ l21^T restricted to the trailing block
        upd = jnp.dot(l21, l21.T, preferred_element_type=jnp.float32).astype(A.dtype)
        cols = jnp.arange(n)[None, :]
        mask = (rows >= k + nb) & (cols >= k + nb)
        A = A - jnp.where(mask, upd, 0.0)
        return A

    nsteps = n // nb
    for i in range(nsteps):
        A = step(A, i * nb, nb)
    tail = n - nsteps * nb
    if tail:
        A = A.at[nsteps * nb:, nsteps * nb:].set(
            jnp.linalg.cholesky(A[nsteps * nb:, nsteps * nb:]))
    return jnp.tril(A)


def training_step(A, B, C, mesh=None):
    """One flagship 'step': C += A@B then Cholesky-factor a diagonal block.

    This is the driver-facing composite (the framework's unit of useful work:
    the GEMM+POTRF mix of the headline benchmarks) expressed fully SPMD.
    """
    jax = _jax()
    import jax.numpy as jnp

    C2 = distributed_gemm_allgather(A, B, mesh)
    C2 = C + C2
    # SPD-ify the result then factor: exercises cholesky + triangular solves
    sym = C2 @ C2.T / C2.shape[0] + jnp.eye(C2.shape[0], dtype=C2.dtype) * 2.0
    L = jnp.linalg.cholesky(sym)
    return C2, L
