"""Multi-controller SPMD: one GLOBAL device mesh spanning OS processes.

The true multi-host shape (the reference's mpirun-over-NCCL/MPI scale-out,
SURVEY §2.3/§2.8): each host runs ONE controller process that owns its
local chips; ``jax.distributed.initialize`` joins them so `jax.devices()`
is the GLOBAL device list, a `Mesh` spans every host, and XLA collectives
inside `shard_map`/`pjit` cross the host boundary on ICI/DCN (Gloo on the
CPU rehearsal backend) — no framework-level message passing at all.

This module is the thin layer that makes the shape usable and testable:

* :func:`init_multihost` — controller bring-up (coordinator rendezvous),
  env-driven so the same script runs under any launcher;
* :func:`global_mesh` — a named mesh over ALL processes' devices;
* :func:`host_local_to_global` — per-host shards assembled into one global
  array (`jax.make_array_from_process_local_data`), the input-feeding
  idiom (each host contributes its local batch);
* :func:`run_multicontroller` — N real controller processes on localhost
  with virtual CPU devices, for tests/rehearsal (the mpirun stand-in).

Every `parallel/` building block (train steps, ring attention, MoE,
pipeline) is mesh-agnostic: handed a global mesh from here, the SAME
compiled program scales from one chip to a pod.
"""

from __future__ import annotations

import os
from typing import Any, Callable, List, Optional, Sequence, Tuple

ENV_COORD = "PARSEC_TPU_COORDINATOR"
ENV_PROC = "PARSEC_TPU_PROCESS_ID"
ENV_NPROC = "PARSEC_TPU_NUM_PROCESSES"


def cpu_collectives_available() -> bool:
    """True when the installed jax can run MULTIPROCESS computations on
    the CPU rehearsal backend (a cross-process collectives implementation
    — Gloo — is wired into the CPU client). Without it, any multi-
    controller CPU job dies with "Multiprocess computations aren't
    implemented on the CPU backend": an environment limit, not a runtime
    bug, so tests skip on it instead of failing."""
    try:
        import jax
        from jax._src.lib import xla_extension as xe
        if not hasattr(xe, "make_gloo_tcp_collectives"):
            return False
        return _cpu_collectives_flag(jax) is not None
    except Exception:  # noqa: BLE001 - any probe failure = unavailable
        return False


def _cpu_collectives_flag(jax):
    """Current value of the CPU-collectives config flag, or None when the
    installed jax has no such flag. Registered config options are not
    always exposed as ``jax.config.<name>`` attributes (0.4.x keeps them
    in the holder registry), so probe both."""
    name = "jax_cpu_collectives_implementation"
    val = getattr(jax.config, name, None)
    if val is not None:
        return val
    holders = getattr(jax.config, "_value_holders", None) or {}
    if name in holders:
        try:
            return holders[name].value or "none"
        except Exception:  # noqa: BLE001
            return "none"
    return None


def _enable_cpu_collectives() -> None:
    """Multi-controller on the CPU rehearsal backend needs a collectives
    implementation compiled into the CPU client (the default is none —
    jax then refuses multiprocess computations outright). Select Gloo
    BEFORE the backend initializes; a no-op when unsupported or when the
    user already chose one (e.g. mpi via JAX_CPU_COLLECTIVES_*)."""
    import jax
    try:
        if _cpu_collectives_flag(jax) in (None, "none"):
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except Exception:  # noqa: BLE001 - older/newer jax: leave the default
        pass


def init_multihost(coordinator: Optional[str] = None,
                   num_processes: Optional[int] = None,
                   process_id: Optional[int] = None) -> int:
    """Join this controller to the job (env fallbacks: PARSEC_TPU_
    COORDINATOR / PROCESS_ID / NUM_PROCESSES). Returns the process id.
    Call BEFORE any other jax API touches the backend."""
    import jax
    coordinator = coordinator or os.environ.get(ENV_COORD)
    num_processes = int(num_processes if num_processes is not None
                        else os.environ.get(ENV_NPROC, "1"))
    process_id = int(process_id if process_id is not None
                     else os.environ.get(ENV_PROC, "0"))
    if num_processes > 1:
        plats = str(getattr(jax.config, "jax_platforms", "") or "")
        if plats.startswith("cpu") or os.environ.get("PARSEC_TPU_FORCE_CPU"):
            _enable_cpu_collectives()
        jax.distributed.initialize(coordinator_address=coordinator,
                                   num_processes=num_processes,
                                   process_id=process_id)
    return process_id


def global_mesh(axis_names: Sequence[str],
                shape: Optional[Sequence[int]] = None):
    """A mesh over the GLOBAL device list (every process's chips). With no
    ``shape``, one axis spans all devices; otherwise reshape to ``shape``
    (must multiply to the global device count)."""
    import numpy as np
    import jax
    from jax.sharding import Mesh
    devs = np.array(jax.devices())
    if shape is None:
        shape = (devs.size,) if len(axis_names) == 1 else None
    if shape is None or int(np.prod(shape)) != devs.size:
        raise ValueError(f"mesh shape {shape} != {devs.size} global devices")
    return Mesh(devs.reshape(tuple(shape)), tuple(axis_names))


def host_local_to_global(mesh, pspec, host_data):
    """Assemble per-host data into one global sharded array: every process
    passes ITS slice of the global batch (equal leading-dim shares in
    process order), and the result is addressable wherever sharding says.
    The multi-host input pipeline idiom."""
    import jax
    from jax.sharding import NamedSharding
    return jax.make_array_from_process_local_data(
        NamedSharding(mesh, pspec), host_data)


def fetch_replicated(x):
    """Host value of a replicated/global array on every process
    (process-local addressable shards suffice for replicated outputs)."""
    import numpy as np
    import jax
    shard = x.addressable_shards[0]
    return np.asarray(jax.device_get(shard.data))


# ---------------------------------------------------------------- launcher

def run_multicontroller(nprocs: int, script: str,
                        devices_per_proc: int = 4,
                        timeout: float = 240.0,
                        extra_env: Optional[dict] = None) -> List[str]:
    """Run ``script`` as N controller processes on localhost, each with
    ``devices_per_proc`` virtual CPU devices, joined into ONE jax job
    (the mpirun stand-in for tests; ``nprocs=1`` runs plain single-
    controller with the same env plumbing). Returns each stdout.

    Process management mirrors :mod:`parsec_tpu.launch`: one JOB-wide
    deadline (a hung collective must not serialize N full timeouts),
    cleanup in a ``finally`` reaching whole process GROUPS (controllers
    spawn their own children)."""
    import subprocess
    import sys
    import time

    from ..comm.tcp import _free_port
    from ..launch import _kill_group

    coord = f"127.0.0.1:{_free_port()}"
    procs = []
    for pid in range(nprocs):
        env = dict(os.environ)
        env.pop("JAX_PLATFORMS", None)
        env[ENV_COORD] = coord
        env[ENV_PROC] = str(pid)
        env[ENV_NPROC] = str(nprocs)
        env["PARSEC_TPU_FORCE_CPU"] = "1"
        # replace (not append after) any inherited device-count flag: the
        # caller may itself run under a virtual-device env, and relying on
        # last-flag-wins is fragile
        kept = [f for f in env.get("XLA_FLAGS", "").split()
                if not f.startswith("--xla_force_host_platform_device_count")]
        kept.append(f"--xla_force_host_platform_device_count="
                    f"{devices_per_proc}")
        env["XLA_FLAGS"] = " ".join(kept)
        if extra_env:
            env.update(extra_env)
        procs.append(subprocess.Popen(
            [sys.executable, script], env=env, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True, start_new_session=True))
    outs: List[str] = []
    failed: List[str] = []
    deadline = time.monotonic() + timeout
    try:
        for p in procs:
            try:
                out, _ = p.communicate(
                    timeout=max(0.1, deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                import signal
                _kill_group(p, signal.SIGKILL)
                out, _ = p.communicate()
                failed.append(f"controller timed out:\n{out[-1500:]}")
            outs.append(out or "")
            if p.returncode not in (0, None):
                failed.append(f"controller rc={p.returncode}:\n"
                              f"{(out or '')[-1500:]}")
    finally:
        import signal
        for p in procs:
            if p.poll() is None:
                _kill_group(p, signal.SIGKILL)
    if failed:
        # EVERY failing controller's tail rides along: the root cause
        # (e.g. a collectives-layer abort) often lives in the peer that
        # died first, not the one that reported first
        raise RuntimeError("\n---\n".join(failed))
    return outs
