"""Pipeline parallelism: GPipe-style microbatch streaming over a mesh axis.

Each device owns ONE stage's parameters (stage-major pytrees sharded over
``pp``); microbatches enter stage 0, activations hop one neighbor per tick
via ``lax.ppermute`` (the ICI ring), and after the P-1 fill ticks every
device computes every tick — the classic (M + P - 1)-tick GPipe schedule
expressed as one ``lax.scan`` inside ``shard_map``. The task runtime
expresses the same pattern as cross-rank chain deps (examples/ex03); this
is the compiler-scheduled, jittable form.
"""

from __future__ import annotations

import functools
from typing import Callable, Optional, Tuple

import numpy as np


def make_pp_mesh(n_devices: Optional[int] = None):
    from .spmd import make_1d_mesh
    return make_1d_mesh("pp", n_devices)


def init_pipeline_params(seed: int, n_stages: int, d: int,
                         dtype=np.float32):
    """Stage-major weights: one (W, b) per stage, leading axis = stage."""
    rng = np.random.default_rng(seed)
    s = np.sqrt(1.0 / d)
    return {
        "w": (rng.standard_normal((n_stages, d, d)) * s).astype(dtype),
        "b": np.zeros((n_stages, d), dtype),
    }


def stage_apply(w, b, x):
    """One pipeline stage: x -> gelu(x W + b) + x."""
    import jax
    return x + jax.nn.gelu(x @ w + b)


def reference_forward(params, x):
    """Sequential application of all stages (the single-device truth)."""
    import jax.numpy as jnp
    out = jnp.asarray(x)
    for i in range(params["w"].shape[0]):
        out = stage_apply(jnp.asarray(params["w"][i]),
                          jnp.asarray(params["b"][i]), out)
    return out


def _mlp_stage(sp, x):
    """The simple-MLP stage as a stage-pytree fn (the original pipeline)."""
    return stage_apply(sp["w"], sp["b"], x)


@functools.lru_cache(maxsize=None)
def _pipe_stages_call(mesh, n_micro: int, stage_fn: Callable,
                      replicate_out: bool = True):
    """The (M + P - 1)-tick GPipe schedule for an ARBITRARY stage pytree
    (leading axis = stage) and stage function
    ``stage_fn(stage_params, act) -> act`` — e.g. a group of transformer
    blocks. ``stage_fn`` must be jit-traceable and shape-preserving.
    Returns a ``run(sp, xs)`` whose jitted shard_map program is built ONCE
    per stage-pytree structure (jax's own trace cache handles shapes)."""
    import jax
    import jax.numpy as jnp
    from .compat import shard_map
    from jax.sharding import PartitionSpec as P

    axis = mesh.axis_names[0]
    nP = mesh.devices.size
    perm = [(i, (i + 1) % nP) for i in range(nP)]

    def local(sp, xs):
        idx = jax.lax.axis_index(axis)
        p0 = jax.tree_util.tree_map(lambda l: l[0], sp)   # my stage's slice
        # derive the zero bubble from a device-varying leaf so the scan
        # carry is varying from step 0 (manual-axes typing)
        zv = jax.tree_util.tree_leaves(p0)[0].ravel()[0] * 0.0
        act = jnp.zeros(xs.shape[1:], xs.dtype) + zv   # the in-flight bubble
        out = jnp.zeros_like(xs) + zv       # filled on the LAST stage

        def tick(carry, t):
            act, out = carry
            # stage 0 ingests microbatch t (while t < n_micro)
            feed = jnp.where(t < n_micro, 1.0, 0.0).astype(xs.dtype)
            mb = xs[jnp.minimum(t, n_micro - 1)]
            act = jnp.where(idx == 0, feed * mb, act)
            act = stage_fn(p0, act)
            # the LAST stage retires microbatch t-(P-1)
            done = t - (nP - 1)
            is_out = jnp.logical_and(idx == nP - 1, done >= 0)
            slot = jnp.maximum(done, 0)
            out = jnp.where(is_out, out.at[slot].set(act), out)
            act = jax.lax.ppermute(act, axis, perm)
            return (act, out), None

        (act, out), _ = jax.lax.scan(tick, (act, out),
                                     jnp.arange(n_micro + nP - 1))
        if replicate_out:
            # outputs live on the last stage only: everyone else holds
            # zeros, one psum replicates them. O(P·B·S·D) redundant ICI
            # traffic — acceptable for validation shapes, NOT at LM scale;
            # pass replicate_out=False to keep them resident where the
            # last stage computed them
            return jax.lax.psum(jnp.where(idx == nP - 1, out, 0.0), axis)
        return out          # stage-local: only the last stage's block is real

    def spec_of(leaf):
        return P(axis, *([None] * (leaf.ndim - 1)))

    jitted = {}     # one compiled wrapper per stage-pytree structure

    def run(sp, xs):
        key = (jax.tree_util.tree_structure(sp),
               tuple(l.ndim for l in jax.tree_util.tree_leaves(sp)))
        fn = jitted.get(key)
        if fn is None:
            in_specs = (jax.tree_util.tree_map(spec_of, sp), P())
            out_spec = P() if replicate_out else P(axis)
            fn = jax.jit(shard_map(local, mesh=mesh, in_specs=in_specs,
                                   out_specs=out_spec))
            jitted[key] = fn
        return fn(sp, xs)

    return run


def pipeline_forward_stages(stage_params, x, stage_fn, mesh=None,
                            n_micro: Optional[int] = None,
                            replicate_out: bool = True):
    """GPipe over an arbitrary stage pytree: every leaf of
    ``stage_params`` has leading axis P (stage-major); device i runs
    ``stage_fn(stage_i_params, act)``. ``x``: (n_micro, B, ...)
    microbatches; returns the same shape. ``stage_fn`` must be a STABLE
    function object (module-level or cached) — it keys the compiled
    program cache.

    ``replicate_out=True`` (default) replicates the result to every stage
    with a psum — O(P·activations) ICI traffic, fine for validation
    shapes. ``replicate_out=False`` keeps the result SHARDED over the
    stage axis (only the last stage's shard is live), so downstream
    consumers (the LM head) read it where it was produced instead of
    paying a full replication every forward."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = mesh if mesh is not None else make_pp_mesh()
    axis = mesh.axis_names[0]
    nP = mesh.devices.size
    leaves = jax.tree_util.tree_leaves(stage_params)
    assert leaves and all(l.shape[0] == nP for l in leaves), \
        f"every stage-params leaf needs leading axis {nP} (the stage axis)"
    xs = np.asarray(x) if not hasattr(x, "dtype") else x
    m = int(n_micro) if n_micro is not None else xs.shape[0]
    assert m <= xs.shape[0], \
        f"n_micro={m} exceeds the {xs.shape[0]} provided microbatches"
    xs = xs[:m]        # honor the (n_micro, B, ...) return contract exactly
    run = _pipe_stages_call(mesh, m, stage_fn, replicate_out)
    sp = jax.tree_util.tree_map(
        lambda l: jax.device_put(
            l, NamedSharding(mesh, P(axis, *([None] * (l.ndim - 1))))),
        stage_params)
    xd = jax.device_put(xs, NamedSharding(mesh, P()))
    res = run(sp, xd)
    if not replicate_out:
        # global shape (P·m, B, ...): block s is stage s's residue; only
        # the LAST block carries the pipeline's output. The slice is lazy
        # over the sharded array — it addresses the last stage's shard
        # without replicating the others
        res = res[(nP - 1) * m:]
    return res


def pipeline_forward(params, x, mesh=None, n_micro: Optional[int] = None):
    """Run (n_micro, B, d) microbatches through the P-stage MLP pipeline
    (the :func:`pipeline_forward_stages` schedule with the simple-MLP
    stage). ``params['w']``: (P, d, d) — stage i's weights live on
    device i. Returns (n_micro, B, d), matching :func:`reference_forward`
    applied per microbatch within float32 tolerance."""
    mesh = mesh if mesh is not None else make_pp_mesh()
    nP = mesh.devices.size
    assert params["w"].shape[0] == nP, \
        f"{params['w'].shape[0]} stages need a {params['w'].shape[0]}-device" \
        f" mesh (have {nP})"
    return pipeline_forward_stages(
        {"w": params["w"], "b": params["b"]}, x, _mlp_stage, mesh=mesh,
        n_micro=n_micro)
