"""Pipeline parallelism: GPipe-style microbatch streaming over a mesh axis.

Each device owns ONE stage's parameters (stage-major pytrees sharded over
``pp``); microbatches enter stage 0, activations hop one neighbor per tick
via ``lax.ppermute`` (the ICI ring), and after the P-1 fill ticks every
device computes every tick — the classic (M + P - 1)-tick GPipe schedule
expressed as one ``lax.scan`` inside ``shard_map``. The task runtime
expresses the same pattern as cross-rank chain deps (examples/ex03); this
is the compiler-scheduled, jittable form.
"""

from __future__ import annotations

import functools
from typing import Callable, Optional, Tuple

import numpy as np


def make_pp_mesh(n_devices: Optional[int] = None):
    from .spmd import make_1d_mesh
    return make_1d_mesh("pp", n_devices)


def init_pipeline_params(seed: int, n_stages: int, d: int,
                         dtype=np.float32):
    """Stage-major weights: one (W, b) per stage, leading axis = stage."""
    rng = np.random.default_rng(seed)
    s = np.sqrt(1.0 / d)
    return {
        "w": (rng.standard_normal((n_stages, d, d)) * s).astype(dtype),
        "b": np.zeros((n_stages, d), dtype),
    }


def stage_apply(w, b, x):
    """One pipeline stage: x -> gelu(x W + b) + x."""
    import jax
    return x + jax.nn.gelu(x @ w + b)


def reference_forward(params, x):
    """Sequential application of all stages (the single-device truth)."""
    import jax.numpy as jnp
    out = jnp.asarray(x)
    for i in range(params["w"].shape[0]):
        out = stage_apply(jnp.asarray(params["w"][i]),
                          jnp.asarray(params["b"][i]), out)
    return out


@functools.lru_cache(maxsize=None)
def _pipe_call(mesh, n_micro: int):
    import jax
    import jax.numpy as jnp
    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    axis = mesh.axis_names[0]
    nP = mesh.devices.size
    perm = [(i, (i + 1) % nP) for i in range(nP)]

    def local(w, b, xs):
        # w: (1, d, d) this device's stage; xs: (n_micro, B, d) microbatches
        # (replicated input; stage 0 consumes them in order)
        idx = jax.lax.axis_index(axis)
        w0, b0 = w[0], b[0]
        # zero initials derived from the (device-varying) stage weights so
        # the scan carry is varying from step 0 (shard_map's manual-axes
        # type system requires carry-in == carry-out)
        zv = w0[0, 0] * 0.0
        act = jnp.zeros(xs.shape[1:], xs.dtype) + zv   # the in-flight bubble
        out = jnp.zeros_like(xs) + zv       # filled on the LAST stage

        def tick(carry, t):
            act, out = carry
            # stage 0 ingests microbatch t (while t < n_micro)
            feed = jnp.where(t < n_micro, 1.0, 0.0)
            mb = xs[jnp.minimum(t, n_micro - 1)]
            act = jnp.where(idx == 0, feed * mb, act)
            act = stage_apply(w0, b0, act)
            # the LAST stage retires microbatch t-(P-1)
            done = t - (nP - 1)
            is_out = jnp.logical_and(idx == nP - 1, done >= 0)
            slot = jnp.maximum(done, 0)
            out = jnp.where(is_out, out.at[slot].set(act), out)
            act = jax.lax.ppermute(act, axis, perm)
            return (act, out), None

        (act, out), _ = jax.lax.scan(tick, (act, out),
                                     jnp.arange(n_micro + nP - 1))
        # outputs live on the last stage only: everyone else holds zeros,
        # one psum replicates them (tiny shapes; fine for validation/driver)
        return jax.lax.psum(jnp.where(idx == nP - 1, out, 0.0), axis)

    return jax.jit(shard_map(
        local, mesh=mesh,
        in_specs=(P(axis, None, None), P(axis, None), P()),
        out_specs=P()))


def pipeline_forward(params, x, mesh=None, n_micro: Optional[int] = None):
    """Run (n_micro, B, d) microbatches through the P-stage pipeline.

    ``params['w']``: (P, d, d) — stage i's weights live on device i.
    Returns (n_micro, B, d), matching :func:`reference_forward` applied per
    microbatch within float32 tolerance.
    """
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = mesh if mesh is not None else make_pp_mesh()
    axis = mesh.axis_names[0]
    nP = mesh.devices.size
    assert params["w"].shape[0] == nP, \
        f"{params['w'].shape[0]} stages need a {params['w'].shape[0]}-device" \
        f" mesh (have {nP})"
    xs = np.asarray(x)
    m = n_micro if n_micro is not None else xs.shape[0]
    assert m <= xs.shape[0], \
        f"n_micro={m} exceeds the {xs.shape[0]} provided microbatches"
    xs = xs[:m]        # honor the (n_micro, B, d) return contract exactly
    fn = _pipe_call(mesh, m)
    wd = jax.device_put(params["w"], NamedSharding(mesh, P(axis, None, None)))
    bd = jax.device_put(params["b"], NamedSharding(mesh, P(axis, None)))
    xd = jax.device_put(xs, NamedSharding(mesh, P()))
    return fn(wd, bd, xd)
