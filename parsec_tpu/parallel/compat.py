"""jax API compatibility shims for the parallel layer.

``shard_map`` moved from ``jax.experimental.shard_map`` to the ``jax``
top level across jax releases, and its replication-checker kwarg was
renamed ``check_rep`` -> ``check_vma``. The two changes did NOT land in
the same release, so the kwarg spelling is probed by TypeError rather
than inferred from where the symbol lives. Import ``shard_map`` from
here so every SPMD module works on any of the three vintages.
"""

try:                                     # newer jax: top-level
    from jax import shard_map as _sm     # type: ignore[attr-defined]
    _EXPERIMENTAL = False
except ImportError:                      # jax 0.4/0.5: experimental
    from jax.experimental.shard_map import shard_map as _sm
    _EXPERIMENTAL = True


def shard_map(f, **kwargs):
    """Version-tolerant ``shard_map``. Callers use the current kwarg
    spelling (``check_vma``); the shim translates for older signatures.

    On the experimental vintage the checker additionally defaults OFF:
    its shard_map transpose under ``check_rep=True`` produces symbolic
    ``Zero`` tangents that crash ``psum`` gradients (the
    upstream-documented workaround; newer jax needs neither)."""
    if _EXPERIMENTAL:
        if "check_vma" in kwargs:
            kwargs["check_rep"] = kwargs.pop("check_vma")
        kwargs.setdefault("check_rep", False)
        return _sm(f, **kwargs)
    try:
        return _sm(f, **kwargs)
    except TypeError:
        # transition-window jax: top-level symbol, pre-rename signature
        if "check_vma" in kwargs:
            kw = dict(kwargs)
            kw["check_rep"] = kw.pop("check_vma")
            return _sm(f, **kw)
        raise
