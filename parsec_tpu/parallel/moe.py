"""Expert parallelism: a top-1 routed MoE layer over a mesh axis.

The GShard/Switch dispatch pattern, TPU-native: tokens are data-sharded
over ``ep``; a router scores every local token, tokens are packed into
fixed-capacity per-expert buffers (one-hot dispatch einsum — static
shapes, MXU-friendly), ``lax.all_to_all`` ships each expert's slice to the
device that OWNS that expert, the expert MLPs run local and dense, and a
second all_to_all brings results home where the combine einsum unpacks
them. Capacity >= local tokens means no drops, which makes the layer
bit-comparable to its dense equivalent (the tests' invariant).
"""

from __future__ import annotations

import functools
from typing import Optional

import numpy as np


def make_ep_mesh(n_devices: Optional[int] = None):
    from .spmd import make_1d_mesh
    return make_1d_mesh("ep", n_devices)


def init_moe_params(seed: int, n_experts: int, d: int, d_ff: int,
                    dtype=np.float32):
    """Router + per-expert 2-layer MLPs (expert-major leading axis)."""
    rng = np.random.default_rng(seed)

    def g(*shape, fan):
        return (rng.standard_normal(shape) / np.sqrt(fan)).astype(dtype)

    return {
        "router": g(d, n_experts, fan=d),
        "w1": g(n_experts, d, d_ff, fan=d),
        "w2": g(n_experts, d_ff, d, fan=d_ff),
    }


def _expert_mlp(w1, w2, x):
    import jax
    return jax.nn.gelu(x @ w1) @ w2


def dense_reference(params, x):
    """Every token through its routed expert, no parallelism (the truth)."""
    import jax
    import jax.numpy as jnp
    xt = jnp.asarray(x)
    T, D = xt.shape
    logits = xt @ params["router"]
    eid = jnp.argmax(logits, axis=-1)
    gate = jax.nn.softmax(logits, axis=-1)[jnp.arange(T), eid]
    E = params["w1"].shape[0]
    out = jnp.zeros_like(xt)
    for e in range(E):
        sel = (eid == e)[:, None]
        y = _expert_mlp(jnp.asarray(params["w1"][e]),
                        jnp.asarray(params["w2"][e]), xt)
        out = jnp.where(sel, y, out)
    return out * gate[:, None]


@functools.lru_cache(maxsize=None)
def _moe_call(mesh, capacity: int, experts_per_dev: int):
    import jax
    import jax.numpy as jnp
    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    axis = mesh.axis_names[0]
    nP = mesh.devices.size

    def local(router, w1, w2, xb):
        # xb: (T_loc, D) this device's tokens; w1/w2: this device's experts
        T, D = xb.shape
        E = nP * experts_per_dev
        logits = xb @ router
        eid = jnp.argmax(logits, axis=-1)
        gate = jax.nn.softmax(logits, axis=-1)[jnp.arange(T), eid]
        # dispatch tensor (T, E, C): token t -> slot (e, c) in its expert's
        # fixed-capacity buffer (GShard one-hot dispatch, static shapes)
        onehot = jax.nn.one_hot(eid, E, dtype=xb.dtype)           # (T, E)
        pos = (jnp.cumsum(onehot, axis=0) - 1.0) * onehot         # (T, E)
        keep = (pos < capacity).astype(xb.dtype)
        dispatch = (onehot * keep)[..., None] * jax.nn.one_hot(
            pos.astype(jnp.int32), capacity, dtype=xb.dtype)      # (T, E, C)
        # pack per global expert, grouped by owning device
        buf = jnp.einsum("td,tec->ecd", xb, dispatch)             # (E, C, D)
        buf = buf.reshape(nP, experts_per_dev, capacity, D)
        # ship slice [dst] to device dst; recv[s, e] = source s's tokens
        # for MY local expert e
        recv = jax.lax.all_to_all(buf, axis, split_axis=0,
                                  concat_axis=0, tiled=True)
        work = jnp.moveaxis(recv, 0, 1).reshape(
            experts_per_dev, nP * capacity, D)
        done = jnp.stack([_expert_mlp(w1[e], w2[e], work[e])
                          for e in range(experts_per_dev)])
        done = done.reshape(experts_per_dev, nP, capacity, D)
        # return trip: slice [src] goes home to device src; ret[d, e] =
        # device d's local expert e results for MY tokens — which is
        # exactly the (global expert, capacity) layout dispatch used
        ret = jax.lax.all_to_all(jnp.moveaxis(done, 1, 0), axis,
                                 split_axis=0, concat_axis=0, tiled=True)
        y = jnp.einsum("ecd,tec->td", ret.reshape(E, capacity, D), dispatch)
        return y * gate[:, None]

    return jax.jit(shard_map(
        local, mesh=mesh,
        in_specs=(P(), P(axis, None, None), P(axis, None, None),
                  P(axis, None)),
        out_specs=P(axis, None)))


def moe_forward(params, x, mesh=None, capacity: Optional[int] = None):
    """Expert-parallel forward of the routed MoE layer.

    ``x``: (tokens, d) global; tokens must divide the mesh size. Experts
    must divide the mesh size (``experts_per_dev`` each). With capacity >=
    local tokens (the default) no token is dropped and the result matches
    :func:`dense_reference`.
    """
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = mesh if mesh is not None else make_ep_mesh()
    axis = mesh.axis_names[0]
    nP = mesh.devices.size
    T, D = x.shape
    E = params["w1"].shape[0]
    assert T % nP == 0 and E % nP == 0
    cap = capacity if capacity is not None else (T // nP)
    fn = _moe_call(mesh, cap, E // nP)
    ns = lambda spec: NamedSharding(mesh, spec)
    rd = jax.device_put(params["router"], ns(P()))
    w1 = jax.device_put(params["w1"], ns(P(axis, None, None)))
    w2 = jax.device_put(params["w2"], ns(P(axis, None, None)))
    xd = jax.device_put(np.asarray(x), ns(P(axis, None)))
    return fn(rd, w1, w2, xd)
