"""Expert parallelism: a top-k routed MoE layer over a mesh axis.

The GShard/Switch dispatch pattern, TPU-native: tokens are data-sharded
over ``ep``; a router scores every local token, the top-k experts per token
are packed into fixed-capacity per-expert buffers (one-hot dispatch einsum
— static shapes, MXU-friendly), ``lax.all_to_all`` ships each expert's
slice to the device that OWNS that expert, the expert MLPs run local and
dense, and a second all_to_all brings results home where the combine
einsum unpacks and gate-weights them. Capacity >= local tokens means no
drops, which makes the layer bit-comparable to its dense equivalent (the
tests' invariant); tighter capacities drop overflow tokens with the drop
COUNT reported, and the Switch-style auxiliary load-balancing loss is
computed over the global batch (psum across the mesh).

Routing follows the standard recipes: top-1 gates with the raw router
probability (Switch); top-k>=2 renormalizes the k gates to sum to one
(GShard/Mixtral). Slot assignment is choice-major — every token's first
choice claims buffer slots before any second choice — so under pressure
drops hit lower-priority routes first, as in GShard.
"""

from __future__ import annotations

import functools
import math
from typing import Optional, Tuple

import numpy as np


def make_ep_mesh(n_devices: Optional[int] = None):
    from .spmd import make_1d_mesh
    return make_1d_mesh("ep", n_devices)


def init_moe_params(seed: int, n_experts: int, d: int, d_ff: int,
                    dtype=np.float32):
    """Router + per-expert 2-layer MLPs (expert-major leading axis)."""
    rng = np.random.default_rng(seed)

    def g(*shape, fan):
        return (rng.standard_normal(shape) / np.sqrt(fan)).astype(dtype)

    return {
        "router": g(d, n_experts, fan=d),
        "w1": g(n_experts, d, d_ff, fan=d),
        "w2": g(n_experts, d_ff, d, fan=d_ff),
    }


def _expert_mlp(w1, w2, x):
    import jax
    return jax.nn.gelu(x @ w1) @ w2


def _topk_gates(probs, k: int):
    """(gates, expert ids), both (T, k): raw top-1 prob for k=1 (Switch),
    renormalized over the k winners for k>=2 (GShard/Mixtral)."""
    import jax
    import jax.numpy as jnp
    gate_k, eid_k = jax.lax.top_k(probs, k)
    if k > 1:
        gate_k = gate_k / jnp.maximum(gate_k.sum(-1, keepdims=True), 1e-9)
    return gate_k, eid_k


def dense_reference(params, x, k: int = 1):
    """Every token through its top-k routed experts, no parallelism (the
    truth the expert-parallel layer must match when nothing is dropped)."""
    import jax.numpy as jnp
    xt = jnp.asarray(x)
    logits = xt @ params["router"]
    import jax
    gate_k, eid_k = _topk_gates(jax.nn.softmax(logits, axis=-1), k)
    E = params["w1"].shape[0]
    out = jnp.zeros_like(xt)
    for e in range(E):
        y = _expert_mlp(jnp.asarray(params["w1"][e]),
                        jnp.asarray(params["w2"][e]), xt)
        w = (gate_k * (eid_k == e)).sum(-1)          # this expert's gate
        out = out + y * w[:, None]
    return out


@functools.lru_cache(maxsize=None)
def _moe_call(mesh, capacity: int, experts_per_dev: int, k: int):
    import jax
    import jax.numpy as jnp
    from .compat import shard_map
    from jax.sharding import PartitionSpec as P

    axis = mesh.axis_names[0]
    nP = mesh.devices.size

    def local(router, w1, w2, xb):
        # xb: (T_loc, D) this device's tokens; w1/w2: this device's experts
        T, D = xb.shape
        E = nP * experts_per_dev
        logits = xb @ router
        probs = jax.nn.softmax(logits, axis=-1)
        gate_k, eid_k = _topk_gates(probs, k)                  # (T, k)
        # choice-major slot assignment: flatten (k, T) so every token's
        # 1st choice claims capacity before any 2nd choice (GShard
        # priority); cumsum over that order numbers the slots
        oh = jax.nn.one_hot(eid_k, E, dtype=xb.dtype)          # (T, k, E)
        ohf = jnp.moveaxis(oh, 1, 0).reshape(k * T, E)         # (kT, E)
        posf = (jnp.cumsum(ohf, axis=0) - 1.0) * ohf
        keepf = ohf * (posf < capacity).astype(xb.dtype)
        dropped = ohf.sum() - keepf.sum()                      # local drops
        dispf = keepf[..., None] * jax.nn.one_hot(
            posf.astype(jnp.int32), capacity, dtype=xb.dtype)  # (kT, E, C)
        disp = jnp.moveaxis(dispf.reshape(k, T, E, capacity), 0, 1)
        dispatch = disp.sum(1)                   # (T, E, C) raw packing
        combine = jnp.einsum("tkec,tk->tec", disp, gate_k)   # gate-weighted
        # pack per global expert, grouped by owning device
        buf = jnp.einsum("td,tec->ecd", xb, dispatch)          # (E, C, D)
        buf = buf.reshape(nP, experts_per_dev, capacity, D)
        # ship slice [dst] to device dst; recv[s, e] = source s's tokens
        # for MY local expert e
        recv = jax.lax.all_to_all(buf, axis, split_axis=0,
                                  concat_axis=0, tiled=True)
        work = jnp.moveaxis(recv, 0, 1).reshape(
            experts_per_dev, nP * capacity, D)
        done = jnp.stack([_expert_mlp(w1[e], w2[e], work[e])
                          for e in range(experts_per_dev)])
        done = done.reshape(experts_per_dev, nP, capacity, D)
        # return trip: slice [src] goes home to device src; ret[d, e] =
        # device d's local expert e results for MY tokens — which is
        # exactly the (global expert, capacity) layout dispatch used
        ret = jax.lax.all_to_all(jnp.moveaxis(done, 1, 0), axis,
                                 split_axis=0, concat_axis=0, tiled=True)
        y = jnp.einsum("ecd,tec->td", ret.reshape(E, capacity, D), combine)
        # Switch aux load-balancing loss over the GLOBAL batch:
        # E * sum_e f_e * p_e, f_e = fraction of tokens whose TOP-1 is e,
        # p_e = mean router prob for e (both psum-averaged over the mesh)
        top1 = jax.nn.one_hot(eid_k[:, 0], E, dtype=jnp.float32)
        f = jax.lax.psum(top1.sum(0), axis) / (T * nP)
        p = jax.lax.psum(probs.astype(jnp.float32).sum(0), axis) / (T * nP)
        aux = E * jnp.sum(f * p)
        return y, aux, jax.lax.psum(dropped, axis)

    return jax.jit(shard_map(
        local, mesh=mesh,
        in_specs=(P(), P(axis, None, None), P(axis, None, None),
                  P(axis, None)),
        out_specs=(P(axis, None), P(), P())))


def moe_forward(params, x, mesh=None, capacity: Optional[int] = None,
                k: int = 1, capacity_factor: Optional[float] = None,
                return_aux: bool = False):
    """Expert-parallel forward of the top-k routed MoE layer.

    ``x``: (tokens, d) global; tokens must divide the mesh size, experts
    must divide the mesh size (``experts_per_dev`` each), ``k`` <= experts.
    Per-expert buffer capacity, in priority order:

    * ``capacity`` — explicit slots per (expert, source device);
    * ``capacity_factor`` — ``ceil(cf * k * T_loc / E)`` slots, the GShard
      convention (cf=1.0 is "fair share", cf>1 headroom);
    * default — ``T_loc`` slots: no token can be dropped, and the result
      matches :func:`dense_reference` exactly.

    ``return_aux=True`` also returns ``{"aux_loss", "dropped"}`` — the
    Switch load-balancing loss over the global batch (add
    ``lambda * aux_loss`` to the training objective) and the global count
    of routed (token, choice) pairs that overflowed capacity.
    """
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = mesh if mesh is not None else make_ep_mesh()
    axis = mesh.axis_names[0]
    nP = mesh.devices.size
    T, D = x.shape
    E = params["w1"].shape[0]
    assert T % nP == 0 and E % nP == 0
    assert 1 <= k <= E, f"top-{k} routing needs k in [1, {E}]"
    t_loc = T // nP
    if capacity is not None:
        cap = int(capacity)
    elif capacity_factor is not None:
        cap = max(1, math.ceil(capacity_factor * k * t_loc / E))
    else:
        cap = t_loc
    fn = _moe_call(mesh, cap, E // nP, k)
    import jax.core
    leaves = [params["router"], params["w1"], params["w2"], x]
    if any(isinstance(v, jax.core.Tracer) for v in leaves):
        # under an outer jit/grad trace: no host-side placement — the
        # shard_map in_specs become sharding constraints and gradients
        # flow through dispatch/combine (the MoE-LM training path)
        y, aux, dropped = fn(params["router"], params["w1"],
                             params["w2"], x)
    else:
        ns = lambda spec: NamedSharding(mesh, spec)
        rd = jax.device_put(params["router"], ns(P()))
        w1 = jax.device_put(params["w1"], ns(P(axis, None, None)))
        w2 = jax.device_put(params["w2"], ns(P(axis, None, None)))
        xd = jax.device_put(np.asarray(x), ns(P(axis, None)))
        y, aux, dropped = fn(rd, w1, w2, xd)
    if return_aux:
        return y, {"aux_loss": aux, "dropped": dropped}
    return y
