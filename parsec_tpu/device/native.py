"""Native device lane (ptdev): the Python half of L4-in-C.

``native/src/ptdev.cpp`` owns the device hot path — a per-device manager
thread draining a lock-free MPSC pending queue that the execution
engines feed STRAIGHT from their GIL-free release sweeps
(``ptdev_iface.h``), taking the GIL only to issue the asynchronous JAX
dispatch / ``device_put`` and to poll ``jax.Array.is_ready()`` (the
cudaEventQuery of device_gpu.c:2593), then landing completions back into
the engines through the GIL-free ``retire()`` entry. This module is
everything around it:

* **lifecycle** — one :class:`NativeDeviceLane` per (context, device),
  created lazily the first time a TPU-bodied pool prepares for the
  native execution lane and torn down at ``Context.fini``;
* **pool routing** — the manager calls ONE ``dispatch(pool, ids)`` /
  ``poll()`` pair; this module routes them to the per-pool closures the
  PTG compiler builds (input gather from the lane's slot array,
  version-checked stage-in through the C coherency table, async jitted
  dispatch, write-backs at completion);
* **counters** — ``PTDEV_STATS`` engagement accounting plus the C-side
  lane and coherency counters exported under ``ptdev.*`` through the
  unified registry (utils/counters.install_native_counters), so a
  silent fall-back to the interpreted device module is a CI failure.

The lane is the FAST path, not the only path: ``device/tpu.py``'s
kernel_scheduler stays as the interpreted route for DTD pools and any
pool the execution lane declines — but its residency/eviction POLICY now
also lives in the C coherency table (``CohTable``), so both paths share
one authoritative view of what is resident at which version.
"""

from __future__ import annotations

import atexit
import time
import weakref
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..utils import mca, output
from ..utils.counters import LaneStats

mca.register("device_native", True,
             "Drive TPU-bodied native-lane pools through the native "
             "device lane (native/src/ptdev.cpp): per-device async "
             "dispatch queues, event-based retirement into the engines, "
             "C-side coherency/zone accounting. Ineligible pools keep "
             "the interpreted device module (counted)", type=bool)
mca.register("device_native_poll_us", 100,
             "Manager-thread completion poll cadence while device work "
             "is in flight (microseconds)", type=int)

#: lane engagement accounting, the PTEXEC_STATS/PTCOMM_STATS template:
#: ``pools_engaged``/``tasks_engaged`` prove the lane carried device
#: bodies; ``pools_ineligible`` counts by-design declines (mca off,
#: distributed pools, DTD pools this PR); ``pools_fallback`` counts
#: eligible pools that still declined (native module missing) — the
#: silent-regression signal the ci.sh gate asserts is zero.
PTDEV_STATS = LaneStats(lanes_up=0, pools_engaged=0, tasks_engaged=0,
                        pools_fallback=0, pools_ineligible=0)

#: live lanes, for the process-wide ``ptdev.*`` counter samplers
_lanes: "weakref.WeakSet[NativeDeviceLane]" = weakref.WeakSet()


def _stop_abandoned_lanes() -> None:
    """atexit net: a lane whose context never fini'd must stop its
    manager thread BEFORE interpreter teardown — a C thread blocked in
    PyGILState_Ensure during finalization would hang the exit join."""
    for lane in list(_lanes):
        try:
            lane.clane.stop()
        except Exception:  # noqa: BLE001 — already down
            pass


atexit.register(_stop_abandoned_lanes)

#: C-side counters exported into the unified registry (ptdev.<name>);
#: the lane half comes from Lane.stats(), the coherency half from the
#: bound device's CohTable.stats()
DEV_COUNTER_KEYS = ("submitted", "dispatched", "retired",
                    "dispatch_batches", "overlap_hits", "late_submits",
                    "late_retires", "cb_errors", "inflight")
COH_COUNTER_KEYS = ("evictions", "pinned_skips", "coh_hits", "coh_misses",
                    "stage_in_bytes", "stage_out_bytes", "resident_bytes")


def dev_counter_sampler(key: str):
    """Sampler summing one C-side counter across every live lane (TTL-
    cached snapshot: one stats() call per lane per registry sweep)."""
    def sample():
        total = 0
        for lane in list(_lanes):
            try:
                total += lane.stats_cached()[key]
            except Exception:  # noqa: BLE001 - a torn-down lane samples 0
                pass
        return total
    return sample


def coh_counter_sampler(key: str):
    """Sampler summing one coherency-table counter across every device
    table attached to a live lane's device."""
    def sample():
        total = 0
        for lane in list(_lanes):
            try:
                st = lane.coh_stats_cached()
                if st is not None:
                    total += st[key]
            except Exception:  # noqa: BLE001
                pass
        return total
    return sample


def load_ptdev():
    from .. import native as native_mod
    return native_mod.load_ptdev()


def make_coh_table(budget: int):
    """A C-side coherency/residency table, or None when the native
    module is unavailable (the Python LRU stays the policy then)."""
    if not mca.get("device_native", True):
        return None
    mod = load_ptdev()
    if mod is None:
        return None
    try:
        return mod.CohTable(int(budget))
    except Exception as e:  # noqa: BLE001 — degrade to the Python LRU
        output.debug_verbose(1, "ptdev", f"CohTable unavailable: {e}")
        return None


class _PoolState:
    """One bound pool's dispatch/poll closures (built by the compiler)."""

    __slots__ = ("dispatch", "poll", "engine")

    def __init__(self, dispatch: Callable, poll: Callable, engine) -> None:
        self.dispatch = dispatch
        self.poll = poll
        self.engine = engine


class NativeDeviceLane:
    """One (context, device) native device lane: the C ``Lane`` object
    plus pool routing and lifecycle."""

    @staticmethod
    def available(ctx) -> Optional[str]:
        """None when the lane can engage, else the reason it cannot."""
        if not mca.get("device_native", True):
            return "disabled by --mca device_native 0"
        from ..core.task import DEV_TPU
        devs = ctx.devices.by_type(DEV_TPU)
        if not devs:
            return "no accelerator device registered"
        if load_ptdev() is None:
            return "native module unavailable"
        return None

    @classmethod
    def maybe_create(cls, ctx) -> Optional["NativeDeviceLane"]:
        reason = cls.available(ctx)
        if reason is not None:
            output.debug_verbose(2, "ptdev",
                                 f"device lane not engaged: {reason}")
            return None
        from ..core.task import DEV_TPU
        return cls(ctx, ctx.devices.by_type(DEV_TPU)[0])

    def __init__(self, ctx, device) -> None:
        self.ctx = ctx
        self.device = device          # the TPUDevice whose chip we drive
        self._mod = load_ptdev()
        self.clane = self._mod.Lane()
        self._pools: Dict[int, _PoolState] = {}
        self._next_pool = 1
        self._stats_cache: Tuple[float, Optional[dict]] = (0.0, None)
        self._coh_cache: Tuple[float, Optional[dict]] = (0.0, None)
        self.clane.start(self._dispatch, self._poll,
                         mca.get("device_native_poll_us", 100))
        self._up = True
        PTDEV_STATS["lanes_up"] += 1
        _lanes.add(self)
        # in-lane ring events (EV_DEV_*) land as `ptdev-w*` PBP streams
        # through the same bridge as the execution lanes
        ctx._ntrace_attach("ptdev", self.clane)
        output.debug_verbose(1, "ptdev",
                             f"native device lane up on {device.name}")

    # --------------------------------------------------------- pool routing
    def bind_pool(self, engine, dispatch: Callable, poll: Callable) -> int:
        """Route a pool's device tasks: ``engine`` provides the GIL-free
        retire entry (dev_retire_capsule); ``dispatch(ids)`` issues the
        async device work; ``poll()`` returns completed tids whose
        outputs have landed. Returns the lane-local pool id to pass to
        the engine's ``dev_bind``."""
        pid = self._next_pool
        self._next_pool += 1
        self.clane.bind_pool(pid, engine.dev_retire_capsule(), engine)
        self._pools[pid] = _PoolState(dispatch, poll, engine)
        return pid

    def unbind_pool(self, pool_id: int) -> None:
        self._pools.pop(pool_id, None)
        try:
            self.clane.unbind_pool(pool_id)
        except Exception:  # noqa: BLE001 — teardown races are benign
            pass

    def submit_capsule(self):
        return self.clane.submit_capsule()

    def failed(self) -> Optional[str]:
        """The message of the callback exception that poisoned the lane,
        or None. Drain loops surface it as the pool's error."""
        return self.clane.failed()

    # ------------------------------------------------ manager-thread hooks
    # Both run ON the manager thread with the GIL held; self._pools is
    # only mutated under the GIL (bind/unbind), so plain dict ops are
    # safe. A pool unbound between submit and dispatch just drops its
    # ids here (the C side counts unrouted retires as late_retires).
    def _dispatch(self, pool: int, ids: List[int]) -> int:
        st = self._pools.get(pool)
        if st is None:
            return 0
        return st.dispatch(ids)

    def _poll(self):
        done = []
        for pid, st in list(self._pools.items()):
            for tid in st.poll():
                done.append((pid, tid))
        return done

    # -------------------------------------------------------------- stats
    def stats_cached(self, ttl: float = 0.05) -> Dict[str, Any]:
        now = time.monotonic()
        stamp, snap = self._stats_cache
        if snap is None or now - stamp > ttl:
            snap = self.clane.stats()
            self._stats_cache = (now, snap)
        return snap

    def coh_stats_cached(self, ttl: float = 0.05) -> Optional[Dict[str, Any]]:
        tbl = getattr(self.device, "_ncoh", None)
        if tbl is None:
            return None
        now = time.monotonic()
        stamp, snap = self._coh_cache
        if snap is None or now - stamp > ttl:
            snap = tbl.stats()
            self._coh_cache = (now, snap)
        return snap

    # ------------------------------------------------------------ teardown
    def fini(self) -> None:
        if not self._up:
            return
        self._up = False
        # bounded wait for in-flight dispatches to retire: stopping with
        # work on the chip would strand the owning graphs undone. A
        # poisoned lane or one with no bound pools left can never drain
        # what remains (an unbound pool's completions are uncollectable
        # by design) — break immediately instead of stalling every
        # error-path teardown for the full deadline
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            if self.clane.failed() is not None or not self._pools:
                break
            s = self.clane.stats()
            if s["inflight"] == 0 and s["submitted"] == s["dispatched"] \
                    + s["late_submits"]:
                break
            time.sleep(1e-3)
        try:
            self.ctx._ntrace_detach(self.clane)
        except Exception:  # noqa: BLE001 — no bridge attached
            pass
        self.clane.stop()
        for pid in list(self._pools):
            self.unbind_pool(pid)
        output.debug_verbose(1, "ptdev",
                             f"native device lane down on "
                             f"{self.device.name}: {self.clane.stats()}")
