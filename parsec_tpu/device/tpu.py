"""TPU device module: async kernel dispatch, HBM tile heap, stage in/out.

This module stands where parsec/mca/device/cuda + the generic GPU runtime
(parsec/mca/device/device_gpu.c) stand in the reference, re-designed for the
XLA/PJRT execution model:

* ``kernel_scheduler`` mirrors parsec_device_kernel_scheduler
  (device_gpu.c:3376): the calling worker enqueues and returns ``HOOK_ASYNC``;
  whichever thread wins the manager try-lock drives the device (the CAS
  owner/manager model of device_gpu.c:3398-3424).
* The push/exec/pop pipeline (streams[0]=H2D, [1]=D2H, [2+]=exec,
  device_gpu.c:3438-3515) collapses naturally: JAX dispatch is asynchronous
  and XLA orders transfers and compute on the device's streams, so the
  manager's job is issuing work early and polling completion *events* — here
  ``jax.Array.is_ready()`` plays cudaEventQuery
  (ref: parsec_device_progress_stream, device_gpu.c:2593).
* Stage-in re-creates parsec_device_data_stage_in (device_gpu.c:1800):
  version-checked transfer from the newest copy (host numpy or another
  device's jax.Array) via ``jax.device_put``.
* The HBM tile heap re-creates the LRU zone-malloc management
  (parsec_device_data_reserve_space, device_gpu.c:1210): resident copies are
  tracked in an LRU; exceeding the byte budget evicts clean (non-owned) copies
  first, then writes back owned ones (the w2r task role, transfer_gpu.c).
* Task batching (parsec_gpu_task_collect_batch, device_gpu.c:2229,
  docs/doxygen/task-batching.md): compatible queued tasks are handed to a
  batch hook in one dispatch when the task class opts in.
"""

from __future__ import annotations

import collections
import os
import threading
from typing import Any, Callable, Deque, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..core.task import (DEV_TPU, FLOW_ACCESS_CTL, FLOW_ACCESS_WRITE,
                         HOOK_ASYNC, HOOK_DONE, Task)
from ..data.data import COHERENCY_INVALID, COHERENCY_OWNED, COHERENCY_SHARED, Data, DataCopy
from ..utils import mca, output
from .device import DeviceModule

mca.register("device_tpu_max_bytes", 0,
             "HBM tile-heap budget in bytes (0 = 75% of reported, else 12GiB)", type=int)
mca.register("device_tpu_max_inflight", 64,
             "Max concurrently dispatched device tasks", type=int)
mca.register("device_tpu_batch_max", 16,
             "Max compatible tasks collapsed into one batched dispatch", type=int)
mca.register("device_tpu_over_cpu", False,
             "TEST MODE: register the device module over a host jax device",
             type=bool)
mca.register("device_tpu_over_cpu_index", 0,
             "TEST MODE: which host jax device to register over (lets each "
             "in-process rank bind a distinct virtual device)", type=int)


class TPUTask:
    """Device-side task descriptor (ref: parsec_gpu_task_t, device_gpu.h:117-155)."""

    __slots__ = ("task", "submit", "stage_in", "stage_out", "pushout",
                 "batchable", "batch_submit", "load", "out_arrays",
                 "complete_cb", "oom_retries", "pinned")

    def __init__(self, task: Task, submit: Callable, stage_in=None,
                 stage_out=None, pushout: int = 0, batchable: bool = False,
                 batch_submit: Optional[Callable] = None) -> None:
        self.task = task
        self.submit = submit          # submit(device, task, inputs)->outputs
        self.stage_in = stage_in      # optional override (ref: custom stage, stage_custom.jdf)
        self.stage_out = stage_out
        self.pushout = pushout        # bitmask of flows to push back to host now
        self.batchable = batchable
        #: batch_submit(device, tasks, inputs_list) -> list of output tuples;
        #: compatible queued tasks collapse into one dispatch
        #: (ref: parsec_gpu_task_collect_batch, device_gpu.c:2229)
        self.batch_submit = batch_submit
        self.load = 0.0
        self.out_arrays: Optional[Sequence[Any]] = None
        self.complete_cb: Optional[Callable] = None
        self.oom_retries = 0
        #: device copies whose ``readers`` count this inflight task holds
        #: (pinned against eviction between stage-in and epilog, ref:
        #: the readers guard of parsec_device_data_stage_in/epilog,
        #: device_gpu.c:1210,1800)
        self.pinned: List[Any] = []


class TPUDevice(DeviceModule):
    """One TPU chip as a PaRSEC-style device module."""

    def __init__(self, jax_device) -> None:
        super().__init__(f"tpu({jax_device.id})", DEV_TPU)
        self.jax_device = jax_device
        import jax
        self._jax = jax
        # crude per-chip speed for ETA selection; real estimates come from
        # task-class time_estimate properties
        self.gflops = 100_000.0
        self._pending: Deque[TPUTask] = collections.deque()
        self._inflight: Deque[TPUTask] = collections.deque()
        self._manager_lock = threading.Lock()  # the CAS mutex (device_gpu.c:3408)
        self._fifo_lock = threading.Lock()
        # LRU tile heap bookkeeping (ref: gpu_mem_lru / gpu_mem_owned_lru)
        self.batched_dispatches = 0
        self._prof_stream = None
        self._prof_keys = None
        self._lru: "collections.OrderedDict[Any, DataCopy]" = collections.OrderedDict()
        self._lru_sizes: Dict[Any, int] = {}   # accounted bytes per key
        self._lru_segs: Dict[Any, Any] = {}    # key -> pt_zone segment
        self._resident_bytes = 0
        self.evictions = 0          # copies evicted (budget pressure stat)
        self.pinned_skips = 0       # eviction walks that skipped a pinned copy
        budget = mca.get("device_tpu_max_bytes", 0)
        if not budget:
            try:
                stats = jax_device.memory_stats() or {}
                budget = int(stats.get("bytes_limit", 0) * 0.75)
            except Exception:
                budget = 0
        self._budget = budget or (12 << 30)
        # the device heap ledger: every resident tile owns a pt_zone segment
        # (offset + size), so occupancy/fragmentation are first-class stats
        # (ref: the GPU zone_malloc heap, parsec/utils/zone_malloc.c; native
        # allocator: native/src/ptcore.cpp pt_zone) — XLA still owns the
        # physical bytes, the zone is the runtime's own accounting
        from ..utils.zone_malloc import ZoneMalloc
        # 64KB units keep the ledger granularity close to the byte-exact
        # eviction accounting even for small tiles (a 1MB default unit would
        # fill the zone ~100x faster than _resident_bytes and desync them)
        self._zone = ZoneMalloc(self._budget, unit=65536)
        # the NATIVE coherency/residency table (ISSUE 10): when _ptdev is
        # available, C owns residency and eviction POLICY — the LRU order,
        # the byte budget, the stage-in version check, victim selection —
        # while Python keeps owning the payloads, the write-back mechanism
        # and the `_lru`/`_zone` mirror the tests inspect. One authority
        # instead of the two unsynchronized views (this LRU vs data.py
        # coherency) that the eviction/reader race grew from.
        from .native import make_coh_table
        self._ncoh = make_coh_table(self._budget)
        # serializes the Python residency MIRROR (_lru/_lru_sizes/_zone/
        # _resident_bytes): the interpreted path mutates it from worker
        # threads (under _manager_lock) while the ptdev manager thread
        # mutates it from lane stage-ins — compound updates like the
        # resident-bytes delta are not GIL-atomic across both
        self._heap_lock = threading.RLock()

    # ------------------------------------------------- native coherency map
    @staticmethod
    def res_key(data: Data) -> int:
        """The canonical residency key for BOTH the Python LRU mirror and
        the C coherency table. ``data.key`` is only unique per collection
        (A(0,0)/B(0,0)/C(0,0) all carry key 0 — the aliasing the table
        exposed), so the Data object's identity is the key: a resident
        entry's DataCopy pins its Data, so the id cannot be reused while
        the entry lives; a dead Data's stale table entry can only cause a
        spurious re-stage (version mismatch), never a wrong hit on a live
        payload."""
        return id(data)

    def _coh_pin(self, data: Data) -> None:
        if self._ncoh is not None and data is not None:
            self._ncoh.pin(self.res_key(data))

    def _coh_unpin(self, data: Data) -> None:
        if self._ncoh is not None and data is not None:
            self._ncoh.unpin(self.res_key(data))

    def _coh_mark_owned(self, data: Data, copy: DataCopy) -> None:
        """Writer completed on this device: the table's entry becomes the
        OWNER at the new version (the epilog bump); growth past the
        budget returns eviction victims to apply."""
        if self._ncoh is None:
            return
        victims = self._ncoh.mark_owned(self.res_key(data),
                                        data.version & 0xFFFFFFFF,
                                        _nbytes(copy.payload))
        if victims:
            self._apply_victims(victims)

    def _apply_victims(self, victims) -> None:
        """Commit the C table's eviction decisions: write back +
        invalidate each victim ATOMICALLY with its version check
        (Data.evict_copy), then update the Python mirror (sizes, zone
        ledger, counters). Policy came from C; this is pure mechanism."""
        with self._heap_lock:
            self._apply_victims_locked(victims)

    def _apply_victims_locked(self, victims) -> None:
        for key, _owned in victims:
            copy = self._lru.get(key)
            if copy is None:
                continue
            if copy.readers > 0:
                # a Python-side pin the table could not see (a custom
                # stage hook pins only after its stage-in returns): veto
                # the eviction — the table already dropped its entry, so
                # the next stage-in simply re-reserves, and the inflight
                # reader keeps its payload
                self.pinned_skips += 1
                continue
            self._lru.pop(key)
            self._evict_key_locked(key, copy, drop_table=False)

    def _evict_key_locked(self, key: Any, copy: DataCopy,
                          drop_table: bool) -> None:
        """The ONE eviction mechanism (heap lock held, `key` already out
        of ``_lru``): mirror bookkeeping, the atomic write-back +
        invalidate (Data.evict_copy), and the counters. ``drop_table``
        removes the C entry too (the Python-LRU fallback path decided the
        victim itself; C-decided victims already left the table)."""
        freed = self._lru_sizes.pop(key, 0)
        self._resident_bytes -= freed
        seg = self._lru_segs.pop(key, None)
        if seg is not None:
            seg.free()
        data = copy.original
        wrote = False
        if data is not None:
            _evicted, wrote = data.evict_copy(self.device_index)
        else:
            copy.coherency_state = COHERENCY_INVALID
            copy.payload = None
        if wrote:
            self.transfer_out_bytes += freed
            if self._ncoh is not None:
                self._ncoh.count_writeback(freed)
        if drop_table and self._ncoh is not None:
            self._ncoh.drop(key)
        self.evictions += 1
        self._trace_mem(-freed)

    def coh_stats(self) -> Optional[Dict[str, int]]:
        """The native coherency/residency counters, or None when the
        table is unavailable (Python-LRU fallback mode)."""
        return None if self._ncoh is None else self._ncoh.stats()

    # ------------------------------------------------------------- dispatch API
    def kernel_scheduler(self, stream, task: Task, tpu_task: Optional[TPUTask] = None,
                         submit: Optional[Callable] = None) -> int:
        """Enqueue a device task; ref: parsec_device_kernel_scheduler
        (device_gpu.c:3376). Returns HOOK_ASYNC immediately."""
        if tpu_task is None:
            tpu_task = TPUTask(task, submit)
        tpu_task.load = self.time_estimate(task)
        self.load_add(tpu_task.load)
        with self._fifo_lock:
            self._pending.append(tpu_task)
        # opportunistically become the manager right away
        self.progress(stream)
        return HOOK_ASYNC

    # ------------------------------------------------------------- progress
    def progress(self, stream) -> int:
        """Manager drive: submit pending, poll events, run epilogs.

        Only one thread at a time is the manager (try-lock = the CAS in
        device_gpu.c:3398-3424); others return immediately after enqueueing.
        """
        if not self._pending and not self._inflight:
            # idle fast-path: this poll sits in every hot-loop iteration,
            # and CPU-chore-only workloads must not pay the manager lock +
            # MCA lookups per loop (an enqueue racing this check is picked
            # up on the very next iteration — the enqueue sets work_event)
            return 0
        if not self._manager_lock.acquire(blocking=False):
            return 0
        try:
            completed = 0
            max_inflight = mca.get("device_tpu_max_inflight", 64)
            # kernel_push + kernel_exec phases (device_gpu.c:2746,2874)
            batch_max = mca.get("device_tpu_batch_max", 16)
            while len(self._inflight) < max_inflight:
                with self._fifo_lock:
                    if not self._pending:
                        break
                    head = self._pending[0]
                    # batchable head while the device is busy: let the batch
                    # accumulate — deferral is free, the chip has work
                    # (the collect discipline of parsec_gpu_task_collect_batch)
                    if (head.batchable and head.batch_submit is not None and
                            self._inflight and
                            len(self._pending) < batch_max):
                        break
                    gt = self._pending.popleft()
                    group = [gt]
                    # collect compatible pending tasks into one dispatch
                    # (ref: parsec_gpu_task_collect_batch)
                    if gt.batchable and gt.batch_submit is not None:
                        while (self._pending and len(group) < batch_max and
                               self._pending[0].batchable and
                               self._pending[0].batch_submit == gt.batch_submit and
                               self._pending[0].task.task_class is gt.task.task_class):
                            group.append(self._pending.popleft())
                if len(group) > 1:
                    submitted = self._submit_group(group)
                    if len(submitted) == len(group):
                        self.batched_dispatches += 1
                else:
                    submitted = group if self._submit_one_retry(gt) else []
                self._inflight.extend(submitted)
            # event polling + kernel_pop/epilog: poll each task's events
            # independently — inflight tasks are mutually independent (their
            # deps only release at epilog), so one slow kernel must not
            # head-of-line block completed peers behind it (ref: per-stream
            # event polls, device_gpu.c:2593,2944,3179)
            still: Deque[TPUTask] = collections.deque()
            while self._inflight:
                gt = self._inflight.popleft()
                if gt.out_arrays and not all(a.is_ready() for a in gt.out_arrays):
                    still.append(gt)
                    continue
                self._epilog(stream, gt)
                completed += 1
            self._inflight = still
            return completed
        finally:
            self._manager_lock.release()

    # ------------------------------------------------------------- internals
    def _stage_in_copy(self, data: Data, access: int,
                       pin: bool = False) -> DataCopy:
        """Version-checked stage-in (ref: parsec_device_data_stage_in
        device_gpu.c:1800). Returns the device-resident copy.

        With the native table up, the residency decision — is a copy of
        exactly this version resident, and which victims must leave to
        make room — is C's (CohTable.stage_in issues the early reserve of
        the push stage); this method stays the transfer MECHANISM.
        ``pin=True`` takes the eviction pin INSIDE the table's reserve
        critical section (a concurrent stage-in on another thread could
        otherwise evict this entry between the reserve and the caller's
        pin) and bumps the Python reader count to match — release with
        :meth:`unpin_copy`."""
        dev_idx = self.device_index
        copy = data.get_copy(dev_idx)
        newest = data.newest_copy()
        if self._ncoh is not None and newest is not None:
            nbytes = _nbytes(newest.payload)
            need, victims = self._ncoh.stage_in(
                self.res_key(data), nbytes,
                newest.version & 0xFFFFFFFF, 0, 1 if pin else 0)
            if victims:
                self._apply_victims(victims)
            if not need and copy is not None and \
                    copy.version == newest.version and \
                    copy.coherency_state != COHERENCY_INVALID:
                self._lru_touch(self.res_key(data), copy)
                if pin:
                    with self._heap_lock:
                        copy.readers += 1     # table half pinned above
                return copy
            # table said transfer (or the mirror lost the payload: the
            # stale table entry was already replaced by stage_in)
        elif copy is not None and newest is not None and \
                copy.version == newest.version and \
                copy.coherency_state != COHERENCY_INVALID:
            self._lru_touch(self.res_key(data), copy)
            if pin:
                self.pin_copy(copy)
            return copy
        src = newest
        if src is None:
            raise RuntimeError(f"no valid copy to stage in for {data!r}")
        arr = self._jax.device_put(src.payload, self.jax_device)  # async H2D/D2D
        nbytes = _nbytes(arr)
        if self._ncoh is None:
            self._reserve(nbytes)   # native mode: stage_in reserved above
        if copy is None:
            copy = data.create_copy(dev_idx, arr, COHERENCY_SHARED)
        else:
            copy.payload = arr
            copy.coherency_state = COHERENCY_SHARED
        copy.version = src.version
        self.transfer_in_bytes += nbytes
        self._lru_touch(self.res_key(data), copy)
        if pin:
            if self._ncoh is not None:
                with self._heap_lock:
                    copy.readers += 1     # table half pinned in stage_in
            else:
                self.pin_copy(copy)
        return copy

    def lane_stage_in(self, data: Data, pin: bool = False) -> DataCopy:
        """Stage-in entry for the native device lane's dispatch callback
        (the push phase of ptdev): version-checked through the C table,
        asynchronous, returns the device copy — pinned atomically with
        the reserve when ``pin``."""
        return self._stage_in_copy(data, 0, pin=pin)

    def _prof(self):
        """Per-device profiling stream (ref: per-GPU-stream profiling
        streams, profiling.h:146-440), lazily bound to ctx.profiling."""
        prof = getattr(self.context, "profiling", None)
        if prof is None:
            return None
        if getattr(self, "_prof_stream", None) is None:
            self._prof_stream = prof.stream(self.name)
            self._prof_keys = prof.add_dictionary_keyword(f"{self.name}::exec")
            # memory-ledger events (the dbp2mem surface, tools/profiling/
            # dbp2mem.c): every residency change is a POINT event carrying
            # the post-change occupancy, rendered over time by
            # parsec_tpu.tools.mem_view
            self._mem_key = prof.add_dictionary_keyword(
                f"{self.name}::mem", info_desc="resident{q};delta{q}")[0]
            self._prof_ref = prof
            self._mem_seq = 0
        return self._prof_stream

    def _trace_mem(self, delta: int) -> None:
        """Record a residency change (bytes) on the device's trace stream."""
        ps = self._prof()
        if ps is None or delta == 0:
            return
        from ..utils.trace import EVENT_FLAG_POINT
        self._mem_seq += 1
        ps.trace(self._mem_key, self._mem_seq, 0, EVENT_FLAG_POINT,
                 self._prof_ref.pack_info(f"{self.name}::mem",
                                          resident=self._resident_bytes,
                                          delta=delta))

    def _submit_one(self, gt: TPUTask) -> None:
        task = gt.task
        ps = self._prof()
        if ps is not None:
            from ..utils.trace import EVENT_FLAG_START
            ps.trace(self._prof_keys[0], hash(task.key) & 0x7FFFFFFF,
                     task.taskpool.taskpool_id, EVENT_FLAG_START)
        inputs = self._gather_inputs(gt)
        outs = gt.submit(self, task, inputs)
        if outs is None:
            outs = ()
        elif not isinstance(outs, (tuple, list)):
            outs = (outs,)
        gt.out_arrays = outs

    def _default_stage_in(self, data: Data, access: int) -> DataCopy:
        return self._stage_in_copy(data, access)

    def _gather_inputs(self, gt: TPUTask) -> List[Any]:
        task = gt.task
        inputs: List[Any] = []
        for flow in task.task_class.flows:
            slot = task.data[flow.flow_index]
            if flow.access & FLOW_ACCESS_CTL or slot.data_in is None:
                inputs.append(None)
                continue
            copy_in = slot.data_in
            # PTG intermediates may ride as raw arrays (no backing Data);
            # they bypass the LRU heap and just get placed on-device
            data = getattr(copy_in, "original", None)
            if data is not None:
                # pin between stage-in and epilog: the eviction walks skip
                # copies with readers > 0, so an inflight task's inputs
                # can never be evicted under it (device_gpu.c:1210). The
                # default path pins INSIDE the table's reserve critical
                # section; custom stage hooks pin right after
                if gt.stage_in is None:
                    dev_copy = self._stage_in_copy(data, flow.access,
                                                   pin=True)
                else:
                    dev_copy = gt.stage_in(data, flow.access)
                    self.pin_copy(dev_copy)
                slot.data_in = dev_copy
                gt.pinned.append(dev_copy)
                inputs.append(dev_copy.payload)
            else:
                payload = getattr(copy_in, "payload", copy_in)
                inputs.append(self._jax.device_put(payload, self.jax_device))
        return inputs

    def _unpin(self, gt: TPUTask) -> None:
        """Drop this task's reader pins (epilog or failed submit)."""
        for copy in gt.pinned:
            self.unpin_copy(copy)
        gt.pinned.clear()

    def _submit_one_retry(self, gt: TPUTask) -> bool:
        """Submit with the OOM -> evict -> retry -> HOOK_AGAIN discipline of
        device_gpu.c. Returns True when dispatched; False when the task was
        bounced back to the scheduler."""
        try:
            self._submit_one(gt)
            return True
        except Exception as e:  # noqa: BLE001
            self._unpin(gt)     # the retry re-gathers (and re-pins) inputs
            if not _is_oom(e):
                self.load_sub(gt.load)
                output.fatal(f"TPU submit failed for {gt.task!r}: {e}")
            freed = self.evict_bytes(max(self._resident_bytes // 2, 1))
            try:
                self._submit_one(gt)
                return True
            except Exception as e2:  # noqa: BLE001
                self._unpin(gt)
                if not _is_oom(e2):
                    self.load_sub(gt.load)
                    output.fatal(f"TPU submit failed for {gt.task!r}: {e2}")
                gt.oom_retries += 1
                if freed == 0 or gt.oom_retries > 8:
                    output.fatal(
                        f"task {gt.task!r} does not fit in device memory "
                        f"(resident={self._resident_bytes}, "
                        f"retries={gt.oom_retries})")
                self.load_sub(gt.load)
                self.context.schedule([gt.task])
                return False

    def _submit_group(self, group: List[TPUTask]) -> List[TPUTask]:
        """One dispatch for a batch of compatible independent tasks; ragged
        batches (e.g. boundary tiles of a different shape) fall back to
        per-task submission. Returns the tasks actually dispatched."""
        try:
            inputs_list = [self._gather_inputs(g) for g in group]
            outs_list = group[0].batch_submit(self, [g.task for g in group],
                                              inputs_list)
        except Exception as e:  # noqa: BLE001 - ragged shapes, stage-in OOM
            output.debug_verbose(2, "device",
                                 f"batch of {len(group)} fell back: {e}")
            # unpin EVERY member (a stage-in failure mid-gather leaves
            # earlier members pinned); per-task retries re-gather + re-pin
            for g in group:
                self._unpin(g)
            return [g for g in group if self._submit_one_retry(g)]
        for g, outs in zip(group, outs_list):
            if outs is None:
                outs = ()
            elif not isinstance(outs, (tuple, list)):
                outs = (outs,)
            g.out_arrays = tuple(outs)
        return group

    def _epilog(self, stream, gt: TPUTask) -> None:
        """parsec_device_kernel_epilog (device_gpu.c:3179): attach outputs,
        bump versions, OWNED->SHARED transitions, then complete the task."""
        task = gt.task
        tc = task.task_class
        outs = list(gt.out_arrays or ())
        oi = 0
        for flow in tc.flows:
            if not (flow.access & FLOW_ACCESS_WRITE) or flow.access & FLOW_ACCESS_CTL:
                continue
            if oi >= len(outs):
                break
            arr = outs[oi]
            oi += 1
            slot = task.data[flow.flow_index]
            src = slot.data_in
            data = getattr(src, "original", None)
            if data is not None:
                copy = data.get_copy(self.device_index)
                if copy is None:
                    copy = data.create_copy(self.device_index, arr, COHERENCY_OWNED)
                else:
                    copy.payload = arr
                data.bump_version(self.device_index)
                slot.data_out = copy
                self._lru_touch(self.res_key(data), copy)
                self._coh_mark_owned(data, copy)
                if gt.pushout & (1 << flow.flow_index):
                    self._stage_out(data, copy)
            else:
                slot.data_out = arr
        ps = self._prof()
        if ps is not None:
            from ..utils.trace import EVENT_FLAG_END
            ps.trace(self._prof_keys[1], hash(task.key) & 0x7FFFFFFF,
                     task.taskpool.taskpool_id, EVENT_FLAG_END)
        self._unpin(gt)     # inputs consumed: copies evictable again
        self.executed_tasks += 1
        self.load_sub(gt.load)
        if gt.complete_cb is not None:
            gt.complete_cb(gt)
        self.context and self.context.complete_task_execution(stream, task)

    def _stage_out(self, data: Data, copy: DataCopy) -> None:
        """D2H write-back (ref: stage_out device_gpu.c:1674 + w2r task)."""
        host = np.asarray(copy.payload)
        hcopy = data.get_copy(0)
        if hcopy is None:
            hcopy = data.create_copy(0, host, COHERENCY_SHARED)
        else:
            hcopy.payload = host
            hcopy.coherency_state = COHERENCY_SHARED
        hcopy.version = copy.version
        self.transfer_out_bytes += _nbytes(copy.payload)

    # ------------------------------------------------------------- LRU heap
    def _lru_touch(self, key: Any, copy: DataCopy) -> None:
        # account by the size actually resident under this key: an epilog may
        # rebind the copy's payload to a different-sized array, and the budget
        # must follow (the eviction math drifts otherwise)
        with self._heap_lock:
            self._lru_touch_locked(key, copy)

    def _lru_touch_locked(self, key: Any, copy: DataCopy) -> None:
        self._lru.pop(key, None)
        new_size = _nbytes(copy.payload)
        old_size = self._lru_sizes.get(key, 0)
        self._resident_bytes += new_size - old_size
        self._lru_sizes[key] = new_size
        self._lru[key] = copy
        self._trace_mem(new_size - old_size)
        if new_size != old_size or key not in self._lru_segs:
            # re-register on size change AND whenever the key has no live
            # segment (a past allocate() miss under pressure must not
            # permanently drop the tile from the ledger)
            seg = self._lru_segs.pop(key, None)
            if seg is not None:
                seg.free()
            seg = self._zone.allocate(new_size)
            if seg is not None:
                self._lru_segs[key] = seg

    def _evict_one(self) -> bool:
        """Evict the least-recently-used unpinned copy; an OWNED copy
        writes back AND downgrades atomically with the version check
        (Data.evict_copy — one critical section, so a reader racing the
        eviction can never see the newest version without a valid
        payload). Python-LRU fallback path; with the native table up,
        victim selection comes from C (:meth:`_apply_victims`)."""
        with self._heap_lock:
            return self._evict_one_locked()

    def _evict_one_locked(self) -> bool:
        for key in list(self._lru):
            copy = self._lru[key]
            if copy.readers > 0:
                self.pinned_skips += 1
                continue
            self._lru.pop(key)
            self._evict_key_locked(key, copy, drop_table=True)
            return True
        return False

    def evict_bytes(self, nbytes: int) -> int:
        """Force eviction of about ``nbytes`` of resident clean/dirty copies
        (the explicit half of the OOM retry path). With the native table
        up, the victim set is C's decision."""
        freed0 = self._resident_bytes
        if self._ncoh is not None:
            victims, skips = self._ncoh.evict(nbytes)
            self._apply_victims(victims)
            self.pinned_skips += skips
            return freed0 - self._resident_bytes
        target = max(0, self._resident_bytes - nbytes)
        while self._resident_bytes > target and self._lru:
            if not self._evict_one():
                break
        return freed0 - self._resident_bytes

    def pin_copy(self, copy: DataCopy) -> None:
        """Pin a device copy against eviction (the inflight-task reader
        guard): bumps the Python reader count AND the native table's pin
        so C's victim selection honors it. The reader count mutates from
        interpreted-path workers AND the ptdev manager thread — the
        non-atomic ``+=`` goes under the heap lock so no update is lost."""
        with self._heap_lock:
            copy.readers += 1
        self._coh_pin(copy.original)

    def unpin_copy(self, copy: DataCopy) -> None:
        with self._heap_lock:
            copy.readers -= 1
        self._coh_unpin(copy.original)

    def _reserve(self, nbytes: int) -> None:
        """Evict LRU copies until ``nbytes`` fits the budget
        (ref: parsec_device_data_reserve_space device_gpu.c:1210)."""
        while self._resident_bytes + nbytes > self._budget and self._lru:
            if not self._evict_one():
                break  # everything pinned; rely on XLA allocator

    def zone_stats(self) -> Dict[str, int]:
        """Device-heap ledger stats (occupancy, fragmentation, high-water
        mark) — the zonemalloc_benchmark surface of the reference."""
        return self._zone.stats()

    def set_budget(self, nbytes: int, unit: Optional[int] = None) -> None:
        """Resize the HBM tile budget (tests / MCA reconfiguration): the
        zone ledger is rebuilt and current residents re-registered."""
        from ..utils.zone_malloc import ZoneMalloc
        with self._heap_lock:
            self._budget = nbytes
            if self._ncoh is not None:
                # C applies the new budget first (victims leave both views)
                self._apply_victims_locked(self._ncoh.set_budget(nbytes))
            self._zone = ZoneMalloc(nbytes, unit)
            self._lru_segs = {}
            for key, sz in self._lru_sizes.items():
                seg = self._zone.allocate(sz)
                if seg is not None:
                    self._lru_segs[key] = seg

    def fini(self) -> None:
        self._lru.clear()
        self._lru_sizes.clear()
        for seg in self._lru_segs.values():
            seg.free()
        self._lru_segs.clear()
        self._resident_bytes = 0
        self._pending.clear()


def _is_oom(e: Exception) -> bool:
    msg = str(e).upper()
    return "RESOURCE_EXHAUSTED" in msg or "OUT OF MEMORY" in msg or "OOM" in msg


def _nbytes(arr) -> int:
    try:
        return int(arr.nbytes)
    except Exception:
        return int(np.prod(getattr(arr, "shape", (1,))) * 4)


# rank→chip binding handed down by the launcher: index into this process's
# local device list (ref: the mpiexec + one-GPU-per-rank production shape,
# tests/CMakeLists.txt:1032-1042)
ENV_LOCAL_DEVICE = "PARSEC_TPU_LOCAL_DEVICE"


def discover_tpu_devices() -> List[TPUDevice]:
    """Enumerate local accelerator chips through JAX (ref: device discovery,
    device_cuda_module.c:45). Non-TPU accelerators (gpu) are accepted too so
    the framework degrades gracefully on CPU-only CI (no device created).

    Discovery runs under a hard timeout: on TPU pods the first backend touch
    can hang indefinitely when the chip transport is unhealthy; a wedged
    discovery must degrade to CPU instead of hanging the whole runtime. The
    first line of defense is the subprocess health probe (`probe.py`) BEFORE
    any in-process backend touch — the in-thread timeout below only covers
    the residual race where a backend was initialized behind our back.
    """
    from .probe import decide_backend
    decide_backend()
    import jax
    result: List[TPUDevice] = []
    done = threading.Event()
    over_cpu = mca.get("device_tpu_over_cpu", False)
    # launcher-provided rank→chip binding (the mpiexec + CUDA_VISIBLE_DEVICES
    # role): each process binds exactly its local device i instead of
    # claiming every chip on the host
    bind = os.environ.get(ENV_LOCAL_DEVICE)

    def _probe() -> None:
        try:
            accels, cpus = [], []
            for d in jax.devices():
                if d.platform in ("tpu", "gpu", "axon"):
                    accels.append(d)
                elif over_cpu and d.platform == "cpu":
                    cpus.append(d)
            if accels:
                if bind is not None:
                    result.append(TPUDevice(accels[int(bind) % len(accels)]))
                else:
                    result.extend(TPUDevice(d) for d in accels)
            elif cpus:
                # test mode: drive the full async device pipeline (stage-in,
                # LRU, events, batching) over one host device — selectable so
                # oversubscribed ranks can spread over a virtual device mesh
                idx = (int(bind) if bind is not None
                       else mca.get("device_tpu_over_cpu_index", 0)) % len(cpus)
                result.append(TPUDevice(cpus[idx]))
        except Exception as e:
            output.debug_verbose(1, "device", f"jax.devices() failed: {e}")
        finally:
            done.set()

    t = threading.Thread(target=_probe, daemon=True, name="parsec-tpu-discover")
    t.start()
    if not done.wait(timeout=mca.get("device_discovery_timeout_s", 45)):
        output.warning("accelerator discovery timed out; forcing CPU backend")
        try:
            jax.config.update("jax_platforms", "cpu")
        except Exception:
            pass
        return []
    return result


def make_tpu_hook(submit: Callable) -> Callable:
    """Build a chore hook dispatching ``submit`` on the selected TPU device.

    Plays the role of the generated GPU hook (jdf2c.c:6613) wrapping the body
    into a gpu_task and invoking the kernel scheduler.
    ``submit(device, task, inputs)`` must return the output arrays for WRITE
    flows in flow order; typically it calls a pre-compiled jitted function.
    """
    def hook(stream, task: Task) -> int:
        dev = task.selected_device
        if dev is None or not isinstance(dev, TPUDevice):
            return HOOK_DONE if submit is None else _run_inline(stream, task, submit)
        return dev.kernel_scheduler(stream, task, submit=submit)
    return hook


def _run_inline(stream, task, submit) -> int:
    """CPU fallback: run the body synchronously on host copies."""
    inputs = []
    for flow in task.task_class.flows:
        slot = task.data[flow.flow_index]
        inputs.append(None if slot.data_in is None else slot.data_in.payload)
    outs = submit(None, task, inputs)
    if outs is not None and not isinstance(outs, (tuple, list)):
        outs = (outs,)
    oi = 0
    for flow in task.task_class.flows:
        if flow.access & FLOW_ACCESS_WRITE and outs and oi < len(outs):
            slot = task.data[flow.flow_index]
            if slot.data_in is not None and slot.data_in.original is not None:
                data = slot.data_in.original
                slot.data_in.payload = outs[oi]
                data.bump_version(slot.data_in.device_index)
                slot.data_out = slot.data_in
            else:
                slot.data_out = outs[oi]
            oi += 1
    return HOOK_DONE
