"""``python -m parsec_tpu.launch -n N script.py [args...]`` — the mpiexec.

Spawns N copies of ``script.py`` as real OS processes, each with
``PARSEC_TPU_RANK`` / ``PARSEC_TPU_NPROCS`` / ``PARSEC_TPU_RDV`` set; the
script calls :func:`parsec_tpu.comm.tcp.init_from_env` to join the TCP mesh
(its `MPI_Init` moment). Stands where ``mpiexec -n N`` stands in the
reference's workflow (tests/CMakeLists.txt:1032-1042 oversubscribed-host
test mode).
"""

from __future__ import annotations

import argparse
import contextlib
import os
import signal
import subprocess
import sys
import tempfile
import time
from typing import Optional

from .comm.tcp import ENV_NPROCS, ENV_RANK, ENV_RDV, _free_port

_MULTIPROC_LOCK_PATH = os.path.join(tempfile.gettempdir(),
                                    "parsec_tpu_multiproc.lock")


@contextlib.contextmanager
def multiproc_lock(timeout: float = 300.0):
    """Serialize multiproc phases across SESSIONS on one host (lock-file).

    Spawned-rank jobs are the one test class that cannot tolerate a busy
    host: every rank pays a full interpreter+jax import before it can
    rendezvous, so two concurrent multiproc jobs (e.g. a background full
    suite plus a foreground test run) push each other past their
    deadlines and flap. Taking this advisory flock around each job makes
    the host run them one at a time; a holder that outlives ``timeout``
    degrades to running unserialized (never deadlocks on a dead peer's
    stale lock — flock dies with its process anyway).

    Ranks themselves (PARSEC_TPU_RANK set) skip the lock: the parent job
    already holds it, and a child blocking on it would self-deadlock.
    """
    if os.environ.get(ENV_RANK) is not None:
        yield
        return
    try:
        f = open(_MULTIPROC_LOCK_PATH, "a+b")
    except OSError:
        yield                     # unwritable tmp: run unserialized
        return
    try:
        import fcntl
        deadline = time.monotonic() + timeout
        while True:
            try:
                fcntl.flock(f, fcntl.LOCK_EX | fcntl.LOCK_NB)
                break
            except OSError:
                if time.monotonic() > deadline:
                    break         # degrade rather than queue forever
                time.sleep(0.2)
        yield
    finally:
        try:
            import fcntl
            fcntl.flock(f, fcntl.LOCK_UN)
        except OSError:
            pass
        f.close()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="parsec_tpu.launch",
                                 description="run a script on N TCP-mesh ranks")
    ap.add_argument("-n", "--np", type=int, default=2, dest="nprocs")
    ap.add_argument("--timeout", type=float, default=300.0)
    ap.add_argument("--cpu", action="store_true",
                    help="force the CPU backend on every rank (no probe)")
    ap.add_argument("--bind-devices", action="store_true",
                    help="bind rank i to local accelerator chip i "
                         "(PARSEC_TPU_LOCAL_DEVICE=i; ranks beyond the chip "
                         "count fall back to CPU)")
    ap.add_argument("--virtual-devices", type=int, default=0, metavar="N",
                    help="give every rank N virtual CPU devices "
                         "(--xla_force_host_platform_device_count) and bind "
                         "rank i to device i%%N through the TPU device module "
                         "— the production process-per-rank/chip-per-process "
                         "shape, rehearsed without chips")
    ap.add_argument("--mca", nargs=2, action="append", default=[],
                    metavar=("PARAM", "VALUE"),
                    help="set an MCA parameter in every rank (exported as "
                         "PARSEC_MCA_<param>; the mpirun --mca role)")
    ap.add_argument("script")
    ap.add_argument("args", nargs=argparse.REMAINDER)
    opts = ap.parse_args(argv)

    # one accelerator decision for the whole job, made HERE: ranks must never
    # probe concurrently (a single-session TPU transport wedges under
    # multiple clients), and a lone chip belongs to rank 0 only
    accel_ok, accel_count = False, 0
    if not opts.cpu and not opts.virtual_devices:
        try:
            p = subprocess.run(
                [sys.executable, "-c",
                 "import jax; d = jax.devices(); print(d[0].platform, len(d))"],
                capture_output=True, text=True, timeout=90)
            last = (p.stdout.strip().splitlines()[-1]
                    if p.returncode == 0 and p.stdout.strip() else "")
            plat, _, cnt = last.partition(" ")
            accel_ok = plat in ("tpu", "axon", "gpu")
            accel_count = int(cnt) if accel_ok and cnt.isdigit() else 0
        except Exception:
            accel_ok = False

    with multiproc_lock():
        return _run_job(opts, accel_ok, accel_count)


def _run_job(opts, accel_ok: bool, accel_count: int) -> int:
    rdv = f"127.0.0.1:{_free_port()}"
    procs = []
    for rank in range(opts.nprocs):
        env = dict(os.environ)
        env[ENV_RANK] = str(rank)
        env[ENV_NPROCS] = str(opts.nprocs)
        env[ENV_RDV] = rdv
        for pname, pval in opts.mca:
            env["PARSEC_MCA_" + pname] = pval
        if opts.virtual_devices:
            # rehearse the chip-per-process shape over virtual CPU devices
            n = opts.virtual_devices
            flag = f"--xla_force_host_platform_device_count={n}"
            env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "") + " " + flag).strip()
            env["PARSEC_TPU_FORCE_CPU"] = "1"
            env["PARSEC_MCA_device_tpu_over_cpu"] = "1"
            env["PARSEC_TPU_LOCAL_DEVICE"] = str(rank % n)
        elif opts.bind_devices and accel_ok and rank < max(accel_count, 1):
            env["PARSEC_TPU_LOCAL_DEVICE"] = str(rank)
        elif not accel_ok or rank > 0:
            env["PARSEC_TPU_FORCE_CPU"] = "1"
        # each rank leads its own process group so cleanup can reach
        # grandchildren even if the launcher itself is killed mid-wait
        procs.append(subprocess.Popen(
            [sys.executable, opts.script, *opts.args], env=env,
            start_new_session=True))
    rc = 0
    deadline = time.monotonic() + opts.timeout   # one job-wide deadline
    try:
        for p in procs:
            try:
                p.wait(timeout=max(0.1, deadline - time.monotonic()))
                rc = rc or p.returncode
            except subprocess.TimeoutExpired:
                rc = 124
                break
    finally:
        for p in procs:
            if p.poll() is None:
                _kill_group(p, signal.SIGTERM)
        t0 = time.monotonic()
        for p in procs:
            if p.poll() is None:
                try:
                    p.wait(timeout=max(0.1, 5.0 - (time.monotonic() - t0)))
                except subprocess.TimeoutExpired:
                    _kill_group(p, signal.SIGKILL)
    return rc


def cpu_budget() -> dict:
    """The host's REAL cpu allowance — cgroup quota + affinity mask — so
    scaling rows are reproducible from logged inputs (VERDICT r4 weak #3:
    an aggregate above the nominal core count must be explainable)."""
    quota = None
    try:
        raw = open("/sys/fs/cgroup/cpu.max").read().split()
        if raw and raw[0] != "max":
            quota = float(raw[0]) / float(raw[1])
    except OSError:
        try:
            q = int(open("/sys/fs/cgroup/cpu/cpu.cfs_quota_us").read())
            p = int(open("/sys/fs/cgroup/cpu/cpu.cfs_period_us").read())
            if q > 0:
                quota = q / p
        except OSError:
            pass
    try:
        allowed = len(os.sched_getaffinity(0))
    except (AttributeError, OSError):
        allowed = os.cpu_count()
    return {"cgroup_cpu_quota_cores": quota, "cpus_allowed": allowed,
            "nproc": os.cpu_count()}


def ep_scaling_rates(proc_counts=(1, 2, 4), ntasks: int = 20000,
                     timeout: float = 240.0,
                     detail: Optional[dict] = None) -> dict:
    """Aggregate EP task throughput at P OS processes — the framework's
    official scaling row.

    Process-per-chip IS the architecture (one host process drives one chip's
    task graph; ranks mesh over TCP — the reference's one-MPI-rank-per-GPU
    shape, mca/device/cuda + remote_dep.c). Thread counts beyond one measure
    only the GIL, so scale-out is measured the way it is deployed: real OS
    processes through this launcher, barrier-aligned, aggregate =
    P·ntasks / max(rank wall). On a 1-core container a flat aggregate is the
    physical ceiling — the row proves process scale-out adds no runtime
    penalty, not that one core can exceed itself.

    Returns {P: aggregate tasks/s}.
    """
    import re

    pkg_parent = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    rates = {}
    for nprocs in proc_counts:
        rdv = f"127.0.0.1:{_free_port()}"
        procs = []
        for rank in range(nprocs):
            env = dict(os.environ)
            env[ENV_RANK] = str(rank)
            env[ENV_NPROCS] = str(nprocs)
            env[ENV_RDV] = rdv
            # the EP row measures host machinery; ranks must not race for
            # the (single-session) accelerator transport
            env["PARSEC_TPU_FORCE_CPU"] = "1"
            procs.append(subprocess.Popen(
                [sys.executable, "-m", "parsec_tpu._bench_ep_worker",
                 str(ntasks)],
                env=env, cwd=pkg_parent, stdout=subprocess.PIPE,
                stderr=subprocess.DEVNULL, text=True,
                start_new_session=True))
        walls = []
        try:
            deadline = time.monotonic() + timeout
            for p in procs:
                out, _ = p.communicate(
                    timeout=max(1.0, deadline - time.monotonic()))
                m = re.search(r"wall=([0-9.]+)", out or "")
                if p.returncode != 0 or not m:
                    raise RuntimeError(
                        f"EP worker failed (rc={p.returncode}): "
                        f"{(out or '').strip()[-200:]}")
                walls.append(float(m.group(1)))
        finally:
            for p in procs:
                if p.poll() is None:
                    _kill_group(p, signal.SIGKILL)
        rates[nprocs] = round(nprocs * ntasks / max(walls))
        if detail is not None:
            detail[nprocs] = {"walls_s": [round(w, 4) for w in walls],
                              "aggregate_tasks_per_sec": rates[nprocs]}
    if detail is not None:
        detail["cpu_budget"] = cpu_budget()
    return rates


def _kill_group(p: subprocess.Popen, sig) -> None:
    try:
        os.killpg(p.pid, sig)
    except (ProcessLookupError, PermissionError, OSError):
        try:
            p.send_signal(sig)
        except Exception:
            pass


if __name__ == "__main__":
    sys.exit(main())
