"""Bridge: native in-lane event rings → the PBP profiling trace.

The observability half of the native execution lanes (the role the
reference's profiling.c per-ES buffers play for its generated-C hot
path): ``native/src/ptexec.cpp`` and ``ptdtd.cpp`` record
``(key, id, flags, monotonic-ns)`` events into per-worker lock-free ring
buffers while the FSM walks with the GIL dropped (``ptrace_ring.h``).
This module drains those rings and lands the events into the existing
:mod:`parsec_tpu.utils.trace` machinery:

* native event keys register in the process PBP **dictionary**
  (``ptexec::task``, ``ptexec::dispatch``, ``ptdtd::link``,
  ``ptdtd::exec``, ``ptdtd::task``) — begin/end pairs share a key with
  the low bit distinguishing START/END exactly like every other keyword;
* each (lane, ring) pair becomes a per-worker **profiling stream**
  (``ptexec-w0`` …), so :mod:`parsec_tpu.tools.trace_reader` (summary,
  CSV, chrome://tracing/Perfetto JSON) and the PTF2 backend consume
  native-lane runs unchanged;
* each drain that landed events fires coarse ``SCHEDULE_BEGIN/END``
  PINS batch markers (a :class:`NativeDrainMarker`, NOT per-task events)
  so existing ``pins_modules`` consumers observe lane activity — exact
  per-task counts live in the counter registry
  (``utils/counters.install_native_counters``), not in the markers;
* ring **drop counters** (overflow never blocks the lane) surface
  through :func:`total_dropped` / the ``trace.events_dropped`` counter.

Timestamp calibration: the rings record ``steady_clock`` ns while the
PBP streams use ``time.perf_counter()`` seconds; the offset is sampled
once per attach (on Linux both read CLOCK_MONOTONIC, so it is ~0, but
the bridge does not rely on that).
"""

from __future__ import annotations

import struct
import threading
import time
import weakref
from typing import Any, Dict, List, Optional, Tuple

from . import mca

mca.register("trace_ring_capacity", 1 << 16,
             "Events per in-lane trace ring (native/src/ptrace_ring.h); "
             "overflow drops events and bumps trace.events_dropped "
             "instead of blocking the lane", type=int)
mca.register("trace_rings", 16,
             "Per-engine worker ring count for in-lane tracing (one ring "
             "is claimed per concurrent engine call)", type=int)

#: the ring event record (ptrace_ring.h Event): t_ns, id, key, flags
_EVENT_FMT = "<qqII"
EVENT_SIZE = struct.calcsize(_EVENT_FMT)

# native key -> PBP keyword name per lane kind (must mirror the EV_*
# constants exported by the extension modules)
NATIVE_KEYWORDS: Dict[str, Dict[int, str]] = {
    "ptexec": {1: "ptexec::task", 2: "ptexec::dispatch",
               # fused-region body intervals (ISSUE 12): merged Perfetto
               # timelines separate regions from per-task seams
               3: "ptexec::region"},
    "ptdtd": {1: "ptdtd::link", 2: "ptdtd::exec", 3: "ptdtd::task"},
    # the comm lane's EV_COMM_* points (native/src/ptcomm.cpp): one
    # per-rank progress-thread stream, so compute/comm overlap is
    # measurable in the same Perfetto view as the execution lanes
    "ptcomm": {1: "ptcomm::act_tx", 2: "ptcomm::act_rx",
               3: "ptcomm::data_tx", 4: "ptcomm::data_rx",
               5: "ptcomm::rdv_get", 6: "ptcomm::rdv_rep",
               # flow identity points (ISSUE 8): id = (peer << 40) | seq
               # of one K_ACTS frame; merge_traces pairs frame_tx on the
               # sender with frame_rx on the receiver into Perfetto flow
               # arrows, one causal edge per cross-rank activation frame
               7: "ptcomm::frame_tx", 8: "ptcomm::frame_rx",
               # serving-fabric credit flow (ISSUE 11): one POINT per
               # K_CRED frame each way, id = credit count (returns
               # negative) — admission-control traffic pairs with the
               # ACT/DATA frames it gates in the merged timeline
               9: "ptfab::cred_tx", 10: "ptfab::cred_rx"},
    # the device lane's manager-thread events (native/src/ptdev.cpp):
    # dispatch batches as intervals, per-task retirements as points —
    # device occupancy/overlap in the same Perfetto view as the engines
    # (`ptdev-w*` streams; one ring, the manager is a single thread)
    "ptdev": {1: "ptdev::dispatch", 2: "ptdev::retire"},
}

#: live bridges, for the process-wide drop/landed samplers
_bridges: "weakref.WeakSet[NativeTraceBridge]" = weakref.WeakSet()


def total_dropped() -> int:
    """Events lost to ring overflow across every live bridge (the
    ``trace.events_dropped`` counter sampler)."""
    return sum(b.dropped() for b in list(_bridges))


def total_landed() -> int:
    """Events landed into profiling streams across every live bridge."""
    return sum(b.events_landed for b in list(_bridges))


class NativeDrainMarker:
    """The coarse PINS payload fired once per drain (a batch marker, not
    a task): ``lane`` names the engine kind, ``n_events`` counts what the
    drain landed. Fired through SCHEDULE_BEGIN/END *and* COMPLETE_EXEC_END
    so payload-agnostic consumers (``install_scheduler_counters``, ALPerf)
    see one balanced enabled/retired tick per drain — canonical gauges
    like ``scheduler.pending_tasks`` cannot drift from markers alone."""

    __slots__ = ("lane", "n_events")

    def __init__(self, lane: str, n_events: int) -> None:
        self.lane = lane
        self.n_events = n_events

    def __repr__(self) -> str:  # pragma: no cover
        return f"<native-drain {self.lane}: {self.n_events} events>"


class _Target:
    __slots__ = ("kind", "obj", "tpid", "offset")

    def __init__(self, kind: str, obj: Any, tpid: int, offset: float) -> None:
        self.kind = kind
        self.obj = obj          # strong ref; detach() drops it
        self.tpid = tpid
        self.offset = offset    # perf_counter seconds - monotonic_ns * 1e-9


class NativeTraceBridge:
    """Owns the ring lifecycle for one context's native engines:
    enable at attach → record in-lane → drain (starvation hook +
    quiescence points) → land into the PBP dictionary/streams.

    ``profiling`` may be None (PINS-only instrumentation, no tracer
    attached): the lanes still stay engaged and the bridge runs in
    marker-only mode — rings are drained and counted but discarded, and
    the coarse :class:`NativeDrainMarker` PINS events are the whole
    signal (``--mca pins_paranoid 1`` buys back per-task fidelity)."""

    def __init__(self, profiling, pins=None) -> None:
        self.prof = profiling
        self.pins = pins
        self._targets: List[_Target] = []
        self._dropped_detached = 0   # keep detached lanes' drop accounting
        self._streams: Dict[Tuple[str, int], Any] = {}
        self._keys: Dict[Tuple[str, int], Tuple[int, int]] = {}
        self.events_landed = 0
        # drains run from EVERY worker stream's hot loop (context drain
        # hooks) plus quiescence points: one lock serializes the
        # stream/keyword caches, target list edits, and the landing
        # appends (two unserialized drains could mint duplicate
        # `ptexec-w0` streams, splitting START/END pairs across them)
        self._mu = threading.Lock()
        _bridges.add(self)

    # ------------------------------------------------------------ lifecycle
    def attach(self, kind: str, obj: Any, tpid: int = 0) -> bool:
        """Arm ``obj``'s in-lane rings and start landing its events.
        Idempotent per object; returns False when the object predates
        in-lane tracing (older extension build)."""
        if not hasattr(obj, "trace_enable"):
            return False
        with self._mu:
            for t in self._targets:
                if t.obj is obj:
                    return True
            obj.trace_enable(mca.get("trace_rings", 16),
                             mca.get("trace_ring_capacity", 1 << 16))
            # clock calibration: sample both clocks back to back
            offset = time.perf_counter() - obj.monotonic_ns() * 1e-9
            self._targets.append(_Target(kind, obj, tpid, offset))
        return True

    def detach(self, obj: Any) -> None:
        """Final-drain ``obj`` and stop holding it (a finished pool's
        graph — and its ring storage — must not be pinned by the tracer).
        Its cumulative drop count is snapshotted into the bridge so it
        stays visible through :meth:`dropped`."""
        fired = []
        with self._mu:
            for t in list(self._targets):
                if t.obj is obj:
                    fired.append((t.kind, self._drain_target(t)))
                    self._targets.remove(t)
                    try:
                        self._dropped_detached += t.obj.trace_dropped()
                    except Exception:  # noqa: BLE001 — accounting only
                        pass
        self._fire_markers(fired)

    # --------------------------------------------------------------- drain
    def drain_all(self, wait: bool = False) -> int:
        """Land every target's pending ring events; returns the event
        count. Registered as a context drain hook, so it runs at progress
        -loop start and whenever a stream starves — plus explicitly at
        pool quiescence (compiler/dtd retire paths) and fini, which pass
        ``wait=True`` so the final drain cannot be skipped."""
        # non-blocking from the hot loops: when another worker is already
        # mid-drain the events are in good hands — skip, don't stall
        if not self._mu.acquire(blocking=wait):
            return 0
        try:
            fired = [(t.kind, self._drain_target(t)) for t in self._targets]
        finally:
            self._mu.release()
        self._fire_markers(fired)
        return sum(n for _, n in fired)

    def dropped(self) -> int:
        with self._mu:
            return self._dropped_detached + sum(t.obj.trace_dropped()
                                                for t in self._targets)

    # ------------------------------------------------------------ internals
    def _key_for(self, kind: str, key: int) -> Optional[Tuple[int, int]]:
        ks = self._keys.get((kind, key))
        if ks is None:
            name = NATIVE_KEYWORDS.get(kind, {}).get(key)
            if name is None:
                return None       # unknown key: a newer engine — skip
            ks = self.prof.add_dictionary_keyword(name)
            self._keys[(kind, key)] = ks
        return ks

    def _stream_for(self, kind: str, ring: int):
        s = self._streams.get((kind, ring))
        if s is None:
            s = self.prof.stream(f"{kind}-w{ring}")
            self._streams[(kind, ring)] = s
        return s

    def _drain_target(self, t: _Target) -> int:
        try:
            pending = t.obj.trace_drain()
        except Exception:  # noqa: BLE001 — tracing must never kill the lane
            return 0
        if not pending:
            return 0
        n = 0
        if self.prof is None:
            # marker-only mode (PINS without a tracer): consume and count
            # the rings so drop accounting stays live, land nothing
            n = sum(len(blob) // EVENT_SIZE for _, blob in pending)
        else:
            # taskpool-tagged event ids: two pools' task #k must not pair
            # against each other in one per-worker stream
            eid_base = t.tpid << 40
            for ring, blob in pending:
                stream = self._stream_for(t.kind, ring)
                append = stream.events.append
                for t_ns, eid, key, flags in struct.iter_unpack(_EVENT_FMT,
                                                                blob):
                    ks = self._key_for(t.kind, key)
                    if ks is None:
                        continue
                    pbp_key = ks[1] if flags == 0x2 else ks[0]
                    append((pbp_key, eid_base + eid, t.tpid,
                            t_ns * 1e-9 + t.offset, flags, b""))
                    n += 1
            self.events_landed += n
        return n

    def _fire_markers(self, fired: List[Tuple[str, int]]) -> None:
        """Coarse per-drain batch markers for pins_modules consumers —
        fired OUTSIDE the bridge lock (a callback may read back
        :meth:`dropped`). SCHEDULE-shaped, with one matching COMPLETE
        tick so the canonical enabled/retired counters stay balanced;
        per-task fidelity needs --mca pins_paranoid 1."""
        if self.pins is None or not self.pins.enabled:
            return
        from ..core import pins as P
        for kind, n in fired:
            if not n:
                continue
            marker = NativeDrainMarker(kind, n)
            self.pins.fire(P.SCHEDULE_BEGIN, None, marker)
            self.pins.fire(P.SCHEDULE_END, None, marker)
            self.pins.fire(P.COMPLETE_EXEC_END, None, marker)
