"""Profiling: per-stream event buffers → binary trace files.

Re-design of parsec/profiling.{c,h} + the dbp binary format
(parsec/parsec_binary_profile.h): events are (key, event_id, taskpool_id,
timestamp, flags, optional typed info blob) recorded into per-stream buffers
with a process-wide **dictionary** of keywords; begin/end pairs share a key
with the low bit distinguishing START/END (ref: KEY_START/KEY_END macros).
Files carry a header, the dictionary, then per-stream event blocks — the
"PBP" (parsec-tpu binary profile) format, read back by
:mod:`parsec_tpu.tools.trace_reader` (the PBT→PTT pandas pipeline role).

Info blobs are described by a struct-format string in the dictionary entry
(e.g. ``"src{i};dst{i};size{q}"`` — the reference uses the same idea with C
type names, remote_dep_mpi.c:1286-1302).

GPU/TPU note: device streams get their own profiling streams like the
reference's per-GPU-stream profiling (profiling.h:146-440); XLA-level kernel
timing belongs to jax.profiler (the swap for profiling_nvtx named in
BASELINE.json's north star) — this module covers the runtime-event layer.
"""

from __future__ import annotations

import io
import struct
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from . import mca, output

mca.register("profile_enabled", False, "Record runtime events", type=bool)
mca.register("profile_filename", "parsec_tpu.pbp", "Trace output path")
mca.register("profile_backend", "pbp",
             "Trace output format: 'pbp' (flat binary file) or 'ptf2' "
             "(archive directory following OTF2's architecture: anchor + "
             "global defs + per-location event files — a PRIVATE format, "
             "not OTF2 interchange; the profiling_otf2.c role). 'otf2' is "
             "accepted as a deprecated alias and warns.", type=str)

MAGIC = b"PTPBP001"

EVENT_FLAG_START = 0x1
EVENT_FLAG_END = 0x2
EVENT_FLAG_POINT = 0x4

_INFO_TYPES = {"i": "i", "q": "q", "d": "d", "f": "f"}


def parse_info_desc(desc: str) -> Tuple[List[Tuple[str, str]], str]:
    """``"src{i};dst{i};size{q}"`` -> ([(name, code)...], struct_fmt)."""
    fields: List[Tuple[str, str]] = []
    fmt = "<"
    if desc:
        for part in desc.split(";"):
            part = part.strip()
            if not part:
                continue
            name, _, ty = part.partition("{")
            ty = ty.rstrip("}")
            if ty not in _INFO_TYPES:
                raise ValueError(f"unsupported info type {ty!r} in {desc!r}")
            fields.append((name, ty))
            fmt += _INFO_TYPES[ty]
    return fields, fmt


@dataclass
class DictEntry:
    """One dictionary keyword (ref: dbp dictionary entries)."""
    key: int
    name: str
    attr: str = ""          # color attribute in the reference
    info_desc: str = ""     # struct descriptor for the info blob
    fields: List[Tuple[str, str]] = field(default_factory=list)
    fmt: str = "<"


class ProfilingStream:
    """Per-thread/per-device-stream event buffer (ref: per-ES buffers)."""

    __slots__ = ("name", "stream_id", "events")

    def __init__(self, name: str, stream_id: int) -> None:
        self.name = name
        self.stream_id = stream_id
        self.events: List[Tuple[int, int, int, float, int, bytes]] = []

    def trace(self, key: int, event_id: int, taskpool_id: int,
              flags: int, info: bytes = b"") -> None:
        """parsec_profiling_trace_flags equivalent."""
        self.events.append((key, event_id, taskpool_id,
                            time.perf_counter(), flags, info))


class Profiling:
    """Process-wide tracer (ref: parsec_profiling_init / dbp_start)."""

    def __init__(self) -> None:
        self._dict: Dict[str, DictEntry] = {}
        self._streams: List[ProfilingStream] = []
        self._lock = threading.Lock()
        self._next_key = 0
        self.t0 = time.perf_counter()
        self.enabled = True

    # -- dictionary -----------------------------------------------------------
    def add_dictionary_keyword(self, name: str, attr: str = "",
                               info_desc: str = "") -> Tuple[int, int]:
        """Returns (start_key, end_key) like the reference
        (parsec_profiling_add_dictionary_keyword)."""
        with self._lock:
            e = self._dict.get(name)
            if e is None:
                fields, fmt = parse_info_desc(info_desc)
                e = DictEntry(self._next_key, name, attr, info_desc, fields, fmt)
                self._dict[name] = e
                self._next_key += 1
        return (e.key << 1) | 0, (e.key << 1) | 1

    def keyword(self, name: str) -> Optional[DictEntry]:
        return self._dict.get(name)

    # -- streams ---------------------------------------------------------------
    def stream(self, name: str) -> ProfilingStream:
        """parsec_profiling_stream_init: one buffer per thread/device stream."""
        with self._lock:
            s = ProfilingStream(name, len(self._streams))
            self._streams.append(s)
            return s

    def pack_info(self, keyword: str, **kw) -> bytes:
        e = self._dict[keyword]
        if not e.fields:
            return b""
        return struct.pack(e.fmt, *[kw.get(n, 0) for n, _ in e.fields])

    # -- output ------------------------------------------------------------------
    def dump(self, path: Optional[str] = None,
             backend: Optional[str] = None) -> str:
        """Write the trace (ref: dbp file writing at parsec_fini). The
        backend — flat PBP file or PTF2 archive (OTF2-architecture,
        private format) — is chosen by
        ``backend`` / ``--mca profile_backend`` (profiling_otf2.c role)."""
        path = path or mca.get("profile_filename", "parsec_tpu.pbp")
        backend = backend or mca.get("profile_backend", "pbp")
        if backend == "otf2":
            output.warning(
                "profile_backend 'otf2' is a deprecated alias for 'ptf2' — "
                "the archive follows OTF2's architecture but is NOT "
                "readable by OTF2 tools (use tools/trace_reader)")
            backend = "ptf2"
        if backend == "ptf2":
            from .trace_ptf2 import write_archive
            return write_archive(self, path)
        if backend != "pbp":
            raise ValueError(f"unknown profile_backend {backend!r}")
        with self._lock:
            buf = io.BytesIO()
            buf.write(MAGIC)
            buf.write(struct.pack("<dII", self.t0, len(self._dict),
                                  len(self._streams)))
            for e in sorted(self._dict.values(), key=lambda e: e.key):
                for text in (e.name, e.attr, e.info_desc):
                    raw = text.encode()
                    buf.write(struct.pack("<I", len(raw)))
                    buf.write(raw)
            for s in self._streams:
                raw = s.name.encode()
                buf.write(struct.pack("<I", len(raw)))
                buf.write(raw)
                buf.write(struct.pack("<I", len(s.events)))
                for key, eid, tpid, t, flags, info in s.events:
                    buf.write(struct.pack("<IqIdII", key, eid, tpid, t, flags,
                                          len(info)))
                    buf.write(info)
            data = buf.getvalue()
        with open(path, "wb") as f:
            f.write(data)
        output.debug_verbose(1, "profiling", f"trace written to {path}")
        return path

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {
                "streams": len(self._streams),
                "keywords": len(self._dict),
                "events": sum(len(s.events) for s in self._streams),
            }
