"""Properties dictionary + software-defined counter export.

Re-design of parsec/dictionary.c (live properties registry) and
parsec/papi_sde.c (PAPI software-defined events exposing runtime counters —
pending tasks, tasks enabled, tasks retired; scheduling.c:330-337,491).
Counters register once and are sampled on read; an aggregation hook serves
the live-visualization role of tools/aggregator_visu.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, List, Optional, Union

Sampler = Callable[[], Union[int, float]]

# canonical counter names (ref: PAPI_SDE parsec::SCHEDULER::PENDING_TASKS etc.)
PENDING_TASKS = "scheduler.pending_tasks"
TASKS_ENABLED = "scheduler.tasks_enabled"
TASKS_RETIRED = "scheduler.tasks_retired"


class LaneStats(dict):
    """Engagement-counter dict for the native lanes (PTEXEC_STATS /
    PTDTD_STATS) with proper lifecycle helpers, so CI gates and tests
    stop hand-poking raw keys. Still a plain dict underneath — the hot
    paths keep their ``stats[key] += 1`` shape."""

    def snapshot(self) -> Dict[str, Union[int, float]]:
        """A point-in-time copy (compare with :meth:`delta`)."""
        return dict(self)

    def reset(self) -> None:
        """Zero every counter (bench/test isolation)."""
        for k in self:
            self[k] = 0

    def delta(self, since: Dict[str, Union[int, float]]) -> Dict[str, int]:
        """Per-key change since a :meth:`snapshot`."""
        return {k: self[k] - since.get(k, 0) for k in self}


class CounterRegistry:
    """Process-wide named counters: either atomic accumulators or samplers."""

    def __init__(self) -> None:
        self._acc: Dict[str, float] = {}
        self._samplers: Dict[str, Sampler] = {}
        self._lock = threading.Lock()

    def register(self, name: str, sampler: Optional[Sampler] = None) -> None:
        with self._lock:
            if sampler is not None:
                self._samplers[name] = sampler
            else:
                self._acc.setdefault(name, 0)

    def add(self, name: str, v: Union[int, float] = 1) -> None:
        with self._lock:
            self._acc[name] = self._acc.get(name, 0) + v

    def set(self, name: str, v: Union[int, float]) -> None:
        with self._lock:
            self._acc[name] = v

    def read(self, name: str) -> Union[int, float]:
        s = self._samplers.get(name)
        if s is not None:
            return s()
        with self._lock:
            return self._acc.get(name, 0)

    def snapshot(self, skip: Optional[Callable[[str], bool]] = None
                 ) -> Dict[str, Union[int, float]]:
        """All counters at once (the aggregator_visu export). ``skip``
        filters keys BEFORE their samplers run — a sweeper that doesn't
        want a family of derived gauges (pttel skips ``*.hist.*``) must
        not pay for computing them."""
        out: Dict[str, Union[int, float]] = {}
        with self._lock:
            out.update(self._acc)
            samplers = dict(self._samplers)
        if skip is not None:
            for name in [n for n in out if skip(n)]:
                del out[name]
        for name, s in samplers.items():
            if skip is not None and skip(name):
                continue
            try:
                out[name] = s()
            except Exception:  # noqa: BLE001 - sampling must never break
                out[name] = float("nan")
        return out


counters = CounterRegistry()

# canonical native-lane counter names (the SDE-style export of the lane
# engagement/tracing state; see install_native_counters)
PTEXEC_POOLS_ENGAGED = "ptexec.pools_engaged"
PTDTD_TASKS_BATCHED = "ptdtd.tasks_batched"
TRACE_EVENTS_DROPPED = "trace.events_dropped"
TRACE_EVENTS_NATIVE = "trace.events_native"
PTEXEC_SLOTS_RETIRED = "ptexec.slots_retired"


def install_native_counters() -> None:
    """Register the native lanes' engagement stats, the lane-side
    datarepo retire counter, and the in-lane trace drop/landed counters
    as samplers under canonical names (``ptexec.*``, ``ptdtd.*``,
    ``trace.*``) so :mod:`parsec_tpu.tools.live_view` and the SDE-style
    snapshot export see the lanes. Idempotent."""
    from ..comm import native as _cnative        # lazy: avoid import cycles
    from ..comm import pttel as _tel
    from ..core import costmodel as _cm
    from ..core import sched_plane as _sp
    from ..core import watchdog as _wd
    from ..device import native as _dnative
    from ..dsl import dtd as _dtd
    from ..dsl import fusion as _fus
    from ..dsl.ptg import compiler as _ptg
    from ..serving import fabric as _fab
    from ..serving import reconcile as _rec
    from ..tools import flight as _fl
    from . import native_trace as _nt
    from .hist import install_hist_counters

    def _sampler(stats, key):
        return lambda: stats[key]

    for stats, prefix in ((_ptg.PTEXEC_STATS, "ptexec"),
                          (_dtd.PTDTD_STATS, "ptdtd"),
                          (_cnative.PTCOMM_STATS, "ptcomm"),
                          (_dnative.PTDEV_STATS, "ptdev"),
                          (_fab.FAB_STATS, "ptfab"),
                          (_sp.SCHED_STATS, "sched"),
                          # the persistent executable cache (ISSUE 12):
                          # capture.cache_{hits,misses,evictions} — the
                          # warm-pool contract on /metrics
                          (_fus.CAPTURE_CACHE_STATS, "capture"),
                          # the online cost models (ISSUE 18):
                          # costmodel.{keys,folds,decisions,decision_ns,
                          # placements_diverged,...} — the adaptive-
                          # engagement truth the ci gate asserts
                          (_cm.COSTMODEL_STATS, "costmodel"),
                          # the mesh telemetry plane (ISSUE 20):
                          # pttel.{rounds,frames_tx,frames_rx,folds,...}
                          # — the O(log P) frame contract on /metrics
                          (_tel.TEL_STATS, "pttel"),
                          # the lane stall watchdog + flight recorder +
                          # push-mode reconciler (ISSUE 20)
                          (_wd.WATCHDOG_STATS, "watchdog"),
                          (_fl.FLIGHT_STATS, "flight"),
                          (_rec.RECONCILE_STATS, "reconcile")):
        for key in stats:
            counters.register(f"{prefix}.{key}", sampler=_sampler(stats, key))
    # the comm lane's C-side wire counters (summed across live lanes)
    for key in _cnative.COMM_COUNTER_KEYS:
        counters.register(f"ptcomm.{key}",
                          sampler=_cnative.comm_counter_sampler(key))
    # the device lane's C-side counters: dispatch/retire/overlap splits
    # from the Lane, residency/eviction/stage-in from the CohTable —
    # ISSUE 10's "device occupancy shows up on /metrics"
    for key in _dnative.DEV_COUNTER_KEYS:
        counters.register(f"ptdev.{key}",
                          sampler=_dnative.dev_counter_sampler(key))
    for key in _dnative.COH_COUNTER_KEYS:
        counters.register(f"ptdev.{key}",
                          sampler=_dnative.coh_counter_sampler(key))
    # the scheduler plane's C-side counters (summed across live planes):
    # steals, spills, served, queued, admission stalls — ISSUE 9
    for key in _sp.PLANE_COUNTER_KEYS:
        counters.register(f"sched.{key}",
                          sampler=_sp.plane_counter_sampler(key))
    # the serving fabric's wire counters (credit grants/spends/reclaims
    # summed across live fabrics) — ISSUE 11's "credit flow shows up on
    # /metrics"; ptfab.served.<tenant> registers per served tenant
    for name, ckey in _fab.FAB_WIRE_KEYS.items():
        counters.register(f"ptfab.{name}",
                          sampler=_fab.fab_wire_sampler(ckey))
    counters.register(TRACE_EVENTS_DROPPED, sampler=_nt.total_dropped)
    counters.register(TRACE_EVENTS_NATIVE, sampler=_nt.total_landed)
    counters.register(PTEXEC_SLOTS_RETIRED)   # accumulator: lane finalize adds
    # latency percentiles (<kind>.hist.<name>.p99_us etc. — ISSUE 8)
    install_hist_counters()


def install_scheduler_counters(context) -> None:
    """Wire the canonical scheduler counters onto a context via PINS."""
    from ..core import pins as P

    counters.register(TASKS_ENABLED)
    counters.register(TASKS_RETIRED)
    counters.register(PENDING_TASKS, sampler=lambda: (
        counters.read(TASKS_ENABLED) - counters.read(TASKS_RETIRED)))

    def on_sched(stream, tasks, extra) -> None:
        counters.add(TASKS_ENABLED, len(tasks) if isinstance(tasks, list) else 1)

    def on_complete(stream, task, extra) -> None:
        counters.add(TASKS_RETIRED, 1)

    context.pins.register(P.SCHEDULE_END, on_sched)
    context.pins.register(P.COMPLETE_EXEC_END, on_complete)
