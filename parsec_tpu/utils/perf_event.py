"""Linux hardware performance counters via raw perf_event_open.

The PAPI role (ref: parsec/mca/pins/papi/ — the reference samples PMU
counters around task lifecycle events through libpapi). No PAPI exists in
this stack, so the syscall is issued directly through ctypes: self-process,
user-space-only counting needs no privileges at perf_event_paranoid <= 2.

Degrades gracefully everywhere it cannot work (seccomp-filtered
containers, non-Linux, PMU-less VMs): :func:`available` probes once and
the PINS module becomes a no-op, mirroring how the reference builds the
papi module only when libpapi is found (CMake feature probe).
"""

from __future__ import annotations

import ctypes
import os
import struct
import threading
from typing import Dict, Optional, Sequence, Tuple

_SYS_perf_event_open = {"x86_64": 298, "aarch64": 241}.get(os.uname().machine)

_PERF_TYPE_HARDWARE = 0
#: PERF_COUNT_HW_* ids (linux/perf_event.h)
EVENTS: Dict[str, int] = {
    "cycles": 0,
    "instructions": 1,
    "cache_references": 2,
    "cache_misses": 3,
    "branch_instructions": 4,
    "branch_misses": 5,
}

# ioctls (linux/perf_event.h): _IO('$', 0..2)
_PERF_IOC_ENABLE = 0x2400
_PERF_IOC_DISABLE = 0x2401
_PERF_IOC_RESET = 0x2403

_ATTR_SIZE = 128          # PERF_ATTR_SIZE_VER7


def _attr_bytes(config: int) -> bytes:
    """A perf_event_attr for plain counting: disabled at open,
    exclude_kernel | exclude_hv (bits 5 and 6 of the flags word)."""
    flags = (1 << 0) | (1 << 5) | (1 << 6)    # disabled, excl_kernel, excl_hv
    return struct.pack(
        "IIQQQQ",
        _PERF_TYPE_HARDWARE,   # type
        _ATTR_SIZE,            # size
        config,                # config
        0,                     # sample_period/freq
        0,                     # sample_type
        0,                     # read_format
    ) + struct.pack("Q", flags) + b"\x00" * (_ATTR_SIZE - 48)


_libc = None


def _open_event(config: int) -> int:
    """fd for a self-process, any-cpu counter; raises OSError."""
    global _libc
    if _SYS_perf_event_open is None:
        raise OSError("unsupported architecture for perf_event_open")
    if _libc is None:
        _libc = ctypes.CDLL(None, use_errno=True)
    buf = ctypes.create_string_buffer(_attr_bytes(config), _ATTR_SIZE)
    fd = _libc.syscall(_SYS_perf_event_open, buf, 0, -1, -1, 0)
    if fd < 0:
        e = ctypes.get_errno()
        raise OSError(e, f"perf_event_open failed: {os.strerror(e)}")
    return fd


class HWCounterSet:
    """A group of hardware counters read together.

    >>> hw = HWCounterSet(("cycles", "instructions"))
    >>> hw.start(); ...work...; delta = hw.read()
    """

    def __init__(self, events: Sequence[str] = ("cycles", "instructions")):
        self.events: Tuple[str, ...] = tuple(events)
        self._fds = []
        try:
            for name in self.events:
                self._fds.append(_open_event(EVENTS[name]))
        except OSError:
            self.close()
            raise
        self._lock = threading.Lock()

    def start(self) -> None:
        import fcntl
        for fd in self._fds:
            fcntl.ioctl(fd, _PERF_IOC_RESET, 0)
            fcntl.ioctl(fd, _PERF_IOC_ENABLE, 0)

    def read(self) -> Dict[str, int]:
        out = {}
        for name, fd in zip(self.events, self._fds):
            out[name] = struct.unpack("q", os.read(fd, 8))[0]
        return out

    def stop(self) -> Dict[str, int]:
        import fcntl
        vals = self.read()
        for fd in self._fds:
            fcntl.ioctl(fd, _PERF_IOC_DISABLE, 0)
        return vals

    def close(self) -> None:
        for fd in self._fds:
            try:
                os.close(fd)
            except OSError:
                pass
        self._fds = []

    def __del__(self):  # pragma: no cover - interpreter shutdown
        self.close()


_avail: Optional[bool] = None


def available() -> bool:
    """One cached probe: can this process count its own cycles?"""
    global _avail
    if _avail is None:
        try:
            hw = HWCounterSet(("cycles",))
            hw.start()
            hw.stop()
            hw.close()
            _avail = True
        except Exception:  # noqa: BLE001 — seccomp/EPERM/ENOENT/arch
            _avail = False
    return _avail
