"""Native latency histograms: bucket math, summarization, process registry.

The Python half of ``native/src/pthist.h`` (ISSUE 8): the lanes record
fixed-bucket log2 (HdrHistogram-style) latency distributions with relaxed
atomics — task execute latency and ready-queue wait in ``ptexec``/
``ptdtd``, rendezvous round-trip and send-queue lag in ``ptcomm``. This
module mirrors the bucket scheme, sums snapshots across every live lane
object (plus lanes that already finished — their buckets are accumulated
at detach, like the trace bridge's drop accounting), and summarizes
p50/p99/p999 for the counter registry, ``live_view``, and the
``/metrics`` endpoint (tools/metrics_server.py).

Bucket scheme (must mirror pthist.h): values < 8 ns map exactly to
buckets 0..7; above that the index is ``(exponent, top-3-mantissa-bits)``
— 8 sub-buckets per power of two, ~12.5% relative resolution, 496
buckets total. Percentiles report the bucket midpoint, so their error is
bounded by half a bucket width (~6%).

Cost contract: recording is gated exactly like the PR 5 rings (one
predictable null branch per site when off) and the armed cost is
amortized/sampled in the hot lanes; ``bench.py`` asserts
``hist_overhead_pct_native < 2`` on the chain bench.
"""

from __future__ import annotations

import struct
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from . import mca

mca.register("hist_enabled", False,
             "Arm the native lanes' latency histograms "
             "(ptexec/ptdtd/ptcomm; native/src/pthist.h). Implied by an "
             "active metrics endpoint (--mca metrics_port / metrics_uds) "
             "so /metrics always serves live percentiles", type=bool)

SUB_BITS = 3
SUBS = 1 << SUB_BITS
NBUCKETS = (64 - SUB_BITS + 1) * SUBS          # 496, mirrors pthist.h
_BUCKET_FMT = f"<{NBUCKETS}Q"

#: the histogram names each lane kind exports (hist_snapshot() keys)
HIST_NAMES: Dict[str, Tuple[str, ...]] = {
    "ptexec": ("exec_ns", "ready_wait_ns"),
    "ptdtd": ("exec_ns", "ready_wait_ns"),
    "ptcomm": ("rdv_rtt_ns", "act_queue_ns"),
    "sched": ("queue_ns",),     # plane push->pop wait (ISSUE 9)
}


def bucket_index(v: int) -> int:
    """Mirror of pthist.h bucket_of() — tested against the C constants."""
    if v < 0:
        v = 0
    if v < SUBS:
        return v
    e = v.bit_length() - 1
    idx = ((e - SUB_BITS + 1) << SUB_BITS) | ((v >> (e - SUB_BITS)) & (SUBS - 1))
    return min(idx, NBUCKETS - 1)


def bucket_lo(i: int) -> int:
    """Smallest value (ns) mapping to bucket ``i``."""
    if i < SUBS:
        return i
    e, m = divmod(i, SUBS)
    return (SUBS + m) << (e - 1)


def bucket_width(i: int) -> int:
    return 1 if i < SUBS else 1 << (i // SUBS - 1)


def bucket_mid(i: int) -> float:
    """The representative value reported for bucket ``i`` (midpoint)."""
    return bucket_lo(i) + bucket_width(i) / 2.0


def decode_buckets(raw: bytes) -> List[int]:
    """The ``hist_snapshot()`` bytes blob -> per-bucket counts."""
    return list(struct.unpack(_BUCKET_FMT, raw))


def percentile(buckets: List[int], q: float,
               total: Optional[int] = None) -> float:
    """The q-quantile (0 < q <= 1) in ns, bucket-midpoint resolution.
    Returns 0.0 for an empty histogram. ``total`` is clamped to the
    bucket mass: a live snapshot copies buckets before the count, so a
    concurrent bump can make the counter exceed the copied cells — an
    unclamped target would then walk off the end and report the top log2
    bucket (~1.7e19 ns) as p999."""
    bsum = sum(buckets)
    total = bsum if total is None else min(total, bsum)
    if total <= 0:
        return 0.0
    target = q * total
    acc = 0
    for i, c in enumerate(buckets):
        acc += c
        if acc >= target:
            return bucket_mid(i)
    return _max_bucket(buckets)


def summarize(buckets: List[int], count: int, sum_ns: int) -> Dict[str, float]:
    """The percentile summary served by /metrics and the counter
    registry (µs — latency numbers humans read)."""
    return {
        "count": count,
        "mean_us": (sum_ns / count / 1e3) if count else 0.0,
        "p50_us": percentile(buckets, 0.50, count) / 1e3,
        "p99_us": percentile(buckets, 0.99, count) / 1e3,
        "p999_us": percentile(buckets, 0.999, count) / 1e3,
        "max_us": _max_bucket(buckets) / 1e3,
    }


def _max_bucket(buckets: List[int]) -> float:
    for i in range(NBUCKETS - 1, -1, -1):
        if buckets[i]:
            return bucket_mid(i)
    return 0.0


class NativeHistograms:
    """Process-wide registry of armed native histogram objects, the
    ``utils/native_trace`` shape: live objects are held strongly for the
    attach window (the C extension types expose no weakrefs) and
    :meth:`detach` — called from the same lifecycle points as the trace
    bridge's detach, so a finished pool's graph is never pinned — folds
    the object's buckets into a per-kind accumulator so /metrics keeps
    reporting completed work."""

    def __init__(self) -> None:
        self._mu = threading.Lock()
        # kind -> list of live armed objects (strong refs; see detach)
        self._objs: Dict[str, List[Any]] = {}
        # kind -> name -> [count, sum, buckets] accumulated from detaches
        self._done: Dict[str, Dict[str, list]] = {}
        self._cache: Tuple[float, Optional[Dict[str, Any]]] = (0.0, None)

    # ----------------------------------------------------------- lifecycle
    def attach(self, kind: str, obj: Any) -> bool:
        """Arm ``obj``'s native histograms and track it. Idempotent;
        False when the object predates histograms (older extension)."""
        if not hasattr(obj, "hist_enable"):
            return False
        with self._mu:
            objs = self._objs.setdefault(kind, [])
            if not any(o is obj for o in objs):
                obj.hist_enable()
                objs.append(obj)
            self._cache = (0.0, None)
        return True

    def detach(self, obj: Any) -> None:
        """Fold a finishing object's buckets into the accumulator and
        stop tracking it (its storage may be freed right after)."""
        with self._mu:
            for kind, objs in self._objs.items():
                for i, o in enumerate(objs):
                    if o is obj:
                        try:
                            self._fold_locked(kind, obj.hist_snapshot())
                        except Exception:  # noqa: BLE001 — accounting only
                            pass
                        del objs[i]
                        self._cache = (0.0, None)
                        return

    @staticmethod
    def _merge(acc: Dict[str, list], snap: Dict[str, tuple]) -> None:
        """Fold one ``hist_snapshot()`` result into ``acc`` (the single
        home of the count/sum/per-bucket merge invariant)."""
        for name, (count, sum_ns, raw) in snap.items():
            cur = acc.get(name)
            if cur is None:
                acc[name] = [count, sum_ns, decode_buckets(raw)]
            else:
                cur[0] += count
                cur[1] += sum_ns
                for i, c in enumerate(decode_buckets(raw)):
                    cur[2][i] += c

    def _fold_locked(self, kind: str, snap: Dict[str, tuple]) -> None:
        self._merge(self._done.setdefault(kind, {}), snap)

    # ----------------------------------------------------------- snapshots
    def snapshot(self) -> Dict[str, Dict[str, Any]]:
        """``{"<kind>.<hist>": {"count", "sum_ns", "buckets"}}`` summed
        over live + detached objects."""
        out: Dict[str, Dict[str, Any]] = {}
        with self._mu:
            per_kind: Dict[str, Dict[str, list]] = {}
            for kind, acc in self._done.items():
                per_kind[kind] = {n: [v[0], v[1], list(v[2])]
                                  for n, v in acc.items()}
            for kind, objs in self._objs.items():
                for obj in list(objs):
                    try:
                        snap = obj.hist_snapshot()
                    except Exception:  # noqa: BLE001 — torn-down object
                        continue
                    self._merge(per_kind.setdefault(kind, {}), snap)
        for kind, acc in per_kind.items():
            for name, (count, sum_ns, buckets) in acc.items():
                out[f"{kind}.{name}"] = {"count": count, "sum_ns": sum_ns,
                                         "buckets": buckets}
        return out

    def summaries(self, ttl: float = 0.05) -> Dict[str, Dict[str, float]]:
        """Percentile summaries per histogram, TTL-cached: one registry
        sweep samples many ``*.p99_us`` keys and must not pay one full
        bucket walk per key."""
        now = time.monotonic()
        stamp, cached = self._cache
        if cached is not None and now - stamp <= ttl:
            return cached
        out = {name: summarize(d["buckets"], d["count"], d["sum_ns"])
               for name, d in self.snapshot().items()}
        self._cache = (now, out)
        return out

    def reset(self) -> None:
        """Drop accumulated (detached) buckets — bench/test isolation.
        Live objects keep their counts (native buckets never reset)."""
        with self._mu:
            self._done.clear()
            self._cache = (0.0, None)


#: the process-wide registry (Context._hist_attach feeds it)
histograms = NativeHistograms()

_installed = False


def install_hist_counters() -> None:
    """Register ``<kind>.hist.<name>.{count,p50_us,p99_us,p999_us}``
    samplers in the unified counter registry, so live_view, the fini
    aggregation, and /metrics all see latency percentiles under
    canonical names. Idempotent."""
    global _installed
    if _installed:
        return
    from .counters import counters

    def _sampler(key: str, stat: str):
        def sample():
            s = histograms.summaries().get(key)
            return 0 if s is None else s[stat]
        return sample

    for kind, names in HIST_NAMES.items():
        for name in names:
            for stat in ("count", "p50_us", "p99_us", "p999_us"):
                counters.register(f"{kind}.hist.{name}.{stat}",
                                  sampler=_sampler(f"{kind}.{name}", stat))
    _installed = True
