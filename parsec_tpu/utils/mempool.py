"""Thread-affine object mempools.

Re-design of parsec/mempool.{c,h}: a :class:`Mempool` owns one freelist per
thread; elements remember the thread pool that constructed them and return
THERE on release, regardless of which thread releases — so steady-state
traffic between a producing thread and a consuming thread keeps each
thread's list populated without cross-thread allocation churn (the
reference's parsec_thread_mempool_t ownership protocol, mempool.h:60-104).

This replaces the earlier "GC-threshold stretch" as the ANSWER to the
reference's mempool component (VERDICT r4: 'capability argument, not a
mempool'): the GC stretch remains a complementary runtime knob
(runtime_gc_defer), while this is the actual allocator — construct-once,
reset-on-return, per-thread freelists, stats.

Under the GIL a deque append/pop is atomic, so the per-thread freelists
need no locks; the owner tag rides on the element (``_mp_owner`` slot or
attribute).
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Any, Callable, Dict, Optional


class _ThreadPool:
    """One thread's freelist (ref: parsec_thread_mempool_t)."""

    __slots__ = ("free", "constructed", "max_free", "thread_ref")

    def __init__(self, max_free: int) -> None:
        self.free: deque = deque()
        self.constructed = 0
        self.max_free = max_free
        import weakref
        self.thread_ref = weakref.ref(threading.current_thread())

    @property
    def dead(self) -> bool:
        t = self.thread_ref()
        return t is None or not t.is_alive()


class Mempool:
    """A typed object pool with thread-affine freelists.

    ``factory()`` builds a new element; ``reset(obj)`` (optional) scrubs a
    released element before it re-enters circulation. ``owner_attr`` names
    the slot/attribute used to tag ownership (the element type must allow
    setting it — add it to ``__slots__`` for slotted classes).
    """

    def __init__(self, factory: Callable[[], Any],
                 reset: Optional[Callable[[Any], None]] = None,
                 max_free_per_thread: int = 4096,
                 owner_attr: str = "_mp_owner") -> None:
        self.factory = factory
        self.reset = reset
        self.owner_attr = owner_attr
        self.max_free = max_free_per_thread
        self._tls = threading.local()
        self._pools: list = []          # every pool ever (pruned when dead
        self._pools_lock = threading.Lock()   # AND drained)

    def _my_pool(self) -> _ThreadPool:
        tp = getattr(self._tls, "pool", None)
        if tp is None:
            tp = _ThreadPool(self.max_free)
            self._tls.pool = tp
            with self._pools_lock:
                self._pools.append(tp)
        return tp

    def alloc(self) -> Any:
        """parsec_thread_mempool_allocate: pop my freelist; empty → adopt a
        DEAD thread's orphaned elements (the reference ties thread pools to
        runtime thread fini; short-lived threads here just leave their
        lists for the living); else construct."""
        tp = self._my_pool()
        try:
            return tp.free.pop()
        except IndexError:
            pass
        obj = self._adopt_orphan(tp)
        if obj is not None:
            return obj
        obj = self.factory()
        setattr(obj, self.owner_attr, tp)
        tp.constructed += 1
        return obj

    def _adopt_orphan(self, mine: _ThreadPool) -> Any:
        with self._pools_lock:
            for p in self._pools:
                if p is mine or not p.dead:
                    continue
                try:
                    obj = p.free.pop()
                except IndexError:
                    continue
                setattr(obj, self.owner_attr, mine)   # re-home
                mine.constructed += 1
                p.constructed = max(0, p.constructed - 1)
                return obj
            # prune pools that are dead AND drained
            self._pools = [p for p in self._pools
                           if not (p.dead and not p.free)]
        return None

    def release(self, obj: Any) -> None:
        """parsec_mempool_free: reset and return to the OWNER's freelist
        (deque.append is GIL-atomic, so cross-thread returns are safe). An
        owner whose thread died gets the element re-homed to the RELEASING
        thread instead of stranding it."""
        if self.reset is not None:
            self.reset(obj)
        tp = getattr(obj, self.owner_attr, None)
        if tp is None:
            return
        if tp.dead:
            old = tp
            tp = self._my_pool()
            setattr(obj, self.owner_attr, tp)
            old.constructed = max(0, old.constructed - 1)
            tp.constructed += 1         # re-homed: the count moves with it
        if len(tp.free) >= tp.max_free:
            tp.constructed = max(0, tp.constructed - 1)
            return                      # overflow: dropped to GC, uncounted
        tp.free.append(obj)

    def stats(self) -> Dict[str, int]:
        with self._pools_lock:
            pools = list(self._pools)
        return {
            "threads": len(pools),
            "constructed": sum(p.constructed for p in pools),
            "free": sum(len(p.free) for p in pools),
        }
