"""DAG grapher: emit DOT of the executed task graph.

Re-design of parsec/parsec_prof_grapher.c (enabled by ``--mca profile_dot``
in the reference, parsec.c:618): a PINS-driven recorder capturing every
task execution and every released dependency edge, dumped as GraphViz DOT.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Set, Tuple

from ..core import pins as P
from ..utils import mca

mca.register("profile_dot", "", "Write the executed DAG as DOT to this path")

_COLORS = ["#4c72b0", "#dd8452", "#55a868", "#c44e52", "#8172b3",
           "#937860", "#da8bc3", "#8c8c8c", "#ccb974", "#64b5cd"]


class DotGrapher:
    """Record executed tasks + dataflow edges; render DOT."""

    def __init__(self) -> None:
        self._nodes: Dict[str, Tuple[str, int]] = {}   # label -> (class, th)
        self._edges: Set[Tuple[str, str, str]] = set()
        self._lock = threading.Lock()

    def enable(self, context) -> None:
        self.context = context
        context.pins.register(P.EXEC_BEGIN, self._on_exec)
        context.pins.register(P.RELEASE_DEPS_BEGIN, self._on_release)

    def disable(self, context) -> None:
        context.pins.unregister(P.EXEC_BEGIN, self._on_exec)
        context.pins.unregister(P.RELEASE_DEPS_BEGIN, self._on_release)

    @staticmethod
    def _label(task) -> str:
        loc = "_".join(str(v) for v in task.locals.values())
        if not loc:
            # DTD tasks carry no named locals; their identity is the
            # insertion index
            ident = getattr(task, "ident", None)
            loc = str(ident) if ident is not None else ""
        return f"{task.task_class.name}_{loc}" if loc else task.task_class.name

    def _on_exec(self, stream, task, extra) -> None:
        with self._lock:
            self._nodes[self._label(task)] = (task.task_class.name,
                                              getattr(stream, "th_id", 0))

    def _on_release(self, stream, task, extra) -> None:
        src = self._label(task)
        tc = task.task_class
        # DTD tasks carry explicit successor lists; PTG tasks declarative deps
        succs = getattr(task, "successors", None)
        with self._lock:
            if succs:
                for s in succs:
                    self._edges.add((src, self._label(s), ""))
                return
            for flow in tc.flows:
                for dep in flow.deps_out:
                    if dep.task_class is None:
                        continue
                    if dep.cond is not None and not dep.cond(task.locals):
                        continue
                    targets = dep.target_locals(task.locals) if dep.target_locals \
                        else [task.locals]
                    if isinstance(targets, dict):
                        targets = [targets]
                    for tl in targets:
                        loc = "_".join(str(v) for v in tl.values())
                        dst = f"{dep.task_class.name}_{loc}" if loc else dep.task_class.name
                        self._edges.add((src, dst, flow.name))

    def to_dot(self, name: str = "parsec_tpu") -> str:
        with self._lock:
            classes = sorted({c for c, _ in self._nodes.values()})
            color = {c: _COLORS[i % len(_COLORS)] for i, c in enumerate(classes)}
            lines = [f"digraph {name} {{", "  rankdir=TB;",
                     "  node [style=filled, fontname=monospace];"]
            for label, (cls, th) in sorted(self._nodes.items()):
                lines.append(f'  "{label}" [fillcolor="{color[cls]}", '
                             f'tooltip="thread {th}"];')
            for src, dst, flow in sorted(self._edges):
                attr = f' [label="{flow}"]' if flow else ""
                lines.append(f'  "{src}" -> "{dst}"{attr};')
            lines.append("}")
            return "\n".join(lines)

    def dump(self, path: str) -> str:
        dot = self.to_dot()
        with open(path, "w") as f:
            f.write(dot)
        return path
