"""Compatibility shim: the second trace backend is named PTF2 (see
utils/trace_ptf2.py — a private format following OTF2's architecture, NOT
readable by OTF2 tools; the old module name oversold it)."""

from .trace_ptf2 import *                                    # noqa: F401,F403
from .trace_ptf2 import read_archive, write_archive          # noqa: F401
