"""PTF2: the second trace backend — an archive of definitions +
per-location event files.

NAMING IS DELIBERATE AND HONEST: PTF2 is a PRIVATE format that follows
OTF2's *architecture* (anchor + global defs + per-location event files,
varint/delta encodings) but is NOT the OTF2 interchange format — neither
``otf2-print`` nor Vampir can read it. Interop with external tooling goes
through ``tools/trace_reader`` (pandas tables, Chrome ``chrome://tracing``
JSON). The reference's profiling_otf2.c (1316 LoC) emits real OTF2 by
linking the OTF2 library; no such library exists in this stack, and
hand-emitting the full interchange format is out of scope — so the feature
is named for what it is.

Re-design of the reference's second profiling backend (parsec/profiling_otf2.c):
the SAME tracer API (dictionary keywords, per-stream buffers,
:class:`parsec_tpu.utils.trace.Profiling`) can be written out in a second,
structurally different format. Where PBP is a single flat file of
fixed-width records, the PTF2 archive follows OTF2's architecture:

* ``<name>.ptf2/`` — an archive **directory** (OTF2 archives are directories)
* ``anchor.json`` — the anchor file: format/version, clock properties,
  definition and location counts (OTF2's anchor file role)
* ``global.defs`` — global definitions: a string table, region definitions
  (one per dictionary keyword, referencing strings by index, carrying the
  info-struct descriptor), and location definitions (one per stream)
* ``loc_<i>.evt`` — one event file per location (stream), records carrying
  **varint-encoded fields and delta-encoded integer timestamps** in
  nanosecond ticks (OTF2 encodes event time as integer ticks with a clock
  resolution from the anchor; PBP stores absolute float seconds)

Select with ``--mca profile_backend otf2`` — :meth:`Profiling.dump` then
writes an archive instead of a PBP file. ``tools/trace_reader.read_trace``
reads either format into the same in-memory model, so the whole analysis
pipeline (pandas tables, Chrome trace, check-comms) is format-agnostic —
the property the reference gets from OTF2 tooling interop.
"""

from __future__ import annotations

import io
import json
import os
import struct
from typing import Any, Dict, List, Tuple

MAGIC_DEFS = b"PTF2DEF1"
MAGIC_EVT = b"PTF2EVT1"
TICKS_PER_SECOND = 1_000_000_000       # ns resolution, like OTF2 archives


# ------------------------------------------------------------- varints

def _zigzag(n: int) -> int:
    return (n << 1) ^ (n >> 63)


def _unzigzag(n: int) -> int:
    return (n >> 1) ^ -(n & 1)


def write_varint(buf: io.BytesIO, n: int) -> None:
    """LEB128 unsigned varint (OTF2 uses the same compression idea)."""
    if n < 0:
        raise ValueError("unsigned varint")
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            buf.write(bytes((b | 0x80,)))
        else:
            buf.write(bytes((b,)))
            return


def write_svarint(buf: io.BytesIO, n: int) -> None:
    write_varint(buf, _zigzag(n))


def read_varint(raw: bytes, off: int) -> Tuple[int, int]:
    n = shift = 0
    while True:
        b = raw[off]
        off += 1
        n |= (b & 0x7F) << shift
        if not b & 0x80:
            return n, off
        shift += 7


def read_svarint(raw: bytes, off: int) -> Tuple[int, int]:
    n, off = read_varint(raw, off)
    return _unzigzag(n), off


# ------------------------------------------------------------- writing

def _write_string_table(buf: io.BytesIO, strings: List[str]) -> None:
    write_varint(buf, len(strings))
    for s in strings:
        raw = s.encode()
        write_varint(buf, len(raw))
        buf.write(raw)


def write_archive(prof, path: str) -> str:
    """Write ``prof`` (a :class:`Profiling`) as a PTF2 archive directory.

    The layout mirrors OTF2: anchor + global defs + per-location events
    (ref: profiling_otf2.c's archive/def-writer/evt-writer structure).
    """
    if path.endswith(".pbp"):
        path = path[:-4]
    if not path.endswith(".ptf2"):
        path = path + ".ptf2"
    os.makedirs(path, exist_ok=True)

    with prof._lock:
        entries = sorted(prof._dict.values(), key=lambda e: e.key)
        streams = list(prof._streams)

        # ---- global definitions: strings, regions, locations ----
        strings: List[str] = []
        sidx: Dict[str, int] = {}

        def intern(s: str) -> int:
            if s not in sidx:
                sidx[s] = len(strings)
                strings.append(s)
            return sidx[s]

        regions = [(e.key, intern(e.name), intern(e.attr),
                    intern(e.info_desc)) for e in entries]
        locations = [(s.stream_id, intern(s.name), len(s.events))
                     for s in streams]

        defs = io.BytesIO()
        defs.write(MAGIC_DEFS)
        _write_string_table(defs, strings)
        write_varint(defs, len(regions))
        for key, name_i, attr_i, desc_i in regions:
            for v in (key, name_i, attr_i, desc_i):
                write_varint(defs, v)
        write_varint(defs, len(locations))
        for loc_id, name_i, nev in locations:
            for v in (loc_id, name_i, nev):
                write_varint(defs, v)
        with open(os.path.join(path, "global.defs"), "wb") as f:
            f.write(defs.getvalue())

        # ---- per-location event files: delta-encoded tick timestamps ----
        for s in streams:
            evt = io.BytesIO()
            evt.write(MAGIC_EVT)
            write_varint(evt, s.stream_id)
            write_varint(evt, len(s.events))
            last_ticks = 0
            for key, eid, tpid, t, flags, info in s.events:
                ticks = int(round((t - prof.t0) * TICKS_PER_SECOND))
                write_varint(evt, key)
                write_svarint(evt, eid)
                write_varint(evt, tpid)
                write_svarint(evt, ticks - last_ticks)
                last_ticks = ticks
                write_varint(evt, flags)
                write_varint(evt, len(info))
                evt.write(info)
            with open(os.path.join(path, f"loc_{s.stream_id}.evt"), "wb") as f:
                f.write(evt.getvalue())

        anchor = {
            "format": "PTF2",
            "version": 1,
            "clock": {"t0": prof.t0, "ticks_per_second": TICKS_PER_SECOND},
            "num_definitions": len(entries),
            "num_locations": len(streams),
        }
        with open(os.path.join(path, "anchor.json"), "w") as f:
            json.dump(anchor, f, indent=1)
    return path


# ------------------------------------------------------------- reading

def read_archive(path: str) -> Dict[str, Any]:
    """Read a PTF2 archive back into the {t0, dictionary, streams} model
    (the same shape tools.trace_reader builds from PBP files)."""
    with open(os.path.join(path, "anchor.json")) as f:
        anchor = json.load(f)
    if anchor.get("format") != "PTF2":
        raise ValueError(f"{path}: not a PTF2 archive")
    tps = anchor["clock"]["ticks_per_second"]
    t0 = anchor["clock"]["t0"]

    raw = open(os.path.join(path, "global.defs"), "rb").read()
    if raw[:8] != MAGIC_DEFS:
        raise ValueError(f"{path}: bad defs magic {raw[:8]!r}")
    off = 8
    nstr, off = read_varint(raw, off)
    strings: List[str] = []
    for _ in range(nstr):
        n, off = read_varint(raw, off)
        strings.append(raw[off:off + n].decode())
        off += n
    nreg, off = read_varint(raw, off)
    dictionary: List[Dict[str, Any]] = []
    for _ in range(nreg):
        key, off = read_varint(raw, off)
        name_i, off = read_varint(raw, off)
        attr_i, off = read_varint(raw, off)
        desc_i, off = read_varint(raw, off)
        dictionary.append({"key": key, "name": strings[name_i],
                           "attr": strings[attr_i],
                           "info_desc": strings[desc_i]})
    nloc, off = read_varint(raw, off)
    loc_meta: List[Tuple[int, str, int]] = []
    for _ in range(nloc):
        loc_id, off = read_varint(raw, off)
        name_i, off = read_varint(raw, off)
        nev, off = read_varint(raw, off)
        loc_meta.append((loc_id, strings[name_i], nev))

    streams: List[Dict[str, Any]] = []
    for loc_id, name, nev in loc_meta:
        raw = open(os.path.join(path, f"loc_{loc_id}.evt"), "rb").read()
        if raw[:8] != MAGIC_EVT:
            raise ValueError(f"{path}/loc_{loc_id}.evt: bad magic")
        off = 8
        got_id, off = read_varint(raw, off)
        if got_id != loc_id:
            raise ValueError(f"loc_{loc_id}.evt claims location {got_id}")
        n, off = read_varint(raw, off)
        if n != nev:
            raise ValueError(f"loc_{loc_id}.evt holds {n} events, "
                             f"defs say {nev}")
        events = []
        ticks = 0
        for _ in range(n):
            key, off = read_varint(raw, off)
            eid, off = read_svarint(raw, off)
            tpid, off = read_varint(raw, off)
            dticks, off = read_svarint(raw, off)
            ticks += dticks
            flags, off = read_varint(raw, off)
            ilen, off = read_varint(raw, off)
            info = raw[off:off + ilen]
            off += ilen
            events.append((key, eid, tpid, t0 + ticks / tps, flags, info))
        streams.append({"name": name, "events": events})
    return {"t0": t0, "dictionary": dictionary, "streams": streams}
