"""Trace analysis pipeline: PBP binary traces → tables / Chrome trace.

Re-design of the reference's profiling toolchain (tools/profiling):
``dbpreader`` + the Cython PBT→PTT pandas pipeline (pbt2ptt.pyx,
parsec_trace_tables.py) and the Chrome-trace converter (h5toctf.py):

* :func:`read_pbp` — parse the binary trace into dictionary + event records.
* :func:`to_dataframe` — pandas "trace tables": one row per matched
  begin/end interval with stream, taskpool, duration, unpacked info fields.
* :func:`to_chrome_trace` — chrome://tracing / Perfetto JSON.
* CLI: ``python -m parsec_tpu.tools.trace_reader trace.pbp [--ctf out.json]``.
"""

from __future__ import annotations

import json
import struct
import sys
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

from ..utils.trace import EVENT_FLAG_POINT, MAGIC, parse_info_desc


@dataclass
class TraceData:
    t0: float
    dictionary: List[Dict[str, Any]]
    streams: List[Dict[str, Any]]   # {name, events: [(key,eid,tp,t,flags,info)]}


def read_pbp(path: str) -> TraceData:
    with open(path, "rb") as f:
        raw = f.read()
    if raw[:8] != MAGIC:
        raise ValueError(f"{path}: not a PBP trace (magic {raw[:8]!r})")
    off = 8
    t0, ndict, nstreams = struct.unpack_from("<dII", raw, off)
    off += struct.calcsize("<dII")

    def read_str() -> str:
        nonlocal off
        (n,) = struct.unpack_from("<I", raw, off)
        off += 4
        s = raw[off:off + n].decode()
        off += n
        return s

    dictionary = []
    for key in range(ndict):
        name, attr, info_desc = read_str(), read_str(), read_str()
        fields, fmt = parse_info_desc(info_desc)
        dictionary.append({"key": key, "name": name, "attr": attr,
                           "info_desc": info_desc, "fields": fields,
                           "fmt": fmt})
    streams = []
    for _ in range(nstreams):
        name = read_str()
        (nev,) = struct.unpack_from("<I", raw, off)
        off += 4
        events = []
        for _ in range(nev):
            key, eid, tpid, t, flags, ilen = struct.unpack_from("<IqIdII", raw, off)
            off += struct.calcsize("<IqIdII")
            info = raw[off:off + ilen]
            off += ilen
            events.append((key, eid, tpid, t, flags, info))
        streams.append({"name": name, "events": events})
    return TraceData(t0, dictionary, streams)


def _intervals(trace: TraceData):
    """Match begin/end pairs per (stream, base key, event id); POINT
    events (e.g. the native lanes' ``ptdtd::task`` completion marks)
    yield as zero-duration intervals."""
    for si, stream in enumerate(trace.streams):
        open_ev: Dict[Tuple[int, int], Tuple[float, bytes, int]] = {}
        for key, eid, tpid, t, flags, info in stream["events"]:
            base, is_end = key >> 1, key & 1
            if flags & EVENT_FLAG_POINT:
                yield si, stream["name"], base, eid, tpid, t, t, info
            elif not is_end:
                open_ev[(base, eid)] = (t, info, tpid)
            else:
                start = open_ev.pop((base, eid), None)
                if start is None:
                    continue
                t_s, info_s, tpid_s = start
                yield si, stream["name"], base, eid, tpid_s, t_s, t, info_s


def to_dataframe(trace: TraceData):
    """The PTT role: one pandas row per begin/end interval."""
    import pandas as pd
    rows = []
    for si, sname, base, eid, tpid, t_s, t_e, info in _intervals(trace):
        d = trace.dictionary[base]
        row = {
            "stream": sname,
            "stream_id": si,
            "name": d["name"],
            "event_id": eid,
            "taskpool_id": tpid,
            "begin": t_s - trace.t0,
            "end": t_e - trace.t0,
            "duration": t_e - t_s,
        }
        if d["fields"] and info:
            vals = struct.unpack(d["fmt"], info)
            row.update({fname: v for (fname, _), v in zip(d["fields"], vals)})
        rows.append(row)
    return pd.DataFrame(rows)


def to_chrome_trace(trace: TraceData) -> Dict[str, Any]:
    """Chrome trace-event JSON (the h5toctf.py role): load into Perfetto."""
    events = []
    for si, sname, base, eid, tpid, t_s, t_e, info in _intervals(trace):
        d = trace.dictionary[base]
        if t_e == t_s:          # POINT events render as thread instants
            events.append({
                "name": d["name"],
                "cat": f"taskpool{tpid}",
                "ph": "i",
                "s": "t",
                "ts": (t_s - trace.t0) * 1e6,
                "pid": 0,
                "tid": si,
                "args": {"event_id": eid},
            })
            continue
        events.append({
            "name": d["name"],
            "cat": f"taskpool{tpid}",
            "ph": "X",
            "ts": (t_s - trace.t0) * 1e6,
            "dur": (t_e - t_s) * 1e6,
            "pid": 0,
            "tid": si,
            "args": {"event_id": eid},
        })
    meta = [{"name": "thread_name", "ph": "M", "pid": 0, "tid": si,
             "args": {"name": s["name"]}}
            for si, s in enumerate(trace.streams)]
    return {"traceEvents": meta + events, "displayTimeUnit": "ms"}


_SVG_COLORS = ["#4c72b0", "#dd8452", "#55a868", "#c44e52", "#8172b3",
               "#937860", "#da8bc3", "#8c8c8c", "#ccb974", "#64b5cd"]


def to_animated_svg(trace: TraceData, playback_s: float = 5.0) -> str:
    """Self-contained animated SVG: a Gantt of the execution that draws
    itself in playback order (SMIL timing) — the role of the reference's
    trace animation tool (tools/profiling/animation.c), with no external
    renderer. One lane per stream, one color per keyword; each task
    interval fades in at its (scaled) begin time."""
    ivs = list(_intervals(trace))
    if not ivs:
        return "<svg xmlns='http://www.w3.org/2000/svg'/>"
    t0 = min(iv[5] for iv in ivs)
    t1 = max(iv[6] for iv in ivs)
    span = max(t1 - t0, 1e-9)
    lane_h, pad, width = 26, 30, 960
    lanes = len(trace.streams)
    height = pad * 2 + lanes * lane_h
    color = {d["key"]: _SVG_COLORS[i % len(_SVG_COLORS)]
             for i, d in enumerate(trace.dictionary)}
    out = [f'<svg xmlns="http://www.w3.org/2000/svg" width="{width}" '
           f'height="{height}" font-family="monospace" font-size="10">']
    for si, s in enumerate(trace.streams):
        y = pad + si * lane_h
        out.append(f'<text x="2" y="{y + lane_h - 10}" '
                   f'fill="#333">{s["name"][:14]}</text>')
        out.append(f'<line x1="{pad + 90}" y1="{y + lane_h - 4}" '
                   f'x2="{width - 10}" y2="{y + lane_h - 4}" '
                   f'stroke="#ddd"/>')
    x0, x1 = pad + 90, width - 10
    for si, sname, base, eid, tpid, tb, te, info in ivs:
        bx = x0 + (tb - t0) / span * (x1 - x0)
        w = max((te - tb) / span * (x1 - x0), 1.0)
        y = pad + si * lane_h
        begin = (tb - t0) / span * playback_s
        name = trace.dictionary[base]["name"]
        out.append(
            f'<rect x="{bx:.1f}" y="{y + 4}" width="{w:.1f}" '
            f'height="{lane_h - 10}" fill="{color[base]}" opacity="0">'
            f'<title>{name} #{eid} [{(tb - t0)*1e3:.2f}..'
            f'{(te - t0)*1e3:.2f} ms]</title>'
            f'<set attributeName="opacity" to="0.9" '
            f'begin="{begin:.3f}s" fill="freeze"/></rect>')
    out.append("</svg>")
    return "\n".join(out)


def read_ptf2(path: str) -> TraceData:
    """Read a PTF2 archive (the OTF2-class backend) into the same model as
    PBP files, so the whole analysis pipeline is format-agnostic."""
    from ..utils.trace_ptf2 import read_archive
    d = read_archive(path)
    dictionary = []
    for e in d["dictionary"]:
        fields, fmt = parse_info_desc(e["info_desc"])
        dictionary.append({**e, "fields": fields, "fmt": fmt})
    return TraceData(d["t0"], dictionary, d["streams"])


def read_trace(path: str) -> TraceData:
    """Format dispatch: PTF2 archives are directories, PBP traces files."""
    import os
    if os.path.isdir(path):
        return read_ptf2(path)
    return read_pbp(path)


# ------------------------------------------------- multi-rank trace merge

#: the per-rank clock metadata keyword (stamped by
#: comm/remote_dep.py stamp_clock_meta): one POINT event per rank
#: carrying (rank, offset_ns to rank 0, min-RTT of the estimate)
CLOCK_KEYWORD = "meta::clock"
#: the ptcomm flow-identity keywords (native/src/ptcomm.cpp): POINT
#: events whose id encodes (peer_rank << 40) | frame_seq
FRAME_TX = "ptcomm::frame_tx"
FRAME_RX = "ptcomm::frame_rx"
_FRAME_SEQ_MASK = (1 << 40) - 1


def clock_meta(trace: TraceData) -> Optional[Dict[str, Any]]:
    """This trace's clock metadata, or None (pre-merge single-rank
    traces, or a run without a comm engine). A trace may carry several
    stamps (an incomplete ok=0 one from an early dump plus the completed
    estimate): the ok=1 record wins, else the last seen."""
    entry = next((d for d in trace.dictionary
                  if d["name"] == CLOCK_KEYWORD), None)
    if entry is None:
        return None
    best: Optional[Dict[str, Any]] = None
    for stream in trace.streams:
        for key, eid, tpid, t, flags, info in stream["events"]:
            if key >> 1 != entry["key"] or not info:
                continue
            vals = struct.unpack(entry["fmt"], info)
            meta = {name: v for (name, _), v in zip(entry["fields"], vals)}
            if meta.get("ok"):
                return meta
            best = meta
    return best


def merge_traces(paths: List[str], rebase: bool = True) -> TraceData:
    """Load N per-rank traces and merge them into ONE TraceData whose
    timestamps all live on rank 0's clock (the reference's offline
    profile merge, ``profiling-tools dbp`` merging per-rank .prof files).

    Each rank's ``meta::clock`` event supplies its rank id and its
    measured ``local - rank0`` offset (min-RTT ping-pong estimate, error
    bounded by RTT/2); ``rebase=True`` subtracts it from every timestamp.
    Traces without metadata fall back to positional rank (``paths[i]`` =
    rank i) and offset 0. Stream names gain an ``r<rank>:`` prefix and
    dictionaries are unified by keyword name, so the merged trace flows
    through the whole existing pipeline (dataframe, chrome JSON, SVG)
    unchanged."""
    traces = [read_trace(p) for p in paths]
    merged_dict: List[Dict[str, Any]] = []
    by_name: Dict[str, int] = {}
    streams: List[Dict[str, Any]] = []
    t0 = None
    for pos, trace in enumerate(traces):
        meta = clock_meta(trace)
        rank = int(meta["rank"]) if meta is not None else pos
        off = (meta["offset_ns"] * 1e-9
               if rebase and meta is not None else 0.0)
        keymap: Dict[int, int] = {}
        for d in trace.dictionary:
            nk = by_name.get(d["name"])
            if nk is None:
                nk = len(merged_dict)
                by_name[d["name"]] = nk
                merged_dict.append(dict(d, key=nk))
            keymap[d["key"]] = nk
        rt0 = trace.t0 - off
        t0 = rt0 if t0 is None else min(t0, rt0)
        for s in trace.streams:
            events = [((keymap[key >> 1] << 1) | (key & 1), eid, tpid,
                       t - off, flags, info)
                      for key, eid, tpid, t, flags, info in s["events"]]
            streams.append({"name": f"r{rank}:{s['name']}",
                            "events": events})
    return TraceData(t0 or 0.0, merged_dict, streams)


def _frame_events(trace: TraceData, keyword: str):
    """(src_rank_of_stream, peer, seq, t) for every flow-identity point.
    Rank comes from the merged ``r<rank>:`` stream-name prefix."""
    entry = next((d for d in trace.dictionary if d["name"] == keyword), None)
    if entry is None:
        return
    for stream in trace.streams:
        name = stream["name"]
        if not name.startswith("r") or ":" not in name:
            continue
        try:
            rank = int(name[1:name.index(":")])
        except ValueError:
            continue
        for key, eid, tpid, t, flags, info in stream["events"]:
            if key >> 1 != entry["key"]:
                continue
            yield rank, eid >> 40, eid & _FRAME_SEQ_MASK, t


def act_flows(trace: TraceData) -> Dict[str, Any]:
    """Pair every cross-rank activation frame's send with the peer's
    ingest in a MERGED trace: frame_tx on rank a toward peer b with
    sequence s matches frame_rx on rank b from peer a with the same s.
    Returns ``{"pairs": [(src, dst, seq, t_tx, t_rx)], "unmatched_tx",
    "unmatched_rx"}`` — the ci gate requires both unmatched lists empty
    (every cross-rank activation reads as one causal edge)."""
    tx: Dict[Tuple[int, int, int], float] = {}
    for rank, peer, seq, t in _frame_events(trace, FRAME_TX):
        tx[(rank, peer, seq)] = t
    pairs: List[Tuple[int, int, int, float, float]] = []
    unmatched_rx: List[Tuple[int, int, int]] = []
    for rank, peer, seq, t in _frame_events(trace, FRAME_RX):
        t_tx = tx.pop((peer, rank, seq), None)
        if t_tx is None:
            unmatched_rx.append((peer, rank, seq))
        else:
            pairs.append((peer, rank, seq, t_tx, t))
    return {"pairs": sorted(pairs, key=lambda p: p[3]),
            "unmatched_tx": sorted(tx),
            "unmatched_rx": sorted(unmatched_rx)}


def flow_chrome_events(trace: TraceData,
                       flows: Optional[Dict[str, Any]] = None
                       ) -> List[Dict[str, Any]]:
    """Chrome trace-event flow records ("s"/"f" phases) for the paired
    cross-rank activations, ready to extend a merged trace's
    ``traceEvents`` — Perfetto draws one arrow per frame from the
    sender's progress-thread track to the receiver's. Pass an
    :func:`act_flows` result to avoid re-scanning the events."""
    sid = {s["name"]: i for i, s in enumerate(trace.streams)}

    def tid_of(rank: int) -> int:
        # the frame points live on the ptcomm progress-thread streams
        for name, i in sid.items():
            if name.startswith(f"r{rank}:ptcomm-"):
                return i
        return 0

    if flows is None:
        flows = act_flows(trace)
    out: List[Dict[str, Any]] = []
    for src, dst, seq, t_tx, t_rx in flows["pairs"]:
        fid = f"act:{src}>{dst}#{seq}"
        out.append({"name": "xrank-activate", "cat": "ptcomm", "ph": "s",
                    "id": fid, "ts": (t_tx - trace.t0) * 1e6, "pid": 0,
                    "tid": tid_of(src)})
        out.append({"name": "xrank-activate", "cat": "ptcomm", "ph": "f",
                    "bp": "e", "id": fid, "ts": (t_rx - trace.t0) * 1e6,
                    "pid": 0, "tid": tid_of(dst)})
    return out


def merge_to_chrome(paths: List[str]
                    ) -> Tuple[Dict[str, Any], Dict[str, Any]]:
    """One-call merge recipe — THE home of the merge+flow invariant
    (the CLI and the ci gate both call it): N per-rank .pbp files ->
    ``(chrome_json_with_flow_arrows, act_flows_result)``."""
    merged = merge_traces(paths)
    flows = act_flows(merged)
    out = to_chrome_trace(merged)
    out["traceEvents"].extend(flow_chrome_events(merged, flows))
    return out, flows


def comm_events(trace: TraceData) -> List[Dict[str, Any]]:
    """Extract typed comm-stream events (``comm::*`` keywords) with their
    decoded src/dst/bytes info blobs (ref: the comm-thread stream written
    by remote_dep_mpi.c:1286-1302)."""
    by_key = {d["key"]: d for d in trace.dictionary}
    out: List[Dict[str, Any]] = []
    for stream in trace.streams:
        for key, eid, tpid, t, flags, info in stream["events"]:
            entry = by_key.get(key >> 1)
            if entry is None or not entry["name"].startswith("comm::"):
                continue
            ev = {"kind": entry["name"][len("comm::"):], "t": t,
                  "stream": stream["name"], "event_id": eid}
            if entry["fields"] and info:
                vals = struct.unpack(entry["fmt"], info)
                ev.update({n: v for (n, _), v in zip(entry["fields"], vals)})
            out.append(ev)
    return out


def check_comms(paths: List[str]) -> Dict[str, Any]:
    """Cross-rank validation of the comm streams (the check-comms.py role,
    ref: tests/profiling/check-comms.py): every send event recorded by one
    rank must have a matching receive on the destination rank with the
    same (src, dst, bytes), for each protocol leg (activate/get/put).

    ``paths[i]`` is rank i's PBP file. Returns a summary dict with an
    ``errors`` list (empty = consistent).
    """
    pairs = [("activate_snd", "activate_rcv"), ("get_snd", "get_rcv"),
             ("put_snd", "put_rcv")]
    per_rank = [comm_events(read_trace(p)) for p in paths]
    errors: List[str] = []
    counts: Dict[str, int] = {}
    for snd_kind, rcv_kind in pairs:
        # multiset of (src, dst, bytes) on each side
        snd: Dict[Tuple, int] = {}
        rcv: Dict[Tuple, int] = {}
        for rank, evs in enumerate(per_rank):
            for ev in evs:
                if ev["kind"] == snd_kind:
                    if ev.get("src") != rank:
                        errors.append(f"{snd_kind} recorded on rank {rank} "
                                      f"but src={ev.get('src')}")
                    k = (ev.get("src"), ev.get("dst"), ev.get("bytes"))
                    snd[k] = snd.get(k, 0) + 1
                elif ev["kind"] == rcv_kind:
                    if ev.get("dst") != rank:
                        errors.append(f"{rcv_kind} recorded on rank {rank} "
                                      f"but dst={ev.get('dst')}")
                    k = (ev.get("src"), ev.get("dst"), ev.get("bytes"))
                    rcv[k] = rcv.get(k, 0) + 1
        counts[snd_kind] = sum(snd.values())
        counts[rcv_kind] = sum(rcv.values())
        for k, n in snd.items():
            if rcv.get(k, 0) != n:
                errors.append(f"{snd_kind} {k} sent {n}x but received "
                              f"{rcv.get(k, 0)}x")
        for k, n in rcv.items():
            if k not in snd:
                errors.append(f"{rcv_kind} {k} received with no matching send")
    # protocol shape: every rendezvous put pairs with exactly one get
    if counts.get("put_snd", 0) != counts.get("get_rcv", 0):
        errors.append(f"put_snd={counts.get('put_snd')} != "
                      f"get_rcv={counts.get('get_rcv')}")
    return {"ranks": len(paths), "counts": counts, "errors": errors}


def main(argv: Optional[List[str]] = None) -> int:
    argv = argv if argv is not None else sys.argv[1:]
    if not argv:
        print("usage: trace_reader <trace.pbp|archive.ptf2> "
              "[--ctf out.json] [--csv out.csv] [--svg out.svg]\n"
              "       trace_reader --check-comms <rank0.pbp> <rank1.pbp> ...\n"
              "       trace_reader --merge out.json <rank0.pbp> "
              "<rank1.pbp> ...  (clock-aligned Perfetto timeline with "
              "cross-rank flow arrows)",
              file=sys.stderr)
        return 2
    if argv[0] == "--check-comms":
        summary = check_comms(argv[1:])
        print(json.dumps(summary))
        return 1 if summary["errors"] else 0
    if argv[0] == "--merge":
        out_path, paths = argv[1], argv[2:]
        ctf, flows = merge_to_chrome(paths)
        with open(out_path, "w") as f:
            json.dump(ctf, f)
        print(f"merged {len(paths)} rank traces -> {out_path}: "
              f"{len(flows['pairs'])} cross-rank flow pairs, "
              f"{len(flows['unmatched_tx'])} unmatched tx, "
              f"{len(flows['unmatched_rx'])} unmatched rx")
        return 1 if flows["unmatched_tx"] or flows["unmatched_rx"] else 0
    trace = read_trace(argv[0])
    print(f"trace: {len(trace.dictionary)} keywords, "
          f"{len(trace.streams)} streams, "
          f"{sum(len(s['events']) for s in trace.streams)} events")
    if "--ctf" in argv:
        out = argv[argv.index("--ctf") + 1]
        with open(out, "w") as f:
            json.dump(to_chrome_trace(trace), f)
        print(f"chrome trace -> {out}")
    if "--csv" in argv:
        out = argv[argv.index("--csv") + 1]
        to_dataframe(trace).to_csv(out, index=False)
        print(f"trace tables -> {out}")
    if "--svg" in argv:
        out = argv[argv.index("--svg") + 1]
        with open(out, "w") as f:
            f.write(to_animated_svg(trace))
        print(f"animated gantt -> {out}")
    if not any(f in argv for f in ("--ctf", "--csv", "--svg")):
        df = to_dataframe(trace)
        if len(df):
            print(df.groupby("name")["duration"].describe())
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
