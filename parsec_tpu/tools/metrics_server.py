"""Per-rank metrics endpoint: the counter registry over HTTP/UDS JSON.

The cross-process half of the observability plane (ISSUE 8, ROADMAP item
4's "export the PR 5 counter registry over a local endpoint so live_view
works cross-process like a real ops dashboard") — the role the
reference's PINS/PAPI-SDE export plus ``tools/aggregator_visu`` demo
server play: every rank runs a tiny stdlib HTTP server (TCP on
127.0.0.1, or a unix-domain socket) serving

* ``GET /metrics``     — ``{"rank", "nb_ranks", "pid", "ts",
  "counters": {...unified registry snapshot...},
  "percentiles": {...native latency histogram summaries...}}``
* ``GET /health``      — liveness probe (``{"ok": true, "rank": r}``);
  when a stall watchdog is armed (``--mca watchdog_stall_ms``) a latched
  stall degrades it to ``ok: false`` with the attributed stall list
* ``GET /histograms``  — raw log2 bucket arrays (non-zero entries), for
  consumers that want to merge distributions instead of percentiles
* ``GET /mesh``        — rank 0 only in practice: the telemetry plane's
  tree-aggregated mesh rollup (``comm/pttel.py``) — summed counters,
  merged histogram buckets, per-rank gauges and per-rank staleness —
  with zero per-request cross-rank traffic (the data was pushed here)

Started from ``Context`` init via ``--mca metrics_port <base>`` (rank r
binds ``base + r``, loopback only) or ``--mca metrics_uds <path>``
(rank r binds ``<path>.r<r>``), torn down at fini. ``live_view`` polls
one or many rank endpoints through :func:`fetch`, which speaks plain
HTTP/1.0 over either transport, so a 2-rank run reads as one dashboard.

Everything here is stdlib-only and off the hot path: a scrape costs one
registry snapshot (the samplers are TTL-cached where they are
expensive) on a daemon thread.
"""

from __future__ import annotations

import json
import os
import socket
import socketserver
import threading
import time
from http.server import BaseHTTPRequestHandler, HTTPServer
from typing import Any, Dict, List, Optional

from ..utils import mca, output

mca.register("metrics_port", 0,
             "Serve the unified counter registry + latency percentiles "
             "as JSON on 127.0.0.1:<metrics_port + my_rank> "
             "(/metrics, /health, /histograms). 0 = disabled. Implies "
             "hist_enabled", type=int)
mca.register("metrics_uds", "",
             "Serve the metrics endpoint on a unix-domain socket at "
             "<path>.r<rank> instead of TCP. Empty = disabled", type=str)


def _json_safe(v):
    """Replace non-finite floats with None, recursively (RFC 8259 JSON
    has no NaN/Infinity)."""
    if isinstance(v, float):
        return v if v == v and v not in (float("inf"), float("-inf")) \
            else None
    if isinstance(v, dict):
        return {k: _json_safe(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [_json_safe(x) for x in v]
    return v


class _Handler(BaseHTTPRequestHandler):
    server_version = "parsec-tpu-metrics/1.0"

    def log_message(self, *args) -> None:  # silence per-request stderr
        pass

    def do_GET(self) -> None:  # noqa: N802 — BaseHTTPRequestHandler API
        srv: "MetricsServer" = self.server.metrics   # type: ignore[attr-defined]
        path = self.path.split("?", 1)[0].rstrip("/") or "/metrics"
        try:
            if path == "/health":
                body = srv.health_body()
            elif path == "/metrics":
                body = srv.metrics_body()
            elif path == "/histograms":
                body = srv.histograms_body()
            elif path == "/mesh":
                body = srv.mesh_body()
            else:
                self.send_error(404, "unknown path (try /metrics)")
                return
        except Exception as e:  # noqa: BLE001 — a scrape must not 500-loop
            self.send_error(500, f"snapshot failed: {e}")
            return
        # strict JSON: a NaN counter (e.g. a clock offset not yet
        # measured, or a failing sampler — CounterRegistry maps those to
        # float('nan')) must serialize as null, not the bare `NaN` token
        # Python emits by default, or `curl | jq` and every RFC-8259
        # parser choke on the scrape
        raw = json.dumps(_json_safe(body)).encode()
        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(raw)))
        self.end_headers()
        self.wfile.write(raw)


class _TCPServer(socketserver.ThreadingMixIn, HTTPServer):
    daemon_threads = True
    allow_reuse_address = True


class _UDSServer(socketserver.ThreadingMixIn, socketserver.UnixStreamServer):
    daemon_threads = True
    allow_reuse_address = True

    def get_request(self):
        # BaseHTTPRequestHandler expects a (host, port)-shaped address
        request, _ = super().get_request()
        return request, ("uds", 0)


class MetricsServer:
    """One rank's metrics endpoint. ``port`` > 0 binds TCP
    ``127.0.0.1:port + rank``; ``port`` == 0 with no ``uds`` binds an
    ephemeral TCP port (tests); a non-empty ``uds`` binds
    ``<uds>.r<rank>`` instead."""

    def __init__(self, rank: int = 0, nb_ranks: int = 1, port: int = 0,
                 uds: str = "", registry=None) -> None:
        self.rank = rank
        self.nb_ranks = nb_ranks
        self._uds_path: Optional[str] = None
        self._thread: Optional[threading.Thread] = None
        if registry is None:
            from ..utils.counters import counters as registry  # noqa: PLW0127
        self.registry = registry
        # make the native lanes + latency percentiles visible to scrapes
        # (idempotent; tolerate partial native availability)
        try:
            from ..utils.counters import install_native_counters
            install_native_counters()
        except Exception:  # noqa: BLE001 — registry still serves the rest
            pass
        if uds:
            self._uds_path = f"{uds}.r{rank}"
            try:
                os.unlink(self._uds_path)
            except OSError:
                pass
            self._srv = _UDSServer(self._uds_path, _Handler)
            self.endpoint = f"unix:{self._uds_path}"
        else:
            bind_port = port + rank if port else 0
            self._srv = _TCPServer(("127.0.0.1", bind_port), _Handler)
            self.endpoint = f"http://127.0.0.1:{self._srv.server_address[1]}"
        self._srv.metrics = self   # type: ignore[attr-defined]

    # ------------------------------------------------------------- bodies
    def health_body(self) -> Dict[str, Any]:
        body: Dict[str, Any] = {"ok": True, "rank": self.rank,
                                "pid": os.getpid()}
        try:
            from ..core.watchdog import health_report
            wd = health_report()
        except Exception:  # noqa: BLE001 — health must still answer
            wd = None
        if wd is not None:
            body["watchdog"] = wd
            if wd["degraded"]:
                body["ok"] = False
        return body

    def mesh_body(self) -> Dict[str, Any]:
        """The telemetry plane's mesh rollup — only meaningful where the
        tree's frames land (rank 0), but any rank answers with whatever
        subtree it has folded, attributed when the plane is off."""
        from ..comm.pttel import current_plane
        tel = current_plane()
        if tel is None:
            return {"rank": self.rank, "ts": time.time(), "mesh": None,
                    "reason": "telemetry plane not running "
                              "(--mca tel_interval_ms 0)"}
        body = tel.rollup()
        body["ts"] = time.time()
        return body

    def metrics_body(self) -> Dict[str, Any]:
        from ..utils.hist import histograms
        return {
            "rank": self.rank,
            "nb_ranks": self.nb_ranks,
            "pid": os.getpid(),
            "ts": time.time(),
            "counters": self.registry.snapshot(),
            "percentiles": histograms.summaries(),
        }

    def histograms_body(self) -> Dict[str, Any]:
        from ..utils.hist import histograms
        out = {}
        for name, d in histograms.snapshot().items():
            out[name] = {
                "count": d["count"],
                "sum_ns": d["sum_ns"],
                # sparse form: log2 buckets are mostly empty
                "buckets": [[i, c] for i, c in enumerate(d["buckets"]) if c],
            }
        return {"rank": self.rank, "ts": time.time(), "histograms": out}

    # ---------------------------------------------------------- lifecycle
    def start(self) -> "MetricsServer":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._srv.serve_forever, daemon=True,
                name=f"parsec-tpu-metrics-r{self.rank}")
            self._thread.start()
            output.debug_verbose(1, "metrics",
                                 f"rank {self.rank} metrics endpoint up "
                                 f"at {self.endpoint}")
        return self

    def stop(self) -> None:
        """Shut down cleanly: no leaked thread, socket, or UDS inode —
        the test-isolation contract (a later bind of the same port/path
        must succeed)."""
        if self._thread is None:
            return
        self._srv.shutdown()
        self._srv.server_close()
        self._thread.join(timeout=2.0)
        self._thread = None
        if self._uds_path:
            try:
                os.unlink(self._uds_path)
            except OSError:
                pass

    @classmethod
    def maybe_start(cls, rank: int, nb_ranks: int) -> Optional["MetricsServer"]:
        """Context-init hook: build from the mca params, or None when the
        endpoint is not configured. A bind failure warns and disables
        (observability must never kill the runtime)."""
        port = mca.get("metrics_port", 0)
        uds = mca.get("metrics_uds", "")
        if not port and not uds:
            return None
        try:
            return cls(rank=rank, nb_ranks=nb_ranks, port=port,
                       uds=uds).start()
        except OSError as e:
            output.warning(f"metrics endpoint disabled: cannot bind "
                           f"(port={port} uds={uds!r} rank={rank}): {e}")
            return None


# ------------------------------------------------------------------ client

def fetch(endpoint: str, path: str = "/metrics",
          timeout: float = 2.0) -> Dict[str, Any]:
    """Minimal HTTP/1.0 GET over TCP (``http://host:port``) or UDS
    (``unix:/path``), returning the decoded JSON body. stdlib-socket on
    purpose: urllib cannot speak unix-domain sockets, and the poller
    (live_view cross-process mode, the ci gate) needs both."""
    if endpoint.startswith("unix:"):
        s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        s.settimeout(timeout)
        s.connect(endpoint[len("unix:"):])
        host = "localhost"
    else:
        hostport = endpoint.split("//", 1)[-1].rstrip("/")
        host, _, port_s = hostport.partition(":")
        s = socket.create_connection((host, int(port_s)), timeout=timeout)
    try:
        s.sendall(f"GET {path} HTTP/1.0\r\nHost: {host}\r\n\r\n".encode())
        chunks: List[bytes] = []
        while True:
            b = s.recv(65536)
            if not b:
                break
            chunks.append(b)
    finally:
        s.close()
    raw = b"".join(chunks)
    head, _, body = raw.partition(b"\r\n\r\n")
    status_line = head.split(b"\r\n", 1)[0].split()
    if len(status_line) < 2 or status_line[1] != b"200":
        raise RuntimeError(f"{endpoint}{path}: {head[:200]!r}")
    return json.loads(body)
