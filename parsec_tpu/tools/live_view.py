"""Live counter visualization: periodic sampling + rendered time-series.

Re-design of the reference's ``tools/aggregator_visu`` (a demo server
exporting MCA counters plus a matplotlib GUI, ``aggregator.py``): a
background sampler records the counter registry on an interval, and
:meth:`render` draws the series with matplotlib. Headless-friendly (Agg
backend) — on a cluster the PNG lands where a dashboard can poll it, which
is the TPU-pod-operations shape of "live GUI". Cross-rank aggregation at
fini stays with ``--mca counter_aggregate 1`` (comm/remote_dep.py); this
module covers the time dimension.

Usage::

    from parsec_tpu.tools.live_view import LiveCounterView
    view = LiveCounterView(interval_s=0.05)
    view.start()
    ... run taskpools ...
    view.stop()
    view.render("counters.png")
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

from ..utils.counters import counters as default_registry


class LiveCounterView:
    """Sample a CounterRegistry on an interval; render the series."""

    def __init__(self, registry=None, interval_s: float = 0.1,
                 max_samples: int = 10000) -> None:
        if registry is None:
            # default view: make the native lanes visible (ptexec.*,
            # ptdtd.*, trace.* samplers — idempotent registration)
            from ..utils.counters import install_native_counters
            install_native_counters()
        self.registry = registry if registry is not None else default_registry
        self.interval_s = interval_s
        self.max_samples = max_samples
        self.times: List[float] = []
        self.series: Dict[str, List[float]] = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._t0 = None

    # ------------------------------------------------------------- sampling
    def sample(self) -> None:
        """Record one snapshot (also usable standalone, without start())."""
        snap = self.registry.snapshot()
        now = time.perf_counter()
        with self._lock:
            if self._t0 is None:
                self._t0 = now
            if len(self.times) >= self.max_samples:
                return
            self.times.append(now - self._t0)
            for name, v in snap.items():
                s = self.series.setdefault(name, [0.0] * (len(self.times) - 1))
                s.append(float(v))
            for name, s in self.series.items():
                if len(s) < len(self.times):      # counter appeared late
                    s.extend([s[-1] if s else 0.0] *
                             (len(self.times) - len(s)))

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            self.sample()

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self.sample()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="parsec-tpu-liveview")
        self._thread.start()

    def stop(self) -> None:
        if self._thread is None:
            return
        self._stop.set()
        self._thread.join(timeout=2.0)
        self._thread = None
        self.sample()

    # ------------------------------------------------------------- rendering
    def active_series(self) -> Dict[str, List[float]]:
        """Counters whose value changed during the observation window."""
        with self._lock:
            return {n: list(s) for n, s in self.series.items()
                    if s and (max(s) != min(s))}

    def render(self, path: str, title: str = "parsec_tpu counters") -> str:
        """Draw the changing counters as time series (PNG/SVG by suffix)."""
        import matplotlib
        matplotlib.use("Agg", force=False)
        import matplotlib.pyplot as plt
        active = self.active_series()
        with self._lock:
            ts = list(self.times)
        fig, ax = plt.subplots(figsize=(9, 4.5))
        if active:
            for name, s in sorted(active.items()):
                ax.plot(ts[:len(s)], s, label=name, linewidth=1.2)
            ax.legend(loc="upper left", fontsize=8)
        else:
            ax.text(0.5, 0.5, "no counter activity", ha="center",
                    transform=ax.transAxes)
        ax.set_xlabel("seconds")
        ax.set_ylabel("count")
        ax.set_title(title)
        fig.tight_layout()
        fig.savefig(path)
        plt.close(fig)
        return path
