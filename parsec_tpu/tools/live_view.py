"""Live counter visualization: periodic sampling + rendered time-series.

Re-design of the reference's ``tools/aggregator_visu`` (a demo server
exporting MCA counters plus a matplotlib GUI, ``aggregator.py``): a
background sampler records counters on an interval, and :meth:`render`
draws the series with matplotlib. Headless-friendly (Agg backend) — on a
cluster the PNG lands where a dashboard can poll it, which is the
TPU-pod-operations shape of "live GUI".

Three sources (ISSUE 8, mesh mode ISSUE 20):

* **in-process** (default): the unified counter registry of THIS process;
* **cross-process**: pass ``endpoints=[...]`` — one or many rank metrics
  endpoints (``http://127.0.0.1:port`` / ``unix:/path``, served by
  ``tools/metrics_server`` from each rank's Context) — and the sampler
  polls ``/metrics`` over the wire instead, so a real multi-OS-rank run
  reads as one dashboard. With several endpoints the series are prefixed
  ``r<rank>.``; an unreachable endpoint counts into ``poll_errors`` and
  the other ranks keep sampling.
* **mesh**: pass ``mesh_endpoint="http://..."`` — ONE poll of rank 0's
  ``/mesh`` (the pttel tree-aggregated rollup) replaces N per-rank
  fetches: per-rank series (``r<rank>.``) plus the mesh sums
  (``mesh.``) from a single GET, with each rank's push staleness
  surfaced in :meth:`stats` (``mesh_staleness``). A poll while the
  telemetry plane is down counts into ``plane_down``.

Long runs never lose their early history: hitting ``max_samples``
decimates the stored series in half (every other sample dropped, counted
in ``samples_dropped``/``decimations``) instead of silently discarding
new samples, so the series always spans the whole run at a resolution
that degrades gracefully.

Usage::

    from parsec_tpu.tools.live_view import LiveCounterView
    view = LiveCounterView(interval_s=0.05)            # in-process
    view = LiveCounterView(endpoints=["http://127.0.0.1:9130",
                                      "http://127.0.0.1:9131"])
    view.start()
    ... run taskpools ...
    view.stop()
    view.render("counters.png")
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Sequence

from ..utils.counters import counters as default_registry


class LiveCounterView:
    """Sample a CounterRegistry (or remote rank endpoints) on an
    interval; render the series."""

    def __init__(self, registry=None, interval_s: float = 0.1,
                 max_samples: int = 10000,
                 endpoints: Optional[Sequence[str]] = None,
                 mesh_endpoint: Optional[str] = None) -> None:
        self.endpoints = list(endpoints) if endpoints else None
        self.mesh_endpoint = mesh_endpoint
        self.plane_down = 0            # /mesh polls with no plane data
        self.mesh_staleness: Dict[int, float] = {}  # rank -> seconds
        if registry is None and self.endpoints is None \
                and mesh_endpoint is None:
            # default view: make the native lanes visible (ptexec.*,
            # ptdtd.*, trace.* samplers — idempotent registration)
            from ..utils.counters import install_native_counters
            install_native_counters()
        self.registry = registry if registry is not None else default_registry
        self.interval_s = interval_s
        self.max_samples = max(2, max_samples)
        self.times: List[float] = []
        self.series: Dict[str, List[float]] = {}
        self.samples_dropped = 0     # samples discarded by decimation
        self.decimations = 0         # how many times the window halved
        self.poll_errors = 0         # unreachable-endpoint scrapes
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._t0 = None

    # ------------------------------------------------------------- sampling
    def _snapshot_mesh(self) -> Dict[str, float]:
        """One GET of rank 0's /mesh: the whole mesh's per-rank counters
        plus the rollup sums ride a single pushed snapshot — O(1) polls
        regardless of mesh size (the N-fetch mode stays as fallback)."""
        from .metrics_server import fetch
        try:
            m = fetch(self.mesh_endpoint, path="/mesh")
        except Exception:  # noqa: BLE001 — poll again next interval
            self.poll_errors += 1
            return {}
        if m.get("ranks") is None:
            self.plane_down += 1
            return {}
        snap: Dict[str, float] = {}
        staleness: Dict[int, float] = {}
        for r, ent in m["ranks"].items():
            r = int(r)   # JSON object keys arrive as strings
            staleness[r] = float(ent.get("staleness_s") or 0.0)
            for k, v in ent.get("counters", {}).items():
                if isinstance(v, (int, float)):
                    snap[f"r{r}.{k}"] = v
        for k, v in m.get("rollup", {}).items():
            if isinstance(v, (int, float)):
                snap[f"mesh.{k}"] = v
        with self._lock:
            self.mesh_staleness = staleness
        return snap

    def _snapshot(self) -> Dict[str, float]:
        if self.mesh_endpoint is not None:
            return self._snapshot_mesh()
        if self.endpoints is None:
            return {k: v for k, v in self.registry.snapshot().items()
                    if isinstance(v, (int, float))}
        from .metrics_server import fetch
        snap: Dict[str, float] = {}
        many = len(self.endpoints) > 1
        for ep in self.endpoints:
            try:
                m = fetch(ep)
            except Exception:  # noqa: BLE001 — a dead rank must not
                self.poll_errors += 1   # stall the other ranks' series
                continue
            prefix = f"r{m.get('rank', 0)}." if many else ""
            for k, v in m.get("counters", {}).items():
                if isinstance(v, (int, float)):
                    snap[prefix + k] = v
        return snap

    def sample(self) -> None:
        """Record one snapshot (also usable standalone, without start())."""
        snap = self._snapshot()
        now = time.perf_counter()
        with self._lock:
            if self._t0 is None:
                self._t0 = now
            if len(self.times) >= self.max_samples:
                # decimate-in-half: keep every other sample so the series
                # still covers the full run (half resolution) instead of
                # silently freezing at the window edge
                kept = self.times[::2]
                self.samples_dropped += len(self.times) - len(kept)
                self.decimations += 1
                self.times = kept
                for name in self.series:
                    self.series[name] = self.series[name][::2]
            self.times.append(now - self._t0)
            for name, v in snap.items():
                s = self.series.setdefault(name, [0.0] * (len(self.times) - 1))
                s.append(float(v))
            for name, s in self.series.items():
                if len(s) < len(self.times):      # counter appeared late
                    s.extend([s[-1] if s else 0.0] *
                             (len(self.times) - len(s)))

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            self.sample()

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self.sample()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="parsec-tpu-liveview")
        self._thread.start()

    def stop(self) -> None:
        if self._thread is None:
            return
        self._stop.set()
        self._thread.join(timeout=2.0)
        self._thread = None
        self.sample()

    def stats(self) -> Dict[str, int]:
        """Sampling health: window decimations and endpoint poll errors."""
        with self._lock:
            out = {"samples": len(self.times),
                   "samples_dropped": self.samples_dropped,
                   "decimations": self.decimations,
                   "poll_errors": self.poll_errors}
            if self.mesh_endpoint is not None:
                out["plane_down"] = self.plane_down
                out["mesh_staleness"] = dict(self.mesh_staleness)
            return out

    # ------------------------------------------------------------- rendering
    def active_series(self) -> Dict[str, List[float]]:
        """Counters whose value changed during the observation window."""
        with self._lock:
            return {n: list(s) for n, s in self.series.items()
                    if s and (max(s) != min(s))}

    def render(self, path: str, title: str = "parsec_tpu counters") -> str:
        """Draw the changing counters as time series (PNG/SVG by suffix)."""
        import matplotlib
        matplotlib.use("Agg", force=False)
        import matplotlib.pyplot as plt
        active = self.active_series()
        with self._lock:
            ts = list(self.times)
        fig, ax = plt.subplots(figsize=(9, 4.5))
        if active:
            for name, s in sorted(active.items()):
                ax.plot(ts[:len(s)], s, label=name, linewidth=1.2)
            ax.legend(loc="upper left", fontsize=8)
        else:
            ax.text(0.5, 0.5, "no counter activity", ha="center",
                    transform=ax.transAxes)
        ax.set_xlabel("seconds")
        ax.set_ylabel("count")
        ax.set_title(title)
        fig.tight_layout()
        fig.savefig(path)
        plt.close(fig)
        return path
